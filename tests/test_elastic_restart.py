"""Elastic restart: device state is a cache; durable storage is truth.

VERDICT round-1 item 7 / SURVEY section 5 failure-elastic story: the
design claims a process can die and be rebuilt from Parquet + partition
manifest (persisted layer) + durable log replay (recent live writes).
This proves it end-to-end: build a DeviceIndex over an FS store plus a
live layer backed by a FileFeatureLog, record query results, throw every
object away, reopen from disk alone, and require identical results.
"""

import numpy as np
import pytest

from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.stream.live import LiveFeatureStore
from geomesa_tpu.stream.log import FileFeatureLog

SPEC = "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"
QUERIES = [
    "BBOX(geom, -5, 42, 8, 51) AND dtg DURING 2020-01-05T00:00:00Z/2020-02-20T00:00:00Z",
    "BBOX(geom, -120, 20, -60, 55) AND count > 40",
    "name = 'alpha'",
]


def _cols(rng, n, t0=1_578_000_000_000, t1=1_580_000_000_000):
    return {
        "name": rng.choice(["alpha", "beta", "gamma"], n),
        "count": rng.integers(0, 100, n),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }


def _combined_fids(store, live, query):
    """Query the persisted layer (via its DeviceIndex cache) and the live
    layer; live wins per fid (the lambda-merge view)."""
    di = DeviceIndex(store, "ev")
    persisted = set(di.query(query).fids.tolist())
    live_hits = set(live.query(query).fids.tolist())
    live_all = set(live.snapshot().fids.tolist())
    # live supersedes: any fid present in the live layer is answered there
    return (persisted - live_all) | live_hits


def test_restart_from_parquet_manifest_and_log_replay(tmp_path):
    rng = np.random.default_rng(21)
    data_dir = tmp_path / "fsstore"
    log_path = tmp_path / "live.log"

    # ---- original process: durable writes + recent live writes ----------
    store = FileSystemDataStore(str(data_dir), partition_size=2048)
    sft = store.create_schema("ev", SPEC)
    store.write("ev", _cols(rng, 10_000), fids=np.arange(10_000))
    store.flush("ev")

    live = LiveFeatureStore(sft, log=FileFeatureLog(str(log_path), sft))
    # recent writes: some brand-new fids, some overwriting persisted ones
    live.put(_cols(rng, 500), fids=np.arange(10_000, 10_500))
    live.put(_cols(rng, 200), fids=np.arange(200))  # upserts
    # delete fids that ARE in the live layer, so remove-replay is exercised
    live.remove(np.arange(10_480, 10_500))

    before = {q: _combined_fids(store, live, q) for q in QUERIES}
    assert any(len(v) for v in before.values())
    n_live_before = len(live)

    # ---- crash: every in-memory object is gone --------------------------
    live.log.close()
    del store, live

    # ---- fresh process: reopen from disk alone --------------------------
    store2 = FileSystemDataStore(str(data_dir), partition_size=2048)
    assert "ev" in store2.type_names  # manifest + metadata reopened
    sft2 = store2.get_schema("ev")
    live2 = LiveFeatureStore(sft2, log=FileFeatureLog(str(log_path), sft2))
    assert len(live2) == n_live_before  # log replay rebuilt the cache

    after = {q: _combined_fids(store2, live2, q) for q in QUERIES}
    assert after == before

    # the rebuilt device cache serves counts identical to a fresh scan
    di = DeviceIndex(store2, "ev")
    for q in QUERIES:
        assert di.count(q) == len(di.query(q))


def test_restart_survives_torn_log_tail(tmp_path):
    """A crash mid-append leaves a torn record; reopen must drop ONLY the
    torn tail and keep every complete record."""
    rng = np.random.default_rng(3)
    log_path = tmp_path / "live.log"
    from geomesa_tpu.features.sft import SimpleFeatureType

    sft = SimpleFeatureType.create("ev", SPEC)
    live = LiveFeatureStore(sft, log=FileFeatureLog(str(log_path), sft))
    live.put(_cols(rng, 100), fids=np.arange(100))
    live.put(_cols(rng, 50), fids=np.arange(100, 150))
    live.log.close()

    with open(log_path, "ab") as fh:
        fh.write(b"\x90\x01\x00\x00partial-record-torn")  # torn tail

    live2 = LiveFeatureStore(sft, log=FileFeatureLog(str(log_path), sft))
    assert len(live2) == 150
    np.testing.assert_array_equal(
        np.sort(live2.snapshot().fids.astype(np.int64)), np.arange(150)
    )
