"""Out-of-core streamed device scan (store/oocscan.py): parity vs the
store's host path, manifest pruning, multi-slab streaming."""

import numpy as np
import pytest

from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.sql import SpatialFrame  # noqa: F401  (import side effects none)
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.oocscan import SlabStream, StreamedDeviceScan

ECQL = (
    "BBOX(geom, -10, 0, 40, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z"
)


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ooc")
    ds = FileSystemDataStore(str(tmp / "s"), partition_size=1 << 12)
    ds.create_schema(
        "t", "val:Int,tone:Float,dtg:Date,*geom:Point:srid=4326"
    )
    n = 60_000
    rng = np.random.default_rng(11)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-02-01T00:00:00")
    ds.write("t", {
        "val": rng.integers(0, 100, n),
        "tone": rng.uniform(-10, 10, n).astype(np.float32),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack(
            [rng.uniform(-60, 60, n), rng.uniform(-50, 50, n)], axis=1
        ),
    }, fids=np.arange(n))
    ds.flush("t")
    return ds


def test_count_parity_multi_slab(store):
    # slab far below the dataset: many slabs stream through the pump
    scan = StreamedDeviceScan(store, "t", slab_rows=1 << 13)
    want = len(store.query("t", ECQL).batch)
    assert scan.count(ECQL) == want
    # repeated query reuses the cached slab kernels (and stays right)
    assert scan.count(ECQL) == want


def test_count_parity_with_attribute_predicate(store):
    scan = StreamedDeviceScan(store, "t", slab_rows=1 << 13)
    q = ECQL + " AND val < 30"
    assert scan.count(q) == len(store.query("t", q).batch)


def test_query_parity_and_order_insensitive_fids(store):
    scan = StreamedDeviceScan(store, "t", slab_rows=1 << 13)
    got = scan.query(ECQL)
    want = store.query("t", ECQL).batch
    assert sorted(map(str, got.fids)) == sorted(map(str, want.fids))
    # residual (host-only) predicates refine per slab
    q = ECQL + " AND val IN (1, 2, 3)"
    got = scan.query(q)
    want = store.query("t", q).batch
    assert sorted(map(str, got.fids)) == sorted(map(str, want.fids))


def test_empty_result(store):
    scan = StreamedDeviceScan(store, "t", slab_rows=1 << 13)
    assert scan.count("BBOX(geom, 170, 80, 171, 81)") == 0
    assert len(scan.query("BBOX(geom, 170, 80, 171, 81)")) == 0


def test_pruning_streams_fewer_partitions(store):
    scan = StreamedDeviceScan(store, "t", slab_rows=1 << 13)
    _, all_parts = scan._parts("INCLUDE")
    _, pruned = scan._parts("BBOX(geom, -1, -1, 1, 1) AND "
                            "dtg DURING 2020-01-05T00:00:00Z/"
                            "2020-01-06T00:00:00Z")
    assert len(pruned) < len(all_parts)
    # and the pruned stream still answers exactly
    q = ("BBOX(geom, -1, -1, 1, 1) AND dtg DURING "
         "2020-01-05T00:00:00Z/2020-01-06T00:00:00Z")
    assert scan.count(q) == len(store.query("t", q).batch)


def test_slab_stream_pump_shapes_and_order():
    """The pump pads to pow2 buckets, packs 4-byte planes, keeps chunk
    order, and bounds in-flight slabs."""
    import jax.numpy as jnp

    def agg(cols, valid):
        return jnp.sum(jnp.where(valid, cols["a"], 0), dtype=jnp.int64)

    stream = SlabStream(agg, in_flight=2)
    chunks = [
        {"a": np.arange(10, dtype=np.int32)},
        {"a": np.arange(100, dtype=np.int32)},
        {"a": np.arange(3, dtype=np.int32)},
        {"a": np.zeros(0, dtype=np.int32)},  # empty chunk skipped
        {"a": np.arange(7, dtype=np.int32)},
    ]
    outs = stream.run(iter(chunks))
    assert [int(o) for o in outs] == [45, 4950, 3, 21]
    assert stream.slabs == 4 and stream.rows == 120


def test_stream_generator_yields_aux_aligned():
    """stream() pairs each output with ITS aux even when empty chunks
    are skipped, and retires slabs lazily (the larger-than-memory query
    path depends on both)."""
    import jax.numpy as jnp

    def agg(cols, valid):
        return jnp.sum(jnp.where(valid, cols["a"], 0), dtype=jnp.int32)

    stream = SlabStream(agg, in_flight=2)
    pairs = [
        ({"a": np.arange(10, dtype=np.int32)}, "p0"),
        ({"a": np.zeros(0, dtype=np.int32)}, "SKIP"),  # empty: aux dropped
        ({"a": np.arange(4, dtype=np.int32)}, "p2"),
        ({"a": np.arange(3, dtype=np.int32)}, "p3"),
    ]
    got = list(stream.stream(iter(pairs)))
    assert [(int(o), a) for o, a in got] == [(45, "p0"), (6, "p2"), (3, "p3")]
