"""Native C++ library vs Python oracle: bit-identical outputs."""

import numpy as np
import pytest

from geomesa_tpu import native
from geomesa_tpu.curves import zorder
from geomesa_tpu.curves.z3 import Z3SFC
from geomesa_tpu.curves.zranges import zranges

needs_native = pytest.mark.skipif(
    not native.available(), reason="native lib not built (no toolchain)"
)


@needs_native
def test_encode_3d_matches_numpy(rng):
    x = rng.integers(0, 1 << 21, 10000).astype(np.uint64)
    y = rng.integers(0, 1 << 21, 10000).astype(np.uint64)
    t = rng.integers(0, 1 << 21, 10000).astype(np.uint64)
    np.testing.assert_array_equal(
        native.encode_3d(x, y, t), zorder.encode_3d_np(x, y, t)
    )


@needs_native
def test_z3_index_fused_matches(rng):
    sfc = Z3SFC()
    x = rng.uniform(-180, 180, 10000)
    y = rng.uniform(-90, 90, 10000)
    t = rng.uniform(0, 604800, 10000)
    got = native.z3_index(x, y, t, 604800.0)
    np.testing.assert_array_equal(got, sfc.index(x, y, t))


@needs_native
@pytest.mark.parametrize(
    "qlo,qhi,bits,mr",
    [
        ((1, 2), (6, 5), 3, 1000),
        ((0, 0), (7, 7), 3, 1000),
        ((5, 9), (900, 700), 10, 64),
        ((0, 0, 0), ((1 << 21) - 1, (1 << 21) - 1, 1000), 21, 2000),
        ((123456, 654321, 1000), (1234567, 6543210, 2000), 21, 500),
        ((100, 200), (2**30, 2**30 + 5000), 31, 2000),
    ],
)
def test_zranges_bit_identical(qlo, qhi, bits, mr):
    py = zranges(qlo, qhi, bits, max_ranges=mr, use_native=False)
    cc = zranges(qlo, qhi, bits, max_ranges=mr, use_native=True)
    assert cc == py


@needs_native
def test_zranges_speed(rng):
    import time

    qlo = (0, 0, 0)
    qhi = ((1 << 21) - 1, (1 << 20), 10000)
    t0 = time.perf_counter()
    cc = zranges(qlo, qhi, 21, max_ranges=2000, use_native=True)
    t_cc = time.perf_counter() - t0
    t0 = time.perf_counter()
    py = zranges(qlo, qhi, 21, max_ranges=2000, use_native=False)
    t_py = time.perf_counter() - t0
    assert cc == py
    assert t_cc < t_py, f"native {t_cc:.4f}s not faster than python {t_py:.4f}s"
