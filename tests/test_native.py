"""Native C++ library vs Python oracle: bit-identical outputs."""

import numpy as np
import pytest

from geomesa_tpu import native
from geomesa_tpu.curves import zorder
from geomesa_tpu.curves.z3 import Z3SFC
from geomesa_tpu.curves.zranges import zranges

needs_native = pytest.mark.skipif(
    not native.available(), reason="native lib not built (no toolchain)"
)


@needs_native
def test_encode_3d_matches_numpy(rng):
    x = rng.integers(0, 1 << 21, 10000).astype(np.uint64)
    y = rng.integers(0, 1 << 21, 10000).astype(np.uint64)
    t = rng.integers(0, 1 << 21, 10000).astype(np.uint64)
    np.testing.assert_array_equal(
        native.encode_3d(x, y, t), zorder.encode_3d_np(x, y, t)
    )


@needs_native
def test_z3_index_fused_matches(rng):
    sfc = Z3SFC()
    x = rng.uniform(-180, 180, 10000)
    y = rng.uniform(-90, 90, 10000)
    t = rng.uniform(0, 604800, 10000)
    got = native.z3_index(x, y, t, 604800.0)
    np.testing.assert_array_equal(got, sfc.index(x, y, t))


@needs_native
@pytest.mark.parametrize(
    "qlo,qhi,bits,mr",
    [
        ((1, 2), (6, 5), 3, 1000),
        ((0, 0), (7, 7), 3, 1000),
        ((5, 9), (900, 700), 10, 64),
        ((0, 0, 0), ((1 << 21) - 1, (1 << 21) - 1, 1000), 21, 2000),
        ((123456, 654321, 1000), (1234567, 6543210, 2000), 21, 500),
        ((100, 200), (2**30, 2**30 + 5000), 31, 2000),
    ],
)
def test_zranges_bit_identical(qlo, qhi, bits, mr):
    py = zranges(qlo, qhi, bits, max_ranges=mr, use_native=False)
    cc = zranges(qlo, qhi, bits, max_ranges=mr, use_native=True)
    assert cc == py


@needs_native
def test_zranges_speed(rng):
    import time

    qlo = (0, 0, 0)
    qhi = ((1 << 21) - 1, (1 << 20), 10000)
    t0 = time.perf_counter()
    cc = zranges(qlo, qhi, 21, max_ranges=2000, use_native=True)
    t_cc = time.perf_counter() - t0
    t0 = time.perf_counter()
    py = zranges(qlo, qhi, 21, max_ranges=2000, use_native=False)
    t_py = time.perf_counter() - t0
    assert cc == py
    assert t_cc < t_py, f"native {t_cc:.4f}s not faster than python {t_py:.4f}s"


class TestBinserNative:
    """C++ batch decoder vs the pure-Python oracle: bit-identical."""

    def _roundtrip_batch(self, n=500, seed=77):
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.features.sft import SimpleFeatureType

        rng = np.random.default_rng(seed)
        sft = SimpleFeatureType.create(
            "t",
            "name:String,count:Int,big:Long,ratio:Float,score:Double,"
            "flag:Boolean,dtg:Date,*geom:Point",
        )
        batch = FeatureBatch.from_columns(
            sft,
            {
                "name": rng.choice(["alpha", "b", "", "日本語"], n),
                "count": rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64),
                "big": rng.integers(-(2**62), 2**62, n),
                "ratio": rng.normal(size=n).astype(np.float32),
                "score": rng.normal(size=n),
                "flag": rng.integers(0, 2, n).astype(bool),
                "dtg": rng.integers(0, 2**41, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)],
                    axis=1,
                ),
            },
            fids=np.arange(n),
        )
        return sft, batch

    def test_native_matches_python_oracle(self):
        import geomesa_tpu.native as native
        from geomesa_tpu.features.binser import (
            deserialize_batch,
            serialize_batch,
        )

        if not native.enabled():
            import pytest

            pytest.skip("native lib unavailable or disabled")
        sft, batch = self._roundtrip_batch()
        rows = serialize_batch(batch)
        got = deserialize_batch(sft, rows, use_native=True)
        want = deserialize_batch(sft, rows, use_native=False)
        np.testing.assert_array_equal(got.fids, want.fids)
        for name in batch.sft.attribute_names:
            g, w = got.columns[name], want.columns[name]
            assert g.dtype == w.dtype, f"{name}: {g.dtype} != {w.dtype}"
            if g.dtype == object:
                assert list(g) == list(w), name
            else:
                np.testing.assert_array_equal(g, w, err_msg=name)

    def test_native_string_fids_and_visibility(self):
        import geomesa_tpu.native as native
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.features.sft import SimpleFeatureType
        from geomesa_tpu.features.binser import (
            deserialize_batch,
            serialize_batch,
        )

        if not native.enabled():
            import pytest

            pytest.skip("native lib unavailable or disabled")
        sft = SimpleFeatureType.create("t", "name:String,*geom:Point")
        batch = FeatureBatch.from_columns(
            sft,
            {"name": ["a", "b", "c"], "geom": np.zeros((3, 2))},
            ["s1", "s2", "s3"],
        ).with_visibility(["secret", "", "a&b"])
        rows = serialize_batch(batch)
        got = deserialize_batch(sft, rows)
        assert list(got.fids) == ["s1", "s2", "s3"]
        assert list(got.visibilities) == ["secret", "", "a&b"]

    def test_native_null_numeric_falls_back(self):
        import geomesa_tpu.native as native
        from geomesa_tpu.features.binser import (
            FeatureSerializer,
            deserialize_batch,
        )
        from geomesa_tpu.features.sft import SimpleFeatureType

        if not native.enabled():
            import pytest

            pytest.skip("native lib unavailable or disabled")
        sft = SimpleFeatureType.create("t", "name:String,*geom:Point")
        ser = FeatureSerializer(sft)
        rows = [
            ser.serialize("a", [None, (1.0, 2.0)]),  # null string
            ser.serialize("b", ["x", (3.0, 4.0)]),
        ]
        got = deserialize_batch(sft, rows)
        assert list(got.columns["name"]) == [None, "x"]
        np.testing.assert_allclose(
            got.columns["geom"], [[1.0, 2.0], [3.0, 4.0]]
        )

    def test_native_decode_speedup(self):
        """The point of the C++ pass: meaningfully faster than Python."""
        import time

        import geomesa_tpu.native as native
        from geomesa_tpu.features.binser import (
            deserialize_batch,
            serialize_batch,
        )

        if not native.enabled():
            import pytest

            pytest.skip("native lib unavailable or disabled")
        sft, batch = self._roundtrip_batch(n=20000)
        rows = serialize_batch(batch)
        t = time.perf_counter()
        deserialize_batch(sft, rows, use_native=False)
        t_py = time.perf_counter() - t
        t = time.perf_counter()
        deserialize_batch(sft, rows, use_native=True)
        t_nat = time.perf_counter() - t
        assert t_nat < t_py  # typically 5-20x; just pin the direction


def test_native_xz_index_bit_identical(rng):
    """C++ XZ extent-curve walk == the numpy oracle, including exact
    power-of-two extents, degenerate point boxes and the whole space."""
    from geomesa_tpu.curves.xz import XZSFC

    if not native.enabled():
        pytest.skip("native library unavailable")
    if not getattr(native.get_lib(), "_has_xz", False):
        pytest.skip("prebuilt library lacks gm_xz_index")
    for dims, g in ((2, 12), (3, 12), (2, 20)):
        sfc = XZSFC(g, dims)
        n = 40_000
        mins = rng.uniform(0, 0.98, (dims, n))
        ext = rng.uniform(0, 0.05, (dims, n)) * rng.choice([0, 1], (dims, n))
        maxs = np.minimum(mins + ext, 1.0)
        nat = sfc.index(mins, maxs)
        ora = sfc.index(mins, maxs, use_native=False)
        np.testing.assert_array_equal(nat, ora)
    sfc = XZSFC(12, 2)
    mins = np.array([[0.0, 0.25, 0.5, 0.0], [0.0, 0.25, 0.5, 0.0]])
    maxs = np.array([[1.0, 0.5, 0.5, 2.0**-12], [1.0, 0.5, 0.5, 2.0**-12]])
    np.testing.assert_array_equal(
        sfc.index(mins, maxs), sfc.index(mins, maxs, use_native=False)
    )


def test_radix_argsort_matches_lexsort():
    """The native LSD radix argsort must be BIT-IDENTICAL to the numpy
    stable lexsort oracle — stability over duplicates, signed biasing,
    hi/lo 64-bit lane splits, and the constant-digit pass skip all ride
    on it (a silent mis-sort corrupts every flushed index)."""
    from geomesa_tpu import native

    if not native.enabled() or not getattr(native.get_lib(), "_has_sort", False):
        pytest.skip("native sort not built")
    rng = np.random.default_rng(42)
    n = 100_000
    cases = [
        # z3-shaped: narrow int32 bin + uint64 z (hi/lo split)
        [rng.integers(2600, 2604, n).astype(np.int32),
         rng.integers(0, 1 << 63, n, dtype=np.uint64)],
        # duplicate-heavy (stability): tiny key alphabet
        [np.zeros(n, np.int32), rng.integers(0, 3, n, dtype=np.uint64)],
        # negative int64 (sign-bias mapping)
        [rng.integers(-10**12, 10**12, n).astype(np.int64)],
        # negative int32 alone
        [rng.integers(-5, 5, n).astype(np.int32)],
        # xz-shaped int64 codes
        [rng.integers(0, 10**14, n).astype(np.int64)],
        # three lanes
        [rng.integers(-3, 3, n).astype(np.int32),
         rng.integers(0, 1 << 40, n, dtype=np.uint64),
         rng.integers(0, 7, n).astype(np.uint32)],
        # constant lane (every digit pass skipped)
        [np.full(n, 7, np.int32), rng.integers(0, 100, n, dtype=np.uint64)],
    ]
    for cols in cases:
        got = native.radix_argsort(cols)
        assert got is not None
        ref = (
            np.argsort(cols[0], kind="stable")
            if len(cols) == 1
            else np.lexsort(tuple(reversed(cols)))
        )
        assert np.array_equal(got, ref), [c.dtype for c in cols]
    # empty + object-dtype fall through
    assert len(native.radix_argsort([np.empty(0, np.int32)])) == 0
    assert native.radix_argsort([np.array(["a"], dtype=object)]) is None
