"""Host-I/O prefetch pipeline (store/prefetch.py) and its integrations:
ordered delivery, bounded read-ahead, error/cancel hygiene (no deadlocks,
no leaked threads), serial-vs-pipelined result parity for the out-of-core
scan / FS store / bulk ingest, the scheduler-deadline drain, and the
bench smoke leg."""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.oocscan import StreamedDeviceScan
from geomesa_tpu.store.prefetch import (
    WORKER_PREFIX,
    PrefetchConfig,
    prefetch_map,
)

ECQL = (
    "BBOX(geom, -10, 0, 40, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z"
)


def _io_threads() -> list:
    return [
        t for t in threading.enumerate() if t.name.startswith(WORKER_PREFIX)
    ]


def _assert_io_threads_gone(timeout_s: float = 5.0) -> None:
    """Prefetch workers must be joined when their pipeline ends — poll
    briefly (executor shutdown joins, but give the OS a beat)."""
    deadline = time.monotonic() + timeout_s
    while _io_threads():
        if time.monotonic() > deadline:
            raise AssertionError(f"leaked io threads: {_io_threads()}")
        time.sleep(0.01)


# -- prefetch_map core -------------------------------------------------------


@pytest.mark.parametrize("workers", [0, 1, 4])
def test_order_and_results(workers):
    """Results arrive in input order at every worker count — including
    when late items finish before early ones."""
    def fn(i):
        time.sleep(0.002 * ((7 - i) % 5))  # early items are SLOW
        return i * i

    got = list(prefetch_map(fn, range(12), PrefetchConfig(workers=workers)))
    assert got == [i * i for i in range(12)]
    _assert_io_threads_gone()


def test_serial_workers0_spawns_no_threads():
    before = threading.active_count()
    assert list(prefetch_map(lambda i: i, range(8), 0)) == list(range(8))
    assert threading.active_count() == before


def test_readahead_is_bounded_and_overlaps():
    """At most ``depth`` items are in flight, and with workers > 1 the
    pipeline genuinely overlaps (two fn calls concurrent at some point).
    """
    live = {"now": 0, "max": 0}
    lock = threading.Lock()

    def fn(i):
        with lock:
            live["now"] += 1
            live["max"] = max(live["max"], live["now"])
        time.sleep(0.01)
        with lock:
            live["now"] -= 1
        return i

    cfg = PrefetchConfig(workers=4, depth=3)
    consumed = 0
    for _ in prefetch_map(fn, range(12), cfg):
        consumed += 1
        time.sleep(0.002)
    assert consumed == 12
    assert live["max"] <= 3  # never more than depth in flight
    assert live["max"] >= 2  # and the overlap actually happened


def test_items_iterator_stays_on_consumer_thread():
    """The items generator is advanced only on the consuming thread (the
    documented contract that lets plain generators feed the pipeline)."""
    main = threading.current_thread()
    seen = []

    def items():
        for i in range(6):
            seen.append(threading.current_thread())
            yield i

    assert list(prefetch_map(lambda i: i, items(), 2)) == list(range(6))
    assert all(t is main for t in seen)


def test_byte_budget_throttles_but_completes():
    """A byte budget far below the stream size stalls top-up, never the
    pipeline: everything still arrives, in order."""
    cfg = PrefetchConfig(workers=4, depth=8, byte_budget=100)
    out = list(prefetch_map(
        lambda i: bytes(64), range(10), cfg, size_of=len
    ))
    assert len(out) == 10
    _assert_io_threads_gone()


def test_error_propagates_at_position_and_cleans_up():
    """An fn exception surfaces at ITS position; the pipeline then shuts
    down without deadlocking or leaking threads, and items beyond the
    read-ahead window were never started."""
    started = []

    def fn(i):
        started.append(i)
        if i == 3:
            raise RuntimeError("decode failed")
        return i

    cfg = PrefetchConfig(workers=2, depth=2)
    got = []
    with pytest.raises(RuntimeError, match="decode failed"):
        for v in prefetch_map(fn, range(100), cfg):
            got.append(v)
    assert got == [0, 1, 2]
    assert len(started) < 100  # the tail was cancelled, not run
    _assert_io_threads_gone()
    # the failed item must not leak into the in-flight gauge (regression:
    # it was popped before .result() raised, skipping its decrement)
    from geomesa_tpu.metrics import io_prefetch_depth, io_queue_bytes

    assert io_prefetch_depth.value() == 0
    assert io_queue_bytes.value() == 0


def test_close_mid_stream_cancels():
    """Closing the generator early (consumer abandons the scan) joins
    the workers and stops consuming items."""
    pulled = []

    def items():
        for i in range(1000):
            pulled.append(i)
            yield i

    gen = prefetch_map(lambda i: i, items(), PrefetchConfig(workers=2, depth=4))
    assert next(gen) == 0
    assert next(gen) == 1
    gen.close()
    _assert_io_threads_gone()
    assert len(pulled) <= 2 + 4 + 1  # consumed + read-ahead, not the stream


# -- store integration -------------------------------------------------------


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("prefetch")
    ds = FileSystemDataStore(str(tmp / "s"), partition_size=1 << 11)
    ds.create_schema(
        "t", "val:Int,tone:Float,dtg:Date,*geom:Point:srid=4326"
    )
    n = 40_000
    rng = np.random.default_rng(23)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-02-01T00:00:00")
    ds.write("t", {
        "val": rng.integers(0, 100, n),
        "tone": rng.uniform(-10, 10, n).astype(np.float32),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack(
            [rng.uniform(-60, 60, n), rng.uniform(-50, 50, n)], axis=1
        ),
    }, fids=np.arange(n))
    ds.flush("t")
    return ds


@pytest.mark.parametrize("workers", [1, 4])
def test_oocscan_parity_prefetched_vs_serial(store, workers):
    """count AND query parity, same hits in the SAME order, between the
    serial baseline (io=0) and the pipelined path — the byte-identical
    contract of the acceptance criteria."""
    serial = StreamedDeviceScan(store, "t", slab_rows=1 << 13, io=0)
    piped = StreamedDeviceScan(
        store, "t", slab_rows=1 << 13, io=PrefetchConfig(workers=workers)
    )
    for q in (ECQL, ECQL + " AND val < 30", "BBOX(geom, 170, 80, 171, 81)"):
        assert piped.count(q) == serial.count(q)
        got, want = piped.query(q), serial.query(q)
        assert list(map(str, got.fids)) == list(map(str, want.fids))
        np.testing.assert_array_equal(
            got.column("val"), want.column("val")
        )
    _assert_io_threads_gone()


def test_oocscan_pairs_alignment_regression(store):
    """Regression for the old ``groups.pop(0)`` side channel: every
    (host_cols, source_batch) pair the pipeline yields must be
    self-consistent — the staged planes ARE the staging of that exact
    batch — even when pairs are materialized out of lockstep with the
    consumer (the prefetcher runs chunks ahead). Under the old implicit
    chunk<->batch pairing, consuming the chunk stream ahead of the
    gather desynced the two lists; explicit tuples make that skew
    structurally impossible."""
    from geomesa_tpu.ops.scan import stage_columns_host

    scan = StreamedDeviceScan(
        store, "t", slab_rows=1 << 12, io=PrefetchConfig(workers=4)
    )
    plan, parts = scan._parts(ECQL)
    names = plan.compiled.device_cols
    pairs = list(scan._pairs(parts, names))  # materialize ALL ahead
    assert len(pairs) > 3  # multi-chunk stream or the test proves nothing
    for cols, batch in pairs:
        want = stage_columns_host(batch, names)
        assert set(cols) == set(want)
        for k in names:
            assert len(cols[k]) == len(batch)
            np.testing.assert_array_equal(cols[k], want[k])


def test_oocscan_under_exclusive_lock_degrades_to_serial(store):
    """A scan issued by a thread HOLDING the store's exclusive lock (an
    in-place maintenance job) must degrade to in-line serial reads:
    worker threads could neither see the holder's re-entrant lock depth
    nor take a shared flock against our own exclusive one — without the
    guard this deadlocks, then dies with LockTimeout."""
    want = StreamedDeviceScan(store, "t", slab_rows=1 << 13).count(ECQL)
    scan = StreamedDeviceScan(
        store, "t", slab_rows=1 << 13, io=PrefetchConfig(workers=4)
    )
    with store._exclusive():
        assert scan.count(ECQL) == want


def test_query_partitions_under_exclusive_lock_degrades(store):
    """Iterating query_partitions from a thread holding the store's
    exclusive lock worked serially pre-pipeline (the re-entrant lock
    depth short-circuits _shared); with workers it must DEGRADE to that
    serial path rather than deadlock workers on the consumer-held
    _mem_lock."""
    try:
        store.io = PrefetchConfig(workers=4)
        want = sum(len(b) for b in store.query_partitions("t", ECQL))
        with store._exclusive():
            got = sum(len(b) for b in store.query_partitions("t", ECQL))
    finally:
        store.io = None
    assert got == want > 0


def test_oocscan_stream_cache_lru_bounded(store):
    """Satellite: the compiled-stream cache must not grow without bound
    across many distinct filters — and eviction must not break results."""
    scan = StreamedDeviceScan(store, "t", slab_rows=1 << 13)
    cap = StreamedDeviceScan.STREAM_CACHE_MAX
    counts = {}
    for i in range(cap + 5):
        q = f"BBOX(geom, {-10 - i}, 0, 40, 45)"
        counts[q] = scan.count(q)
        assert len(scan._streams) <= cap
    # the oldest filters were evicted; re-querying them still answers
    # exactly (a fresh stream is compiled on demand)
    for q, want in list(counts.items())[:3]:
        assert scan.count(q) == want


def test_oocscan_decode_error_no_deadlock_no_leak(store, monkeypatch):
    """A decode error mid-stream must surface as the scan's exception —
    not hang the bounded queue — and must leave no worker threads
    behind; the store then serves the next scan normally."""
    real = FileSystemDataStore._read_part_table
    calls = {"n": 0}

    def flaky(self, type_name, p):
        calls["n"] += 1
        if calls["n"] == 3:
            raise ValueError("corrupt partition file")
        return real(self, type_name, p)

    store._types["t"].cache = {}  # cached partitions would skip the read
    monkeypatch.setattr(FileSystemDataStore, "_read_part_table", flaky)
    scan = StreamedDeviceScan(
        store, "t", slab_rows=1 << 12, io=PrefetchConfig(workers=4)
    )
    with pytest.raises(ValueError, match="corrupt partition"):
        scan.count(ECQL)
    _assert_io_threads_gone()
    monkeypatch.undo()
    # the failed scan released the store lock: fresh scans still answer
    want = len(store.query("t", ECQL).batch)
    assert StreamedDeviceScan(store, "t", slab_rows=1 << 12).count(ECQL) == want


def test_scheduler_deadline_drains_inflight_prefetch(store, monkeypatch):
    """The scheduler's deadline path (HTTP 504 in the server) while a
    prefetch is in flight: the single device worker is busy with an
    oocscan whose pipeline is mid-read-ahead, a second request expires
    in the queue (-> DeadlineExpired to its waiter, the 504), and the
    in-flight pipeline still runs to completion, answers exactly, and
    winds down without leaking a thread."""
    from geomesa_tpu.sched import DeadlineExpired, QueryScheduler, SchedConfig

    real = FileSystemDataStore._read_part_table
    started = threading.Event()

    def slow(self, type_name, p):
        started.set()
        time.sleep(0.02)  # keep the prefetch in flight past the deadline
        return real(self, type_name, p)

    scan = StreamedDeviceScan(
        store, "t", slab_rows=1 << 12, io=PrefetchConfig(workers=2)
    )
    want = len(store.query("t", ECQL).batch)  # BEFORE the slow patch
    # drop pinned partitions so the scheduled scan actually hits the
    # (slowed) read path — cached reads would finish inside the deadline
    store._types["t"].cache = {}
    monkeypatch.setattr(FileSystemDataStore, "_read_part_table", slow)
    with QueryScheduler(SchedConfig(max_inflight=1)) as sched:
        inflight = sched.submit(fn=lambda: scan.count(ECQL))
        assert started.wait(timeout=10.0)  # its prefetch is running NOW
        expired = sched.submit(
            fn=lambda: scan.count(ECQL), deadline_ms=30.0
        )
        with pytest.raises(DeadlineExpired):
            sched.wait(expired)  # the 504: expired while queued
        # ...and the in-flight scan's pipeline drains to the exact count
        assert sched.wait(inflight) == want
    _assert_io_threads_gone()
    monkeypatch.undo()
    assert StreamedDeviceScan(store, "t", slab_rows=1 << 12).count(ECQL) == want


def test_fs_query_parity_across_io_workers(store):
    """The FS store's own scan (plan + per-partition read + merge) is
    byte-identical with the pipeline on and off."""
    try:
        store.io = 0
        base = store.query("t", ECQL)
        store.io = PrefetchConfig(workers=4)
        res = store.query("t", ECQL)
    finally:
        store.io = None
    assert list(map(str, res.batch.fids)) == list(map(str, base.batch.fids))
    assert res.scanned == base.scanned


def test_fs_read_all_merge_parity(tmp_path):
    """Flush-merge (_read_all rides the pipeline under the exclusive
    lock): a second write merges with partitions read in parallel, and
    the merged dataset is exactly the union."""
    ds = FileSystemDataStore(
        str(tmp_path / "s"), partition_size=1 << 8,
        io=PrefetchConfig(workers=4),
    )
    ds.create_schema("t", "val:Int,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(5)
    t0 = parse_instant("2020-01-01T00:00:00")

    def rows(n, base):
        return {
            "val": np.arange(base, base + n),
            "dtg": rng.integers(t0, t0 + 10_000_000, n),
            "geom": np.stack(
                [rng.uniform(-60, 60, n), rng.uniform(-50, 50, n)], axis=1
            ),
        }

    ds.write("t", rows(3000, 0), fids=np.arange(3000))
    ds.flush("t")
    ds.write("t", rows(2000, 3000), fids=np.arange(3000, 5000))
    ds.flush("t")  # merge path: _read_all over ~12 partitions
    got = ds.query("t", "INCLUDE").batch
    assert sorted(int(v) for v in got.column("val")) == list(range(5000))


def test_parallel_ingest_pipelined_deterministic_and_error_isolated(tmp_path):
    """Bulk ingest through the pipeline: file order in = write order in
    (deterministic replay), a bad file is reported without killing the
    run, and worker counts do not change the stored result."""
    from geomesa_tpu.jobs import parallel_ingest

    conv = {"type": "delimited-text", "format": "csv", "fields": [
        {"name": "val", "transform": "$1::int"},
        {"name": "geom", "transform": "point($2::double, $3::double)"},
    ]}
    files = []
    for i in range(6):
        p = tmp_path / f"in-{i}.csv"
        p.write_text("".join(
            f"{i * 10 + j},{float(i)},{float(j)}\n" for j in range(10)
        ))
        files.append(str(p))
    bad = tmp_path / "missing.csv"  # never created -> open() fails
    files.insert(3, str(bad))

    def run(root, workers):
        ds = FileSystemDataStore(str(tmp_path / root), partition_size=1 << 10)
        ds.create_schema("t", "val:Int,*geom:Point:srid=4326")
        rep = parallel_ingest(ds, "t", conv, files, workers=workers)
        vals = [int(v) for v in ds.query("t", "INCLUDE").batch.column("val")]
        return rep, vals

    rep4, vals4 = run("w4", 4)
    rep0, vals0 = run("w0", 0)
    assert rep4.success == rep0.success == 60
    assert [e[0] for e in rep4.errors] == [str(bad)]
    assert [e[0] for e in rep0.errors] == [str(bad)]
    assert sorted(vals4) == sorted(vals0) == list(range(60))
    assert vals4 == vals0  # write order identical at every worker count
    _assert_io_threads_gone()


def test_io_metrics_exported():
    """The geomesa_io_* series ride the registry (ops dashboards key on
    the names)."""
    from geomesa_tpu.metrics import REGISTRY

    text = REGISTRY.prometheus_text()
    for name in (
        "geomesa_io_read_seconds",
        "geomesa_io_decode_seconds",
        "geomesa_io_stage_seconds",
        "geomesa_io_prefetch_depth",
        "geomesa_io_queue_bytes",
        "geomesa_io_chunks_total",
    ):
        assert name in text


# -- bench smoke leg (CI guard) ---------------------------------------------


def _bench_args(**kw):
    import argparse

    ns = argparse.Namespace(
        n=None, check=False, smoke=True, io_workers=0, iters=3
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def test_bench_oocscan_smoke_leg():
    """The fast CI leg: store-integrated serial vs pipelined sustained
    MB/s with the regression guard, at a size tier-1 can afford."""
    bench = pytest.importorskip("bench")
    out = bench._bench_oocscan_store(_bench_args(n=1 << 15), smoke=True)
    assert out["oocscan_smoke"] is True
    assert out["oocscan_serial_mbps"] > 0
    assert out["oocscan_pipelined_mbps"] > 0
    # serial and pipelined counted the same hits (asserted inside too)
    assert out["oocscan_store_hits"] >= 0


@pytest.mark.slow
def test_bench_oocscan_full_leg():
    """The full leg (device pump + big store leg) — slow by design; the
    driver's bench run records it, tier-1 skips it."""
    bench = pytest.importorskip("bench")
    out = bench.bench_oocscan(_bench_args(smoke=False, n=1 << 20))
    assert out["oocscan_sustained_mbps"] > 0
    assert out["oocscan_pipelined_mbps"] > 0
