"""Jobs: parallel ingest/export, KV index back-population, FS re-index."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.jobs import (
    backpopulate_index,
    parallel_export,
    parallel_ingest,
)
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.kv import KVDataStore, MemoryKV

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"

CSV_CONFIG = {
    "type": "delimited-text",
    "format": "csv",
    "id-field": "$1",
    "fields": [
        {"name": "name", "transform": "$2"},
        {"name": "dtg", "transform": "$3::long"},
        {"name": "geom", "transform": "point($4::double, $5::double)"},
    ],
}


def _write_csvs(tmp_path, n_files=6, rows=50):
    files = []
    k = 0
    for i in range(n_files):
        lines = []
        for _ in range(rows):
            lines.append(f"f{k},n{k % 3},{k * 1000},{(k % 360) - 180},{(k % 180) - 90}")
            k += 1
        p = tmp_path / f"in{i}.csv"
        p.write_text("\n".join(lines) + "\n")
        files.append(str(p))
    return files


def test_parallel_ingest(tmp_path):
    ds = FileSystemDataStore(str(tmp_path / "store"))
    ds.create_schema("t", SPEC)
    files = _write_csvs(tmp_path)
    rep = parallel_ingest(ds, "t", CSV_CONFIG, files, workers=4)
    assert rep.files == 6 and rep.failed == 0 and not rep.errors
    assert rep.success == 300
    assert ds.count("t") == 300


def test_parallel_ingest_collects_errors(tmp_path):
    ds = FileSystemDataStore(str(tmp_path / "store"))
    ds.create_schema("t", SPEC)
    files = _write_csvs(tmp_path, n_files=2)
    files.append(str(tmp_path / "missing.csv"))
    rep = parallel_ingest(ds, "t", CSV_CONFIG, files, workers=2)
    assert rep.success == 100
    assert len(rep.errors) == 1 and "missing.csv" in rep.errors[0][0]


def test_parallel_export_partition_files(tmp_path):
    ds = FileSystemDataStore(str(tmp_path / "store"), partition_size=64)
    ds.create_schema("t", SPEC)
    files = _write_csvs(tmp_path, n_files=4, rows=100)
    parallel_ingest(ds, "t", CSV_CONFIG, files, workers=2)
    out = str(tmp_path / "export")
    paths = parallel_export(ds, "t", "INCLUDE", out, fmt="parquet", workers=4)
    assert len(paths) > 1
    import pyarrow.parquet as pq

    total = sum(pq.read_table(p).num_rows for p in paths)
    assert total == 400


def test_kv_backpopulate_attribute_index():
    ds = KVDataStore(MemoryKV())
    ds.create_schema("t", SPEC)
    n = 500
    rng = np.random.default_rng(2)
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "dtg": rng.integers(0, 10**6, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    assert "attr:name" not in ds.indices("t")
    written = backpopulate_index(ds, "t", "attr:name")
    assert written == n
    assert "attr:name" in ds.indices("t")
    # the new index serves equality queries with real pruning
    res = ds.query("t", "name = 'a'")
    plan = ds.plan("t", "name = 'a'")
    assert plan.index_name == "attr:name"
    oracle = ds.query("t", "INCLUDE").batch
    expected = (oracle.column("name") == "a").sum()
    assert len(res) == expected
    assert res.scanned < n  # actually pruned via the new index
    # new writes maintain the new index too
    ds.write(
        "t",
        {"name": ["a"], "dtg": [1], "geom": np.zeros((1, 2))},
        fids=["extra"],
    )
    assert len(ds.query("t", "name = 'a'")) == expected + 1
    # duplicate add rejected; id index protected
    with pytest.raises(ValueError):
        ds.add_index("t", "attr:name")
    with pytest.raises(ValueError):
        ds.remove_index("t", "id")
    ds.remove_index("t", "attr:name")
    assert "attr:name" not in ds.indices("t")
    assert len(ds.query("t", "name = 'a'")) == expected + 1  # still correct


def test_invalid_attr_index_rejected_without_damage(tmp_path):
    # KV: unknown attribute rejected up front, no orphan table, writes fine
    kv = KVDataStore(MemoryKV())
    kv.create_schema("t", SPEC)
    kv.write("t", {"name": ["a"], "dtg": [0], "geom": np.zeros((1, 2))}, ["f0"])
    with pytest.raises(ValueError, match="no attribute"):
        kv.add_index("t", "attr:nope")
    kv.write("t", {"name": ["b"], "dtg": [0], "geom": np.zeros((1, 2))}, ["f1"])
    assert len(kv.query("t", "INCLUDE")) == 2

    # FS: invalid reindex raises before data is lost; store still queryable
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("t", SPEC)
    fs.write("t", {"name": ["a"], "dtg": [0], "geom": np.zeros((1, 2))}, ["f0"])
    fs.flush("t")
    with pytest.raises(ValueError, match="no attribute"):
        fs.reindex("t", "attr:nope")
    assert fs.count("t") == 1
    fs2 = FileSystemDataStore(str(tmp_path))  # reopen still works
    assert fs2.count("t") == 1


def test_fs_flush_failure_preserves_data(tmp_path, monkeypatch):
    # if the rewrite fails mid-flush the dataset stays pending in memory
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("t", SPEC)
    fs.write("t", {"name": ["a", "b"], "dtg": [0, 1], "geom": np.zeros((2, 2))},
             ["f0", "f1"])
    fs.flush("t")
    import geomesa_tpu.store.fs as fsmod

    def boom(*a, **k):
        raise RuntimeError("disk full")

    monkeypatch.setattr(fsmod, "_write_part_file", boom)
    with pytest.raises(RuntimeError):
        fs.reindex("t", "z2")
    monkeypatch.undo()
    # data still pending; a retry fully recovers it
    fs.flush("t")
    assert fs.count("t") == 2


def test_fs_reindex_and_repartition(tmp_path):
    ds = FileSystemDataStore(str(tmp_path), partition_size=128)
    ds.create_schema("t", SPEC)
    n = 1000
    rng = np.random.default_rng(4)
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b"], n),
            "dtg": rng.integers(0, 10**6, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    ds.flush("t")
    before = sorted(ds.query("t", "BBOX(geom, 0, 0, 90, 90)").batch.fids)
    ds.reindex("t", "z2")
    assert ds._types["t"].primary == "z2"
    after = sorted(ds.query("t", "BBOX(geom, 0, 0, 90, 90)").batch.fids)
    np.testing.assert_array_equal(before, after)
    # reopen: new primary persisted
    ds2 = FileSystemDataStore(str(tmp_path))
    assert ds2._types["t"].primary == "z2"
    # repartition into an attribute layout
    ds2.repartition("t", "attribute:name")
    assert (tmp_path / "t" / "a").is_dir()
    assert ds2.count("t") == n
    res = ds2.query("t", "name = 'b'")
    assert res.scanned < n  # leaf pruned
    # drop the scheme again
    ds2.repartition("t", None)
    assert ds2.count("t") == n
