"""Continuous queries (ISSUE 16): the geofence/alert push tier.

The contracts under test:

- **Registry**: bbox/CQL/dwithin predicates validate at subscribe time;
  the registry persists through its own WAL (recovering on reopen) and
  replicates through the ordinary ship plumbing (``apply_replicated``
  is idempotent, gaps raise).
- **Matcher**: every acked append batch costs exactly ONE fused join
  launch no matter how many subscriptions are armed (launch counts are
  counted, never trusted); residuals are exact — coarse envelope hits
  are refined by visibility (fail closed), exact dwithin distance, and
  full CQL evaluation.
- **Delivery**: the WAL seq is the cursor. A resuming subscriber gets
  replay below its watermark and live above it, exactly once; a slow
  consumer tears down bounded (``end: overflow``) and resumes from the
  cursor; a cursor below the compacted tail is an honest 410; a match
  fault never un-acks the append (replay re-derives the alert).
- **Commit gate**: under ``replica.ack=replica`` the leader holds
  alerts until the seq is follower-applied, so a failover can never
  void-and-reassign a seq a subscriber already acked.
- **Failover**: the registry rides the WAL ship; a promoted follower
  re-arms matching and a reconnecting subscriber sees zero missed and
  zero duplicate alerts across the promotion.
- **HTTP plane**: SSE framing (``id:`` = seq, ``:keepalive``
  heartbeats that survive the idle-socket reaper), ``Last-Event-ID``
  resume, negotiated arrow/bin push formats, router forwarding.
"""

import json
import math
import shutil
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override
from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.store.fs import FileSystemDataStore

SPEC = "val:Int,dtg:Date,*geom:Point:srid=4326"


def _mk_store(tmp_path, name="store"):
    root = str(tmp_path / name)
    ds = FileSystemDataStore(root, partition_size=128)
    ds.create_schema("t", SPEC)
    return root, ds


def _cols(pts, vals=None):
    pts = np.asarray(pts, dtype=float)
    n = len(pts)
    return {
        "val": np.asarray(vals if vals is not None else range(n)),
        "dtg": np.arange(n) + 1000,
        "geom": pts,
    }


def _wait(pred, timeout_s=20.0, poll_s=0.05, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out waiting for {msg}")


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, doc, timeout=30):
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _delete(base, path, timeout=30):
    req = urllib.request.Request(base + path, method="DELETE")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _append_doc(fids, x=10.0, vals=None):
    n = len(fids)
    return {
        "columns": {
            "val": list(vals) if vals is not None else list(range(n)),
            "dtg": [1000 + i for i in range(n)],
            "geom": [[x, x]] * n,
        },
        "fids": list(fids),
    }


# -- registry ----------------------------------------------------------------


def test_subscription_parse_validates(tmp_path):
    from geomesa_tpu.pubsub.registry import Subscription

    _, ds = _mk_store(tmp_path)
    sft = ds.get_schema("t")

    def parse(doc):
        return Subscription.parse(
            "t", doc, sft, tenant="tn", auths=(), created_seq=-1
        )

    sub = parse({"bbox": [0, 0, 10, 10], "cql": "val > 5"})
    assert sub.type_name == "t" and sub.tenant == "tn"
    assert len(sub.sub_id) == 12
    with pytest.raises(ValueError):
        parse({"bbox": [10, 0, 0, 10]})  # unordered
    with pytest.raises(ValueError):
        parse({"bbox": [0, 0, 10]})  # not 4 numbers
    with pytest.raises(ValueError):
        parse({})  # at least one predicate required
    with pytest.raises(ValueError):
        parse({"cql": "val >"})  # unparseable ECQL
    with pytest.raises(ValueError):
        parse({"dwithin": {"x": 0, "y": 0}})  # missing distance
    with pytest.raises(ValueError):
        parse({"dwithin": {"x": 0, "y": 0, "distance": -1}})


def test_subscription_envelope_intersects_predicates(tmp_path):
    from geomesa_tpu.pubsub.registry import Subscription

    _, ds = _mk_store(tmp_path)
    sft = ds.get_schema("t")
    sub = Subscription.parse(
        "t",
        {"bbox": [0, 0, 10, 10], "dwithin": {"x": 2, "y": 2, "distance": 1}},
        sft, tenant="x", auths=(), created_seq=-1,
    )
    assert tuple(sub.envelope()) == (1.0, 1.0, 3.0, 3.0)  # bbox ∩ dwithin
    # provably-disjoint predicates make an empty (NaN) envelope: the
    # matcher keeps the row slot but masks it out of every result
    empty = Subscription.parse(
        "t",
        {"bbox": [0, 0, 1, 1], "dwithin": {"x": 50, "y": 50, "distance": 1}},
        sft, tenant="x", auths=(), created_seq=-1,
    )
    assert all(math.isnan(v) for v in empty.envelope())


def test_registry_persists_and_recovers(tmp_path):
    from geomesa_tpu.pubsub.registry import Subscription, SubscriptionRegistry

    root, ds = _mk_store(tmp_path)
    sft = ds.get_schema("t")
    reg = SubscriptionRegistry(root)
    a = Subscription.parse("t", {"bbox": [0, 0, 5, 5]}, sft,
                           tenant="a", auths=(), created_seq=3)
    b = Subscription.parse("t", {"cql": "val > 1"}, sft,
                           tenant="b", auths=("secret",), created_seq=4)
    reg.subscribe(a)
    reg.subscribe(b)
    assert reg.count("t") == 2
    assert reg.unsubscribe(a.sub_id)
    gen = reg.gen
    reg.close()

    reg2 = SubscriptionRegistry(root)
    assert reg2.count("t") == 1
    got = reg2.get(b.sub_id)
    assert got is not None
    assert got.tenant == "b" and got.auths == ("secret",)
    assert got.created_seq == 4
    assert reg2.gen >= gen  # layout caches keyed on gen stay invalid
    reg2.close()


def test_registry_apply_replicated_idempotent_and_gapless(tmp_path):
    from geomesa_tpu.pubsub.registry import Subscription, SubscriptionRegistry

    root, ds = _mk_store(tmp_path)
    sft = ds.get_schema("t")
    leader = SubscriptionRegistry(root)
    s = Subscription.parse("t", {"bbox": [0, 0, 5, 5]}, sft,
                           tenant="a", auths=(), created_seq=-1)
    leader.subscribe(s)
    leader.unsubscribe(s.sub_id)
    records = list(leader.wal.read_from(-1))
    leader.close()

    froot = str(tmp_path / "follower")
    fds = FileSystemDataStore(froot, partition_size=128)
    fds.create_schema("t", SPEC)
    f = SubscriptionRegistry(froot)
    assert f.apply_replicated(*records[0]) is True
    assert f.apply_replicated(*records[0]) is False  # idempotent re-ship
    with pytest.raises(ValueError):
        f.apply_replicated(records[1][0] + 5, records[1][1])  # gap
    assert f.apply_replicated(*records[1]) is True
    assert f.count("t") == 0  # subscribe then unsubscribe, converged
    f.close()


def test_registry_cap_per_type(tmp_path):
    from geomesa_tpu.pubsub.registry import Subscription, SubscriptionRegistry

    root, ds = _mk_store(tmp_path)
    sft = ds.get_schema("t")
    reg = SubscriptionRegistry(root)
    with prop_override("sub.max.per.type", 2):
        for _ in range(2):
            reg.subscribe(Subscription.parse(
                "t", {"bbox": [0, 0, 5, 5]}, sft,
                tenant="a", auths=(), created_seq=-1))
        with pytest.raises(ValueError):
            reg.subscribe(Subscription.parse(
                "t", {"bbox": [0, 0, 5, 5]}, sft,
                tenant="a", auths=(), created_seq=-1))
    reg.close()


# -- matcher + in-process delivery -------------------------------------------


@pytest.fixture
def hub_env(tmp_path):
    from geomesa_tpu.pubsub import PubSubHub
    from geomesa_tpu.store.stream import StreamingStore

    root, ds = _mk_store(tmp_path)
    layer = StreamingStore(ds)
    hub = PubSubHub(layer)
    yield layer, hub
    hub.close()
    layer.close()


def _take_matches(hub, sub_id, from_seq, want, heartbeat_s=0.05,
                  timeout_s=15.0):
    """Drive the events generator until `want` match events arrived."""
    out = []
    gen = hub.events("t", sub_id, from_seq, heartbeat_s)
    deadline = time.monotonic() + timeout_s
    try:
        for ev in gen:
            if ev[0] == "match":
                out.append(ev)
                if len(out) >= want:
                    break
            assert time.monotonic() < deadline, (
                f"only {len(out)}/{want} matches before timeout"
            )
    finally:
        gen.close()
    return out


def test_one_fused_launch_per_batch_regardless_of_subs(hub_env):
    layer, hub = hub_env
    rng = np.random.default_rng(7)
    for k in range(16):
        x, y = float(rng.uniform(-170, 150)), float(rng.uniform(-80, 60))
        hub.subscribe("t", {"bbox": [x, y, x + 15, y + 15]},
                      tenant=f"t{k}", auths=None)
    base = hub.matcher.launches
    for b in range(5):
        layer.append("t", _cols(rng.uniform(-90, 90, size=(32, 2))),
                     fids=np.arange(b * 32, b * 32 + 32))
    assert hub.matcher.launches - base == 5
    assert hub.matched_records == 5


def test_residuals_bbox_cql_dwithin_exact(hub_env):
    layer, hub = hub_env
    s_box = hub.subscribe("t", {"bbox": [0, 0, 10, 10]},
                          tenant="a", auths=None)
    s_cql = hub.subscribe("t", {"bbox": [0, 0, 10, 10], "cql": "val > 50"},
                          tenant="b", auths=None)
    s_dw = hub.subscribe("t", {"dwithin": {"x": 0, "y": 0, "distance": 1.0}},
                         tenant="c", auths=None)
    # fid 0: in bbox, val low.  fid 1: in bbox, val high.  fid 2: far.
    # fid 3: inside the dwithin BOX corner but outside the exact radius.
    # fid 4: inside the radius.
    layer.append(
        "t",
        _cols([[5, 5], [6, 6], [120, 40], [0.9, 0.9], [0.5, 0.0]],
              vals=[10, 90, 90, 0, 0]),
        fids=np.arange(5),
    )
    got_box = _take_matches(hub, s_box["id"], -1, 1)
    assert sorted(got_box[0][2].fids.tolist()) == [0, 1, 3, 4]
    got_cql = _take_matches(hub, s_cql["id"], -1, 1)
    assert got_cql[0][2].fids.tolist() == [1]  # 0 killed by the residual
    got_dw = _take_matches(hub, s_dw["id"], -1, 1)
    # 3 survives the coarse envelope but hypot(.9,.9)≈1.27 > 1.0 exact
    assert got_dw[0][2].fids.tolist() == [4]


def test_visibility_residual_fails_closed(hub_env):
    layer, hub = hub_env
    s_none = hub.subscribe("t", {"bbox": [0, 0, 10, 10]},
                           tenant="a", auths=None)
    s_auth = hub.subscribe("t", {"bbox": [0, 0, 10, 10]},
                           tenant="b", auths=("secret",))
    sft = layer.store.get_schema("t")
    batch = FeatureBatch.from_columns(
        sft, _cols([[5, 5], [6, 6]]), fids=np.arange(2)
    ).with_visibility(["", "secret"])
    layer.append("t", batch)
    got = _take_matches(hub, s_none["id"], -1, 1)
    assert got[0][2].fids.tolist() == [0]  # labeled row hidden, no auths
    got = _take_matches(hub, s_auth["id"], -1, 1)
    assert sorted(got[0][2].fids.tolist()) == [0, 1]


def test_exactly_once_resume_across_disconnect(hub_env):
    layer, hub = hub_env
    sub = hub.subscribe("t", {"bbox": [0, 0, 20, 20]},
                        tenant="a", auths=None)
    layer.append("t", _cols([[5, 5]]), fids=[0])
    first = _take_matches(hub, sub["id"], sub["cursor"], 1)
    assert first[0][1] == 0  # seq rides the event
    cursor = first[0][1]
    # away: two more batches land while nothing is connected
    layer.append("t", _cols([[6, 6]]), fids=[1])
    layer.append("t", _cols([[7, 7]]), fids=[2])
    resumed = _take_matches(hub, sub["id"], cursor, 2)
    assert [ev[1] for ev in resumed] == [1, 2]  # no seq 0 replay, no gap
    assert [ev[2].fids.tolist() for ev in resumed] == [[1], [2]]


def test_slow_consumer_overflow_teardown(hub_env):
    layer, hub = hub_env
    sub = hub.subscribe("t", {"bbox": [0, 0, 20, 20]},
                        tenant="a", auths=None)
    with prop_override("sub.queue.events", 3):
        gen = hub.events("t", sub["id"], sub["cursor"], 0.05)
        assert next(gen)[0] == "heartbeat"  # connected, queue armed
        for i in range(6):  # 2x the queue bound, nothing consuming
            layer.append("t", _cols([[5, 5]]), fids=[i])
        ended = None
        for ev in gen:
            if ev[0] == "end":
                ended = ev
                break
        assert ended == ("end", "overflow")
        gen.close()
    # the cursor survives the teardown: a reconnect replays everything
    replay = _take_matches(hub, sub["id"], sub["cursor"], 6)
    assert [ev[1] for ev in replay] == list(range(6))


def test_match_fault_never_unacks_append(hub_env):
    from geomesa_tpu.failpoints import failpoint_override

    layer, hub = hub_env
    sub = hub.subscribe("t", {"bbox": [0, 0, 20, 20]},
                        tenant="a", auths=None)
    with failpoint_override("fail.sub.match", "raise:1"):
        out = layer.append("t", _cols([[5, 5]]), fids=[0])
    assert out["rows"] == 1  # the append acked despite the match fault
    assert hub.match_faults == 1
    # the cursor replay re-derives the alert the live path dropped
    replay = _take_matches(hub, sub["id"], sub["cursor"], 1)
    assert replay[0][1] == 0 and replay[0][2].fids.tolist() == [0]


def test_retention_floor_pins_then_ages_out(hub_env):
    layer, hub = hub_env
    sub = hub.subscribe("t", {"bbox": [0, 0, 20, 20]},
                        tenant="a", auths=None)
    layer.append("t", _cols([[5, 5]]), fids=[0])
    # never-connected: pinned at the creation seq while within retain.s
    assert hub.retention_floor("t") == sub["cursor"]
    got = _take_matches(hub, sub["id"], sub["cursor"], 1)
    # disconnected at watermark 0: still pinned there…
    assert got[0][1] == 0
    assert hub.retention_floor("t") == 0
    with prop_override("sub.retain.s", 0.05):
        time.sleep(0.12)
        assert hub.retention_floor("t") is None  # …until it ages out


def test_cursor_gone_detected(hub_env, monkeypatch):
    from geomesa_tpu.pubsub import CursorGoneError

    layer, hub = hub_env
    sub = hub.subscribe("t", {"bbox": [0, 0, 20, 20]},
                        tenant="a", auths=None)
    for i in range(3):
        layer.append("t", _cols([[5, 5]]), fids=[i])
    wal = layer._ts("t").wal
    monkeypatch.setattr(wal, "first_seq", lambda: 2)  # compacted past 0,1
    with pytest.raises(CursorGoneError):
        next(hub.events("t", sub["id"], 0, 0.05))
    # at-or-above the retained tail is fine
    gen = hub.events("t", sub["id"], 1, 0.05)
    assert next(gen)[0] == "match"
    gen.close()


def test_commit_gate_holds_alerts_until_floor_advances(hub_env):
    layer, hub = hub_env
    sub = hub.subscribe("t", {"bbox": [0, 0, 20, 20]},
                        tenant="a", auths=None)
    floor = [-1]
    hub.commit_gate = lambda type_name: floor[0]
    gen = hub.events("t", sub["id"], sub["cursor"], 0.05)
    assert next(gen)[0] == "heartbeat"
    layer.append("t", _cols([[5, 5]]), fids=[0])
    # matched but NOT replication-durable: held, not delivered
    assert next(gen)[0] == "heartbeat"
    assert hub.stats()["commit_pending"] == 1
    # a subscriber connecting NOW must not replay the pending seq either
    gen2 = hub.events("t", sub["id"], -1, 0.05)
    assert next(gen2)[0] == "heartbeat"
    floor[0] = 0
    hub.commit_advanced("t")
    # both connections get the flushed alert exactly once
    assert next(gen)[0:2] == ("match", 0)
    assert next(gen2)[0:2] == ("match", 0)
    assert next(gen)[0] == "heartbeat"
    assert hub.stats()["commit_pending"] == 0
    gen.close()
    gen2.close()


# -- HTTP plane ---------------------------------------------------------------


class _SSEReader:
    """Background SSE consumer: collects (seq, fids) match events,
    keepalive counts, and end reasons; reconnects are the caller's job
    (one reader = one connection, like a real client socket)."""

    def __init__(self, base, sub_id, from_seq=None, type_name="t"):
        import threading

        url = f"{base}/subscribe/{type_name}?id={sub_id}"
        if from_seq is not None:
            url += f"&from={from_seq}"
        self.matches: list = []
        self.keepalives = 0
        self.ends: list = []
        self.error = None
        self._stop = False
        self._thread = threading.Thread(target=self._run, args=(url,),
                                        daemon=True)
        self._thread.start()

    def _run(self, url):
        try:
            self._resp = urllib.request.urlopen(url, timeout=30)
            buf = b""
            while not self._stop:
                chunk = self._resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n\n" in buf:
                    frame, buf = buf.split(b"\n\n", 1)
                    self._frame(frame)
        except Exception as e:  # noqa: BLE001 - surfaced via .error
            self.error = e

    def _frame(self, frame):
        if frame.startswith(b":keepalive"):
            self.keepalives += 1
            return
        if b"event: end" in frame:
            for ln in frame.split(b"\n"):
                if ln.startswith(b"data: "):
                    self.ends.append(json.loads(ln[6:]))
            return
        if b"event: match" in frame:
            seq, fids = None, []
            for ln in frame.split(b"\n"):
                if ln.startswith(b"id: "):
                    seq = int(ln[4:])
                elif ln.startswith(b"data: "):
                    doc = json.loads(ln[6:])
                    fids = [int(f["id"]) for f in doc["features"]]
                    assert doc["seq"] == seq  # body and cursor agree
            self.matches.append((seq, fids))

    def stop(self):
        self._stop = True
        try:
            self._resp.close()
        except Exception:
            pass
        self._thread.join(10)


@pytest.fixture
def http_server(tmp_path):
    from geomesa_tpu.server import serve_background

    root, _ = _mk_store(tmp_path)
    with prop_override("sub.heartbeat.s", 0.2), \
            prop_override("http.keepalive.s", 0.5):
        srv, _ = serve_background(
            FileSystemDataStore(root, partition_size=128), stream=True,
        )
        base = "http://%s:%s" % srv.server_address[:2]
        yield base, srv
        srv.shutdown()
        srv.server_close()


def test_http_subscribe_stream_and_cancel(http_server):
    base, srv = http_server
    sub = _post(base, "/subscribe/t?tenant=alice",
                {"bbox": [0, 0, 20, 20], "cql": "val > 5"})
    assert sub["type"] == "t" and sub["cursor"] == -1
    rd = _SSEReader(base, sub["id"])
    try:
        out = _post(base, "/append/t", _append_doc([7, 8], x=10.0,
                                                   vals=[3, 9]))
        assert out["acked"] == 2
        _wait(lambda: rd.matches, msg="live SSE match")
        assert rd.matches == [(out["seq"], [8])]  # val=3 residual-killed
        st = _get(base, "/stats/pubsub")
        assert st["enabled"] and st["connections"] == 1
        (doc,) = st["subscriptions"]
        assert doc["tenant"] == "alice" and doc["connected"] == 1
        assert doc["cursor"] == out["seq"] and doc["lag"] == 0
        assert _get(base, "/stats")["pubsub"]["enabled"]
        assert _delete(base, f"/subscribe/t?id={sub['id']}")["cancelled"]
        _wait(lambda: rd.ends, msg="end frame after cancel")
        assert rd.ends[0]["reason"] == "cancelled"
    finally:
        rd.stop()


def test_http_heartbeats_outlive_idle_socket_reaper(http_server):
    """Satellite regression: ``http.keepalive.s`` (0.5s here) reaps
    idle keep-alive sockets, but a quiet subscription stream must NOT
    be torn down — the handler exempts itself and emits ``:keepalive``
    comments every ``sub.heartbeat.s`` instead."""
    base, _ = http_server
    sub = _post(base, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
    rd = _SSEReader(base, sub["id"])
    try:
        time.sleep(1.6)  # > 3x the idle reap timeout, zero traffic
        assert rd.error is None
        assert rd.keepalives >= 3  # the stream stayed warm, audibly
        out = _post(base, "/append/t", _append_doc([1]))
        _wait(lambda: rd.matches, msg="match after the quiet window")
        assert rd.matches == [(out["seq"], [1])]
    finally:
        rd.stop()


def test_http_resume_from_cursor_and_last_event_id(http_server):
    base, _ = http_server
    sub = _post(base, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
    seqs = [_post(base, "/append/t", _append_doc([i]))["seq"]
            for i in range(3)]
    rd = _SSEReader(base, sub["id"], from_seq=seqs[0])
    try:
        _wait(lambda: len(rd.matches) == 2, msg="replay above the cursor")
        assert [s for s, _ in rd.matches] == seqs[1:]
    finally:
        rd.stop()
    # Last-Event-ID carries the cursor when the query param is absent
    req = urllib.request.Request(
        f"{base}/subscribe/t?id={sub['id']}",
        headers={"Last-Event-ID": str(seqs[1])},
    )
    resp = urllib.request.urlopen(req, timeout=30)
    try:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        buf = b""
        while b"event: match" not in buf:
            buf += resp.read1(4096)
        assert f"id: {seqs[2]}".encode() in buf
    finally:
        resp.close()


def test_http_cursor_gone_is_410(http_server, monkeypatch):
    base, srv = http_server
    sub = _post(base, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
    for i in range(3):
        _post(base, "/append/t", _append_doc([i]))
    wal = srv.pubsub.stream._ts("t").wal
    monkeypatch.setattr(wal, "first_seq", lambda: 2)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"{base}/subscribe/t?id={sub['id']}&from=0", timeout=30)
    assert ei.value.code == 410
    ei.value.close()


def test_http_push_formats_negotiated(http_server):
    base, _ = http_server
    sub = _post(base, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
    _post(base, "/append/t", _append_doc([1, 2]))
    for fmt, ctype in (
        ("arrow", "application/vnd.apache.arrow.stream"),
        ("bin", "application/vnd.geomesa.bin"),
    ):
        resp = urllib.request.urlopen(
            f"{base}/subscribe/t?id={sub['id']}&from=-1&f={fmt}",
            timeout=30,
        )
        try:
            assert resp.headers["Content-Type"] == ctype
            assert len(resp.read1(65536)) > 0  # replayed batch framed
        finally:
            resp.close()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"{base}/subscribe/t?id={sub['id']}&f=nope", timeout=30)
    assert ei.value.code == 400
    ei.value.close()


def test_http_subscribe_errors(http_server):
    base, _ = http_server
    for path, doc, code in (
        ("/subscribe/missing", {"bbox": [0, 0, 1, 1]}, 404),
        ("/subscribe/t", {}, 400),
        ("/subscribe/t", {"bbox": [9, 9, 0, 0]}, 400),
    ):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, path, doc)
        assert ei.value.code == code
        ei.value.close()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/subscribe/t?id=nope", timeout=30)
    assert ei.value.code == 404
    ei.value.close()


def test_subs_cli_lists_and_cancels(http_server, capsys):
    from geomesa_tpu.tools.cli import main

    base, _ = http_server
    sub = _post(base, "/subscribe/t?tenant=ops",
                {"bbox": [0, 0, 20, 20], "cql": "val > 5"})
    main(["subs", "--url", base])
    out = capsys.readouterr().out
    assert sub["id"] in out and "ops" in out and "val > 5" in out
    main(["subs", "--url", base, "--id", sub["id"], "--cancel"])
    capsys.readouterr()
    assert _get(base, "/stats/pubsub")["subscriptions"] == []


# -- replication + failover ---------------------------------------------------


@pytest.fixture
def pair(tmp_path):
    """Leader + follower on copied roots with fast replication knobs,
    mirroring tests/test_replica.py's pair."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot, ds = _mk_store(tmp_path, "leader")
    ds.write("t", _cols([[10, 10]] * 4), fids=np.arange(4))
    ds.flush("t")
    del ds
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    with prop_override("replica.lease.s", 1.5), \
            prop_override("replica.poll.ms", 25.0), \
            prop_override("replica.failover.s", 8.0), \
            prop_override("sub.heartbeat.s", 0.2):
        lsrv, _ = serve_background(
            FileSystemDataStore(lroot, partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        fsrv, _ = serve_background(
            FileSystemDataStore(froot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(role="follower", leader_url=lbase),
        )
        fbase = "http://%s:%s" % fsrv.server_address[:2]
        yield lbase, fbase, lsrv, fsrv
        for s in (lsrv, fsrv):
            try:
                s.shutdown()
                s.server_close()
            except Exception:
                pass


def test_registry_replicates_and_follower_bounces_writes(pair):
    lbase, fbase, _, _ = pair
    sub = _post(lbase, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
    _wait(
        lambda: [d["id"] for d in
                 _get(fbase, "/stats/pubsub")["subscriptions"]] == [sub["id"]],
        msg="registry record shipped to the follower",
    )
    # subscription writes are leader-pinned exactly like appends
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(fbase, "/subscribe/t", {"bbox": [0, 0, 1, 1]})
    assert ei.value.code == 503
    assert json.loads(ei.value.read())["leader"] == lbase
    ei.value.close()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _delete(fbase, f"/subscribe/t?id={sub['id']}")
    assert ei.value.code == 503
    ei.value.close()
    # cancel on the leader converges the follower's registry too
    assert _delete(lbase, f"/subscribe/t?id={sub['id']}")["cancelled"]
    _wait(
        lambda: _get(fbase, "/stats/pubsub")["subscriptions"] == [],
        msg="unsubscribe shipped to the follower",
    )


def test_commit_gate_armed_under_replica_ack(pair):
    lbase, fbase, _, _ = pair
    with prop_override("replica.ack", "replica"):
        sub = _post(lbase, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
        assert _get(lbase, "/stats/pubsub")["commit_gated"]
        rd = _SSEReader(lbase, sub["id"])
        try:
            out = _post(lbase, "/append/t", _append_doc([50]))
            assert out["replicated"] is True
            # delivered only AFTER the follower applied the record
            _wait(lambda: rd.matches, msg="gated alert after follower ack")
            assert rd.matches == [(out["seq"], [50])]
            assert _get(lbase, "/stats/pubsub")["commit_pending"] == 0
        finally:
            rd.stop()


def test_failover_rearm_zero_missed_zero_duplicate(pair):
    lbase, fbase, lsrv, _ = pair
    sub = _post(lbase, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
    delivered: list = []
    seqs = [_post(lbase, "/append/t", _append_doc([100 + i]))["seq"]
            for i in range(3)]
    rd = _SSEReader(lbase, sub["id"], from_seq=sub["cursor"])
    try:
        _wait(lambda: len(rd.matches) == 3, msg="pre-failover delivery")
        delivered += rd.matches
    finally:
        rd.stop()
    cursor = delivered[-1][0]
    # the follower must hold everything acked before the leader dies
    _wait(lambda: _get(fbase, "/count/t")["count"] == 7,
          msg="follower caught up pre-kill")
    _wait(
        lambda: [d["id"] for d in
                 _get(fbase, "/stats/pubsub")["subscriptions"]] == [sub["id"]],
        msg="registry shipped pre-kill",
    )
    lsrv.socket.close()  # abrupt leader death, no drain
    lsrv.shutdown()
    _wait(lambda: _get(fbase, "/stats/replica")["role"] == "leader",
          timeout_s=30, msg="promotion")
    # the role flips observable a few steps before note_promoted runs
    # (the failover flight bundle writes in between): wait, don't race
    _wait(lambda: _get(fbase, "/stats/pubsub")["rearms"] == 1,
          msg="matcher re-armed")
    st = _get(fbase, "/stats/pubsub")
    assert [d["id"] for d in st["subscriptions"]] == [sub["id"]]
    # resume on the NEW leader from the acked cursor, then append more
    rd = _SSEReader(fbase, sub["id"], from_seq=cursor)
    try:
        seqs += [_post(fbase, "/append/t", _append_doc([200 + i]))["seq"]
                 for i in range(2)]
        _wait(lambda: len(rd.matches) == 2, msg="post-failover delivery")
        delivered += rd.matches
    finally:
        rd.stop()
    got = [s for s, _ in delivered]
    assert got == seqs  # zero missed, zero duplicate, in order
    assert len(set(got)) == len(got)


def test_router_forwards_subscription_writes_to_leader(pair):
    from geomesa_tpu.router import route_background

    lbase, fbase, _, _ = pair
    with prop_override("router.health.ms", 100.0):
        rsrv, _ = route_background([lbase, fbase])
        rbase = "http://%s:%s" % rsrv.server_address[:2]
        try:
            sub = _post(rbase, "/subscribe/t", {"bbox": [0, 0, 20, 20]})
            assert [d["id"] for d in
                    _get(lbase, "/stats/pubsub")["subscriptions"]] \
                == [sub["id"]]
            assert _delete(rbase, f"/subscribe/t?id={sub['id']}")["cancelled"]
            assert _get(lbase, "/stats/pubsub")["subscriptions"] == []
        finally:
            rsrv.shutdown()
            rsrv.server_close()
