"""Converter framework + CLI tools."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu.convert import converter_for, parse_expression
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.tools.cli import main

SPEC = "name:String,age:Int,dtg:Date,*geom:Point"
SFT = SimpleFeatureType.create("people", SPEC)

CSV_CONFIG = {
    "type": "delimited-text",
    "format": "csv",
    "id-field": "$1",
    "options": {"skip-lines": 1},
    "fields": [
        {"name": "name", "transform": "lowercase($1)"},
        {"name": "age", "transform": "$2::int"},
        {"name": "dtg", "transform": "datetime($3)"},
        {"name": "geom", "transform": "point($4::double, $5::double)"},
    ],
}

CSV_DATA = """name,age,date,lon,lat
Alice,34,2020-01-05T12:00:00Z,2.35,48.85
BOB,55,2020-02-01T00:30:00Z,-0.12,51.5
Carol,21,2020-03-15T08:00:00Z,13.4,52.5
"""


class TestExpression:
    def test_refs_and_casts(self):
        e = parse_expression("$2::int")
        out = e({"2": np.array(["41", "42"], dtype=object)})
        np.testing.assert_array_equal(out, [41, 42])
        assert out.dtype == np.int32

    def test_functions(self):
        cols = {"1": np.array(["a", "b"], dtype=object)}
        assert parse_expression("concat($1, 'x')")(cols).tolist() == ["ax", "bx"]
        assert parse_expression("uppercase($1)")(cols).tolist() == ["A", "B"]
        pts = parse_expression("point($1::double, $1::double)")(
            {"1": np.array(["1.5", "2.5"], dtype=object)}
        )
        assert pts.shape == (2, 2)

    def test_string_to_int_with_default(self):
        e = parse_expression("stringToInt($1, 7)")
        out = e({"1": np.array(["3", "oops"], dtype=object)})
        np.testing.assert_array_equal(out, [3, 7])

    def test_errors(self):
        with pytest.raises(ValueError):
            parse_expression("nosuchfn($1)")
        with pytest.raises(ValueError):
            parse_expression("$1::nope")


class TestDelimited:
    def test_csv(self):
        conv = converter_for(CSV_CONFIG, SFT)
        res = conv.process(CSV_DATA)
        assert res.success == 3 and res.failed == 0
        b = res.batch
        assert b.columns["name"].tolist() == ["alice", "bob", "carol"]
        assert b.columns["age"].tolist() == [34, 55, 21]
        assert b.fids.tolist() == ["Alice", "BOB", "Carol"]
        x, y = b.point_coords()
        assert x[1] == pytest.approx(-0.12)

    def test_bad_records_skipped(self):
        conv = converter_for(CSV_CONFIG, SFT)
        res = conv.process(CSV_DATA + "short,row\n")
        assert res.success == 3
        assert res.failed == 1


class TestJson:
    def test_feature_path(self):
        config = {
            "type": "json",
            "feature-path": "$.features[*]",
            "id-field": "$id",
            "fields": [
                {"name": "name", "json-path": "$.props.name"},
                {"name": "age", "json-path": "$.props.age", "transform": "$age::int"},
                {"name": "dtg", "json-path": "$.when", "transform": "datetime($dtg)"},
                {"name": "geom", "json-path": "$.loc",
                 "transform": "point($geom::double, $geom::double)"},
                {"name": "id", "json-path": "$.id"},
            ],
        }
        # geom transform above is nonsense for a list; use explicit x/y
        config["fields"][3] = {
            "name": "geom", "json-path": "$.loc[0]", "transform": "point($geom::double, $y::double)"
        }
        config["fields"].append({"name": "y", "json-path": "$.loc[1]"})
        sft = SimpleFeatureType.create(
            "j", "name:String,age:Int,dtg:Date,*geom:Point,id:String,y:Double"
        )
        doc = {
            "features": [
                {"id": "f1", "props": {"name": "n1", "age": 10},
                 "when": "2021-01-01T00:00:00Z", "loc": [1.0, 2.0]},
                {"id": "f2", "props": {"name": "n2", "age": 20},
                 "when": "2021-06-01T00:00:00Z", "loc": [3.0, 4.0]},
            ]
        }
        conv = converter_for(config, sft)
        res = conv.process(json.dumps(doc))
        assert res.success == 2
        assert res.batch.fids.tolist() == ["f1", "f2"]
        x, y = res.batch.point_coords()
        np.testing.assert_allclose(x, [1.0, 3.0])
        np.testing.assert_allclose(y, [2.0, 4.0])


class TestCli:
    def test_full_workflow(self, tmp_path, capsys):
        root = str(tmp_path / "store")
        conv_path = str(tmp_path / "conv.json")
        csv_path = str(tmp_path / "data.csv")
        with open(conv_path, "w") as fh:
            json.dump(CSV_CONFIG, fh)
        with open(csv_path, "w") as fh:
            fh.write(CSV_DATA)

        main(["--root", root, "create-schema", "-f", "people", "-s", SPEC])
        main(["--root", root, "ingest", "-f", "people", "-C", conv_path, csv_path])
        main(["--root", root, "get-sfts"])
        main(["--root", root, "describe-schema", "-f", "people"])
        main(["--root", root, "count", "-f", "people", "-q", "age > 30"])
        out = capsys.readouterr().out
        assert "ingested 3 features" in out
        assert "people" in out
        assert out.strip().endswith("2")

        main(["--root", root, "explain", "-f", "people", "-q",
              "BBOX(geom, 0, 45, 5, 50)"])
        out = capsys.readouterr().out
        assert "Chosen index" in out

        csv_out = str(tmp_path / "out.csv")
        main(["--root", root, "export", "-f", "people", "-q", "age > 30",
              "-F", "csv", "-o", csv_out])
        lines = open(csv_out).read().strip().splitlines()
        assert len(lines) == 3  # header + 2

        json_out = str(tmp_path / "out.json")
        main(["--root", root, "export", "-f", "people", "-F", "json", "-o", json_out])
        doc = json.load(open(json_out))
        assert len(doc["features"]) == 3
        assert doc["features"][0]["geometry"]["type"] == "Point"

        pq_out = str(tmp_path / "out.parquet")
        main(["--root", root, "export", "-f", "people", "-F", "parquet", "-o", pq_out])
        import pyarrow.parquet as pq

        assert pq.read_table(pq_out).num_rows == 3

        main(["--root", root, "stats", "-f", "people", "-s",
              'Count();MinMax("age")'])
        out = capsys.readouterr().out
        stats_lines = [json.loads(l) for l in out.strip().splitlines() if l.startswith("{")]
        assert stats_lines[-1]["min"] == 21 and stats_lines[-1]["max"] == 55
