"""Stat-based strategy decider: costs come from write-time stats."""

import numpy as np

from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.store.kv import KVDataStore, MemoryKV
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = (
    "name:String,val:Int:index=true,dtg:Date,*geom:Point:srid=4326"
)


def _fill(ds, n=20000, seed=5):
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    ds.create_schema("t", SPEC)
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b"], n),
            "val": rng.integers(0, 1000, n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return ds


def test_costs_are_row_estimates():
    ds = _fill(MemoryDataStore())
    plan = ds.plan(
        "t",
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-01-15T00:00:00Z",
    )
    costs = dict(plan.candidates)
    # z3 prunes space AND time: its estimate must beat space-only z2
    assert plan.index_name == "z3"
    assert costs["z3"] < costs["z2"]
    # estimates are in rows: z3's should be near the true hit count
    true = len(ds.query("t", plan.filter))
    assert 0.2 * true <= max(costs["z3"], 1.0) <= 12 * max(true, 1)


def test_selective_attr_range_beats_wide_bbox():
    # a tight attribute range with a world-spanning bbox: stat costing
    # must route through the attribute index, not the spatial one
    ds = _fill(MemoryDataStore())
    plan = ds.plan("t", "val BETWEEN 10 AND 12 AND BBOX(geom, -180, -90, 180, 90)")
    costs = dict(plan.candidates)
    assert costs["attr:val"] < costs["z2"]
    assert plan.index_name == "attr:val"


def test_empty_region_estimated_near_zero():
    # all data in the eastern hemisphere; a western-hemisphere query
    # should carry a near-zero z3 estimate
    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    n = 5000
    rng = np.random.default_rng(8)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "t",
        {
            "name": ["a"] * n,
            "val": rng.integers(0, 10, n),
            "dtg": t0 + rng.integers(0, 10**9, n),
            "geom": np.stack(
                [rng.uniform(10, 170, n), rng.uniform(-80, 80, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    plan = ds.plan(
        "t",
        "BBOX(geom, -170, -80, -10, 80) AND "
        "dtg DURING 2020-01-02T00:00:00Z/2020-01-09T00:00:00Z",
    )
    costs = dict(plan.candidates)
    assert costs["z3"] < 0.02 * n


def test_kv_store_stats_survive_reopen(tmp_path):
    import os

    path = os.path.join(str(tmp_path), "kv.db")
    from geomesa_tpu.store.kv import SqliteKV

    _fill(KVDataStore(SqliteKV(path)), n=2000)
    ds2 = KVDataStore(SqliteKV(path))
    plan = ds2.plan(
        "t",
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-01-15T00:00:00Z",
    )
    assert plan.index_name == "z3"
    assert dict(plan.candidates)["z3"] < 2000  # stat-based, not heuristic


def test_attr_eq_beats_unbounded_spatial():
    # the review repro: equality + time-only filter must route through the
    # attribute index, not a near-full z3 scan (mixed cost scales bug)
    ds = _fill(MemoryDataStore())
    plan = ds.plan(
        "t", "val = 7 AND dtg DURING 2020-01-01T00:00:00Z/2020-02-28T00:00:00Z"
    )
    costs = dict(plan.candidates)
    assert plan.index_name == "attr:val"
    assert costs["attr:val"] < costs["z3"]


def test_huge_in_list_does_not_exceed_total():
    ds = _fill(MemoryDataStore(), n=2000)
    vals = ",".join(str(v) for v in range(1500))
    plan = ds.plan("t", f"val IN ({vals})")
    costs = dict(plan.candidates)
    assert costs["attr:val"] <= 2000


def test_clustered_data_same_model_for_z2_and_z3():
    # all points in one 4x4-degree box: z2 must not win on a bogus
    # uniform-area assumption when z3 prunes time too
    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    n = 20000
    rng = np.random.default_rng(11)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    ds.write(
        "t",
        {
            "name": ["a"] * n,
            "val": rng.integers(0, 10, n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(10, 14, n), rng.uniform(40, 44, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    plan = ds.plan(
        "t",
        "BBOX(geom, 10, 40, 14, 44) AND "
        "dtg DURING 2020-01-08T00:00:00Z/2020-01-15T00:00:00Z",
    )
    costs = dict(plan.candidates)
    assert plan.index_name == "z3"
    assert costs["z3"] < costs["z2"]
    # both spatial candidates use the histogram: z2's estimate is far above
    # the bogus uniform-area number (4x4 deg / whole world * n would be ~5)
    assert costs["z2"] > 100


def test_low_cardinality_equality_estimate():
    # 'name' has 2 values; equality selectivity must come from the HLL,
    # not a 0.1% guess (indexed attribute -> cardinality stat exists)
    ds = MemoryDataStore()
    ds.create_schema("t", "name:String:index=true,dtg:Date,*geom:Point")
    n = 10000
    rng = np.random.default_rng(4)
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b"], n),
            "dtg": rng.integers(0, 10**9, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    plan = ds.plan("t", "name = 'a'")
    costs = dict(plan.candidates)
    assert 0.3 * n <= costs["attr:name"] <= 0.7 * n  # ~n/2, not n/1000


def test_fs_store_stats_persist_and_plan(tmp_path):
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = _fill(FileSystemDataStore(str(tmp_path)))
    ds.flush("t")
    plan = ds.plan(
        "t",
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-01-15T00:00:00Z",
    )
    cost = dict(plan.candidates)["z3"]
    assert cost < 20000  # stat-based rows estimate, not a heuristic constant
    # reopened store keeps the stats (no rescan needed to plan well)
    ds2 = FileSystemDataStore(str(tmp_path))
    plan2 = ds2.plan(
        "t",
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-01-15T00:00:00Z",
    )
    assert abs(dict(plan2.candidates)["z3"] - cost) < 1e-6


def test_stats_json_codec_roundtrip():
    # every stat type round-trips through the JSON codec (no pickle in
    # store manifests) with estimates preserved
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stats.sketches import seq_from_json
    from geomesa_tpu.store.memory import build_default_stats
    import json as _json

    sft = SimpleFeatureType.create("t", SPEC)
    rng = np.random.default_rng(2)
    n = 3000
    t0 = parse_instant("2020-01-01T00:00:00")
    batch = FeatureBatch.from_columns(
        sft,
        {
            "name": rng.choice(["a", "b", "c"], n),
            "val": rng.integers(0, 50, n),
            "dtg": t0 + rng.integers(0, 10**9, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        np.arange(n),
    )
    seq = build_default_stats(sft, batch)
    doc = _json.loads(_json.dumps(seq.to_json()))  # strict JSON round-trip
    rt = seq_from_json(doc)
    for a, b in zip(seq.stats, rt.stats):
        assert type(a) is type(b)
        assert a.to_json() == b.to_json()


def test_string_hash_vectorized_quality():
    from geomesa_tpu.stats.sketches import Cardinality

    # 50k distinct strings incl. shared prefixes: HLL estimate within 5%
    vals = np.array(
        [f"prefix-common-{i:06d}-suffix" for i in range(50000)], dtype=object
    )
    c = Cardinality("s")
    c.observe(vals)
    assert abs(c.estimate - 50000) / 50000 < 0.05
    # equal values hash equally across calls
    c2 = Cardinality("s")
    c2.observe(vals[:1000])
    c2.observe(vals[:1000])
    c3 = Cardinality("s")
    c3.observe(vals[:1000])
    assert abs(c2.estimate - c3.estimate) < 1e-9


def test_legacy_stats_blob_does_not_brick_kv(tmp_path):
    # a pre-JSON (pickled) ~stats blob must degrade to rebuilt defaults,
    # not crash writes on reopen
    import os
    import pickle

    from geomesa_tpu.store.kv import SqliteKV

    path = os.path.join(str(tmp_path), "kv.db")
    ds = _fill(KVDataStore(SqliteKV(path)), n=100)
    ds._meta_put("t~stats", pickle.dumps({"legacy": True}))
    ds.backend.close()
    ds2 = KVDataStore(SqliteKV(path))
    # write path works; stats rebuilt as advisory defaults
    ds2.write(
        "t",
        {"name": ["x"], "val": [1], "dtg": [0], "geom": np.zeros((1, 2))},
        fids=["extra"],
    )
    assert len(ds2.query("t", "INCLUDE")) == 101


def test_topk_and_frequency_roundtrip_after_reobserve():
    import json as _json

    from geomesa_tpu.stats.sketches import Frequency, TopK, stat_from_json

    t = TopK("v")
    t.observe(np.array([1, 1, 2, 2, 3]))
    rt = stat_from_json(_json.loads(_json.dumps(t.to_json())))
    rt.observe(np.array([1, 1, 1]))
    assert dict(rt.topk)["1"] == 5  # one canonical key, no split counts

    f = Frequency("v")
    f.observe(np.array([7, 7, 8]))
    rf = stat_from_json(_json.loads(_json.dumps(f.to_json())))
    assert rf.count(7) == 2 and rf.count(8) == 1
