"""Test configuration: force CPU jax with a virtual 8-device mesh.

Must run before any jax import (SURVEY.md section 4 rebuild test plan:
multi-chip tests via host-platform device-count simulation).

The runtime lock-order checker (analysis/lockcheck.py) is switched on
for the WHOLE suite: the env var must be set before any geomesa_tpu
module import so module-level locks (metrics, failpoints, native) are
built instrumented. Subprocesses spawned by the chaos suite inherit it.
The session-end hook prints the acquisition-graph summary;
tests/test_lockcheck.py asserts the zero-findings invariant and the
seeded detections.
"""

import os

os.environ.setdefault("GEOMESA_TPU_LOCKCHECK", "1")

from geomesa_tpu.jaxconf import force_cpu_devices

force_cpu_devices(8)

import numpy as np
import pytest

# Tests run the host-parity path: float64 quantization + uint64 z lanes on
# CPU jax. (The TPU 32-bit lane path is covered by the hi/lo encode tests.)
from geomesa_tpu.jaxconf import require_x64

require_x64()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def pytest_terminal_summary(terminalreporter):
    """One line of lock-order-checker state at session end; any global
    finding is spelled out (and fails the session, see below).
    tests/test_lockcheck.py additionally asserts the invariant mid-run."""
    from geomesa_tpu.analysis.lockcheck import CHECKER, enabled

    if not enabled():
        return
    rep = CHECKER.report()
    terminalreporter.write_line(
        f"lockcheck: {len(rep['locks'])} locks, {len(rep['edges'])} order "
        f"edges, {len(rep['cycles'])} cycles, {len(rep['blocking'])} "
        "held-across-blocking events"
    )
    for c in rep["cycles"]:
        terminalreporter.write_line(f"lockcheck CYCLE: {c}")
    for b in rep["blocking"]:
        terminalreporter.write_line(f"lockcheck BLOCKING: {b}")


def pytest_sessionfinish(session, exitstatus):
    """The enforcement half: a lock-order cycle or a held-across-
    blocking event ANYWHERE in the session (including suites that ran
    after test_lockcheck's in-run assertion) fails the run."""
    from geomesa_tpu.analysis.lockcheck import CHECKER, enabled

    if not enabled():
        return
    rep = CHECKER.report()
    if (rep["cycles"] or rep["blocking"]) and session.exitstatus == 0:
        session.exitstatus = 1
