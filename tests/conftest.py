"""Test configuration: force CPU jax with a virtual 8-device mesh.

Must run before any jax import (SURVEY.md section 4 rebuild test plan:
multi-chip tests via host-platform device-count simulation).
"""

from geomesa_tpu.jaxconf import force_cpu_devices

force_cpu_devices(8)

import numpy as np
import pytest

# Tests run the host-parity path: float64 quantization + uint64 z lanes on
# CPU jax. (The TPU 32-bit lane path is covered by the hi/lo encode tests.)
from geomesa_tpu.jaxconf import require_x64

require_x64()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
