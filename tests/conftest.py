"""Test configuration: force CPU jax with a virtual 8-device mesh.

Must run before any jax import (SURVEY.md section 4 rebuild test plan:
multi-chip tests via host-platform device-count simulation).

The runtime sanitizers (``analysis/``) are switched on for the WHOLE
suite -- env vars must be set before any geomesa_tpu module import so
module-level state is built instrumented; subprocesses spawned by the
chaos suite inherit them:

- lock-order checker (``GEOMESA_TPU_LOCKCHECK``, analysis/lockcheck.py):
  acquisition-graph cycles + held-across-blocking events.
- context checker (``GEOMESA_TPU_CTXCHECK``, analysis/ctxcheck.py):
  blessed-spawn worker tasks with orphaned or mismatched request
  context (trace/cost/degraded/compile-scope accounting).
- compile checker (``GEOMESA_TPU_COMPILECHECK``,
  analysis/compilecheck.py): backend compiles while a server is live
  that carry no blessed ``compile_scope`` attribution.

The session-end hooks print each checker's summary; any finding fails
the run. tests/test_lockcheck.py, tests/test_ctxcheck.py and
tests/test_compilecheck.py additionally assert the zero-findings
invariants mid-run plus the seeded detections.
"""

import os

os.environ.setdefault("GEOMESA_TPU_LOCKCHECK", "1")
os.environ.setdefault("GEOMESA_TPU_CTXCHECK", "1")
os.environ.setdefault("GEOMESA_TPU_COMPILECHECK", "1")

from geomesa_tpu.jaxconf import force_cpu_devices

force_cpu_devices(8)

import numpy as np
import pytest

# Tests run the host-parity path: float64 quantization + uint64 z lanes on
# CPU jax. (The TPU 32-bit lane path is covered by the hi/lo encode tests.)
from geomesa_tpu.jaxconf import require_x64

require_x64()

# Arm the observer seams now that the package is importable: install()
# is a no-op when the env var is off, and idempotent when on.
from geomesa_tpu.analysis import compilecheck as _compilecheck
from geomesa_tpu.analysis import ctxcheck as _ctxcheck

if _ctxcheck.enabled():
    _ctxcheck.install()
if _compilecheck.enabled():
    _compilecheck.install()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def pytest_terminal_summary(terminalreporter):
    """One line of sanitizer state per checker at session end; any
    global finding is spelled out (and fails the session, see below).
    The per-checker tests additionally assert the invariants mid-run."""
    from geomesa_tpu.analysis import compilecheck, ctxcheck
    from geomesa_tpu.analysis.lockcheck import CHECKER, enabled

    if enabled():
        rep = CHECKER.report()
        terminalreporter.write_line(
            f"lockcheck: {len(rep['locks'])} locks, {len(rep['edges'])} "
            f"order edges, {len(rep['cycles'])} cycles, "
            f"{len(rep['blocking'])} held-across-blocking events"
        )
        for c in rep["cycles"]:
            terminalreporter.write_line(f"lockcheck CYCLE: {c}")
        for b in rep["blocking"]:
            terminalreporter.write_line(f"lockcheck BLOCKING: {b}")
    if ctxcheck.enabled():
        rep = ctxcheck.CHECKER.report()
        terminalreporter.write_line(
            f"ctxcheck: {rep['tasks']} blessed tasks, {rep['attaches']} "
            f"attaches, {rep['charges']} charges, {rep['compiles']} "
            f"compiles, {len(rep['findings'])} findings"
        )
        for f in rep["findings"]:
            terminalreporter.write_line(f"ctxcheck FINDING: {f}")
    if compilecheck.enabled():
        rep = compilecheck.CHECKER.report()
        terminalreporter.write_line(
            f"compilecheck: {rep['compiles']} compiles "
            f"({rep['serving_compiles']} while serving), "
            f"{len(rep['violations'])} unattributed"
        )
        for v in rep["violations"]:
            terminalreporter.write_line(f"compilecheck VIOLATION: {v}")


def pytest_sessionfinish(session, exitstatus):
    """The enforcement half: a lock-order cycle, a held-across-blocking
    event, an orphaned-context worker task, or an unattributed
    serving-path compile ANYWHERE in the session (including suites that
    ran after the checkers' in-run assertions) fails the run."""
    from geomesa_tpu.analysis import compilecheck, ctxcheck
    from geomesa_tpu.analysis.lockcheck import CHECKER, enabled

    bad = False
    if enabled():
        rep = CHECKER.report()
        bad = bool(rep["cycles"] or rep["blocking"])
    if ctxcheck.enabled() and ctxcheck.CHECKER.report()["findings"]:
        bad = True
    if compilecheck.enabled() and (
        compilecheck.CHECKER.report()["violations"]
    ):
        bad = True
    if bad and session.exitstatus == 0:
        session.exitstatus = 1
