"""Test configuration: force CPU jax with a virtual 8-device mesh.

Must run before any jax import (SURVEY.md section 4 rebuild test plan:
multi-chip tests via host-platform device-count simulation).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize hook sets jax.config.jax_platforms directly (which
# outranks the env var), so force the config back to cpu before any backend
# initializes.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# Tests run the host-parity path: float64 quantization + uint64 z lanes on
# CPU jax. (The TPU 32-bit lane path is covered by the hi/lo encode tests.)
from geomesa_tpu.jaxconf import require_x64

require_x64()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
