"""Streaming live layer + lambda store."""

import numpy as np
import pytest

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.store import MemoryDataStore
from geomesa_tpu.stream import FeatureLog, LambdaDataStore, LiveFeatureStore, Put

SPEC = "track:String,v:Int,dtg:Date,*geom:Point"
SFT = SimpleFeatureType.create("live", SPEC)


class FakeClock:
    def __init__(self):
        self.t = 1_000_000

    def __call__(self):
        return self.t


def cols(fid_vals, xs, ys, v=0, t=0):
    n = len(fid_vals)
    return {
        "track": [f"t{f}" for f in fid_vals],
        "v": np.full(n, v),
        "dtg": np.full(n, t, dtype=np.int64),
        "geom": np.stack([np.asarray(xs, float), np.asarray(ys, float)], axis=1),
    }


class TestLive:
    def test_upsert_and_query(self):
        s = LiveFeatureStore(SFT)
        s.put(cols([1, 2], [0.0, 10.0], [0.0, 10.0]), [1, 2])
        assert len(s) == 2
        hits = s.query("BBOX(geom, -1, -1, 1, 1)")
        assert list(hits.fids) == [1]
        # upsert moves feature 1
        s.put(cols([1], [20.0], [20.0]), [1])
        assert len(s) == 2
        assert len(s.query("BBOX(geom, -1, -1, 1, 1)")) == 0
        assert list(s.query("BBOX(geom, 19, 19, 21, 21)").fids) == [1]

    def test_remove_and_clear(self):
        s = LiveFeatureStore(SFT)
        s.put(cols([1, 2, 3], [0, 1, 2], [0, 1, 2]), [1, 2, 3])
        s.remove([2])
        assert sorted(s.snapshot().fids.tolist()) == [1, 3]
        s.clear()
        assert len(s) == 0

    def test_replay_recovery(self):
        log = FeatureLog()
        s1 = LiveFeatureStore(SFT, log)
        s1.put(cols([1, 2], [0, 1], [0, 1]), [1, 2])
        s1.remove([1])
        # a second consumer rebuilt from the same log sees identical state
        s2 = LiveFeatureStore(SFT, log)
        assert sorted(s2.snapshot().fids.tolist()) == sorted(
            s1.snapshot().fids.tolist()
        )

    def test_expiry(self):
        clock = FakeClock()
        s = LiveFeatureStore(SFT, expiry_ms=5000, clock=clock)
        s.put(cols([1], [0], [0]), [1])
        clock.t += 3000
        s.put(cols([2], [1], [1]), [2])
        clock.t += 3000
        assert sorted(s.snapshot().fids.tolist()) == [2]  # 1 expired

    def test_listeners(self):
        events = []
        s = LiveFeatureStore(SFT)
        s.add_listener(lambda m: events.append(type(m).__name__))
        s.put(cols([1], [0], [0]), [1])
        s.remove([1])
        assert events == ["Put", "Remove"]


class TestLambda:
    def _mk(self):
        clock = FakeClock()
        persistent = MemoryDataStore()
        persistent.create_schema(SFT)
        return LambdaDataStore(persistent, "live", persist_after_ms=10_000, clock=clock), clock

    def test_merge_and_persist(self):
        lam, clock = self._mk()
        lam.write(cols([1, 2], [0, 5], [0, 5], v=1), [1, 2])
        assert lam.count() == 2
        clock.t += 20_000
        lam.write(cols([3], [9], [9], v=2), [3])
        moved = lam.persist()
        assert moved == 2
        assert len(lam.live) == 1
        assert lam.count() == 3  # merged view unchanged
        # live update shadows the persisted version
        lam.write(cols([1], [50.0], [50.0], v=9), [1])
        got = lam.query("BBOX(geom, 49, 49, 51, 51)")
        assert list(got.fids) == [1]
        assert lam.count() == 3

    def test_persist_upsert_replaces(self):
        lam, clock = self._mk()
        lam.write(cols([1], [0], [0], v=1), [1])
        clock.t += 20_000
        lam.persist()
        lam.write(cols([1], [10.0], [10.0], v=2), [1])
        clock.t += 20_000
        lam.persist()
        assert lam.persistent.count("live") == 1
        got = lam.persistent.query("live", "INCLUDE").batch
        assert got.column("v")[0] == 2


def test_ordered_delivery_under_concurrent_producers_and_expiry():
    """Listener deliveries are ticketed in state-mutation order, so an
    attached delta consumer converges to exactly the live state even
    with concurrent producers and reader-triggered expiry."""
    import threading

    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stream.live import LiveFeatureStore
    from geomesa_tpu.stream.log import Put, Remove

    sft = SimpleFeatureType.create("ev", "val:Int,*geom:Point")
    now = [1_000_000]
    live = LiveFeatureStore(
        sft, standalone=True, expiry_ms=10_000, clock=lambda: now[0]
    )

    # a deliberately stateful order-sensitive consumer (mini delta cache)
    state: dict = {}

    def listener(msg):
        if isinstance(msg, Put):
            for i, f in enumerate(np.asarray(msg.fids).tolist()):
                state[f] = int(np.asarray(msg.columns["val"])[i])
        elif isinstance(msg, Remove):
            for f in np.asarray(msg.fids).tolist():
                state.pop(f, None)

    live.add_listener(listener)

    def producer(tid):
        rng = np.random.default_rng(tid)
        for k in range(60):
            fid = int(rng.integers(0, 40))
            if rng.random() < 0.25:
                live.apply(Remove(np.array([fid])))
            else:
                live.apply(Put(
                    {"val": np.array([tid * 1000 + k]),
                     "geom": np.zeros((1, 2))},
                    np.array([fid]),
                ))
            if k % 7 == 0:
                now[0] += 500
                len(live)  # reader: triggers expiry notifications

    threads = [threading.Thread(target=producer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    now[0] += 20_000
    assert len(live) == 0  # everything expired at the end
    assert state == {}, f"consumer diverged: {state}"


def test_raising_listener_does_not_wedge_delivery():
    """A listener exception must not strand tickets: later messages still
    deliver to the other listeners, and the store keeps working."""
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stream.live import LiveFeatureStore
    from geomesa_tpu.stream.log import Put

    sft = SimpleFeatureType.create("ev", "val:Int,*geom:Point")
    live = LiveFeatureStore(sft, standalone=True)
    seen = []

    def bad(msg):
        raise RuntimeError("listener bug")

    live.add_listener(bad)
    live.add_listener(lambda m: seen.append(m))
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="listener bug"):
        live.apply(Put({"val": np.array([1]), "geom": np.zeros((1, 2))},
                       np.array([0])))
    # the second listener still got the message despite the first raising
    assert len(seen) == 1
    # and the store is not wedged: further messages flow (the bad
    # listener raises again, after full delivery)
    with _pytest.raises(RuntimeError, match="listener bug"):
        live.apply(Put({"val": np.array([2]), "geom": np.zeros((1, 2))},
                       np.array([1])))
    assert len(seen) == 2  # good listener saw it despite the raise
    live.remove_listener(bad)
    live.apply(Put({"val": np.array([3]), "geom": np.zeros((1, 2))},
                   np.array([2])))
    assert len(seen) == 3  # delivery kept advancing throughout
    assert len(live) == 3
