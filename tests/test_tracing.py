"""End-to-end query tracing (tracing.py): span nesting/ordering across
the prefetch worker pool, sampling + slow-query always-capture, Perfetto
export schema, the scheduler's trace spans, the /debug/traces endpoints
through a real server, and the metrics/audit satellite regressions."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override
from geomesa_tpu.tracing import (
    Tracer,
    attach,
    capture,
    coverage,
    current_trace_id,
    format_trace,
    record_span,
    span,
)

SPEC = "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"


def _poll(fn, timeout=5.0):
    """Retry ``fn`` until it returns truthy: trace retention (and the
    slow-query log append) happen on the handler thread AFTER the
    response bytes go out, so an immediate read can race them."""
    deadline = time.time() + timeout
    while True:
        out = fn()
        if out or time.time() > deadline:
            return out
        time.sleep(0.01)


def _fill(store, n=6000, seed=11):
    from geomesa_tpu.filter.ecql import parse_instant

    store.create_schema("gdelt", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    store.write("gdelt", {
        "name": rng.choice(["alpha", "beta"], n),
        "count": rng.integers(0, 100, n),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }, fids=np.arange(n))
    store.flush("gdelt")


class TestSpans:
    def test_nesting_and_ordering(self):
        tr = Tracer()
        with prop_override("trace.sample", 1.0):
            with tr.trace("req") as t:
                with span("a"):
                    with span("a1"):
                        pass
                with span("b") as sp:
                    sp.set(rows=7)
        doc = tr.get(t.trace_id).to_dict()
        root = doc["spans"]
        assert root["name"] == "req"
        assert [c["name"] for c in root["children"]] == ["a", "b"]
        a, b = root["children"]
        assert a["children"][0]["name"] == "a1"
        assert b["attrs"]["rows"] == 7
        # start offsets are trace-relative and ordered; durations filled
        assert 0.0 <= a["start_ms"] <= b["start_ms"]
        for sp_ in (root, a, b, a["children"][0]):
            assert sp_["dur_ms"] is not None and sp_["dur_ms"] >= 0.0
        assert doc["duration_ms"] >= a["dur_ms"]

    def test_no_active_trace_is_noop(self):
        # span() outside any trace yields the shared no-op — set() works,
        # nothing records, and nothing leaks into later traces
        with span("orphan") as sp:
            sp.set(x=1)
        assert capture() is None
        assert current_trace_id() == ""

    def test_spans_cross_prefetch_worker_threads(self):
        from geomesa_tpu.store.prefetch import (
            WORKER_PREFIX,
            PrefetchConfig,
            prefetch_map,
        )

        tr = Tracer()

        def work(i):
            with span("work", i=i):
                time.sleep(0.002)
            return i

        with prop_override("trace.sample", 1.0):
            with tr.trace("req") as t:
                out = list(
                    prefetch_map(work, range(8), PrefetchConfig(workers=4))
                )
        assert out == list(range(8))
        root = tr.get(t.trace_id).to_dict()["spans"]
        works = [c for c in root["children"] if c["name"] == "work"]
        # every item's span landed in THIS trace despite running on the
        # pool (capture/attach in prefetch_map), and at least one really
        # ran on a worker thread
        assert sorted(c["attrs"]["i"] for c in works) == list(range(8))
        assert any(c["thread"].startswith(WORKER_PREFIX) for c in works)

    def test_explicit_parent_and_record_span(self):
        tr = Tracer()
        with prop_override("trace.sample", 1.0):
            with tr.trace("req") as t:
                ctx = capture()
                done = threading.Event()

                def worker():
                    # no attach -> no current span on this thread
                    assert capture() is None
                    with attach(ctx):
                        with span("threaded"):
                            pass
                    t0 = time.perf_counter()
                    record_span(ctx, "retro", t0, 0.005, k="v")
                    done.set()

                th = threading.Thread(target=worker)
                th.start()
                th.join()
                assert done.is_set()
        root = tr.get(t.trace_id).to_dict()["spans"]
        names = {c["name"] for c in root["children"]}
        assert {"threaded", "retro"} <= names
        retro = next(c for c in root["children"] if c["name"] == "retro")
        assert retro["dur_ms"] == 5.0 and retro["attrs"]["k"] == "v"


class TestSamplingAndSlowCapture:
    def test_unsampled_fast_trace_not_retained(self, tmp_path):
        tr = Tracer()
        tr.slow_log_path = str(tmp_path / "_slow_queries.jsonl")
        with prop_override("trace.sample", 0.0), \
                prop_override("trace.slow_ms", 60_000.0):
            with tr.trace("fast") as t:
                with span("x"):
                    pass
        assert t.recording  # slow capture armed -> spans were recorded
        assert tr.get(t.trace_id) is None  # ...but fast + unsampled drops
        assert not (tmp_path / "_slow_queries.jsonl").exists()

    def test_slow_always_captured_and_logged(self, tmp_path):
        tr = Tracer()
        tr.slow_log_path = str(tmp_path / "_slow_queries.jsonl")
        with prop_override("trace.sample", 0.0), \
                prop_override("trace.slow_ms", 1.0):
            with tr.trace("slow") as t:
                with span("x"):
                    time.sleep(0.01)
        got = tr.get(t.trace_id)
        assert got is not None and got.slow and not got.sampled
        lines = [
            json.loads(line)
            for line in open(tmp_path / "_slow_queries.jsonl")
        ]
        assert lines[-1]["trace_id"] == t.trace_id
        assert lines[-1]["slow"] is True
        assert lines[-1]["spans"]["children"][0]["name"] == "x"

    def test_recording_fully_off(self):
        tr = Tracer()
        with prop_override("trace.sample", 0.0), \
                prop_override("trace.slow_ms", 0.0):
            with tr.trace("off") as t:
                with span("x") as sp:
                    sp.set(a=1)  # no-op, must not raise
        assert not t.recording
        assert t.trace_id  # the X-Request-Id echo still works
        assert tr.get(t.trace_id) is None

    def test_ring_is_bounded(self):
        tr = Tracer(capacity=4)
        ids = []
        with prop_override("trace.sample", 1.0):
            for i in range(10):
                with tr.trace(f"r{i}") as t:
                    pass
                ids.append(t.trace_id)
        assert tr.get(ids[0]) is None  # evicted
        assert tr.get(ids[-1]) is not None
        assert len(tr.recent(100)) == 4
        # newest first
        assert tr.recent(100)[0]["trace_id"] == ids[-1]
        # limit=0 means none (not "the whole ring" via a -0 slice)
        assert tr.recent(0) == [] and tr.recent(-3) == []
        assert len(tr.recent(2)) == 2

    def test_malformed_trace_env_degrades_not_raises(self, monkeypatch):
        # a bad GEOMESA_TPU_TRACE_SAMPLE must never drop the request the
        # trace wraps: fall back to slow-capture-only defaults
        monkeypatch.setenv("GEOMESA_TPU_TRACE_SAMPLE", "on")
        tr = Tracer()
        with tr.trace("req") as t:
            with span("x"):
                pass
        assert t.trace_id and not t.sampled and t.recording

    def test_inbound_trace_id_sanitized(self):
        tr = Tracer()
        with prop_override("trace.sample", 1.0):
            with tr.trace("req", trace_id='abc\n"123/../x') as t:
                pass
        assert "\n" not in t.trace_id and '"' not in t.trace_id
        assert "/" not in t.trace_id
        assert "abc" in t.trace_id


class TestExport:
    def _one_trace(self):
        tr = Tracer()
        with prop_override("trace.sample", 1.0):
            with tr.trace("req") as t:
                with span("a", rows=3):
                    with span("b"):
                        pass
        return tr.get(t.trace_id)

    def test_perfetto_schema(self):
        t = self._one_trace()
        doc = t.to_perfetto()
        assert doc["otherData"]["trace_id"] == t.trace_id
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        ms = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert {e["name"] for e in xs} == {"req", "a", "b"}
        for e in xs:
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
            assert e["ts"] > 0 and e["dur"] >= 0
        assert ms and all(e["name"] == "thread_name" for e in ms)
        # nesting holds on the timeline: child events start no earlier
        by_name = {e["name"]: e for e in xs}
        assert by_name["req"]["ts"] <= by_name["a"]["ts"]
        assert by_name["a"]["args"]["rows"] == 3

    def test_format_trace_tree(self):
        doc = self._one_trace().to_dict()
        text = format_trace(doc)
        assert doc["trace_id"] in text
        for name in ("req", "a", "b"):
            assert name in text

    def test_coverage(self):
        doc = self._one_trace().to_dict()
        # "a" wraps nearly the whole trace -> high coverage; empty
        # children -> zero
        assert 0.0 < coverage(doc) <= 1.0
        assert coverage({"spans": None}) == 0.0


class TestSchedulerSpans:
    def test_serial_execution_spans(self):
        from geomesa_tpu.sched import QueryScheduler, SchedConfig

        tr = Tracer()
        with prop_override("trace.sample", 1.0):
            with QueryScheduler(SchedConfig(max_inflight=1)) as sched:
                with tr.trace("req") as t:
                    def work():
                        with span("inner"):
                            return 42

                    assert sched.run(fn=work) == 42
        root = tr.get(t.trace_id).to_dict()["spans"]
        names = [c["name"] for c in root["children"]]
        assert "sched.wait" in names and "sched.execute" in names
        ex = next(c for c in root["children"] if c["name"] == "sched.execute")
        assert ex["attrs"]["fused"] == 1 and ex["attrs"]["launch"] >= 1
        # the work's own span nests under the execute span (attach)
        assert [c["name"] for c in ex["children"]] == ["inner"]


class TestServerEndToEnd:
    @pytest.fixture()
    def served(self, tmp_path):
        from geomesa_tpu.server import serve_background
        from geomesa_tpu.store.fs import FileSystemDataStore
        from geomesa_tpu.tracing import TRACER

        store = FileSystemDataStore(
            str(tmp_path), partition_size=2048, audit=True
        )
        _fill(store)
        prev = TRACER.slow_log_path
        server, _ = serve_background(store)
        host, port = server.server_address[:2]
        yield f"http://{host}:{port}", store, tmp_path
        server.shutdown()
        TRACER.slow_log_path = prev

    def test_trace_id_flow_and_debug_endpoints(self, served):
        url, store, root = served
        cql = urllib.request.quote(
            "BBOX(geom, -5, 42, 8, 51) AND "
            "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z"
        )
        rid = "req-e2e-1"
        # trace.slow_ms tiny: EVERY request is a "slow query" -> always
        # captured + slow-logged, even at sample=0 (the always-on path)
        with prop_override("trace.sample", 0.0), \
                prop_override("trace.slow_ms", 0.001):
            req = urllib.request.Request(
                f"{url}/count/gdelt?cql={cql}",
                headers={"X-Request-Id": rid},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.headers["X-Request-Id"] == rid
                json.loads(r.read())

        # ring: the summary lists it (poll — retention happens on the
        # handler thread after the response is written)
        def _listed():
            with urllib.request.urlopen(
                f"{url}/debug/traces", timeout=30
            ) as r:
                summaries = json.loads(r.read())["traces"]
            return rid in [t["trace_id"] for t in summaries]

        assert _poll(_listed)

        # full tree covers every serving level that ran
        with urllib.request.urlopen(
            f"{url}/debug/traces/{rid}", timeout=30
        ) as r:
            doc = json.loads(r.read())
        names: set = set()

        def walk(sp):
            names.add(sp["name"])
            for c in sp.get("children") or []:
                walk(c)

        walk(doc["spans"])
        # /count serves through the chunk-stats pushdown (PR 6): the
        # levels that run are plan -> agg.pushdown -> boundary-chunk
        # refinement (read/decode/scan); store.query only appears on
        # the row-scan fallback
        assert {
            "agg.pushdown", "query.plan", "query.scan",
            "store.read", "store.decode",
        } <= names
        assert doc["spans"]["attrs"]["status"] == 200
        # the acceptance-criteria number, asserted on a request with
        # real work (/features: scan + geojson encode — measured 99+%):
        # child spans must explain >= 95% of the request's wall time.
        # (A near-instant /count can sit just under the bar: its fixed
        # few-hundred-us Python gaps don't amortize.)
        rid2 = "req-e2e-2"
        with prop_override("trace.sample", 1.0):
            req2 = urllib.request.Request(
                f"{url}/features/gdelt",  # full scan + full encode
                headers={"X-Request-Id": rid2},
            )
            with urllib.request.urlopen(req2, timeout=60) as r:
                r.read()

        def _doc2():
            try:
                with urllib.request.urlopen(
                    f"{url}/debug/traces/{rid2}", timeout=30
                ) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError:
                return None

        doc2 = _poll(_doc2)
        assert doc2 is not None
        assert coverage(doc2) >= 0.95

        # perfetto export
        with urllib.request.urlopen(
            f"{url}/debug/traces/{rid}?format=perfetto", timeout=30
        ) as r:
            pf = json.loads(r.read())
        assert pf["traceEvents"] and any(
            e["name"] == "store.read" for e in pf["traceEvents"]
        )

        # the SAME id in the slow-query log and the audit log
        def _slow_logged():
            p = root / "_slow_queries.jsonl"
            if not p.exists():
                return False
            slow = [json.loads(line) for line in open(p)]
            return rid in [e["trace_id"] for e in slow]

        assert _poll(_slow_logged)
        store.audit_writer.flush()
        events = store.audit_writer.read_events()
        assert rid in [e.trace_id for e in events]

    def test_unknown_trace_404(self, served):
        url, _, _ = served
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{url}/debug/traces/nosuchtrace", timeout=30
            )
        assert ei.value.code == 404

    def test_error_responses_are_traced_with_status(self, served):
        # the error handler runs INSIDE the trace: a failed request's
        # trace carries its HTTP status (and is slow-capturable)
        url, _, _ = served
        rid = "req-err-1"
        with prop_override("trace.sample", 0.0), \
                prop_override("trace.slow_ms", 0.001):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"{url}/count/nosuchtype",
                        headers={"X-Request-Id": rid},
                    ),
                    timeout=30,
                )
            assert ei.value.code == 404
            assert ei.value.headers["X-Request-Id"] == rid

        def _doc():
            try:
                with urllib.request.urlopen(
                    f"{url}/debug/traces/{rid}", timeout=30
                ) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError:
                return None

        doc = _poll(_doc)
        assert doc is not None
        assert doc["spans"]["attrs"]["status"] == 404

    def test_monitoring_endpoints_not_traced(self, served):
        url, _, _ = served
        from geomesa_tpu.tracing import TRACER

        with prop_override("trace.sample", 1.0):
            before = {t["trace_id"] for t in TRACER.recent(200)}
            for ep in ("metrics", "debug/traces", "stats/store"):
                urllib.request.urlopen(f"{url}/{ep}", timeout=30).read()
            time.sleep(0.1)
            after = {t["trace_id"] for t in TRACER.recent(200)}
        assert after == before  # no trace churn from scrapes/snapshots

    def test_trace_cli(self, served, capsys):
        url, _, _ = served
        from geomesa_tpu.tools.cli import main as cli_main

        cql = urllib.request.quote("BBOX(geom, -5, 42, 8, 51)")
        rid = "req-cli-1"
        with prop_override("trace.sample", 1.0):
            req = urllib.request.Request(
                f"{url}/count/gdelt?cql={cql}",
                headers={"X-Request-Id": rid},
            )
            urllib.request.urlopen(req, timeout=30).read()

        def _retained():
            try:
                urllib.request.urlopen(
                    f"{url}/debug/traces/{rid}", timeout=30
                ).read()
                return True
            except urllib.error.HTTPError:
                return False

        assert _poll(_retained)
        cli_main(["trace", "--url", url])
        assert rid in capsys.readouterr().out
        cli_main(["trace", "--url", url, rid])
        out = capsys.readouterr().out
        # the /count request serves via the aggregation pushdown (PR 6)
        assert "agg.pushdown" in out and "coverage" in out


class TestMetricsRegressions:
    def test_label_values_escaped(self):
        from geomesa_tpu.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("esc_total", "h")
        c.inc(filter='name = "a\\b"\nAND x')
        text = reg.prometheus_text()
        lines = [
            line for line in text.splitlines()
            if line.startswith("esc_total{")
        ]
        # ONE physical line: the newline was escaped, quotes/backslashes
        # can't break out of the label value
        assert len(lines) == 1
        assert lines[0] == (
            'esc_total{filter="name = \\"a\\\\b\\"\\nAND x"} 1'
        )

    def test_prometheus_text_vs_concurrent_writers(self):
        from geomesa_tpu.metrics import MetricsRegistry

        reg = MetricsRegistry()
        h = reg.histogram("h_seconds", "h")
        c = reg.counter("c_total", "h")
        errs: list = []

        def writer(wid: int):
            try:
                # fresh label keys every iteration: the scrape iterates
                # while the dicts grow (pre-fix this raised "dictionary
                # changed size during iteration" in the scrape thread)
                for i in range(4000):
                    h.observe(0.001 * (i % 50), tag=f"{wid}-{i}")
                    c.inc(tag=f"{wid}-{i}")
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(3)
        ]
        for t in threads:
            t.start()
        try:
            while any(t.is_alive() for t in threads):
                text = reg.prometheus_text()
                assert "h_seconds_bucket" in text
        finally:
            for t in threads:
                t.join()
        assert not errs


class TestAuditClose:
    def test_close_drains_queue(self, tmp_path):
        from geomesa_tpu.audit import AuditedEvent, FileAuditWriter

        w = FileAuditWriter(str(tmp_path / "q.jsonl"))
        for i in range(25):
            w.write(AuditedEvent(
                store="s", type_name="t", filter=f"f{i}",
                trace_id=f"tid{i}",
            ))
        w.close()
        events = w.read_events()
        assert len(events) == 25
        assert events[0].trace_id == "tid0"
        # idempotent; post-close stragglers land synchronously
        w.close()
        w.write(AuditedEvent(store="s", type_name="t", filter="late"))
        assert len(w.read_events()) == 26
