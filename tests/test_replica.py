"""Replicated serving tier (ISSUE 14): WAL shipping, follower apply,
bounded failover, the router front tier and rolling restarts.

The contracts under test:

- **Framing**: the replication wire format IS the on-disk WAL framing —
  ``pack_record`` + ``RecordParser`` roundtrip byte-exactly across any
  chunking; corruption raises, never mis-applies.
- **Cursor**: ``read_from`` is a READONLY iterator — it never truncates
  a live appender's torn tail (the CLI ``wal`` command and the ship
  endpoint share it); ``append_at`` installs leader-assigned seqs.
- **Ship + tail**: a follower converges to the leader's exact row set
  and reports lag 0; appends to a follower bounce 503 + the leader's
  URL; a position below the leader's compaction watermark is 410 Gone
  (re-provision), not silent wrong answers.
- **Failover kill matrix**: SIGKILL the leader at ``fail.wal.append``,
  mid-tail under load, and with promotion itself faulted
  (``fail.replica.promote``) — the surviving fleet serves exactly
  seed ∪ acked rows: no phantoms, no double-apply, bounded promotion.
- **Rolling restart**: the fleet orchestrator cycles a 3-replica group
  with /count bit-identical across the fleet after every step.
"""

import json
import multiprocessing as mp
import os
import shutil
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override, sys_prop
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.wal import (
    RecordParser,
    WalCorruption,
    WriteAheadLog,
    pack_record,
)

SPEC = "val:Int,dtg:Date,*geom:Point:srid=4326"
N0 = 40


def _rows(n, seed, fid0=0):
    rng = np.random.default_rng(seed)
    cols = {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(0, 10**9, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    return cols, np.arange(fid0, fid0 + n)


def _seeded_root(tmp_path, name="leader", n0=N0):
    root = str(tmp_path / name)
    ds = FileSystemDataStore(root, partition_size=128)
    ds.create_schema("t", SPEC)
    cols, fids = _rows(n0, seed=1)
    ds.write("t", cols, fids=fids)
    ds.flush("t")
    del ds
    return root


def _get(base, path, timeout=60):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, doc, timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _append_doc(fids, x=10.0):
    n = len(fids)
    return {
        "columns": {
            "val": list(range(n)),
            "dtg": [1000 + i for i in range(n)],
            "geom": [[x, x]] * n,
        },
        "fids": list(fids),
    }


def _fids(base):
    feats = _get(base, "/features/t?cql=INCLUDE&maxFeatures=100000")
    return {int(f["id"]) for f in feats["features"]}


def _wait(pred, timeout_s=20.0, poll_s=0.05, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out waiting for {msg}")


# -- framing + cursor unit tests ---------------------------------------------


def test_pack_record_parser_roundtrip_any_chunking():
    records = [(i, f"payload-{i}".encode() * (i + 1)) for i in range(20)]
    wire = b"".join(pack_record(s, p) for s, p in records)
    for chunk in (1, 7, 64, len(wire)):
        parser = RecordParser()
        got = []
        for off in range(0, len(wire), chunk):
            got.extend(parser.feed(wire[off:off + chunk]))
        assert got == records
        assert parser.pending_bytes == 0


def test_record_parser_rejects_corruption():
    wire = pack_record(0, b"x" * 64)
    bad = bytearray(wire)
    bad[-5] ^= 0xFF  # payload bit flip -> CRC mismatch
    with pytest.raises(WalCorruption):
        RecordParser().feed(bytes(bad))
    bad2 = bytearray(wire)
    bad2[0] ^= 0xFF  # magic damage
    with pytest.raises(WalCorruption):
        RecordParser().feed(bytes(bad2))


def test_wal_read_from_cursor_and_append_at(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    for i in range(5):
        wal.append(f"rec-{i}".encode())
    assert [s for s, _ in wal.read_from(-1)] == [0, 1, 2, 3, 4]
    assert [s for s, _ in wal.read_from(2)] == [3, 4]
    assert wal.first_seq() == 0
    # append_at adopts a leader-assigned seq (gaps allowed, rewinds not)
    assert wal.append_at(9, b"from-leader") == 9
    assert wal.next_seq == 10
    with pytest.raises(ValueError):
        wal.append_at(3, b"rewind")
    assert [s for s, _ in wal.read_from(4)] == [9]
    wal.close()


def test_wal_read_from_never_truncates_live_tail(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    for i in range(4):
        wal.append(f"rec-{i}".encode())
    wal.close()
    [seg] = wal.segments()
    with open(seg, "ab") as fh:  # a torn in-flight append
        fh.write(b"\x41\x57\x4d\x47torn-garbage")
    size = os.path.getsize(seg)
    ro = WriteAheadLog(str(tmp_path / "wal"), readonly=True)
    assert [s for s, _ in ro.read_from(-1)] == [0, 1, 2, 3]
    # the cursor must NOT have cut the tail out from under the appender
    assert os.path.getsize(seg) == size
    assert ro.truncations == 0
    ro.close()


def test_http_keepalive_is_a_declared_conf_key(tmp_path):
    """Satellite: the PR 12 hard-coded ``_Handler.timeout = 60`` is now
    the declared ``http.keepalive.s`` key, resolved at make_server."""
    from geomesa_tpu.server import serve_background

    assert float(sys_prop("http.keepalive.s")) == 60.0
    root = _seeded_root(tmp_path, "ka")
    ds = FileSystemDataStore(root, partition_size=128)
    with prop_override("http.keepalive.s", 17.5):
        server, _ = serve_background(ds)
        try:
            assert server.RequestHandlerClass.timeout == 17.5
        finally:
            server.shutdown()
            server.server_close()


# -- ship + tail --------------------------------------------------------------


@pytest.fixture
def pair(tmp_path):
    """A leader + one follower on copied roots, fast replication knobs.
    Yields (leader_base, follower_base, leader_server, follower_server);
    shuts both down afterwards."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    with prop_override("replica.lease.s", 1.5), \
            prop_override("replica.poll.ms", 25.0), \
            prop_override("replica.failover.s", 8.0):
        lsrv, _ = serve_background(
            FileSystemDataStore(lroot, partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        fsrv, _ = serve_background(
            FileSystemDataStore(froot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(role="follower", leader_url=lbase),
        )
        fbase = "http://%s:%s" % fsrv.server_address[:2]
        yield lbase, fbase, lsrv, fsrv
        for s in (lsrv, fsrv):
            try:
                s.shutdown()
                s.server_close()
            except Exception:
                pass


def test_follower_converges_and_reports_lag(pair):
    lbase, fbase, _, _ = pair
    out = _post(lbase, "/append/t", _append_doc([9001, 9002, 9003]))
    assert out["acked"] == 3
    _wait(
        lambda: _get(fbase, "/count/t")["count"] == N0 + 3,
        msg="follower catch-up",
    )
    assert _fids(fbase) == _fids(lbase)
    st = _get(fbase, "/stats/replica")
    assert st["enabled"] and st["role"] == "follower"
    assert st["lag_records"] == 0
    assert st["leader"] == lbase
    assert st["types"]["t"]["next_seq"] == 1
    lst = _get(lbase, "/stats/replica")
    assert lst["role"] == "leader"
    # the leader saw the follower's applied position (ship accounting)
    assert fbase in lst["followers"]
    # the roll-ups carry the replica doc too
    assert _get(fbase, "/stats")["replica"]["role"] == "follower"
    assert _get(fbase, "/readyz")["replica_role"] == "follower"


def test_follower_rejects_appends_with_leader_url(pair):
    lbase, fbase, _, _ = pair
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(fbase, "/append/t", _append_doc([9100]))
    assert ei.value.code == 503
    assert ei.value.headers["Retry-After"]
    doc = json.loads(ei.value.read())
    assert doc["leader"] == lbase


def test_apply_fault_retries_without_loss_or_double_apply(pair):
    from geomesa_tpu.failpoints import failpoint_override

    lbase, fbase, _, _ = pair
    with failpoint_override("fail.replica.apply", "raise:1"):
        _post(lbase, "/append/t", _append_doc([9301, 9302]))
        _wait(
            lambda: _get(fbase, "/count/t")["count"] == N0 + 2,
            msg="apply retried past the fault",
        )
    assert _fids(fbase) == _fids(lbase)


def test_ship_from_compacted_position_is_410_gone(tmp_path):
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    ds = FileSystemDataStore(lroot, partition_size=128)
    # tiny segments (clamped to 4 KiB) so the appends below seal at
    # least one segment for truncate_through to actually remove
    with prop_override("wal.segment.bytes", 1):
        lsrv, _ = serve_background(
            ds, stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        for i in range(24):
            _post(
                lbase, "/append/t",
                _append_doc(list(range(9000 + i * 8, 9008 + i * 8))),
            )
    try:
        stream = lsrv.stream_layer
        stream.compact_now("t")  # publishes the watermark AND truncates
        ts = stream._ts("t")
        assert ts.wal.first_seq() > 0  # the shipped history is really gone
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(lbase, "/wal/t?from=0")
        assert ei.value.code == 410
        doc = json.loads(ei.value.read())
        assert "re-provision" in doc["error"]
        # a CURRENT position still ships fine (204-equivalent empty 200)
        nxt = int(_get(lbase, "/stats/replica")["types"]["t"]["next_seq"])
        with urllib.request.urlopen(
            f"{lbase}/wal/t?from={nxt}", timeout=30
        ) as r:
            assert r.status == 200
            assert int(r.headers["X-Wal-Next-Seq"]) == nxt
            assert r.read() == b""
    finally:
        lsrv.shutdown()
        lsrv.server_close()


def test_replica_ack_mode_waits_for_follower(pair):
    lbase, fbase, _, _ = pair
    with prop_override("replica.ack", "replica"):
        out = _post(lbase, "/append/t", _append_doc([9401, 9402]))
    assert out["acked"] == 2
    assert out["replicated"] is True
    # replicated=True means the follower already holds the rows NOW
    assert _get(fbase, "/count/t")["count"] == N0 + 2


# -- failover ----------------------------------------------------------------


def test_lease_expiry_promotes_follower_exactly(pair):
    lbase, fbase, lsrv, _ = pair
    _post(lbase, "/append/t", _append_doc([9501, 9502]))
    _wait(
        lambda: _get(fbase, "/count/t")["count"] == N0 + 2,
        msg="pre-failover catch-up",
    )
    expected = _fids(lbase)
    lsrv.socket.close()  # abrupt death, no drain
    lsrv.shutdown()
    _wait(
        lambda: _get(fbase, "/stats/replica")["role"] == "leader",
        msg="promotion",
    )
    st = _get(fbase, "/stats/replica")
    assert st["failovers"] == 1
    bound = float(sys_prop("replica.failover.s"))
    assert st["last_failover_seconds"] <= bound
    # watermark-exact: the promoted follower serves exactly the acked set
    assert _fids(fbase) == expected
    # and takes appends at the next seq — the sequence space never forks
    out = _post(fbase, "/append/t", _append_doc([9503]))
    assert out["acked"] == 1
    assert _get(fbase, "/count/t")["count"] == N0 + 3


def test_promotion_fault_rolls_back_then_retries(pair):
    from geomesa_tpu.failpoints import failpoint_override

    lbase, fbase, lsrv, fsrv = pair
    with failpoint_override("fail.replica.promote", "raise:1"):
        lsrv.socket.close()
        lsrv.shutdown()
        # first promotion attempt fails AND rolls back to follower;
        # the next election cycle succeeds once the fault budget is spent
        _wait(
            lambda: _get(fbase, "/stats/replica")["role"] == "leader",
            timeout_s=30.0, msg="promotion after a faulted attempt",
        )
    assert _fids(fbase) == set(range(N0))
    assert _post(fbase, "/append/t", _append_doc([9601]))["acked"] == 1


def test_failover_stamped_in_flight_recorder(tmp_path):
    """Promotion writes a ``replica-failover`` flight-recorder bundle
    (the follower's make_server configured the recorder last, so its
    ``<root>/_flightrec`` is live when the promotion fires)."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    # interval 0: an earlier test's promotion must not rate-limit ours
    with prop_override("replica.lease.s", 1.0), \
            prop_override("replica.poll.ms", 25.0), \
            prop_override("slo.flightrec.interval.s", 0.0):
        lsrv, _ = serve_background(
            FileSystemDataStore(lroot, partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        fsrv, _ = serve_background(
            FileSystemDataStore(froot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(role="follower", leader_url=lbase),
        )
        fbase = "http://%s:%s" % fsrv.server_address[:2]
        try:
            _wait(
                lambda: fbase
                in _get(lbase, "/stats/replica")["followers"],
                msg="tail established (a ship happened)",
            )
            lsrv.socket.close()
            lsrv.shutdown()
            _wait(
                lambda: _get(fbase, "/stats/replica")["role"] == "leader",
                msg="promotion",
            )
            recdir = os.path.join(froot, "_flightrec")

            def _bundles():
                try:
                    return sorted(
                        e for e in os.listdir(recdir)
                        if e.endswith("-replica-failover")
                    )
                except FileNotFoundError:
                    return []

            # the bundle publishes via atomic rename off the promotion
            # thread; give the dump a beat
            _wait(lambda: _bundles(), msg="flight-recorder bundle")
            bundles = _bundles()
            with open(os.path.join(recdir, bundles[-1], "reason.json")) as fh:
                doc = json.load(fh)
            assert doc["reason"] == "replica-failover"
            assert doc["detail"]["self"] == fbase
            assert doc["detail"]["dead_leader"] == lbase
        finally:
            for s in (lsrv, fsrv):
                try:
                    s.shutdown()
                    s.server_close()
                except Exception:
                    pass


# -- the kill matrix (subprocess SIGKILL legs) --------------------------------


def _leader_proc(root, portfile, armfile):
    """Subprocess body: a replicated leader that arms
    ``fail.wal.append=kill`` once ``armfile`` appears — the next append
    SIGKILLs the process mid-write, the exact instant the matrix
    needs."""
    from geomesa_tpu import failpoints
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background
    from geomesa_tpu.store.fs import FileSystemDataStore as _FS

    srv, _ = serve_background(
        _FS(root, partition_size=128), stream=True,
        replica=ReplicaConfig(role="leader"),
    )
    port = srv.server_address[1]
    with open(portfile + ".tmp", "w") as fh:
        fh.write(str(port))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(portfile + ".tmp", portfile)
    while True:
        if armfile and os.path.exists(armfile):
            failpoints.set_failpoint("fail.wal.append", "kill")
        time.sleep(0.01)


def _spawn_leader(tmp_path, lroot, arm=True):
    ctx = mp.get_context("spawn")  # fork is unsafe under JAX threads
    portfile = str(tmp_path / "port")
    armfile = str(tmp_path / "arm") if arm else ""
    p = ctx.Process(
        target=_leader_proc, args=(lroot, portfile, armfile)
    )
    p.start()
    deadline = time.monotonic() + 60
    while not os.path.exists(portfile):
        assert time.monotonic() < deadline, "leader subprocess never bound"
        assert p.is_alive(), "leader subprocess died during startup"
        time.sleep(0.05)
    port = int(open(portfile).read())
    return p, f"http://127.0.0.1:{port}", armfile


@pytest.fixture
def follower_of(tmp_path):
    """Factory: an in-process follower of ``leader_url`` on a copy of
    ``lroot`` made BEFORE the leader process opened it."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    made = []
    overrides = [
        prop_override("replica.lease.s", 1.5),
        prop_override("replica.poll.ms", 25.0),
    ]
    for o in overrides:
        o.__enter__()

    def make(froot, leader_url):
        srv, _ = serve_background(
            FileSystemDataStore(froot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(role="follower", leader_url=leader_url),
        )
        made.append(srv)
        return "http://%s:%s" % srv.server_address[:2], srv

    yield make
    for srv in made:
        try:
            srv.shutdown()
            srv.server_close()
        except Exception:
            pass
    for o in reversed(overrides):
        o.__exit__(None, None, None)


def test_kill_matrix_sigkill_at_wal_append(tmp_path, follower_of):
    """SIGKILL the leader inside the WAL append (before durability):
    the follower serves exactly seed ∪ previously-acked rows — the
    killed append was never acked and never ships."""
    lroot = _seeded_root(tmp_path, "leader")
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    p, lbase, armfile = _spawn_leader(tmp_path, lroot)
    try:
        fbase, _ = follower_of(froot, lbase)
        acked = set(range(N0))
        out = _post(lbase, "/append/t", _append_doc([9001, 9002, 9003]))
        assert out["acked"] == 3
        acked |= {9001, 9002, 9003}
        _wait(
            lambda: _get(fbase, "/count/t")["count"] == len(acked),
            msg="pre-kill catch-up",
        )
        open(armfile, "w").close()
        time.sleep(0.3)  # the subprocess polls the armfile every 10ms
        with pytest.raises(Exception):  # connection dies mid-append
            _post(lbase, "/append/t", _append_doc([9004, 9005]))
        p.join(60)
        assert p.exitcode == -signal.SIGKILL
        # no phantoms (9004/9005 never acked), no loss, no double-apply
        time.sleep(0.5)
        assert _fids(fbase) == acked
        assert _get(fbase, "/count/t")["count"] == len(acked)
    finally:
        if p.is_alive():
            p.kill()
        p.join(10)


def test_kill_matrix_sigkill_mid_tail_under_load(tmp_path, follower_of):
    """External SIGKILL while the follower is actively tailing under
    concurrent append + query load: reads never fail over the window,
    and the follower ends with acked ⊆ served ⊆ acked ∪ the one
    in-flight batch (durable-but-unacked at the kill is legal — it is
    the same ambiguity a crashed single node has)."""
    lroot = _seeded_root(tmp_path, "leader")
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    p, lbase, _ = _spawn_leader(tmp_path, lroot, arm=False)
    acked = set(range(N0))
    inflight: set = set()
    read_errors = []
    stop_reads = threading.Event()

    def reader():
        while not stop_reads.is_set():
            try:
                _get(fbase, "/count/t", timeout=10)
            except Exception as e:
                read_errors.append(repr(e))
            time.sleep(0.01)

    try:
        fbase, _ = follower_of(froot, lbase)
        rt = threading.Thread(target=reader)
        rt.start()
        fid = 9000
        batches = 0
        while batches < 6:
            fids = list(range(fid, fid + 4))
            fid += 4
            inflight.update(fids)
            out = _post(lbase, "/append/t", _append_doc(fids))
            assert out["acked"] == 4
            inflight.difference_update(fids)
            acked.update(fids)
            batches += 1
        # the leader acks LOCAL durability (default replica.ack) — an
        # acked batch the tail has not fetched yet legally dies with
        # the leader. Wait until the 6 safe batches actually shipped,
        # so the kill window holds only the one racing batch below.
        _wait(
            lambda: _get(fbase, "/count/t")["count"] == len(acked),
            msg="safe batches shipped before the kill",
        )
        # one more append racing the kill: ack AND ship outcome unknown
        # (local ack ≠ replicated), so it stays in-flight either way
        fids = list(range(fid, fid + 4))
        inflight.update(fids)
        killer = threading.Timer(0.01, lambda: os.kill(p.pid, signal.SIGKILL))
        killer.start()
        try:
            _post(lbase, "/append/t", _append_doc(fids))
        except Exception:
            pass  # killed mid-request
        p.join(60)
        assert p.exitcode == -signal.SIGKILL
        time.sleep(1.0)  # let the tail drain whatever shipped
        stop_reads.set()
        rt.join(10)
        # reads kept serving from the follower throughout the kill
        assert read_errors == []
        got = _fids(fbase)
        assert acked <= got, f"lost acked rows: {sorted(acked - got)[:10]}"
        assert got <= acked | inflight, (
            f"phantom rows: {sorted(got - acked - inflight)[:10]}"
        )
        # no double-apply: row count == distinct fids
        assert _get(fbase, "/count/t")["count"] == len(got)
    finally:
        stop_reads.set()
        if p.is_alive():
            p.kill()
        p.join(10)


# -- router front tier --------------------------------------------------------


def test_router_reads_retry_and_appends_pin_to_leader(pair):
    from geomesa_tpu.router import route_background

    lbase, fbase, lsrv, _ = pair
    with prop_override("router.health.ms", 80.0):
        rsrv, _ = route_background([lbase, fbase])
        rbase = "http://%s:%s" % rsrv.server_address[:2]
        try:
            _wait(
                lambda: _get(rbase, "/stats/router")["leader"] == lbase,
                msg="router leader discovery",
            )
            # reads round-robin both replicas
            for _ in range(4):
                assert _get(rbase, "/count/t")["count"] == N0
            # appends land on the leader through the router
            out = _post(rbase, "/append/t", _append_doc([9701]))
            assert out["acked"] == 1
            _wait(
                lambda: _get(fbase, "/count/t")["count"] == N0 + 1,
                msg="follower catch-up",
            )
            # leader dies: reads keep serving (retried onto the follower)
            lsrv.socket.close()
            lsrv.shutdown()
            for _ in range(10):
                assert _get(rbase, "/count/t")["count"] == N0 + 1
            # appends shed 503+Retry-After until promotion, then resume
            deadline = time.monotonic() + 20
            out = None
            while time.monotonic() < deadline:
                try:
                    out = _post(rbase, "/append/t", _append_doc([9702]))
                    break
                except urllib.error.HTTPError as e:
                    assert e.code == 503
                    assert e.headers.get("Retry-After")
                    time.sleep(0.2)
            assert out is not None and out["acked"] == 1
            st = _get(rbase, "/stats/router")
            assert st["leader"] == fbase
        finally:
            rsrv.shutdown()
            rsrv.server_close()


def test_router_rejects_admin_posts(pair):
    from geomesa_tpu.router import route_background

    lbase, fbase, _, _ = pair
    rsrv, _ = route_background([lbase, fbase])
    rbase = "http://%s:%s" % rsrv.server_address[:2]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(rbase, "/admin/shutdown", {})
        assert ei.value.code == 404  # backends must be drained directly
        assert _get(lbase, "/healthz")  # nobody drained anything
    finally:
        rsrv.shutdown()
        rsrv.server_close()


# -- quorum, fencing, gap + GC safety, admin gate, streaming relay -----------


def _reserve_ports(n):
    """Bind-then-release N loopback ports so a replica group can know
    every member's URL before any member starts."""
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_minority_partition_never_self_promotes(tmp_path):
    """A follower whose electorate majority is unreachable must NOT
    promote when its leader stops answering: one vote of three is a
    minority — it stays follower (reads keep serving) instead of
    forking the seq space from the wrong side of a partition."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    phantom = "http://127.0.0.1:9"  # reserved port: never answers
    with prop_override("replica.lease.s", 1.0), \
            prop_override("replica.poll.ms", 25.0):
        lsrv, _ = serve_background(
            FileSystemDataStore(lroot, partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        fsrv, _ = serve_background(
            FileSystemDataStore(froot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(
                role="follower", leader_url=lbase,
                peers=(lbase, phantom),
            ),
        )
        fbase = "http://%s:%s" % fsrv.server_address[:2]
        try:
            _wait(
                lambda: fbase
                in _get(lbase, "/stats/replica")["followers"],
                msg="tail established",
            )
            lsrv.socket.close()
            lsrv.shutdown()
            # hold through SEVERAL expired leases: still a follower
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                st = _get(fbase, "/stats/replica")
                assert st["role"] == "follower", "minority self-promoted"
                assert _get(fbase, "/count/t")["count"] == N0
                time.sleep(0.25)
            assert _get(fbase, "/stats/replica")["failovers"] == 0
        finally:
            for s in (lsrv, fsrv):
                try:
                    s.shutdown()
                    s.server_close()
                except Exception:
                    pass


def test_quorum_failover_elects_one_leader_with_higher_epoch(tmp_path):
    """3-replica group with the full electorate declared: after the
    leader dies, the two survivors form a majority, exactly ONE
    promotes — at an election epoch above the dead leader's — and the
    other re-points and tails the winner."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    r0 = _seeded_root(tmp_path, "n0")
    roots = {0: r0}
    for i in (1, 2):
        roots[i] = str(tmp_path / f"n{i}")
        shutil.copytree(r0, roots[i])
    ports = _reserve_ports(3)
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    servers = []
    with prop_override("replica.lease.s", 1.5), \
            prop_override("replica.poll.ms", 25.0), \
            prop_override("replica.failover.s", 8.0):
        for i in range(3):
            srv, _ = serve_background(
                FileSystemDataStore(roots[i], partition_size=128),
                port=ports[i], stream=True,
                replica=ReplicaConfig(
                    role="leader" if i == 0 else "follower",
                    self_url=urls[i],
                    leader_url="" if i == 0 else urls[0],
                    peers=tuple(u for j, u in enumerate(urls) if j != i),
                ),
            )
            servers.append(srv)
        try:
            _post(urls[0], "/append/t", _append_doc([9001, 9002]))
            for u in urls[1:]:
                _wait(
                    lambda u=u: _get(u, "/count/t")["count"] == N0 + 2,
                    msg="pre-failover catch-up",
                )
            servers[0].socket.close()
            servers[0].shutdown()
            survivors = urls[1:]
            _wait(
                lambda: any(
                    _get(u, "/stats/replica")["role"] == "leader"
                    for u in survivors
                ),
                timeout_s=25.0, msg="quorum promotion",
            )
            docs = {u: _get(u, "/stats/replica") for u in survivors}
            leaders = [u for u, d in docs.items() if d["role"] == "leader"]
            assert len(leaders) == 1, docs
            winner = leaders[0]
            loser = next(u for u in survivors if u != winner)
            # the fencing token moved past the dead leader's epoch 1
            assert docs[winner]["epoch"] >= 2
            _wait(
                lambda: _get(loser, "/stats/replica")["leader"] == winner,
                msg="loser re-points at the winner",
            )
            assert _post(winner, "/append/t",
                         _append_doc([9003]))["acked"] == 1
            _wait(
                lambda: _get(loser, "/count/t")["count"] == N0 + 3,
                msg="loser tails the winner",
            )
        finally:
            for srv in servers:
                try:
                    srv.shutdown()
                    srv.server_close()
                except Exception:
                    pass


def test_ship_request_with_higher_epoch_fences_stale_leader(pair):
    """The fencing token rides every ship request: a leader seeing a
    follower tail at a HIGHER election epoch learns a quorum elected a
    successor while it was stalled — it demotes in that same request
    and refuses appends, so two processes never extend one seq space."""
    lbase, fbase, _, _ = pair
    st = _get(lbase, "/stats/replica")
    assert st["role"] == "leader" and st["epoch"] == 1
    nxt = int(st["types"]["t"]["next_seq"])
    with urllib.request.urlopen(
        f"{lbase}/wal/t?from={nxt}&epoch=7", timeout=30
    ) as r:
        assert r.status == 200
        # the SAME response already answers as a demoted node — a
        # tailing follower refuses it instead of adopting a forked tail
        assert r.headers["X-Replica-Role"] == "follower"
        assert r.headers["X-Replica-Epoch"] == "7"
        r.read()
    st = _get(lbase, "/stats/replica")
    assert st["role"] == "follower"
    assert st["epoch"] == 7
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(lbase, "/append/t", _append_doc([9801]))
    assert ei.value.code == 503


def test_revenant_leader_demotes_via_peer_watch(tmp_path):
    """Fencing with no client in the loop: a leader that declares
    peers probes them every half-lease, and on finding one advertising
    a higher election epoch demotes itself and re-tails the successor
    (the revenant ex-leader scenario after a restart-as-leader)."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    aroot = _seeded_root(tmp_path, "a")
    broot = str(tmp_path / "b")
    shutil.copytree(aroot, broot)
    with prop_override("replica.lease.s", 1.0), \
            prop_override("replica.poll.ms", 25.0):
        asrv, _ = serve_background(
            FileSystemDataStore(aroot, partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        abase = "http://%s:%s" % asrv.server_address[:2]
        asrv.replica._epoch = 4  # "a" won an election the revenant missed
        bsrv, _ = serve_background(
            FileSystemDataStore(broot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(role="leader", peers=(abase,)),
        )
        bbase = "http://%s:%s" % bsrv.server_address[:2]
        try:
            _wait(
                lambda: _get(bbase, "/stats/replica")["role"] == "follower",
                msg="revenant demotion",
            )
            st = _get(bbase, "/stats/replica")
            assert st["epoch"] == 4
            assert st["leader"] == abase
            _post(abase, "/append/t", _append_doc([9901]))
            _wait(
                lambda: _get(bbase, "/count/t")["count"] == N0 + 1,
                msg="ex-leader tails the successor",
            )
        finally:
            for s in (asrv, bsrv):
                try:
                    s.shutdown()
                    s.server_close()
                except Exception:
                    pass


def test_apply_replicated_rejects_gapped_seq(tmp_path):
    """A shipped record whose seq would GAP the local WAL (leader-side
    GC raced the ship) raises instead of applying — permanently missing
    acked rows behind a lag-0 report is the one outcome the apply path
    must never produce. Already-held seqs stay an idempotent skip."""
    from geomesa_tpu.store.stream import ReplicationGapError, StreamingStore

    ds = FileSystemDataStore(
        _seeded_root(tmp_path, "n"), partition_size=128
    )
    layer = StreamingStore(ds)
    try:
        cols, fids = _rows(4, seed=5, fid0=9000)
        layer.append("t", cols, fids=fids)  # local seq 0
        payload = next(iter(layer._ts("t").wal.read_from(-1)))[1]
        assert layer.apply_replicated("t", 1, payload) > 0  # contiguous
        assert layer.apply_replicated("t", 0, payload) == 0  # idempotent
        with pytest.raises(ReplicationGapError):
            layer.apply_replicated("t", 5, payload)
        assert int(layer._ts("t").wal.next_seq) == 2  # nothing landed
    finally:
        layer.close()


def test_wal_gc_pinned_to_live_follower_position(tmp_path):
    """The leader's compactor must not truncate WAL segments a live
    follower still has to ship (that forces the 410 re-provision
    cliff); a follower silent past ``replica.retain.s`` stops pinning
    — a dead follower must not pin the log forever."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    ds = FileSystemDataStore(lroot, partition_size=128)
    with prop_override("wal.segment.bytes", 1):
        lsrv, _ = serve_background(
            ds, stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        for i in range(24):
            _post(
                lbase, "/append/t",
                _append_doc(list(range(9000 + i * 8, 9008 + i * 8))),
            )
    try:
        stream = lsrv.stream_layer
        ts = stream._ts("t")
        lsrv.replica.note_follower("http://follower:1", "t", 3)
        stream.compact_now("t")
        first = ts.wal.first_seq()
        assert 0 <= first <= 4, first  # segments past seq 3 survive GC
        with urllib.request.urlopen(
            f"{lbase}/wal/t?from=4", timeout=30
        ) as r:
            assert r.status == 200  # the pinned position still ships
        # the follower goes silent past the retention window: unpinned
        with prop_override("replica.retain.s", 0.0):
            time.sleep(0.05)
            stream.compact_now("t")
        assert ts.wal.first_seq() > 3
    finally:
        lsrv.shutdown()
        lsrv.server_close()


def test_ship_never_streams_across_a_missing_segment(tmp_path):
    """A WAL segment unlinked under the walking ship cursor must END
    the stream at the hole, never skip it: the shipped prefix stays
    contiguous, and the follower re-asks from its true position (where
    the gap machinery answers honestly)."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    ds = FileSystemDataStore(lroot, partition_size=128)
    with prop_override("wal.segment.bytes", 1):
        lsrv, _ = serve_background(
            ds, stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        for i in range(24):
            _post(
                lbase, "/append/t",
                _append_doc(list(range(9000 + i * 8, 9008 + i * 8))),
            )
    try:
        segs = lsrv.stream_layer._ts("t").wal.segments()
        assert len(segs) >= 3, segs
        os.remove(segs[1])  # GC racing the cursor, mid-walk
        with urllib.request.urlopen(
            lbase + "/wal/t?from=0", timeout=30
        ) as r:
            data = r.read()
        seqs = [s for s, _ in RecordParser().feed(data)]
        assert seqs, "nothing shipped at all"
        assert seqs == list(range(seqs[0], seqs[0] + len(seqs))), seqs
        assert seqs[-1] < 23  # ended BEFORE the hole, no post-gap tail
    finally:
        lsrv.shutdown()
        lsrv.server_close()


def test_persistent_apply_fault_holds_lease_and_flags_reprovision(pair):
    """An apply-side failure is NOT leader death: the follower keeps
    renewing its lease (no spurious election against a healthy
    leader) and, after repeated failures, flags the type
    ``needs_reprovision`` for the operator instead of retrying
    silently forever."""
    from geomesa_tpu.failpoints import failpoint_override

    lbase, fbase, _, _ = pair
    with failpoint_override("fail.replica.apply", "raise:1000"):
        _post(lbase, "/append/t", _append_doc([9951]))
        _wait(
            lambda: _get(fbase, "/stats/replica")["types"]["t"].get(
                "needs_reprovision"),
            msg="needs_reprovision flagged",
        )
        time.sleep(3.0)  # several lease periods under the fault
        st = _get(fbase, "/stats/replica")
        assert st["role"] == "follower"
        assert st["failovers"] == 0
    # fault lifted: the very next fetch heals — contact never lapsed
    _wait(
        lambda: _get(fbase, "/count/t")["count"] == N0 + 1,
        msg="catch-up after the fault burns out",
    )
    assert not _get(
        fbase, "/stats/replica"
    )["types"]["t"].get("needs_reprovision")


def test_admin_shutdown_gated_by_token(tmp_path):
    """With ``admin.token`` configured, ``/admin/shutdown`` refuses
    callers without the exact ``X-Admin-Token`` header — a reachable
    serving port must not double as an unauthenticated kill switch.
    ``fleet.drain`` presents the token from its own conf."""
    from geomesa_tpu.server import serve_background
    from geomesa_tpu.tools import fleet

    root = _seeded_root(tmp_path, "one")
    server, _ = serve_background(
        FileSystemDataStore(root, partition_size=128)
    )
    base = "http://%s:%s" % server.server_address[:2]
    try:
        with prop_override("admin.token", "s3cret"):
            for hdrs in ({}, {"X-Admin-Token": "wrong"}):
                req = urllib.request.Request(
                    base + "/admin/shutdown", data=b"", method="POST",
                    headers=hdrs,
                )
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=10)
                assert ei.value.code == 403
            assert _get(base, "/healthz")  # nothing drained
            assert fleet.drain(base)["draining"] is True
    finally:
        try:
            server.shutdown()
            server.server_close()
        except Exception:
            pass


def test_router_relays_streams_chunkwise(pair):
    """The proxied ship stream arrives byte-identical through the
    router — which now relays chunk-by-chunk instead of buffering
    whole bodies — replication headers (epoch included) intact, and
    Content-Length JSON responses ride the same path."""
    from geomesa_tpu.router import route_background

    lbase, fbase, _, _ = pair
    _post(lbase, "/append/t", _append_doc([9851, 9852, 9853]))
    rsrv, _ = route_background([lbase])
    rbase = "http://%s:%s" % rsrv.server_address[:2]
    try:
        with urllib.request.urlopen(
            lbase + "/wal/t?from=0", timeout=30
        ) as r:
            direct = r.read()
            want_next = r.headers["X-Wal-Next-Seq"]
        assert direct  # the appends above really shipped bytes
        with urllib.request.urlopen(
            rbase + "/wal/t?from=0", timeout=30
        ) as r:
            via = r.read()
            assert r.headers["X-Wal-Next-Seq"] == want_next
            assert r.headers["X-Replica-Epoch"] == "1"
        assert via == direct
        assert _get(rbase, "/count/t") == _get(lbase, "/count/t")
    finally:
        rsrv.shutdown()
        rsrv.server_close()


# -- rolling restart ----------------------------------------------------------


def test_rolling_restart_three_replicas_bit_identical(tmp_path):
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background
    from geomesa_tpu.tools import fleet

    roots = {}
    r0 = _seeded_root(tmp_path, "n0")
    roots[0] = r0
    for i in (1, 2):
        roots[i] = str(tmp_path / f"n{i}")
        shutil.copytree(r0, roots[i])
    servers: dict = {}
    with prop_override("replica.lease.s", 1.5), \
            prop_override("replica.poll.ms", 25.0), \
            prop_override("replica.failover.s", 8.0):
        lsrv, _ = serve_background(
            FileSystemDataStore(roots[0], partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        lurl = "http://%s:%s" % lsrv.server_address[:2]
        urls = [lurl]
        servers[lurl] = lsrv
        rootof = {lurl: roots[0]}
        for i in (1, 2):
            srv, _ = serve_background(
                FileSystemDataStore(roots[i], partition_size=128),
                stream=True,
                replica=ReplicaConfig(role="follower", leader_url=lurl),
            )
            u = "http://%s:%s" % srv.server_address[:2]
            urls.append(u)
            servers[u] = srv
            rootof[u] = roots[i]
        try:
            _post(lurl, "/append/t", _append_doc([9001, 9002]))

            def restart(url, role, leader_url):
                old = servers.pop(url, None)
                if old is not None:
                    old.server_close()  # a real exit frees the port
                port = int(url.rsplit(":", 1)[1])
                srv, _ = serve_background(
                    FileSystemDataStore(rootof[url], partition_size=128),
                    port=port, stream=True,
                    replica=ReplicaConfig(
                        role=role, self_url=url, leader_url=leader_url,
                        peers=tuple(u for u in urls if u != url),
                    ),
                )
                servers[url] = srv

            report = fleet.rolling_restart(
                urls, restart, timeout_s=40.0, log=lambda m: None
            )
            assert report["baseline_counts"] == {"t": N0 + 2}
            assert report["final_counts"] == {"t": N0 + 2}
            assert len(report["steps"]) == 3
            # EVERY step re-verified bit-identical counts fleet-wide
            assert all(s["counts"] == {"t": N0 + 2} for s in report["steps"])
            roles = sorted(
                fleet.probe(u)["role"] for u in urls
            )
            assert roles == ["follower", "follower", "leader"]
        finally:
            for srv in servers.values():
                try:
                    srv.shutdown()
                    srv.server_close()
                except Exception:
                    pass
