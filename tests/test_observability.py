"""Audit log + metrics registry (ref geomesa audit/metrics subsystems)."""

import numpy as np
import pytest

from geomesa_tpu.audit import AuditedEvent, FileAuditWriter, MemoryAuditWriter
from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.metrics import REGISTRY, MetricsRegistry
from geomesa_tpu.store import MemoryDataStore
from geomesa_tpu.store.fs import FileSystemDataStore


def small_store(**kw):
    sft = SimpleFeatureType.create("t", "count:Int,*geom:Point:srid=4326")
    ds = MemoryDataStore(**kw)
    ds.create_schema(sft)
    ds.write(
        "t", {"count": np.arange(10), "geom": np.zeros((10, 2))}
    )
    return ds


class TestAudit:
    def test_memory_store_audits_queries(self):
        aw = MemoryAuditWriter()
        ds = small_store(audit_writer=aw)
        ds.query("t", "count < 5")
        aw.flush()
        assert len(aw.events) == 1
        ev = aw.events[0]
        assert ev.type_name == "t"
        assert ev.hits == 5
        assert ev.planning_ms >= 0 and ev.scanning_ms >= 0
        assert "count" in ev.filter

    def test_fs_store_audit_file(self, tmp_path):
        root = str(tmp_path / "cat")
        ds = FileSystemDataStore(root, audit=True)
        sft = SimpleFeatureType.create("t", "count:Int,*geom:Point:srid=4326")
        ds.create_schema(sft)
        ds.write("t", {"count": np.arange(6), "geom": np.zeros((6, 2))})
        ds.flush("t")
        ds.query("t", "count >= 3")
        ds.audit_writer.flush()
        events = ds.audit_writer.read_events()
        assert len(events) == 1
        assert events[0].hits == 3
        # round-trips through json
        assert AuditedEvent(**{
            k: v for k, v in events[0].__dict__.items()
        }).hits == 3

    def test_audit_never_breaks_query(self):
        class Broken(MemoryAuditWriter):
            def write(self, event):
                raise RuntimeError("boom")

        ds = small_store(audit_writer=Broken())
        assert len(ds.query("t", "INCLUDE")) == 10  # no raise


class TestMetrics:
    def test_counter_labels(self):
        r = MetricsRegistry()
        c = r.counter("x_total", "help")
        c.inc(store="a")
        c.inc(2, store="a")
        c.inc(store="b")
        assert c.value(store="a") == 3
        assert c.value(store="b") == 1
        text = r.prometheus_text()
        assert '# TYPE x_total counter' in text
        assert 'x_total{store="a"} 3' in text

    def test_histogram_buckets_cumulative(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        text = r.prometheus_text()
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1"} 3' in text
        assert 'lat_seconds_bucket{le="10"} 4' in text
        assert 'lat_seconds_bucket{le="+Inf"} 5' in text
        assert "lat_seconds_count 5" in text

    def test_boundary_value_in_le_bucket(self):
        r = MetricsRegistry()
        h = r.histogram("h", buckets=(1.0, 2.0))
        h.observe(1.0)  # le="1" must include exactly-1.0
        assert 'h_bucket{le="1"} 1' in r.prometheus_text()

    def test_gauge_and_kind_conflict(self):
        r = MetricsRegistry()
        g = r.gauge("g")
        g.set(7, role="x")
        assert g.value(role="x") == 7
        with pytest.raises(TypeError):
            r.counter("g")

    def test_query_path_increments_global_registry(self):
        before = REGISTRY.counter("geomesa_queries_total").value(
            store="memory", type="t"
        )
        ds = small_store()
        ds.query("t", "INCLUDE")
        after = REGISTRY.counter("geomesa_queries_total").value(
            store="memory", type="t"
        )
        assert after == before + 1
