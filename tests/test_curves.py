"""Z2/Z3 SFC + normalization + time binning semantics tests."""

import numpy as np
import pytest

from geomesa_tpu.curves import (
    BinnedTime,
    NormalizedLat,
    NormalizedLon,
    TimePeriod,
    Z2SFC,
    Z3SFC,
)
from geomesa_tpu.curves import binnedtime


class TestNormalize:
    def test_edges(self):
        lon = NormalizedLon(31)
        assert int(lon.normalize(-180.0)) == 0
        assert int(lon.normalize(180.0)) == lon.max_index
        assert int(lon.normalize(179.99999999)) == lon.max_index
        assert int(lon.normalize(0.0)) == 1 << 30

    def test_roundtrip_within_bin(self, rng):
        lat = NormalizedLat(21)
        v = rng.uniform(-90, 90, size=1000)
        idx = lat.normalize(v)
        back = lat.denormalize(idx)
        width = 180.0 / (1 << 21)
        assert np.all(np.abs(back - v) <= width)

    def test_denormalize_is_bin_center(self):
        lon = NormalizedLon(31)
        width = 360.0 / (1 << 31)
        assert lon.denormalize(0) == pytest.approx(-180.0 + width / 2)

    def test_jax_matches_np(self, rng):
        import jax.numpy as jnp

        lon = NormalizedLon(21)
        v = rng.uniform(-180, 180, size=4096)
        np.testing.assert_array_equal(
            np.asarray(lon.normalize_jax(jnp.asarray(v))), lon.normalize(v)
        )

    def test_jax_boundary_no_int32_overflow(self):
        # floor((v-min)*scale) == 2**31 for v just below max at precision=31;
        # must clamp in float before the int cast (code-review finding).
        import jax.numpy as jnp

        lon = NormalizedLon(31)
        vals = np.array(
            [np.nextafter(180.0, -np.inf), 180.0, -180.0, np.nextafter(-180.0, np.inf)]
        )
        np.testing.assert_array_equal(
            np.asarray(lon.normalize_jax(jnp.asarray(vals))), lon.normalize(vals)
        )


class TestBinnedTime:
    def test_week_binning(self):
        # 1970-01-08T00:00:00Z = exactly 1 week after epoch
        ms = 7 * 86400000
        b, off = binnedtime.to_binned_time(ms, TimePeriod.WEEK)
        assert (int(b), int(off)) == (1, 0)
        b, off = binnedtime.to_binned_time(ms - 1000, TimePeriod.WEEK)
        assert (int(b), int(off)) == (0, 604799)

    def test_day_binning(self):
        b, off = binnedtime.to_binned_time(86400000 + 123, TimePeriod.DAY)
        assert (int(b), int(off)) == (1, 123)

    def test_month_binning(self):
        # 2020-03-01T00:00:10Z
        ms = np.datetime64("2020-03-01T00:00:10", "ms").astype(np.int64)
        b, off = binnedtime.to_binned_time(ms, TimePeriod.MONTH)
        assert int(b) == (2020 - 1970) * 12 + 2
        assert int(off) == 10

    def test_year_binning(self):
        ms = np.datetime64("1999-01-01T00:02:00", "ms").astype(np.int64)
        b, off = binnedtime.to_binned_time(ms, TimePeriod.YEAR)
        assert (int(b), int(off)) == (29, 2)

    def test_roundtrip(self, rng):
        ms = rng.integers(0, 2**41, size=500)  # up to ~2039
        for period in TimePeriod:
            b, off = binnedtime.to_binned_time(ms, period)
            back = binnedtime.binned_time_to_millis(b, off, period)
            unit = {"day": 1, "week": 1000, "month": 1000, "year": 60000}[
                period.value
            ]
            assert np.all(ms - back < unit)
            assert np.all(back <= ms)

    def test_bins_for_interval(self):
        wk = 7 * 86400000
        spans = binnedtime.bins_for_interval(wk - 5000, 2 * wk + 1000, "week")
        assert spans == [
            (0, 604795, 604800),
            (1, 0, 604800),
            (2, 0, 1),
        ]

    def test_max_offsets(self):
        assert binnedtime.max_offset("day") == 86400000
        assert binnedtime.max_offset("week") == 604800
        assert binnedtime.max_offset("month") == 2678400
        assert binnedtime.max_offset("year") == 527040


class TestZ2:
    def test_known_corners(self):
        sfc = Z2SFC()
        assert int(sfc.index(-180.0, -90.0)) == 0
        assert int(sfc.index(180.0, 90.0)) == (1 << 62) - 1

    def test_invert_roundtrip(self, rng):
        sfc = Z2SFC()
        x = rng.uniform(-180, 180, 1000)
        y = rng.uniform(-90, 90, 1000)
        ix, iy = sfc.invert(sfc.index(x, y))
        assert np.all(np.abs(ix - x) <= 360.0 / (1 << 31))
        assert np.all(np.abs(iy - y) <= 180.0 / (1 << 31))


class TestZ3:
    def test_z3_range_containment(self, rng):
        sfc = Z3SFC()
        box = (-10.0, 20.0, 5.0, 45.0)
        t0, t1 = 10000.0, 200000.0
        ranges = sfc.ranges(box[0], box[1], box[2], box[3], t0, t1)
        arr = np.array([(r.lower, r.upper) for r in ranges], dtype=np.int64)
        # every point inside the box must land in some range
        x = rng.uniform(box[0], box[2], 2000)
        y = rng.uniform(box[1], box[3], 2000)
        t = rng.uniform(t0, t1, 2000)
        z = sfc.index(x, y, t).astype(np.int64)
        idx = np.searchsorted(arr[:, 0], z, side="right") - 1
        ok = (idx >= 0) & (z <= arr[np.clip(idx, 0, len(arr) - 1), 1])
        assert np.all(ok)

    def test_z3_ranges_exclude_far_points(self, rng):
        sfc = Z3SFC()
        ranges = sfc.ranges(-10.0, 20.0, 5.0, 45.0, 10000.0, 200000.0)
        arr = np.array([(r.lower, r.upper) for r in ranges], dtype=np.int64)
        # points far outside should mostly not be covered
        x = rng.uniform(100, 170, 2000)
        y = rng.uniform(-80, -50, 2000)
        t = rng.uniform(400000, 600000, 2000)
        z = sfc.index(x, y, t).astype(np.int64)
        idx = np.searchsorted(arr[:, 0], z, side="right") - 1
        hit = (idx >= 0) & (z <= arr[np.clip(idx, 0, len(arr) - 1), 1])
        assert np.mean(hit) < 0.05

    def test_hi_lo_encode_matches(self, rng):
        import jax.numpy as jnp

        sfc = Z3SFC()
        x = rng.uniform(-180, 180, 1024)
        y = rng.uniform(-90, 90, 1024)
        t = rng.uniform(0, 604800, 1024)
        hi, lo = sfc.index_jax_hi_lo(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(t)
        )
        z = sfc.index(x, y, t)
        np.testing.assert_array_equal(
            np.asarray(hi, dtype=np.uint64), z >> np.uint64(32)
        )
        np.testing.assert_array_equal(
            np.asarray(lo, dtype=np.uint64), z & np.uint64(0xFFFFFFFF)
        )
