"""Pallas fused-scan kernel vs the exact host oracle (interpret mode on
CPU -- the same kernel code the TPU runs, per SURVEY.md section 4 rebuild
test plan)."""

import numpy as np

from geomesa_tpu.jaxconf import scoped_x64
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter.compile import compile_filter
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.ops.scan import stage_columns

SFT = SimpleFeatureType.create(
    "t", "count:Int,score:Float,dtg:Date,*geom:Point:srid=4326"
)


T0 = 1_577_836_800_000  # 2020-01-01 in epoch-ms


def make_batch(rng, n):
    return FeatureBatch.from_columns(
        SFT,
        {
            "count": rng.integers(0, 100, n),
            "score": rng.uniform(0, 1, n),
            "dtg": rng.integers(T0, T0 + 90 * 86400_000, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
    )


FILTERS = [
    "BBOX(geom, -10, 35, 30, 60)",
    "BBOX(geom, -10, 35, 30, 60) AND "
    "dtg DURING 2020-01-10T00:00:00Z/2020-02-15T00:00:00Z",
    "count > 50 AND score <= 0.25",
    "count BETWEEN 10 AND 20 OR NOT BBOX(geom, 0, 0, 90, 45)",
    "count IN (1, 2, 3, 42)",
    "dtg > '2020-02-01T00:00:00Z'",
    "INTERSECTS(geom, POLYGON((-10 0, 40 10, 20 50, -30 40, -10 0)))",
    "DWITHIN(geom, POINT(5 45), 10, kilometers)",
]


def test_mosaic_mod_recursion_repro():
    """Minimal repro of the Mosaic bug that kept the point-in-polygon
    Pallas kernel off the TPU through round 3: with x64 enabled,
    lowering `int32_array % 2` recurses forever in
    jax/_src/pallas/mosaic/lowering.py::_convert_element_type_lowering_rule
    (the weak Python-int literal round-trips through i64 and
    _convert_helper re-enters itself until RecursionError). `x & 1` is
    the working spelling — ops/pallas_scan.py's crossing-parity test uses
    it. This repro only exercises the real Mosaic lowering, so it runs
    on TPU only (interpret mode never hits Mosaic).

    Verified against the installed stack (jax 0.9 line): `% 2` raises
    RecursionError, `& 1` compiles and runs.
    """
    import jax

    if jax.devices()[0].platform != "tpu":
        pytest.skip("Mosaic lowering repro requires a real TPU backend")
    import sys

    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    old = sys.getrecursionlimit()
    sys.setrecursionlimit(20000)
    try:
        with scoped_x64():

            def kern_mod(x_ref, o_ref):
                o_ref[...] = x_ref[...].astype(jnp.int32) % 2

            def kern_and(x_ref, o_ref):
                o_ref[...] = x_ref[...].astype(jnp.int32) & 1

            x = jnp.ones((256, 128), jnp.float32)
            shape = jax.ShapeDtypeStruct((256, 128), jnp.int32)
            with pytest.raises(RecursionError):
                jax.block_until_ready(
                    pl.pallas_call(kern_mod, out_shape=shape)(x)
                )
            out = jax.block_until_ready(
                pl.pallas_call(kern_and, out_shape=shape)(x)
            )
            assert int(out.sum()) == 256 * 128
    finally:
        sys.setrecursionlimit(old)


def test_pip_kernel_parity_under_x64():
    """The polygon kernel must produce oracle-exact results with x64
    enabled (the bench enables x64 for data generation; round-3 shipped
    with the Pallas engine disabled under exactly this flag)."""
    import jax

    rng = np.random.default_rng(7)
    batch = make_batch(rng, 4096)
    ecql = FILTERS[6]
    compiled = compile_filter(parse_ecql(ecql), SFT)
    with scoped_x64():
        scan = compiled.pallas_scan()
        assert scan is not None
        cols = stage_columns(batch, list(compiled.device_cols))
        got = np.asarray(scan[1](cols))[: len(batch)]
    expect = compiled.host_mask(batch)
    np.testing.assert_array_equal(got, expect)


class TestPallasScanParity:
    @pytest.mark.parametrize("ecql", FILTERS)
    def test_count_and_mask_match_oracle(self, rng, ecql):
        batch = make_batch(rng, 777)  # deliberately not a tile multiple
        cf = compile_filter(parse_ecql(ecql), SFT)
        assert cf.fully_on_device, ecql
        scan = cf.pallas_scan(block_rows=32)  # force multi-tile grids
        assert scan is not None, f"pallas rejected {ecql}"
        count_fn, mask_fn = scan
        cols = stage_columns(batch, cf.device_cols)
        expect = cf.host_mask(batch)
        got_mask = np.asarray(mask_fn(cols))
        assert got_mask.shape == expect.shape
        np.testing.assert_array_equal(got_mask, expect)
        assert int(count_fn(cols)) == int(expect.sum())

    def test_single_partial_tile(self, rng):
        batch = make_batch(rng, 17)
        cf = compile_filter(parse_ecql("count >= 0"), SFT)
        count_fn, mask_fn = cf.pallas_scan()
        cols = stage_columns(batch, cf.device_cols)
        assert int(count_fn(cols)) == 17
        assert np.asarray(mask_fn(cols)).sum() == 17

    def test_i64_word_boundary(self):
        """Values straddling the 2^32 word boundary and negatives
        (pre-1970) must compare exactly under the hi/lo split."""
        vals = np.array(
            [
                -(1 << 40),
                -1,
                0,
                1,
                (1 << 32) - 1,
                1 << 32,
                (1 << 32) + 1,
                (1 << 45) + 7,
            ],
            dtype=np.int64,
        )
        n = len(vals)
        batch = FeatureBatch.from_columns(
            SFT,
            {
                "count": np.zeros(n, np.int32),
                "score": np.zeros(n),
                "dtg": vals,
                "geom": np.zeros((n, 2)),
            },
        )
        for op in ("<", "<=", "=", "<>", ">=", ">"):
            for pivot in (-1, 0, (1 << 32) - 1, 1 << 32):
                from geomesa_tpu.filter import ast

                cf = compile_filter(ast.Compare(op, "dtg", pivot), SFT)
                count_fn, mask_fn = cf.pallas_scan()
                cols = stage_columns(batch, cf.device_cols)
                expect = cf.host_mask(batch)
                np.testing.assert_array_equal(
                    np.asarray(mask_fn(cols)), expect, err_msg=f"{op} {pivot}"
                )

    def test_float_bounds_on_i64_column(self, rng):
        batch = make_batch(rng, 64)
        from geomesa_tpu.filter import ast

        lo = int(np.asarray(batch.column("dtg")).min())
        for op in ("<", "<=", ">", ">="):
            cf = compile_filter(ast.Compare(op, "dtg", lo + 0.5), SFT)
            count_fn, _ = cf.pallas_scan()
            cols = stage_columns(batch, cf.device_cols)
            d = np.asarray(batch.column("dtg"))
            expect = {
                "<": d < lo + 0.5,
                "<=": d <= lo + 0.5,
                ">": d > lo + 0.5,
                ">=": d >= lo + 0.5,
            }[op]
            assert int(count_fn(cols)) == int(expect.sum()), op

    def test_unsupported_falls_back(self):
        sft = SimpleFeatureType.create("u", "name:String,*geom:Point")
        cf = compile_filter(parse_ecql("name = 'x'"), sft)
        assert cf.pallas_scan() is None  # string col -> host residual

    def test_jnp_device_fn_i64_split_agrees(self, rng):
        """The non-pallas device path reads the same hi/lo planes."""
        import jax

        batch = make_batch(rng, 256)
        cf = compile_filter(
            parse_ecql("dtg DURING 2020-01-10T00:00:00Z/2020-02-15T00:00:00Z"),
            SFT,
        )
        assert cf.device_cols == ["dtg__hi", "dtg__lo"]
        cols = stage_columns(batch, cf.device_cols)
        got = np.asarray(jax.jit(cf.device_fn)(cols))
        np.testing.assert_array_equal(got, cf.host_mask(batch))

    def test_float64_boundary_precision_preserved(self):
        """On the CPU (x64) parity path the kernel must compare staged
        float64 coordinate planes at full precision -- an implicit f32
        truncation would flip sub-f32-ulp boundary comparisons against
        the host oracle."""
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.filter import ast
        from geomesa_tpu.filter.compile import evaluate_host

        sft = SimpleFeatureType.create("t", "*geom:Point")
        # point above the box edge by 5e-10 in f64, identical in f32
        xmax = float(np.float32(10.1)) - 1e-9
        x = np.full(4, np.float32(10.1) - 5e-10, dtype=np.float64)
        batch = FeatureBatch.from_columns(
            sft, {"geom": np.stack([x, np.zeros(4)], axis=1)}, np.arange(4)
        )
        f = ast.BBox("geom", -20.0, -1.0, xmax, 1.0)
        cf = compile_filter(f, sft)
        cols = stage_columns(batch, cf.device_cols)
        assert cols["geom__x"].dtype == np.float64
        host = int(evaluate_host(f, batch).sum())
        count_fn, mask_fn = cf.pallas_scan()
        assert host == int(count_fn(cols)) == 0
        assert int(np.asarray(mask_fn(cols)).sum()) == 0
