"""Visibility expression parsing + auth filtering through the query path
(ref geomesa-security VisibilityEvaluator semantics)."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.query.plan import Query
from geomesa_tpu.security import (
    AuthorizationsProvider,
    VisibilityEvaluator,
    VisibilityParseError,
    parse_visibility,
)
from geomesa_tpu.store import MemoryDataStore


class TestParsing:
    @pytest.mark.parametrize(
        "expr,auths,expect",
        [
            ("A", {"A"}, True),
            ("A", {"B"}, False),
            ("A&B", {"A", "B"}, True),
            ("A&B", {"A"}, False),
            ("A|B", {"B"}, True),
            ("A|B", set(), False),
            ("A&(B|C)", {"A", "C"}, True),
            ("A&(B|C)", {"A"}, False),
            ("A&(B|C)", {"B", "C"}, False),
            ("(A|B)&(C|D)", {"B", "D"}, True),
            ('"weird token"&A', {"weird token", "A"}, True),
            ("", {"A"}, True),  # public
            ("  ", set(), True),
        ],
    )
    def test_evaluate(self, expr, auths, expect):
        ev = VisibilityEvaluator(auths)
        assert ev.can_see(expr) is expect

    @pytest.mark.parametrize(
        "bad", ["A&B|C", "A&&B", "(A", "A)", '"unterminated', "&A", "A!B"]
    )
    def test_parse_errors(self, bad):
        with pytest.raises(VisibilityParseError):
            parse_visibility(bad)

    def test_none_is_public(self):
        assert VisibilityEvaluator(set()).can_see(None)

    def test_provider(self):
        p = AuthorizationsProvider(["A", "B"])
        assert p.get_authorizations() == ("A", "B")


class TestQueryIntegration:
    def make_store(self):
        sft = SimpleFeatureType.create("s", "count:Int,*geom:Point:srid=4326")
        n = 8
        batch = FeatureBatch.from_columns(
            sft,
            {
                "count": np.arange(n),
                "geom": np.zeros((n, 2)),
            },
        ).with_visibility(
            ["", "A", "B", "A&B", "A|B", "secret&(A|B)", "", "C"]
        )
        ds = MemoryDataStore()
        ds.create_schema(sft)
        ds.write("s", batch)
        return ds

    def query_counts(self, ds, auths):
        res = ds.query("s", Query("INCLUDE", hints={"auths": auths}))
        return sorted(res.batch.column("count").tolist())

    def test_no_auths_sees_only_public(self):
        ds = self.make_store()
        assert self.query_counts(ds, ()) == [0, 6]

    def test_single_auth(self):
        ds = self.make_store()
        assert self.query_counts(ds, ("A",)) == [0, 1, 4, 6]

    def test_two_auths(self):
        ds = self.make_store()
        assert self.query_counts(ds, ("A", "B")) == [0, 1, 2, 3, 4, 6]

    def test_secret_requires_both(self):
        ds = self.make_store()
        assert 5 in self.query_counts(ds, ("secret", "B"))
        assert 5 not in self.query_counts(ds, ("secret",))

    def test_unlabeled_store_unaffected(self):
        sft = SimpleFeatureType.create("u", "count:Int,*geom:Point:srid=4326")
        ds = MemoryDataStore()
        ds.create_schema(sft)
        ds.write(
            "u", {"count": np.arange(4), "geom": np.zeros((4, 2))}
        )
        res = ds.query("u", Query("INCLUDE", hints={"auths": ("A",)}))
        assert len(res.batch) == 4


class TestVisibilityPersistence:
    def test_fs_store_round_trips_labels(self, tmp_path):
        from geomesa_tpu.store.fs import FileSystemDataStore

        sft = SimpleFeatureType.create("s", "count:Int,*geom:Point:srid=4326")
        root = str(tmp_path / "cat")
        ds = FileSystemDataStore(root)
        ds.create_schema(sft)
        batch = FeatureBatch.from_columns(
            sft, {"count": np.arange(3), "geom": np.zeros((3, 2))}
        ).with_visibility(["secret", "secret", ""])
        ds.write("s", batch)
        ds.flush("s")
        # reopen from disk: labels must survive the parquet round trip
        ds2 = FileSystemDataStore(root)
        res = ds2.query("s", Query("INCLUDE", hints={"auths": ()}))
        assert sorted(res.batch.column("count").tolist()) == [2]
        res = ds2.query("s", Query("INCLUDE", hints={"auths": ("secret",)}))
        assert len(res.batch) == 3

    def test_mixed_labeled_unlabeled_batches(self):
        sft = SimpleFeatureType.create("m", "count:Int,*geom:Point:srid=4326")
        ds = MemoryDataStore()
        ds.create_schema(sft)
        ds.write("m", {"count": [0, 1], "geom": np.zeros((2, 2))})
        labeled = FeatureBatch.from_columns(
            sft, {"count": [2, 3], "geom": np.zeros((2, 2))},
            fids=np.array([10, 11]),
        ).with_visibility(["secret", ""])
        ds.write("m", labeled)
        counts = sorted(
            ds.query("m", Query("INCLUDE", hints={"auths": ()}))
            .batch.column("count").tolist()
        )
        assert counts == [0, 1, 3]  # unlabeled rows public, secret hidden
        # reversed write order (labeled first) must not crash either
        ds2 = MemoryDataStore()
        ds2.create_schema(SimpleFeatureType.create("m2", "count:Int,*geom:Point:srid=4326"))
        ds2.write("m2", labeled_first := FeatureBatch.from_columns(
            ds2.get_schema("m2"),
            {"count": [9], "geom": np.zeros((1, 2))},
        ).with_visibility(["secret"]))
        ds2.write("m2", {"count": [7], "geom": np.zeros((1, 2))})
        counts2 = sorted(
            ds2.query("m2", Query("INCLUDE", hints={"auths": ()}))
            .batch.column("count").tolist()
        )
        assert counts2 == [7]

    def test_auths_none_fails_closed(self):
        sft = SimpleFeatureType.create("n", "count:Int,*geom:Point:srid=4326")
        ds = MemoryDataStore()
        ds.create_schema(sft)
        ds.write(
            "n",
            FeatureBatch.from_columns(
                sft, {"count": [1], "geom": np.zeros((1, 2))}
            ).with_visibility(["secret"]),
        )
        res = ds.query("n", Query("INCLUDE", hints={"auths": None}))
        assert len(res.batch) == 0

    def test_arrow_stream_carries_labels(self):
        import io as _io

        from geomesa_tpu.arrow_io import read_feature_stream, write_feature_stream

        sft = SimpleFeatureType.create("a", "count:Int,*geom:Point:srid=4326")
        batch = FeatureBatch.from_columns(
            sft, {"count": [1, 2], "geom": np.zeros((2, 2))}
        ).with_visibility(["A", ""])
        buf = _io.BytesIO()
        write_feature_stream(buf, [batch])
        buf.seek(0)
        (back,) = read_feature_stream(buf)
        assert list(back.visibilities) == ["A", ""]
