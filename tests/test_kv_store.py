"""Sorted-KV DataStore: write/scan/filter parity with the in-memory
columnar store (ref test role: AccumuloDataStoreQueryTest against
MiniAccumuloCluster, here against MemoryKV and SqliteKV)."""

import os

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.query.plan import Query
from geomesa_tpu.store.kv import (
    KVDataStore,
    MemoryKV,
    SqliteKV,
    _enc_attr,
    _enc_f64,
    _enc_i32,
    _enc_i64,
    _incr,
)
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"


def _columns(n=500, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(1600000000000, 1600000000000 + 28 * 86400000, n)
    return {
        "name": np.array([f"n{i % 17}" for i in range(n)], dtype=object),
        "age": rng.integers(0, 100, n),
        "dtg": t,
        "geom": np.stack([x, y], axis=1),
    }


QUERIES = [
    "bbox(geom, -50, -20, 40, 60) and dtg during 2020-09-14T00:00:00Z/2020-09-21T00:00:00Z",
    "bbox(geom, 0, 0, 10, 10)",
    "age > 50 and bbox(geom, -180, -90, 180, 90)",
    "name = 'n3'",
    "dtg after 2020-09-20T00:00:00Z",
    "INCLUDE",
]


@pytest.fixture(params=["memory", "sqlite"])
def kv_store(request, tmp_path):
    if request.param == "memory":
        ds = KVDataStore(MemoryKV(), catalog="cat")
    else:
        ds = KVDataStore(
            SqliteKV(os.path.join(tmp_path, "cat.db")), catalog="cat"
        )
    yield ds
    ds.close()


class TestKeyCodec:
    def test_i64_order(self):
        vals = [-(2**62), -5, -1, 0, 1, 7, 2**62]
        encs = [_enc_i64(v) for v in vals]
        assert encs == sorted(encs)

    def test_i32_order(self):
        vals = [-100, -1, 0, 1, 100]
        encs = [_enc_i32(v) for v in vals]
        assert encs == sorted(encs)

    def test_f64_order(self):
        vals = [-1e300, -2.5, -0.0, 0.0, 1e-9, 3.7, 1e300]
        encs = [_enc_f64(v) for v in vals]
        assert encs == sorted(encs)

    def test_str_order(self):
        vals = ["", "a", "ab", "b"]
        encs = [_enc_attr(v) for v in vals]
        assert encs == sorted(encs)

    def test_incr(self):
        assert _incr(b"ab") == b"ac"
        assert _incr(b"a\xff") == b"b"
        assert _incr(b"\xff\xff") is None


class TestBackends:
    def test_memory_scan_order_and_bounds(self):
        kv = MemoryKV()
        kv.create_table("t")
        kv.write("t", [(b"c", b"3"), (b"a", b"1"), (b"b", b"2")])
        assert list(kv.scan("t", b"a", b"c")) == [(b"a", b"1"), (b"b", b"2")]
        assert list(kv.scan("t", b"", None)) == [
            (b"a", b"1"), (b"b", b"2"), (b"c", b"3"),
        ]
        kv.delete("t", [b"b"])
        assert [k for k, _ in kv.scan("t", b"", None)] == [b"a", b"c"]

    def test_sqlite_persistence(self, tmp_path):
        path = os.path.join(tmp_path, "kv.db")
        kv = SqliteKV(path)
        kv.create_table("t")
        kv.write("t", [(b"k1", b"v1"), (b"k0", b"v0")])
        kv.close()
        kv2 = SqliteKV(path)
        assert list(kv2.scan("t", b"", None)) == [(b"k0", b"v0"), (b"k1", b"v1")]
        kv2.close()


class TestKVStoreParity:
    def test_query_parity_with_memory_store(self, kv_store):
        cols = _columns()
        kv_store.create_schema("gdelt", SPEC)
        kv_store.write("gdelt", cols)

        oracle = MemoryDataStore()
        oracle.create_schema("gdelt", SPEC)
        oracle.write("gdelt", cols)

        for q in QUERIES:
            got = sorted(kv_store.query("gdelt", q).batch.fids)
            want = sorted(oracle.query("gdelt", q).batch.fids)
            assert got == want, f"mismatch for {q!r}"

    def test_projection_sort_limit(self, kv_store):
        kv_store.create_schema("gdelt", SPEC)
        kv_store.write("gdelt", _columns())
        res = kv_store.query(
            "gdelt",
            Query(
                filter=parse_ecql("bbox(geom, -90, -45, 90, 45)"),
                properties=["age", "geom"],
                sort_by="age",
                max_features=10,
            ),
        )
        assert len(res) == 10
        assert set(res.batch.columns) == {"age", "geom"}
        ages = res.batch.column("age")
        assert list(ages) == sorted(ages)

    def test_prefilter_prunes_scanned_rows(self, kv_store):
        kv_store.create_schema("gdelt", SPEC)
        kv_store.write("gdelt", _columns(n=2000))
        res = kv_store.query("gdelt", QUERIES[0])
        # z-range pruning must beat a full scan
        assert res.scanned < 2000
        assert res.total == 2000


class TestKVStoreLifecycle:
    def test_reopen_from_disk(self, tmp_path):
        path = os.path.join(tmp_path, "cat.db")
        ds = KVDataStore(SqliteKV(path), catalog="cat")
        ds.create_schema("pts", SPEC)
        ds.write("pts", _columns(n=100))
        before = sorted(ds.query("pts", QUERIES[1]).batch.fids)
        ds.close()

        ds2 = KVDataStore(SqliteKV(path), catalog="cat")
        assert ds2.type_names == ["pts"]
        assert ds2.get_schema("pts").spec.startswith("name:String")
        assert sorted(ds2.query("pts", QUERIES[1]).batch.fids) == before
        ds2.close()

    def test_delete_and_get_by_ids(self, kv_store):
        kv_store.create_schema("pts", SPEC)
        kv_store.write("pts", _columns(n=50))
        got = kv_store.get_by_ids("pts", [3, 7])
        assert sorted(got.fids) == [3, 7]
        assert kv_store.delete("pts", [3, 7]) == 2
        assert len(kv_store.get_by_ids("pts", [3, 7])) == 0
        assert len(kv_store.query("pts", "INCLUDE")) == 48

    def test_age_off(self, kv_store):
        kv_store.create_schema("pts", SPEC)
        cols = _columns(n=100)
        kv_store.write("pts", cols)
        cutoff = int(np.median(cols["dtg"]))
        removed = kv_store.age_off("pts", cutoff)
        assert removed == int((cols["dtg"] < cutoff).sum())
        left = kv_store.query("pts", "INCLUDE")
        assert (left.batch.column("dtg") >= cutoff).all()

    def test_remove_schema_drops_tables(self, kv_store):
        kv_store.create_schema("pts", SPEC)
        kv_store.write("pts", _columns(n=10))
        kv_store.remove_schema("pts")
        assert kv_store.type_names == []
        assert all("pts" not in t for t in kv_store.backend.list_tables())

    def test_visibility_rows_hidden_without_auths(self, kv_store):
        kv_store.create_schema("pts", SPEC)
        b = FeatureBatch.from_columns(
            kv_store.get_schema("pts"), _columns(n=4)
        ).with_visibility(["admin", "", "admin", ""])
        kv_store.write("pts", b)
        assert len(kv_store.query("pts", "INCLUDE")) == 2
        res = kv_store.query(
            "pts", Query(filter="INCLUDE", hints={"auths": ("admin",)})
        )
        assert len(res) == 4

    def test_delete_leaves_no_stale_index_rows(self, kv_store):
        # regression: TWKB-rounded geometry payloads used to shift z2 cells
        # on re-keying, stranding rows in the secondary index tables
        kv_store.create_schema("pts", SPEC)
        cols = _columns(n=300, seed=42)
        kv_store.write("pts", cols)
        assert kv_store.delete("pts", list(range(300))) == 300
        for table in kv_store.backend.list_tables():
            if table.startswith("cat_pts_"):
                rows = list(kv_store.backend.scan(table, b"", None))
                assert rows == [], f"stale rows in {table}"
        assert len(kv_store.query("pts", "bbox(geom,-180,-90,180,90)")) == 0

    def test_string_fids_survive_reopen(self, tmp_path):
        # regression: shard bytes must come from a process-stable hash
        path = os.path.join(tmp_path, "cat.db")
        ds = KVDataStore(SqliteKV(path), catalog="cat")
        ds.create_schema("pts", SPEC)
        fids = np.array([f"feat-{i}" for i in range(20)], dtype=object)
        ds.write("pts", _columns(n=20), fids=fids)
        ds.close()
        import subprocess
        import sys

        # verify from a *different* process (different hash salt)
        code = (
            "from geomesa_tpu.store.kv import KVDataStore, SqliteKV\n"
            f"ds = KVDataStore(SqliteKV({path!r}), catalog='cat')\n"
            "got = ds.get_by_ids('pts', ['feat-3', 'feat-7'])\n"
            "assert sorted(got.fids) == ['feat-3', 'feat-7'], got.fids\n"
            "assert ds.delete('pts', ['feat-3']) == 1\n"
        )
        subprocess.run(
            [sys.executable, "-c", code], check=True, cwd="/root/repo"
        )

    def test_stats_maintained(self, kv_store):
        kv_store.create_schema("pts", SPEC)
        kv_store.write("pts", _columns(n=30))
        stats = kv_store.stats("pts")
        js = stats.to_json()
        assert any(s.get("count") == 30 for s in js if isinstance(s, dict))

    def test_explain_mentions_ranges(self, kv_store):
        kv_store.create_schema("pts", SPEC)
        kv_store.write("pts", _columns(n=30))
        text = kv_store.explain("pts", QUERIES[0])
        assert "z3" in text and "Ranges" in text
