"""Dim-plane resident key scans (VERDICT round-3 item 1): the
de-interleaved z3 layout (nx, ny, packed bt) must serve DeviceIndex's
loose path with exact parity against the interleaved masked-compare
engine and the host oracle, across binned windows, streaming appends
(including a bin_base rebase), fused aggregations and per-auth serving.

Ref role: Z3Iterator, the reference's hottest scan (SURVEY section 3.1
[UNVERIFIED - empty reference mount]) — the loose-bbox key-only scan must
run the repo's fastest kernel, not a bench-local copy of it.
"""

from __future__ import annotations

import numpy as np
import pytest

from geomesa_tpu.device_cache import (
    Z_BIN,
    Z_BT,
    Z_HI,
    Z_LO,
    Z_NX,
    Z_NY,
    DeviceIndex,
    StreamingDeviceIndex,
)
from geomesa_tpu.store.memory import MemoryDataStore

DAY_MS = 86_400_000
T0 = 1_577_836_800_000  # 2020-01-01


def _store(n=4000, t_lo=T0, t_hi=T0 + 60 * DAY_MS, seed=7, name="gdelt"):
    rng = np.random.default_rng(seed)
    ds = MemoryDataStore()
    ds.create_schema(name, "val:Int,dtg:Date,*geom:Point:srid=4326")
    ds.write(name, {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(t_lo, t_hi, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    })
    return ds


ECQL = (
    "BBOX(geom, -10, 35, 30, 60) AND "
    "dtg DURING 2020-01-10T00:00:00Z/2020-01-25T00:00:00Z"
)
BBOX_ONLY = "BBOX(geom, -10, 35, 30, 60)"


def test_dim_mode_on_by_default_for_z3():
    di = DeviceIndex(_store(), "gdelt", z_planes=True)
    assert di._z_kind == "z3" and di._dim_mode
    assert Z_NX in di._cols and Z_NY in di._cols and Z_BT in di._cols
    # the interleaved planes are NOT staged twice: same 12B/row as before
    assert Z_HI not in di._cols and Z_LO not in di._cols
    assert Z_BIN not in di._cols


def test_dim_staging_matches_host_oracle():
    """Device-encoded nx/ny/bt planes == the host numpy packing."""
    from geomesa_tpu.curves.binnedtime import to_binned_time
    from geomesa_tpu.index.keyplanes import schema_kind
    from geomesa_tpu.ops import zscan

    ds = _store()
    di = DeviceIndex(ds, "gdelt", z_planes=True)
    assert di._dim_mode and not di._z_encode_failed
    assert di._dim_encode_jit is not None  # device path actually ran
    batch = ds.query("gdelt").batch
    _, sfc = schema_kind(di.sft)
    x, y = batch.point_coords("geom")
    bins, off = to_binned_time(batch.column("dtg"), sfc.period)
    nx = np.asarray(sfc.lon.normalize(x)).astype(np.uint32)
    ny = np.asarray(sfc.lat.normalize(y)).astype(np.uint32)
    nt = np.asarray(
        sfc.time.normalize(np.asarray(off, np.float64))
    ).astype(np.uint32)
    enx, eny, ebt = zscan.z3_dim_planes(
        sfc, nx, ny, nt, bins.astype(np.uint32), di._bt_base
    )
    np.testing.assert_array_equal(np.asarray(di._cols[Z_NX]), enx)
    np.testing.assert_array_equal(np.asarray(di._cols[Z_NY]), eny)
    np.testing.assert_array_equal(np.asarray(di._cols[Z_BT]), ebt)


@pytest.mark.parametrize("ecql", [ECQL, BBOX_ONLY])
def test_dim_loose_parity_vs_masked_compare(ecql):
    """The dim-plane loose answer == the interleaved masked-compare
    answer, bit for bit (two independent engines over two layouts)."""
    ds = _store()
    dim = DeviceIndex(ds, "gdelt", z_planes=True)
    cmp_ = DeviceIndex(ds, "gdelt", z_planes=True, dim_planes=False)
    assert dim._dim_mode and not cmp_._dim_mode
    np.testing.assert_array_equal(
        dim.mask(ecql, loose=True), cmp_.mask(ecql, loose=True)
    )
    assert dim.count(ecql, loose=True) == cmp_.count(ecql, loose=True)


def test_dim_loose_is_superset_of_exact():
    di = DeviceIndex(_store(), "gdelt", z_planes=True)
    loose = di.mask(ECQL, loose=True)
    exact = di.mask(ECQL, loose=False)
    assert not np.any(exact & ~loose)  # superset contract
    assert loose.sum() < len(loose)  # pruning actually happens


def test_dim_loose_count_uses_pallas_kernel(monkeypatch):
    """count(loose=True) must dispatch the Pallas dim kernel (not the
    XLA mask + host sum)."""
    di = DeviceIndex(_store(), "gdelt", z_planes=True)
    calls = []
    orig = di._dim_kernel

    def spy(r):
        fns = orig(r)
        calls.append(r)
        return fns

    monkeypatch.setattr(di, "_dim_kernel", spy)
    n = di.count(ECQL, loose=True)
    assert calls, "Pallas dim kernel was not used for the loose count"
    assert n == int(di.mask(ECQL, loose=True).sum())


def test_dim_kernel_single_compile_across_windows():
    """One R bucket == one compiled kernel: distinct windows reuse it."""
    di = DeviceIndex(_store(), "gdelt", z_planes=True)
    a = di.count(ECQL, loose=True)
    b = di.count(
        "BBOX(geom, 0, 0, 90, 80) AND "
        "dtg DURING 2020-02-01T00:00:00Z/2020-02-12T00:00:00Z",
        loose=True,
    )
    c = di.count(BBOX_ONLY, loose=True)
    assert a >= 0 and b >= 0 and c >= 0
    # every one-range window shares the R=1 bucket; no per-window entries
    assert set(di._dim_kernels) <= {1, 2, 4, 8}


def test_loose_scan_kernel_is_dim_and_matches_count():
    """The bench hook returns the dim kernel + resident planes and its
    count equals the serving count."""
    di = DeviceIndex(_store(), "gdelt", z_planes=True)
    got = di.loose_scan_kernel(ECQL)
    assert got is not None
    fn, args = got
    assert len(args) == 4  # (qarr, nx, ny, bt): the dim signature
    assert int(fn(*args)) == di.count(ECQL, loose=True)


def test_wide_bin_span_falls_back_to_masked_compare():
    """Data spanning >= 2^11 - 1 weekly bins cannot pack the bt word:
    staging must keep the interleaved layout and loose must still work."""
    from geomesa_tpu.ops.zscan import BT_BIN_SPAN

    wide = _store(
        n=1500, t_lo=T0 - (BT_BIN_SPAN + 10) * 7 * DAY_MS, t_hi=T0
    )
    di = DeviceIndex(wide, "gdelt", z_planes=True)
    assert not di._dim_mode
    assert Z_HI in di._cols and Z_NX not in di._cols
    loose = di.mask(BBOX_ONLY, loose=True)
    exact = di.mask(BBOX_ONLY, loose=False)
    assert not np.any(exact & ~loose)


def test_dim_planes_true_raises_on_wide_span():
    from geomesa_tpu.ops.zscan import BT_BIN_SPAN

    wide = _store(
        n=500, t_lo=T0 - (BT_BIN_SPAN + 10) * 7 * DAY_MS, t_hi=T0
    )
    with pytest.raises(ValueError, match="span"):
        DeviceIndex(wide, "gdelt", z_planes=True, dim_planes=True)


def test_dim_planes_true_raises_on_non_point():
    """Non-point schemas (xz keys) cannot pack dim planes."""
    from geomesa_tpu.geom.wkt import parse_wkt

    ds = MemoryDataStore()
    ds.create_schema("polys", "val:Int,*geom:Polygon:srid=4326")
    ds.write("polys", {
        "val": np.arange(2),
        "geom": np.array([
            parse_wkt("POLYGON((0 0, 1 0, 1 1, 0 0))"),
            parse_wkt("POLYGON((2 2, 3 2, 3 3, 2 2))"),
        ], dtype=object),
    })
    with pytest.raises(ValueError, match="z3/z2"):
        DeviceIndex(ds, "polys", z_planes=True, dim_planes=True)


class TestZ2Dim:
    """Date-less point schemas stage the 2-plane dim layout."""

    def _z2_store(self, n=3000, seed=4):
        rng = np.random.default_rng(seed)
        ds = MemoryDataStore()
        ds.create_schema("z2t", "val:Int,*geom:Point:srid=4326")
        ds.write("z2t", {
            "val": rng.integers(0, 100, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)],
                axis=1,
            ),
        })
        return ds

    def test_dim_mode_default_and_planes(self):
        di = DeviceIndex(self._z2_store(), "z2t", z_planes=True)
        assert di._z_kind == "z2" and di._dim_mode
        assert Z_NX in di._cols and Z_NY in di._cols
        assert Z_BT not in di._cols  # no time in the key
        assert Z_HI not in di._cols

    def test_loose_parity_vs_masked_compare(self):
        ds = self._z2_store()
        dim = DeviceIndex(ds, "z2t", z_planes=True)
        cmp_ = DeviceIndex(ds, "z2t", z_planes=True, dim_planes=False)
        np.testing.assert_array_equal(
            dim.mask(BBOX_ONLY, loose=True),
            cmp_.mask(BBOX_ONLY, loose=True),
        )
        assert dim.count(BBOX_ONLY, loose=True) == cmp_.count(
            BBOX_ONLY, loose=True
        )
        # superset of exact
        loose = dim.mask(BBOX_ONLY, loose=True)
        exact = dim.mask(BBOX_ONLY, loose=False)
        assert not np.any(exact & ~loose)

    def test_kernel_and_fused_paths(self):
        ds = self._z2_store()
        di = DeviceIndex(ds, "z2t", z_planes=True)
        got = di.loose_scan_kernel(BBOX_ONLY)
        assert got is not None
        fn, args = got
        assert len(args) == 3  # (qarr, nx, ny): the 2-plane signature
        assert int(fn(*args)) == di.count(BBOX_ONLY, loose=True)
        seq = di.stats(BBOX_ONLY, "Count()", loose=True)
        assert seq.stats[0].count == di.count(BBOX_ONLY, loose=True)

    def test_streaming_append(self):
        ds = self._z2_store(n=1000)
        di = StreamingDeviceIndex(ds, "z2t", z_planes=True, capacity=8192)
        extra = self._z2_store(n=500, seed=9)
        di.append(extra.query("z2t").batch)
        assert di.delta_appends == 1 and di._dim_mode
        loose = di.mask(BBOX_ONLY, loose=True)
        exact = di.mask(BBOX_ONLY, loose=False)
        assert not np.any(exact & ~loose)
        assert exact.sum() > 0


def test_fused_stats_on_dim_planes():
    """Count + MinMax through the fused loose dispatch on dim planes must
    match the masked-compare index's results."""
    ds = _store()
    dim = DeviceIndex(ds, "gdelt", z_planes=True)
    cmp_ = DeviceIndex(ds, "gdelt", z_planes=True, dim_planes=False)
    a = dim.stats(ECQL, 'Count();MinMax("val")', loose=True)
    b = cmp_.stats(ECQL, 'Count();MinMax("val")', loose=True)
    assert a.stats[0].count == b.stats[0].count
    assert (a.stats[1].min, a.stats[1].max) == (b.stats[1].min, b.stats[1].max)


def test_fused_density_on_dim_planes():
    from geomesa_tpu.geom import Envelope

    ds = _store(n=6000)
    dim = DeviceIndex(ds, "gdelt", z_planes=True)
    cmp_ = DeviceIndex(ds, "gdelt", z_planes=True, dim_planes=False)
    env = Envelope(-10, 35, 30, 60)
    ga = dim.density(ECQL, env, 32, 16, loose=True)
    gb = cmp_.density(ECQL, env, 32, 16, loose=True)
    assert ga is not None and gb is not None
    np.testing.assert_array_equal(ga, gb)


def test_dim_auths_fail_closed_and_serve_per_request():
    rng = np.random.default_rng(5)
    n = 3000
    from geomesa_tpu.features.batch import FeatureBatch

    ds = MemoryDataStore()
    ds.create_schema("sec", "val:Int,dtg:Date,*geom:Point:srid=4326")
    vis = np.array(
        [None, "admin", "admin&ops"], dtype=object
    )[rng.integers(0, 3, n)]
    batch = FeatureBatch.from_columns(
        ds.get_schema("sec"),
        {
            "val": rng.integers(0, 9, n),
            "dtg": rng.integers(T0, T0 + 30 * DAY_MS, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    ).with_visibility(vis)
    ds.write("sec", batch)
    di = DeviceIndex(ds, "sec", z_planes=True)
    assert di._dim_mode
    none_ct = di.count(BBOX_ONLY, loose=True)
    admin_ct = di.count(BBOX_ONLY, loose=True, auths=("admin",))
    all_ct = di.count(BBOX_ONLY, loose=True, auths=("admin", "ops"))
    assert none_ct < admin_ct < all_ct
    m = di.mask(BBOX_ONLY, loose=True, auths=("admin",))
    assert int(m.sum()) == admin_ct


def test_z3_interval_hint_reaches_resident_planes():
    """``geomesa.z3.interval`` must drive the SAME period in the resident
    key planes as in the durable key space (they diverged before round
    4: schema_kind hardcoded WEEK)."""
    from geomesa_tpu.curves.binnedtime import TimePeriod
    from geomesa_tpu.index.keyplanes import schema_kind
    from geomesa_tpu.index.keyspaces import keyspace_for

    rng = np.random.default_rng(3)
    n = 800
    ds = MemoryDataStore()
    ds.create_schema(
        "d", "dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval=day"
    )
    ds.write("d", {
        "dtg": rng.integers(T0, T0 + 7 * DAY_MS, n),
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
        ),
    })
    sft = ds.get_schema("d")
    _, sfc = schema_kind(sft)
    assert sfc.period == TimePeriod.DAY
    assert keyspace_for(sft, "z3").period == TimePeriod.DAY
    di = DeviceIndex(ds, "d", z_planes=True)
    assert di._dim_mode  # day precision is still 21 bits
    ecql = (
        "BBOX(geom, -5, -5, 5, 5) AND "
        "dtg DURING 2020-01-02T00:00:00Z/2020-01-04T00:00:00Z"
    )
    loose = di.mask(ecql, loose=True)
    exact = di.mask(ecql, loose=False)
    assert not np.any(exact & ~loose) and exact.sum() > 0
    # masked-compare engine agrees under the same period
    cmp_ = DeviceIndex(ds, "d", z_planes=True, dim_planes=False)
    np.testing.assert_array_equal(loose, cmp_.mask(ecql, loose=True))


def test_fuzz_dim_vs_masked_compare_random_windows():
    """Differential fuzz: 40 random bbox(+during) windows over z3 AND z2
    dim-mode indexes must match the masked-compare engine bit for bit
    (covers qarr construction, bin-range clamping, range merging and the
    R-bucket padding across window shapes)."""
    rng = np.random.default_rng(99)
    ds3 = _store(n=5000, seed=31)
    dim3 = DeviceIndex(ds3, "gdelt", z_planes=True)
    cmp3 = DeviceIndex(ds3, "gdelt", z_planes=True, dim_planes=False)

    ds2 = MemoryDataStore()
    n = 4000
    ds2.create_schema("z2f", "val:Int,*geom:Point:srid=4326")
    ds2.write("z2f", {
        "val": rng.integers(0, 9, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    })
    dim2 = DeviceIndex(ds2, "z2f", z_planes=True)
    cmp2 = DeviceIndex(ds2, "z2f", z_planes=True, dim_planes=False)

    from geomesa_tpu.filter import ast

    for i in range(40):
        x0, y0 = rng.uniform(-185, 175), rng.uniform(-95, 85)
        w = 10 ** rng.uniform(-2, 2.3)
        h = 10 ** rng.uniform(-2, 2)
        bbox = ast.BBox("geom", x0, y0, min(x0 + w, 180), min(y0 + h, 90))
        # z3: random windows incl. degenerate/outside/bin-straddling
        t_lo = T0 + int(rng.uniform(-30, 90) * DAY_MS)
        t_hi = t_lo + int(10 ** rng.uniform(3, 7.2))
        f3 = ast.And((bbox, ast.During("dtg", t_lo, t_hi)))
        np.testing.assert_array_equal(
            dim3.mask(f3, loose=True), cmp3.mask(f3, loose=True),
            err_msg=f"z3 window {i}",
        )
        np.testing.assert_array_equal(
            dim2.mask(bbox, loose=True), cmp2.mask(bbox, loose=True),
            err_msg=f"z2 window {i}",
        )


class TestStreamingDim:
    def test_append_keeps_dim_mode_and_parity(self):
        ds = _store(n=2000)
        di = StreamingDeviceIndex(ds, "gdelt", z_planes=True, capacity=8192)
        assert di._dim_mode
        extra = _store(n=1000, seed=11, t_lo=T0 + 30 * DAY_MS,
                       t_hi=T0 + 90 * DAY_MS)
        di.append(ds.query("gdelt").batch.__class__.concat(
            [extra.query("gdelt").batch]
        ))
        assert di.delta_appends == 1 and di._dim_mode
        # parity against a cold full-restage index over the same rows
        merged = MemoryDataStore()
        merged.create_schema("gdelt", "val:Int,dtg:Date,*geom:Point:srid=4326")
        b = di._live_rows()
        merged.write("gdelt", {
            "val": b.column("val"), "dtg": b.column("dtg"),
            "geom": np.stack(b.point_coords("geom"), axis=1),
        })
        cold = DeviceIndex(merged, "gdelt", z_planes=True)
        assert di.count(ECQL, loose=True) == cold.count(ECQL, loose=True)

    def test_append_below_base_rebases(self):
        """A delta OLDER than every staged row forces a bt repack (the
        sentinel would wrongly hide it from loose supersets)."""
        ds = _store(n=1500, t_lo=T0 + 30 * DAY_MS, t_hi=T0 + 60 * DAY_MS)
        di = StreamingDeviceIndex(ds, "gdelt", z_planes=True)
        base_before = di._bt_base
        old = _store(n=800, seed=13, t_lo=T0, t_hi=T0 + 7 * DAY_MS)
        restages_before = di.restages
        di.append(old.query("gdelt").batch)
        assert di.restages == restages_before + 1  # rebase happened
        assert di._bt_base < base_before
        # loose still answers the OLD window (superset incl. the delta)
        m = di.mask(
            "dtg DURING 2020-01-01T00:00:00Z/2020-01-08T00:00:00Z",
            loose=True,
        )
        exact = di.mask(
            "dtg DURING 2020-01-01T00:00:00Z/2020-01-08T00:00:00Z",
            loose=False,
        )
        assert not np.any(exact & ~m)
        assert exact.sum() > 0

    def test_eviction_respected_by_dim_loose(self):
        ds = _store(n=1200)
        di = StreamingDeviceIndex(ds, "gdelt", z_planes=True)
        hits = np.nonzero(di.mask(BBOX_ONLY, loose=True))[0]
        assert len(hits) > 2
        victim_fids = di._host_rows().fids[hits[:2]]
        di.evict(victim_fids)
        m = di.mask(BBOX_ONLY, loose=True)
        assert not m[hits[0]] and not m[hits[1]]
        assert di.count(BBOX_ONLY, loose=True) == int(m.sum())
