"""XZ2/XZ3 extent-curve properties.

Key invariant (the XZ coverage property): for any set of boxes and any query
window, every box that intersects the query must have its code inside the
emitted ranges (no false negatives); boxes far from the query should mostly
be excluded.
"""

import numpy as np
import pytest

from geomesa_tpu.curves import XZ2SFC, XZ3SFC


def _covered(codes, ranges):
    arr = np.array([(r.lower, r.upper) for r in ranges], dtype=np.int64)
    idx = np.searchsorted(arr[:, 0], codes, side="right") - 1
    return (idx >= 0) & (codes <= arr[np.clip(idx, 0, len(arr) - 1), 1])


def _rand_boxes(rng, n, x0, y0, x1, y1, max_size):
    xmin = rng.uniform(x0, x1 - max_size, n)
    ymin = rng.uniform(y0, y1 - max_size, n)
    w = rng.uniform(0, max_size, n)
    h = rng.uniform(0, max_size, n)
    return xmin, ymin, xmin + w, ymin + h


class TestXZ2:
    def test_point_boxes_deterministic(self):
        sfc = XZ2SFC()
        c1 = sfc.index(np.array([2.0]), np.array([48.0]), np.array([2.0]), np.array([48.0]))
        c2 = sfc.index(np.array([2.0]), np.array([48.0]), np.array([2.0]), np.array([48.0]))
        assert c1[0] == c2[0] >= 0

    def test_codes_within_keyspace(self, rng):
        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 5000, -180, -90, 180, 90, 5.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        max_code = (4 ** (sfc.g + 1) - 1) // 3
        assert np.all(codes >= 0)
        assert np.all(codes <= max_code)

    def test_no_false_negatives(self, rng):
        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 5000, -20, 20, 30, 60, 2.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        q = (-5.0, 42.0, 8.0, 51.0)
        ranges = sfc.ranges(*q)
        hits = _covered(codes, ranges)
        intersecting = (
            (xmax >= q[0]) & (xmin <= q[2]) & (ymax >= q[1]) & (ymin <= q[3])
        )
        assert np.all(hits[intersecting]), "false negatives in XZ2 ranges"

    def test_prunes_far_boxes(self, rng):
        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 5000, 100, -80, 170, -40, 2.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        ranges = sfc.ranges(-5.0, 42.0, 8.0, 51.0)
        assert np.mean(_covered(codes, ranges)) < 0.05

    def test_large_geometries_low_level(self):
        # a hemisphere-sized box is stored at level 1 (every box fits some
        # level-1 enlarged cell, which spans the whole space), so its code is
        # one of the four level-1 quadrant codes.
        sfc = XZ2SFC()
        code = sfc.index(
            np.array([-170.0]), np.array([-80.0]), np.array([170.0]), np.array([80.0])
        )
        step = (4**sfc.g - 1) // 3
        assert int(code[0]) in {1 + q * step for q in range(4)}


class TestValidation:
    def test_inverted_box_rejected(self):
        sfc = XZ2SFC()
        with pytest.raises(ValueError, match="antimeridian"):
            sfc.index(
                np.array([170.0]), np.array([0.0]), np.array([-170.0]), np.array([1.0])
            )

    def test_g_capacity_limits(self):
        from geomesa_tpu.curves.xz import XZSFC

        with pytest.raises(ValueError, match="int64"):
            XZSFC(32, dims=2)
        with pytest.raises(ValueError, match="int64"):
            XZSFC(21, dims=3)
        XZSFC(31, dims=2)
        XZSFC(20, dims=3)


class TestXZ3:
    def test_no_false_negatives(self, rng):
        sfc = XZ3SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 3000, -20, 20, 30, 60, 2.0)
        tmin = rng.uniform(0, 500000, 3000)
        tmax = tmin + rng.uniform(0, 3600, 3000)
        codes = sfc.index(xmin, ymin, tmin, xmax, ymax, np.minimum(tmax, 604800))
        q = (-5.0, 42.0, 86400.0, 8.0, 51.0, 259200.0)
        ranges = sfc.ranges(*q)
        hits = _covered(codes, ranges)
        inter = (
            (xmax >= q[0])
            & (xmin <= q[3])
            & (ymax >= q[1])
            & (ymin <= q[4])
            & (tmax >= q[2])
            & (tmin <= q[5])
        )
        assert np.all(hits[inter]), "false negatives in XZ3 ranges"

    def test_prunes_far_boxes(self, rng):
        sfc = XZ3SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 3000, 100, -80, 170, -40, 2.0)
        tmin = rng.uniform(400000, 500000, 3000)
        codes = sfc.index(xmin, ymin, tmin, xmax, ymax, tmin + 100)
        ranges = sfc.ranges(-5.0, 42.0, 1000.0, 8.0, 51.0, 2000.0)
        assert np.mean(_covered(codes, ranges)) < 0.05
