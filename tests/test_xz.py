"""XZ2/XZ3 extent-curve properties.

Key invariant (the XZ coverage property): for any set of boxes and any query
window, every box that intersects the query must have its code inside the
emitted ranges (no false negatives); boxes far from the query should mostly
be excluded.
"""

import numpy as np
import pytest

from geomesa_tpu.curves import XZ2SFC, XZ3SFC


def _covered(codes, ranges):
    arr = np.array([(r.lower, r.upper) for r in ranges], dtype=np.int64)
    idx = np.searchsorted(arr[:, 0], codes, side="right") - 1
    return (idx >= 0) & (codes <= arr[np.clip(idx, 0, len(arr) - 1), 1])


def _rand_boxes(rng, n, x0, y0, x1, y1, max_size):
    xmin = rng.uniform(x0, x1 - max_size, n)
    ymin = rng.uniform(y0, y1 - max_size, n)
    w = rng.uniform(0, max_size, n)
    h = rng.uniform(0, max_size, n)
    return xmin, ymin, xmin + w, ymin + h


class TestXZ2:
    def test_point_boxes_deterministic(self):
        sfc = XZ2SFC()
        c1 = sfc.index(np.array([2.0]), np.array([48.0]), np.array([2.0]), np.array([48.0]))
        c2 = sfc.index(np.array([2.0]), np.array([48.0]), np.array([2.0]), np.array([48.0]))
        assert c1[0] == c2[0] >= 0

    def test_codes_within_keyspace(self, rng):
        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 5000, -180, -90, 180, 90, 5.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        max_code = (4 ** (sfc.g + 1) - 1) // 3
        assert np.all(codes >= 0)
        assert np.all(codes <= max_code)

    def test_no_false_negatives(self, rng):
        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 5000, -20, 20, 30, 60, 2.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        q = (-5.0, 42.0, 8.0, 51.0)
        ranges = sfc.ranges(*q)
        hits = _covered(codes, ranges)
        intersecting = (
            (xmax >= q[0]) & (xmin <= q[2]) & (ymax >= q[1]) & (ymin <= q[3])
        )
        assert np.all(hits[intersecting]), "false negatives in XZ2 ranges"

    def test_prunes_far_boxes(self, rng):
        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 5000, 100, -80, 170, -40, 2.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        ranges = sfc.ranges(-5.0, 42.0, 8.0, 51.0)
        assert np.mean(_covered(codes, ranges)) < 0.05

    def test_large_geometries_low_level(self):
        # a hemisphere-sized box is stored at level 1 (every box fits some
        # level-1 enlarged cell, which spans the whole space), so its code is
        # one of the four level-1 quadrant codes.
        sfc = XZ2SFC()
        code = sfc.index(
            np.array([-170.0]), np.array([-80.0]), np.array([170.0]), np.array([80.0])
        )
        step = (4**sfc.g - 1) // 3
        assert int(code[0]) in {1 + q * step for q in range(4)}


class TestValidation:
    def test_inverted_box_rejected(self):
        sfc = XZ2SFC()
        with pytest.raises(ValueError, match="antimeridian"):
            sfc.index(
                np.array([170.0]), np.array([0.0]), np.array([-170.0]), np.array([1.0])
            )

    def test_g_capacity_limits(self):
        from geomesa_tpu.curves.xz import XZSFC

        with pytest.raises(ValueError, match="int64"):
            XZSFC(32, dims=2)
        with pytest.raises(ValueError, match="int64"):
            XZSFC(21, dims=3)
        XZSFC(31, dims=2)
        XZSFC(20, dims=3)


class TestXZ3:
    def test_no_false_negatives(self, rng):
        sfc = XZ3SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 3000, -20, 20, 30, 60, 2.0)
        tmin = rng.uniform(0, 500000, 3000)
        tmax = tmin + rng.uniform(0, 3600, 3000)
        codes = sfc.index(xmin, ymin, tmin, xmax, ymax, np.minimum(tmax, 604800))
        q = (-5.0, 42.0, 86400.0, 8.0, 51.0, 259200.0)
        ranges = sfc.ranges(*q)
        hits = _covered(codes, ranges)
        inter = (
            (xmax >= q[0])
            & (xmin <= q[3])
            & (ymax >= q[1])
            & (ymin <= q[4])
            & (tmax >= q[2])
            & (tmin <= q[5])
        )
        assert np.all(hits[inter]), "false negatives in XZ3 ranges"

    def test_prunes_far_boxes(self, rng):
        sfc = XZ3SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 3000, 100, -80, 170, -40, 2.0)
        tmin = rng.uniform(400000, 500000, 3000)
        codes = sfc.index(xmin, ymin, tmin, xmax, ymax, tmin + 100)
        ranges = sfc.ranges(-5.0, 42.0, 1000.0, 8.0, 51.0, 2000.0)
        assert np.mean(_covered(codes, ranges)) < 0.05


def _u64(hi, lo):
    return (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
        lo
    ).astype(np.uint64)


class TestDeviceEncode:
    """index_jax_hi_lo must agree bit-for-bit with the host encode under
    float64 (the CPU/x64 test platform; VERDICT round-2 item 1)."""

    def test_xz2_parity_random(self, rng):
        import jax
        import jax.numpy as jnp

        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 50_000, -180, -90, 179, 89, 3.0)
        xmax = np.minimum(xmax, 180.0)
        ymax = np.minimum(ymax, 90.0)
        host = sfc.index(xmin, ymin, xmax, ymax).astype(np.uint64)
        hi, lo = jax.jit(sfc.index_jax_hi_lo)(
            *map(jnp.asarray, (xmin, ymin, xmax, ymax))
        )
        np.testing.assert_array_equal(_u64(hi, lo), host)

    def test_xz2_parity_adversarial(self):
        import jax
        import jax.numpy as jnp

        sfc = XZ2SFC()
        # degenerate points, whole world, exact power-of-two extents,
        # lat/lon maxima
        xmin = np.array([-180.0, 0.0, -180.0, 10.0, -45.0, 179.9])
        ymin = np.array([-90.0, 0.0, -90.0, 10.0, -45.0, 89.9])
        xmax = np.array(
            [180.0, 0.0, -180.0 + 360.0 * 0.25, 10.0 + 360 * 2**-10,
             -45.0 + 360 * 2**-12, 180.0]
        )
        ymax = np.array(
            [90.0, 0.0, -90.0 + 180.0 * 0.25, 10.0 + 180 * 2**-10,
             -45.0 + 180 * 2**-12, 90.0]
        )
        host = sfc.index(xmin, ymin, xmax, ymax).astype(np.uint64)
        hi, lo = jax.jit(sfc.index_jax_hi_lo)(
            *map(jnp.asarray, (xmin, ymin, xmax, ymax))
        )
        np.testing.assert_array_equal(_u64(hi, lo), host)

    def test_xz3_parity_random(self, rng):
        import jax
        import jax.numpy as jnp

        sfc = XZ3SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 50_000, -180, -90, 179, 89, 3.0)
        xmax = np.minimum(xmax, 180.0)
        ymax = np.minimum(ymax, 90.0)
        tmin = rng.uniform(0, sfc.t_max, len(xmin))
        tmax = np.minimum(
            tmin + rng.uniform(0, sfc.t_max * 0.01, len(xmin)), sfc.t_max
        )
        host = sfc.index(xmin, ymin, tmin, xmax, ymax, tmax).astype(np.uint64)
        hi, lo = jax.jit(sfc.index_jax_hi_lo)(
            *map(jnp.asarray, (xmin, ymin, tmin, xmax, ymax, tmax))
        )
        np.testing.assert_array_equal(_u64(hi, lo), host)


class TestDeviceRangeMask:
    """The device xz key-range mask must agree with the host range cover
    (same ranges, same codes) and keep the no-false-negative invariant."""

    def test_xz2_mask_matches_host_cover(self, rng):
        import jax
        import jax.numpy as jnp

        from geomesa_tpu.ops import zscan

        sfc = XZ2SFC()
        xmin, ymin, xmax, ymax = _rand_boxes(rng, 20_000, -20, 20, 30, 60, 2.0)
        codes = sfc.index(xmin, ymin, xmax, ymax)
        q = (-5.0, 42.0, 8.0, 51.0)
        bounds = zscan.pad_ranges(zscan.xz2_query_bounds(sfc, *q))
        hi = (codes.astype(np.uint64) >> np.uint64(32)).astype(np.uint32)
        lo = (codes.astype(np.uint64) & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        m = np.asarray(
            jax.jit(zscan.xz_range_mask)(
                jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(bounds)
            )
        )
        # no false negatives vs true box intersection
        intersecting = (
            (xmax >= q[0]) & (xmin <= q[2]) & (ymax >= q[1]) & (ymin <= q[3])
        )
        assert np.all(m[intersecting])
        # the device mask equals the HOST cover for the same budgeted ranges
        host_cover = _covered(
            codes, sfc.ranges(*q, max_ranges=128)
        )
        np.testing.assert_array_equal(m, host_cover)
        # and it prunes: far boxes mostly excluded
        assert m.mean() < 0.5

    def test_xz3_mask_binned(self, rng):
        import jax
        import jax.numpy as jnp

        from geomesa_tpu.curves.binnedtime import to_binned_time
        from geomesa_tpu.ops import zscan

        sfc = XZ3SFC()
        n = 20_000
        xmin, ymin, xmax, ymax = _rand_boxes(rng, n, -20, 20, 30, 60, 2.0)
        # ~5 weeks of instantaneous rows
        ms = rng.integers(1_577_836_800_000, 1_580_860_800_000, n)
        bins, off = to_binned_time(ms, sfc.period)
        offf = off.astype(np.float64)
        codes = sfc.index(xmin, ymin, offf, xmax, ymax, offf)
        q = (-5.0, 42.0, 8.0, 51.0)
        t0, t1 = 1_578_441_600_000, 1_580_256_000_000  # inner window
        bounds, ids = zscan.xz3_query_bounds(sfc, *q, t0, t1)
        bounds, ids = zscan.pad_bins(bounds, ids)
        hi = (codes.astype(np.uint64) >> np.uint64(32)).astype(np.uint32)
        lo = (codes.astype(np.uint64) & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        m = np.asarray(
            jax.jit(zscan.xz3_range_mask)(
                jnp.asarray(hi), jnp.asarray(lo),
                jnp.asarray(bins.astype(np.int32)),
                jnp.asarray(bounds), jnp.asarray(ids),
            )
        )
        intersecting = (
            (xmax >= q[0]) & (xmin <= q[2]) & (ymax >= q[1]) & (ymin <= q[3])
            & (ms >= t0) & (ms <= t1)
        )
        assert intersecting.sum() > 0
        assert np.all(m[intersecting]), "false negatives in device xz3 mask"
        # rows entirely outside the time window's bins never match
        outside_bins = ~np.isin(bins, ids[ids >= 0])
        assert not np.any(m[outside_bins])
