"""Durable/partitioned logs, GeoMessage codec, consumer threads, facade."""

import numpy as np
import pytest

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.stream import (
    CacheLoader,
    Clear,
    FileFeatureLog,
    LiveDataStore,
    LiveFeatureStore,
    PartitionedFeatureLog,
    Put,
    Remove,
    decode_message,
    encode_message,
)

SPEC = "name:String,count:Int,dtg:Date,*geom:Point"
SFT = SimpleFeatureType.create("live", SPEC)


def _put(n=4, base=0):
    return Put(
        {
            "name": [f"n{i}" for i in range(base, base + n)],
            "count": np.arange(base, base + n),
            "dtg": np.full(n, 1000 * (base + 1)),
            "geom": np.stack([np.arange(base, base + n) * 1.0,
                              np.zeros(n)], axis=1),
        },
        np.array([f"f{i}" for i in range(base, base + n)], dtype=object),
    )


def test_geomessage_roundtrip():
    for msg in [_put(), Remove(np.array(["f1", "f2"], dtype=object)), Clear()]:
        rt = decode_message(SFT, encode_message(SFT, msg))
        assert type(rt) is type(msg)
        if isinstance(msg, Put):
            np.testing.assert_array_equal(rt.fids, msg.fids)
            np.testing.assert_array_equal(rt.columns["count"], msg.columns["count"])
            np.testing.assert_allclose(
                np.asarray(rt.columns["geom"], dtype=float),
                np.asarray(msg.columns["geom"], dtype=float),
            )
        if isinstance(msg, Remove):
            np.testing.assert_array_equal(rt.fids, msg.fids)


def test_file_log_durability(tmp_path):
    path = str(tmp_path / "t.log")
    log = FileFeatureLog(path, SFT)
    log.append(_put(4))
    log.append(Remove(np.array(["f1"], dtype=object)))
    log.close()
    # reopen: full history recovered, cache rebuilds via replay
    log2 = FileFeatureLog(path, SFT)
    assert len(log2) == 2
    store = LiveFeatureStore(SFT, log=log2)
    assert sorted(store.snapshot().fids.tolist()) == ["f0", "f2", "f3"]


def test_partitioned_log_routing_and_ordering():
    plog = PartitionedFeatureLog(4)
    plog.append(_put(16))
    assert len(plog) >= 1
    # same fid must always land in the same partition
    plog.append(Remove(np.array(["f3"], dtype=object)))
    part_of = {}
    for p, log in enumerate(plog.partitions):
        for m in log.read_from(0):
            for f in np.asarray(m.fids).tolist():
                part_of.setdefault(f, set()).add(p)
    assert all(len(ps) == 1 for ps in part_of.values())


def test_cache_loader_threads():
    plog = PartitionedFeatureLog(4)
    store = LiveFeatureStore(SFT, standalone=True)
    loader = CacheLoader(store, plog, poll_ms=5)
    loader.start()
    try:
        for i in range(8):
            plog.append(_put(8, base=i * 8))
        plog.append(Remove(np.array(["f0"], dtype=object)))
        import time

        deadline = time.time() + 5
        while time.time() < deadline and len(store) != 63:
            time.sleep(0.01)
        assert len(store) == 63
    finally:
        loader.stop()


def test_cache_loader_catch_up_deterministic():
    plog = PartitionedFeatureLog(2)
    store = LiveFeatureStore(SFT, standalone=True)
    loader = CacheLoader(store, plog, poll_ms=1000)
    plog.append(_put(10))
    loader.catch_up()
    assert len(store) == 10
    res = store.query("count >= 5")
    assert len(res) == 5


def test_live_datastore_facade(tmp_path):
    ds = LiveDataStore(root=str(tmp_path))
    ds.create_schema("tracks", SPEC)
    events = []
    ds.add_listener("tracks", events.append)
    ds.write(
        "tracks",
        {
            "name": ["a", "b"],
            "count": [1, 2],
            "dtg": [0, 0],
            "geom": np.array([[0.0, 0.0], [5.0, 5.0]]),
        },
        ["t1", "t2"],
    )
    assert len(events) == 1
    assert len(ds.query("tracks", "BBOX(geom, -1, -1, 1, 1)")) == 1
    ds.remove("tracks", ["t1"])
    # restart from disk: schema + state recovered by log replay
    ds2 = LiveDataStore(root=str(tmp_path))
    assert ds2.type_names == ["tracks"]
    assert ds2.query("tracks").fids.tolist() == ["t2"]


def test_file_log_torn_tail_recovery(tmp_path):
    path = str(tmp_path / "t.log")
    log = FileFeatureLog(path, SFT)
    log.append(_put(4))
    log.append(_put(2, base=4))
    log.close()
    # simulate a crash mid-append: truncate the last record's payload
    import os

    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 3)
    log2 = FileFeatureLog(path, SFT)  # must not raise
    assert len(log2) == 1  # torn record dropped
    log2.append(_put(1, base=9))  # appends continue cleanly
    log2.close()
    assert len(FileFeatureLog(path, SFT)) == 2


def test_standalone_store_rejects_producer_calls():
    store = LiveFeatureStore(SFT, standalone=True)
    with pytest.raises(ValueError, match="consumer-only"):
        store.put({"name": ["x"], "count": [1], "dtg": [0],
                   "geom": np.zeros((1, 2))}, ["f0"])
    with pytest.raises(ValueError, match="consumer-only"):
        store.remove(["f0"])


def test_snapshot_is_isolated_from_later_writes():
    store = LiveFeatureStore(SFT)
    p = _put(2)
    store.put(p.columns, p.fids)
    snap = store.snapshot()
    before = snap.column("count").copy()
    # in-place upsert of the same fids must not mutate the snapshot
    store.put(
        {
            "name": ["z", "z"],
            "count": [99, 99],
            "dtg": [5, 5],
            "geom": np.ones((2, 2)),
        },
        p.fids,
    )
    np.testing.assert_array_equal(snap.column("count"), before)


def test_out_of_order_subscriber_delivery_not_dropped():
    # simulate the producer race: callbacks arrive in reversed offset order
    from geomesa_tpu.stream import FeatureLog

    log = FeatureLog()
    log.messages = []  # plain log; we drive callbacks manually
    store = LiveFeatureStore(SFT, log=log)
    m0, m1 = _put(2), _put(2, base=2)
    log.messages.append(m0)
    log.messages.append(m1)
    store._on_message(1, m1)  # later offset delivered first
    store._on_message(0, m0)
    assert len(store) == 4  # both applied, none dropped


def test_clear_barrier_across_partitions():
    # a partition's late Clear must not wipe puts sequenced after it
    plog = PartitionedFeatureLog(4)
    store = LiveFeatureStore(SFT, standalone=True)
    plog.append(_put(4))          # seq 1
    plog.append(Clear())          # seq 2, broadcast to all partitions
    plog.append(_put(4, base=10))  # seq 3
    # adversarial consumption order: fully drain one partition at a time
    for log in plog.partitions:
        for m in log.read_from(0):
            store.apply(m)
    assert sorted(store.snapshot().fids.tolist()) == [
        "f10", "f11", "f12", "f13"
    ]


def test_clear_seq_survives_wire_codec():
    msg = Clear(seq=42)
    rt = decode_message(SFT, encode_message(SFT, msg))
    assert rt.seq == 42
    p = decode_message(SFT, encode_message(SFT, Put(_put(2).columns, _put(2).fids, seq=7)))
    assert p.seq == 7


def test_live_expiry_still_works_with_facade():
    clock = {"t": 1000}
    store = LiveFeatureStore(
        SFT, expiry_ms=50, clock=lambda: clock["t"]
    )
    store.put(_put(3).columns, _put(3).fids)
    assert len(store) == 3
    clock["t"] = 2000
    assert len(store) == 0
