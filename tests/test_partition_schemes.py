"""Named FS partition schemes: leaf assignment, pruning, store layout."""

import os

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.partitions import (
    AttributeScheme,
    CompositeScheme,
    DateTimeScheme,
    Z2Scheme,
    XZ2Scheme,
    scheme_for,
)

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


def _batch(n=1000, seed=3):
    sft = SimpleFeatureType.create("t", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-01-10T00:00:00")
    return FeatureBatch.from_columns(
        sft,
        {
            "name": rng.choice(["a", "b", "c"], n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        np.arange(n),
    )


def test_scheme_parsing():
    assert isinstance(scheme_for("z2-2bit"), Z2Scheme)
    assert isinstance(scheme_for("xz2-4bits"), XZ2Scheme)
    assert isinstance(scheme_for("daily"), DateTimeScheme)
    assert isinstance(scheme_for("attribute:name"), AttributeScheme)
    comp = scheme_for("hourly,z2-2bit")
    assert isinstance(comp, CompositeScheme)
    assert comp.depth == 5  # 4 datetime segments + 1 z2
    with pytest.raises(ValueError):
        scheme_for("bogus")
    with pytest.raises(ValueError):
        scheme_for("z2-3bit")  # odd bits


def test_datetime_leaves_and_buckets():
    b = _batch(100)
    for step, seg in [("daily", 3), ("hourly", 4), ("monthly", 2), ("yearly", 1)]:
        s = DateTimeScheme(step)
        leaves = s.leaves(b)
        assert all(leaf.count("/") == seg - 1 for leaf in leaves)
        # every feature's dtg falls inside its own leaf bucket
        dtg = b.column("dtg")
        for i in [0, 17, 99]:
            lo, hi = s._bucket_ms(leaves[i])
            assert lo <= int(dtg[i]) < hi
    w = DateTimeScheme("weekly")
    leaves = w.leaves(b)
    assert all(leaf.startswith("W") for leaf in leaves)


def test_z2_leaf_cells_contain_points():
    b = _batch(200)
    s = Z2Scheme(4)
    leaves = s.leaves(b)
    geom = b.columns["geom"]
    for i in [0, 50, 150]:
        env = s._cell_env(leaves[i])
        assert env.xmin <= geom[i, 0] <= env.xmax
        assert env.ymin <= geom[i, 1] <= env.ymax


def test_fs_store_with_scheme_layout_and_prune(tmp_path):
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.fs.partition-scheme"] = "daily,z2-2bit"
    ds = FileSystemDataStore(str(tmp_path), partition_size=256)
    ds.create_schema(sft)
    n = 3000
    rng = np.random.default_rng(5)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-01-10T00:00:00")
    cols = {
        "name": rng.choice(["a", "b"], n),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    ds.write("t", cols, fids=np.arange(n))
    ds.flush("t")
    # leaf directories exist: t/2020/01/05/<z>/part-*.parquet
    assert (tmp_path / "t" / "2020" / "01" / "05").is_dir()

    ecql = (
        "BBOX(geom, -170, -80, -100, -10) AND "
        "dtg DURING 2020-01-02T00:00:00Z/2020-01-04T00:00:00Z"
    )
    res = ds.query("t", ecql)
    batch = FeatureBatch.from_columns(sft, cols, np.arange(n))
    expected = np.sort(batch.fids[evaluate_host(parse_ecql(ecql), batch)])
    np.testing.assert_array_equal(np.sort(res.batch.fids), expected)
    assert res.scanned < res.total  # leaf prune actually skipped data

    # reopen from disk: scheme + leaves persist
    ds2 = FileSystemDataStore(str(tmp_path))
    res2 = ds2.query("t", ecql)
    np.testing.assert_array_equal(np.sort(res2.batch.fids), expected)


def test_fs_attribute_scheme_prune(tmp_path):
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.fs.partition-scheme"] = "attribute:name"
    ds = FileSystemDataStore(str(tmp_path))
    ds.create_schema(sft)
    n = 600
    rng = np.random.default_rng(9)
    cols = {
        "name": rng.choice(["a", "b", "c"], n),
        "dtg": rng.integers(0, 10**6, n),
        "geom": np.zeros((n, 2)),
    }
    ds.write("t", cols, fids=np.arange(n))
    ds.flush("t")
    assert (tmp_path / "t" / "a").is_dir()
    res = ds.query("t", "name = 'a'")
    assert res.scanned == (cols["name"] == "a").sum()  # only leaf 'a' read
    assert len(res) == res.scanned
    res_in = ds.query("t", "name IN ('a', 'b')")
    assert len(res_in) == ((cols["name"] == "a") | (cols["name"] == "b")).sum()


def test_minute_composite_scheme(tmp_path):
    # 'minute' leaves must be 5 clean path segments so composites slice
    # correctly (a ':' in the leaf previously broke depth accounting)
    s = scheme_for("minute,z2-2bit")
    b = _batch(50)
    leaves = s.leaves(b)
    assert all(leaf.count("/") == 5 for leaf in leaves)
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.fs.partition-scheme"] = "minute,z2-2bit"
    ds = FileSystemDataStore(str(tmp_path))
    ds.create_schema(sft)
    cols = {
        "name": ["x"] * 10,
        "dtg": np.arange(10) * 60_000 + parse_instant("2020-01-01T00:00:00"),
        "geom": np.zeros((10, 2)),
    }
    ds.write("t", cols, fids=np.arange(10))
    ds.flush("t")
    res = ds.query(
        "t", "dtg DURING 2020-01-01T00:00:00Z/2020-01-01T00:03:00Z"
    )
    assert len(res) == 4  # minutes 0..3 inclusive


def test_attribute_scheme_sanitizes_path_values(tmp_path):
    # hostile values must not escape the store root or add path segments
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.fs.partition-scheme"] = "attribute:name"
    ds = FileSystemDataStore(str(tmp_path / "store"))
    ds.create_schema(sft)
    names = ["../../escape", "a/b", "ok"]
    ds.write(
        "t",
        {"name": names, "dtg": [0, 0, 0], "geom": np.zeros((3, 2))},
        fids=np.arange(3),
    )
    ds.flush("t")
    # nothing written outside the store root
    outside = [
        p
        for p in (tmp_path).rglob("part-*")
        if "store" not in p.parts
    ]
    assert outside == []
    # queries still find everything, including sanitized-leaf features
    assert ds.count("t") == 3
    assert len(ds.query("t", "name = 'a/b'")) == 1


def test_xz2_scheme_roundtrip(tmp_path):
    from geomesa_tpu.geom import Polygon

    sft = SimpleFeatureType.create("t", "name:String,*geom:Polygon")
    sft.user_data["geomesa.fs.partition-scheme"] = "xz2-4bit"
    ds = FileSystemDataStore(str(tmp_path))
    ds.create_schema(sft)
    polys = [
        Polygon([(x, y), (x + 1, y), (x + 1, y + 1), (x, y + 1), (x, y)])
        for x, y in [(-170, -80), (0, 0), (100, 40), (150, 70)]
    ]
    ds.write(
        "t",
        {"name": ["p0", "p1", "p2", "p3"], "geom": np.array(polys, dtype=object)},
        fids=np.arange(4),
    )
    ds.flush("t")
    res = ds.query("t", "BBOX(geom, -1, -1, 3, 3)")
    assert list(res.batch.column("name")) == ["p1"]


def test_xz3_scheme_roundtrip(tmp_path):
    from geomesa_tpu.geom import Polygon

    sft = SimpleFeatureType.create("t", "name:String,dtg:Date,*geom:Polygon")
    sft.user_data["geomesa.fs.partition-scheme"] = "xz3-4bit"
    ds = FileSystemDataStore(str(tmp_path))
    ds.create_schema(sft)
    t0 = parse_instant("2020-01-01T00:00:00")
    week = 7 * 86400 * 1000
    polys = [
        Polygon([(x, y), (x + 1, y), (x + 1, y + 1), (x, y + 1), (x, y)])
        for x, y in [(-170, -80), (0, 0), (100, 40)]
    ]
    ds.write(
        "t",
        {
            "name": ["p0", "p1", "p2"],
            "dtg": [t0, t0, t0 + 3 * week],  # p2 in a different week bin
            "geom": np.array(polys, dtype=object),
        },
        fids=np.arange(3),
    )
    ds.flush("t")
    # leaf dirs: W<bin>/<code>
    leaves = [p.leaf for p in ds._types["t"].partitions]
    assert all(leaf and leaf.startswith("W") and "/" in leaf for leaf in leaves)
    res = ds.query(
        "t",
        "BBOX(geom, -1, -1, 3, 3) AND "
        "dtg DURING 2019-12-30T00:00:00Z/2020-01-08T00:00:00Z",
    )
    assert list(res.batch.column("name")) == ["p1"]
    # time-only prune drops the other week bin entirely
    res2 = ds.query(
        "t", "dtg DURING 2019-12-30T00:00:00Z/2020-01-08T00:00:00Z"
    )
    assert sorted(res2.batch.column("name")) == ["p0", "p1"]
    # scheme survives reopen
    ds2 = FileSystemDataStore(str(tmp_path))
    assert ds2.count("t") == 3


def test_xz3_scheme_validation():
    import pytest as _pytest

    from geomesa_tpu.store.partitions import XZ3Scheme

    s = scheme_for("xz3-4bit")
    assert isinstance(s, XZ3Scheme)
    sft = SimpleFeatureType.create("t", "name:String,*geom:Polygon")  # no dtg
    with _pytest.raises(ValueError, match="Date"):
        s.validate(sft)
