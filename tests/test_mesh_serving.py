"""Multi-chip sharded serving (ISSUE 8): ShardedDeviceIndex parity with
single-device serving across shard counts — including a non-power-of-two
count, adversarial layouts and padding edges — plus the mesh server
endpoints, the distributed-sort engines, and the degraded-build ladder.

Runs in-process on the 8-virtual-device CPU harness conftest provides.
"""

import json
import urllib.request
from urllib.parse import quote

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override
from geomesa_tpu.device_cache import DeviceIndex, ShardedDeviceIndex
from geomesa_tpu.parallel.mesh import make_mesh
from geomesa_tpu.store import MemoryDataStore

T0 = 1577836800000  # 2020-01-01


def _write(store, name, x, y, t):
    n = len(x)
    store.create_schema(
        name, "name:String,v:Integer,dtg:Date,*geom:Point:srid=4326"
    )
    rng = np.random.default_rng(len(x))
    store.write(
        name,
        {
            "name": rng.choice(["a", "b", "c"], n),
            "v": rng.integers(0, 100, n).astype(np.int32),
            "dtg": np.asarray(t, dtype=np.int64),
            "geom": np.stack([x, y], axis=1),
        },
        fids=np.arange(n),
    )


def _layout(kind, n, rng):
    """Adversarial coordinate layouts: uniform, pre-sorted along x,
    all-duplicate (one point), and GDELT-style hot city clusters."""
    if kind == "uniform":
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
    elif kind == "presorted":
        x = np.sort(rng.uniform(-180, 180, n))
        y = rng.uniform(-90, 90, n)
    elif kind == "duplicate":
        x = np.full(n, 2.3522)
        y = np.full(n, 48.8566)
    else:  # clustered: 90% of points in 4 tiny city cells
        centers = np.array(
            [[2.35, 48.85], [-74.0, 40.7], [139.7, 35.7], [28.0, -26.2]]
        )
        which = rng.integers(0, 4, n)
        x = centers[which, 0] + rng.uniform(-0.01, 0.01, n)
        y = centers[which, 1] + rng.uniform(-0.01, 0.01, n)
        cold = rng.random(n) < 0.1
        x[cold] = rng.uniform(-180, 180, int(cold.sum()))
        y[cold] = rng.uniform(-90, 90, int(cold.sum()))
    t = T0 + rng.integers(0, 30 * 86400_000, n)
    return x, y, t


CQLS = (
    "BBOX(geom, -10, 35, 30, 60)",
    "BBOX(geom, 2.34, 48.84, 2.36, 48.86)",  # the Paris hot cell
    "BBOX(geom, -10, 35, 30, 60) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z",
    "INCLUDE",
    "BBOX(geom, 100, -20, 140, 20) AND v < 50",  # residual predicate
)


@pytest.mark.parametrize("layout", ["uniform", "presorted", "duplicate",
                                    "clustered"])
def test_sharded_parity_matrix(layout):
    """count / query / fused count / fused query bit-identical to the
    single-device DeviceIndex across shard counts {1, 2, 8} and a
    non-power-of-two count (3), for every adversarial layout. n is NOT
    shard-divisible, so the padding/valid-mask edge is always live."""
    rng = np.random.default_rng(hash(layout) % (1 << 31))
    n = 6007  # prime: pads under every shard count
    x, y, t = _layout(layout, n, rng)
    store = MemoryDataStore()
    _write(store, "pts", x, y, t)
    base = DeviceIndex(store, "pts", z_planes=True)
    fuseable = [CQLS[0], CQLS[1], "BBOX(geom, -120, 20, -60, 55)"]
    for ns in (1, 2, 3, 8):
        di = ShardedDeviceIndex(store, "pts", mesh=make_mesh(ns))
        assert di.mesh_shards == ns
        for cql in CQLS:
            assert di.count(cql) == base.count(cql), (layout, ns, cql)
            np.testing.assert_array_equal(
                di.query(cql).fids, base.query(cql).fids,
                err_msg=f"{layout}/{ns}/{cql}",
            )
        with prop_override("query.loose.bbox", True):
            for cql in CQLS[:3]:
                assert di.count(cql, loose=True) == base.count(
                    cql, loose=True
                ), (layout, ns, cql)
                np.testing.assert_array_equal(
                    di.query(cql, loose=True).fids,
                    base.query(cql, loose=True).fids,
                    err_msg=f"loose {layout}/{ns}/{cql}",
                )
            fb = base.fused_loose_counts(fuseable, loose=True)
            fs = di.fused_loose_counts(fuseable, loose=True)
            assert fb == fs, (layout, ns)
            qb = base.fused_loose_query(fuseable, loose=True)
            qs = di.fused_loose_query(fuseable, loose=True)
            for b, s in zip(qb, qs):
                np.testing.assert_array_equal(b.fids, s.fids)


def test_sharded_rider_parity():
    """The non-count riders — density grid, kNN, stats — answer
    identically from the mesh-sharded planes."""
    from geomesa_tpu.geom import Envelope

    rng = np.random.default_rng(9)
    n = 8000
    x, y, t = _layout("clustered", n, rng)
    store = MemoryDataStore()
    _write(store, "pts", x, y, t)
    base = DeviceIndex(store, "pts", z_planes=True)
    di = ShardedDeviceIndex(store, "pts", mesh=make_mesh(8))
    cql = CQLS[0]
    gb = base.density(cql, Envelope(-10, 35, 30, 60), 32, 32)
    gs = di.density(cql, Envelope(-10, 35, 30, 60), 32, 32)
    np.testing.assert_array_equal(np.asarray(gb), np.asarray(gs))
    kb, db = base.knn(2.35, 48.85, 7)
    ks, ds = di.knn(2.35, 48.85, 7)
    np.testing.assert_array_equal(kb.fids, ks.fids)
    np.testing.assert_allclose(np.asarray(db), np.asarray(ds))
    sb = base.stats("INCLUDE", 'Count();MinMax("v")')
    ss = di.stats("INCLUDE", 'Count();MinMax("v")')
    assert sb.to_json() == ss.to_json()


def test_shard_ranges_are_contiguous_z_ranges():
    """The mesh manifest: shard s's key range ends before shard s+1's
    begins (contiguous global Z-ranges), rows sum to the dataset, and
    the staged layout equals the host (bin, hi, lo, rid) lexsort."""
    rng = np.random.default_rng(4)
    n = 10000
    x, y, t = _layout("uniform", n, rng)
    store = MemoryDataStore()
    _write(store, "pts", x, y, t)
    di = ShardedDeviceIndex(store, "pts", mesh=make_mesh(8))
    stats = di.mesh_stats()
    assert stats["shards"] == 8 and stats["rows"] == n
    assert stats["build_engine"] == "mesh"
    ranges = stats["shard_ranges"]
    assert sum(r["rows"] for r in ranges) == n
    prev_hi = None
    for r in ranges:
        if not r["rows"]:
            continue
        assert tuple(r["key_lo"]) <= tuple(r["key_hi"])
        if prev_hi is not None:
            assert tuple(r["key_lo"]) >= prev_hi
        prev_hi = tuple(r["key_hi"])


def test_mesh_build_degrades_to_host_sort(monkeypatch):
    """A mesh-sort fault must not fail staging: the build falls back to
    the host lexsort (identical layout), counts the fallback and keeps
    serving — PR 7's taxonomy applied to the build path."""
    from geomesa_tpu import metrics
    from geomesa_tpu.parallel import dist

    rng = np.random.default_rng(11)
    n = 5000
    x, y, t = _layout("uniform", n, rng)
    store = MemoryDataStore()
    _write(store, "pts", x, y, t)
    ref = ShardedDeviceIndex(store, "pts", mesh=make_mesh(8))

    def boom(*a, **k):
        raise RuntimeError("injected mesh sort fault")

    monkeypatch.setattr(dist, "distributed_sort", boom)
    before = metrics.mesh_build_fallbacks.value()
    with pytest.warns(RuntimeWarning, match="mesh build sort failed"):
        di = ShardedDeviceIndex(store, "pts", mesh=make_mesh(8))
    assert metrics.mesh_build_fallbacks.value() == before + 1
    assert di.mesh_stats()["build_engine"] == "host-fallback"
    # identical staged layout and answers either way
    cql = CQLS[2]
    assert di.count(cql) == ref.count(cql)
    np.testing.assert_array_equal(di.query(cql).fids, ref.query(cql).fids)


def test_distributed_sort_engine_parity():
    """The device engine (single fused all_to_all + measured-capacity
    retry) and the host-staged engine return the same sorted key
    multiset and loss-free payloads, including under adversarial
    pre-sorted input that forces the device engine's capacity retry."""
    import jax.numpy as jnp

    from geomesa_tpu import metrics
    from geomesa_tpu.parallel.dist import distributed_sort

    mesh = make_mesh(8)
    n = 1 << 14
    rng = np.random.default_rng(2)
    for name, z in {
        "uniform": rng.integers(0, 2**62, n, dtype=np.uint64),
        "presorted": np.sort(rng.integers(0, 2**62, n, dtype=np.uint64)),
        "duplicate": np.full(n, 12345678901234, np.uint64),
    }.items():
        hi = jnp.asarray((z >> np.uint64(32)).astype(np.uint32))
        lo = jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        pay = {"f": jnp.asarray((z % 1000).astype(np.float32)),
               "i": jnp.asarray((z % 251).astype(np.uint8)),
               "d": jnp.asarray((z % 97).astype(np.float64))}
        results = {}
        for engine in ("host", "device"):
            (sh, sl), p, v = distributed_sort(
                mesh, (hi, lo), payload=pay, engine=engine,
                on_overflow="raise",
            )
            sh_, sl_, v_ = np.asarray(sh), np.asarray(sl), np.asarray(v)
            zz = ((sh_.astype(np.uint64) << np.uint64(32)) | sl_)[v_]
            assert len(zz) == n, (name, engine)
            np.testing.assert_array_equal(np.sort(zz), np.sort(z),
                                          err_msg=f"{name}/{engine}")
            # payloads still satisfy payload == f(key) row for row
            np.testing.assert_array_equal(
                np.asarray(p["f"])[v_], (zz % 1000).astype(np.float32))
            np.testing.assert_array_equal(
                np.asarray(p["i"])[v_], (zz % 251).astype(np.uint8))
            np.testing.assert_array_equal(
                np.asarray(p["d"])[v_], (zz % 97).astype(np.float64))
            results[engine] = zz
        np.testing.assert_array_equal(results["host"], results["device"])


def test_device_engine_capacity_retry_counts():
    """Pre-sorted input defeats the optimistic first-launch capacity;
    the device engine must relaunch at the measured bound (counted)
    instead of dropping rows."""
    import jax.numpy as jnp

    from geomesa_tpu import metrics
    from geomesa_tpu.parallel.dist import distributed_sort

    mesh = make_mesh(8)
    n = 1 << 14
    z = np.sort(
        np.random.default_rng(0).integers(0, 2**62, n, dtype=np.uint64)
    )
    before = metrics.mesh_exchange_retries.value()
    (sh, sl), _, v = distributed_sort(
        mesh,
        (jnp.asarray((z >> np.uint64(32)).astype(np.uint32)),
         jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))),
        engine="device", on_overflow="raise",
    )
    assert int(np.asarray(v).sum()) == n  # loss-free
    assert metrics.mesh_exchange_retries.value() > before


def test_mesh_server_endpoints():
    """Resident mesh serving over HTTP: parity with the store, the
    /stats/mesh topology document, and the /stats roll-up with compile
    cache hit/miss."""
    from geomesa_tpu.server import serve_background

    rng = np.random.default_rng(21)
    n = 9001  # non-divisible: padding live on the serving path
    x, y, t = _layout("clustered", n, rng)
    store = MemoryDataStore()
    _write(store, "pts", x, y, t)
    server, _ = serve_background(store, resident=True, mesh=True)
    try:
        host, port = server.server_address[:2]
        base = f"http://{host}:{port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=120) as r:
                return r.status, json.loads(r.read())

        cql = quote(CQLS[0])
        st, doc = get(f"/count/pts?cql={cql}")
        oracle = len(store.query("pts", CQLS[0]))
        assert st == 200 and doc["count"] == oracle
        st, doc = get(f"/features/pts?cql={cql}")
        assert st == 200 and len(doc["features"]) == oracle
        st, doc = get("/stats/mesh")
        assert st == 200 and doc["enabled"]
        mt = doc["types"]["pts"]
        assert mt["shards"] == 8 and mt["rows"] == n
        assert sum(r["rows"] for r in mt["shard_ranges"]) == n
        st, doc = get("/stats")
        assert st == 200
        assert {"compile_cache", "mesh"} <= set(doc)
        cc = doc["compile_cache"]
        assert {"hits", "misses", "requests", "enabled"} <= set(cc)
    finally:
        server.shutdown()


def test_mesh_conf_keys_declared():
    """GT008 contract: the mesh.* / compile cache keys resolve and the
    engine key validates."""
    from geomesa_tpu.conf import declared_keys, sys_prop

    for key in ("mesh.enabled", "mesh.devices", "mesh.replicas",
                "mesh.sort.engine", "compile.cache.dir"):
        assert key in declared_keys()
        sys_prop(key)
    with prop_override("mesh.sort.engine", "host"):
        assert sys_prop("mesh.sort.engine") == "host"
    with pytest.raises(ValueError):
        with prop_override("mesh.sort.engine", "banana"):
            pass


def test_replicated_mesh_parity():
    """mesh.replicas > 1: the shard x replica factoring still answers
    bit-identically (whole-index replication across the replica axis)."""
    rng = np.random.default_rng(6)
    n = 4001
    x, y, t = _layout("uniform", n, rng)
    store = MemoryDataStore()
    _write(store, "pts", x, y, t)
    base = DeviceIndex(store, "pts", z_planes=True)
    mesh = make_mesh(8, axes=("shard", "replica"), replicas=2)
    di = ShardedDeviceIndex(store, "pts", mesh=mesh)
    assert di.mesh_shards == 4
    assert di.mesh_stats()["replicas"] == 2
    for cql in CQLS[:3]:
        assert di.count(cql) == base.count(cql), cql
        np.testing.assert_array_equal(
            di.query(cql).fids, base.query(cql).fids
        )


def test_empty_and_tiny_types():
    """Padding edges: an empty type and a type smaller than the shard
    count (every shard but one empty) stage and answer."""
    store = MemoryDataStore()
    _write(store, "tiny", np.array([2.35, 100.0, -74.0]),
           np.array([48.85, 10.0, 40.7]),
           np.full(3, T0))
    store.create_schema("empty", "dtg:Date,*geom:Point:srid=4326")
    base = DeviceIndex(store, "tiny", z_planes=True)
    di = ShardedDeviceIndex(store, "tiny", mesh=make_mesh(8))
    assert len(di) == 3
    assert di.count("BBOX(geom, 0, 40, 10, 55)") == 1
    np.testing.assert_array_equal(
        di.query("INCLUDE").fids, base.query("INCLUDE").fids
    )
    de = ShardedDeviceIndex(store, "empty", mesh=make_mesh(8))
    assert len(de) == 0
    assert de.count("INCLUDE") == 0
