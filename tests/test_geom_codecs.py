"""GeoHash + WKB/TWKB codec tests (ref geomesa-utils geohash/WKBUtils)."""

import numpy as np
import pytest

from geomesa_tpu.geom import parse_wkt
from geomesa_tpu.geom.geohash import (
    bbox_geohashes,
    decode,
    decode_bbox,
    encode,
    neighbors,
)
from geomesa_tpu.geom.wkb import from_twkb, from_wkb, to_twkb, to_wkb
from geomesa_tpu.geom.wkt import to_wkt

WKTS = [
    "POINT (2.3488 48.8534)",
    "LINESTRING (0 0, 1.5 1.5, 3 0)",
    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))",
    "MULTIPOINT (1 2, -3 -4)",
    "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))",
    "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((5 5, 6 5, 6 6, 5 6, 5 5)))",
]


class TestGeoHash:
    # canonical vectors (public geohash test values)
    @pytest.mark.parametrize(
        "lon,lat,gh",
        [
            (-5.6, 42.6, "ezs42"),
            (2.3488, 48.8534, "u09tvmq"),
            (-122.4194, 37.7749, "9q8yyk8"),
            (0.0, 0.0, "s0000"),
        ],
    )
    def test_known_hashes(self, lon, lat, gh):
        assert encode(lon, lat, precision=len(gh)) == gh

    def test_vectorized_matches_scalar(self, rng):
        lon = rng.uniform(-180, 180, 200)
        lat = rng.uniform(-90, 90, 200)
        vec = encode(lon, lat, 8)
        for i in range(0, 200, 17):
            assert vec[i] == encode(float(lon[i]), float(lat[i]), 8)

    def test_decode_contains_point(self, rng):
        for _ in range(50):
            lon = float(rng.uniform(-180, 180))
            lat = float(rng.uniform(-90, 90))
            gh = encode(lon, lat, 9)
            (lo0, lo1), (la0, la1) = decode_bbox(gh)
            assert lo0 <= lon <= lo1 and la0 <= lat <= la1
            clon, clat = decode(gh)
            assert abs(clon - lon) < 1e-3 and abs(clat - lat) < 1e-3

    def test_neighbors(self):
        ns = neighbors("u09tvmq")
        assert len(ns) == 8
        assert "u09tvmq" not in ns
        # all neighbors share the 4-char prefix at this precision
        assert all(n.startswith("u09t") for n in ns)

    def test_invalid_char(self):
        with pytest.raises(ValueError):
            decode_bbox("abcl")  # 'l' is not base-32

    def test_bbox_cover(self):
        cells = bbox_geohashes(2.0, 48.0, 3.0, 49.0, 4)
        assert encode(2.3488, 48.8534, 4) in cells
        # every cell intersects the box
        for gh in cells:
            (lo0, lo1), (la0, la1) = decode_bbox(gh)
            assert lo1 >= 2.0 and lo0 <= 3.0 and la1 >= 48.0 and la0 <= 49.0


class TestWkb:
    @pytest.mark.parametrize("wkt", WKTS)
    def test_round_trip(self, wkt):
        g = parse_wkt(wkt)
        assert to_wkt(from_wkb(to_wkb(g))) == to_wkt(g)

    def test_big_endian_read(self):
        # hand-built big-endian POINT(1 2)
        import struct

        data = b"\x00" + struct.pack(">I", 1) + struct.pack(">dd", 1.0, 2.0)
        g = from_wkb(data)
        assert (g.x, g.y) == (1.0, 2.0)


class TestTwkb:
    @pytest.mark.parametrize("wkt", WKTS)
    def test_round_trip_at_precision(self, wkt):
        g = parse_wkt(wkt)
        back = from_twkb(to_twkb(g, precision=7))
        assert to_wkt(back) == to_wkt(g)  # coords are sub-precision ints

    def test_compact_vs_wkb(self, rng):
        coords = np.cumsum(rng.uniform(-0.001, 0.001, (500, 2)), axis=0) + [
            2.0,
            48.0,
        ]
        from geomesa_tpu.geom.base import LineString

        g = LineString(np.round(coords, 6))
        assert len(to_twkb(g, 6)) < len(to_wkb(g)) / 3  # delta varints win

    def test_precision_rounding(self):
        from geomesa_tpu.geom.base import Point

        g = Point(1.23456789, -9.87654321)
        back = from_twkb(to_twkb(g, precision=4))
        assert back.x == pytest.approx(1.2346, abs=1e-9)
        assert back.y == pytest.approx(-9.8765, abs=1e-9)
