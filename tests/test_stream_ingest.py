"""Streaming live layer (ISSUE 10): WAL semantics, live-merge parity,
backpressure, recovery, incremental resident refresh and the serving
endpoints.

The contracts under test:

- WAL: an acked record survives anything; a torn tail truncates at the
  last valid checksum; rotation seals segments; ``truncate_through``
  GC's only wholly-compacted segments; interior damage raises loudly.
- Live merge: (resident ⊎ memtable ⊎ mid-compaction) answers are
  IDENTICAL to the same data batch-flushed — query/count/density/stats,
  visibility labels included.
- Backpressure: at ``wal.max.generations`` live runs, appends shed
  429-style instead of growing read amplification unboundedly.
- Recovery: reopen serves exactly the acked rows; replay is idempotent
  and watermark-guarded.
"""

import json
import os
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.stream import (
    IngestBackpressureError,
    StreamingStore,
)
from geomesa_tpu.store.wal import WalCorruption, WriteAheadLog

SPEC = "val:Int,dtg:Date,*geom:Point:srid=4326"


def _rows(n, seed, fid0=0):
    rng = np.random.default_rng(seed)
    cols = {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(0, 10**9, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    return cols, np.arange(fid0, fid0 + n)


def _store(tmp_path, n0=400, name="store"):
    ds = FileSystemDataStore(str(tmp_path / name), partition_size=128)
    ds.create_schema("t", SPEC)
    if n0:
        cols, fids = _rows(n0, seed=1)
        ds.write("t", cols, fids=fids)
        ds.flush("t")
    return ds


# -- WAL unit tests ----------------------------------------------------------


def test_wal_append_replay_roundtrip(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    payloads = [f"record-{i}".encode() * (i + 1) for i in range(10)]
    seqs = [wal.append(p) for p in payloads]
    assert seqs == list(range(10))
    wal.close()
    wal2 = WriteAheadLog(str(tmp_path / "wal"))
    got = list(wal2.replay())
    assert [s for s, _ in got] == seqs
    assert [p for _, p in got] == payloads
    # after_seq skips the already-compacted prefix
    assert [s for s, _ in wal2.replay(after_seq=6)] == [7, 8, 9]
    # new appends continue the sequence
    assert wal2.append(b"x") == 10
    wal2.close()


def test_wal_torn_tail_truncated(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    for i in range(5):
        wal.append(f"rec-{i}".encode())
    wal.close()
    [seg] = wal.segments()
    size = os.path.getsize(seg)
    with open(seg, "ab") as fh:  # a crash mid-append: half a record
        fh.write(b"\x41\x57\x4d\x47garbage-torn-tail")
    wal2 = WriteAheadLog(str(tmp_path / "wal"))
    got = list(wal2.replay())
    assert [s for s, _ in got] == list(range(5))
    assert wal2.truncations == 1
    assert os.path.getsize(seg) == size  # cut back to the valid prefix
    # the next append lands cleanly after the truncation point
    assert wal2.append(b"after") == 5
    assert [s for s, _ in wal2.replay()] == [0, 1, 2, 3, 4, 5]
    wal2.close()


def test_wal_corrupt_record_payload_truncates_at_damage(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"))
    for i in range(3):
        wal.append(b"x" * 64)
    wal.close()
    [seg] = wal.segments()
    data = bytearray(open(seg, "rb").read())
    data[-10] ^= 0xFF  # flip a payload byte of the LAST record
    open(seg, "wb").write(bytes(data))
    wal2 = WriteAheadLog(str(tmp_path / "wal"))
    assert [s for s, _ in wal2.replay()] == [0, 1]  # bad crc = torn tail
    assert wal2.truncations == 1
    wal2.close()


def test_wal_rotation_and_truncate_through(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"), segment_bytes=1 << 12)
    for i in range(40):
        wal.append(b"p" * 512)
    assert len(wal.segments()) > 2
    nseg = len(wal.segments())
    # GC everything below seq 20: only sealed, wholly-consumed segments
    removed = wal.truncate_through(20)
    assert removed >= 1
    assert len(wal.segments()) == nseg - removed
    survivors = [s for s, _ in wal.replay()]
    # nothing above the truncation watermark may be lost
    assert set(range(21, 40)) <= set(survivors)
    wal.close()


def test_wal_interior_damage_raises(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"), segment_bytes=1 << 12)
    for i in range(40):
        wal.append(b"p" * 512)
    wal.close()
    first = wal.segments()[0]
    data = bytearray(open(first, "rb").read())
    data[20] ^= 0xFF
    open(first, "wb").write(bytes(data))
    with pytest.raises(WalCorruption):
        WriteAheadLog(str(tmp_path / "wal"))


# -- live-merge parity -------------------------------------------------------


def _twin(tmp_path, batches):
    """A batch-flushed twin store holding seed + every streamed batch."""
    ds = _store(tmp_path, name="twin")
    for cols, fids in batches:
        ds.write("t", dict(cols), fids=fids)
    if batches:
        ds.flush("t")
    return ds


FILTERS = [
    "INCLUDE",
    "BBOX(geom, -90, -45, 90, 45)",
    "BBOX(geom, -180, -90, 0, 90) AND val < 50",
    "val >= 25 AND val < 75",
]


def test_live_merge_parity_query_count(tmp_path):
    """Property-style parity: N appends of varying sizes through the
    live layer answer every filter identically to the same rows
    batch-flushed — while the memtable holds them, mid-compaction, and
    after full compaction."""
    with prop_override("stream.run.rows", 128), \
            prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path)
        layer = StreamingStore(ds)
        rng = np.random.default_rng(7)
        batches = []
        fid0 = 10_000
        for i in range(6):
            n = int(rng.integers(10, 200))
            cols, fids = _rows(n, seed=100 + i, fid0=fid0)
            fid0 += n
            batches.append((cols, fids))
            layer.append("t", cols, fids=fids)
        twin = _twin(tmp_path, batches)
        assert layer.stream_stats()["types"]["t"]["memtable_rows"] > 0

        def check():
            for f in FILTERS:
                got = layer.query("t", f).batch
                want = twin.query("t", f).batch
                assert sorted(map(int, got.fids)) == \
                    sorted(map(int, want.fids)), f
                assert layer.count("t", f) == len(want), f

        check()  # memtable live
        layer.compact_now("t")
        assert layer.stream_stats()["types"]["t"]["memtable_rows"] == 0
        check()  # fully compacted
        # appends after a compaction merge with the new generation
        cols, fids = _rows(50, seed=999, fid0=90_000)
        layer.append("t", cols, fids=fids)
        batches.append((cols, fids))
        twin2 = _twin(tmp_path / "b", batches)
        for f in FILTERS:
            assert layer.count("t", f) == len(twin2.query("t", f)), f
        layer.close()


def test_live_merge_density_and_stats_parity(tmp_path):
    from geomesa_tpu.process import run_stats
    from geomesa_tpu.process.density import density
    from geomesa_tpu.geom import Envelope

    with prop_override("stream.memtable.rows", 1 << 20), \
            prop_override("store.chunk.pushdown", False):
        ds = _store(tmp_path)
        layer = StreamingStore(ds)
        batches = []
        for i in range(3):
            cols, fids = _rows(120, seed=200 + i, fid0=10_000 + i * 1000)
            batches.append((cols, fids))
            layer.append("t", cols, fids=fids)
        twin = _twin(tmp_path, batches)
        env = Envelope(-180, -90, 180, 90)
        g1 = density(layer, "t", "INCLUDE", env, 64, 32, use_device=False)
        g2 = density(twin, "t", "INCLUDE", env, 64, 32, use_device=False)
        assert np.array_equal(g1, g2)
        s1 = run_stats(layer, "t", "INCLUDE", "Count();MinMax('val')")
        s2 = run_stats(twin, "t", "INCLUDE", "Count();MinMax('val')")
        assert s1.to_json() == s2.to_json()
        layer.close()


def test_live_merge_visibility_labels(tmp_path):
    """Labeled streamed rows hide without auths and serve with them —
    identical to the batch path."""
    from geomesa_tpu.query.plan import Query

    with prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path, n0=0)
        layer = StreamingStore(ds)
        cols, fids = _rows(40, seed=5, fid0=100)
        batch_cols = dict(cols)
        batch_cols["__vis__"] = np.array(
            ["secret"] * 20 + [""] * 20, dtype=object
        )
        layer.append("t", batch_cols, fids=fids)
        public = layer.query("t", Query(filter="INCLUDE"))
        assert len(public) == 20  # labeled rows hidden, fail closed
        cleared = layer.query(
            "t", Query(filter="INCLUDE", hints={"auths": ("secret",)})
        )
        assert len(cleared) == 40
        # parity holds through compaction
        layer.compact_now("t")
        assert len(layer.query("t", Query(filter="INCLUDE"))) == 20
        layer.close()


def test_mid_compaction_consistency(tmp_path):
    """A query racing repeated compactions must never double-count or
    miss rows: sampled counts are exactly the monotone acked totals."""
    with prop_override("stream.run.rows", 64), \
            prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path, n0=100)
        layer = StreamingStore(ds)
        seen, errors = [], []
        stop = threading.Event()

        def sampler():
            try:
                while not stop.is_set():
                    seen.append(layer.count("t"))
            except Exception as e:  # pragma: no cover - fails the test
                errors.append(e)

        th = threading.Thread(target=sampler)
        th.start()
        total = 100
        valid = {total}
        try:
            for i in range(8):
                cols, fids = _rows(64, seed=300 + i, fid0=50_000 + i * 100)
                layer.append("t", cols, fids=fids)
                total += 64
                valid.add(total)
                if i % 2:
                    layer.compact_now("t")
        finally:
            stop.set()
            th.join()
        assert not errors
        assert seen, "sampler never ran"
        assert set(seen) <= valid, sorted(set(seen) - valid)
        # monotone: a later sample never loses rows an earlier one had
        assert seen == sorted(seen)
        assert layer.count("t") == total
        layer.close()


def test_pushdown_gated_while_memtable_live(tmp_path):
    with prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path)
        layer = StreamingStore(ds)
        assert layer.has_chunk_stats("t")  # nothing streamed yet
        cols, fids = _rows(10, seed=9, fid0=10_000)
        layer.append("t", cols, fids=fids)
        # pre-aggregates cannot see the memtable: decline, don't lie
        assert not layer.has_chunk_stats("t")
        from geomesa_tpu.geom import Envelope

        assert layer.density_pushdown(
            "t", "INCLUDE", Envelope(-180, -90, 180, 90), 8, 8
        ) is None
        layer.compact_now("t")
        assert layer.has_chunk_stats("t")
        layer.close()


# -- backpressure ------------------------------------------------------------


def test_backpressure_at_max_generations(tmp_path):
    from geomesa_tpu import metrics

    with prop_override("wal.max.generations", 2), \
            prop_override("stream.run.rows", 8), \
            prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path, n0=0)
        layer = StreamingStore(ds)
        before = metrics.stream_backpressure.value()
        cols, fids = _rows(8, seed=1, fid0=0)
        layer.append("t", cols, fids=fids)
        cols, fids = _rows(8, seed=2, fid0=100)
        layer.append("t", cols, fids=fids)
        with pytest.raises(IngestBackpressureError) as ei:
            cols, fids = _rows(8, seed=3, fid0=200)
            layer.append("t", cols, fids=fids)
        assert ei.value.retry_after_s > 0
        assert metrics.stream_backpressure.value() == before + 1
        # nothing was acked for the shed append
        assert layer.count("t") == 16
        # compaction clears the bound and appends flow again
        layer.compact_now("t")
        cols, fids = _rows(8, seed=3, fid0=200)
        layer.append("t", cols, fids=fids)
        assert layer.count("t") == 24
        layer.close()


def test_failed_compaction_unseals_runs_and_rolls_back(tmp_path):
    """A pre-publish flush failure must leave the memtable EXACTLY as
    it was: runs un-sealed (tail coalescing keeps working — a sealed
    leftover would pin every later append into its own run and race
    the 429 bound), the merged batch out of pending, the watermark
    restored, and every row still served."""
    from geomesa_tpu.failpoints import FailpointError, failpoint_override

    with prop_override("stream.memtable.rows", 1 << 20), \
            prop_override("stream.run.rows", 1 << 20):
        ds = _store(tmp_path)
        layer = StreamingStore(ds)
        cols, fids = _rows(40, seed=31, fid0=10_000)
        layer.append("t", cols, fids=fids)
        wm0 = ds._types["t"].wal_watermark
        with failpoint_override("fail.flush.before_publish", "raise"):
            with pytest.raises(FailpointError):
                layer.compact_now("t")
        assert layer.count("t") == 440  # rows still served
        assert ds._types["t"].wal_watermark == wm0  # rolled back
        assert not ds._types["t"].pending  # merged batch stripped
        runs = layer._runs_snapshot("t")
        assert runs and not any(r.sealed for r in runs)
        # tail coalescing still works: the next append must NOT open a
        # new run (run target is huge)
        cols, fids = _rows(10, seed=32, fid0=20_000)
        layer.append("t", cols, fids=fids)
        assert len(layer._runs_snapshot("t")) == len(runs)
        # and a clean retry compacts everything
        layer.compact_now("t")
        assert layer.count("t") == 450
        assert layer.stream_stats()["types"]["t"]["memtable_rows"] == 0
        layer.close()


def test_stall_trigger_does_not_deadlock_append(tmp_path):
    """The ingest-stall flight trigger fires with the memtable lock
    RELEASED — its bundle providers re-take that lock (stream_stats),
    and firing under it wedged the appender forever."""
    from geomesa_tpu import slo

    with prop_override("wal.max.generations", 1), \
            prop_override("stream.run.rows", 4), \
            prop_override("stream.stall.s", 0.001), \
            prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path, n0=0)
        layer = StreamingStore(ds)
        slo.FLIGHTREC.configure(
            str(tmp_path / "_flightrec"),
            providers={"stream": layer.stream_stats},
        )
        try:
            cols, fids = _rows(4, seed=1, fid0=0)
            layer.append("t", cols, fids=fids)
            done = []

            def shed_append():
                cols, fids = _rows(4, seed=2, fid0=100)
                with pytest.raises(IngestBackpressureError):
                    layer.append("t", cols, fids=fids)
                done.append(True)

            th = threading.Thread(target=shed_append, daemon=True)
            th.start()
            th.join(timeout=20)
            assert done, "backpressured append deadlocked on the " \
                "flight-recorder providers"
            bundles = os.listdir(str(tmp_path / "_flightrec"))
            assert any("ingest-stall" in b for b in bundles), bundles
        finally:
            slo.FLIGHTREC.configure(None)
            slo.FLIGHTREC.providers.pop("stream", None)
            layer.close()


# -- recovery ----------------------------------------------------------------


def test_recovery_replays_acked_rows(tmp_path):
    with prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path)
        layer = StreamingStore(ds)
        for i in range(3):
            cols, fids = _rows(50, seed=400 + i, fid0=10_000 + i * 100)
            layer.append("t", cols, fids=fids)
        layer.close()  # no compaction: the WAL alone carries the rows

        ds2 = FileSystemDataStore(str(tmp_path / "store"), partition_size=128)
        layer2 = StreamingStore(ds2)
        assert layer2.count("t") == 400 + 150
        st = layer2.stream_stats()["types"]["t"]
        assert st["memtable_rows"] == 150
        layer2.close()
        # replay is idempotent: a third open serves the same set
        ds3 = FileSystemDataStore(str(tmp_path / "store"), partition_size=128)
        layer3 = StreamingStore(ds3)
        assert layer3.count("t") == 550
        batch = layer3.query("t").batch
        assert len(batch) == len({int(f) for f in batch.fids})
        layer3.close()


def test_recovery_skips_compacted_segments_via_watermark(tmp_path):
    """A compaction that published but crashed before WAL truncation
    (simulated with a raising failpoint) must NOT re-apply its rows at
    the next open — the manifest watermark skips them."""
    from geomesa_tpu.failpoints import FailpointError, failpoint_override

    with prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path)
        layer = StreamingStore(ds)
        cols, fids = _rows(60, seed=11, fid0=10_000)
        layer.append("t", cols, fids=fids)
        with failpoint_override("fail.compact.publish", "raise"):
            with pytest.raises(FailpointError):
                layer.compact_now("t")
        # published: the memtable dropped the runs, the WAL kept them
        assert layer.count("t") == 460
        assert layer.stream_stats()["types"]["t"]["memtable_rows"] == 0
        assert layer._ts("t").wal.stats()["segments"] >= 1
        layer.close()

        ds2 = FileSystemDataStore(str(tmp_path / "store"), partition_size=128)
        layer2 = StreamingStore(ds2)
        assert layer2.count("t") == 460  # not 520: replay skipped them
        assert layer2.stream_stats()["types"]["t"]["memtable_rows"] == 0
        layer2.close()


def test_recovery_truncates_torn_tail_and_stamps(tmp_path):
    from geomesa_tpu import metrics

    with prop_override("stream.memtable.rows", 1 << 20):
        ds = _store(tmp_path, n0=0)
        layer = StreamingStore(ds)
        for i in range(2):
            cols, fids = _rows(30, seed=500 + i, fid0=i * 100)
            layer.append("t", cols, fids=fids)
        layer.close()
        wal_dir = str(tmp_path / "store" / "t" / "_wal")
        seg = sorted(os.listdir(wal_dir))[-1]
        with open(os.path.join(wal_dir, seg), "ab") as fh:
            fh.write(b"GMWA-half-a-record")  # the crash's torn tail
        before = metrics.stream_wal_truncations.value()
        ds2 = FileSystemDataStore(str(tmp_path / "store"), partition_size=128)
        layer2 = StreamingStore(ds2)
        assert layer2.count("t") == 60  # acked rows intact
        assert metrics.stream_wal_truncations.value() == before + 1
        layer2.close()


# -- incremental resident refresh -------------------------------------------


def test_streaming_device_index_delta_refresh(tmp_path):
    from geomesa_tpu import metrics
    from geomesa_tpu.device_cache import StreamingDeviceIndex

    ds = _store(tmp_path)
    # capacity headroom: the delta path needs free padded slots (the
    # server's streaming wiring sizes this from stream.memtable.rows)
    di = StreamingDeviceIndex(ds, "t", z_planes=True, capacity=2048)
    n0 = len(di)
    restages0 = di.restages
    cols, fids = _rows(64, seed=21, fid0=10_000)
    from geomesa_tpu.features.batch import FeatureBatch

    batch = FeatureBatch.from_columns(ds.get_schema("t"), cols, fids)
    before = metrics.stream_delta_refreshes.value(mode="delta")
    mode = di.refresh_delta(batch)
    assert mode == "delta"
    assert di.restages == restages0  # no restage on the ack path
    assert len(di) == n0 + 64
    assert di.count("INCLUDE") == n0 + 64
    assert metrics.stream_delta_refreshes.value(mode="delta") == before + 1


def test_base_device_index_delta_falls_back_to_restage(tmp_path):
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.store.stream import StreamingStore as _SS

    ds = _store(tmp_path)
    layer = _SS(ds)
    di = DeviceIndex(layer, "t")
    cols, fids = _rows(16, seed=22, fid0=10_000)
    layer.append("t", cols, fids=fids)  # the layer's merged view
    batch = FeatureBatch.from_columns(ds.get_schema("t"), cols, fids)
    assert di.refresh_delta(batch) == "restage"
    assert di.count("INCLUDE") == 416  # restaged THROUGH the merged view
    layer.close()


@pytest.mark.skipif(
    len(__import__("jax").devices()) < 2,
    reason="sharded-mesh delta refresh needs > 1 device "
    "(XLA_FLAGS=--xla_force_host_platform_device_count=8)",
)
def test_sharded_index_delta_refresh_parity(tmp_path):
    """Mesh path: streamed appends land in the reserved tail slots
    behind the validity plane — no restage — and answers match the
    single-chip oracle."""
    from geomesa_tpu.device_cache import ShardedDeviceIndex
    from geomesa_tpu.features.batch import FeatureBatch

    ds = _store(tmp_path)
    di = ShardedDeviceIndex(ds, "t", z_planes=True, reserve_rows=4096)
    n0 = len(ds.query("t"))
    cols, fids = _rows(100, seed=23, fid0=10_000)
    batch = FeatureBatch.from_columns(ds.get_schema("t"), cols, fids)
    mode = di.refresh_delta(batch)
    assert mode == "delta"
    assert di.count("INCLUDE") == n0 + 100
    f = "BBOX(geom, -90, -45, 90, 45)"
    ds2 = _store(tmp_path, name="twin")
    ds2.write("t", cols, fids=fids)
    ds2.flush("t")
    assert di.count(f) == len(ds2.query("t", f))
    got = di.query(f)
    assert sorted(map(int, got.fids)) == sorted(
        map(int, ds2.query("t", f).batch.fids)
    )
    # reserve exhaustion falls back to a full restage, still exact —
    # the restage reads the backing store (in production the streaming
    # layer's merged view, which still holds every acked row)
    big_cols, big_fids = _rows(8192, seed=24, fid0=50_000)
    big = FeatureBatch.from_columns(ds.get_schema("t"), big_cols, big_fids)
    assert di.refresh_delta(big) == "restage"
    assert di.count("INCLUDE") == n0


# -- serving endpoints -------------------------------------------------------


@pytest.fixture
def stream_server(tmp_path):
    from geomesa_tpu.server import serve_background

    ds = _store(tmp_path)
    with prop_override("stream.memtable.rows", 1 << 20):
        server, _ = serve_background(
            ds, resident=True, sched=True, stream=True
        )
        host, port = server.server_address[:2]
        yield f"http://{host}:{port}", server
        server.shutdown()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=120) as r:
        return json.loads(r.read())


def _post(base, path, doc):
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def test_server_append_visible_within_one_roundtrip(stream_server):
    base, server = stream_server
    assert _get(base, "/count/t")["count"] == 400  # stages resident
    out = _post(base, "/append/t", {
        "columns": {
            "val": [1, 2, 3],
            "dtg": [1000, 2000, 3000],
            "geom": [[10.0, 10.0], [11.0, 11.0], [12.0, 12.0]],
        },
        "fids": [9001, 9002, 9003],
    })
    assert out == {"acked": 3, "seq": 0}
    # the VERY NEXT read serves the rows — no flush/restage happened
    assert _get(base, "/count/t")["count"] == 403
    cql = urllib.parse.quote("BBOX(geom, 9, 9, 13, 13)")
    feats = _get(base, f"/features/t?cql={cql}")
    ids = {f["id"] for f in feats["features"]}
    assert {"9001", "9002", "9003"} <= ids
    # and the streaming state is inspectable
    ss = _get(base, "/stats/stream")
    assert ss["types"]["t"]["memtable_rows"] == 3
    assert ss["types"]["t"]["appended_rows"] == 3
    assert ss["counters"]["appends"] >= 1  # process-global counter
    assert "stream" in _get(base, "/stats")


def test_server_append_backpressure_is_429(stream_server):
    base, server = stream_server
    doc = {"columns": {
        "val": [1] * 8,
        "dtg": [1000] * 8,
        "geom": [[1.0, 1.0]] * 8,
    }}
    with prop_override("wal.max.generations", 1), \
            prop_override("stream.run.rows", 8):
        doc["fids"] = list(range(9100, 9108))
        _post(base, "/append/t", doc)
        with pytest.raises(urllib.error.HTTPError) as ei:
            doc["fids"] = list(range(9200, 9208))
            _post(base, "/append/t", doc)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1


def test_server_append_errors(stream_server):
    base, server = stream_server
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base, "/append/nosuch", {"columns": {"val": [1]}})
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base, "/append/t", {"nope": 1})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base, "/nosuch/t", {"columns": {"val": [1]}})
    assert ei.value.code == 404


def test_server_append_body_bound_413(stream_server):
    base, server = stream_server
    doc = {"columns": {
        "val": [1] * 64, "dtg": [1] * 64, "geom": [[0.0, 0.0]] * 64,
    }, "fids": list(range(9300, 9364))}
    with prop_override("stream.append.max.bytes", 64):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, "/append/t", doc)
        assert ei.value.code == 413
    # nothing was acked for the refused body
    assert _get(base, "/stats/stream")["types"] \
        .get("t", {}).get("memtable_rows", 0) == 0
    _post(base, "/append/t", doc)  # under the default bound: acked
    assert _get(base, "/count/t")["count"] == 464


def test_server_append_ledger_fields(stream_server):
    base, server = stream_server
    _post(base, "/append/t", {
        "columns": {
            "val": [7], "dtg": [123], "geom": [[5.0, 5.0]],
        },
        "fids": [9500],
    })
    led = _get(base, "/stats/ledger")
    fields: dict = {}
    for doc in (led.get("tenants") or {}).values():
        for k, v in (doc.get("cost") or {}).items():
            fields[k] = fields.get(k, 0) + v
    assert fields.get("wal_bytes", 0) > 0
    assert fields.get("memtable_rows", 0) >= 1
