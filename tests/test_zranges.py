"""zranges decomposition properties: exact cover, sortedness, budget."""

import numpy as np

from geomesa_tpu.curves.zorder import encode_py
from geomesa_tpu.curves.zranges import zranges


def brute_force_cover(qlo, qhi, bits):
    """All z values whose cell is in the box (tiny spaces only)."""
    dims = len(qlo)
    zs = set()
    import itertools

    axes = [range(qlo[d], qhi[d] + 1) for d in range(dims)]
    for coords in itertools.product(*axes):
        zs.add(encode_py(coords, bits))
    return zs


def ranges_cover(ranges):
    zs = set()
    for r in ranges:
        zs.update(range(r.lower, r.upper + 1))
    return zs


def test_exact_cover_small_2d():
    for qlo, qhi in [((1, 2), (6, 5)), ((0, 0), (7, 7)), ((3, 3), (3, 3))]:
        ranges = zranges(qlo, qhi, bits_per_dim=3, max_ranges=1000)
        expected = brute_force_cover(qlo, qhi, 3)
        assert ranges_cover(ranges) == expected  # tight when budget is ample


def test_exact_cover_small_3d():
    qlo, qhi = (1, 0, 2), (3, 3, 3)
    ranges = zranges(qlo, qhi, bits_per_dim=2, max_ranges=1000)
    assert ranges_cover(ranges) == brute_force_cover(qlo, qhi, 2)


def test_overcover_with_budget():
    qlo, qhi = (1, 2), (6, 5)
    full = brute_force_cover(qlo, qhi, 3)
    ranges = zranges(qlo, qhi, bits_per_dim=3, max_ranges=3)
    assert len(ranges) <= 3
    assert ranges_cover(ranges) >= full  # never under-covers


def test_sorted_disjoint():
    ranges = zranges((5, 9), (900, 700), bits_per_dim=10, max_ranges=64)
    for a, b in zip(ranges, ranges[1:]):
        assert a.upper < b.lower  # disjoint and sorted with gaps


def test_budget_respected_large():
    ranges = zranges(
        (0, 0, 0), ((1 << 21) - 1, (1 << 21) - 1, 1000), 21, max_ranges=2000
    )
    assert len(ranges) <= 2000


def test_full_space_is_single_range():
    ranges = zranges((0, 0), (7, 7), bits_per_dim=3)
    assert len(ranges) == 1
    assert (ranges[0].lower, ranges[0].upper) == (0, 63)
    assert ranges[0].contained


def test_contained_flag():
    ranges = zranges((0, 0), (3, 1), bits_per_dim=2, max_ranges=100)
    # box x[0..3], y[0..1]: y bit 1 == 0 -> z bit 3 == 0 -> z 0..7 contiguous
    assert [(r.lower, r.upper, r.contained) for r in ranges] == [(0, 7, True)]
    # box x[0..1], y[0..3]: x bit 1 == 0 -> z bit 2 == 0 -> z 0..3 and 8..11
    ranges = zranges((0, 0), (1, 3), bits_per_dim=2, max_ranges=100)
    assert [(r.lower, r.upper, r.contained) for r in ranges] == [
        (0, 3, True),
        (8, 11, True),
    ]
