"""Morton kernels vs the pure-Python oracle (bit-identical)."""

import numpy as np

from geomesa_tpu.curves import zorder


def test_encode_2d_matches_oracle(rng):
    x = rng.integers(0, 1 << 31, size=1000, dtype=np.uint64)
    y = rng.integers(0, 1 << 31, size=1000, dtype=np.uint64)
    z = zorder.encode_2d_np(x, y)
    for i in range(0, 1000, 37):
        assert int(z[i]) == zorder.encode_py((int(x[i]), int(y[i])), 31)


def test_roundtrip_2d(rng):
    x = rng.integers(0, 1 << 31, size=10000, dtype=np.uint64)
    y = rng.integers(0, 1 << 31, size=10000, dtype=np.uint64)
    dx, dy = zorder.decode_2d_np(zorder.encode_2d_np(x, y))
    np.testing.assert_array_equal(dx, x)
    np.testing.assert_array_equal(dy, y)


def test_encode_3d_matches_oracle(rng):
    x = rng.integers(0, 1 << 21, size=1000, dtype=np.uint64)
    y = rng.integers(0, 1 << 21, size=1000, dtype=np.uint64)
    t = rng.integers(0, 1 << 21, size=1000, dtype=np.uint64)
    z = zorder.encode_3d_np(x, y, t)
    for i in range(0, 1000, 37):
        assert int(z[i]) == zorder.encode_py(
            (int(x[i]), int(y[i]), int(t[i])), 21
        )


def test_roundtrip_3d(rng):
    x = rng.integers(0, 1 << 21, size=10000, dtype=np.uint64)
    y = rng.integers(0, 1 << 21, size=10000, dtype=np.uint64)
    t = rng.integers(0, 1 << 21, size=10000, dtype=np.uint64)
    dx, dy, dt = zorder.decode_3d_np(zorder.encode_3d_np(x, y, t))
    np.testing.assert_array_equal(dx, x)
    np.testing.assert_array_equal(dy, y)
    np.testing.assert_array_equal(dt, t)


def test_monotone_ordering_along_dims():
    # z-order preserves per-dim ordering when other dims fixed
    x = np.arange(100, dtype=np.uint64)
    z = zorder.encode_2d_np(x, np.zeros(100, dtype=np.uint64))
    assert np.all(np.diff(z.astype(np.int64)) > 0)


def test_jax_2d_hi_lo_matches_np(rng):
    import jax.numpy as jnp

    x = rng.integers(0, 1 << 31, size=2048, dtype=np.int64)
    y = rng.integers(0, 1 << 31, size=2048, dtype=np.int64)
    hi, lo = zorder.encode_2d_jax(jnp.asarray(x), jnp.asarray(y))
    z = zorder.encode_2d_np(x.astype(np.uint64), y.astype(np.uint64))
    np.testing.assert_array_equal(np.asarray(hi, dtype=np.uint64), z >> np.uint64(32))
    np.testing.assert_array_equal(
        np.asarray(lo, dtype=np.uint64), z & np.uint64(0xFFFFFFFF)
    )


def test_jax_3d_hi_lo_matches_np(rng):
    import jax.numpy as jnp

    x = rng.integers(0, 1 << 21, size=2048, dtype=np.int64)
    y = rng.integers(0, 1 << 21, size=2048, dtype=np.int64)
    t = rng.integers(0, 1 << 21, size=2048, dtype=np.int64)
    hi, lo = zorder.encode_3d_hi_lo_jax(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(t)
    )
    z = zorder.encode_3d_np(
        x.astype(np.uint64), y.astype(np.uint64), t.astype(np.uint64)
    )
    np.testing.assert_array_equal(np.asarray(hi, dtype=np.uint64), z >> np.uint64(32))
    np.testing.assert_array_equal(
        np.asarray(lo, dtype=np.uint64), z & np.uint64(0xFFFFFFFF)
    )


def test_jax_3d_u64_matches_np(rng):
    import jax.numpy as jnp

    x = rng.integers(0, 1 << 21, size=2048, dtype=np.int64)
    y = rng.integers(0, 1 << 21, size=2048, dtype=np.int64)
    t = rng.integers(0, 1 << 21, size=2048, dtype=np.int64)
    z = zorder.encode_3d_jax(jnp.asarray(x), jnp.asarray(y), jnp.asarray(t))
    z_np = zorder.encode_3d_np(
        x.astype(np.uint64), y.astype(np.uint64), t.astype(np.uint64)
    )
    np.testing.assert_array_equal(np.asarray(z, dtype=np.uint64), z_np)
