"""Seeded differential fuzz: random ECQL filters (bbox/during/attribute
clauses under AND/OR/NOT) evaluated on every store implementation must
match the host oracle's exact result set. A longer ad-hoc run (300
filters x 3 stores) passes clean; this seeded slice guards the property
in CI time."""

import random

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.kv import KVDataStore, MemoryKV
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,val:Int,score:Double,dtg:Date,*geom:Point:srid=4326"
N = 4000
N_FILTERS = 40

T0 = parse_instant("2020-01-01T00:00:00")
T1 = parse_instant("2020-04-01T00:00:00")


def _data():
    rng = np.random.default_rng(99)
    return {
        "name": rng.choice(["a", "b", "c", "d"], N),
        "val": rng.integers(-50, 50, N),
        "score": rng.normal(0, 10, N),
        "dtg": rng.integers(T0, T1, N),
        "geom": np.stack(
            [rng.uniform(-180, 180, N), rng.uniform(-90, 90, N)], axis=1
        ),
    }


def _rand_filter(r: random.Random, depth=0) -> str:
    def bbox():
        x0, y0 = r.uniform(-180, 170), r.uniform(-90, 80)
        return (
            f"BBOX(geom, {x0:.3f}, {y0:.3f}, "
            f"{x0 + r.uniform(0.1, 120):.3f}, {y0 + r.uniform(0.1, 60):.3f})"
        )

    def during():
        import datetime

        a = r.randint(T0, T1 - 1)
        b = r.randint(a, T1)
        f = lambda ms: datetime.datetime.fromtimestamp(  # noqa: E731
            ms / 1000, datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ")
        return f"dtg DURING {f(a)}/{f(b)}"

    def attr():
        return r.choice(
            [
                f"val >= {r.randint(-50, 50)}",
                f"val BETWEEN {r.randint(-50, 0)} AND {r.randint(0, 50)}",
                f"name = '{r.choice('abcd')}'",
                f"name IN ('{r.choice('abcd')}', '{r.choice('abcd')}')",
                f"score < {r.uniform(-15, 15):.2f}",
                f"val <> {r.randint(-50, 50)}",
            ]
        )

    def spatial():
        # polygon intersects (device point-in-polygon + prefilter paths)
        # and dwithin (distance compare) — convex pentagon around a
        # random center so the ring is always valid
        cx, cy = r.uniform(-150, 150), r.uniform(-70, 70)
        if r.random() < 0.5:
            import math

            rad = r.uniform(1, 25)
            pts = [
                (cx + rad * math.cos(2 * math.pi * k / 5),
                 cy + rad * math.sin(2 * math.pi * k / 5))
                for k in range(5)
            ]
            pts.append(pts[0])
            ring = ", ".join(f"{x:.3f} {y:.3f}" for x, y in pts)
            return f"INTERSECTS(geom, POLYGON(({ring})))"
        return (
            f"DWITHIN(geom, POINT({cx:.3f} {cy:.3f}), "
            f"{r.uniform(0.5, 10):.3f}, kilometers)"
        )

    x = r.random()
    if depth < 2 and x < 0.35:
        op = r.choice(["AND", "OR"])
        return f"({_rand_filter(r, depth + 1)} {op} {_rand_filter(r, depth + 1)})"
    if depth < 2 and x < 0.45:
        return f"NOT ({_rand_filter(r, depth + 1)})"
    return r.choice([bbox, during, attr, spatial])()


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    cols = _data()
    sft = SimpleFeatureType.create("t", SPEC)
    batch = FeatureBatch.from_columns(sft, cols, np.arange(N))
    stores = {
        "memory": MemoryDataStore(),
        "kv": KVDataStore(MemoryKV()),
        "fs": FileSystemDataStore(
            str(tmp_path_factory.mktemp("fuzz_fs")), partition_size=1024
        ),
    }
    for s in stores.values():
        s.create_schema("t", SPEC)
        s.write("t", cols, fids=np.arange(N))
        if hasattr(s, "flush"):
            s.flush("t")
    return batch, stores


def test_differential_fuzz(setup):
    batch, stores = setup
    r = random.Random(20260730)
    for i in range(N_FILTERS):
        q = _rand_filter(r)
        expect = set(batch.fids[evaluate_host(parse_ecql(q), batch)].tolist())
        for name, s in stores.items():
            got = set(int(v) for v in s.query("t", q).batch.fids)
            assert got == expect, (
                f"filter {i} ({q!r}) on {name}: "
                f"+{len(got - expect)} -{len(expect - got)}"
            )


def test_differential_fuzz_device_index(setup):
    """The resident device caches (full + streaming) must answer the same
    random filters exactly; loose mode must be a superset that never
    misses a true hit (its overscan is bounded by cell granularity)."""
    batch, stores = setup
    from geomesa_tpu.device_cache import DeviceIndex, StreamingDeviceIndex

    ds = stores["memory"]
    di = DeviceIndex(ds, "t", z_planes=True)
    sdi = StreamingDeviceIndex(ds, "t", z_planes=True)
    r = random.Random(20260731)
    for i in range(N_FILTERS):
        q = _rand_filter(r)
        expect = set(batch.fids[evaluate_host(parse_ecql(q), batch)].tolist())
        for name, idx in (("device", di), ("streaming", sdi)):
            got = set(int(v) for v in idx.query(q).fids)
            assert got == expect, (
                f"filter {i} ({q!r}) on {name}: "
                f"+{len(got - expect)} -{len(expect - got)}"
            )
            assert idx.count(q) == len(expect), (i, q, name)
            loose = set(int(v) for v in idx.query(q, loose=True).fids)
            # loose only kicks in for bbox(+during)-only filters; either
            # way it must never drop a true hit when it applies
            if loose != expect:
                assert expect <= loose, (
                    f"filter {i} ({q!r}) on {name}: loose dropped "
                    f"{len(expect - loose)} true hits"
                )


def test_differential_fuzz_device_stats(setup):
    """Fused device stats equal host-observed stats for random filters."""
    batch, stores = setup
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.stats import parse_stat

    ds = stores["memory"]
    di = DeviceIndex(ds, "t")
    spec = 'Count();MinMax("val");MinMax("dtg");Histogram("val",12,-50,50)'
    r = random.Random(20260801)
    for i in range(12):
        q = _rand_filter(r)
        got = di.stats(q, spec)
        exp = parse_stat(spec)
        exp.observe_batch(
            batch.take(np.nonzero(evaluate_host(parse_ecql(q), batch))[0])
        )
        g, e = got.to_json(), exp.to_json()
        # float64 'val' is int here; dtg exact via hi/lo; all exact on CPU
        assert g == e, f"filter {i} ({q!r}): {g} != {e}"


# -- non-point (polygon / xz key space) schemas ------------------------------

POLY_SPEC = "name:String,val:Int,dtg:Date,*geom:Polygon:srid=4326"


def _poly_data(n=2500):
    rng = np.random.default_rng(17)
    x = rng.uniform(-170, 160, n)
    y = rng.uniform(-85, 75, n)
    w = rng.uniform(0.01, 6.0, n)
    h = rng.uniform(0.01, 6.0, n)
    wkt = np.array(
        [
            f"POLYGON (({a:.5f} {b:.5f}, {a + c:.5f} {b:.5f}, "
            f"{a + c:.5f} {b + d:.5f}, {a:.5f} {b + d:.5f}, "
            f"{a:.5f} {b:.5f}))"
            for a, b, c, d in zip(x, y, w, h)
        ],
        dtype=object,
    )
    return {
        "name": rng.choice(["a", "b", "c"], n),
        "val": rng.integers(-50, 50, n),
        "dtg": rng.integers(T0, T1, n),
        "geom": wkt,
    }


@pytest.fixture(scope="module")
def poly_setup(tmp_path_factory):
    cols = _poly_data()
    n = len(cols["val"])
    sft = SimpleFeatureType.create("p", POLY_SPEC)
    batch = FeatureBatch.from_columns(sft, cols, np.arange(n))
    stores = {
        "memory": MemoryDataStore(),
        "kv": KVDataStore(MemoryKV()),
        "fs": FileSystemDataStore(
            str(tmp_path_factory.mktemp("fuzz_fs_poly")), partition_size=512
        ),
    }
    for s in stores.values():
        s.create_schema("p", POLY_SPEC)
        s.write("p", cols, fids=np.arange(n))
        if hasattr(s, "flush"):
            s.flush("p")
    return batch, stores


def _rand_poly_filter(r: random.Random, depth=0) -> str:
    """bbox/during/attr/intersects over a non-point schema (xz3 primary)."""

    def bbox():
        x0, y0 = r.uniform(-180, 160), r.uniform(-90, 70)
        return (
            f"BBOX(geom, {x0:.3f}, {y0:.3f}, "
            f"{x0 + r.uniform(1, 90):.3f}, {y0 + r.uniform(1, 50):.3f})"
        )

    def during():
        import datetime

        a = r.randint(T0, T1 - 1)
        b = r.randint(a, T1)
        f = lambda ms: datetime.datetime.fromtimestamp(  # noqa: E731
            ms / 1000, datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ")
        return f"dtg DURING {f(a)}/{f(b)}"

    def attr():
        return r.choice(
            [
                f"val >= {r.randint(-50, 50)}",
                f"name = '{r.choice('abc')}'",
            ]
        )

    def isect():
        cx, cy = r.uniform(-120, 120), r.uniform(-60, 60)
        s = r.uniform(2, 30)
        return (
            f"INTERSECTS(geom, POLYGON(({cx:.3f} {cy:.3f}, "
            f"{cx + s:.3f} {cy:.3f}, {cx + s:.3f} {cy + s:.3f}, "
            f"{cx:.3f} {cy + s:.3f}, {cx:.3f} {cy:.3f})))"
        )

    x = r.random()
    if depth < 2 and x < 0.3:
        op = r.choice(["AND", "OR"])
        return (
            f"({_rand_poly_filter(r, depth + 1)} {op} "
            f"{_rand_poly_filter(r, depth + 1)})"
        )
    if depth < 2 and x < 0.4:
        return f"NOT ({_rand_poly_filter(r, depth + 1)})"
    return r.choice([bbox, during, attr, isect])()


def test_differential_fuzz_polygons(poly_setup):
    """Random filters over a POLYGON schema (xz3/xz2 primary index path):
    every store must match the host oracle exactly."""
    batch, stores = poly_setup
    r = random.Random(20260732)
    for i in range(N_FILTERS):
        q = _rand_poly_filter(r)
        expect = set(batch.fids[evaluate_host(parse_ecql(q), batch)].tolist())
        for name, s in stores.items():
            got = set(int(v) for v in s.query("p", q).batch.fids)
            assert got == expect, (
                f"filter {i} ({q!r}) on {name}: "
                f"+{len(got - expect)} -{len(expect - got)}"
            )


def test_differential_fuzz_polygon_device_index(poly_setup):
    """The resident cache over a non-point schema (xz key planes): exact
    results equal the oracle; loose xz mode never drops a true hit."""
    batch, stores = poly_setup
    from geomesa_tpu.device_cache import DeviceIndex

    di = DeviceIndex(stores["memory"], "p", z_planes=True)
    assert di._z_kind == "xz3"
    r = random.Random(20260733)
    for i in range(N_FILTERS // 2):
        q = _rand_poly_filter(r)
        expect = set(batch.fids[evaluate_host(parse_ecql(q), batch)].tolist())
        got = set(int(v) for v in di.query(q).fids)
        assert got == expect, f"filter {i} ({q!r})"
        assert di.count(q) == len(expect)
        loose = set(int(v) for v in di.query(q, loose=True).fids)
        if loose != expect:
            assert expect <= loose, (
                f"filter {i} ({q!r}): loose xz dropped "
                f"{len(expect - loose)} true hits"
            )
