"""Serving-path recompile tripwire (analysis/compilecheck.py): the
allowed compile_scope namespace, seeded violations on private checker
instances, the server-lifecycle serving window, and the zero-violations
invariant over a real served workload."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import ledger
from geomesa_tpu.analysis import compilecheck
from geomesa_tpu.store import MemoryDataStore


def _cost(tenant="t"):
    return ledger.RequestCost(
        tenant=tenant, endpoint="e", lane="interactive", shape="s"
    )


@pytest.fixture
def chk(monkeypatch):
    """A private checker swapped in for the module-level one (the
    observer seam and the server lifecycle hooks both dispatch through
    the module attribute)."""
    c = compilecheck.CompileCheck("private")
    monkeypatch.setattr(compilecheck, "CHECKER", c)
    return c


def test_enabled_for_the_suite():
    assert compilecheck.enabled()


def test_global_checker_zero_violations_invariant():
    """The mid-run half of the conftest enforcement: no suite that ran
    before this test compiled outside the blessed namespace while a
    server was live."""
    rep = compilecheck.CHECKER.report()
    assert rep["violations"] == [], rep["violations"]


def test_allowed_families_is_the_documented_namespace():
    fams = {fam for fam, _ in ledger.SCOPE_FAMILIES}
    assert compilecheck.ALLOWED_FAMILIES == fams | {"warmup", "_system"}
    # the PR 17 bucketing families the serving path actually uses
    assert {"fused.dim", "cache.stage", "knn"} <= fams


# -- the decision table, seeded ---------------------------------------------


def test_not_serving_records_nothing(chk):
    chk.on_compile(None, _cost(), 0.1)
    chk.on_compile("rogue.family:x", None, 0.1)
    rep = chk.report()
    assert rep["violations"] == [] and rep["serving_compiles"] == 0
    assert rep["compiles"] == 2


def test_serving_allowed_scopes_are_clean(chk):
    chk.serving_up()
    for sig in ("fused.dim:r=64:q=8", "cache.stage:pts", "knn:k=16",
                "warmup:pts", "join.refine:m=4"):
        chk.on_compile(sig, None, 0.1)
    rep = chk.report()
    assert rep["violations"] == []
    assert rep["serving_compiles"] == 5


def test_serving_unknown_family_is_a_violation(chk):
    chk.serving_up()
    chk.on_compile("rogue.family:whatever", _cost("t1"), 0.2)
    vs = chk.report()["violations"]
    assert len(vs) == 1 and vs[0]["family"] == "rogue.family"
    assert vs[0]["tenant"] == "t1"


def test_serving_scopeless_live_request_is_a_violation(chk):
    """The compile-cliff regression shape: a live (non-_system) request
    blocked on a compile no compile_scope claimed."""
    chk.serving_up()
    chk.on_compile(None, _cost("tenant-a"), 0.4)
    vs = chk.report()["violations"]
    assert len(vs) == 1 and vs[0]["scope"] is None
    assert vs[0]["tenant"] == "tenant-a"
    assert "cliff" in vs[0]["detail"]


def test_serving_scopeless_worker_thread_is_a_violation(chk):
    chk.serving_up()
    t = threading.Thread(  # lint: disable=GT010(seeding the violation the blessed helper exists to prevent)
        target=lambda: chk.on_compile(None, None, 0.3), name="rogue-w"
    )
    t.start()
    t.join()
    vs = chk.report()["violations"]
    assert len(vs) == 1 and vs[0]["thread"] == "rogue-w"


def test_serving_exemptions_main_thread_and_system(chk):
    chk.serving_up()
    chk.on_compile(None, None, 0.1)  # main thread, no collector
    chk.on_compile(None, _cost("_system"), 0.1)  # warmup/staging leg
    assert chk.report()["violations"] == []


def test_violations_dedupe_by_site(chk):
    chk.serving_up()
    for _ in range(4):
        chk.on_compile("rogue.family:x", None, 0.1)
    assert len(chk.report()["violations"]) == 1


def test_serving_window_refcounts(chk):
    assert not chk.serving
    chk.serving_up()
    chk.serving_up()
    chk.serving_down()
    assert chk.serving  # two servers up, one down: still live
    chk.serving_down()
    assert not chk.serving
    chk.serving_down()  # extra downs clamp at zero
    chk.serving_up()
    assert chk.serving


# -- the server lifecycle brackets the window --------------------------------


def _serve(store, **kw):
    from geomesa_tpu.server import serve_background

    return serve_background(store, **kw)


def test_server_lifecycle_brackets_serving_window(chk):
    server, _ = _serve(MemoryDataStore())
    try:
        assert chk.serving
    finally:
        server.shutdown()
    assert not chk.serving
    # idempotent shutdown must not double-decrement someone else's window
    chk.serving_up()
    server.shutdown()
    assert chk.serving


def test_real_compile_while_serving_trips_and_scoped_does_not(chk):
    """End-to-end through jax.monitoring: while a real server is live, a
    genuinely novel jit under an allowed scope is clean, the same
    without any scope (charged to a live request) is THE violation."""
    import jax
    import jax.numpy as jnp

    ledger.install()
    server, _ = _serve(MemoryDataStore())
    try:
        uniq = int(time.perf_counter() * 1e9) % 1_000_003 + 2
        with ledger.compile_scope("fused.dim:test"):
            jax.jit(lambda x: x * uniq + 3)(jnp.arange(277))
        assert chk.report()["violations"] == []
        with ledger.collect_cost(
            tenant="live-tenant", endpoint="knn", lane="interactive",
            shape="s",
        ):
            jax.jit(lambda x: x * uniq + 5)(jnp.arange(281))
        vs = chk.report()["violations"]
        assert len(vs) == 1 and vs[0]["tenant"] == "live-tenant"
        assert chk.report()["serving_compiles"] >= 2
    finally:
        server.shutdown()


def test_served_workload_is_compile_clean(chk):
    """The acceptance invariant in miniature: a real HTTP workload
    (schema create, writes, count + features queries) over a resident
    server produces ZERO unattributed serving-path compiles -- every
    serving jit goes through the blessed scopes. The suite-wide version
    is the conftest enforcement over all of tier-1."""
    rng = np.random.default_rng(7)
    n = 513
    store = MemoryDataStore()
    store.create_schema(
        "pts", "name:String,dtg:Date,*geom:Point:srid=4326"
    )
    store.write(
        "pts",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "dtg": rng.integers(0, 86_400, n).astype(np.int64),
            "geom": np.stack(
                [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)],
                axis=1,
            ),
        },
        fids=np.arange(n),
    )
    server, _ = _serve(store, resident=True)
    try:
        host, port = server.server_address[:2]
        base = f"http://{host}:{port}"
        for path in (
            "/count/pts?cql=BBOX(geom,-5,-5,5,5)",
            "/features/pts?cql=BBOX(geom,-5,-5,5,5)",
            "/count/pts?cql=BBOX(geom,-2,-2,2,2)",
        ):
            with urllib.request.urlopen(base + path, timeout=120) as r:
                assert r.status == 200
                json.loads(r.read())
    finally:
        server.shutdown()
    rep = chk.report()
    assert rep["violations"] == [], rep["violations"]
