"""Device-side spatial join engine (ISSUE 11): the planner/engine must
be BIT-IDENTICAL to the numpy host reference across strategies, engines,
shard counts and adversarial layouts; plus the frame/process routing,
the skew-split escape, the overflow-counting satellite and the join.*
registries.

Runs on the 8-virtual-device CPU harness conftest provides.
"""

import numpy as np
import pytest

from geomesa_tpu import metrics
from geomesa_tpu.conf import prop_override
from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.join import JoinEngine, plan_join
from geomesa_tpu.join.engine import _join_conf
from geomesa_tpu.parallel.mesh import make_mesh
from geomesa_tpu.sql.frame import SpatialFrame
from geomesa_tpu.store import MemoryDataStore

T0 = 1_577_836_800_000


def _layout(kind, n, rng):
    """Adversarial coordinate layouts (the mesh-serving suite's set)."""
    if kind == "uniform":
        x = rng.uniform(-60, 60, n)
        y = rng.uniform(-50, 50, n)
    elif kind == "presorted":
        x = np.sort(rng.uniform(-60, 60, n))
        y = rng.uniform(-50, 50, n)
    elif kind == "hotcell":  # every point in one Z-cell
        x = 2.3522 + rng.uniform(-0.005, 0.005, n)
        y = 48.8566 + rng.uniform(-0.005, 0.005, n)
    else:  # clustered: GDELT-style hot cities
        centers = np.array(
            [[2.35, 48.85], [-74.0, 40.7], [139.7, 35.7], [28.0, -26.2]]
        )
        which = rng.integers(0, 4, n)
        x = centers[which, 0] + rng.uniform(-0.01, 0.01, n)
        y = centers[which, 1] + rng.uniform(-0.01, 0.01, n)
    return x, y


def _store(x, y, dtg=True, fids=None):
    n = len(x)
    rng = np.random.default_rng(n)
    ds = MemoryDataStore()
    spec = "v:Integer,*geom:Point:srid=4326"
    cols = {
        "v": rng.integers(0, 100, n).astype(np.int32),
        "geom": np.stack([x, y], axis=1),
    }
    if dtg:
        spec = "v:Integer,dtg:Date,*geom:Point:srid=4326"
        cols["dtg"] = rng.integers(T0, T0 + 10**9, n)
    ds.create_schema("t", spec)
    ds.write("t", cols, fids=fids)
    return ds


def _windows(rng, m, w=2.0):
    x0 = rng.uniform(-60, 58, m)
    y0 = rng.uniform(-50, 48, m)
    return np.stack([x0, y0, x0 + w, y0 + w], axis=1)


def _reference(ds, envs, gate=None):
    """Exact inclusive envelope-join oracle over the STAGED row order,
    pairs sorted (window, row)."""
    g = np.asarray(
        ds.query("t", "INCLUDE").batch.columns["geom"], np.float64
    )
    out = []
    for j in range(len(envs)):
        a, b, c, d = envs[j]
        hit = (
            (g[:, 0] >= a) & (g[:, 0] <= c)
            & (g[:, 1] >= b) & (g[:, 1] <= d)
        )
        if gate is not None:
            hit &= gate
        for i in np.nonzero(hit)[0]:
            out.append((int(i), j))
    return out


def _got(res):
    return list(zip(res.rows.tolist(), res.wins.tolist()))


# -- property suite: strategies x engines x layouts ------------------------


@pytest.mark.parametrize(
    "layout", ["uniform", "presorted", "hotcell", "clustered"]
)
@pytest.mark.parametrize("strategy", ["auto", "broadcast", "grouped",
                                      "zmerge"])
def test_engine_matches_reference(layout, strategy, rng):
    n, m = 4096, 60
    x, y = _layout(layout, n, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    envs = _windows(rng, m)
    ref = _reference(ds, envs)
    with prop_override("join.strategy", strategy):
        host = JoinEngine(di).join(envs)
    assert _got(host) == ref, (layout, strategy, "host")
    with prop_override("join.strategy", strategy), \
            prop_override("join.engine", "device"):
        dev = JoinEngine(di).join(envs)
    assert _got(dev) == ref, (layout, strategy, "device")


@pytest.mark.parametrize("shards", [1, 2, 3, 8])
@pytest.mark.parametrize("layout", ["uniform", "hotcell", "clustered"])
def test_mesh_copartitioned_parity(shards, layout, rng):
    """Co-partitioned mesh refinement is bit-identical at every shard
    count — including non-power-of-two — and every pair a shard emits
    references only that shard's own row range (the zero-exchange
    property made observable)."""
    n, m = 4099, 40  # prime n: shard padding always live
    x, y = _layout(layout, n, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    envs = _windows(rng, m)
    ref = _reference(ds, envs)
    mesh = make_mesh(n_devices=shards)
    res = JoinEngine(di, mesh=mesh).join(envs)
    assert _got(res) == ref
    assert res.shards == shards
    assert res.engine == "device"


def test_empty_and_tiny_sides(rng):
    x, y = _layout("uniform", 300, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    # empty right
    res = JoinEngine(di).join(np.zeros((0, 4)))
    assert res.pairs == 0
    # inverted (empty) windows
    res = JoinEngine(di).join(np.array([[10.0, 10.0, -10.0, -10.0]]))
    assert res.pairs == 0
    # tiny right side (broadcast territory)
    envs = _windows(rng, 3)
    assert _got(JoinEngine(di).join(envs)) == _reference(ds, envs)
    # empty left
    ds0 = _store(np.zeros(0), np.zeros(0))
    di0 = DeviceIndex(ds0, "t")
    assert JoinEngine(di0).join(envs).pairs == 0


def test_duplicate_fids_and_points(rng):
    """Duplicate coordinates AND duplicate fids stay distinct rows."""
    x, y = _layout("uniform", 400, rng)
    x[100:200] = x[0]
    y[100:200] = y[0]
    fids = np.concatenate([np.zeros(200, np.int64),
                           np.arange(200, 400)])
    ds = _store(x, y, fids=fids)
    di = DeviceIndex(ds, "t")
    envs = _windows(rng, 25)
    assert _got(JoinEngine(di).join(envs)) == _reference(ds, envs)


def test_skew_split_correctness(rng):
    """A hot cell under a tiny join.split.rows must split runs (counted
    on the metric) without changing a single pair."""
    x, y = _layout("hotcell", 5000, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    envs = np.array([[2.0, 48.0, 3.0, 49.5], [2.34, 48.84, 2.36, 48.86]])
    ref = _reference(ds, envs)
    before = metrics.join_skew_splits.value()
    with prop_override("join.split.rows", 1024), \
            prop_override("join.strategy", "grouped"):
        res = JoinEngine(di).join(envs)
    assert _got(res) == ref
    assert res.splits > 0
    assert metrics.join_skew_splits.value() > before
    # and the split plan stays device-parity
    with prop_override("join.split.rows", 1024), \
            prop_override("join.strategy", "grouped"), \
            prop_override("join.engine", "device"):
        dev = JoinEngine(di).join(envs)
    assert _got(dev) == ref


def test_gate_and_streaming_validity(rng):
    """Row gates (base filter) and the index's implicit validity both
    cut pairs exactly."""
    x, y = _layout("uniform", 2000, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    envs = _windows(rng, 30)
    batch = ds.query("t", "INCLUDE").batch
    gate = np.asarray(batch.columns["v"]) < 50
    ref = _reference(ds, envs, gate=gate)
    res = JoinEngine(di).join(envs, gate=gate)
    assert _got(res) == ref
    with prop_override("join.engine", "device"):
        dev = JoinEngine(di).join(envs, gate=gate)
    assert _got(dev) == ref


def test_adaptive_selection_shifts_strategy(rng):
    """Tiny right sides broadcast; many small windows merge Z-intervals;
    the planner records honest estimates."""
    x, y = _layout("uniform", 8192, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    small = JoinEngine(di).join(_windows(rng, 4))
    assert small.strategy == "broadcast"
    many = JoinEngine(di).join(_windows(rng, 300, w=0.5))
    assert many.strategy in ("grouped", "zmerge")
    assert many.stats.est_pairs >= 0
    assert many.candidates >= many.pairs


def test_join_index_caches_per_generation(rng):
    x, y = _layout("uniform", 1000, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    eng = JoinEngine(di)
    j1 = eng.prepare()
    assert eng.prepare() is j1  # cached
    di.refresh()
    j2 = eng.prepare()
    assert j2 is not j1  # staging invalidated the layout


def test_frame_routes_through_engine(rng):
    """frame.spatial_join with a device_index must equal the (oracle)
    default path — including a frame filter and polygon right sides
    whose pip semantics differ from envelope tests."""
    from geomesa_tpu.geom import Polygon

    x, y = _layout("uniform", 3000, rng)
    ds = _store(x, y)
    polys = []
    for j in range(40):
        cx, cy = rng.uniform(-55, 55), rng.uniform(-45, 45)
        w, h = rng.uniform(0.5, 3), rng.uniform(0.5, 3)
        if j % 2:
            ring = np.array([[cx, cy - h], [cx + w, cy], [cx, cy + h],
                             [cx - w, cy], [cx, cy - h]])  # diamond
        else:
            ring = np.array([[cx - w, cy - h], [cx + w, cy - h],
                             [cx + w, cy + h], [cx - w, cy + h],
                             [cx - w, cy - h]])
        polys.append(Polygon(ring))
    ds.create_schema("r", "*geom:Geometry:srid=4326")
    ds.write("r", {"geom": np.array(polys, dtype=object)})
    di = DeviceIndex(ds, "t")
    fl = SpatialFrame(ds, "t").where("v < 70")
    fr = SpatialFrame(ds, "r")

    def canon(left, pairs):
        return sorted((left.fids[i], j) for i, j in pairs)

    for on, dist in (("intersects", None), ("dwithin", 1.0)):
        rl, _, rp = fl.spatial_join(fr, on=on, distance=dist)
        el, _, ep = fl.spatial_join(
            fr, on=on, distance=dist, device_index=di
        )
        assert canon(rl, rp) == canon(el, ep), on
    # engine path compacts left to exactly the referenced rows
    el, _, ep = fl.spatial_join(fr, device_index=di)
    if len(ep):
        assert len(el) == len(np.unique(ep[:, 0]))


def test_nonpoint_left_xz_layout(rng):
    """Polygon LEFT side: the XZ2 extent-curve layout plans per-window
    code ranges; pairs equal the oracle path."""
    from geomesa_tpu.geom import Polygon

    ds = MemoryDataStore()
    k = 800
    cx = rng.uniform(-60, 60, k)
    cy = rng.uniform(-50, 50, k)
    w = rng.uniform(0.05, 0.4, k)
    boxes = [
        Polygon(np.array([
            [cx[i] - w[i], cy[i] - w[i]], [cx[i] + w[i], cy[i] - w[i]],
            [cx[i] + w[i], cy[i] + w[i]], [cx[i] - w[i], cy[i] + w[i]],
            [cx[i] - w[i], cy[i] - w[i]],
        ]))
        for i in range(k)
    ]
    ds.create_schema("pl", "*geom:Geometry:srid=4326")
    ds.write("pl", {"geom": np.array(boxes, dtype=object)})
    di = DeviceIndex(ds, "pl")
    jidx = JoinEngine(di).prepare()
    assert jidx.kind == "xz2"
    fl = SpatialFrame(ds, "pl")
    fr_store = MemoryDataStore()
    rp = [
        Polygon(np.array([
            [a, b], [a + 3, b], [a + 3, b + 3], [a, b + 3], [a, b],
        ]))
        for a, b in zip(rng.uniform(-55, 50, 25), rng.uniform(-45, 40, 25))
    ]
    fr_store.create_schema("r", "*geom:Geometry:srid=4326")
    fr_store.write("r", {"geom": np.array(rp, dtype=object)})
    fr = SpatialFrame(fr_store, "r")
    rl, _, rpairs = fl.spatial_join(fr)
    el, _, epairs = fl.spatial_join(fr, device_index=di)
    canon = lambda l, p: sorted((l.fids[i], j) for i, j in p)  # noqa: E731
    assert canon(rl, rpairs) == canon(el, epairs)


def test_process_operator(rng):
    from geomesa_tpu import process

    x, y = _layout("uniform", 1500, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    envs = _windows(rng, 20)
    # envelope join returns the engine result directly
    res = process.spatial_join(ds, "t", envs, device_index=di)
    assert _got(res) == _reference(ds, envs)
    # with a left filter
    batch = ds.query("t", "INCLUDE").batch
    gate = np.asarray(batch.columns["v"]) < 30
    resf = process.spatial_join(
        ds, "t", envs, left_filter="v < 30", device_index=di
    )
    assert _got(resf) == _reference(ds, envs, gate=gate)
    # store-collected left side (no resident index)
    res2 = process.spatial_join(ds, "t", envs)
    assert _got(res2) == _reference(ds, envs)
    report = res.report()
    assert report["pairs"] == res.pairs
    assert report["strategy"] in ("broadcast", "grouped", "zmerge")


def test_scheduler_rides_refinement(rng):
    from geomesa_tpu.sched.scheduler import QueryScheduler, SchedConfig

    x, y = _layout("uniform", 2000, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    envs = _windows(rng, 40)
    ref = _reference(ds, envs)
    sched = QueryScheduler(SchedConfig(max_inflight=2))
    try:
        before = sched.queries
        res = JoinEngine(di, sched=sched).join(envs)
        assert _got(res) == ref
        assert sched.queries > before  # batches went through admission
    finally:
        sched.close()


def test_streaming_live_rows_join(rng):
    """Enrichment against a live (appended) streaming index: freshly
    acked rows join immediately; evicted rows drop out."""
    from geomesa_tpu.device_cache import StreamingDeviceIndex

    x, y = _layout("uniform", 1200, rng)
    ds = _store(x, y, fids=np.arange(1200))
    di = StreamingDeviceIndex(ds, "t")
    envs = _windows(rng, 25)
    base = JoinEngine(di).join(envs)
    assert _got(base) == _reference(ds, envs)
    # append live rows: the next join sees them (generation bump)
    from geomesa_tpu.features.batch import FeatureBatch

    sft = ds.get_schema("t")
    extra = FeatureBatch.from_columns(
        sft,
        {
            "v": np.arange(50, dtype=np.int32),
            "dtg": np.full(50, T0, np.int64),
            "geom": np.stack(
                [rng.uniform(-60, 60, 50), rng.uniform(-50, 50, 50)],
                axis=1,
            ),
        },
        fids=np.arange(5000, 5050),
    )
    di.append(extra)
    res = JoinEngine(di).join(envs)
    g = np.asarray(di._host_rows().columns["geom"], np.float64)
    hv = di._host_valid()
    expect = 0
    for j in range(len(envs)):
        a, b, c, d = envs[j]
        hit = ((g[:, 0] >= a) & (g[:, 0] <= c)
               & (g[:, 1] >= b) & (g[:, 1] <= d))
        if hv is not None:
            hit &= hv
        expect += int(hit.sum())
    assert res.pairs == expect
    # evict the appended rows: pairs revert to the base join
    di.evict(np.arange(5000, 5050))
    res2 = JoinEngine(di).join(envs)
    assert _got(res2) == _got(base)


def test_pair_overflow_metric_and_span(rng):
    """Satellite: the window_pairs_query compaction-cap overflow is
    counted and stamped on the join.pairs span."""
    from geomesa_tpu.tracing import Tracer

    n = 9000  # past the 4096 compaction cap: the full-group refetch
    x, y = _layout("uniform", n, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    before = metrics.join_pair_overflows.value()
    tr = Tracer()
    with prop_override("trace.sample", 1.0):
        with tr.trace("join-overflow-test") as t:
            # whole-world windows: every row hits -> cap overflow
            rows, wins = di.window_pairs_query(
                np.array([[-180.0, -90.0, 180.0, 90.0]] * 2)
            )
    assert len(rows) == 2 * n
    assert metrics.join_pair_overflows.value() > before
    root = tr.get(t.trace_id).to_dict()["spans"]

    def find(node, name):
        if node["name"] == name:
            return node
        for c in node.get("children", ()):
            got = find(c, name)
            if got is not None:
                return got
        return None

    sp = find(root, "join.pairs")
    assert sp is not None and sp["attrs"]["overflows"] >= 1


def test_conf_and_registries():
    """join.* keys declared (GT008), metrics registered (GT006), ledger
    fields present (GT009)."""
    from geomesa_tpu import ledger
    from geomesa_tpu.conf import declared_keys

    for k in ("join.engine", "join.strategy", "join.broadcast.windows",
              "join.split.rows", "join.batch.candidates",
              "join.hist.bits", "join.xz.ranges"):
        assert k in declared_keys(), k
    conf = _join_conf()
    assert conf["strategy"] == "auto"
    for f in ("join_candidates", "join_pairs"):
        assert f in ledger.FIELDS
    for m in (metrics.join_queries, metrics.join_pairs,
              metrics.join_candidates, metrics.join_launches,
              metrics.join_skew_splits, metrics.join_pair_overflows):
        assert m.name.startswith("geomesa_join_")


def test_forced_strategy_invalid_conf():
    with pytest.raises(ValueError):
        with prop_override("join.strategy", "quantum"):
            pass
    with pytest.raises(ValueError):
        with prop_override("join.engine", "gpu"):
            pass


def test_planner_interior_runs_are_exact(rng):
    """Interior-flagged runs (strictly inside the covering ring in cell
    space) must contain ONLY true hits — the no-coordinate-test claim."""
    x, y = _layout("uniform", 20000, rng)
    ds = _store(x, y)
    di = DeviceIndex(ds, "t")
    eng = JoinEngine(di)
    jidx = eng.prepare()
    envs = _windows(rng, 10, w=8.0)  # big windows: interior cells exist
    from geomesa_tpu.join.planner import clip_envs

    with prop_override("join.strategy", "zmerge"):
        plan = plan_join(jidx, clip_envs(envs), _join_conf())
    ii = np.nonzero(plan.interior)[0]
    assert len(ii), "expected interior runs for 8-degree windows"
    xs, ys = jidx.planes["x"], jidx.planes["y"]
    for r in ii[:50]:
        s, e, j = plan.starts[r], plan.ends[r], plan.wins[r]
        a, b, c, d = envs[j]
        assert np.all((xs[s:e] >= a) & (xs[s:e] <= c)
                      & (ys[s:e] >= b) & (ys[s:e] <= d))


def test_frame_threads_mesh_through(rng):
    """The predicate-join path honors ``mesh=`` (review regression: it
    used to be silently dropped) and an explicit join.engine=host pin
    beats an attached mesh."""
    from geomesa_tpu.geom import Polygon

    x, y = _layout("uniform", 1500, rng)
    ds = _store(x, y)
    rp = [
        Polygon(np.array([
            [a, b], [a + 2, b], [a + 2, b + 2], [a, b + 2], [a, b],
        ]))
        for a, b in zip(rng.uniform(-55, 50, 15), rng.uniform(-45, 40, 15))
    ]
    ds.create_schema("r", "*geom:Geometry:srid=4326")
    ds.write("r", {"geom": np.array(rp, dtype=object)})
    di = DeviceIndex(ds, "t")
    fl, fr = SpatialFrame(ds, "t"), SpatialFrame(ds, "r")
    rl, _, rpairs = fl.spatial_join(fr)
    mesh = make_mesh(n_devices=4)
    el, _, epairs = fl.spatial_join(fr, device_index=di, mesh=mesh)
    canon = lambda l, p: sorted((l.fids[i], j) for i, j in p)  # noqa: E731
    assert canon(rl, rpairs) == canon(el, epairs)
    # host pin wins over the mesh (the oracle engine stays forceable)
    envs = _windows(rng, 20)
    with prop_override("join.engine", "host"):
        res = JoinEngine(di, mesh=mesh).join(envs)
    assert res.engine == "host" and res.shards == 0
    assert _got(res) == _reference(ds, envs)
