"""Wide st_* UDF surface, GeoJSON codec, SpatialFrame partitions/join."""

import json

import numpy as np
import pytest

import geomesa_tpu.sql as sql
from geomesa_tpu.geom import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geom.geojson import from_geojson, to_geojson
from geomesa_tpu.sql import SpatialFrame
from geomesa_tpu.sql.functions import FUNCTIONS

SQUARE = sql.st_makeBBOX(0, 0, 10, 10)
LINE = LineString(np.array([[0.0, 0.0], [3.0, 4.0], [3.0, 8.0]]))


def test_registry_has_full_surface():
    expected = {
        "st_point", "st_makeBBOX", "st_makeLine", "st_makePolygon",
        "st_geomFromWKT", "st_geomFromWKB", "st_geomFromGeoJSON",
        "st_geomFromGeoHash", "st_pointFromGeoHash", "st_pointFromText",
        "st_lineFromText", "st_polygonFromText", "st_castToPoint",
        "st_castToPolygon", "st_geometryType", "st_isEmpty", "st_isClosed",
        "st_isRing", "st_isCollection", "st_dimension", "st_coordDim",
        "st_numGeometries", "st_geometryN", "st_exteriorRing",
        "st_interiorRingN", "st_pointN", "st_startPoint", "st_endPoint",
        "st_asText", "st_asBinary", "st_asGeoJSON", "st_asTWKB",
        "st_geoHash", "st_translate", "st_convexHull", "st_closestPoint",
        "st_lengthSphere", "st_antimeridianSafeGeom", "st_idlSafeGeom",
        "st_equals", "st_covers", "st_intersects", "st_contains",
        "st_within", "st_distance", "st_dwithin", "st_area", "st_centroid",
    }
    missing = expected - set(FUNCTIONS)
    assert not missing, f"missing st_ functions: {sorted(missing)}"
    assert len(FUNCTIONS) >= 60


def test_constructors():
    line = sql.st_makeLine([Point(0, 0), Point(1, 1), Point(2, 0)])
    assert isinstance(line, LineString) and len(line.coords) == 3
    poly = sql.st_makePolygon(line)
    assert isinstance(poly, Polygon)
    assert np.array_equal(poly.shell[0], poly.shell[-1])
    p = sql.st_pointFromText("POINT (3 4)")
    assert (p.x, p.y) == (3, 4)
    with pytest.raises(ValueError):
        sql.st_pointFromText("LINESTRING (0 0, 1 1)")
    assert isinstance(sql.st_polygonFromText("POLYGON ((0 0, 1 0, 1 1, 0 0))"), Polygon)


def test_geohash_functions():
    gh = sql.st_geoHash(Point(2.35, 48.85), 9)
    assert isinstance(gh, str) and len(gh) == 9
    cell = sql.st_geomFromGeoHash(gh)
    assert isinstance(cell, Polygon)
    center = sql.st_pointFromGeoHash(gh)
    assert abs(center.x - 2.35) < 0.01 and abs(center.y - 48.85) < 0.01
    # vectorized over point columns
    pts = np.array([[2.35, 48.85], [-0.12, 51.5]])
    ghs = sql.st_geoHash(pts, 7)
    assert len(ghs) == 2 and all(len(h) == 7 for h in ghs)


def test_accessors():
    assert sql.st_geometryType(SQUARE) == "Polygon"
    assert sql.st_dimension(LINE) == 1 and sql.st_dimension(SQUARE) == 2
    assert sql.st_numGeometries(SQUARE) == 1
    mp = MultiPoint((Point(0, 0), Point(1, 1)))
    assert sql.st_numGeometries(mp) == 2
    assert sql.st_geometryN(mp, 2).x == 1
    ring = sql.st_exteriorRing(SQUARE)
    assert isinstance(ring, LineString) and sql.st_isRing(ring)
    assert not sql.st_isClosed(LINE)
    assert sql.st_startPoint(LINE).x == 0 and sql.st_endPoint(LINE).y == 8
    assert sql.st_pointN(LINE, 2).y == 4
    assert not sql.st_isEmpty(LINE)
    assert sql.st_isCollection(mp) and not sql.st_isCollection(LINE)
    assert sql.st_coordDim(LINE) == 2


def test_outputs_roundtrip():
    wkt = sql.st_asText(SQUARE)
    assert sql.st_equals(sql.st_geomFromWKT(wkt), SQUARE)
    wkb = sql.st_asBinary(LINE)
    assert sql.st_equals(sql.st_geomFromWKB(wkb), LINE)
    gj = sql.st_asGeoJSON(SQUARE)
    assert json.loads(gj)["type"] == "Polygon"
    assert sql.st_equals(sql.st_geomFromGeoJSON(gj), SQUARE)
    twkb = sql.st_asTWKB(LINE)
    from geomesa_tpu.geom.wkb import from_twkb

    assert sql.st_equals(from_twkb(twkb), LINE)


def test_geojson_all_types():
    geoms = [
        Point(1, 2),
        LINE,
        SQUARE,
        MultiPoint((Point(0, 0), Point(1, 1))),
        MultiLineString((LINE,)),
        MultiPolygon((SQUARE,)),
    ]
    for g in geoms:
        rt = from_geojson(to_geojson(g))
        assert sql.st_equals(rt, g), type(g).__name__


def test_processing():
    t = sql.st_translate(Point(1, 1), 2, 3)
    assert (t.x, t.y) == (3, 4)
    hull = sql.st_convexHull(MultiPoint(tuple(
        Point(x, y) for x, y in [(0, 0), (4, 0), (4, 4), (0, 4), (2, 2), (1, 1)]
    )))
    assert isinstance(hull, Polygon)
    assert abs(sql.st_area(hull) - 16.0) < 1e-9  # interior points dropped
    cp = sql.st_closestPoint(LINE, Point(6, 4))
    assert abs(cp.x - 3) < 1e-9 and abs(cp.y - 4) < 1e-9
    # ~111 km for 1 degree of latitude
    merid = LineString(np.array([[0.0, 0.0], [0.0, 1.0]]))
    assert abs(sql.st_lengthSphere(merid) - 111_195) < 500


def test_regressions_from_review(tmp_path):
    # st_equals point-column vs non-point: all False, no crash
    res = sql.st_equals(np.zeros((3, 2)), SQUARE)
    assert not res.any()
    # st_geoHash of a non-point raises a clear error
    with pytest.raises(ValueError, match="st_geoHash"):
        sql.st_geoHash(np.array([SQUARE], dtype=object))
    # west-spilling polygon wraps too
    west = sql.st_makeBBOX(-185, 10, -175, 20)
    safe = sql.st_antimeridianSafeGeom(west)
    assert isinstance(safe, MultiPolygon)
    assert all(
        p.envelope.xmin >= -180 and p.envelope.xmax <= 180
        for p in safe.polygons
    )
    # z2 scheme rejects non-point geometry fields at schema-bind time,
    # before any writes are accepted
    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.features.sft import SimpleFeatureType

    sft = SimpleFeatureType.create("z", "name:String,*geom:Polygon")
    sft.user_data["geomesa.fs.partition-scheme"] = "z2-4bit"
    zs = FileSystemDataStore(str(tmp_path / "zs"))
    with pytest.raises(ValueError, match="xz2"):
        zs.create_schema(sft)
    # geohash precision means characters in both directions
    gh9 = sql.st_geoHash(Point(2.35, 48.85), 9)
    cell = sql.st_geomFromGeoHash(gh9, 9)
    assert sql.st_contains(cell, Point(2.35, 48.85))
    e = cell.envelope
    assert (e.xmax - e.xmin) < 0.0001  # ~5m cell, not a truncated 11-degree one
    # antimeridian split carries interior rings
    outer = np.array(
        [[175.0, 0.0], [185.0, 0.0], [185.0, 10.0], [175.0, 10.0], [175.0, 0.0]]
    )
    hole = np.array(
        [[177.0, 4.0], [183.0, 4.0], [183.0, 6.0], [177.0, 6.0], [177.0, 4.0]]
    )
    donut = Polygon(outer, (hole,))
    safe = sql.st_antimeridianSafeGeom(donut)
    assert isinstance(safe, MultiPolygon)
    assert abs(sql.st_area(safe) - sql.st_area(donut)) < 1e-6
    assert not sql.st_intersects(safe, Point(179.0, 5.0))  # inside the hole
    # backslash-heavy user-data values survive the spec round-trip
    s2 = SimpleFeatureType.create("t", "name:String,*geom:Point")
    s2.user_data["a"] = "C:\\"
    s2.user_data["b"] = "x,y"
    rt = SimpleFeatureType.create("t", s2.spec)
    assert rt.user_data == s2.user_data


def test_partitions_respect_visibility_and_projection(tmp_path):
    from geomesa_tpu.store.fs import FileSystemDataStore

    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType

    ds = FileSystemDataStore(str(tmp_path))
    sft = SimpleFeatureType.create("t", "name:String,dtg:Date,*geom:Point")
    ds.create_schema(sft)
    batch = FeatureBatch.from_columns(
        sft,
        {"name": ["open", "secret"], "dtg": [0, 0], "geom": np.zeros((2, 2))},
        [0, 1],
    ).with_visibility(["", "admin"])
    ds.write("t", batch)
    ds.flush("t")
    frame = SpatialFrame(ds, "t")
    names = [n for p in frame.partitions() for n in p.column("name")]
    assert names == ["open"]  # visibility honored without auths
    admin = frame.with_auths("admin")
    names = sorted(n for p in admin.partitions() for n in p.column("name"))
    assert names == ["open", "secret"]
    proj = [list(p.sft.attribute_names) for p in frame.select("name").partitions()]
    assert all(cols == ["name"] for cols in proj)


def test_antimeridian_safe():
    # polygon spilling past lon 180 splits into two in-range parts
    poly = sql.st_makeBBOX(175, 10, 185, 20)
    safe = sql.st_antimeridianSafeGeom(poly)
    assert isinstance(safe, MultiPolygon)
    envs = [p.envelope for p in safe.polygons]
    assert all(e.xmin >= -180 and e.xmax <= 180 for e in envs)
    assert abs(sum(sql.st_area(p) for p in safe.polygons) - sql.st_area(poly)) < 1e-6
    # in-range geometry passes through unchanged
    assert sql.st_antimeridianSafeGeom(SQUARE) is SQUARE
    p = sql.st_antimeridianSafeGeom(Point(190.0, 5.0))
    assert p.x == -170.0


def _fill_store(tmp_path, n=5000):
    from geomesa_tpu.filter.ecql import parse_instant
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(str(tmp_path), partition_size=512)
    ds.create_schema("t", "name:String,val:Int,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(13)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "val": rng.integers(0, 100, n),
            "dtg": t0 + rng.integers(0, 10**8, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    ds.flush("t")
    return ds


def test_frame_partitions_and_map(tmp_path):
    ds = _fill_store(tmp_path)
    frame = SpatialFrame(ds, "t").where("BBOX(geom, -10, -10, 10, 10)")
    parts = list(frame.partitions())
    assert len(parts) > 1  # multiple storage partitions survive
    assert sum(len(p) for p in parts) == frame.count()
    counts = frame.map_partitions(len, parallelism=4)
    assert sum(counts) == frame.count()


def test_frame_group_by(tmp_path):
    ds = _fill_store(tmp_path, n=1000)
    frame = SpatialFrame(ds, "t")
    vc = frame.value_counts("name")
    assert sum(vc.values()) == 1000
    means = frame.group_by("name", "val", "mean")
    assert set(means) == set(vc)
    assert all(0 <= v <= 100 for v in means.values())


def test_frame_spatial_join(tmp_path):
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = _fill_store(tmp_path, n=2000)
    zones = FileSystemDataStore(str(tmp_path / "zones"))
    zones.create_schema("z", "zone:String,*geom:Polygon")
    zpolys = np.array(
        [sql.st_makeBBOX(-5, -5, 0, 0), sql.st_makeBBOX(0, 0, 5, 5)],
        dtype=object,
    )
    zones.write("z", {"zone": ["sw", "ne"], "geom": zpolys}, fids=[0, 1])
    zones.flush("z")
    pts = SpatialFrame(ds, "t")
    zf = SpatialFrame(zones, "z")
    left, right, pairs = pts.spatial_join(zf, on="within")
    assert len(pairs) > 0
    # verify each pair against the exact predicate
    lg = left.columns["geom"]
    for i, j in pairs[:50]:
        assert sql.st_within(
            Point(float(lg[i, 0]), float(lg[i, 1])), right.columns["geom"][j]
        )
    # oracle count: points in either box
    g = ds.query("t").batch.columns["geom"]
    in_sw = (g[:, 0] >= -5) & (g[:, 0] <= 0) & (g[:, 1] >= -5) & (g[:, 1] <= 0)
    in_ne = (g[:, 0] >= 0) & (g[:, 0] <= 5) & (g[:, 1] >= 0) & (g[:, 1] <= 5)
    assert len(pairs) == int(in_sw.sum() + in_ne.sum())


def test_frame_to_pandas(tmp_path):
    ds = _fill_store(tmp_path, n=200)
    df = SpatialFrame(ds, "t").where("BBOX(geom, -10, -10, 10, 10)").to_pandas()
    assert df.index.name == "fid"
    assert set(df.columns) == {"name", "val", "dtg", "geom"}
    assert len(df) == SpatialFrame(ds, "t").where("BBOX(geom, -10, -10, 10, 10)").count()
    assert df["geom"].iloc[0].startswith("POINT")
    assert str(df["dtg"].dtype).startswith("datetime64")


def test_cli_ingest_workers(tmp_path, capsys):
    import json as _json

    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.tools.cli import main

    root = str(tmp_path / "store")
    FileSystemDataStore(root).create_schema(
        "t", "name:String,*geom:Point"
    )
    files = []
    for i in range(3):
        p = tmp_path / f"in{i}.csv"
        p.write_text(f"x{i},1.0,2.0\ny{i},3.0,4.0\n")
        files.append(str(p))
    conv = tmp_path / "c.json"
    conv.write_text(_json.dumps({
        "type": "delimited-text", "format": "csv", "id-field": "$1",
        "fields": [
            {"name": "name", "transform": "$1"},
            {"name": "geom", "transform": "point($2::double, $3::double)"},
        ],
    }))
    main(["--root", root, "ingest", "-f", "t", "-C", str(conv),
          "-t", "3", *files])
    assert "ingested 6 features" in capsys.readouterr().out
    main(["--root", root, "count", "-f", "t"])
    assert int(capsys.readouterr().out) == 6


def test_device_spatial_join_matches_host(tmp_path):
    """The device coarse pass (window_pairs_query, bit-packed candidate
    pairs) must produce the SAME pair set as the host join — incl. with
    a frame filter fused on device, dwithin, and >64 right rows (the
    64-window chunking boundary)."""
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = _fill_store(tmp_path, n=3000)
    zones = FileSystemDataStore(str(tmp_path / "zones"))
    zones.create_schema("z", "zone:String,*geom:Polygon")
    rng = np.random.default_rng(5)
    m = 70  # crosses the 64-window group boundary
    polys, names = [], []
    for k in range(m):
        x0 = rng.uniform(-9, 7)
        y0 = rng.uniform(-9, 7)
        polys.append(sql.st_makeBBOX(x0, y0, x0 + 2, y0 + 2))
        names.append(f"z{k}")
    zones.write(
        "z", {"zone": names, "geom": np.array(polys, dtype=object)},
        fids=np.arange(m),
    )
    zones.flush("z")
    zf = SpatialFrame(zones, "z")
    di = DeviceIndex(ds, "t")

    def pair_fids(left, right, pairs):
        return sorted(
            (str(left.fids[i]), str(right.fids[j])) for i, j in pairs
        )

    for kwargs in (
        {"on": "within"},
        {"on": "intersects"},
        {"on": "dwithin", "distance": 0.7},
    ):
        pts = SpatialFrame(ds, "t")
        host = pts.spatial_join(zf, **kwargs)
        dev = pts.spatial_join(zf, device_index=di, **kwargs)
        assert pair_fids(*host) == pair_fids(*dev), kwargs

    # frame filter fuses into the device coarse pass
    flt = SpatialFrame(ds, "t").where("val < 50")
    host = flt.spatial_join(zf, on="within")
    dev = flt.spatial_join(zf, on="within", device_index=di)
    assert pair_fids(*host) == pair_fids(*dev)
    assert len(host[2]) > 0
    # every joined left row satisfies the filter
    assert np.all(dev[0].columns["val"][dev[2][:, 0]] < 50)

    # a host-residual filter falls back (still correct)
    flt2 = SpatialFrame(ds, "t").where("name LIKE 'a%'")
    host2 = flt2.spatial_join(zf, on="within")
    dev2 = flt2.spatial_join(zf, on="within", device_index=di)
    assert pair_fids(*host2) == pair_fids(*dev2)


# -- spheroid measures (WGS84 Vincenty + antipodal fallback) -----------------


def test_distance_spheroid_known_values():
    # one degree of latitude at the equator on WGS84: 110,574.3 m
    d = sql.st_distanceSpheroid(sql.st_point(0, 0), sql.st_point(0, 1))
    assert abs(d - 110_574.3) < 5.0
    # one degree of longitude on the equator: 111,319.49 m
    d = sql.st_distanceSpheroid(sql.st_point(0, 0), sql.st_point(1, 0))
    assert abs(d - 111_319.49) < 5.0
    # coincident points are exactly zero
    assert sql.st_distanceSpheroid(sql.st_point(5, 5), sql.st_point(5, 5)) == 0.0


def test_distance_spheroid_antipodal_fallback():
    # Vincenty's lambda iteration oscillates for (near-)antipodal pairs;
    # the documented haversine fallback must kick in with a finite,
    # sane value (half the mean circumference ~ 20,015 km).
    for lon2, lat2 in ((180.0, 0.0), (179.7, 0.3), (-179.9, 0.05)):
        d = sql.st_distanceSpheroid(sql.st_point(0, 0), sql.st_point(lon2, lat2))
        assert np.isfinite(d)
        assert 19_800_000 < d < 20_100_000, (lon2, lat2, d)


def test_length_spheroid_matches_segment_sum():
    line = sql.st_makeLine([sql.st_point(0, 0), sql.st_point(0, 1), sql.st_point(1, 1)])
    total = sql.st_lengthSpheroid(line)
    d1 = sql.st_distanceSpheroid(sql.st_point(0, 0), sql.st_point(0, 1))
    d2 = sql.st_distanceSpheroid(sql.st_point(0, 1), sql.st_point(1, 1))
    assert abs(total - (d1 + d2)) < 1e-6


def test_window_pairs_compaction_overflow_fallback():
    """A dense window whose candidates exceed the device-compaction cap
    C must fall back to the full bit-plane fetch and still return every
    pair (the only correctness-critical branch of the compaction)."""
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.store.memory import MemoryDataStore

    n = 1 << 18  # plane_n 262144 -> C = 8192 << n: overflow reachable
    rng = np.random.default_rng(9)
    ds = MemoryDataStore()
    ds.create_schema("t", "dtg:Date,*geom:Point:srid=4326")
    ds.write("t", {
        "dtg": rng.integers(1_577_836_800_000, 1_583_020_800_000, n),
        "geom": np.stack(
            [rng.uniform(-60, 60, n), rng.uniform(-50, 50, n)], axis=1
        ),
    })
    di = DeviceIndex(ds, "t")
    # window 0 covers everything (cnt == n > C); window 1 is tiny
    envs = np.array([
        [-180.0, -90.0, 180.0, 90.0],
        [0.0, 0.0, 0.5, 0.5],
    ])
    rows, wins = di.window_pairs_query(envs)
    assert int((wins == 0).sum()) == n  # dense window: every row
    g = np.asarray(ds.query("t", "INCLUDE").batch.columns["geom"])
    want1 = np.nonzero(
        (g[:, 0] >= 0) & (g[:, 0] <= 0.5) & (g[:, 1] >= 0) & (g[:, 1] <= 0.5)
    )[0]
    got1 = np.sort(rows[wins == 1])
    assert set(want1.tolist()) <= set(got1.tolist())
