"""AOT warmup (ISSUE 17): the closed bucket x kernel-family pre-compile
pass. Proves the three load-bearing properties end to end on the CPU
harness: (1) warmup charges the ``_system`` ledger tenant — never the
request collector that happens to be installed on the caller's thread
(the misattribution bugfix); (2) ``/readyz`` gates on warmup per
``compile.warmup.gate`` with the ``warming`` stamp race-free from the
moment ``start()`` returns; (3) after a warmup pass the base serving
legs pay ZERO backend compiles — the acceptance criterion behind the
fleet warm-handoff guarantee."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import ledger, metrics, warmup
from geomesa_tpu.conf import prop_override
from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.server import serve_background
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"


def _store(n=800, tn="t"):
    ds = MemoryDataStore()
    ds.create_schema(tn, SPEC)
    rng = np.random.default_rng(7)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        tn,
        {
            "name": rng.choice(["a", "b"], n),
            "val": rng.integers(0, 100, n),
            "dtg": t0 + rng.integers(0, 10**8, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return ds


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_run_charges_system_tenant_never_the_caller():
    """The bugfix regression: a request collector installed on the
    CALLER's thread must see none of the warmup compiles — every leg
    runs under its own ``_system`` collector on the pool thread, so
    ``/stats/ledger`` pins background compile seconds where they
    belong instead of on the first unlucky tenant."""
    di = DeviceIndex(_store(), "t", z_planes=True)
    ledger.LEDGER.reset()
    warmup.reset()
    with ledger.collect_cost(
        tenant="alice", endpoint="query", lane="online"
    ) as cost:
        out = warmup.run({"t": di}, threads=2, knn_kmax=8, fusion_max=4)
    assert cost.snapshot_fields().get("compiles", 0) == 0
    assert cost.snapshot_fields().get("compile_s", 0) == 0
    snap = ledger.LEDGER.snapshot()
    assert "_system" in snap["tenants"]
    assert snap["tenants"]["_system"]["cost"].get("compiles", 0) > 0
    assert "alice" not in snap["tenants"]  # alice's cost never recorded
    # progress accounting closes: every leg lands in exactly one bucket
    assert out["state"] == "warm" and out["failed"] == 0
    assert out["done"] == out["signatures_total"] == len(
        warmup.plan({"t": di}, knn_kmax=8, fusion_max=4)
    )
    assert out["compiled"] + out["from_cache"] == out["done"]
    assert out["compiled"] > 0  # a fresh index really compiled
    # ...and the progress gauge mirrors the document
    assert metrics.warmup_signatures.value(state="total") == out["done"]
    assert metrics.warmup_signatures.value(state="failed") == 0


def test_warm_serving_path_pays_zero_compiles():
    """The acceptance criterion in-process: after warmup, replaying the
    base serving legs (plus same-bucket variants at other parameters)
    attributes ZERO backend compiles in the compile ledger."""
    di = DeviceIndex(_store(tn="g"), "g", z_planes=True)
    warmup.reset()
    out = warmup.run({"g": di}, threads=2, knn_kmax=16, fusion_max=8)
    assert out["failed"] == 0
    ledger.COMPILES.reset()
    for _sig, fn in di.warmup_plan():
        fn()
    # same-bucket variants: different k / point / width, same rung
    di.knn(1.5, -2.0, 5)  # kk rung 8, warmed
    di.knn(0.0, 0.0, 13)  # kk rung 16, warmed via the k-ladder
    from geomesa_tpu.filter import ast as _ast

    q = _ast.BBox("geom", -0.05, -0.05, 0.05, 0.05)
    di.fused_loose_counts([q] * 5)  # qcap rung 8, warmed
    snap = ledger.COMPILES.snapshot()
    assert snap["compiles"] == 0, snap["by_signature"]


def test_failed_leg_is_counted_not_raised():
    class _Boom:
        def warmup_plan(self, knn_kmax=None, fusion_max=None):
            return [("boom", self._die), ("ok", lambda: 1)]

        def _die(self):
            raise RuntimeError("kernel exploded")

    warmup.reset()
    out = warmup.run({"t": _Boom()}, threads=1)
    assert out == {
        "state": "warm", "signatures_total": 2, "done": 2,
        "compiled": 0, "from_cache": 1, "failed": 1,
        "seconds": out["seconds"],
    }


class _Blocked:
    """Fake index whose single warmup leg parks until released — makes
    the warming window deterministic for the readiness-gate tests."""

    def __init__(self):
        self.release = threading.Event()

    def warmup_plan(self, knn_kmax=None, fusion_max=None):
        return [("block", self.release.wait)]


@pytest.fixture()
def gated_server():
    ds = _store(n=50)
    server, _ = serve_background(ds)
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    # simulate make_server's warm path: mark warmup started on this
    # server's handler class and run a blocked pass
    server.RequestHandlerClass._warmup_started = True
    warmup.reset()
    fake = _Blocked()
    thread = warmup.start({"t": fake})
    try:
        yield url, fake, thread
    finally:
        fake.release.set()
        thread.join(timeout=10)
        server.shutdown()
        warmup.reset()


def test_readyz_gates_until_warm(gated_server):
    url, fake, thread = gated_server
    # start() stamps `warming` before returning: no ready-but-cold race
    assert warmup.warming()
    status, doc = _get(f"{url}/readyz")  # default gate: ready
    assert status == 503 and doc["warming"] and not doc["ready"]
    with prop_override("compile.warmup.gate", "stamp"):
        status, doc = _get(f"{url}/readyz")
        assert status == 200 and doc["warming"] and doc["ready"]
    with prop_override("compile.warmup.gate", "off"):
        status, doc = _get(f"{url}/readyz")
        assert status == 200 and "warming" not in doc
    # warmup progress is surfaced on /stats while warming
    status, doc = _get(f"{url}/stats")
    assert status == 200 and doc["warmup"]["state"] == "warming"
    assert "compile_cache" in doc
    fake.release.set()
    thread.join(timeout=10)
    status, doc = _get(f"{url}/readyz")
    assert status == 200 and doc["ready"] and "warming" not in doc
    status, doc = _get(f"{url}/stats")
    assert doc["warmup"]["state"] == "warm"
    assert doc["warmup"]["done"] == 1


def test_warmup_cli_reports_remote_progress(gated_server, capsys):
    """`geomesa-tpu warmup --url` is the operator's progress probe."""
    from geomesa_tpu.tools.cli import main

    url, fake, thread = gated_server
    main(["warmup", "--url", url])
    out = capsys.readouterr().out
    assert "warming" in out and "0/1" in out
    fake.release.set()
    thread.join(timeout=10)
    main(["warmup", "--url", url])
    assert "warm" in capsys.readouterr().out


def test_server_warm_runs_background_warmup():
    """make_server(warm=True) + warmup enabled: the resident cache is
    populated synchronously (the PR 4 contract) and the FULL bucket
    ladder warms in the background under the ``_system`` tenant."""
    ds = _store(n=60, tn="gdelt")
    warmup.reset()
    server, _ = serve_background(ds, resident=True, warm=True)
    try:
        host, port = server.server_address[:2]
        url = f"http://{host}:{port}"
        assert "gdelt" in server.RequestHandlerClass._resident_cache
        # poll /readyz until the gate opens (bounded: legs are tiny)
        for _ in range(600):
            status, doc = _get(f"{url}/readyz")
            if status == 200:
                break
            threading.Event().wait(0.1)
        assert status == 200 and "warming" not in doc
        status, doc = _get(f"{url}/stats")
        assert doc["warmup"]["state"] == "warm"
        assert doc["warmup"]["signatures_total"] > 0
        assert doc["warmup"]["failed"] == 0
    finally:
        server.shutdown()
        warmup.reset()
