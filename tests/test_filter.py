"""Filter layer: ECQL parsing, bound extraction, host/device evaluation."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.filter import (
    And,
    BBox,
    Compare,
    During,
    Exclude,
    Include,
    Intersects,
    Not,
    Or,
    compile_filter,
    extract_geometries,
    extract_intervals,
    parse_ecql,
)
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_instant

SPEC = "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"
SFT = SimpleFeatureType.create("t", SPEC)


def make_batch(n=1000, seed=5):
    rng = np.random.default_rng(seed)
    return FeatureBatch.from_columns(
        SFT,
        {
            "name": rng.choice(["alpha", "beta", "gamma"], n),
            "count": rng.integers(0, 50, n),
            "dtg": rng.integers(
                parse_instant("2020-01-01T00:00:00"),
                parse_instant("2020-02-01T00:00:00"),
                n,
            ),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(30, 60, n)], axis=1
            ),
        },
    )


class TestParse:
    def test_bbox_and_during(self):
        f = parse_ecql(
            "BBOX(geom, -5, 42, 8, 51) AND dtg DURING 2020-01-05T00:00:00Z/2020-01-10T00:00:00Z"
        )
        assert isinstance(f, And)
        bbox, during = f.children
        assert bbox == BBox("geom", -5, 42, 8, 51)
        assert during.t0 == parse_instant("2020-01-05T00:00:00")

    def test_intersects_polygon(self):
        f = parse_ecql("INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))")
        assert isinstance(f, Intersects)
        assert f.geometry.envelope.xmax == 10

    def test_comparisons(self):
        f = parse_ecql("count >= 5 AND count < 40 AND name = 'alpha'")
        ops = [c.op for c in f.children]
        assert ops == [">=", "<", "="]
        assert f.children[2].value == "alpha"

    def test_or_not_nesting(self):
        f = parse_ecql("(count > 5 OR count < 2) AND NOT name = 'beta'")
        assert isinstance(f, And)
        assert isinstance(f.children[0], Or)
        assert isinstance(f.children[1], Not)

    def test_between_in_like_null(self):
        f = parse_ecql(
            "count BETWEEN 5 AND 10 OR name IN ('a', 'b') OR name LIKE 'al%' OR name IS NULL"
        )
        assert len(f.children) == 4

    def test_date_compare_quoted(self):
        f = parse_ecql("dtg >= '2020-01-05T00:00:00' AND dtg AFTER 2020-01-01T00:00:00Z")
        assert f.children[0].value == parse_instant("2020-01-05T00:00:00")
        assert f.children[1].op == ">"

    def test_include_exclude(self):
        assert parse_ecql("INCLUDE") is Include
        assert parse_ecql("EXCLUDE") is Exclude

    def test_errors(self):
        for bad in ["count >=", "BBOX(geom, 1, 2, 3)", "name SMELLS 'x'"]:
            with pytest.raises(ValueError):
                parse_ecql(bad)


class TestExtract:
    def test_bbox_and_interval(self):
        f = parse_ecql(
            "BBOX(geom, -5, 42, 8, 51) AND dtg DURING 2020-01-05T00:00:00Z/2020-01-10T00:00:00Z AND count > 3"
        )
        g = extract_geometries(f, "geom")
        assert len(g.values) == 1
        env = g.values[0][0]
        assert (env.xmin, env.ymax) == (-5, 51)
        t = extract_intervals(f, "dtg")
        assert t.values == (
            (parse_instant("2020-01-05T00:00:00"), parse_instant("2020-01-10T00:00:00")),
        )

    def test_and_intersection(self):
        f = parse_ecql("BBOX(geom, 0, 0, 10, 10) AND BBOX(geom, 5, 5, 20, 20)")
        g = extract_geometries(f, "geom")
        env = g.values[0][0]
        assert (env.xmin, env.ymin, env.xmax, env.ymax) == (5, 5, 10, 10)

    def test_and_disjoint_is_empty(self):
        f = parse_ecql("BBOX(geom, 0, 0, 1, 1) AND BBOX(geom, 5, 5, 6, 6)")
        assert extract_geometries(f, "geom").empty

    def test_or_union(self):
        f = parse_ecql("BBOX(geom, 0, 0, 1, 1) OR BBOX(geom, 5, 5, 6, 6)")
        assert len(extract_geometries(f, "geom").values) == 2

    def test_or_with_unbounded_branch(self):
        f = parse_ecql("BBOX(geom, 0, 0, 1, 1) OR count > 5")
        assert extract_geometries(f, "geom").unbounded

    def test_not_unbounded(self):
        f = parse_ecql("NOT BBOX(geom, 0, 0, 1, 1)")
        assert extract_geometries(f, "geom").unbounded

    def test_open_interval(self):
        f = parse_ecql("dtg >= '2020-01-05T00:00:00'")
        t = extract_intervals(f, "dtg")
        assert t.values[0][0] == parse_instant("2020-01-05T00:00:00")


class TestEvaluate:
    def test_host_bbox_during(self):
        b = make_batch()
        f = parse_ecql(
            "BBOX(geom, -5, 42, 8, 51) AND dtg DURING 2020-01-05T00:00:00Z/2020-01-10T00:00:00Z"
        )
        m = evaluate_host(f, b)
        x, y = b.point_coords()
        dtg = b.column("dtg")
        expected = (
            (x >= -5) & (x <= 8) & (y >= 42) & (y <= 51)
            & (dtg >= parse_instant("2020-01-05T00:00:00"))
            & (dtg <= parse_instant("2020-01-10T00:00:00"))
        )
        np.testing.assert_array_equal(m, expected)

    def test_host_string_ops(self):
        b = make_batch()
        m = evaluate_host(parse_ecql("name LIKE 'al%'"), b)
        np.testing.assert_array_equal(m, b.column("name") == "alpha")
        m = evaluate_host(parse_ecql("name IN ('alpha', 'gamma')"), b)
        np.testing.assert_array_equal(
            m, np.isin(b.column("name"), ["alpha", "gamma"])
        )

    def test_host_intersects_points(self):
        b = make_batch()
        f = parse_ecql("INTERSECTS(geom, POLYGON ((-5 40, 10 40, 10 55, -5 55, -5 40)))")
        m = evaluate_host(f, b)
        x, y = b.point_coords()
        expected = (x > -5) & (x < 10) & (y > 40) & (y < 55)
        # interior points agree (boundary measure zero for random data)
        np.testing.assert_array_equal(m, expected)

    def test_device_split_and_equivalence(self):
        import jax.numpy as jnp

        b = make_batch()
        f = parse_ecql(
            "BBOX(geom, -5, 42, 8, 51) AND count > 10 AND name = 'alpha'"
        )
        cf = compile_filter(f, SFT)
        assert not cf.fully_on_device  # name = 'alpha' is host residual
        assert cf.device_cols == ["count", "geom__x", "geom__y"]
        x, y = b.point_coords()
        cols = {
            "geom__x": jnp.asarray(x),
            "geom__y": jnp.asarray(y),
            "count": jnp.asarray(b.column("count")),
        }
        dev_mask = np.asarray(cf.device_fn(cols))
        res_mask = cf.residual_mask(b)
        np.testing.assert_array_equal(dev_mask & res_mask, cf.host_mask(b))

    def test_device_full_filter(self):
        import jax
        import jax.numpy as jnp

        b = make_batch()
        f = parse_ecql(
            "INTERSECTS(geom, POLYGON ((-5 40, 10 40, 10 55, -5 55, -5 40))) AND count BETWEEN 5 AND 30"
        )
        cf = compile_filter(f, SFT)
        assert cf.fully_on_device
        x, y = b.point_coords()
        cols = {
            "geom__x": jnp.asarray(x),
            "geom__y": jnp.asarray(y),
            "count": jnp.asarray(b.column("count")),
        }
        dev_mask = np.asarray(jax.jit(cf.device_fn)(cols))
        np.testing.assert_array_equal(dev_mask, cf.host_mask(b))

    def test_exclude_include(self):
        b = make_batch(10)
        assert evaluate_host(Include, b).all()
        assert not evaluate_host(Exclude, b).any()


class TestNonPointDeviceBBox:
    """Non-point geometries: device BBOX = envelope-overlap on the staged
    bbox planes (exact: BBOX semantics for non-points IS envelope
    intersection), and residual spatial predicates get a device envelope
    prefilter."""

    def _poly_batch(self, n=400, seed=12):
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.features.sft import SimpleFeatureType
        from geomesa_tpu.geom import Polygon

        sft = SimpleFeatureType.create("polys", "val:Int,*geom:Polygon")
        rng = np.random.default_rng(seed)
        polys = []
        for i in range(n):
            cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
            r = rng.uniform(0.1, 3.0)
            ang = np.linspace(0, 2 * np.pi, 8)
            ring = np.stack(
                [cx + r * np.cos(ang), cy + r * np.sin(ang)], axis=1
            )
            ring[-1] = ring[0]
            polys.append(Polygon(ring))
        return sft, FeatureBatch.from_columns(
            sft,
            {"val": rng.integers(0, 100, n),
             "geom": np.array(polys, dtype=object)},
            fids=np.arange(n),
        )

    def test_device_bbox_matches_host(self):
        from geomesa_tpu.filter.compile import compile_filter, evaluate_host
        from geomesa_tpu.filter.ecql import parse_ecql
        from geomesa_tpu.ops.scan import stage_columns

        sft, batch = self._poly_batch()
        f = parse_ecql("BBOX(geom, -20, -20, 40, 30)")
        c = compile_filter(f, sft)
        assert c.fully_on_device, "non-point bbox should be device-only now"
        cols = stage_columns(batch, c.device_cols)
        got = np.asarray(c.device_fn(cols))
        expect = evaluate_host(f, batch)
        np.testing.assert_array_equal(got, expect)

    def test_intersects_gets_envelope_prefilter(self):
        from geomesa_tpu.filter.compile import compile_filter, evaluate_host
        from geomesa_tpu.filter.ecql import parse_ecql
        from geomesa_tpu.ops.scan import stage_columns

        sft, batch = self._poly_batch()
        f = parse_ecql(
            "INTERSECTS(geom, POLYGON((0 0, 25 0, 25 20, 0 20, 0 0)))"
        )
        c = compile_filter(f, sft)
        assert not c.fully_on_device  # exact test stays residual
        assert c.device_cols, "prefilter should stage envelope planes"
        cols = stage_columns(batch, c.device_cols)
        pre = np.asarray(c.device_fn(cols))
        exact = evaluate_host(f, batch)
        assert not np.any(exact & ~pre), "prefilter dropped a true hit"
        assert pre.sum() < len(batch), "prefilter pruned nothing"

    def test_device_index_over_polygons(self):
        from geomesa_tpu.device_cache import DeviceIndex
        from geomesa_tpu.filter.compile import evaluate_host
        from geomesa_tpu.filter.ecql import parse_ecql
        from geomesa_tpu.store.memory import MemoryDataStore

        sft, batch = self._poly_batch(n=300)
        ds = MemoryDataStore()
        ds.create_schema("polys", "val:Int,*geom:Polygon")
        ds.write("polys", dict(batch.columns), fids=batch.fids)
        di = DeviceIndex(ds, "polys")
        all_batch = ds.query("polys").batch
        for ecql in [
            "BBOX(geom, -20, -20, 40, 30)",
            "BBOX(geom, -20, -20, 40, 30) AND val >= 50",
            "INTERSECTS(geom, POLYGON((0 0, 25 0, 25 20, 0 20, 0 0)))",
            "DWITHIN(geom, POINT(10 10), 5, kilometers)",
        ]:
            expect = evaluate_host(parse_ecql(ecql), all_batch)
            assert di.count(ecql) == int(expect.sum()), ecql
            np.testing.assert_array_equal(
                np.sort(di.query(ecql).fids), np.sort(all_batch.fids[expect]),
                err_msg=ecql,
            )

    def test_pallas_tile_kernel_handles_envelope_planes(self):
        from geomesa_tpu.filter.compile import compile_filter, evaluate_host
        from geomesa_tpu.filter.ecql import parse_ecql
        from geomesa_tpu.ops.pallas_scan import build_pallas_scan
        from geomesa_tpu.ops.scan import stage_columns

        sft, batch = self._poly_batch()
        f = parse_ecql("BBOX(geom, -20, -20, 40, 30)")
        count_fn, mask_fn, cols_needed = build_pallas_scan(
            f, sft, interpret=True
        )
        cols = stage_columns(batch, cols_needed)
        expect = evaluate_host(f, batch)
        np.testing.assert_array_equal(np.asarray(mask_fn(cols)), expect)
        assert int(count_fn(cols)) == int(expect.sum())
