"""Runtime context checker (analysis/ctxcheck.py): the zero-findings
invariant the conftest enforces over the whole suite, seeded detections
for every finding kind on private checker instances, and the runtime
half of the PR 17 regression (a raw thread compiling with no
attribution)."""

import threading

import pytest

from geomesa_tpu import ledger, resilience
from geomesa_tpu.analysis import ctxcheck
from geomesa_tpu.spawn import ContextPool, spawn_thread


def _cost(tenant="t"):
    return ledger.RequestCost(
        tenant=tenant, endpoint="e", lane="interactive", shape="s"
    )


@pytest.fixture
def chk(monkeypatch):
    """A private checker swapped in for the module-level one: the
    observer seams dispatch through the module attribute, so seeded
    violations land here and never pollute the session-end report."""
    c = ctxcheck.CtxCheck("private")
    monkeypatch.setattr(ctxcheck, "CHECKER", c)
    return c


def test_enabled_for_the_suite():
    """The conftest arms both env vars before any package import; the
    whole tier-1 run doubles as the sanitizer soak."""
    assert ctxcheck.enabled()


def test_global_checker_zero_findings_invariant():
    """The mid-run half of the conftest enforcement: every blessed task
    spawned by any suite that ran before this test kept its context
    accounting straight."""
    rep = ctxcheck.CHECKER.report()
    assert rep["findings"] == [], rep["findings"]


# -- clean blessed flows stay clean -----------------------------------------


def test_blessed_thread_with_context_is_clean(chk):
    seen = {}

    def work():
        ledger.charge("read_bytes", 3)
        seen["cost"] = ledger.capture_cost()

    with ledger.collect_cost(
        tenant="t", endpoint="e", lane="interactive", shape="s"
    ) as cost:
        t = spawn_thread(work, name="ctx-clean")
        t.start()
        t.join()
    assert seen["cost"] is cost  # the request's collector crossed over
    rep = chk.report()
    assert rep["findings"] == []
    assert rep["tasks"] == 1
    assert rep["charges"] >= 1
    assert rep["attaches"] >= 1


def test_blessed_pool_map_is_clean(chk):
    with resilience.collect_degraded() as reasons:
        with ContextPool(2, thread_name_prefix="ctx-pool") as pool:
            list(pool.map(lambda i: i * 2, range(6)))
    assert reasons == []
    rep = chk.report()
    assert rep["findings"] == []
    assert rep["tasks"] == 6


def test_context_false_service_thread_is_clean(chk):
    t = spawn_thread(lambda: None, name="ctx-svc", context=False)
    t.start()
    t.join()
    rep = chk.report()
    assert rep["findings"] == []
    assert rep["tasks"] == 1


# -- seeded detections, one per finding kind --------------------------------


def test_seeded_ctx_leak_detected(chk):
    """A task that attaches a collector and never resets it poisons its
    pool thread; the pre/post ambient snapshot catches it."""
    cost = _cost()
    token = None
    with chk.task("thread", "leaky", None):
        token = ledger._cost.set(cost)  # attach without reset: the bug
    try:
        kinds = [f["kind"] for f in chk.report()["findings"]]
        assert kinds == ["ctx-leak"]
    finally:
        ledger._cost.reset(token)


def test_seeded_mismatched_cost_detected(chk):
    """A charge into a collector this thread was never handed (the
    smuggled-collector shape) is a finding; a properly attached one is
    not."""
    good, bad = _cost("good"), _cost("bad")
    chk.on_attach(good, True)
    chk.on_charge(good, "device_seconds")
    chk.on_charge(bad, "device_seconds")
    chk.on_attach(good, False)
    fs = chk.report()["findings"]
    assert [f["kind"] for f in fs] == ["mismatched-cost"]
    assert fs[0]["tenant"] == "bad"


def test_seeded_orphan_degraded_detected(chk):
    handed, smuggled = [], []
    chk.on_attach(handed, True)
    chk.on_degraded(handed, "store_read_retry")
    chk.on_degraded(smuggled, "knn_refine_trimmed")
    chk.on_attach(handed, False)
    fs = chk.report()["findings"]
    assert [f["kind"] for f in fs] == ["orphan-degraded"]
    assert fs[0]["reason"] == "knn_refine_trimmed"


def test_seeded_orphan_compile_detected(chk):
    """Scope-less, collector-less compiles are fine on the main thread
    (test harness reality) and a finding on a worker."""
    chk.on_compile(None, None, 0.2)  # main thread: exempt
    chk.on_compile("fused.dim:r=64", None, 0.2)  # scoped: attributed
    chk.on_compile(None, _cost(), 0.2)  # collector: attributed
    t = threading.Thread(  # lint: disable=GT010(seeding the violation the blessed helper exists to prevent)
        target=lambda: chk.on_compile(None, None, 0.3), name="rogue"
    )
    t.start()
    t.join()
    fs = chk.report()["findings"]
    assert [f["kind"] for f in fs] == ["orphan-compile"]
    assert fs[0]["thread"] == "rogue"


def test_findings_dedupe_by_site(chk):
    bad = _cost("bad")
    for _ in range(5):
        chk.on_charge(bad, "device_seconds")
    assert len(chk.report()["findings"]) == 1


def test_clear_resets_counters_and_findings(chk):
    chk.on_charge(_cost("bad"), "read_bytes")
    assert chk.report()["findings"]
    chk.clear()
    rep = chk.report()
    assert rep["findings"] == [] and rep["charges"] == 0


# -- the PR 17 regression, runtime half -------------------------------------


def test_pr17_regression_raw_thread_compile_is_orphaned(chk):
    """A RAW thread (no blessed wrapper, no compile_scope, no request
    collector) that triggers a backend compile: exactly the warmup bug
    PR 17 fixed. The compile-observer seam fires on the compiling
    thread and the checker reports the unattributable seconds."""
    import time

    import jax
    import jax.numpy as jnp

    ledger.install()
    uniq = int(time.perf_counter() * 1e9) % 1_000_033 + 2

    def rogue():
        jax.jit(lambda x: x * uniq + 7)(jnp.arange(263))

    t = threading.Thread(target=rogue, name="pr17-rogue")  # lint: disable=GT010(seeding the violation the blessed helper exists to prevent)
    t.start()
    t.join()
    fs = chk.report()["findings"]
    assert [f["kind"] for f in fs] == ["orphan-compile"], fs
    assert fs[0]["thread"] == "pr17-rogue"
    assert fs[0]["seconds"] > 0


def test_pr17_fixed_shape_blessed_thread_compile_is_attributed(chk):
    """The same compile routed the blessed way -- spawn_thread carrying
    the request context, compile_scope active -- produces zero
    findings and the seconds land on the request collector."""
    import time

    import jax
    import jax.numpy as jnp

    ledger.install()
    uniq = int(time.perf_counter() * 1e9) % 999_959 + 2

    def warm():
        with ledger.compile_scope("warmup:test"):
            jax.jit(lambda x: x * uniq + 9)(jnp.arange(271))

    with ledger.collect_cost(
        tenant="_system", endpoint="warmup", lane="batch", shape="w"
    ) as cost:
        t = spawn_thread(warm, name="pr17-blessed")
        t.start()
        t.join()
    assert chk.report()["findings"] == []
    assert cost.snapshot_fields().get("compiles", 0) >= 1
