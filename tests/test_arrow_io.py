"""Arrow columnar layer: typed geometry vectors, IPC round-trips,
dictionary encoding, self-describing schemas, sorted-stream merge."""

import io

import numpy as np
import pyarrow as pa
import pytest

from geomesa_tpu.arrow_io import (
    arrow_schema_for,
    arrow_to_batch,
    batch_to_arrow,
    merge_sorted_streams,
    read_feature_stream,
    sft_from_schema,
    write_feature_stream,
)
from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.geom import parse_wkt
from geomesa_tpu.geom.wkt import to_wkt


def point_batch(n=50, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.create(
        "pts", "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"
    )
    return FeatureBatch.from_columns(
        sft,
        {
            "name": rng.choice(["alpha", "beta", None], n),
            "count": rng.integers(0, 9, n),
            "dtg": rng.integers(1_577_836_800_000, 1_580_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
    )


class TestSchema:
    def test_point_is_struct_vector(self):
        sch = arrow_schema_for(point_batch().sft)
        f = sch.field("geom")
        assert pa.types.is_struct(f.type)
        assert f.type.field("x").type == pa.float64()

    def test_strings_dictionary_encode(self):
        sch = arrow_schema_for(point_batch().sft)
        assert pa.types.is_dictionary(sch.field("name").type)

    def test_sft_round_trips_via_metadata(self):
        sft = point_batch().sft
        back = sft_from_schema(arrow_schema_for(sft))
        assert back.spec == sft.spec
        assert back.type_name == sft.type_name

    def test_no_metadata_raises(self):
        with pytest.raises(ValueError):
            sft_from_schema(pa.schema([pa.field("a", pa.int32())]))


class TestRoundTrip:
    def test_point_batch(self):
        batch = point_batch()
        back = arrow_to_batch(batch_to_arrow(batch))
        np.testing.assert_allclose(back.column("geom"), batch.column("geom"))
        np.testing.assert_array_equal(back.column("dtg"), batch.column("dtg"))
        np.testing.assert_array_equal(
            back.column("count"), batch.column("count")
        )
        assert list(back.column("name")) == list(batch.column("name"))
        assert [str(f) for f in back.fids] == [str(f) for f in batch.fids]

    @pytest.mark.parametrize(
        "type_name,wkt",
        [
            ("LineString", "LINESTRING (0 0, 1 1, 2 0)"),
            ("Polygon", "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"),
            ("MultiPoint", "MULTIPOINT (1 2, 3 4)"),
            (
                "MultiLineString",
                "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3, 4 2))",
            ),
            (
                "MultiPolygon",
                "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), "
                "((5 5, 7 5, 7 7, 5 7, 5 5), (5.5 5.5, 6 5.5, 6 6, 5.5 6, 5.5 5.5)))",
            ),
        ],
    )
    def test_nested_geometry_vectors(self, type_name, wkt):
        sft = SimpleFeatureType.create("g", f"*geom:{type_name}:srid=4326")
        g = parse_wkt(wkt)
        batch = FeatureBatch.from_columns(
            sft, {"geom": np.array([g, None, g], dtype=object)}
        )
        rb = batch_to_arrow(batch)
        assert not pa.types.is_string(rb.schema.field("geom").type)  # typed!
        back = arrow_to_batch(rb)
        col = back.column("geom")
        assert col[1] is None
        assert to_wkt(col[0]) == to_wkt(g)
        assert to_wkt(col[2]) == to_wkt(g)


class TestIpcStream:
    def test_stream_round_trip_self_describing(self):
        b1, b2 = point_batch(seed=1), point_batch(seed=2)
        buf = io.BytesIO()
        n = write_feature_stream(buf, [b1, b2])
        assert n == 2
        buf.seek(0)
        got = list(read_feature_stream(buf))  # no SFT passed: metadata
        assert len(got) == 2
        np.testing.assert_allclose(
            got[0].column("geom"), b1.column("geom")
        )
        np.testing.assert_array_equal(got[1].column("dtg"), b2.column("dtg"))

    def test_empty_stream_needs_sft(self):
        buf = io.BytesIO()
        with pytest.raises(ValueError):
            write_feature_stream(buf, [])
        buf = io.BytesIO()
        sft = point_batch().sft
        assert write_feature_stream(buf, [], sft=sft) == 0
        buf.seek(0)
        assert list(read_feature_stream(buf)) == []


class TestSortedMerge:
    def test_three_streams_merge_globally_sorted(self):
        rng = np.random.default_rng(0)
        batches = []
        allvals = []
        for s in range(3):
            vals = np.sort(rng.integers(0, 10_000, 257))
            allvals.append(vals)
            sft = point_batch().sft
            n = len(vals)
            batches.append(
                [
                    FeatureBatch.from_columns(
                        sft,
                        {
                            "name": np.array(["s%d" % s] * k, dtype=object),
                            "count": np.zeros(k, np.int32),
                            "dtg": chunk,
                            "geom": np.zeros((k, 2)),
                        },
                        fids=np.arange(k),
                    )
                    for chunk in np.array_split(vals, 3)
                    for k in [len(chunk)]
                ]
            )
        out = list(merge_sorted_streams(batches, "dtg", batch_size=100))
        merged = np.concatenate([b.column("dtg") for b in out])
        expect = np.sort(np.concatenate(allvals))
        np.testing.assert_array_equal(merged, expect)
        assert all(len(b) <= 100 for b in out[:-1])

    def test_merge_empty_streams(self):
        assert list(merge_sorted_streams([[], []], "dtg")) == []


class TestDeltaWriter:
    SPEC = "name:String,tag:String,count:Int,dtg:Date,*geom:Point:srid=4326"

    def _batches(self, seed, n_batches=4, n=500):
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.features.sft import SimpleFeatureType

        sft = SimpleFeatureType.create("delta", self.SPEC)
        rng = np.random.default_rng(seed)
        out = []
        fid = 0
        for k in range(n_batches):
            # vocabulary GROWS across batches: batch k introduces new words
            vocab = [f"w{j}" for j in range((k + 1) * 3)]
            out.append(
                FeatureBatch.from_columns(
                    sft,
                    {
                        "name": rng.choice(vocab, n),
                        "tag": rng.choice(["a", "b"], n),
                        "count": rng.integers(0, 100, n),
                        "dtg": rng.integers(0, 10**9, n),
                        "geom": np.stack(
                            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)],
                            axis=1,
                        ),
                    },
                    np.arange(fid, fid + n),
                )
            )
            fid += n
        return sft, out

    def test_roundtrip_equals_plain_ipc(self):
        import io as _io

        from geomesa_tpu.arrow_io import (
            read_feature_stream,
            write_delta_stream,
            write_feature_stream,
        )
        from geomesa_tpu.features.batch import FeatureBatch

        sft, batches = self._batches(1)
        delta, plain = _io.BytesIO(), _io.BytesIO()
        assert write_delta_stream(delta, batches, sft=sft) == len(batches)
        write_feature_stream(plain, batches, sft=sft)
        got = FeatureBatch.concat(list(read_feature_stream(_io.BytesIO(delta.getvalue()))))
        want = FeatureBatch.concat(list(read_feature_stream(_io.BytesIO(plain.getvalue()))))
        np.testing.assert_array_equal(got.fids, want.fids)
        for name in ("name", "tag", "count", "dtg"):
            np.testing.assert_array_equal(got.columns[name], want.columns[name])
        np.testing.assert_allclose(got.columns["geom"], want.columns["geom"])

    def test_dictionaries_grow_monotonically(self):
        import io as _io

        from geomesa_tpu.arrow_io import DeltaWriter

        sft, batches = self._batches(2)
        sink = _io.BytesIO()
        with DeltaWriter(sink, sft) as w:
            prefixes = []
            for b in batches:
                w.write(b)
                prefixes.append(w.dictionary("name"))
        # each snapshot is a prefix of the next (monotone growth = deltas)
        for a, b in zip(prefixes[:-1], prefixes[1:]):
            assert b[: len(a)] == a
        assert len(prefixes[-1]) > len(prefixes[0])

    def test_delta_messages_on_wire(self):
        """The IPC stream must contain dictionary DELTA messages, not
        full replacements (isDelta flag in the message header)."""
        import io as _io

        import pyarrow.ipc as ipc

        from geomesa_tpu.arrow_io import write_delta_stream

        sft, batches = self._batches(3)
        sink = _io.BytesIO()
        write_delta_stream(sink, batches, sft=sft)
        sink.seek(0)
        kinds = [m.type for m in ipc.MessageReader.open_stream(sink)]
        # growing vocab across 4 batches -> additional dictionary messages
        # after the first (deltas; the stream format forbids replacements,
        # so a successful write with >1 dictionary message means deltas)
        assert kinds.count("dictionary") > 2, kinds
        assert kinds.count("record batch") == len(batches)

    def test_sorted_merge_unified_dictionaries(self):
        import io as _io

        from geomesa_tpu.arrow_io import (
            read_feature_stream,
            write_delta_stream,
            write_merged_delta_stream,
        )
        from geomesa_tpu.features.batch import FeatureBatch

        sft, batches = self._batches(4, n_batches=3, n=400)
        # three independent sorted delta streams (as three servers would)
        sources = []
        all_counts = []
        for b in batches:
            order = np.argsort(b.columns["count"], kind="stable")
            sb = b.take(order)
            all_counts.append(sb.columns["count"])
            s = _io.BytesIO()
            write_delta_stream(s, [sb], sft=sft)
            sources.append(_io.BytesIO(s.getvalue()))
        merged_sink = _io.BytesIO()
        write_merged_delta_stream(merged_sink, sources, "count", sft=sft)
        got = FeatureBatch.concat(
            list(read_feature_stream(_io.BytesIO(merged_sink.getvalue())))
        )
        c = got.columns["count"]
        assert np.all(np.diff(c.astype(np.int64)) >= 0), "merge not sorted"
        np.testing.assert_array_equal(
            np.sort(np.concatenate(all_counts)), np.sort(c)
        )
        assert len(got) == sum(len(b) for b in batches)

    def test_server_arrow_endpoint_emits_deltas(self):
        """The HTTP bridge's f=arrow path streams delta batches."""
        import io as _io

        from geomesa_tpu.arrow_io import read_feature_stream
        from geomesa_tpu.process.conversion import arrow_conversion
        from geomesa_tpu.store import MemoryDataStore

        store = MemoryDataStore()
        sft, batches = self._batches(5, n_batches=2)
        store.create_schema(sft)
        for b in batches:
            store.write("delta", b)
        data = arrow_conversion(store, "delta", batch_size=256)
        got = list(read_feature_stream(_io.BytesIO(data)))
        assert sum(len(b) for b in got) == 1000
        assert len(got) >= 4  # actually chunked

    def test_sort_key_with_chunking_stays_sorted(self):
        """Regression: sorting must happen BEFORE chunking, or chunked
        streams are only per-chunk sorted and the k-way merge silently
        misorders rows."""
        import io as _io

        from geomesa_tpu.arrow_io import read_feature_stream, write_delta_stream
        from geomesa_tpu.features.batch import FeatureBatch

        sft, batches = self._batches(6, n_batches=1, n=1000)
        sink = _io.BytesIO()
        write_delta_stream(
            sink, batches, sft=sft, sort_key="count", chunk_size=100
        )
        got = FeatureBatch.concat(
            list(read_feature_stream(_io.BytesIO(sink.getvalue())))
        )
        c = got.columns["count"].astype(np.int64)
        assert np.all(np.diff(c) >= 0), "chunked stream not globally sorted"

    def test_merge_preserves_visibility_labels(self):
        """Regression: the k-way merge must carry the reserved visibility
        column through _take_rows, not silently drop security labels."""
        import io as _io

        from geomesa_tpu.arrow_io import (
            read_feature_stream,
            write_delta_stream,
            write_merged_delta_stream,
        )
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.security import VIS_COLUMN

        sft, batches = self._batches(7, n_batches=2, n=50)
        sources = []
        for k, b in enumerate(batches):
            b = b.take(np.argsort(b.columns["count"], kind="stable"))
            b = b.with_visibility([f"label{k}"] * len(b))
            s = _io.BytesIO()
            write_delta_stream(s, [b], sft=sft)
            sources.append(_io.BytesIO(s.getvalue()))
        sink = _io.BytesIO()
        write_merged_delta_stream(sink, sources, "count", sft=sft)
        got = FeatureBatch.concat(
            list(read_feature_stream(_io.BytesIO(sink.getvalue())))
        )
        vis = got.columns.get(VIS_COLUMN)
        assert vis is not None
        assert set(vis.tolist()) == {"label0", "label1"}

    def test_merge_mixed_labeled_and_unlabeled_sources(self):
        """Regression: visibility presence is decided from the SOURCE
        stream schemas. With an unlabeled source whose keys sort first,
        the first merged chunk is entirely unlabeled — a first-chunk
        sniff would fix a label-free schema and silently strip every
        later label."""
        import io as _io

        from geomesa_tpu.arrow_io import (
            read_feature_stream,
            write_delta_stream,
            write_merged_delta_stream,
        )
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.security import VIS_COLUMN

        sft, batches = self._batches(11, n_batches=2, n=9000)
        lo, hi = batches
        # unlabeled source occupies keys [0, 100): the whole first merge
        # chunk (8192 rows) comes from it
        lo.columns["count"] = np.asarray(lo.columns["count"]) % 100
        hi.columns["count"] = np.asarray(hi.columns["count"]) % 100 + 20000
        hi = hi.with_visibility(["secret"] * len(hi))
        sources = []
        for b in (lo, hi):
            b = b.take(np.argsort(b.columns["count"], kind="stable"))
            s = _io.BytesIO()
            write_delta_stream(s, [b], sft=sft)
            sources.append(_io.BytesIO(s.getvalue()))
        sink = _io.BytesIO()
        write_merged_delta_stream(sink, sources, "count", sft=sft)
        got = FeatureBatch.concat(
            list(read_feature_stream(_io.BytesIO(sink.getvalue())))
        )
        vis = got.columns.get(VIS_COLUMN)
        assert vis is not None
        labeled = np.asarray(vis) == "secret"
        assert labeled.sum() == 9000
        assert np.all(
            np.asarray(got.columns["count"]).astype(np.int64)[labeled] >= 20000
        )

    def test_later_labeled_batch_on_unlabeled_stream_raises(self):
        """No silent stripping: a labeled batch after an unlabeled first
        batch must fail loudly, not lose its labels."""
        import io as _io

        import pytest as _pytest

        from geomesa_tpu.arrow_io import write_feature_stream

        sft, batches = self._batches(13, n_batches=2, n=50)
        a, b = batches
        b = b.with_visibility(["secret"] * len(b))
        with _pytest.raises(ValueError, match="visibility"):
            write_feature_stream(_io.BytesIO(), [a, b], sft=sft)

    def test_relate_matches_accepts_dimension_matrices(self):
        """Regression: standard JTS-style matrices carry dimension digits;
        a digit cell is non-empty (matches 'T', fails 'F')."""
        from geomesa_tpu.geom.predicates import relate_matches

        assert relate_matches("212101212", "T*T***T**")
        assert not relate_matches("212101212", "FF*FF****")
        assert relate_matches("FF2FF1212", "FF*FF****")
