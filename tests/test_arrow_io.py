"""Arrow columnar layer: typed geometry vectors, IPC round-trips,
dictionary encoding, self-describing schemas, sorted-stream merge."""

import io

import numpy as np
import pyarrow as pa
import pytest

from geomesa_tpu.arrow_io import (
    arrow_schema_for,
    arrow_to_batch,
    batch_to_arrow,
    merge_sorted_streams,
    read_feature_stream,
    sft_from_schema,
    write_feature_stream,
)
from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.geom import parse_wkt
from geomesa_tpu.geom.wkt import to_wkt


def point_batch(n=50, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.create(
        "pts", "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"
    )
    return FeatureBatch.from_columns(
        sft,
        {
            "name": rng.choice(["alpha", "beta", None], n),
            "count": rng.integers(0, 9, n),
            "dtg": rng.integers(1_577_836_800_000, 1_580_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
    )


class TestSchema:
    def test_point_is_struct_vector(self):
        sch = arrow_schema_for(point_batch().sft)
        f = sch.field("geom")
        assert pa.types.is_struct(f.type)
        assert f.type.field("x").type == pa.float64()

    def test_strings_dictionary_encode(self):
        sch = arrow_schema_for(point_batch().sft)
        assert pa.types.is_dictionary(sch.field("name").type)

    def test_sft_round_trips_via_metadata(self):
        sft = point_batch().sft
        back = sft_from_schema(arrow_schema_for(sft))
        assert back.spec == sft.spec
        assert back.type_name == sft.type_name

    def test_no_metadata_raises(self):
        with pytest.raises(ValueError):
            sft_from_schema(pa.schema([pa.field("a", pa.int32())]))


class TestRoundTrip:
    def test_point_batch(self):
        batch = point_batch()
        back = arrow_to_batch(batch_to_arrow(batch))
        np.testing.assert_allclose(back.column("geom"), batch.column("geom"))
        np.testing.assert_array_equal(back.column("dtg"), batch.column("dtg"))
        np.testing.assert_array_equal(
            back.column("count"), batch.column("count")
        )
        assert list(back.column("name")) == list(batch.column("name"))
        assert [str(f) for f in back.fids] == [str(f) for f in batch.fids]

    @pytest.mark.parametrize(
        "type_name,wkt",
        [
            ("LineString", "LINESTRING (0 0, 1 1, 2 0)"),
            ("Polygon", "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"),
            ("MultiPoint", "MULTIPOINT (1 2, 3 4)"),
            (
                "MultiLineString",
                "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3, 4 2))",
            ),
            (
                "MultiPolygon",
                "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), "
                "((5 5, 7 5, 7 7, 5 7, 5 5), (5.5 5.5, 6 5.5, 6 6, 5.5 6, 5.5 5.5)))",
            ),
        ],
    )
    def test_nested_geometry_vectors(self, type_name, wkt):
        sft = SimpleFeatureType.create("g", f"*geom:{type_name}:srid=4326")
        g = parse_wkt(wkt)
        batch = FeatureBatch.from_columns(
            sft, {"geom": np.array([g, None, g], dtype=object)}
        )
        rb = batch_to_arrow(batch)
        assert not pa.types.is_string(rb.schema.field("geom").type)  # typed!
        back = arrow_to_batch(rb)
        col = back.column("geom")
        assert col[1] is None
        assert to_wkt(col[0]) == to_wkt(g)
        assert to_wkt(col[2]) == to_wkt(g)


class TestIpcStream:
    def test_stream_round_trip_self_describing(self):
        b1, b2 = point_batch(seed=1), point_batch(seed=2)
        buf = io.BytesIO()
        n = write_feature_stream(buf, [b1, b2])
        assert n == 2
        buf.seek(0)
        got = list(read_feature_stream(buf))  # no SFT passed: metadata
        assert len(got) == 2
        np.testing.assert_allclose(
            got[0].column("geom"), b1.column("geom")
        )
        np.testing.assert_array_equal(got[1].column("dtg"), b2.column("dtg"))

    def test_empty_stream_needs_sft(self):
        buf = io.BytesIO()
        with pytest.raises(ValueError):
            write_feature_stream(buf, [])
        buf = io.BytesIO()
        sft = point_batch().sft
        assert write_feature_stream(buf, [], sft=sft) == 0
        buf.seek(0)
        assert list(read_feature_stream(buf)) == []


class TestSortedMerge:
    def test_three_streams_merge_globally_sorted(self):
        rng = np.random.default_rng(0)
        batches = []
        allvals = []
        for s in range(3):
            vals = np.sort(rng.integers(0, 10_000, 257))
            allvals.append(vals)
            sft = point_batch().sft
            n = len(vals)
            batches.append(
                [
                    FeatureBatch.from_columns(
                        sft,
                        {
                            "name": np.array(["s%d" % s] * k, dtype=object),
                            "count": np.zeros(k, np.int32),
                            "dtg": chunk,
                            "geom": np.zeros((k, 2)),
                        },
                        fids=np.arange(k),
                    )
                    for chunk in np.array_split(vals, 3)
                    for k in [len(chunk)]
                ]
            )
        out = list(merge_sorted_streams(batches, "dtg", batch_size=100))
        merged = np.concatenate([b.column("dtg") for b in out])
        expect = np.sort(np.concatenate(allvals))
        np.testing.assert_array_equal(merged, expect)
        assert all(len(b) <= 100 for b in out[:-1])

    def test_merge_empty_streams(self):
        assert list(merge_sorted_streams([[], []], "dtg")) == []
