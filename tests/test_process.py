"""Process layer: density, BIN, kNN, sampling, stats DSL, tube select."""

import numpy as np
import pytest

from geomesa_tpu.geom import Envelope
from geomesa_tpu.process import (
    decode_bin,
    density,
    encode_bin,
    knn,
    run_stats,
    sample,
    tube_select,
)
from geomesa_tpu.stats import parse_stat
from geomesa_tpu.store import MemoryDataStore

SPEC = "track:String,val:Double,dtg:Date,*geom:Point"


@pytest.fixture(scope="module")
def store():
    s = MemoryDataStore(partition_size=8192)
    s.create_schema("ais", SPEC)
    rng = np.random.default_rng(9)
    n = 30000
    t0 = np.datetime64("2021-01-01").astype("datetime64[ms]").astype(np.int64)
    s.write(
        "ais",
        {
            "track": rng.choice([f"v{i}" for i in range(50)], n),
            "val": rng.uniform(0, 1, n),
            "dtg": t0 + rng.integers(0, 10 * 86400000, n),
            "geom": np.stack(
                [rng.uniform(-10, 10, n), rng.uniform(40, 60, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return s


class TestDensity:
    def test_counts_conserved(self, store):
        env = Envelope(-10, 40, 10, 60)
        grid = density(store, "ais", "INCLUDE", env, 64, 32)
        assert grid.shape == (32, 64)
        assert int(grid.sum()) == 30000

    def test_device_matches_host(self, store):
        env = Envelope(-10, 40, 10, 60)
        g1 = density(store, "ais", "BBOX(geom, -5, 45, 5, 55)", env, 32, 32, use_device=True)
        g2 = density(store, "ais", "BBOX(geom, -5, 45, 5, 55)", env, 32, 32, use_device=False)
        np.testing.assert_allclose(g1, g2)

    def test_weighted(self, store):
        env = Envelope(-10, 40, 10, 60)
        g = density(store, "ais", "INCLUDE", env, 8, 8, weight_attr="val")
        st = store._state("ais")
        assert g.sum() == pytest.approx(st.data.column("val").sum(), rel=1e-5)


class TestBin:
    def test_roundtrip(self, store):
        res = store.query("ais", "BBOX(geom, -5, 45, 5, 55)")
        data = encode_bin(res.batch, "track", sort=True)
        assert len(data) == 16 * len(res.batch)
        rec = decode_bin(data)
        assert np.all(np.diff(rec["dtg"]) >= 0)
        np.testing.assert_allclose(
            np.sort(rec["lon"]),
            np.sort(res.batch.point_coords()[0].astype(np.float32)),
        )

    def test_labels(self, store):
        res = store.query("ais", "val > 0.9")
        data = encode_bin(res.batch, "track", label_attr="track")
        rec = decode_bin(data, labels=True)
        assert len(rec) == len(res.batch)
        raw = int(rec["label"][0]).to_bytes(8, "little").rstrip(b"\0").decode()
        assert raw == str(res.batch.column("track")[0])[:8]


class TestKnn:
    def test_knn_exact(self, store):
        st = store._state("ais")
        x, y = st.data.point_coords()
        px, py = 1.5, 50.5
        from geomesa_tpu.process.knn import _dist_deg

        d_all = _dist_deg(x, y, px, py)
        expected = np.sort(d_all)[:10]
        batch, dists = knn(store, "ais", px, py, 10)
        assert len(batch) == 10
        np.testing.assert_allclose(np.sort(dists), expected)

    def test_exhausted_window_stays_clamped(self, store):
        """When the expanding window runs out of radius before finding k
        hits, the search stays clamped to the max-radius bbox instead of
        falling back to an unbounded base-filter scan: a target far from
        all data returns empty, not the whole table's nearest rows."""
        batch, dists = knn(
            store, "ais", 120.0, -40.0, 10,
            initial_radius_deg=0.01, max_radius_deg=0.5,
        )
        assert len(batch) == 0

    def test_exhausted_window_returns_in_radius_hits(self, store):
        # k larger than the dataset: window exhausts, clamped fallback
        # still returns everything within max_radius_deg of the target
        batch, dists = knn(
            store, "ais", 1.5, 50.5, 100000,
            initial_radius_deg=0.01, max_radius_deg=2.0,
        )
        assert 0 < len(batch) < 30000
        assert float(dists.max()) <= 2.0 * np.sqrt(2) + 1e-9


class TestSampling:
    def test_fraction(self, store):
        b = sample(store, "ais", "INCLUDE", fraction=0.1)
        assert abs(len(b) - 3000) < 10

    def test_per_track(self, store):
        b = sample(store, "ais", "INCLUDE", n=2, by_attr="track")
        vals, counts = np.unique(b.column("track"), return_counts=True)
        assert np.all(counts <= 2)
        assert len(vals) == 50


class TestStatsDSL:
    def test_parse_and_run(self, store):
        seq = run_stats(
            store,
            "ais",
            "INCLUDE",
            'Count();MinMax("val");Cardinality("track");TopK("track",5);Histogram("val",10,0,1)',
        )
        count, minmax, card, topk, hist = seq.stats
        assert count.value == 30000
        assert 0 <= minmax.min < 0.001 and 0.999 < minmax.max <= 1
        assert abs(card.estimate - 50) < 5
        assert len(topk.topk) == 5
        assert hist.counts.sum() == 30000
        assert 0.45 < hist.selectivity(0.2, 0.7) < 0.55

    def test_merge(self, rng):
        a, b = parse_stat('MinMax("v")'), parse_stat('MinMax("v")')
        a.stats[0].observe(np.array([1.0, 5.0]))
        b.stats[0].observe(np.array([-3.0, 2.0]))
        a.merge(b)
        assert a.stats[0].bounds == (-3.0, 5.0)

    def test_frequency(self):
        from geomesa_tpu.stats import Frequency

        f = Frequency("x")
        f.observe(np.array(["a"] * 100 + ["b"] * 7))
        assert f.count("a") >= 100
        assert f.count("b") >= 7
        assert f.count("zzz") < 5

    def test_z3histogram(self, store):
        seq = run_stats(store, "ais", "INCLUDE", 'Z3Histogram("geom","dtg")')
        z3h = seq.stats[0]
        assert sum(z3h.counts.values()) == 30000
        assert len(z3h.counts) > 10


class TestTube:
    def test_corridor(self, store):
        st = store._state("ais")
        t0 = int(st.data.column("dtg").min())
        track = np.array([[-5.0, 45.0], [0.0, 50.0], [5.0, 55.0]])
        times = np.array([t0, t0 + 3600_000, t0 + 7200_000])
        batch = tube_select(store, "ais", track, times, buffer_deg=1.0, max_dt_ms=86400_000)
        # every result is near the track and time-consistent
        if len(batch):
            from geomesa_tpu.process.tube import _point_segment_dist

            x, y = batch.point_coords()
            d0, _ = _point_segment_dist(x, y, *track[0], *track[1])
            d1, _ = _point_segment_dist(x, y, *track[1], *track[2])
            assert np.all(np.minimum(d0, d1) <= 1.0)
        # a corridor in empty ocean matches nothing
        far = tube_select(
            store,
            "ais",
            np.array([[100.0, -50.0], [110.0, -40.0]]),
            np.array([t0, t0 + 3600_000]),
            buffer_deg=1.0,
            max_dt_ms=86400_000,
        )
        assert len(far) == 0
