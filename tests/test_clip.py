"""Polygon boolean ops (geom/clip.py) vs a Monte-Carlo membership oracle.

The oracle: sample points over the joint bbox; for every op the clipped
result must contain exactly the points satisfying the op's predicate
(inside(A) op inside(B)), judged by the independently-tested
points_in_polygon kernel. Samples within eps of any edge are excluded
(boundary membership is representation-dependent). This checks BOTH area
and topology without trusting the clipper's own machinery.
"""

from __future__ import annotations

import numpy as np
import pytest

from geomesa_tpu.geom.base import MultiPolygon, Polygon
from geomesa_tpu.geom.clip import (
    polygon_difference,
    polygon_intersection,
    polygon_sym_difference,
    polygon_union,
)
from geomesa_tpu.geom.predicates import points_in_polygon


def _inside(pts, geom) -> np.ndarray:
    if isinstance(geom, MultiPolygon):
        m = np.zeros(len(pts), bool)
        for p in geom.polygons:
            m |= _inside(pts, p)
        return m
    return points_in_polygon(pts[:, 0], pts[:, 1], geom.rings())


def _edges(geom):
    if isinstance(geom, MultiPolygon):
        for p in geom.polygons:
            yield from _edges(p)
        return
    for r in geom.rings():
        r = np.asarray(r)
        for i in range(len(r) - 1):
            yield r[i], r[i + 1]


def _near_edge(pts, geoms, eps) -> np.ndarray:
    near = np.zeros(len(pts), bool)
    for g in geoms:
        for a, b in _edges(g):
            d = b - a
            L2 = float(d @ d)
            if L2 == 0:
                continue
            t = np.clip(((pts - a) @ d) / L2, 0, 1)
            c = a + t[:, None] * d
            near |= np.hypot(*(pts - c).T) < eps
    return near


def _mc_check(a, b, rng, n=20000, tolerate_refusals=False):
    """Check all four ops against the sampled-membership oracle over the
    inputs' joint envelope. Returns (checked, refused); refusals
    (NotImplementedError for pathological topology) only pass through
    when ``tolerate_refusals`` is set."""
    ea, eb = a.envelope, b.envelope
    lo = np.minimum([ea.xmin, ea.ymin], [eb.xmin, eb.ymin]) - 0.5
    hi = np.maximum([ea.xmax, ea.ymax], [eb.xmax, eb.ymax]) + 0.5
    pts = rng.uniform(lo, hi, (n, 2))
    in_a = _inside(pts, a)
    in_b = _inside(pts, b)
    span = float(max(hi[0] - lo[0], hi[1] - lo[1]))
    ops = {
        "intersection": (polygon_intersection, in_a & in_b),
        "union": (polygon_union, in_a | in_b),
        "difference": (polygon_difference, in_a & ~in_b),
        "sym_difference": (polygon_sym_difference, in_a ^ in_b),
    }
    checked = refused = 0
    for name, (fn, want) in ops.items():
        try:
            out = fn(a, b)
        except NotImplementedError:
            if not tolerate_refusals:
                raise
            refused += 1
            continue
        keep = ~_near_edge(pts, [a, b, out], span * 2e-3)
        got = _inside(pts, out)
        bad = np.nonzero(got[keep] != want[keep])[0]
        assert len(bad) == 0, (
            f"{name}: {len(bad)}/{keep.sum()} sampled points disagree "
            f"(first at {pts[keep][bad[:3]]})"
        )
        checked += 1
    return checked, refused


def _poly(coords):
    c = np.asarray(coords, np.float64)
    return Polygon(np.concatenate([c, c[:1]], axis=0))


SQUARE = _poly([(0, 0), (4, 0), (4, 4), (0, 4)])
OFFSET_SQUARE = _poly([(2, 2), (6, 2), (6, 6), (2, 6)])
TRIANGLE = _poly([(1, -1), (5, 3), (1, 5)])
CONCAVE = _poly([(0, 0), (6, 0), (6, 6), (3, 2.5), (0, 6)])
DISJOINT = _poly([(10, 10), (12, 10), (12, 12), (10, 12)])
INNER = _poly([(1, 1), (2, 1), (2, 2), (1, 2)])


def test_overlapping_squares():
    _mc_check(SQUARE, OFFSET_SQUARE, np.random.default_rng(1))
    # and the exact area of the known overlap
    inter = polygon_intersection(SQUARE, OFFSET_SQUARE)
    from geomesa_tpu.sql.functions import st_area

    assert st_area(inter) == pytest.approx(4.0)
    assert st_area(polygon_union(SQUARE, OFFSET_SQUARE)) == pytest.approx(
        16 + 16 - 4
    )
    assert st_area(
        polygon_difference(SQUARE, OFFSET_SQUARE)
    ) == pytest.approx(12.0)


def test_triangle_vs_square():
    _mc_check(SQUARE, TRIANGLE, np.random.default_rng(2))


def test_concave_subject():
    _mc_check(CONCAVE, OFFSET_SQUARE, np.random.default_rng(3))


def test_concave_both_multiring_result():
    """A concave ∩ that produces TWO disjoint pieces."""
    bar = _poly([(-1, 3.4), (7, 3.4), (7, 5.2), (-1, 5.2)])
    out = polygon_intersection(CONCAVE, bar)
    assert isinstance(out, MultiPolygon) and len(out.polygons) == 2
    _mc_check(CONCAVE, bar, np.random.default_rng(4))


def test_disjoint():
    assert isinstance(
        polygon_intersection(SQUARE, DISJOINT), MultiPolygon
    )
    u = polygon_union(SQUARE, DISJOINT)
    assert isinstance(u, MultiPolygon) and len(u.polygons) == 2
    d = polygon_difference(SQUARE, DISJOINT)
    from geomesa_tpu.sql.functions import st_area

    assert st_area(d) == pytest.approx(16.0)
    _mc_check(SQUARE, DISJOINT, np.random.default_rng(5))


def test_contained():
    from geomesa_tpu.sql.functions import st_area

    assert st_area(polygon_intersection(SQUARE, INNER)) == pytest.approx(1.0)
    assert st_area(polygon_union(SQUARE, INNER)) == pytest.approx(16.0)
    # inner minus outer = empty
    out = polygon_difference(INNER, SQUARE)
    assert isinstance(out, MultiPolygon) and len(out.polygons) == 0
    # outer minus inner CREATES a hole (supported via the hole-aware
    # decomposition; refused loudly in the first cut of this module)
    donut = polygon_difference(SQUARE, INNER)
    assert isinstance(donut, Polygon)
    assert len(list(donut.rings())) == 2
    assert st_area(donut) == pytest.approx(15.0)


def test_degenerate_shared_edge_retries():
    """Touching squares (shared edge): degenerate for vanilla GH; the
    perturbation retry must resolve it and the oracle must still hold."""
    right = _poly([(4, 0), (8, 0), (8, 4), (4, 4)])
    _mc_check(SQUARE, right, np.random.default_rng(6))
    from geomesa_tpu.sql.functions import st_area

    u = polygon_union(SQUARE, right)
    assert st_area(u) == pytest.approx(32.0, rel=1e-6)


def test_shared_vertex_retries():
    touch = _poly([(4, 4), (6, 4), (6, 6), (4, 6)])
    _mc_check(SQUARE, touch, np.random.default_rng(7))


def test_random_convex_pairs():
    """Fuzz: random convex polygons, all ops vs the oracle."""
    rng = np.random.default_rng(8)
    from geomesa_tpu.sql.functions import st_convexHull

    for _ in range(6):
        a = st_convexHull(_poly_from_points(rng.uniform(0, 6, (12, 2))))
        b = st_convexHull(_poly_from_points(rng.uniform(2, 8, (12, 2))))
        if not isinstance(a, Polygon) or not isinstance(b, Polygon):
            continue
        _mc_check(a, b, rng, n=8000)


def _poly_from_points(pts):
    from geomesa_tpu.geom.base import MultiPoint, Point

    return MultiPoint(
        tuple(Point(float(x), float(y)) for x, y in np.asarray(pts))
    )


HOLED = Polygon(
    np.array([(0, 0), (8, 0), (8, 8), (0, 8), (0, 0)], np.float64),
    (np.array([(3, 3), (5, 3), (5, 5), (3, 5), (3, 3)], np.float64),),
)


class TestHoledIntersection:
    """Intersection supports holes: crossing holes trim the result,
    contained holes carry through, overlapping hole regions merge."""

    def _mc_inter(self, a, b, rng, n=20000):
        ea, eb = a.envelope, b.envelope
        lo = np.minimum([ea.xmin, ea.ymin], [eb.xmin, eb.ymin]) - 0.5
        hi = np.maximum([ea.xmax, ea.ymax], [eb.xmax, eb.ymax]) + 0.5
        pts = rng.uniform(lo, hi, (n, 2))
        out = polygon_intersection(a, b)
        span = float(max(hi[0] - lo[0], hi[1] - lo[1]))
        keep = ~_near_edge(pts, [a, b, out], span * 2e-3)
        want = _inside(pts, a) & _inside(pts, b)
        got = _inside(pts, out)
        bad = np.nonzero(got[keep] != want[keep])[0]
        assert len(bad) == 0, (
            f"{len(bad)}/{keep.sum()} points disagree "
            f"(first {pts[keep][bad[:3]]})"
        )
        return out

    def test_hole_carried_through(self):
        # clip region covers the hole entirely: hole survives in output
        clip = _poly([(1, 1), (7, 1), (7, 7), (1, 7)])
        out = self._mc_inter(HOLED, clip, np.random.default_rng(10))
        from geomesa_tpu.sql.functions import st_area

        assert st_area(out) == pytest.approx(36 - 4)
        assert isinstance(out, Polygon) and len(list(out.rings())) == 2

    def test_hole_crossing_boundary_trims(self):
        # clip boundary passes THROUGH the hole: no hole in the output,
        # the ring is trimmed around it
        clip = _poly([(1, 1), (4, 1), (4, 7), (1, 7)])
        out = self._mc_inter(HOLED, clip, np.random.default_rng(11))
        from geomesa_tpu.sql.functions import st_area

        assert st_area(out) == pytest.approx(3 * 6 - 1 * 2)

    def test_hole_outside_clip_ignored(self):
        clip = _poly([(0, 0), (2, 0), (2, 2), (0, 2)])
        out = self._mc_inter(HOLED, clip, np.random.default_rng(12))
        from geomesa_tpu.sql.functions import st_area

        assert st_area(out) == pytest.approx(4.0)

    def test_overlapping_holes_both_sides_merge(self):
        other = Polygon(
            np.array(
                [(1, 1), (9, 1), (9, 9), (1, 9), (1, 1)], np.float64
            ),
            (np.array(
                [(4, 4), (6, 4), (6, 6), (4, 6), (4, 4)], np.float64
            ),),
        )
        out = self._mc_inter(HOLED, other, np.random.default_rng(13))
        from geomesa_tpu.sql.functions import st_area

        # shells overlap on 7x7; merged hole region = union of the two
        # 2x2 holes overlapping on 1x1 -> area 4+4-1=7
        assert st_area(out) == pytest.approx(49 - 7)

    def test_interlocking_holes_void_refused(self):
        """Two C-shaped holes whose union encloses a void: emitting both
        rings as holes would double-count the void under even-odd
        membership, so the merge must REFUSE (review repro)."""
        c1 = np.array(
            [(2, 2), (5, 2), (5, 3), (3, 3), (3, 5), (5, 5), (5, 6),
             (2, 6), (2, 2)], np.float64,
        )
        c2 = np.array(
            [(6, 2), (6, 6), (3.5, 6.5), (3.5, 5.5), (5.5, 5.5),
             (5.5, 2.5), (4, 2.5), (4, 1.5), (6, 1.5)], np.float64,
        )
        c2 = np.concatenate([c2, c2[:1]])
        shell = np.array(
            [(0, 0), (10, 0), (10, 10), (0, 10), (0, 0)], np.float64
        )
        a = Polygon(shell, (c1,))
        b = Polygon(shell, (c2,))
        with pytest.raises(NotImplementedError, match="void|topology"):
            polygon_intersection(a, b)

    def test_union_with_holes(self):
        """Holed union routes through the A + (B \\ A) decomposition:
        membership and area exact; the covered part of the hole fills."""
        from geomesa_tpu.sql.functions import st_area

        rng = np.random.default_rng(30)
        pts = rng.uniform(-1, 9, (20000, 2))
        out = polygon_union(HOLED, SQUARE)
        span = 10.0
        keep = ~_near_edge(pts, [HOLED, SQUARE, out], span * 2e-3)
        want = _inside(pts, HOLED) | _inside(pts, SQUARE)
        got = _inside(pts, out)
        assert np.array_equal(got[keep], want[keep])
        # SQUARE (0..4)^2 covers the hole corner (3..4, 3..4): hole
        # shrinks from 4 to 3 in area
        assert st_area(out) == pytest.approx(64 - 3)


def test_union_enclosing_void_is_exact():
    """Two interlocking C-frames whose union encloses a central void:
    the pairwise fold would silently emit overlapping rings (area
    double-counted); the fallback decomposition is exact."""
    from geomesa_tpu.sql.functions import st_area

    A = Polygon(np.array(
        [(0, 0), (6, 0), (6, 2.5), (2, 2.5), (2, 3.5), (6, 3.5),
         (6, 6), (0, 6), (0, 0)], np.float64,
    ))
    B = Polygon(np.array(
        [(8, -0.5), (8, 6.5), (2.5, 6.5), (2.5, 4.5), (5, 4.5),
         (5, 1.5), (2.5, 1.5), (2.5, -0.5), (8, -0.5)], np.float64,
    ))
    out = polygon_union(A, B)
    rng = np.random.default_rng(31)
    pts = rng.uniform((-1, -1.5), (9, 7.5), (20000, 2))
    keep = ~_near_edge(pts, [A, B, out], 10 * 2e-3)
    want = _inside(pts, A) | _inside(pts, B)
    got = _inside(pts, out)
    assert np.array_equal(got[keep], want[keep])
    # area must NOT double-count the overlap (the old fold returned
    # st_area == area(A) + area(B) == 63 here)
    mc_area = 10.0 * 9.0 * want.mean()
    assert abs(st_area(out) - mc_area) < 1.5
    assert st_area(out) < 60.0


class TestHoledDifference:
    """Difference supports holes on BOTH sides via the disjoint
    decomposition A\\B = (shellA - merge(holesA + shellsB)) ∪ (A ∩
    holesB)."""

    def _mc(self, a, b, rng, n=20000):
        ea, eb = a.envelope, b.envelope
        lo = np.minimum([ea.xmin, ea.ymin], [eb.xmin, eb.ymin]) - 0.5
        hi = np.maximum([ea.xmax, ea.ymax], [eb.xmax, eb.ymax]) + 0.5
        pts = rng.uniform(lo, hi, (n, 2))
        span = float(max(hi[0] - lo[0], hi[1] - lo[1]))
        for fn, want in (
            (polygon_difference,
             _inside(pts, a) & ~_inside(pts, b)),
            (polygon_sym_difference,
             _inside(pts, a) ^ _inside(pts, b)),
        ):
            out = fn(a, b)
            keep = ~_near_edge(pts, [a, b, out], span * 2e-3)
            got = _inside(pts, out)
            bad = np.nonzero(got[keep] != want[keep])[0]
            assert len(bad) == 0, (
                f"{fn.__name__}: {len(bad)} points disagree "
                f"(first {pts[keep][bad[:3]]})"
            )

    def test_holed_subject_minus_simple(self):
        from geomesa_tpu.sql.functions import st_area

        clip = _poly([(5, 5), (12, 5), (12, 12), (5, 12)])
        self._mc(HOLED, clip, np.random.default_rng(20))
        out = polygon_difference(HOLED, clip)
        # 8x8 shell minus 2x2 hole minus the 3x3 overlap corner, but the
        # hole's (3..5,3..5) corner (5,5) touches the clip corner: area =
        # 64 - 4 - 9 + 0 (hole and clip overlap only at the point (5,5))
        assert st_area(out) == pytest.approx(64 - 4 - 9)

    def test_simple_minus_holed(self):
        """Subtracting a holed polygon keeps the part inside its hole."""
        from geomesa_tpu.sql.functions import st_area

        big = _poly([(-1, -1), (9, -1), (9, 9), (-1, 9)])
        self._mc(big, HOLED, np.random.default_rng(21))
        out = polygon_difference(big, HOLED)
        # 10x10 minus (64 - 4) = 100 - 60 = 40, incl. the 2x2 island
        # that survives inside HOLED's hole
        assert st_area(out) == pytest.approx(40.0)
        # the island is a separate disjoint component
        assert isinstance(out, MultiPolygon)

    def test_holed_minus_holed(self):
        other = Polygon(
            np.array(
                [(4, 4), (12, 4), (12, 12), (4, 12), (4, 4)], np.float64
            ),
            (np.array(
                [(6, 6), (7, 6), (7, 7), (6, 7), (6, 6)], np.float64
            ),),
        )
        self._mc(HOLED, other, np.random.default_rng(22))

    def test_island_in_hole_refused(self):
        donut = Polygon(
            np.array(
                [(0, 0), (10, 0), (10, 10), (0, 10), (0, 0)], np.float64
            ),
            (np.array(
                [(2, 2), (8, 2), (8, 8), (2, 8), (2, 2)], np.float64
            ),),
        )
        island = _poly([(4, 4), (6, 4), (6, 6), (4, 6)])
        world = MultiPolygon((donut, island))
        with pytest.raises(NotImplementedError, match="hole"):
            polygon_difference(SQUARE, world)


def _star(rng, cx, cy, r_lo, r_hi, n_pts=None):
    """Random star polygon (radial, guaranteed simple, usually concave).

    Angles are JITTERED-EVEN, not uniform-random: a random angular gap
    over pi would let a boundary chord cut past the center, so the disc
    r < r_lo*cos(gap/2) would NOT be contained — and a "hole" generated
    inside that disc could poke outside its shell (an invalid polygon,
    which the first cut of this fuzz fed to the clipper). Worst case at
    k=8 with ±30% jitter: gap <= (2π/8)·1.6 ≈ 1.26 rad, so the disc of
    radius cos(0.63)·r_lo ≈ 0.81·r_lo is always covered — hole radii
    must stay BELOW that margin (callers use 1.4 < 0.81·3.0 = 2.43 and
    1.2 < 0.81·2.5 = 2.02)."""
    k = n_pts or int(rng.integers(8, 14))
    base = np.arange(k) * (2 * np.pi / k)
    th = base + rng.uniform(-0.3, 0.3, k) * (2 * np.pi / k)
    rr = rng.uniform(r_lo, r_hi, k)
    c = np.stack([cx + rr * np.cos(th), cy + rr * np.sin(th)], axis=1)
    return np.concatenate([c, c[:1]])


def test_fuzz_all_ops_holed_concave():
    """Random concave star polygons (sometimes holed) through all four
    boolean ops vs the Monte-Carlo membership oracle. Loud refusals
    (pathological topology) are tolerated but must stay rare."""
    rng = np.random.default_rng(77)
    refused = 0
    checked = 0
    for trial in range(12):
        shell_a = _star(rng, 0, 0, 3.0, 6.0)
        holes_a = ()
        if trial % 2:
            holes_a = (_star(rng, 0, 0, 0.5, 1.4, n_pts=6),)
        a = Polygon(shell_a, holes_a)
        off = rng.uniform(-3, 3, 2)
        shell_b = _star(rng, off[0], off[1], 2.5, 5.5)
        holes_b = ()
        if trial % 3 == 0:
            holes_b = (_star(rng, off[0], off[1], 0.4, 1.2, n_pts=6),)
        b = Polygon(shell_b, holes_b)
        c, r = _mc_check(a, b, rng, n=12000, tolerate_refusals=True)
        checked += c
        refused += r
    assert checked >= 36, (checked, refused)  # refusals must stay rare


def test_sql_surface():
    from geomesa_tpu.sql import functions as F

    out = F.st_intersection(SQUARE, OFFSET_SQUARE)
    assert F.st_area(out) == pytest.approx(4.0)
    col = np.array([OFFSET_SQUARE, TRIANGLE, DISJOINT], dtype=object)
    outs = F.st_intersection(SQUARE, col)
    assert len(outs) == 3
    agg = F.st_aggregateUnion([SQUARE, OFFSET_SQUARE, DISJOINT])
    assert F.st_area(agg) == pytest.approx(16 + 16 - 4 + 4)


def test_degenerate_far_from_origin():
    """A small polygon at large coordinate magnitude (EPSG:3857-like)
    with a vertex-on-edge degeneracy: the perturbation scale must stay
    above the coordinate ULP or every retry re-tests the same input."""
    base = 1.2e7  # metres — Web-Mercator range, ULP ~ 2e-9
    a = Polygon(np.array([
        [base, base], [base + 1e-3, base], [base + 1e-3, base + 1e-3],
        [base, base + 1e-3],
    ]))
    # b shares a full edge segment with a (collinear overlap)
    b = Polygon(np.array([
        [base + 2e-4, base], [base + 8e-4, base],
        [base + 8e-4, base + 5e-4], [base + 2e-4, base + 5e-4],
    ]))
    got = polygon_intersection(a, b)

    def shoelace(g):
        if isinstance(g, MultiPolygon):
            return sum(shoelace(q) for q in g.polygons)
        r = np.asarray(g.rings()[0])
        r = r - r.mean(axis=0)  # center: avoid shoelace cancellation
        x, y = r[:, 0], r[:, 1]
        return 0.5 * abs(float(np.dot(x, np.roll(y, -1))
                                - np.dot(y, np.roll(x, -1))))

    assert shoelace(got) == pytest.approx(6e-4 * 5e-4, rel=0.05)
