"""Avro container-file serializer round-trips (pure-python wire codec)."""

import io

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.features.avro import (
    AvroDataFileWriter,
    read_avro,
    read_long,
    write_avro,
    write_long,
)
from geomesa_tpu.geom import parse_wkt
from geomesa_tpu.geom.wkt import to_wkt


class TestVarints:
    @pytest.mark.parametrize(
        "v",
        [0, 1, -1, 63, 64, -64, -65, 2**31 - 1, -(2**31), 2**62, -(2**62)],
    )
    def test_zigzag_round_trip(self, v):
        buf = io.BytesIO()
        write_long(buf, v)
        buf.seek(0)
        assert read_long(buf) == v

    def test_small_values_one_byte(self):
        for v in (0, -1, 1, -64, 63):
            buf = io.BytesIO()
            write_long(buf, v)
            assert len(buf.getvalue()) == 1


class TestContainerRoundTrip:
    def test_point_batch(self, rng):
        sft = SimpleFeatureType.create(
            "t", "name:String,count:Int,score:Double,ok:Boolean,"
            "dtg:Date,*geom:Point:srid=4326"
        )
        n = 500
        batch = FeatureBatch.from_columns(
            sft,
            {
                "name": rng.choice(["a", "b", None], n),
                "count": rng.integers(-5, 100, n),
                "score": rng.uniform(-1, 1, n),
                "ok": rng.integers(0, 2, n).astype(bool),
                "dtg": rng.integers(0, 2**45, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)],
                    axis=1,
                ),
            },
        )
        buf = io.BytesIO()
        write_avro(buf, batch)
        buf.seek(0)
        back = read_avro(buf)  # SFT from embedded spec
        assert back.sft.spec == sft.spec
        np.testing.assert_array_equal(back.column("count"), batch.column("count"))
        np.testing.assert_array_equal(back.column("dtg"), batch.column("dtg"))
        np.testing.assert_array_equal(back.column("ok"), batch.column("ok"))
        np.testing.assert_allclose(back.column("score"), batch.column("score"))
        np.testing.assert_allclose(
            back.column("geom"), batch.column("geom"), atol=1e-12
        )
        assert list(back.column("name")) == list(batch.column("name"))
        assert [str(f) for f in back.fids] == [str(f) for f in batch.fids]

    def test_multi_block_and_polygon(self, rng):
        sft = SimpleFeatureType.create("p", "*geom:Polygon:srid=4326")
        g = parse_wkt("POLYGON ((0 0, 3 0, 3 3, 0 3, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))")
        n = 50
        batch = FeatureBatch.from_columns(
            sft, {"geom": np.array([g] * n, dtype=object)}
        )
        buf = io.BytesIO()
        with AvroDataFileWriter(buf, sft, sync_interval=7) as w:
            w.write(batch)  # forces 8 blocks
        buf.seek(0)
        back = read_avro(buf)
        assert len(back) == n
        assert to_wkt(back.column("geom")[n - 1]) == to_wkt(g)

    def test_bad_magic_raises(self):
        with pytest.raises(ValueError):
            read_avro(io.BytesIO(b"nope"))
