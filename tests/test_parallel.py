"""Multi-chip (8 virtual CPU devices) mesh tests: sharded scan count,
radix-exchange distributed sort."""

import numpy as np
import pytest

from geomesa_tpu.curves import Z3SFC
from geomesa_tpu.parallel import (
    distributed_z3_sort,
    make_mesh,
    sharded_build_and_query_step,
    sharded_count_scan,
)
from geomesa_tpu.parallel.dist import distributed_sort


@pytest.fixture(scope="module")
def mesh():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_sharded_count_matches_host(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 1024
    x = rng.uniform(-180, 180, n).astype(np.float32)
    y = rng.uniform(-90, 90, n).astype(np.float32)

    def device_fn(cols):
        return (cols["x"] >= -10) & (cols["x"] <= 30) & (cols["y"] >= 0)

    count = int(
        sharded_count_scan(
            mesh, device_fn, {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        )
    )
    assert count == int(((x >= -10) & (x <= 30) & (y >= 0)).sum())


def test_distributed_sort_globally_ordered(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 2048
    hi = rng.integers(0, 1 << 31, n).astype(np.uint32)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    sh, sl, sv = distributed_z3_sort(mesh, jnp.asarray(hi), jnp.asarray(lo))
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    per = len(sh) // 8
    all_valid = []
    prev_max = -1
    for s in range(8):
        h = sh[s * per : (s + 1) * per]
        l = sl[s * per : (s + 1) * per]
        v = sv[s * per : (s + 1) * per]
        z = (h[v].astype(np.uint64) << np.uint64(32)) | l[v].astype(np.uint64)
        assert np.all(np.diff(z.astype(np.int64)) >= 0), f"shard {s} not sorted"
        if len(z):
            assert int(z[0]) >= prev_max, "shards out of global order"
            prev_max = int(z[-1])
        all_valid.append(z)
    merged = np.concatenate(all_valid)
    expected = np.sort(
        (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    )
    # no drops with uniform data at capacity 2x
    np.testing.assert_array_equal(merged, expected)


class TestExchangeAtScale:
    """VERDICT round-3 item 5: the exchange's capacity math and wall
    clock, proven at 2^22 rows over 8 virtual devices — uniform, sorted,
    all-duplicate and clustered layouts must all complete with ZERO
    overflow at the default capacity factor, return a correct global
    sort with an intact row-id payload, and finish within a wall-clock
    bound."""

    N = 1 << 22

    def _layout(self, name, rng):
        n = self.N
        if name == "uniform":
            hi = rng.integers(0, 1 << 31, n).astype(np.uint32)
            lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(
                np.uint32
            )
        elif name == "sorted":
            hi = np.sort(rng.integers(0, 1 << 31, n)).astype(np.uint32)
            lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(
                np.uint32
            )
        elif name == "duplicate":
            hi = np.full(n, 0x12345678, np.uint32)
            lo = np.full(n, 0x9ABCDEF0, np.uint32)
        else:  # clustered: 99% of keys in 4 tiny hot ranges
            centers = np.array(
                [0x100, 0x7FFF0000, 0x40000000, 0x2AAA0000], np.uint32
            )
            which = rng.integers(0, 4, n)
            off = rng.integers(0, 64, n).astype(np.uint32)
            hi = centers[which] + off
            cold = rng.random(n) < 0.01
            hi[cold] = rng.integers(0, 1 << 31, int(cold.sum())).astype(
                np.uint32
            )
            lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(
                np.uint32
            )
        return hi, lo

    @pytest.mark.parametrize(
        "layout", ["uniform", "sorted", "duplicate", "clustered"]
    )
    def test_2m_rows_zero_overflow_sorted_with_payload(self, mesh, layout):
        import time

        import jax.numpy as jnp

        rng = np.random.default_rng(hash(layout) % (1 << 31))
        hi, lo = self._layout(layout, rng)
        rid = np.arange(self.N, dtype=np.uint32)
        t0 = time.perf_counter()
        # on_overflow='raise' IS the zero-overflow assertion at the
        # default capacity_factor
        (sh, sl), pay, sv = distributed_sort(
            mesh, (jnp.asarray(hi), jnp.asarray(lo)),
            payload={"rid": jnp.asarray(rid)},
        )
        sh = np.asarray(sh)
        wall = time.perf_counter() - t0
        # generous bound: 2^22 rows through two all_to_all passes + local
        # sorts on an 8-virtual-device CPU mesh takes ~1-5s; a capacity
        # or routing regression shows up as minutes (or a raise above)
        assert wall < 120, f"{layout}: exchange took {wall:.0f}s"
        sl, sv = np.asarray(sl), np.asarray(sv)
        rid_out = np.asarray(pay["rid"])
        z = (sh.astype(np.uint64) << np.uint64(32)) | sl.astype(np.uint64)
        zin = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(
            np.uint64
        )
        per = len(sh) // 8
        prev_max = -1
        got = []
        for s in range(8):
            vs = sv[s * per : (s + 1) * per]
            zs = z[s * per : (s + 1) * per][vs]
            assert np.all(np.diff(zs.astype(np.int64)) >= 0), (
                f"{layout}: shard {s} not locally sorted"
            )
            if len(zs):
                assert int(zs[0]) >= prev_max, (
                    f"{layout}: shards out of global order"
                )
                prev_max = int(zs[-1])
            got.append(zs)
            # the payload permutation must reproduce the keys it rode with
            rs = rid_out[s * per : (s + 1) * per][vs]
            np.testing.assert_array_equal(
                zin[rs], zs, err_msg=f"{layout}: rid payload mispermuted"
            )
        merged = np.concatenate(got)
        assert len(merged) == self.N  # zero rows lost
        np.testing.assert_array_equal(merged, np.sort(zin))


def test_full_build_and_query_step(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 1024
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.uniform(0, 604800, n)
    sfc = Z3SFC()
    bounds = (-10.0, 0.0, 30.0, 40.0, 10000.0, 300000.0)
    sh, sl, sv, count, key_count, hit_rids, hit_valid = (
        sharded_build_and_query_step(
            mesh, sfc, jnp.asarray(x), jnp.asarray(y), jnp.asarray(t), bounds
        )
    )
    expected = int(
        (
            (x >= bounds[0])
            & (x <= bounds[2])
            & (y >= bounds[1])
            & (y <= bounds[3])
            & (t >= bounds[4])
            & (t <= bounds[5])
        ).sum()
    )
    assert int(count) == expected
    # sorted keys match host-side encode of the same points
    z_host = np.sort(sfc.index(x, y, t))
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    z_dev = (
        (sh[sv].astype(np.uint64) << np.uint64(32)) | sl[sv].astype(np.uint64)
    )
    # global order: concatenation of shards ascending
    np.testing.assert_array_equal(np.sort(z_dev), z_host)
    # the query THROUGH the sorted index returns the exact row-id set the
    # quantized-cell host oracle predicts (weak #6: key corruption in the
    # exchange would change this set)
    nx = sfc.lon.normalize(x).astype(np.int64)
    ny = sfc.lat.normalize(y).astype(np.int64)
    nt = sfc.time.normalize(t).astype(np.int64)
    cell = (
        (nx >= int(sfc.lon.normalize(bounds[0])))
        & (nx <= int(sfc.lon.normalize(bounds[2])))
        & (ny >= int(sfc.lat.normalize(bounds[1])))
        & (ny <= int(sfc.lat.normalize(bounds[3])))
        & (nt >= int(sfc.time.normalize(bounds[4])))
        & (nt <= int(sfc.time.normalize(bounds[5])))
    )
    got = np.asarray(hit_rids)[np.asarray(hit_valid)]
    assert int(key_count) == int(cell.sum()) == len(got)
    np.testing.assert_array_equal(np.sort(got), np.nonzero(cell)[0])


def test_sharded_query_scan_returns_features(mesh, rng):
    """The mesh-wide scan streams back row ids AND payload columns, not a
    count (the BatchScanPlan analog); truncation is loud."""
    import jax.numpy as jnp
    import pytest

    from geomesa_tpu.parallel import sharded_query_scan

    n = 8 * 512
    x = rng.uniform(-180, 180, n)
    val = rng.integers(0, 1000, n).astype(np.int32)
    rid = np.arange(n, dtype=np.uint32)
    fn = lambda local: (local["x"] >= 0) & (local["x"] <= 20)  # noqa: E731
    ids, valid, pay, total = sharded_query_scan(
        make_mesh(8),
        fn,
        {"x": jnp.asarray(x)},
        jnp.asarray(rid),
        payload={"val": jnp.asarray(val)},
    )
    expect = (x >= 0) & (x <= 20)
    got_ids = np.asarray(ids)[np.asarray(valid)]
    assert int(total) == int(expect.sum()) == len(got_ids)
    np.testing.assert_array_equal(np.sort(got_ids), np.nonzero(expect)[0])
    # payload rows ride aligned with their ids
    got_val = np.asarray(pay["val"])[np.asarray(valid)]
    order = np.argsort(got_ids)
    np.testing.assert_array_equal(got_val[order], val[expect])
    # a tiny cap truncates loudly
    with pytest.raises(RuntimeError, match="truncated"):
        sharded_query_scan(
            make_mesh(8),
            fn,
            {"x": jnp.asarray(x)},
            jnp.asarray(rid),
            cap_per_shard=1,
            payload={"val": jnp.asarray(val)},
        )


def test_sampled_splitters_survive_skew(mesh):
    """All points in one hot cell: radix routing overflows one destination
    and drops rows; sampled splitters keep every row and stay globally
    sorted (SURVEY hard part #5, GDELT skew)."""
    import jax.numpy as jnp

    n = 4096
    rng = np.random.default_rng(3)
    # a single ~1km cell: all z keys share their high bits
    x = rng.uniform(2.350, 2.351, n)
    y = rng.uniform(48.850, 48.851, n)
    t = rng.uniform(0, 3600.0, n)
    sfc = Z3SFC()
    hi, lo = sfc.index_jax_hi_lo(jnp.asarray(x), jnp.asarray(y), jnp.asarray(t))

    # radix routing overflows and must be LOUD by default
    with pytest.raises(RuntimeError, match="dropped"):
        distributed_z3_sort(mesh, hi, lo, splitters="radix")
    with pytest.warns(RuntimeWarning, match="dropped"):
        rh, rl, rv = distributed_z3_sort(
            mesh, hi, lo, splitters="radix", on_overflow="warn"
        )
    dropped_radix = n - int(np.asarray(rv).sum())
    assert dropped_radix > 0  # the skew actually defeats radix routing

    sh, sl, sv = distributed_z3_sort(mesh, hi, lo, splitters="sampled")
    assert int(np.asarray(sv).sum()) == n  # nothing dropped
    # global sortedness: concatenated valid keys are non-decreasing
    h = np.asarray(sh)[np.asarray(sv)]
    l = np.asarray(sl)[np.asarray(sv)]
    z = (h.astype(np.uint64) << np.uint64(32)) | l.astype(np.uint64)
    # per-shard slices are sorted and shard s's max <= shard s+1's min
    per = np.asarray(sv).reshape(8, -1)
    zs = np.asarray(sh).astype(np.uint64).reshape(8, -1) << np.uint64(32)
    zs |= np.asarray(sl).astype(np.uint64).reshape(8, -1)
    prev_max = None
    for s in range(8):
        vals = zs[s][per[s]]
        assert np.all(np.diff(vals.astype(np.int64)) >= 0)
        if len(vals):
            if prev_max is not None:
                assert vals[0] >= prev_max
            prev_max = vals[-1]


def test_multihost_helpers_single_process(mesh, rng):
    """The multi-host entry points must work unchanged on one process:
    initialize() no-ops, host slices become globally sharded arrays that
    collectives consume."""
    import jax

    from geomesa_tpu.parallel import (
        host_batches_to_global,
        initialize,
        sharded_count_scan,
    )
    from geomesa_tpu.parallel.multihost import global_mesh

    initialize()  # no coordinator configured -> no-op
    gm = global_mesh()
    assert gm.shape["shard"] == len(jax.devices())

    n = 1024
    cols = {
        "x": rng.uniform(-180, 180, n).astype(np.float32),
        "y": rng.uniform(-90, 90, n).astype(np.float32),
    }
    gcols = host_batches_to_global(mesh, cols)
    assert all(v.shape == (n,) for v in gcols.values())

    def fn(local):
        return (
            (local["x"] >= -10)
            & (local["x"] <= 30)
            & (local["y"] >= 35)
            & (local["y"] <= 60)
        )

    got = int(sharded_count_scan(mesh, fn, cols))
    want = int(
        (
            (cols["x"] >= -10)
            & (cols["x"] <= 30)
            & (cols["y"] >= 35)
            & (cols["y"] <= 60)
        ).sum()
    )
    assert got == want


def test_sampled_sort_adversarial_layouts(mesh):
    """Already-globally-sorted input (each source holds one quantile) and
    all-duplicate keys: both defeat naive splitter routing; the rebalance
    pass + tie spreading must keep every row."""
    import jax.numpy as jnp

    n = 4096
    # adversarial 1: globally sorted keys
    z = np.sort(np.random.default_rng(0).integers(0, 2**62, n).astype(np.uint64))
    hi = jnp.asarray((z >> np.uint64(32)).astype(np.uint32))
    lo = jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    sh, sl, sv = distributed_z3_sort(mesh, hi, lo, splitters="sampled")
    assert int(np.asarray(sv).sum()) == n
    got = np.sort(
        (np.asarray(sh).astype(np.uint64) << np.uint64(32))
        | np.asarray(sl).astype(np.uint64)
    )[:n]
    np.testing.assert_array_equal(np.sort(got), np.sort(z))

    # adversarial 2: every key identical
    hi2 = jnp.full(n, np.uint32(7), dtype=jnp.uint32)
    lo2 = jnp.full(n, np.uint32(9), dtype=jnp.uint32)
    sh2, sl2, sv2 = distributed_z3_sort(mesh, hi2, lo2, splitters="sampled")
    assert int(np.asarray(sv2).sum()) == n


def test_device_index_build_matches_host(mesh):
    """VERDICT round-1 item 2: the mesh sort carries row payloads, so the
    device path builds a real queryable BuiltIndex -- bit-identical sorted
    keys and the same query results as the host lexsort build."""
    from geomesa_tpu.filter.ecql import parse_instant
    from geomesa_tpu.index.build import build_index_device
    from geomesa_tpu.query.runner import run_query
    from geomesa_tpu.store import MemoryDataStore

    store = MemoryDataStore(partition_size=2048)
    store.create_schema("pts", "name:String,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(17)
    n = 20000
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    store.write(
        "pts",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    ecql = (
        "BBOX(geom, -5, 42, 8, 51) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-02-20T00:00:00Z"
    )
    plan = store.plan("pts", ecql)  # flushes + builds host indices
    assert plan.index_name == "z3"
    host_built = store._state("pts").indices["z3"]
    dev_built = build_index_device(
        host_built.keyspace, store._state("pts").data, mesh, partition_size=2048
    )
    # bit-identical sorted key columns (device encode == host encode)
    np.testing.assert_array_equal(dev_built.keys["bin"], host_built.keys["bin"])
    np.testing.assert_array_equal(dev_built.keys["z"], host_built.keys["z"])
    np.testing.assert_array_equal(dev_built.batch.fids, host_built.batch.fids)
    assert len(dev_built.partitions) == len(host_built.partitions)
    # the same query plan scans both indices to the same result set
    r_host = run_query(host_built, plan)
    r_dev = run_query(dev_built, plan)
    assert len(r_host) > 0
    assert set(r_dev.batch.fids.tolist()) == set(r_host.batch.fids.tolist())


def test_distributed_sort_payload_travels_with_rows(mesh):
    """Column payloads (not just row ids) ride the exchange: each surviving
    row's payload must still equal f(key)."""
    import jax.numpy as jnp

    from geomesa_tpu.parallel import distributed_sort

    n = 8 * 512
    rng = np.random.default_rng(5)
    z = rng.integers(0, 2**62, n).astype(np.uint64)
    hi = jnp.asarray((z >> np.uint64(32)).astype(np.uint32))
    lo = jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    # payload derived from the key so misrouting is detectable
    pay = {
        "f": jnp.asarray((z % 1000).astype(np.float32)),
        "i": jnp.asarray((z % 255).astype(np.uint8)),
    }
    (sh, sl), pout, sv = distributed_sort(mesh, (hi, lo), payload=pay)
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    zz = ((sh.astype(np.uint64) << np.uint64(32)) | sl.astype(np.uint64))[sv]
    np.testing.assert_array_equal(np.sort(zz), np.sort(z))
    np.testing.assert_array_equal(
        np.asarray(pout["f"])[sv], (zz % 1000).astype(np.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(pout["i"])[sv], (zz % 255).astype(np.uint8)
    )


def test_sampled_sort_periodic_interleaved_clusters(mesh):
    """Rows alternating between two clusters (interleaved ingest from two
    sources) resonate with a plain i%n round-robin rebalance; the hashed
    shuffle must keep every row. Also covers tiny inputs where the
    per-destination mean is ~1 row."""
    import jax.numpy as jnp

    for n in (64, 4096):
        i = np.arange(n)
        z = np.where(i % 2 == 0, i * 7, (1 << 61) + i * 13).astype(np.uint64)
        hi = jnp.asarray((z >> np.uint64(32)).astype(np.uint32))
        lo = jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        sh, sl, sv = distributed_z3_sort(mesh, hi, lo, splitters="sampled")
        assert int(np.asarray(sv).sum()) == n, f"rows lost at n={n}"
        got = (
            (np.asarray(sh).astype(np.uint64) << np.uint64(32))
            | np.asarray(sl).astype(np.uint64)
        )[np.asarray(sv)]
        np.testing.assert_array_equal(got, np.sort(z))


def test_device_build_rejects_out_of_range_bins():
    """A bin beyond the int32 bias must raise, not silently mis-sort."""
    from geomesa_tpu.index.build import _BIN_BIAS, build_index_device
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.index.keyspaces import Z3KeySpace

    sft = SimpleFeatureType.create("b", "dtg:Date,*geom:Point:srid=4326")
    # dtg in ms; a WEEK bin of 2**31 needs ms ~ 2**31 * 604800000 -- beyond
    # int64? no: 1.3e18 < 9.2e18, representable
    ms = np.array([(2**31 + 5) * 604800000], dtype=np.int64)
    batch = FeatureBatch.from_columns(
        sft, {"dtg": ms, "geom": np.array([[0.0, 0.0]])}, np.arange(1)
    )
    with pytest.raises(ValueError, match="device-sortable"):
        build_index_device(Z3KeySpace("geom", "dtg"), batch, make_mesh(8))


def test_device_build_stable_over_duplicate_keys():
    """All-identical (bin, z) keys: the trailing row-id lane must make the
    device sort reproduce the host lexsort's stable tie order exactly."""
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.index.build import build_index, build_index_device
    from geomesa_tpu.index.keyspaces import Z3KeySpace

    sft = SimpleFeatureType.create("dup", "dtg:Date,*geom:Point:srid=4326")
    n = 64
    batch = FeatureBatch.from_columns(
        sft,
        {
            "dtg": np.full(n, 1577836800000, dtype=np.int64),
            "geom": np.tile([[2.35, 48.85]], (n, 1)),
        },
        np.arange(n),
    )
    ks = Z3KeySpace("geom", "dtg")
    host = build_index(ks, batch)
    for n_dev in (8, 1):
        dev = build_index_device(ks, batch, make_mesh(n_dev))
        np.testing.assert_array_equal(dev.batch.fids, host.batch.fids)
        np.testing.assert_array_equal(dev.keys["z"], host.keys["z"])


def test_distributed_sort_single_device_mesh(rng):
    """n_shards == 1 must skip the exchange (no radix lane assumptions)
    and still produce a sorted, loss-free result -- including for lanes
    with bit 31 set (biased bins)."""
    import jax.numpy as jnp

    from geomesa_tpu.parallel import distributed_sort

    n = 512
    lane0 = (rng.integers(0, 1 << 32, n, dtype=np.uint64)).astype(np.uint32)
    lane1 = (rng.integers(0, 1 << 32, n, dtype=np.uint64)).astype(np.uint32)
    (s0, s1), _, v = distributed_sort(
        make_mesh(1), (jnp.asarray(lane0), jnp.asarray(lane1))
    )
    assert int(np.asarray(v).sum()) == n
    z = (np.asarray(s0).astype(np.uint64) << np.uint64(32)) | np.asarray(
        s1
    ).astype(np.uint64)
    np.testing.assert_array_equal(
        z, np.sort((lane0.astype(np.uint64) << np.uint64(32)) | lane1)
    )


def test_radix_bit31_lane_no_silent_loss(mesh, rng):
    """A 32-bit lane 0 (bit 31 set) would previously scatter out of bounds
    and vanish rows without touching the overflow counter; dest clamping
    must keep them accounted for: every row either survives or is counted
    in the loud overflow error."""
    import jax.numpy as jnp

    from geomesa_tpu.parallel import distributed_sort

    n = 8 * 512
    lane0 = (rng.integers(0, 1 << 32, n, dtype=np.uint64)).astype(np.uint32)
    lane1 = (rng.integers(0, 1 << 32, n, dtype=np.uint64)).astype(np.uint32)
    try:
        (s0, s1), _, v = distributed_sort(
            mesh,
            (jnp.asarray(lane0), jnp.asarray(lane1)),
            splitters="radix",
            on_overflow="raise",
        )
        survivors = int(np.asarray(v).sum())
        assert survivors == n  # no error -> nothing may be missing
    except RuntimeError as e:
        # overflow is allowed (clamping skews the top half onto the last
        # shard) but it must be LOUD and fully accounted
        assert "dropped" in str(e)


def test_sharded_zscan_count_matches_host(mesh):
    """Mesh-wide key-only scan: per-shard masked compare + psum equals
    the host quantized-cell oracle."""
    import jax.numpy as jnp

    from geomesa_tpu.curves.binnedtime import to_binned_time
    from geomesa_tpu.curves.z3 import Z3SFC
    from geomesa_tpu.ops import zscan
    from geomesa_tpu.parallel.dist import sharded_zscan_count

    sfc = Z3SFC()
    rng = np.random.default_rng(31)
    n = 1 << 14
    lon = rng.uniform(-180, 180, n)
    lat = rng.uniform(-90, 90, n)
    t0 = np.datetime64("2020-01-06").astype("datetime64[ms]").astype(np.int64)
    t = t0 + rng.integers(0, 21 * 86400_000, n)
    bins_np, off = to_binned_time(t, sfc.period)
    z = sfc.index(lon, lat, off)
    bounds, ids = zscan.z3_query_bounds(
        sfc, -30.0, 20.0, 60.0, 70.0,
        int(t0 + 2 * 86400_000), int(t0 + 9 * 86400_000),
    )
    bounds, ids = zscan.pad_bins(bounds, ids)
    got = int(sharded_zscan_count(
        mesh,
        jnp.asarray(bins_np.astype(np.int32)),
        jnp.asarray((z >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        bounds, ids,
    ))
    expect = np.asarray(zscan.z3_zscan_mask(
        jnp.asarray((z >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        jnp.asarray(bins_np.astype(np.int32)),
        jnp.asarray(bounds), jnp.asarray(ids),
    )).sum()
    assert got == int(expect)


def test_device_index_build_xz_matches_host(mesh):
    """VERDICT round-2 item 1: the device build accepts the XZ (non-point)
    key spaces — bit-identical sorted keys and fids vs the host build."""
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.index.build import build_index, build_index_device
    from geomesa_tpu.index.keyspaces import XZ2KeySpace, XZ3KeySpace

    rng = np.random.default_rng(5)
    n = 10_000
    xs = rng.uniform(-170, 160, n)
    ys = rng.uniform(-85, 75, n)
    ws = rng.uniform(0.01, 5.0, n)
    hs = rng.uniform(0.01, 5.0, n)
    wkt = np.array(
        [
            f"POLYGON (({x} {y}, {x+w} {y}, {x+w} {y+h}, {x} {y+h}, {x} {y}))"
            for x, y, w, h in zip(xs, ys, ws, hs)
        ],
        dtype=object,
    )
    sft3 = SimpleFeatureType.create("pg3", "dtg:Date,*geom:Polygon:srid=4326")
    batch3 = FeatureBatch.from_columns(
        sft3,
        {
            "dtg": rng.integers(1_577_836_800_000, 1_583_020_800_000, n),
            "geom": wkt,
        },
        np.arange(n),
    )
    ks3 = XZ3KeySpace("geom", "dtg")
    host3 = build_index(ks3, batch3, partition_size=2048)
    dev3 = build_index_device(ks3, batch3, mesh, partition_size=2048)
    np.testing.assert_array_equal(dev3.keys["bin"], host3.keys["bin"])
    np.testing.assert_array_equal(dev3.keys["xz"], host3.keys["xz"])
    np.testing.assert_array_equal(dev3.batch.fids, host3.batch.fids)
    assert dev3.keys["xz"].dtype == host3.keys["xz"].dtype

    sft2 = SimpleFeatureType.create("pg2", "*geom:Polygon:srid=4326")
    batch2 = FeatureBatch.from_columns(sft2, {"geom": wkt}, np.arange(n))
    ks2 = XZ2KeySpace("geom")
    host2 = build_index(ks2, batch2, partition_size=2048)
    dev2 = build_index_device(ks2, batch2, mesh, partition_size=2048)
    np.testing.assert_array_equal(dev2.keys["xz"], host2.keys["xz"])
    np.testing.assert_array_equal(dev2.batch.fids, host2.batch.fids)


def test_device_index_build_z2_matches_host(mesh):
    """The date-less point key space (z2) also builds on device."""
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.index.build import build_index, build_index_device
    from geomesa_tpu.index.keyspaces import Z2KeySpace

    rng = np.random.default_rng(6)
    n = 8192
    sft = SimpleFeatureType.create("p2", "*geom:Point:srid=4326")
    batch = FeatureBatch.from_columns(
        sft,
        {"geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        )},
        np.arange(n),
    )
    ks = Z2KeySpace("geom")
    host = build_index(ks, batch, partition_size=1024)
    dev = build_index_device(ks, batch, mesh, partition_size=1024)
    np.testing.assert_array_equal(dev.keys["z"], host.keys["z"])
    np.testing.assert_array_equal(dev.batch.fids, host.batch.fids)


def test_exchange_at_scale_adversarial_layouts(mesh):
    """VERDICT round-2 weak #2: the capacity math (dist.py) proven beyond
    toy n — ~2^22 rows over 8 virtual devices, adversarial layouts
    (uniform, pre-sorted, all-duplicate, hot-cluster), ZERO overflow at
    the default capacity factor, and bounded wall clock."""
    import time

    import jax.numpy as jnp

    from geomesa_tpu.parallel import distributed_sort

    n = 1 << 22
    rng_ = np.random.default_rng(11)
    uniform = rng_.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    layouts = {
        "uniform": uniform,
        "presorted": np.sort(uniform),
        "all-duplicate": np.full(n, 0x1234ABCD, np.uint32),
        # hot cluster: 90% of rows in one tiny key neighborhood (GDELT
        # city-cluster skew, SURVEY hard part #5)
        "clustered": np.where(
            rng_.random(n) < 0.9,
            (0x40000000 + rng_.integers(0, 1024, n)).astype(np.uint32),
            uniform,
        ),
    }
    lo_lane = rng_.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    for name, hi_lane in layouts.items():
        t0 = time.perf_counter()
        (sh, sl), _, v = distributed_sort(
            mesh,
            (jnp.asarray(hi_lane), jnp.asarray(lo_lane)),
            on_overflow="raise",  # zero overflow at DEFAULT capacity
        )
        sh = np.asarray(sh)
        sv = np.asarray(v)
        dt = time.perf_counter() - t0
        assert sv.sum() == n, f"{name}: lost rows"
        z = np.asarray(sh)[sv]
        # shard concatenation is globally sorted on the hi lane
        assert np.all(np.diff(z.astype(np.int64)) >= 0), f"{name}: unsorted"
        # wall-clock bound: generous (covers jit compile + loaded CI
        # hosts) but still catches a degenerated exchange, which would
        # take many minutes at this size
        assert dt < 300, f"{name}: exchange took {dt:.1f}s"
