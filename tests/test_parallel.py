"""Multi-chip (8 virtual CPU devices) mesh tests: sharded scan count,
radix-exchange distributed sort."""

import numpy as np
import pytest

from geomesa_tpu.curves import Z3SFC
from geomesa_tpu.parallel import (
    distributed_z3_sort,
    make_mesh,
    sharded_build_and_query_step,
    sharded_count_scan,
)


@pytest.fixture(scope="module")
def mesh():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_sharded_count_matches_host(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 1024
    x = rng.uniform(-180, 180, n).astype(np.float32)
    y = rng.uniform(-90, 90, n).astype(np.float32)

    def device_fn(cols):
        return (cols["x"] >= -10) & (cols["x"] <= 30) & (cols["y"] >= 0)

    count = int(
        sharded_count_scan(
            mesh, device_fn, {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        )
    )
    assert count == int(((x >= -10) & (x <= 30) & (y >= 0)).sum())


def test_distributed_sort_globally_ordered(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 2048
    hi = rng.integers(0, 1 << 31, n).astype(np.uint32)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    sh, sl, sv = distributed_z3_sort(mesh, jnp.asarray(hi), jnp.asarray(lo))
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    per = len(sh) // 8
    all_valid = []
    prev_max = -1
    for s in range(8):
        h = sh[s * per : (s + 1) * per]
        l = sl[s * per : (s + 1) * per]
        v = sv[s * per : (s + 1) * per]
        z = (h[v].astype(np.uint64) << np.uint64(32)) | l[v].astype(np.uint64)
        assert np.all(np.diff(z.astype(np.int64)) >= 0), f"shard {s} not sorted"
        if len(z):
            assert int(z[0]) >= prev_max, "shards out of global order"
            prev_max = int(z[-1])
        all_valid.append(z)
    merged = np.concatenate(all_valid)
    expected = np.sort(
        (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    )
    # no drops with uniform data at capacity 2x
    np.testing.assert_array_equal(merged, expected)


def test_full_build_and_query_step(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 1024
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.uniform(0, 604800, n)
    sfc = Z3SFC()
    bounds = (-10.0, 0.0, 30.0, 40.0, 10000.0, 300000.0)
    sh, sl, sv, count = sharded_build_and_query_step(
        mesh, sfc, jnp.asarray(x), jnp.asarray(y), jnp.asarray(t), bounds
    )
    expected = int(
        (
            (x >= bounds[0])
            & (x <= bounds[2])
            & (y >= bounds[1])
            & (y <= bounds[3])
            & (t >= bounds[4])
            & (t <= bounds[5])
        ).sum()
    )
    assert int(count) == expected
    # sorted keys match host-side encode of the same points
    z_host = np.sort(sfc.index(x, y, t))
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    z_dev = (
        (sh[sv].astype(np.uint64) << np.uint64(32)) | sl[sv].astype(np.uint64)
    )
    # global order: concatenation of shards ascending
    np.testing.assert_array_equal(np.sort(z_dev), z_host)
