"""Multi-chip (8 virtual CPU devices) mesh tests: sharded scan count,
radix-exchange distributed sort."""

import numpy as np
import pytest

from geomesa_tpu.curves import Z3SFC
from geomesa_tpu.parallel import (
    distributed_z3_sort,
    make_mesh,
    sharded_build_and_query_step,
    sharded_count_scan,
)


@pytest.fixture(scope="module")
def mesh():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_sharded_count_matches_host(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 1024
    x = rng.uniform(-180, 180, n).astype(np.float32)
    y = rng.uniform(-90, 90, n).astype(np.float32)

    def device_fn(cols):
        return (cols["x"] >= -10) & (cols["x"] <= 30) & (cols["y"] >= 0)

    count = int(
        sharded_count_scan(
            mesh, device_fn, {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        )
    )
    assert count == int(((x >= -10) & (x <= 30) & (y >= 0)).sum())


def test_distributed_sort_globally_ordered(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 2048
    hi = rng.integers(0, 1 << 31, n).astype(np.uint32)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    sh, sl, sv = distributed_z3_sort(mesh, jnp.asarray(hi), jnp.asarray(lo))
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    per = len(sh) // 8
    all_valid = []
    prev_max = -1
    for s in range(8):
        h = sh[s * per : (s + 1) * per]
        l = sl[s * per : (s + 1) * per]
        v = sv[s * per : (s + 1) * per]
        z = (h[v].astype(np.uint64) << np.uint64(32)) | l[v].astype(np.uint64)
        assert np.all(np.diff(z.astype(np.int64)) >= 0), f"shard {s} not sorted"
        if len(z):
            assert int(z[0]) >= prev_max, "shards out of global order"
            prev_max = int(z[-1])
        all_valid.append(z)
    merged = np.concatenate(all_valid)
    expected = np.sort(
        (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    )
    # no drops with uniform data at capacity 2x
    np.testing.assert_array_equal(merged, expected)


def test_full_build_and_query_step(mesh, rng):
    import jax.numpy as jnp

    n = 8 * 1024
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.uniform(0, 604800, n)
    sfc = Z3SFC()
    bounds = (-10.0, 0.0, 30.0, 40.0, 10000.0, 300000.0)
    sh, sl, sv, count = sharded_build_and_query_step(
        mesh, sfc, jnp.asarray(x), jnp.asarray(y), jnp.asarray(t), bounds
    )
    expected = int(
        (
            (x >= bounds[0])
            & (x <= bounds[2])
            & (y >= bounds[1])
            & (y <= bounds[3])
            & (t >= bounds[4])
            & (t <= bounds[5])
        ).sum()
    )
    assert int(count) == expected
    # sorted keys match host-side encode of the same points
    z_host = np.sort(sfc.index(x, y, t))
    sh, sl, sv = np.asarray(sh), np.asarray(sl), np.asarray(sv)
    z_dev = (
        (sh[sv].astype(np.uint64) << np.uint64(32)) | sl[sv].astype(np.uint64)
    )
    # global order: concatenation of shards ascending
    np.testing.assert_array_equal(np.sort(z_dev), z_host)


def test_sampled_splitters_survive_skew(mesh):
    """All points in one hot cell: radix routing overflows one destination
    and drops rows; sampled splitters keep every row and stay globally
    sorted (SURVEY hard part #5, GDELT skew)."""
    import jax.numpy as jnp

    n = 4096
    rng = np.random.default_rng(3)
    # a single ~1km cell: all z keys share their high bits
    x = rng.uniform(2.350, 2.351, n)
    y = rng.uniform(48.850, 48.851, n)
    t = rng.uniform(0, 3600.0, n)
    sfc = Z3SFC()
    hi, lo = sfc.index_jax_hi_lo(jnp.asarray(x), jnp.asarray(y), jnp.asarray(t))

    rh, rl, rv = distributed_z3_sort(mesh, hi, lo, splitters="radix")
    dropped_radix = n - int(np.asarray(rv).sum())
    assert dropped_radix > 0  # the skew actually defeats radix routing

    sh, sl, sv = distributed_z3_sort(mesh, hi, lo, splitters="sampled")
    assert int(np.asarray(sv).sum()) == n  # nothing dropped
    # global sortedness: concatenated valid keys are non-decreasing
    h = np.asarray(sh)[np.asarray(sv)]
    l = np.asarray(sl)[np.asarray(sv)]
    z = (h.astype(np.uint64) << np.uint64(32)) | l.astype(np.uint64)
    # per-shard slices are sorted and shard s's max <= shard s+1's min
    per = np.asarray(sv).reshape(8, -1)
    zs = np.asarray(sh).astype(np.uint64).reshape(8, -1) << np.uint64(32)
    zs |= np.asarray(sl).astype(np.uint64).reshape(8, -1)
    prev_max = None
    for s in range(8):
        vals = zs[s][per[s]]
        assert np.all(np.diff(vals.astype(np.int64)) >= 0)
        if len(vals):
            if prev_max is not None:
                assert vals[0] >= prev_max
            prev_max = vals[-1]


def test_multihost_helpers_single_process(mesh, rng):
    """The multi-host entry points must work unchanged on one process:
    initialize() no-ops, host slices become globally sharded arrays that
    collectives consume."""
    import jax

    from geomesa_tpu.parallel import (
        host_batches_to_global,
        initialize,
        sharded_count_scan,
    )
    from geomesa_tpu.parallel.multihost import global_mesh

    initialize()  # no coordinator configured -> no-op
    gm = global_mesh()
    assert gm.shape["shard"] == len(jax.devices())

    n = 1024
    cols = {
        "x": rng.uniform(-180, 180, n).astype(np.float32),
        "y": rng.uniform(-90, 90, n).astype(np.float32),
    }
    gcols = host_batches_to_global(mesh, cols)
    assert all(v.shape == (n,) for v in gcols.values())

    def fn(local):
        return (
            (local["x"] >= -10)
            & (local["x"] <= 30)
            & (local["y"] >= 35)
            & (local["y"] <= 60)
        )

    got = int(sharded_count_scan(mesh, fn, cols))
    want = int(
        (
            (cols["x"] >= -10)
            & (cols["x"] <= 30)
            & (cols["y"] >= 35)
            & (cols["y"] <= 60)
        ).sum()
    )
    assert got == want


def test_sampled_sort_adversarial_layouts(mesh):
    """Already-globally-sorted input (each source holds one quantile) and
    all-duplicate keys: both defeat naive splitter routing; the rebalance
    pass + tie spreading must keep every row."""
    import jax.numpy as jnp

    n = 4096
    # adversarial 1: globally sorted keys
    z = np.sort(np.random.default_rng(0).integers(0, 2**62, n).astype(np.uint64))
    hi = jnp.asarray((z >> np.uint64(32)).astype(np.uint32))
    lo = jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    sh, sl, sv = distributed_z3_sort(mesh, hi, lo, splitters="sampled")
    assert int(np.asarray(sv).sum()) == n
    got = np.sort(
        (np.asarray(sh).astype(np.uint64) << np.uint64(32))
        | np.asarray(sl).astype(np.uint64)
    )[:n]
    np.testing.assert_array_equal(np.sort(got), np.sort(z))

    # adversarial 2: every key identical
    hi2 = jnp.full(n, np.uint32(7), dtype=jnp.uint32)
    lo2 = jnp.full(n, np.uint32(9), dtype=jnp.uint32)
    sh2, sl2, sv2 = distributed_z3_sort(mesh, hi2, lo2, splitters="sampled")
    assert int(np.asarray(sv2).sum()) == n
