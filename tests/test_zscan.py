"""Z-only compare scan: masked key compare == per-dimension cell compare.

The kernel's claim is that no de-interleave is needed: spreading is
monotonic, so comparing (z & dim_mask) against spread bounds equals
comparing the decoded dimension against the cell bounds. The oracle here
decodes and compares per dimension — an independent path through the
same bit layout.
"""

import numpy as np
import pytest

from geomesa_tpu.curves import zorder
from geomesa_tpu.curves.z3 import Z3SFC
from geomesa_tpu.ops import zscan


def _planes(z: np.ndarray):
    import jax.numpy as jnp

    return (
        jnp.asarray((z >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
    )


class TestZ3MaskedCompare:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
    def test_fuzz_matches_decode_oracle(self, seed):
        rng = np.random.default_rng(seed)
        n = 20000
        cx = rng.integers(0, 1 << 21, n).astype(np.uint64)
        cy = rng.integers(0, 1 << 21, n).astype(np.uint64)
        ct = rng.integers(0, 1 << 21, n).astype(np.uint64)
        z = zorder.encode_3d_np(cx, cy, ct)
        lo = np.sort(rng.integers(0, 1 << 21, (3, 2)), axis=1)
        qlo, qhi = tuple(lo[:, 0]), tuple(lo[:, 1])
        bounds = zscan.z3_dim_bounds(qlo, qhi)[None]  # B=1
        bins = np.zeros(n, np.int32)
        import jax.numpy as jnp

        z_hi, z_lo = _planes(z)
        got = np.asarray(
            zscan.z3_zscan_mask(
                z_hi, z_lo, jnp.asarray(bins), jnp.asarray(bounds),
                jnp.asarray(np.array([0], np.int32)),
            )
        )
        expect = (
            (cx >= qlo[0]) & (cx <= qhi[0])
            & (cy >= qlo[1]) & (cy <= qhi[1])
            & (ct >= qlo[2]) & (ct <= qhi[2])
        )
        np.testing.assert_array_equal(got, expect)

    def test_degenerate_single_cell_and_full_domain(self):
        rng = np.random.default_rng(9)
        n = 5000
        cx = rng.integers(0, 1 << 21, n).astype(np.uint64)
        cy = rng.integers(0, 1 << 21, n).astype(np.uint64)
        ct = rng.integers(0, 1 << 21, n).astype(np.uint64)
        z = zorder.encode_3d_np(cx, cy, ct)
        import jax.numpy as jnp

        z_hi, z_lo = _planes(z)
        bins = jnp.zeros(n, jnp.int32)
        ids = jnp.asarray(np.array([0], np.int32))
        # full domain: everything matches
        full = zscan.z3_dim_bounds((0, 0, 0), ((1 << 21) - 1,) * 3)[None]
        assert bool(
            zscan.z3_zscan_mask(z_hi, z_lo, bins, jnp.asarray(full), ids).all()
        )
        # single cell: exactly the rows in that cell
        cell = (int(cx[0]), int(cy[0]), int(ct[0]))
        one = zscan.z3_dim_bounds(cell, cell)[None]
        got = np.asarray(
            zscan.z3_zscan_mask(z_hi, z_lo, bins, jnp.asarray(one), ids)
        )
        expect = (cx == cell[0]) & (cy == cell[1]) & (ct == cell[2])
        np.testing.assert_array_equal(got, expect)

    def test_multi_bin_window(self):
        """Per-bin bounds: edge bins partial, interior bins full, padded
        ids never match."""
        import jax.numpy as jnp

        sfc = Z3SFC()
        rng = np.random.default_rng(4)
        n = 50000
        lon = rng.uniform(-180, 180, n)
        lat = rng.uniform(-90, 90, n)
        t0 = np.datetime64("2020-01-01").astype("datetime64[ms]").astype(np.int64)
        t = t0 + rng.integers(0, 30 * 86400_000, n)
        from geomesa_tpu.curves.binnedtime import to_binned_time

        bins_np, off = to_binned_time(t, sfc.period)
        z = sfc.index(lon, lat, off)
        z_hi, z_lo = _planes(z)

        qx0, qy0, qx1, qy1 = -20.0, 10.0, 60.0, 70.0
        qt0 = int(t0 + 3 * 86400_000)
        qt1 = int(t0 + 17 * 86400_000)  # spans 3 week bins
        bounds, ids = zscan.z3_query_bounds(sfc, qx0, qy0, qx1, qy1, qt0, qt1)
        assert len(ids) == 3
        bounds, ids = zscan.pad_bins(bounds, ids)
        assert len(ids) == 4 and ids[-1] == -1
        got = np.asarray(
            zscan.z3_zscan_mask(
                z_hi, z_lo,
                jnp.asarray(bins_np.astype(np.int32)),
                jnp.asarray(bounds), jnp.asarray(ids),
            )
        )
        # oracle: quantized-cell (loose) semantics per dimension
        nx = np.asarray(sfc.lon.normalize(lon)).astype(np.int64)
        ny = np.asarray(sfc.lat.normalize(lat)).astype(np.int64)
        qnx = (int(sfc.lon.normalize(qx0)), int(sfc.lon.normalize(qx1)))
        qny = (int(sfc.lat.normalize(qy0)), int(sfc.lat.normalize(qy1)))
        spatial = (nx >= qnx[0]) & (nx <= qnx[1]) & (ny >= qny[0]) & (ny <= qny[1])
        nt = np.asarray(sfc.time.normalize(off)).astype(np.int64)
        from geomesa_tpu.curves.binnedtime import bins_for_interval

        temporal = np.zeros(n, bool)
        for b, lo_off, hi_off in bins_for_interval(qt0, qt1, sfc.period):
            lo_c = int(sfc.time.normalize(lo_off))
            hi_c = int(sfc.time.normalize(hi_off))
            temporal |= (bins_np == b) & (nt >= lo_c) & (nt <= hi_c)
        np.testing.assert_array_equal(got, spatial & temporal)
        # loose semantics contain the exact box (no false negatives)
        exact = (
            (lon >= qx0) & (lon <= qx1) & (lat >= qy0) & (lat <= qy1)
            & (t >= qt0) & (t <= qt1)
        )
        assert not np.any(exact & ~got)


class TestZ2MaskedCompare:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_fuzz_matches_cell_oracle(self, seed):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        n = 20000
        cx = rng.integers(0, 1 << 31, n).astype(np.uint64)
        cy = rng.integers(0, 1 << 31, n).astype(np.uint64)
        z = zorder.encode_2d_np(cx, cy)
        lo = np.sort(
            rng.integers(0, 1 << 31, (2, 2), dtype=np.int64), axis=1
        )
        qlo, qhi = tuple(lo[:, 0]), tuple(lo[:, 1])
        bounds = zscan.z2_dim_bounds(qlo, qhi)
        z_hi = jnp.asarray((z >> np.uint64(32)).astype(np.uint32))
        z_lo = jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32))
        got = np.asarray(zscan.z2_zscan_mask(z_hi, z_lo, jnp.asarray(bounds)))
        expect = (
            (cx >= qlo[0]) & (cx <= qhi[0]) & (cy >= qlo[1]) & (cy <= qhi[1])
        )
        np.testing.assert_array_equal(got, expect)


class TestZ3PallasKernel:
    """The Pallas tile kernel (interpret mode on CPU = identical kernel
    code) must agree with the XLA masked-compare path exactly."""

    def _data(self, n=30000, seed=11):
        import jax.numpy as jnp

        sfc = Z3SFC()
        rng = np.random.default_rng(seed)
        lon = rng.uniform(-180, 180, n)
        lat = rng.uniform(-90, 90, n)
        t0 = np.datetime64("2020-01-01").astype("datetime64[ms]").astype(np.int64)
        t = t0 + rng.integers(0, 30 * 86400_000, n)
        from geomesa_tpu.curves.binnedtime import to_binned_time

        bins_np, off = to_binned_time(t, sfc.period)
        z = sfc.index(lon, lat, off)
        return sfc, t0, (
            jnp.asarray(bins_np.astype(np.int32)),
            jnp.asarray((z >> np.uint64(32)).astype(np.uint32)),
            jnp.asarray((z & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        )

    def test_count_and_mask_match_xla_path(self):
        import jax.numpy as jnp

        sfc, t0, (bins, z_hi, z_lo) = self._data()
        bounds, ids = zscan.z3_query_bounds(
            sfc, -20.0, 10.0, 60.0, 70.0,
            int(t0 + 3 * 86400_000), int(t0 + 17 * 86400_000),
        )
        bounds, ids = zscan.pad_bins(bounds, ids)
        count_fn, mask_fn = zscan.build_z3_pallas_scan(bounds, ids)
        expect = np.asarray(
            zscan.z3_zscan_mask(
                z_hi, z_lo, bins, jnp.asarray(bounds), jnp.asarray(ids)
            )
        )
        got_mask = np.asarray(mask_fn(bins, z_hi, z_lo))
        np.testing.assert_array_equal(got_mask, expect)
        assert int(count_fn(bins, z_hi, z_lo)) == int(expect.sum())

    def test_all_padded_bins_counts_zero(self):
        sfc, t0, (bins, z_hi, z_lo) = self._data(n=1000)
        bounds = np.zeros((2, 3, 6), np.uint32)
        ids = np.full(2, -1, np.int32)
        count_fn, _ = zscan.build_z3_pallas_scan(bounds, ids)
        assert int(count_fn(bins, z_hi, z_lo)) == 0


class TestDimPlaneScan:
    """De-interleaved key planes (nx/ny/packed bt) must answer exactly the
    cell-granular query the interleaved masked-compare answers."""

    def _data(self, rng, n=30_000):
        from geomesa_tpu.curves import Z3SFC
        from geomesa_tpu.curves.binnedtime import to_binned_time

        sfc = Z3SFC()
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
        ms = rng.integers(1_577_836_800_000, 1_583_020_800_000, n)
        bins, off = to_binned_time(ms, sfc.period)
        nx = sfc.lon.normalize(x).astype(np.uint32)
        ny = sfc.lat.normalize(y).astype(np.uint32)
        nt = sfc.time.normalize(off.astype(np.float64)).astype(np.uint32)
        return sfc, x, y, ms, bins, off, nx, ny, nt

    def test_matches_masked_compare_engine(self, rng):
        import jax.numpy as jnp

        from geomesa_tpu.ops import zscan

        sfc, x, y, ms, bins, off, nx, ny, nt = self._data(rng)
        bin_base = int(bins.min())
        nxp, nyp, bt = zscan.z3_dim_planes(
            sfc, nx, ny, nt, bins.astype(np.uint32), bin_base
        )
        q = (-10.0, 35.0, 30.0, 60.0)
        t0, t1 = 1_578_614_400_000, 1_580_515_200_000  # multi-bin window
        dq = zscan.z3_dim_plane_query(sfc, *q, t0, t1, bin_base)
        assert dq is not None
        qnx, qny, bt_ranges = dq
        # interior whole bins merged: fewer ranges than bins
        got = np.asarray(
            zscan.z3_dimscan_mask(
                jnp.asarray(nxp), jnp.asarray(nyp), jnp.asarray(bt),
                qnx, qny, bt_ranges,
            )
        )
        # independent engine: interleaved masked-compare
        z = sfc.index(x, y, off.astype(np.float64))
        zh = (z >> np.uint64(32)).astype(np.uint32)
        zl = (z & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        bounds, ids = zscan.z3_query_bounds(sfc, *q, t0, t1)
        bounds, ids = zscan.pad_bins(bounds, ids)
        ref = np.asarray(
            zscan.z3_zscan_mask(
                jnp.asarray(zh), jnp.asarray(zl),
                jnp.asarray(bins.astype(np.int32)),
                jnp.asarray(bounds), jnp.asarray(ids),
            )
        )
        np.testing.assert_array_equal(got, ref)
        assert got.sum() > 0

    def test_pallas_kernel_interpret_matches_xla(self, rng):
        import jax.numpy as jnp

        from geomesa_tpu.ops import zscan

        sfc, x, y, ms, bins, off, nx, ny, nt = self._data(rng, n=70_000)
        bin_base = int(bins.min())
        nxp, nyp, bt = zscan.z3_dim_planes(
            sfc, nx, ny, nt, bins.astype(np.uint32), bin_base
        )
        dq = zscan.z3_dim_plane_query(
            sfc, -10.0, 35.0, 30.0, 60.0,
            1_578_614_400_000, 1_580_515_200_000, bin_base,
        )
        qnx, qny, bt_ranges = dq
        count_fn, mask_fn = zscan.build_z3_dimscan_pallas(
            qnx, qny, bt_ranges
        )
        a = (jnp.asarray(nxp), jnp.asarray(nyp), jnp.asarray(bt))
        ref = np.asarray(
            zscan.z3_dimscan_mask(*a, qnx, qny, bt_ranges)
        )
        assert int(count_fn(*a)) == int(ref.sum())
        np.testing.assert_array_equal(np.asarray(mask_fn(*a)), ref)

    def test_query_outside_packable_window_returns_none(self):
        from geomesa_tpu.curves import Z3SFC
        from geomesa_tpu.ops import zscan

        sfc = Z3SFC()
        # bin_base far in the future: 2020 bins land below it
        out = zscan.z3_dim_plane_query(
            sfc, 0.0, 0.0, 1.0, 1.0,
            1_577_836_800_000, 1_578_441_600_000, 10_000,
        )
        assert out is None

    def test_out_of_window_rows_get_sentinel(self, rng):
        """Rows outside the packable bin window become deterministically
        unmatchable (sentinel bt), never another bin's key space."""
        import jax.numpy as jnp

        from geomesa_tpu.curves import Z3SFC
        from geomesa_tpu.ops import zscan

        sfc = Z3SFC()
        nx = np.zeros(4, np.uint32)
        ny = np.zeros(4, np.uint32)
        nt = np.zeros(4, np.uint32)
        bins = np.array([100, 99, 100 + zscan.BT_BIN_SPAN, 101], np.uint32)
        _, _, bt = zscan.z3_dim_planes(sfc, nx, ny, nt, bins, 100)
        assert bt[1] == 0xFFFFFFFF  # below window
        assert bt[2] == 0xFFFFFFFF  # above window
        assert bt[0] != 0xFFFFFFFF and bt[3] != 0xFFFFFFFF
        # the reserved sentinel bin is never addressable by a query
        lo_ms = 100 * (7 * 86400_000)
        top = (100 + zscan.BT_BIN_SPAN - 1) * (7 * 86400_000)
        assert zscan.z3_dim_plane_query(
            sfc, 0.0, 0.0, 1.0, 1.0, top, top + 1000, 100
        ) is None
        # in-window queries never match the sentinel rows
        dq = zscan.z3_dim_plane_query(
            sfc, -180.0, -90.0, 180.0, 90.0, lo_ms, lo_ms + 10_000, 100
        )
        qnx, qny, rs = dq
        m = np.asarray(zscan.z3_dimscan_mask(
            jnp.asarray(nx), jnp.asarray(ny), jnp.asarray(bt), qnx, qny, rs
        ))
        assert not m[1] and not m[2]
