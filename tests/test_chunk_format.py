"""Chunked columnar partition format v2 + aggregation pushdown.

The contracts under test (ISSUE 6):

- flushes write per-chunk statistics (rows, key min/max, bbox, time
  range, coarse density cells, MinMax partials, row-group byte sizes)
  that round-trip through the manifest, with parquet row groups aligned
  1:1 to the chunks;
- count/stats pushdown is BIT-IDENTICAL to the row scan (interior
  chunks from summaries, boundary chunks row-refined); density pushdown
  is mass-exact and per-cell exact on grid-aligned rasters;
- chunk Z/bbox/time pruning in the streamed scan skips work before
  read/decode without changing any result, at every worker count;
- v1 manifests stay readable and lazily upgrade to v2 on compact;
- fsck cross-checks chunk stats against decoded rows and fails loudly
  on drift.
"""

import json
import os

import numpy as np
import pytest

from geomesa_tpu import metrics
from geomesa_tpu.conf import prop_override
from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.geom import Envelope
from geomesa_tpu.query.plan import Query
from geomesa_tpu.store.fs import FileSystemDataStore

SPEC = "val:Int,tone:Float,dtg:Date,*geom:Point:srid=4326"
N = 4000
T0 = parse_instant("2020-01-01T00:00:00")
T1 = parse_instant("2020-02-01T00:00:00")

WINDOW = (
    "BBOX(geom, -10, 0, 40, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z"
)


def _make(root, n=N, part=512, chunk=128, fmt=2, vis=None):
    with prop_override("store.format.version", fmt), \
            prop_override("store.chunk.rows", chunk), \
            prop_override("store.chunk.grid", 32):
        ds = FileSystemDataStore(root, partition_size=part)
        ds.create_schema("t", SPEC)
        rng = np.random.default_rng(5)
        cols = {
            "val": rng.integers(0, 100, n),
            "tone": rng.uniform(-10, 10, n).astype(np.float32),
            "dtg": rng.integers(T0, T1, n),
            "geom": np.stack(
                [rng.uniform(-60, 60, n), rng.uniform(-50, 50, n)], axis=1
            ),
        }
        if vis is not None:
            from geomesa_tpu.security import VIS_COLUMN

            cols[VIS_COLUMN] = vis
        ds.write("t", cols, fids=np.arange(n))
        ds.flush("t")
    return ds


def _exact(ds, q):
    if isinstance(q, Query):
        import dataclasses

        q = dataclasses.replace(q, hints={**q.hints, "agg.pushdown": False})
    else:
        q = Query(filter=q, hints={"agg.pushdown": False})
    return ds.query("t", q)


# -- format / manifest -------------------------------------------------------


def test_v2_manifest_chunks_round_trip_and_row_group_alignment(tmp_path):
    ds = _make(str(tmp_path / "s"))
    with open(os.path.join(str(tmp_path / "s"), "t", "schema.json")) as fh:
        meta = json.load(fh)
    assert meta["format"] == 2
    import pyarrow.parquet as pq

    for p, pj in zip(ds._types["t"].partitions, meta["partitions"]):
        cs = p.chunks
        assert cs is not None
        assert cs.total_rows == p.count
        assert len(pj["chunks"]["rows"]) == len(cs)
        # chunk key spans tile the partition's key span, in order
        assert tuple(cs.key_lo[0]) == tuple(p.key_lo)
        assert tuple(cs.key_hi[-1]) == tuple(p.key_hi)
        for i in range(len(cs) - 1):
            assert cs.key_hi[i] <= cs.key_lo[i + 1]
        # parquet row groups align 1:1 with the chunks
        md = pq.ParquetFile(ds._part_path("t", p)).metadata
        assert md.num_row_groups == len(cs)
        for i in range(md.num_row_groups):
            assert md.row_group(i).num_rows == int(cs.rows[i])
        assert cs.nbytes is not None and len(cs.nbytes) == len(cs)
        # density-cell mass per chunk == chunk rows (point schema)
        for i in range(len(cs)):
            assert int(cs.cell_counts[i].sum()) == int(cs.rows[i])
    # a reopened store sees the same chunk stats
    ds2 = FileSystemDataStore(str(tmp_path / "s"), partition_size=512)
    p0, q0 = ds._types["t"].partitions[0], ds2._types["t"].partitions[0]
    assert q0.chunks is not None
    assert q0.chunks.key_lo == p0.chunks.key_lo
    np.testing.assert_array_equal(q0.chunks.rows, p0.chunks.rows)
    assert ds2._types["t"].format_version == 2


def test_store_stats_reports_format_mix_and_coverage(tmp_path):
    ds = _make(str(tmp_path / "s"))
    t = ds.store_stats()["types"]["t"]
    assert t["format"] == 2
    assert t["chunked_partitions"] == t["partitions"] > 0
    assert t["chunks"] >= t["partitions"]
    assert t["chunk_rows_covered"] == N
    ds1 = _make(str(tmp_path / "s1"), fmt=1)
    t1 = ds1.store_stats()["types"]["t"]
    assert t1["format"] == 1
    assert t1["chunked_partitions"] == 0 and t1["chunks"] == 0


def test_chunk_selective_read_equals_row_slice(tmp_path):
    ds = _make(str(tmp_path / "s"))
    p = ds._types["t"].partitions[0]
    full = ds._read_partition("t", p, cache=False)
    cs = p.chunks
    sel = [0, len(cs) - 1]
    got = ds._read_partition("t", p, cache=False, chunk_sel=sel)
    idx = np.concatenate(
        [np.arange(cs.starts[i], cs.stops[i]) for i in sel]
    )
    want = full.take(idx)
    assert list(got.fids) == list(want.fids)
    np.testing.assert_array_equal(got.column("val"), want.column("val"))
    # the pruned read fetches only the selected row groups' bytes
    b0 = metrics.io_bytes_read.value()
    ds._read_partition("t", p, cache=False, chunk_sel=[0])
    assert metrics.io_bytes_read.value() - b0 == int(cs.nbytes[0])
    # ...and a cached full batch serves the slice without a file read
    ds._read_partition("t", p, cache=True)
    b0 = metrics.io_bytes_read.value()
    got2 = ds._read_partition("t", p, cache=False, chunk_sel=sel)
    assert metrics.io_bytes_read.value() == b0
    assert list(got2.fids) == list(want.fids)


# -- count pushdown ----------------------------------------------------------


@pytest.mark.parametrize(
    "q",
    [
        "INCLUDE",
        WINDOW,
        "BBOX(geom, -10, 0, 40, 45)",
        "BBOX(geom, -60, -50, 30, 30) AND "
        "dtg DURING 2020-01-03T00:00:00Z/2020-01-28T00:00:00Z",
        "BBOX(geom, 100, 60, 120, 80)",  # provably empty window
        "dtg DURING 2020-01-10T00:00:00Z/2020-01-12T00:00:00Z",
    ],
)
def test_count_pushdown_parity(tmp_path, q):
    ds = _make(str(tmp_path / "s"))
    assert ds.count("t", q) == len(_exact(ds, q).batch)


def test_count_pushdown_short_circuits_include(tmp_path):
    """INCLUDE is the pure pre-aggregate case: every chunk is interior,
    the answer comes from the manifest with zero partition reads."""
    ds = _make(str(tmp_path / "s"))
    r0 = metrics.agg_pushdown_rows.value()
    b0 = metrics.io_bytes_read.value()
    assert ds.count("t") == N
    assert metrics.agg_pushdown_rows.value() - r0 == N
    assert metrics.io_bytes_read.value() == b0  # no file was touched


def test_count_pushdown_fallbacks(tmp_path):
    ds = _make(str(tmp_path / "s"))
    f0 = metrics.agg_pushdown_fallbacks.value(kind="count")
    # attribute predicates are beyond chunk stats: row scan, same answer
    q = "val > 50 AND BBOX(geom, -10, 0, 40, 45)"
    assert ds.count("t", q) == len(_exact(ds, q).batch)
    # max_features caps have row-level semantics
    capped = ds.count("t", Query(filter="INCLUDE", max_features=7))
    assert capped == 7
    # explicit veto
    assert ds.count(
        "t", Query(filter=WINDOW, hints={"agg.pushdown": False})
    ) == len(_exact(ds, WINDOW).batch)
    with prop_override("store.chunk.pushdown", False):
        assert ds.count("t", WINDOW) == len(_exact(ds, WINDOW).batch)
    assert metrics.agg_pushdown_fallbacks.value(kind="count") == f0


def test_count_pushdown_respects_global_max_features_cap(tmp_path):
    """The global query.max.features interceptor caps counts DURING
    planning; pushdown must notice the rewritten query and fall back
    (a manifest-summed count would silently ignore the cap)."""
    ds = _make(str(tmp_path / "s"))
    with prop_override("query.max.features", 5):
        assert ds.count("t") == 5
        from geomesa_tpu.store.oocscan import StreamedDeviceScan

        # oocscan ignores caps only via the store fallback path, which
        # applies them — the pushdown split must not bypass that
        scan = StreamedDeviceScan(ds, "t", slab_rows=1024, io=0)
        assert scan.count("INCLUDE") == 5


def test_count_pushdown_respects_visibility(tmp_path):
    """Labeled rows hide without auths; pushdown cannot see labels, so
    a store with visibility-labeled partitions must fall back."""
    vis = np.array(["secret"] * 10 + [""] * (N - 10), dtype=object)
    ds = _make(str(tmp_path / "s"), vis=vis)
    assert ds._types["t"].partitions[0].chunks is not None
    f0 = metrics.agg_pushdown_fallbacks.value(kind="count")
    assert ds.count("t") == N - 10  # labeled rows hidden (fail closed)
    assert metrics.agg_pushdown_fallbacks.value(kind="count") > f0


# -- density pushdown --------------------------------------------------------


def _aligned_env(grid=32, x0=18, y0=14, x1=26, y1=22):
    cw, ch = 360.0 / grid, 180.0 / grid
    return Envelope(
        -180 + x0 * cw, -90 + y0 * ch, -180 + x1 * cw, -90 + y1 * ch
    )


def test_density_pushdown_mass_and_cell_parity(tmp_path):
    from geomesa_tpu.process.density import density

    ds = _make(str(tmp_path / "s"))
    env = _aligned_env()
    # raster pixels == coarse world cells: placement is exact, not just
    # within tolerance
    w, h = 8, 8
    for q in (
        f"BBOX(geom, {env.xmin}, {env.ymin}, {env.xmax}, {env.ymax})",
        f"BBOX(geom, {env.xmin}, {env.ymin}, {env.xmax}, {env.ymax}) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z",
    ):
        r0 = metrics.agg_pushdown_queries.value(kind="density")
        got = density(ds, "t", q, env, w, h, use_device=False)
        assert metrics.agg_pushdown_queries.value(kind="density") > r0
        want = density(
            ds, "t", Query(filter=q, hints={"agg.pushdown": False}),
            env, w, h, use_device=False,
        )
        assert got.sum() == want.sum()  # total mass exact
        np.testing.assert_allclose(got, want, atol=1e-3)


def test_density_pushdown_unaligned_within_cell_tolerance(tmp_path):
    from geomesa_tpu.process.density import density

    ds = _make(str(tmp_path / "s"))
    env = Envelope(-12.3, -1.7, 38.9, 44.1)  # not grid-aligned
    q = f"BBOX(geom, {env.xmin}, {env.ymin}, {env.xmax}, {env.ymax})"
    got = density(ds, "t", q, env, 16, 16, use_device=False)
    want = density(
        ds, "t", Query(filter=q, hints={"agg.pushdown": False}),
        env, 16, 16, use_device=False,
    )
    # edge cells prorate: mass within one coarse-cell row/column of rows
    assert abs(got.sum() - want.sum()) <= want.sum() * 0.25 + 50
    assert got.sum() > 0


def test_density_pushdown_weighted_falls_back(tmp_path):
    from geomesa_tpu.process.density import density

    ds = _make(str(tmp_path / "s"))
    env = _aligned_env()
    q = f"BBOX(geom, {env.xmin}, {env.ymin}, {env.xmax}, {env.ymax})"
    d0 = metrics.agg_pushdown_queries.value(kind="density")
    got = density(
        ds, "t", q, env, 8, 8, weight_attr="tone", use_device=False
    )
    want = density(
        ds, "t", Query(filter=q, hints={"agg.pushdown": False}),
        env, 8, 8, weight_attr="tone", use_device=False,
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)
    assert metrics.agg_pushdown_queries.value(kind="density") == d0


# -- stats pushdown ----------------------------------------------------------


def test_stats_pushdown_parity_exact(tmp_path):
    from geomesa_tpu.process.statsproc import run_stats

    ds = _make(str(tmp_path / "s"))
    for q in ("INCLUDE", WINDOW):
        s0 = metrics.agg_pushdown_queries.value(kind="stats")
        got = run_stats(ds, "t", q, "Count();MinMax('val');MinMax('dtg')")
        assert metrics.agg_pushdown_queries.value(kind="stats") > s0
        want = run_stats(
            ds, "t", Query(filter=q, hints={"agg.pushdown": False}),
            "Count();MinMax('val');MinMax('dtg')",
        )
        assert [s.to_json() for s in got.stats] == [
            s.to_json() for s in want.stats
        ]


def test_stats_pushdown_unsupported_spec_falls_back(tmp_path):
    from geomesa_tpu.process.statsproc import run_stats

    ds = _make(str(tmp_path / "s"))
    s0 = metrics.agg_pushdown_queries.value(kind="stats")
    got = run_stats(ds, "t", WINDOW, "Cardinality('val')")
    assert metrics.agg_pushdown_queries.value(kind="stats") == s0
    want = run_stats(
        ds, "t", Query(filter=WINDOW, hints={"agg.pushdown": False}),
        "Cardinality('val')",
    )
    assert abs(got.stats[0].estimate - want.stats[0].estimate) < 1e-9


# -- streamed-scan chunk pruning ---------------------------------------------


@pytest.mark.parametrize("workers", [0, 2])
def test_oocscan_chunk_pruning_parity(tmp_path, workers):
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    ds = _make(str(tmp_path / "s"))
    scan = StreamedDeviceScan(ds, "t", slab_rows=1024, io=workers)
    with prop_override("store.chunk.prune", False), \
            prop_override("store.chunk.pushdown", False):
        want_n = scan.count(WINDOW)
        want = scan.query(WINDOW)
    s0 = metrics.store_chunks_skipped.value()
    b0 = metrics.store_chunk_bytes_skipped.value()
    with prop_override("store.chunk.pushdown", False):
        got_n = scan.count(WINDOW)  # pruning alone
        got = scan.query(WINDOW)
    assert got_n == want_n
    assert list(got.fids) == list(want.fids)
    np.testing.assert_array_equal(got.column("val"), want.column("val"))
    # the selective window must actually prune (chunks AND real bytes)
    assert metrics.store_chunks_skipped.value() > s0
    assert metrics.store_chunk_bytes_skipped.value() > b0
    # pruning + pushdown together still agree
    assert scan.count(WINDOW) == want_n


def test_oocscan_count_summary_never_leaks_hidden_rows(tmp_path):
    """Review regression: the non-device INCLUDE count falls back to
    store.query, which hides visibility-labeled rows — a manifest
    summary answering that branch must not widen the count to include
    them (the has_vis guard in _agg_split)."""
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    vis = np.array(["secret"] * 10 + [""] * (N - 10), dtype=object)
    ds = _make(str(tmp_path / "s"), vis=vis)
    scan = StreamedDeviceScan(ds, "t", slab_rows=1024, io=0)
    assert scan.count("INCLUDE") == N - 10  # == store.query semantics
    assert len(ds.query("t").batch) == N - 10


def test_oocscan_count_pushdown_include_reads_nothing(tmp_path):
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    ds = _make(str(tmp_path / "s"))
    scan = StreamedDeviceScan(ds, "t", slab_rows=1024, io=0)
    b0 = metrics.io_bytes_read.value()
    assert scan.count("INCLUDE") == N
    assert metrics.io_bytes_read.value() == b0


def test_nan_coordinates_never_pruned_away(tmp_path):
    """Review regression: a NaN coordinate poisons its chunk's bbox
    (reduceat propagates NaN) and every NaN comparison is False — the
    chunk must classify BOUNDARY (row-refine), never DISJOINT, or its
    VALID rows silently vanish from pruned scans and pushdown counts."""
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    root = str(tmp_path / "s")
    n = 1024
    with prop_override("store.format.version", 2), \
            prop_override("store.chunk.rows", 64):
        ds = FileSystemDataStore(root, partition_size=256)
        ds.create_schema("t", SPEC)
        rng = np.random.default_rng(9)
        gx = rng.uniform(5, 20, n)
        gy = rng.uniform(5, 20, n)
        gx[::97] = np.nan  # NaN rows sprinkled across chunks
        gy[::97] = np.nan
        ds.write("t", {
            "val": rng.integers(0, 100, n),
            "tone": rng.uniform(-1, 1, n).astype(np.float32),
            "dtg": rng.integers(T0, T1, n),
            "geom": np.stack([gx, gy], axis=1),
        }, fids=np.arange(n))
        ds.flush("t")
    q = "BBOX(geom, 0, 0, 30, 30)"
    want = len(_exact(ds, q).batch)
    assert want == int((~np.isnan(gx)).sum())  # valid rows all inside
    assert ds.count("t", q) == want
    scan = StreamedDeviceScan(ds, "t", slab_rows=256, io=0)
    assert scan.count(q) == want
    # fsck tolerates the legitimately-NaN bbox records
    assert ds.verify_chunk_stats("t") == []
    # density: NaN chunks row-refine; mass equals the exact raster
    from geomesa_tpu.geom import Envelope
    from geomesa_tpu.process.density import density

    env = Envelope(0, 0, 30, 30)
    got = density(ds, "t", q, env, 8, 8, use_device=False)
    want_g = density(
        ds, "t", Query(filter=q, hints={"agg.pushdown": False}),
        env, 8, 8, use_device=False,
    )
    assert float(got.sum()) == float(want_g.sum())


# -- v1 compatibility / lazy upgrade -----------------------------------------


def test_v1_reads_and_lazily_upgrades_on_compact(tmp_path):
    root = str(tmp_path / "s")
    ds = _make(root, fmt=1)
    with open(os.path.join(root, "t", "schema.json")) as fh:
        meta = json.load(fh)
    assert meta["format"] == 1
    assert all(p.get("chunks") is None for p in meta["partitions"])
    want = len(_exact(ds, WINDOW).batch)
    # v1 serves correctly; aggregates fall back to the row scan
    assert ds.count("t", WINDOW) == want
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    assert StreamedDeviceScan(ds, "t", slab_rows=1024, io=0).count(
        WINDOW
    ) == want
    # lazy upgrade: compact rewrites at the current format version
    with prop_override("store.chunk.rows", 128):
        ds.compact("t")
    with open(os.path.join(root, "t", "schema.json")) as fh:
        meta = json.load(fh)
    assert meta["format"] == 2
    st = ds._types["t"]
    assert all(p.chunks is not None for p in st.partitions)
    assert ds.count("t", WINDOW) == want
    assert ds.verify_chunk_stats("t") == []


def test_v1_store_written_by_v2_reader_round_trips(tmp_path):
    """A v2-capable process re-reading a v1 manifest must not invent
    chunk stats, and re-flushing under format 1 keeps it v1."""
    root = str(tmp_path / "s")
    ds = _make(root, fmt=1)
    with prop_override("store.format.version", 1):
        ds.write("t", {
            "val": [1], "tone": [0.0], "dtg": [T0],
            "geom": np.array([[0.0, 0.0]]),
        }, fids=[99999])
        ds.flush("t")
    with open(os.path.join(root, "t", "schema.json")) as fh:
        assert json.load(fh)["format"] == 1
    assert ds.count("t") == N + 1


# -- fsck cross-check --------------------------------------------------------


def _tamper_manifest(root, mutate):
    path = os.path.join(root, "t", "schema.json")
    with open(path) as fh:
        meta = json.load(fh)
    mutate(meta)
    with open(path, "w") as fh:
        json.dump(meta, fh)
    gen = meta["generation"]
    with open(path + ".gen", "w") as fh:
        fh.write(gen)


def test_fsck_chunk_stat_drift_detected(tmp_path, capsys):
    from geomesa_tpu.tools.cli import main

    root = str(tmp_path / "s")
    ds = _make(root)
    assert ds.verify_chunk_stats("t") == []
    main(["--root", root, "fsck"])  # clean store exits 0
    assert "chunk stats cross-checked" in capsys.readouterr().out

    def mutate(meta):
        ch = meta["partitions"][0]["chunks"]
        ch["key_lo"][0] = [0, 0]  # lie about the first chunk's key span
        ch["time_range"][1][0] -= 1000

    _tamper_manifest(root, mutate)
    d0 = metrics.store_chunk_stat_drift.value()
    fresh = FileSystemDataStore(root, partition_size=512)
    drift = fresh.verify_chunk_stats("t")
    assert len(drift) >= 2
    assert metrics.store_chunk_stat_drift.value() > d0
    with pytest.raises(SystemExit, match="drifted"):
        main(["--root", root, "fsck"])
    assert "DRIFT" in capsys.readouterr().out


def test_fsck_detects_row_count_drift(tmp_path):
    root = str(tmp_path / "s")
    _make(root)

    def mutate(meta):
        meta["partitions"][0]["chunks"]["rows"][0] += 5

    _tamper_manifest(root, mutate)
    fresh = FileSystemDataStore(root, partition_size=512)
    drift = fresh.verify_chunk_stats("t")
    assert drift and "sum" in drift[0][2]


# -- presized staging --------------------------------------------------------


def test_full_scan_presized_assembly_parity(tmp_path):
    """The manifest-presized full-scan path (what DeviceIndex staging
    rides) must return exactly what the concat path returns."""
    ds = _make(str(tmp_path / "s"))
    res = ds.query("t")  # Include, no ranges -> presized sink
    assert len(res.batch) == N
    assert sorted(int(f) for f in res.batch.fids) == list(range(N))
    assert ds.manifest_rows("t") == N
    cols = res.batch.columns
    assert all(len(v) == N for v in cols.values())
