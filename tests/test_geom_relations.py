"""DE-9IM-lite relation algebra: crosses / touches / overlaps / relate.

Three tiers (no JTS/shapely in the image, so no library oracle):
1. constructed ground-truth cases per dimension pair,
2. randomized consistency invariants (symmetry, mutual exclusivity,
   implication back to intersects),
3. a dense-grid sampling oracle for area/area interior relations
   (interiors are 2-dimensional, so sampling is a sound oracle for them).
"""

import numpy as np
import pytest

from geomesa_tpu.geom.base import (
    LineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geom.predicates import (
    geometry_crosses,
    geometry_intersects,
    geometry_overlaps,
    geometry_relate,
    geometry_relate_matches,
    geometry_touches,
    interior_point,
)


def sq(x0, y0, x1, y1, holes=()):
    return Polygon(
        [[x0, y0], [x1, y0], [x1, y1], [x0, y1], [x0, y0]], tuple(holes)
    )


A = sq(0, 0, 4, 4)
B = sq(2, 2, 6, 6)  # overlaps A
C = sq(4, 0, 8, 4)  # shares the x=4 edge with A
CORNER = sq(4, 4, 6, 6)  # touches A at the single point (4,4)
D = sq(1, 1, 2, 2)  # inside A
E = sq(10, 10, 12, 12)  # disjoint from A


class TestAreaArea:
    def test_overlap(self):
        assert geometry_overlaps(A, B) and geometry_overlaps(B, A)
        assert not geometry_touches(A, B)
        assert not geometry_crosses(A, B)  # crosses undefined for area/area

    def test_shared_edge_touches(self):
        assert geometry_touches(A, C) and geometry_touches(C, A)
        assert not geometry_overlaps(A, C)

    def test_corner_point_touches(self):
        assert geometry_touches(A, CORNER)
        assert not geometry_overlaps(A, CORNER)

    def test_containment_is_neither(self):
        assert not geometry_touches(A, D)
        assert not geometry_overlaps(A, D)

    def test_equal_is_neither(self):
        assert not geometry_overlaps(A, sq(0, 0, 4, 4))
        assert not geometry_touches(A, sq(0, 0, 4, 4))

    def test_disjoint_is_neither(self):
        assert not geometry_touches(A, E)
        assert not geometry_overlaps(A, E)

    def test_hole_boundary_touch(self):
        donut = sq(0, 0, 6, 6, holes=[[[2, 2], [4, 2], [4, 4], [2, 4], [2, 2]]])
        filling = sq(2, 2, 4, 4)
        # the filling exactly fills the hole: contact is boundary-only
        assert geometry_touches(donut, filling)
        assert not geometry_overlaps(donut, filling)


class TestLineLine:
    def test_x_crossing(self):
        x1 = LineString([[0, 0], [2, 2]])
        x2 = LineString([[0, 2], [2, 0]])
        assert geometry_crosses(x1, x2) and geometry_crosses(x2, x1)
        assert not geometry_touches(x1, x2)
        assert not geometry_overlaps(x1, x2)

    def test_t_touch(self):
        t1 = LineString([[0, 1], [2, 1]])
        t2 = LineString([[1, 1], [1, 5]])  # endpoint meets t1's interior
        assert geometry_touches(t1, t2) and geometry_touches(t2, t1)
        assert not geometry_crosses(t1, t2)

    def test_endpoint_touch(self):
        a = LineString([[0, 0], [1, 1]])
        b = LineString([[1, 1], [2, 0]])
        assert geometry_touches(a, b)
        assert not geometry_crosses(a, b)

    def test_collinear_partial_overlap(self):
        a = LineString([[-1, 2], [5, 2]])
        b = LineString([[3, 2], [7, 2]])
        assert geometry_overlaps(a, b) and geometry_overlaps(b, a)
        assert not geometry_crosses(a, b)
        assert not geometry_touches(a, b)

    def test_collinear_covered_not_overlap(self):
        a = LineString([[-1, 2], [5, 2]])
        inner = LineString([[1, 2], [3, 2]])
        assert not geometry_overlaps(a, inner)
        assert not geometry_touches(a, inner)  # interiors intersect


class TestLineArea:
    def test_cross_through(self):
        l = LineString([[-1, 2], [5, 2]])
        assert geometry_crosses(l, A) and geometry_crosses(A, l)

    def test_inside_not_crosses(self):
        l = LineString([[1, 1], [3, 3]])
        assert not geometry_crosses(l, A)
        assert not geometry_touches(l, A)

    def test_along_boundary_touches(self):
        l = LineString([[0, 0], [4, 0]])
        assert geometry_touches(l, A)
        assert not geometry_crosses(l, A)

    def test_ends_on_boundary_from_outside(self):
        l = LineString([[-2, 2], [0, 2]])  # outside, endpoint on boundary
        assert geometry_touches(l, A)
        assert not geometry_crosses(l, A)

    def test_enters_and_stops_inside(self):
        l = LineString([[-2, 2], [2, 2]])  # half out, half in
        assert geometry_crosses(l, A)


class TestPointRelations:
    def test_point_point_never_touches_or_crosses(self):
        assert not geometry_touches(Point(1, 1), Point(1, 1))
        assert not geometry_crosses(Point(1, 1), Point(1, 1))

    def test_point_on_area_boundary_touches(self):
        assert geometry_touches(Point(4, 2), A)
        assert geometry_touches(A, Point(4, 2))
        assert not geometry_touches(Point(2, 2), A)  # interior
        assert not geometry_touches(Point(9, 9), A)  # exterior

    def test_point_on_line_endpoint_touches(self):
        l = LineString([[0, 0], [2, 2]])
        assert geometry_touches(Point(0, 0), l)
        assert not geometry_touches(Point(1, 1), l)  # interior of the line

    def test_multipoint_crosses_area(self):
        mp = MultiPoint((Point(1, 1), Point(9, 9)))
        assert geometry_crosses(mp, A) and geometry_crosses(A, mp)
        inside_only = MultiPoint((Point(1, 1), Point(3, 3)))
        assert not geometry_crosses(inside_only, A)

    def test_multipoint_overlaps(self):
        a = MultiPoint((Point(0, 0), Point(1, 1)))
        b = MultiPoint((Point(1, 1), Point(2, 2)))
        assert geometry_overlaps(a, b)
        assert not geometry_overlaps(a, a)
        assert not geometry_overlaps(a, MultiPoint((Point(5, 5),)))


class TestRelate:
    def test_disjoint_pattern(self):
        assert geometry_relate(A, E) == "FFTFFTTTT"
        assert geometry_relate_matches(A, E, "FF*FF****")

    def test_named_masks(self):
        # overlaps (area/area JTS matrix 212101212)
        assert geometry_relate_matches(A, B, "T*T***T**")
        # touches
        assert geometry_relate_matches(A, C, "F***T****")
        # within / contains
        assert geometry_relate_matches(D, A, "T*F**F***")
        assert geometry_relate_matches(A, D, "T*****FF*")
        # equals
        assert geometry_relate_matches(A, sq(0, 0, 4, 4), "T*F**FFF*")
        assert not geometry_relate_matches(A, B, "T*F**FFF*")

    def test_pattern_digits_match_nonempty(self):
        assert geometry_relate_matches(A, B, "212101212".replace("2", "T")[:9])
        assert geometry_relate_matches(A, B, "2*2***2**")

    def test_bad_pattern(self):
        with pytest.raises(ValueError):
            geometry_relate_matches(A, B, "TTT")
        with pytest.raises(ValueError):
            geometry_relate_matches(A, B, "XXXXXXXXX")


def _random_poly(rng):
    """Random axis-aligned lattice rectangle (chunky: sampling-oracle safe)."""
    x0, y0 = rng.integers(0, 12, 2)
    w, h = rng.integers(1, 6, 2)
    return sq(float(x0), float(y0), float(x0 + w), float(y0 + h))


def _random_line(rng):
    pts = rng.integers(0, 12, (rng.integers(2, 5), 2)).astype(float)
    return LineString(pts)


class TestInvariantFuzz:
    def test_area_pairs_sampling_oracle(self):
        """Interiors are 2-D: a dense lattice-offset grid decides the
        area/area relations exactly for lattice rectangles."""
        rng = np.random.default_rng(42)
        for _ in range(120):
            a, b = _random_poly(rng), _random_poly(rng)
            # sample at quarter-lattice offsets: never on a lattice edge
            xs = np.arange(-0.5, 18.5, 0.25) + 0.125
            gx, gy = np.meshgrid(xs, xs)
            gx, gy = gx.ravel(), gy.ravel()

            def strict_in(p):
                from geomesa_tpu.geom.predicates import points_in_polygon

                return points_in_polygon(gx, gy, p.rings())

            ia, ib = strict_in(a), strict_in(b)
            ii = bool((ia & ib).any())  # interiors intersect
            a_out = bool((ia & ~ib).any())
            b_out = bool((ib & ~ia).any())
            inter = geometry_intersects(a, b)
            assert geometry_overlaps(a, b) == (ii and a_out and b_out)
            assert geometry_touches(a, b) == (inter and not ii)

    def test_symmetry_and_exclusivity(self):
        rng = np.random.default_rng(7)
        geoms = [_random_poly(rng) for _ in range(10)]
        geoms += [_random_line(rng) for _ in range(10)]
        geoms += [
            Point(float(x), float(y)) for x, y in rng.integers(0, 12, (5, 2))
        ]
        for a in geoms:
            for b in geoms:
                t = geometry_touches(a, b)
                c = geometry_crosses(a, b)
                o = geometry_overlaps(a, b)
                # symmetric relations
                assert t == geometry_touches(b, a)
                assert o == geometry_overlaps(b, a)
                assert c == geometry_crosses(b, a)
                # each implies intersects
                if t or c or o:
                    assert geometry_intersects(a, b)
                # mutually exclusive
                assert t + c + o <= 1, (a, b)
                # relate matrix consistency: closures intersect iff one of
                # the II / IB / BI / BB cells is non-empty
                m = geometry_relate(a, b)
                cells_meet = any(m[i] == "T" for i in (0, 1, 3, 4))
                assert geometry_intersects(a, b) == cells_meet, (a, b, m)

    def test_interior_point_always_strictly_inside(self):
        rng = np.random.default_rng(3)
        from geomesa_tpu.geom.predicates import _strict_in_area

        for _ in range(50):
            p = _random_poly(rng)
            x, y = interior_point(p)
            assert _strict_in_area(p, x, y)


class TestFilterWiring:
    SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"

    def _store(self):
        from geomesa_tpu.store import MemoryDataStore

        store = MemoryDataStore(partition_size=512)
        store.create_schema("rel", self.SPEC)
        rng = np.random.default_rng(5)
        n = 4000
        # lattice-ish coords so boundary contact actually occurs
        x = rng.integers(-8, 8, n) + rng.choice([0.0, 0.5], n)
        y = rng.integers(-8, 8, n) + rng.choice([0.0, 0.5], n)
        store.write(
            "rel",
            {
                "name": rng.choice(["a", "b"], n),
                "dtg": rng.integers(1_577_836_800_000, 1_580_000_000_000, n),
                "geom": np.stack([x, y], axis=1),
            },
            fids=np.arange(n),
        )
        return store, x, y

    def test_touches_ecql_matches_oracle(self):
        store, x, y = self._store()
        r = store.query("rel", "TOUCHES(geom, POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0)))")
        got = set(r.batch.fids.tolist())
        expect = {
            i
            for i in range(len(x))
            if geometry_touches(Point(x[i], y[i]), sq(0, 0, 4, 4))
        }
        assert got == expect and len(expect) > 0

    def test_crosses_ecql_multipoint_semantics(self):
        # point data: single points never cross -> empty result
        store, x, y = self._store()
        r = store.query("rel", "CROSSES(geom, POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0)))")
        assert len(r) == 0

    def test_relate_ecql(self):
        store, x, y = self._store()
        # interior-in-interior pattern == within for points
        r = store.query(
            "rel", "RELATE(geom, POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0)), 'T*F**F***')"
        )
        got = set(r.batch.fids.tolist())
        expect = {
            i
            for i in range(len(x))
            if geometry_relate_matches(
                Point(x[i], y[i]), sq(0, 0, 4, 4), "T*F**F***"
            )
        }
        assert got == expect and len(expect) > 0

    def test_overlaps_ecql_parses(self):
        from geomesa_tpu.filter.ecql import parse_ecql

        f = parse_ecql("OVERLAPS(geom, POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0)))")
        assert f.op == "overlaps"
        f = parse_ecql("EQUALS(geom, POINT (1 2))")
        assert f.op == "equals"


class TestSqlFunctions:
    def test_st_relations(self):
        from geomesa_tpu.sql.functions import (
            st_crosses,
            st_overlaps,
            st_relate,
            st_relateBool,
            st_touches,
        )

        assert st_touches(A, C) is True or st_touches(A, C) == True  # noqa: E712
        assert bool(st_overlaps(A, B))
        l = LineString([[-1, 2], [5, 2]])
        assert bool(st_crosses(l, A))
        assert st_relate(A, E) == "FFTFFTTTT"
        assert bool(st_relateBool(A, E, "FF*FF****"))
        # column broadcast: point column vs scalar polygon
        pts = np.array([[4.0, 2.0], [2.0, 2.0], [9.0, 9.0]])
        got = st_touches(pts, A)
        np.testing.assert_array_equal(got, [True, False, False])

    def test_registry(self):
        from geomesa_tpu.sql.functions import FUNCTIONS

        for name in ("st_crosses", "st_touches", "st_overlaps", "st_relate", "st_relateBool"):
            assert name in FUNCTIONS


class TestReviewRegressions:
    def test_equals_detects_collinear_gap(self):
        """A MultiLineString with a gap is NOT equal to the full segment:
        coverage sampling must refine at the covering line's endpoints."""
        from geomesa_tpu.geom.base import MultiLineString

        gapped = MultiLineString(
            (
                LineString([[0, 0], [0.4, 0]]),
                LineString([[0.6, 0], [2, 0]]),
            )
        )
        full = LineString([[0, 0], [2, 0]])
        assert not geometry_relate_matches(gapped, full, "T*F**FFF*")
        assert geometry_relate(gapped, full)[6] == "T"  # EI: gap in b's... a's exterior meets b's interior
        # and a genuinely equal pair still matches
        assert geometry_relate_matches(full, LineString([[0, 0], [2, 0]]), "T*F**FFF*")

    def test_spatial_words_as_column_names(self):
        from geomesa_tpu.filter import ast
        from geomesa_tpu.filter.ecql import parse_ecql

        f = parse_ecql("overlaps > 3")
        assert isinstance(f, ast.Compare) and f.attr == "overlaps"
        f = parse_ecql("EQUALS = 'x'")
        assert isinstance(f, ast.Compare)

    def test_bad_relate_pattern_fails_at_parse(self):
        from geomesa_tpu.filter.ecql import parse_ecql

        with pytest.raises(ValueError, match="DE-9IM"):
            parse_ecql("RELATE(geom, POINT (1 2), 'T*T')")
