"""Fault-tolerant serving (ISSUE 7): fault taxonomy, circuit breakers,
launch watchdog, staging-OOM recovery, the degradation ladder,
healthz/readyz + draining shutdown, adaptive Retry-After, and the chaos
contract — every admitted request gets EXACTLY ONE response (success,
degraded, or typed error; never a hang or a bare 500)."""

import json
import threading
import time
import urllib.error
import urllib.request
from urllib.parse import quote

import numpy as np
import pytest

from geomesa_tpu import failpoints, metrics, resilience
from geomesa_tpu.conf import prop_override
from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.sched import QueryScheduler, SchedConfig

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


@pytest.fixture(autouse=True)
def _fresh_breakers():
    resilience.reset()
    yield
    resilience.reset()


def _mem_store(n=2000, seed=17, audit=None):
    from geomesa_tpu.store.memory import MemoryDataStore

    ds = MemoryDataStore(audit_writer=audit)
    ds.create_schema("gdelt", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "gdelt",
        {
            "name": rng.choice(["a", "b"], n),
            "dtg": t0 + rng.integers(0, 10**8, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return ds


def _fs_store(root, n=600, partition_size=128, audit=False):
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(
        str(root), partition_size=partition_size, audit=audit
    )
    ds.create_schema("t", "val:Int,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(3)
    ds.write("t", {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(0, 10**9, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }, fids=np.arange(n))
    ds.flush("t")
    return ds


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, dict(r.headers), r.read()


def _get_err(url):
    try:
        _get(url)
        return None
    except urllib.error.HTTPError as e:
        return e


# -- fault taxonomy ---------------------------------------------------------


def test_classify_taxonomy():
    from geomesa_tpu.sched.scheduler import DeadlineExpired, RejectedError
    from geomesa_tpu.store.fs import PartitionCorruptError

    C = resilience.classify
    assert C(RejectedError(1.0)) == resilience.FATAL
    assert C(DeadlineExpired()) == resilience.FATAL
    assert C(ValueError("bad cql")) == resilience.FATAL
    assert C(KeyError("nosuch")) == resilience.FATAL
    assert C(FileNotFoundError("gone")) == resilience.FATAL
    assert C(OSError("flaky disk")) == resilience.RETRYABLE
    assert C(failpoints.FailpointError("x")) == resilience.RETRYABLE
    assert C(MemoryError()) == resilience.DEGRADABLE
    assert (
        C(RuntimeError("RESOURCE_EXHAUSTED: out of memory while ..."))
        == resilience.DEGRADABLE
    )
    assert C(resilience.LaunchStuckError("stuck")) == resilience.DEGRADABLE
    assert (
        C(resilience.PartitionUnavailableError("t", 3, "io"))
        == resilience.DEGRADABLE
    )
    assert C(PartitionCorruptError("bad crc")) == resilience.DEGRADABLE
    assert C(RuntimeError("anything else")) == resilience.FATAL


def test_backoff_sleeps_jitter_and_cumulative_cap():
    # jitter: each delay is base*2^k scaled into [0.5, 1.5)
    for _ in range(20):
        ds = list(resilience.backoff_sleeps(3, 100, 0))
        assert len(ds) == 3
        for k, d in enumerate(ds):
            lo, hi = 0.05 * (1 << k), 0.15 * (1 << k)
            assert lo <= d < hi
    # cumulative cap: total sleep never exceeds the budget
    for _ in range(20):
        ds = list(resilience.backoff_sleeps(10, 50, 120))
        assert sum(ds) <= 0.120 + 1e-9
        assert len(ds) < 10  # the cap stopped the schedule early
    # base 0 = immediate retries: the retry COUNT must survive the cap
    # (regression: zero-delay sleeps must not read as budget-exhausted)
    assert list(resilience.backoff_sleeps(3, 0.0, 1000.0)) == [0, 0, 0]


def test_retry_call_retries_then_raises():
    calls = []

    def flaky():
        calls.append(1)
        raise OSError("transient")

    with prop_override("resilience.retries", 3), \
            prop_override("resilience.backoff.ms", 1.0):
        r0 = metrics.resilience_retries.value(domain="device")
        with pytest.raises(OSError):
            resilience.retry_call(flaky, domain="device")
        assert len(calls) == 4  # first attempt + 3 retries
        assert metrics.resilience_retries.value(domain="device") - r0 == 3

    # FATAL faults never retry
    calls.clear()

    def bad():
        calls.append(1)
        raise ValueError("bad request")

    with pytest.raises(ValueError):
        resilience.retry_call(bad)
    assert len(calls) == 1


# -- circuit breakers -------------------------------------------------------


def test_breaker_state_machine():
    b = resilience.CircuitBreaker(
        "t", domain="device", failures=3, cooldown_s=0.1
    )
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"
    b.record_success()  # success resets the consecutive count
    b.record_failure()
    b.record_failure()
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()
    time.sleep(0.12)
    assert b.allow()  # the half-open probe
    assert b.state == "half-open"
    assert not b.allow()  # only ONE probe at a time
    b.record_failure()  # failed probe: re-open
    assert b.state == "open" and not b.allow()
    time.sleep(0.12)
    assert b.allow()
    b.record_success()
    assert b.state == "closed" and b.allow()
    snap = b.snapshot()
    assert snap["opens"] == 2 and snap["state"] == "closed"


def test_breaker_disabled_by_master_switch():
    b = resilience.CircuitBreaker("t", domain="device", failures=1,
                                  cooldown_s=60)
    b.record_failure()
    assert b.state == "open"
    with prop_override("resilience.enabled", False):
        assert b.allow()  # disabled: never gates


def test_partition_breakers_are_scoped():
    a = resilience.partition_breaker("rootA:t", 0)
    b = resilience.partition_breaker("rootB:t", 0)
    assert a is not b
    assert resilience.partition_breaker("rootA:t", 0) is a
    for _ in range(a.failures):
        a.record_failure()
    assert a.state == "open" and b.state == "closed"
    assert resilience.open_partition_breakers() == 1
    assert resilience.snapshot()["partition_open"] == 1


# -- scheduler: watchdog, worker crash, adaptive Retry-After ----------------


def test_watchdog_fails_stuck_launch_and_replaces_worker():
    unwedge = threading.Event()
    sched = QueryScheduler(SchedConfig(
        max_queue=8, max_inflight=1, default_deadline_ms=None
    ))
    try:
        with prop_override("resilience.launch.timeout.s", 0.3):
            t0 = time.monotonic()
            req = sched.submit(fn=lambda: unwedge.wait(10), device=True)
            with pytest.raises(resilience.LaunchStuckError):
                sched.wait(req)
            # failed promptly (not after the 10s wedge)
            assert time.monotonic() - t0 < 5.0
            # the wedged worker was replaced: the scheduler still serves
            assert sched.run(fn=lambda: 42) == 42
            snap = sched.snapshot()
            assert snap["watchdog_timeouts"] == 1
            assert snap["running"] == 0  # the abandoned group retired
            # the abandoned entry was POPPED, not just flagged: the
            # wedged worker never returns to retire it, and a leaked
            # entry would pin the group (closures, results) forever
            # while the watchdog rescans it every tick
            with sched._cv:
                assert not sched._inflight
            # the device breaker recorded the stuck launch
            assert (
                resilience.device_breaker().snapshot()[
                    "consecutive_failures"
                ] >= 1
            )
    finally:
        unwedge.set()
        sched.close(timeout=2.0)


def test_watchdog_exactly_once_when_stuck_fn_returns():
    """The abandoned worker's late completion must NOT overwrite the
    watchdog's answer (idempotent _finish) and the late worker exits."""
    release = threading.Event()
    sched = QueryScheduler(SchedConfig(
        max_queue=8, max_inflight=1, default_deadline_ms=None
    ))
    try:
        with prop_override("resilience.launch.timeout.s", 0.2):
            req = sched.submit(
                fn=lambda: release.wait(10) or "late", device=True
            )
            with pytest.raises(resilience.LaunchStuckError):
                sched.wait(req)
            release.set()  # the wedged fn now completes
            time.sleep(0.3)
            # the first (watchdog) completion stands
            assert isinstance(req.error, resilience.LaunchStuckError)
            assert req.result is None
            assert sched.run(fn=lambda: 7) == 7
    finally:
        release.set()
        sched.close(timeout=2.0)


def test_watchdog_exempts_host_groups():
    """A long-but-progressing HOST scan (fn work not flagged device)
    must not be failed as a stuck launch nor charged to the DEVICE
    breaker — only its deadline and the io.* retry budget bound it."""
    sched = QueryScheduler(SchedConfig(
        max_queue=8, max_inflight=1, default_deadline_ms=None
    ))
    try:
        with prop_override("resilience.launch.timeout.s", 0.2):
            c0 = resilience.device_breaker().snapshot()[
                "consecutive_failures"
            ]
            # runs 3x past the launch timeout, then finishes normally
            assert sched.run(fn=lambda: time.sleep(0.6) or "done") == "done"
            snap = sched.snapshot()
            assert snap["watchdog_timeouts"] == 0
            assert (
                resilience.device_breaker().snapshot()[
                    "consecutive_failures"
                ] == c0
            )
    finally:
        sched.close(timeout=2.0)


def test_watchdog_stall_clock_restarts_on_rider_progress():
    """A fusion-declined group executed serially makes progress launch
    by launch: the watchdog must time the CURRENT launch's stall, not
    the group's cumulative wall-clock."""
    sched = QueryScheduler(SchedConfig(
        max_queue=16, max_inflight=1, fusion_window_ms=200,
        max_fusion=8, default_deadline_ms=None,
    ))

    class _Serial:
        """Fusable by key, but execute_group always declines (no
        DeviceIndex) so the group runs serially via run_serial."""

        fusable = True
        key = ("k",)

        def run_serial(self):
            time.sleep(0.15)
            return "ok"

    try:
        with prop_override("resilience.launch.timeout.s", 0.3):
            # 4 riders x 0.15s = 0.6s group wall-clock, 2x the launch
            # timeout — but each launch completes well within it
            reqs = [sched.submit(fuse=_Serial()) for _ in range(4)]
            assert [sched.wait(r) for r in reqs] == ["ok"] * 4
            assert sched.snapshot()["watchdog_timeouts"] == 0
    finally:
        sched.close(timeout=2.0)


def test_fatal_probe_releases_the_slot():
    """A half-open probe that dies on a FATAL fault (bad request) says
    nothing about device health: the slot must free, same as a shed
    probe (tested below via release_probe directly)."""
    with prop_override("resilience.breaker.failures", 1), \
            prop_override("resilience.breaker.cooldown.s", 30.0):
        br = resilience.CircuitBreaker("fatal-probe-test", "device")
        br.record_failure()
        br._opened_at -= 31.0  # cooldown elapsed
        assert br.allow() and br.state == "half-open"
        br.release_probe()  # what _degradable does on a FATAL probe
        assert br.allow()  # fresh probe without another cooldown


def test_partition_breaker_registry_hard_bound():
    """With every keyed breaker open (store-wide outage) the registry
    must still evict — the bound is hard, not best-effort."""
    from geomesa_tpu.resilience import _PARTITION_BREAKERS_MAX, _breakers

    with prop_override("resilience.breaker.failures", 1):
        for i in range(_PARTITION_BREAKERS_MAX + 50):
            resilience.partition_breaker("hb:t", i).record_failure()
        keyed = [k for k in _breakers if isinstance(k, tuple)]
        assert len(keyed) <= _PARTITION_BREAKERS_MAX
        # the newest breakers survived; the oldest were evicted
        assert ("partition", "hb:t", _PARTITION_BREAKERS_MAX + 49) in _breakers


def test_shed_half_open_probe_frees_the_slot():
    """A probe request shed by flow control (429/504) carries no health
    signal: the slot must free immediately, not after another cooldown,
    or a saturated queue pins the breaker half-open indefinitely."""
    with prop_override("resilience.breaker.failures", 1), \
            prop_override("resilience.breaker.cooldown.s", 0.05):
        br = resilience.CircuitBreaker("probe-release-test", "device")
        br.record_failure()
        assert br.state == "open"
        time.sleep(0.06)
        assert br.allow()  # the half-open probe slot
        assert br.state == "half-open"
        assert not br.allow()  # one probe in flight at a time
        br.release_probe()  # the probe got shed: no outcome to report
        assert br.allow()  # a fresh probe, without waiting out a cooldown
        br.record_success()
        assert br.state == "closed"
        br.release_probe()  # closed: a no-op
        assert br.state == "closed"


def test_sched_worker_crash_fails_typed_and_keeps_serving():
    sched = QueryScheduler(SchedConfig(
        max_queue=8, max_inflight=1, default_deadline_ms=None
    ))
    try:
        with failpoints.failpoint_override("fail.sched.worker", "raise:1"):
            with pytest.raises(failpoints.FailpointError):
                sched.run(fn=lambda: 1)
            assert sched.run(fn=lambda: 2) == 2  # same worker, alive
        assert sched.snapshot()["worker_failures"] == 1
    finally:
        sched.close(timeout=2.0)


def test_exactly_once_under_worker_chaos():
    """Admitted requests each complete exactly once — success or typed
    error — under injected worker crashes."""
    sched = QueryScheduler(SchedConfig(
        max_queue=64, max_inflight=2, default_deadline_ms=None
    ))
    try:
        with failpoints.failpoint_override("fail.sched.worker", "raise:5"):
            reqs = [sched.submit(fn=lambda i=i: i) for i in range(20)]
            ok, failed = 0, 0
            for i, r in enumerate(reqs):
                try:
                    assert sched.wait(r) == i
                    ok += 1
                except failpoints.FailpointError:
                    failed += 1
            assert ok + failed == 20
            assert failed >= 1 and ok >= 1
    finally:
        sched.close(timeout=2.0)


def test_retry_after_computed_and_jittered():
    from geomesa_tpu.sched import RejectedError

    block = threading.Event()
    sched = QueryScheduler(SchedConfig(
        max_queue=1, max_inflight=1, default_deadline_ms=None,
        retry_after_s=2.0,
    ))
    try:
        # a few completions seed the service-time EWMA
        for _ in range(3):
            sched.run(fn=lambda: time.sleep(0.01))
        held = sched.submit(fn=lambda: block.wait(5))
        time.sleep(0.05)  # claimed; the single queue slot is free
        queued = sched.submit(fn=lambda: None)
        values = []
        for _ in range(8):
            with pytest.raises(RejectedError) as ei:
                sched.submit(fn=lambda: None)
            values.append(ei.value.retry_after_s)
        assert all(0.05 <= v <= 30.0 for v in values)
        # jitter: a fleet must not all get the same comeback time
        assert len({round(v, 6) for v in values}) > 1
        assert sched.snapshot()["retry_after_estimate_s"] > 0
        block.set()
        sched.wait(held)
        sched.wait(queued)
    finally:
        block.set()
        sched.close(timeout=2.0)


# -- staging-OOM recovery ---------------------------------------------------


def test_stage_oom_halves_and_retries_with_parity():
    ds = _mem_store(n=512)
    cql = "BBOX(geom, -10, -10, 10, 10)"
    expect = sorted(int(f) for f in ds.query("gdelt", cql).batch.fids)
    o0 = metrics.resilience_oom_recoveries.value()
    with failpoints.failpoint_override("fail.stage.oom", "raise:1"):
        got = sorted(int(f) for f in ds.query("gdelt", cql).batch.fids)
    assert got == expect
    assert metrics.resilience_oom_recoveries.value() - o0 >= 1


def test_device_launch_failure_degrades_to_host_mask():
    ds = _mem_store(n=256)
    cql = "BBOX(geom, -10, -10, 10, 10)"
    expect = sorted(int(f) for f in ds.query("gdelt", cql).batch.fids)
    with failpoints.failpoint_override("fail.device.launch", "raise"), \
            resilience.collect_degraded() as reasons:
        got = sorted(int(f) for f in ds.query("gdelt", cql).batch.fids)
    assert got == expect  # host mask is the exact same predicate
    assert "device-launch-failed" in reasons
    # strict mode: the same fault propagates
    with failpoints.failpoint_override("fail.device.launch", "raise"), \
            prop_override("resilience.degrade", False):
        with pytest.raises(failpoints.FailpointError):
            ds.query("gdelt", cql)


def test_streamed_scan_degrade_reason_matches_fault_domain():
    """The streamed scan's degradation rung must stamp the reason of
    the DOMAIN that failed: a corrupt partition or exhausted disk
    retries labeled ``device-launch-failed`` would send the operator
    to the accelerator for a disk fault (and vice versa)."""
    from geomesa_tpu.store.fs import PartitionCorruptError
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    cases = [
        (failpoints.FailpointError("x", name="fail.device.launch"),
         "device-launch-failed"),
        (failpoints.FailpointError("x", name="fail.stage.oom"),
         "device-oom"),
        (MemoryError("staging"), "device-oom"),
        (OSError("disk gave up"), "partition-unavailable"),
        (failpoints.FailpointError("x", name="fail.read.io"),
         "partition-unavailable"),
        (PartitionCorruptError("pid 3"), "partition-unavailable"),
        (resilience.PartitionUnavailableError("t", 3, "retries exhausted"),
         "partition-unavailable"),
    ]
    for exc, want in cases:
        with resilience.collect_degraded() as reasons:
            StreamedDeviceScan._degrade_or_raise(exc)
        assert reasons == [want], (type(exc).__name__, reasons, want)


# -- prefetch backoff cap / slow-read injection -----------------------------


def test_prefetch_backoff_cumulative_cap_bounds_wall_clock():
    from geomesa_tpu.store.prefetch import prefetch_map

    def always_fails(i):
        raise OSError("flapping")

    with prop_override("io.retries", 50), \
            prop_override("io.backoff.ms", 20.0), \
            prop_override("io.backoff.cap.ms", 60.0):
        t0 = time.monotonic()
        with pytest.raises(OSError):
            list(prefetch_map(always_fails, [1], config=0))
        elapsed = time.monotonic() - t0
    # 50 un-capped doubling retries from 20ms would sleep for days;
    # the cumulative cap bounds it to ~60ms of sleep
    assert elapsed < 2.0


def test_slow_read_failpoint_injects_latency_not_errors(tmp_path):
    ds = _fs_store(tmp_path / "s")
    expect = sorted(int(f) for f in ds.query("t").batch.fids)
    with failpoints.failpoint_override("fail.read.slow", "sleep:20"):
        got = sorted(int(f) for f in ds.query("t").batch.fids)
    assert got == expect


# -- partition-domain degradation ------------------------------------------


def _corrupt_file(path):
    with open(path, "r+b") as fh:
        fh.seek(20)
        fh.write(b"\xde\xad\xbe\xef")


def test_partition_breaker_short_circuits_repeat_failures(tmp_path):
    ds = _fs_store(tmp_path / "s")
    st = ds._types["t"]
    assert len(st.partitions) >= 2
    victim = st.partitions[0]
    all_fids = sorted(int(f) for f in ds.query("t").batch.fids)
    victim_fids = {int(f) for f in ds._read_partition("t", victim).fids}
    _corrupt_file(ds._part_path("t", victim))
    from geomesa_tpu.store.fs import FileSystemDataStore

    with prop_override("store.verify", "always"), \
            prop_override("resilience.breaker.failures", 1), \
            prop_override("resilience.breaker.cooldown.s", 30.0):
        fresh = FileSystemDataStore(str(tmp_path / "s"), partition_size=128)
        expect = sorted(set(all_fids) - victim_fids)
        with resilience.collect_degraded() as r1:
            got1 = sorted(int(f) for f in fresh.query("t").batch.fids)
        assert got1 == expect and "partition-unavailable" in r1
        # the victim's breaker opened on the first failure: the second
        # query degrades WITHOUT touching the file again
        br = resilience.partition_breaker(f"{fresh.root}:t", victim.pid)
        assert br.state == "open"
        c0 = metrics.store_checksum_failures.value()
        with resilience.collect_degraded() as r2:
            got2 = sorted(int(f) for f in fresh.query("t").batch.fids)
        assert got2 == expect and "partition-unavailable" in r2
        assert metrics.store_checksum_failures.value() == c0  # no re-read


def test_query_without_collector_raises_instead_of_silent_partial(tmp_path):
    """Outside a serving request there is no X-Degraded header or audit
    event to stamp: a library/CLI caller of store.query() must get the
    typed partition-scoped error, never a silently-partial batch."""
    ds = _fs_store(tmp_path / "s")
    st = ds._types["t"]
    victim = st.partitions[0]
    _corrupt_file(ds._part_path("t", victim))
    from geomesa_tpu.store.fs import FileSystemDataStore

    with prop_override("store.verify", "always"):
        fresh = FileSystemDataStore(str(tmp_path / "s"), partition_size=128)
        assert resilience.capture_degraded() is None
        with pytest.raises(resilience.PartitionUnavailableError) as ei:
            fresh.query("t")
        assert ei.value.pid == victim.pid


def test_query_partitions_surfaces_partition_scoped_error(tmp_path):
    ds = _fs_store(tmp_path / "s")
    st = ds._types["t"]
    victim = st.partitions[-1]
    _corrupt_file(ds._part_path("t", victim))
    from geomesa_tpu.store.fs import FileSystemDataStore

    with prop_override("store.verify", "always"):
        fresh = FileSystemDataStore(str(tmp_path / "s"), partition_size=128)
        # bulk/export consumers get a TYPED error naming the partition,
        # never a silent partial result
        with pytest.raises(resilience.PartitionUnavailableError) as ei:
            for _ in fresh.query_partitions("t"):
                pass
        assert ei.value.pid == victim.pid


# -- recovery sweep racing live serving (satellite) -------------------------


def test_recover_races_live_queries_never_half_published(tmp_path):
    """A recover() sweep racing in-flight query/query_partitions must
    only ever expose FULLY published generations: every successful
    observation equals the row set of some completed flush (a prefix of
    the writes), never a mix. Runs under the suite-wide lockcheck."""
    ds = _fs_store(tmp_path / "s", n=200)
    base = {int(f) for f in ds.query("t").batch.fids}
    rounds = 4
    batch_n = 60
    # every legal observation, known A PRIORI (fids are deterministic):
    # the base set plus a prefix of the flushed batches — a reader must
    # never see anything else, no matter how the sweep interleaves
    valid = [
        base | set(range(10_000, 10_000 + k * batch_n))
        for k in range(rounds + 1)
    ]
    stop = threading.Event()
    errors: list = []
    observations: list = []
    obs_lock = threading.Lock()

    def writer():
        try:
            fid0 = 10_000
            rng = np.random.default_rng(9)
            for i in range(rounds):
                n = batch_n
                ds.write("t", {
                    "val": rng.integers(0, 100, n),
                    "dtg": rng.integers(0, 10**9, n),
                    "geom": np.stack([
                        rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)
                    ], axis=1),
                }, fids=np.arange(fid0, fid0 + n))
                fid0 += n
                ds.flush("t")
                ds.recover("t")
                time.sleep(0.01)  # give the readers scan windows
        except Exception as e:  # pragma: no cover - fails the test below
            errors.append(e)
        finally:
            stop.set()

    def reader(use_partitions: bool):
        while True:
            done = stop.is_set()  # observe at least once after the end
            try:
                if use_partitions:
                    got: set = set()
                    for b in ds.query_partitions("t"):
                        got |= {int(f) for f in b.fids}
                else:
                    got = {int(f) for f in ds.query("t").batch.fids}
            except (FileNotFoundError,
                    resilience.PartitionUnavailableError):
                if done:
                    break
                continue  # a GC'd stale generation mid-iteration: retry
            with obs_lock:
                observations.append(got)
            if done:
                break

    threads = [
        threading.Thread(target=writer),
        threading.Thread(target=reader, args=(False,)),
        threading.Thread(target=reader, args=(True,)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert observations
    for got in observations:
        assert got in valid, (
            f"observed a row set matching NO published generation "
            f"(sizes: got={len(got)}, valid={[len(v) for v in valid]})"
        )
    # the final state is the fully written one
    assert {int(f) for f in ds.query("t").batch.fids} == valid[-1]


# -- server end-to-end: ladder, headers, health, drain, audit ---------------


@pytest.fixture()
def resident_server(tmp_path):
    from geomesa_tpu.server import serve_background

    ds = _fs_store(tmp_path / "srv", n=400, audit=True)
    server, _ = serve_background(
        ds, resident=True,
        sched=SchedConfig(max_queue=32, max_inflight=1,
                          default_deadline_ms=None),
    )
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", ds, server
    server.shutdown()
    server.scheduler.shutdown(timeout=2.0)


def test_server_device_failure_degrades_breaker_recovers(resident_server):
    url, ds, server = resident_server
    cql = quote("BBOX(geom, -90, -45, 90, 45)")
    target = f"{url}/count/t?cql={cql}"
    status, hdrs, body = _get(target)  # warm: stage + count
    expect = json.loads(body)["count"]
    assert status == 200 and "X-Degraded" not in hdrs
    with prop_override("resilience.retries", 0), \
            prop_override("resilience.breaker.failures", 1), \
            prop_override("resilience.breaker.cooldown.s", 0.2):
        with failpoints.failpoint_override("fail.device.launch", "raise"):
            status, hdrs, body = _get(target)
            assert status == 200
            assert json.loads(body)["count"] == expect
            assert "device-launch-failed" in hdrs.get("X-Degraded", "")
            assert hdrs.get("X-Request-Id")
            # breaker open now: the next request skips the device rung
            status, hdrs, body = _get(target)
            assert json.loads(body)["count"] == expect
            assert "device-breaker-open" in hdrs.get("X-Degraded", "")
        # fault cleared + cooldown over: the half-open probe recovers
        time.sleep(0.25)
        status, hdrs, body = _get(target)
        assert status == 200 and json.loads(body)["count"] == expect
        assert "X-Degraded" not in hdrs
        assert resilience.device_breaker().state == "closed"
    # degraded answers were audited with their reasons
    ds.audit_writer.flush()
    events = ds.audit_writer.read_events()
    assert any("device-launch-failed" in e.degraded for e in events)


def test_server_features_degrade_parity(resident_server):
    url, ds, server = resident_server
    cql = quote("BBOX(geom, -90, -45, 90, 45)")
    target = f"{url}/features/t?cql={cql}"
    _, _, body = _get(target)
    expect = {
        f["id"] for f in json.loads(body)["features"]
    }
    with prop_override("resilience.retries", 0), \
            failpoints.failpoint_override("fail.device.launch", "raise"):
        status, hdrs, body = _get(target)
    assert status == 200
    got = {f["id"] for f in json.loads(body)["features"]}
    assert got == expect
    assert "device-launch-failed" in hdrs.get("X-Degraded", "")


def test_server_healthz_readyz_and_draining(resident_server):
    url, ds, server = resident_server
    status, _, body = _get(f"{url}/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, _, body = _get(f"{url}/readyz")
    doc = json.loads(body)
    assert status == 200 and doc["ready"] and "breakers" in doc
    assert "device" in doc["breakers"]
    # an open breaker shows as a degraded domain; still READY (200)
    for _ in range(resilience.device_breaker().failures):
        resilience.device_breaker().record_failure()
    status, _, body = _get(f"{url}/readyz")
    doc = json.loads(body)
    assert status == 200 and "device" in doc["degraded_domains"]
    resilience.device_breaker().record_success()
    # draining flips readiness + admission; liveness and monitoring
    # stay up (failing /healthz would get the instance KILLED mid-drain
    # instead of de-routed — readiness is the traffic-removal signal)
    server.draining.set()
    try:
        status, _, body = _get(f"{url}/healthz")
        assert status == 200
        assert json.loads(body)["status"] == "draining"
        e = _get_err(f"{url}/readyz")
        assert e is not None and e.code == 503
        assert json.loads(e.read())["draining"] is True
        e = _get_err(f"{url}/count/t")
        assert e is not None and e.code == 503
        assert e.headers.get("Retry-After")
        status, _, _ = _get(f"{url}/metrics")  # scrapes keep working
        assert status == 200
    finally:
        server.draining.clear()
    status, _, _ = _get(f"{url}/count/t")
    assert status == 200


def test_server_error_responses_carry_request_id(resident_server):
    url, _, _ = resident_server
    e = _get_err(f"{url}/features/nosuchtype")
    assert e is not None and e.code == 404
    assert e.headers.get("X-Request-Id")
    # an inbound id echoes back even on errors
    req = urllib.request.Request(
        f"{url}/features/nosuchtype",
        headers={"X-Request-Id": "client-id-123"},
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e2:
        assert e2.headers.get("X-Request-Id") == "client-id-123"


def test_server_shed_and_expired_requests_audited(tmp_path):
    from geomesa_tpu.server import serve_background

    ds = _fs_store(tmp_path / "srv2", n=200, audit=True)
    server, _ = serve_background(
        ds, resident=True,
        sched=SchedConfig(max_queue=1, max_inflight=1,
                          default_deadline_ms=None, fusion_window_ms=0.0),
    )
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    try:
        _get(f"{url}/count/t")  # warm/stage
        # wedge the single worker directly, so HTTP requests pile into
        # the 1-slot queue: the first queued expires its deadline (504),
        # the rest are shed (429)
        block = threading.Event()
        held = server.scheduler.submit(fn=lambda: block.wait(10))
        time.sleep(0.05)  # claimed: the queue slot is free
        codes: list = []
        lock = threading.Lock()

        def fire(path):
            e = _get_err(f"{url}{path}")
            with lock:
                codes.append(e.code if e else 200)

        t504 = threading.Thread(
            target=fire, args=("/count/t?deadlineMs=60&tenant=dl",)
        )
        t504.start()
        time.sleep(0.02)  # let it take the queue slot
        t429s = [
            threading.Thread(
                target=fire, args=(f"/count/t?tenant=w{i}",)
            )
            for i in range(4)
        ]
        for t in t429s:
            t.start()
        for t in [t504] + t429s:
            t.join(timeout=30)
        block.set()
        server.scheduler.wait(held)
        assert codes and all(c in (200, 429, 504) for c in codes)
        assert 429 in codes or 504 in codes
    finally:
        server.shutdown()
        server.scheduler.shutdown(timeout=2.0)
    ds.audit_writer.flush()
    events = ds.audit_writer.read_events()
    outcomes = {e.outcome for e in events}
    if 429 in codes:
        assert "shed" in outcomes
    if 504 in codes:
        assert "deadline-expired" in outcomes
    # shed/expired audit events carry a trace id for correlation
    assert all(
        e.trace_id for e in events if e.outcome in ("shed",
                                                    "deadline-expired")
    )


def test_server_resident_staging_failure_degrades_to_store(tmp_path):
    """A resident cache that cannot stage (cache domain) falls to the
    store path: correct answers, stamped, cache breaker opens."""
    from geomesa_tpu.server import serve_background

    ds = _fs_store(tmp_path / "srv3", n=200)
    server, _ = serve_background(ds, resident=True)
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    expect = len(ds.query("t").batch)
    try:
        import geomesa_tpu.server as srv

        handler = server.RequestHandlerClass

        def boom(self, type_name):
            raise RuntimeError("RESOURCE_EXHAUSTED: staging OOM")

        orig = srv._Handler._build_locked
        handler._build_locked = boom
        try:
            with prop_override("resilience.breaker.failures", 1), \
                    prop_override("resilience.breaker.cooldown.s", 30.0):
                status, hdrs, body = _get(f"{url}/count/t")
                assert status == 200
                assert json.loads(body)["count"] == expect
                assert "resident-unavailable" in hdrs.get("X-Degraded", "")
                # breaker open: next request skips the staging attempt
                status, hdrs, body = _get(f"{url}/count/t")
                assert json.loads(body)["count"] == expect
                assert "cache-breaker-open" in hdrs.get("X-Degraded", "")
        finally:
            handler._build_locked = orig
    finally:
        server.shutdown()


def test_brownout_gate_requires_aggregate_shape(tmp_path):
    """Brownout may only flip to the pre-aggregate rung for filters the
    chunk stats can actually answer (bbox+time conjunctions): anything
    else would FULL-row-scan on the handler thread, outside scheduler
    admission, amplifying the very overload brownout relieves."""
    from types import SimpleNamespace

    from geomesa_tpu.server import _Handler

    ds = _fs_store(tmp_path / "gate", n=200)
    fake = SimpleNamespace(store=ds)
    ok = _Handler._agg_shaped
    assert ok(fake, "t", "INCLUDE")
    assert ok(fake, "t", "BBOX(geom, -10, 35, 30, 60)")
    assert ok(fake, "t", (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 1970-01-01T00:00:00Z/1970-01-02T00:00:00Z"
    ))
    # attribute predicates row-scan inside store.count/density: not
    # brownout-eligible (they take the normal metered path instead)
    assert not ok(fake, "t", "val > 10")
    assert not ok(fake, "t", "BBOX(geom, -10, 35, 30, 60) OR val = 1")
    assert not ok(fake, "nosuch", "INCLUDE")  # unknown type: never eligible
    assert not ok(fake, "t", "NOT VALID CQL ((")
    # a store WITHOUT chunk statistics (v1/legacy/memory) has no
    # pre-aggregates: the 'brownout' answer would quietly row-scan
    assert not ok(SimpleNamespace(store=object()), "t", "INCLUDE")
    nostats = SimpleNamespace(store=SimpleNamespace(
        has_chunk_stats=lambda t: False, get_schema=ds.get_schema
    ))
    assert not ok(nostats, "t", "INCLUDE")


def test_server_brownout_serves_pushdown_density(tmp_path):
    """Scheduler saturation flips aggregate answers to the chunk
    pre-aggregates (PR 6): mass stays within the pushdown parity
    bounds, the response is stamped, and nothing queues behind the
    saturated device lane."""
    from geomesa_tpu.process import density as density_proc
    from geomesa_tpu.geom import Envelope
    from geomesa_tpu.server import serve_background

    ds = _fs_store(tmp_path / "srv4", n=400)
    server, _ = serve_background(
        ds, resident=True,
        sched=SchedConfig(max_queue=8, max_inflight=1,
                          default_deadline_ms=None),
    )
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    bbox = "-180,-90,180,90"
    target = f"{url}/density/t?bbox={bbox}&width=64&height=32"
    try:
        _get(f"{url}/count/t")  # stage
        exact = density_proc(
            ds, "t", "INCLUDE", Envelope(-180, -90, 180, 90), 64, 32
        )
        block = threading.Event()
        # saturate: wedge the worker and sit 2 requests in the queue
        held = [
            server.scheduler.submit(fn=lambda: block.wait(10))
            for _ in range(3)
        ]
        try:
            with prop_override("resilience.brownout.queue.frac", 0.1):
                status, hdrs, body = _get(target)
        finally:
            block.set()
            for h in held:
                server.scheduler.wait(h)
        assert status == 200
        assert "brownout-pushdown" in hdrs.get("X-Degraded", "")
        doc = json.loads(body)
        grid = np.asarray(doc["counts"], dtype=float)
        # PR 6 parity bound: total mass is exact
        assert np.isclose(grid.sum(), float(np.asarray(exact).sum()))
        # healthy again after the queue drains: exact resident answers
        status, hdrs, _ = _get(target)
        assert status == 200 and "X-Degraded" not in hdrs
    finally:
        server.shutdown()
        server.scheduler.shutdown(timeout=2.0)
