"""Binary feature serializer round-trips + lazy access (ref test role:
geomesa-feature-kryo KryoFeatureSerializerTest)."""

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.binser import (
    FeatureSerializer,
    deserialize_batch,
    serialize_batch,
)
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.geom import LineString, Point


SFT = SimpleFeatureType.create(
    "track",
    "name:String,age:Int,weight:Double,alive:Boolean,dtg:Date,*geom:Point:srid=4326",
)


def test_roundtrip_scalar_types():
    ser = FeatureSerializer(SFT)
    values = ("alice", 41, 62.5, True, 1700000000000, (10.25, -33.5))
    data = ser.serialize("f1", values)
    fid, out, ud = ser.deserialize(data)
    assert fid == "f1"
    assert out[0] == "alice"
    assert out[1] == 41
    assert out[2] == 62.5
    assert out[3] is True
    assert out[4] == 1700000000000
    assert (out[5].x, out[5].y) == (10.25, -33.5)
    assert ud == {}


def test_nulls_and_negative_ints():
    sft = SimpleFeatureType.create("t", "a:Int,b:Long,c:String")
    ser = FeatureSerializer(sft)
    fid, out, _ = ser.deserialize(ser.serialize(7, (-123, None, None)))
    assert fid == 7
    assert out == (-123, None, None)


def test_lazy_decodes_only_requested():
    ser = FeatureSerializer(SFT)
    data = ser.serialize("x", ("bob", 1, 2.0, False, 5, (0.0, 0.0)))
    f = ser.lazy(data)
    assert f.get("age") == 1
    assert f._memo.keys() == {1}  # nothing else decoded
    assert f.get("name") == "bob"
    assert f.get(0) == "bob"


def test_user_data_and_visibility_roundtrip():
    b = FeatureBatch.from_columns(
        SFT,
        {
            "name": ["a", "b"],
            "age": [1, 2],
            "weight": [1.0, 2.0],
            "alive": [True, False],
            "dtg": [10, 20],
            "geom": [(0.0, 1.0), (2.0, 3.0)],
        },
        fids=np.array(["u", "v"], dtype=object),
    ).with_visibility(["admin", ""])
    rows = serialize_batch(b)
    out = deserialize_batch(SFT, rows)
    assert list(out.fids) == ["u", "v"]
    assert list(out.visibilities) == ["admin", ""]
    np.testing.assert_allclose(out.column("geom"), b.column("geom"))
    np.testing.assert_array_equal(out.column("dtg"), [10, 20])


def test_batch_roundtrip_line_geometry():
    sft = SimpleFeatureType.create("lines", "n:Int,*geom:LineString")
    line = LineString([(0.0, 0.0), (1.5, 2.5), (3.0, -1.0)])
    b = FeatureBatch.from_columns(sft, {"n": [9], "geom": [line]})
    out = deserialize_batch(sft, serialize_batch(b))
    np.testing.assert_allclose(out.column("geom")[0].coords, line.coords)


def test_projection_skips_columns():
    b = FeatureBatch.from_columns(
        SFT,
        {
            "name": ["a"],
            "age": [5],
            "weight": [1.0],
            "alive": [True],
            "dtg": [77],
            "geom": [(1.0, 2.0)],
        },
    )
    out = deserialize_batch(SFT, serialize_batch(b), columns=["age", "geom"])
    assert set(out.columns) == {"age", "geom"}
    assert out.column("age")[0] == 5
    assert out.sft.attribute_names == ["age", "geom"]


def test_schema_mismatch_rejected():
    ser = FeatureSerializer(SFT)
    other = FeatureSerializer(SimpleFeatureType.create("t", "a:Int"))
    data = other.serialize(1, (2,))
    with pytest.raises(ValueError, match="attributes"):
        ser.lazy(data)
