"""Parquet filesystem store: persistence, reopen, pruned queries."""

import numpy as np
import pytest

from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.query.plan import Query
from geomesa_tpu.store.fs import FileSystemDataStore

SPEC = "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"


def fill(store, n=20000, seed=11):
    sft = store.create_schema("gdelt", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    cols = {
        "name": rng.choice(["alpha", "beta", "gamma"], n),
        "count": rng.integers(0, 100, n),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack([rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1),
    }
    store.write("gdelt", cols, fids=np.arange(n))
    store.flush("gdelt")
    return cols


def test_fs_roundtrip_and_prune(tmp_path):
    store = FileSystemDataStore(str(tmp_path), partition_size=4096)
    cols = fill(store)
    ecql = "BBOX(geom, -5, 42, 8, 51) AND dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z"
    res = store.query("gdelt", ecql)
    # oracle
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType

    sft = SimpleFeatureType.create("gdelt", SPEC)
    all_data = FeatureBatch.from_columns(sft, cols, np.arange(20000))
    expected = np.sort(all_data.fids[evaluate_host(parse_ecql(ecql), all_data)])
    np.testing.assert_array_equal(np.sort(res.batch.fids), expected)
    assert res.scanned < res.total, "manifest pruning should skip partitions"


def test_fs_reopen(tmp_path):
    store = FileSystemDataStore(str(tmp_path), partition_size=4096)
    fill(store, n=5000)
    # reopen from disk only
    store2 = FileSystemDataStore(str(tmp_path))
    assert store2.type_names == ["gdelt"]
    assert store2.get_schema("gdelt").geom_field == "geom"
    n = store2.count("gdelt", "BBOX(geom, -90, -45, 90, 45)")
    assert n == store.count("gdelt", "BBOX(geom, -90, -45, 90, 45)")
    assert n > 0


def test_fs_incremental_write(tmp_path):
    store = FileSystemDataStore(str(tmp_path), partition_size=1024)
    fill(store, n=3000)
    store.write(
        "gdelt",
        {
            "name": ["omega"],
            "count": [1],
            "dtg": [parse_instant("2020-01-10T00:00:00")],
            "geom": np.array([[2.0, 48.0]]),
        },
        fids=[777777],
    )
    store.flush("gdelt")
    res = store.query("gdelt", "name = 'omega'")
    assert list(res.batch.fids) == [777777]


def test_fs_sort_on_dropped_column(tmp_path):
    store = FileSystemDataStore(str(tmp_path), partition_size=1024)
    fill(store, n=3000)
    res = store.query(
        "gdelt",
        Query(filter="INCLUDE", properties=["count"], sort_by="count", max_features=5),
    )
    assert len(res) == 5
    assert np.all(np.diff(res.batch.column("count")) >= 0)


def test_fs_store_mesh_build_matches_host(tmp_path):
    """A mesh-equipped FS store flushes via the DEVICE build (encode +
    all_to_all exchange sort) and produces byte-identical manifests and
    query results to the host-built store — for points (z3) AND polygons
    (xz3)."""
    import json as _json

    from geomesa_tpu.parallel import make_mesh

    rng = np.random.default_rng(33)
    n = 3000
    mesh = make_mesh(8)
    # point schema
    pt_cols = {
        "name": rng.choice(["a", "b"], n),
        "dtg": rng.integers(1_577_836_800_000, 1_583_020_800_000, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    poly_cols = {
        "name": rng.choice(["a", "b"], n),
        "dtg": rng.integers(1_577_836_800_000, 1_583_020_800_000, n),
        "geom": np.array(
            [
                f"POLYGON (({x:.4f} {y:.4f}, {x+1:.4f} {y:.4f}, "
                f"{x+1:.4f} {y+1:.4f}, {x:.4f} {y+1:.4f}, {x:.4f} {y:.4f}))"
                for x, y in zip(
                    rng.uniform(-170, 160, n), rng.uniform(-85, 75, n)
                )
            ],
            dtype=object,
        ),
    }
    for label, spec, cols in (
        ("pt", "name:String,dtg:Date,*geom:Point:srid=4326", pt_cols),
        ("pg", "name:String,dtg:Date,*geom:Polygon:srid=4326", poly_cols),
    ):
        roots = {}
        for mode, m in (("host", None), ("mesh", mesh)):
            root = str(tmp_path / f"{label}_{mode}")
            ds = FileSystemDataStore(root, partition_size=512, mesh=m)
            # force the mesh path at test sizes (production gates small
            # flushes to the host lexsort to dodge per-shape compiles)
            ds.MESH_BUILD_MIN_ROWS = 0
            ds.create_schema("t", spec)
            ds.write("t", cols, fids=np.arange(n))
            ds.flush("t")
            roots[mode] = root
        # identical manifests (modulo the random generation tokens; the
        # per-partition checksums stay in the comparison — both builds
        # must produce byte-identical partition files)
        metas = {}
        for mode, root in roots.items():
            with open(f"{root}/t/schema.json") as fh:
                meta = _json.load(fh)
            meta.pop("generation")
            meta.pop("file_gen")
            metas[mode] = meta
        assert metas["host"] == metas["mesh"], f"{label}: manifests differ"
        # identical query results
        q = (
            "BBOX(geom, -10, 35, 30, 60) AND "
            "dtg DURING 2020-01-10T00:00:00Z/2020-02-20T00:00:00Z"
        )
        a = FileSystemDataStore(roots["host"]).query("t", q).batch
        b = FileSystemDataStore(roots["mesh"]).query("t", q).batch
        np.testing.assert_array_equal(np.sort(a.fids), np.sort(b.fids))
        assert len(a) > 0
