"""Crash-consistent flush: kill-matrix chaos tests + integrity checks.

The contract under test (ISSUE 3): a SIGKILL'd flushing process leaves a
store that reopens cleanly to EXACTLY the pre- or the post-flush row set
— never anything in between — with interrupted-flush leftovers reclaimed
by the recovery sweep and counted in the ``geomesa_store_*`` metrics;
corrupting any partition file is detected under ``store.verify`` and
quarantines only that partition.

The 3-failpoint flush smoke subset runs in tier-1 (marker ``chaos``);
the full kill matrix across compact/reindex/repartition (which all route
through ``_write_sorted``) is additionally marked ``slow``.
"""

import json
import multiprocessing as mp
import os
import signal

import numpy as np
import pytest

from geomesa_tpu.store.fs import FileSystemDataStore, PartitionCorruptError

SPEC = "val:Int,dtg:Date,*geom:Point:srid=4326"

FLUSH_FAILPOINTS = [
    "fail.flush.after_write",
    "fail.flush.before_publish",
    "fail.flush.after_publish",
]

N0 = 500  # pre-crash rows
NEW_FID0, NEW_N = 10_000, 300  # the crashing flush's rows (op == flush)


def _rows(n, seed, fid0=0):
    rng = np.random.default_rng(seed)
    cols = {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(0, 10**9, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    return cols, np.arange(fid0, fid0 + n)


def _populated(root, n=N0, fmt=2):
    from geomesa_tpu.conf import prop_override

    with prop_override("store.format.version", fmt):
        ds = _populate_fmt(root, n)
    return ds


def _populate_fmt(root, n):
    ds = FileSystemDataStore(root, partition_size=128)
    ds.create_schema("t", SPEC)
    cols, fids = _rows(n, seed=1)
    ds.write("t", cols, fids=fids)
    ds.flush("t")
    return ds


def _crash_op(root, op, failpoint, fmt=2):
    """Subprocess body: arm the failpoint with the `kill` action and run
    the operation — the process SIGKILLs ITSELF at the exact instant
    under test, which is as close to `kill -9 at the worst moment` as a
    deterministic test gets."""
    from geomesa_tpu import failpoints
    from geomesa_tpu.conf import set_prop
    from geomesa_tpu.store.fs import FileSystemDataStore

    set_prop("store.format.version", fmt)
    # several chunks per partition: a v2 crash must leave the chunked
    # manifest and the row-group-aligned files consistent, not just the
    # degenerate one-chunk case
    set_prop("store.chunk.rows", 32)
    ds = FileSystemDataStore(root, partition_size=128)
    if op == "flush":
        cols, fids = _rows(NEW_N, seed=7, fid0=NEW_FID0)
        ds.write("t", cols, fids=fids)
    failpoints.set_failpoint(failpoint, "kill")
    if op == "flush":
        ds.flush("t")
    elif op == "compact":
        ds.compact("t")
    elif op == "reindex":
        ds.reindex("t", "z2")
    elif op == "repartition":
        ds.repartition("t", "daily,z2-2bit")
    os._exit(42)  # must be unreachable: every failpoint kills


def _run_crash(tmp_path, op, failpoint, fmt=2):
    """Populate, crash a subprocess mid-op, reopen; returns
    (advanced, orphans_reclaimed) where advanced == the reopened store
    serves the POST-op state."""
    root = str(tmp_path / "store")
    ds = _populated(root, fmt=fmt)
    old_fids = {int(f) for f in ds.query("t").batch.fids}
    assert len(old_fids) == N0
    del ds

    ctx = mp.get_context("spawn")  # fork is unsafe under JAX threads
    p = ctx.Process(target=_crash_op, args=(root, op, failpoint, fmt))
    p.start()
    p.join(180)
    assert p.exitcode == -signal.SIGKILL, (op, failpoint, p.exitcode)

    from geomesa_tpu import metrics

    orphans0 = metrics.store_orphan_files.value()
    ds2 = FileSystemDataStore(root, partition_size=128)  # open = sweep
    got = {int(f) for f in ds2.query("t").batch.fids}
    new_fids = (
        old_fids | set(range(NEW_FID0, NEW_FID0 + NEW_N))
        if op == "flush"
        else old_fids
    )
    # the crash-consistency contract: EXACTLY the old or the new rows
    assert got == old_fids or got == new_fids, (op, failpoint, len(got))
    # structural integrity: after the sweep, the on-disk part files are
    # exactly the manifest's — nothing dangling from the dead flush
    st = ds2._types["t"]
    expected = {
        os.path.abspath(ds2._part_path("t", q)) for q in st.partitions
    }
    on_disk = {
        os.path.abspath(os.path.join(dp, f))
        for dp, _, fs in os.walk(os.path.join(root, "t"))
        for f in fs
        if f.startswith("part-")
    }
    assert on_disk == expected
    assert sum(q.count for q in st.partitions) == len(got)
    return got == new_fids, metrics.store_orphan_files.value() - orphans0


# -- kill matrix -------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("fmt", [1, 2], ids=["v1", "v2"])
@pytest.mark.parametrize(
    "failpoint,expect_new",
    [
        ("fail.flush.after_write", False),  # files written, unpublished
        ("fail.flush.before_publish", False),
        ("fail.flush.after_publish", True),  # published, old gen not GC'd
    ],
)
def test_flush_kill_matrix_smoke(tmp_path, failpoint, expect_new, fmt):
    """The old-xor-new contract must hold for BOTH manifest formats:
    v2's chunked manifests and row-group-aligned files ride the same
    write-new-then-publish protocol, so a crash can never publish a
    manifest whose chunk stats disagree with its files."""
    advanced, orphans = _run_crash(tmp_path, "flush", failpoint, fmt=fmt)
    assert advanced == expect_new
    # every kill leaves an unpublished new generation (pre-publish) or an
    # un-GC'd old one (post-publish): the sweep must reclaim something
    assert orphans >= 1
    if fmt == 2:
        # whichever generation survived, its chunk stats must match the
        # decoded rows bit for bit (the fsck cross-check)
        root = str(tmp_path / "store")
        ds = FileSystemDataStore(root, partition_size=128)
        assert ds.verify_chunk_stats("t") == []


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("failpoint", FLUSH_FAILPOINTS)
@pytest.mark.parametrize("op", ["compact", "reindex", "repartition"])
def test_maintenance_kill_matrix(tmp_path, op, failpoint):
    """compact/reindex/repartition all route through _write_sorted: the
    same old-xor-new guarantee must hold (for these ops the row SET is
    identical either way; the structural assertions in _run_crash pin
    manifest/file consistency)."""
    advanced, orphans = _run_crash(tmp_path, op, failpoint)
    assert orphans >= 1
    if failpoint == "fail.flush.after_publish":
        assert advanced


# -- checksum verification / per-partition quarantine ------------------------


def _corrupt(path):
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(data))


def test_checksum_corruption_quarantines_one_partition(tmp_path):
    from geomesa_tpu import metrics
    from geomesa_tpu.conf import prop_override

    root = str(tmp_path / "store")
    ds = _populated(root)
    st = ds._types["t"]
    assert all(p.checksum for p in st.partitions)
    assert len(st.partitions) >= 2
    # a window that prunes to a strict subset of partitions; corrupt one
    # OUTSIDE it
    ecql = (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 1970-01-01T00:00:00Z/1970-01-02T00:00:00Z"
    )
    plan = ds.plan("t", ecql)
    pruned = {p.pid for p in ds._pruned_parts("t", plan)}
    outside = [p for p in st.partitions if p.pid not in pruned]
    assert outside, "test window must prune at least one partition"
    victim = outside[0]
    before = sorted(int(f) for f in ds.query("t", ecql).batch.fids)
    all_before = sorted(int(f) for f in ds.query("t").batch.fids)
    victim_fids = {int(f) for f in ds._read_partition("t", victim).fids}
    _corrupt(ds._part_path("t", victim))

    with prop_override("store.verify", "always"):
        fresh = FileSystemDataStore(root, partition_size=128)
        c0 = metrics.store_checksum_failures.value()
        with prop_override("resilience.degrade", False):
            # degradation off: touching the corrupt partition fails
            # loudly, naming it (the pre-ISSUE-7 contract, still the
            # strict-mode behavior)
            with pytest.raises(
                PartitionCorruptError, match=f"partition {victim.pid}"
            ):
                fresh.query("t")
            assert metrics.store_checksum_failures.value() - c0 == 1
            assert set(fresh._types["t"].quarantined) == {victim.pid}
            # ... but ONLY that partition: the pruned query still serves,
            # byte-identical to the pre-corruption answer
            after = sorted(
                int(f) for f in fresh.query("t", ecql).batch.fids
            )
            assert after == before
            # repeated reads stay loud without re-counting the failure
            with pytest.raises(PartitionCorruptError):
                fresh.query("t")
            assert metrics.store_checksum_failures.value() - c0 == 1
        # resilience.degrade (the default): the corruption is a
        # PARTITION-SCOPED fault — the full scan serves every healthy
        # sibling, stamped degraded, instead of failing (ISSUE 7)
        from geomesa_tpu import resilience

        with resilience.collect_degraded() as reasons:
            got = sorted(int(f) for f in fresh.query("t").batch.fids)
        assert got == sorted(set(all_before) - victim_fids)
        assert "partition-unavailable" in reasons


def test_verify_open_quarantines_at_open(tmp_path):
    from geomesa_tpu.conf import prop_override

    root = str(tmp_path / "store")
    ds = _populated(root)
    victim = ds._types["t"].partitions[-1]
    _corrupt(ds._part_path("t", victim))
    with prop_override("store.verify", "open"):
        fresh = FileSystemDataStore(root, partition_size=128)  # no raise
    assert set(fresh._types["t"].quarantined) == {victim.pid}
    with pytest.raises(PartitionCorruptError):
        fresh._read_partition("t", victim)
    # siblings serve
    ok = fresh._read_partition("t", fresh._types["t"].partitions[0])
    assert len(ok) > 0


def test_fsck_cli_reports_and_fails_on_corruption(tmp_path, capsys):
    from geomesa_tpu.tools.cli import main

    root = str(tmp_path / "store")
    ds = _populated(root)
    # clean store: fsck sweeps nothing, verifies everything, exits 0
    main(["--root", root, "fsck"])
    out = capsys.readouterr().out
    assert "swept 0 orphan" in out and "partition file(s) ok" in out
    _corrupt(ds._part_path("t", ds._types["t"].partitions[0]))
    with pytest.raises(SystemExit, match="corrupt"):
        main(["--root", root, "fsck"])
    assert "CORRUPT" in capsys.readouterr().out


# -- recovery sweep ----------------------------------------------------------


def test_recovery_sweep_idempotent_and_counted(tmp_path):
    from geomesa_tpu import metrics

    root = str(tmp_path / "store")
    ds = _populated(root)
    d = os.path.join(root, "t")
    with open(os.path.join(d, "part-deadbeef-00099.parquet"), "wb") as fh:
        fh.write(b"junk-from-a-dead-flush")
    with open(os.path.join(d, "schema.json.tmp"), "w") as fh:
        fh.write("{}")
    f0 = metrics.store_orphan_files.value()
    b0 = metrics.store_orphan_bytes.value()
    rep1 = ds.recover("t")
    assert rep1["files"] == 2 and rep1["bytes"] > 0
    assert metrics.store_orphan_files.value() - f0 == 2
    assert metrics.store_orphan_bytes.value() - b0 == rep1["bytes"]
    # idempotent: a second sweep finds nothing
    rep2 = ds.recover("t")
    assert rep2["files"] == 0 and rep2["bytes"] == 0
    # and the data is untouched
    assert ds.count("t") == N0


def test_gen_sidecar_lag_repaired_on_open(tmp_path):
    """A crash between the manifest replace and the sidecar replace
    leaves schema.json.gen one generation behind; open repairs it from
    the manifest (the source of truth)."""
    root = str(tmp_path / "store")
    _populated(root)
    gen_path = os.path.join(root, "t", "schema.json.gen")
    with open(gen_path, "w") as fh:
        fh.write("0123456789abcdef0123456789abcdef")  # stale token
    FileSystemDataStore(root, partition_size=128)  # open sweep repairs
    with open(os.path.join(root, "t", "schema.json")) as fh:
        truth = json.load(fh)["generation"]
    with open(gen_path) as fh:
        assert fh.read().strip() == truth


# -- transient-read retry ----------------------------------------------------


def test_transient_read_errors_retry_with_backoff(tmp_path):
    from geomesa_tpu import failpoints, metrics
    from geomesa_tpu.conf import prop_override

    root = str(tmp_path / "store")
    _populated(root)
    fresh = FileSystemDataStore(root, partition_size=128)
    with prop_override("io.retries", 3), prop_override("io.backoff.ms", 1):
        r0 = metrics.store_read_retries.value()
        with failpoints.failpoint_override("fail.read.io", "raise:2"):
            res = fresh.query("t", "INCLUDE")
        assert len(res.batch) == N0
        assert metrics.store_read_retries.value() - r0 == 2
    # exhausted retries surface a typed, partition-scoped error instead
    # of looping forever (outside a serving request there is nothing to
    # stamp degraded, so the query fails loudly — ISSUE 7)
    from geomesa_tpu import resilience

    fresh2 = FileSystemDataStore(root, partition_size=128)
    with prop_override("io.retries", 1), prop_override("io.backoff.ms", 1):
        with failpoints.failpoint_override("fail.read.io", "raise"):
            with pytest.raises(
                resilience.PartitionUnavailableError, match="failpoint"
            ):
                fresh2.query("t", "INCLUDE")


def test_partial_publish_adopts_new_generation(tmp_path, monkeypatch):
    """If the manifest replace lands but the SIDECAR write then fails
    (e.g. ENOSPC), the disk owns the new generation: the writer must
    adopt it — a restore of the old view would re-queue the pending
    rows and the next flush would publish them twice."""
    import geomesa_tpu.store.fs as fsmod

    root = str(tmp_path / "store")
    ds = _populated(root)
    cols, fids = _rows(50, seed=9, fid0=20_000)
    ds.write("t", cols, fids=fids)
    real = fsmod._write_file

    def flaky(path, data, fsync):
        if path.endswith(".gen.tmp"):
            raise OSError(28, "No space left on device")
        return real(path, data, fsync)

    monkeypatch.setattr(fsmod, "_write_file", flaky)
    with pytest.raises(OSError, match="No space"):
        ds.flush("t")
    monkeypatch.undo()
    # the manifest flipped before the failure: the rows are durable and
    # must appear exactly ONCE (no duplicate re-flush of pending)
    assert ds.count("t") == N0 + 50
    ds2 = FileSystemDataStore(root, partition_size=128)  # repairs sidecar
    assert ds2.count("t") == N0 + 50


def test_failpoint_env_activation(monkeypatch):
    """The GEOMESA_TPU_FAILPOINTS env form (how a chaos subprocess arms
    a point): comma-separated name=action, raise:N budgets honored."""
    from geomesa_tpu import failpoints

    monkeypatch.setenv(
        failpoints.ENV_VAR,
        "fail.read.io=raise:1, fail.flush.after_write=off",
    )
    failpoints.clear_failpoint("fail.read.io")  # fresh raise:N budget
    assert failpoints.action_for("fail.read.io") == "raise:1"
    with pytest.raises(failpoints.FailpointError):
        failpoints.fail_point("fail.read.io")
    failpoints.fail_point("fail.read.io")  # budget spent -> no-op
    failpoints.fail_point("fail.flush.after_write")  # off -> no-op
    monkeypatch.setenv(failpoints.ENV_VAR, "")
    assert failpoints.action_for("fail.read.io") is None


# -- observability -----------------------------------------------------------


def test_stats_store_snapshot_and_endpoint(tmp_path):
    import urllib.request

    root = str(tmp_path / "store")
    ds = _populated(root)
    doc = ds.store_stats()
    assert doc["types"]["t"]["rows"] == N0
    assert doc["types"]["t"]["file_generation"]
    assert doc["types"]["t"]["quarantined"] == {}
    assert "orphan_files_reclaimed" in doc["counters"]

    from geomesa_tpu.server import serve_background

    server, _ = serve_background(ds)
    try:
        host, port = server.server_address[:2]
        with urllib.request.urlopen(
            f"http://{host}:{port}/stats/store", timeout=30
        ) as r:
            doc2 = json.loads(r.read())
        assert doc2["types"]["t"]["rows"] == N0
    finally:
        server.shutdown()


# -- streaming live layer (ISSUE 10): acked-rows-exactly kill matrix ---------

STREAM_FID0, STREAM_BATCH = 20_000, 80


def _stream_rows(i):
    return _rows(STREAM_BATCH, seed=50 + i, fid0=STREAM_FID0 + i * 100)


def _crash_stream(root, failpoint, acked_path):
    """Subprocess body: stream batches through the live layer, fsyncing
    each ACKED batch id to ``acked_path`` AFTER its append returns (the
    client's view of what was acked), then arm ``failpoint`` with
    ``kill`` and keep going — the process dies at the exact instant
    under test. Auto-compaction is disabled so the kill instant, not a
    background race, decides what was compacted."""
    from geomesa_tpu import failpoints
    from geomesa_tpu.conf import set_prop
    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.store.stream import StreamingStore

    set_prop("stream.run.rows", 64)  # every append = its own Z-sorted run
    set_prop("wal.segment.bytes", 1 << 12)  # force segment rotations
    set_prop("stream.memtable.rows", 1 << 20)  # no background compaction
    set_prop("wal.max.generations", 64)  # kill decides, not backpressure
    ds = FileSystemDataStore(root, partition_size=128)
    layer = StreamingStore(ds)
    fh = open(acked_path, "a")

    def ack(i):
        cols, fids = _stream_rows(i)
        layer.append("t", cols, fids=fids)
        fh.write(f"{i}\n")
        fh.flush()
        os.fsync(fh.fileno())

    for i in range(3):  # cleanly acked pre-crash batches
        ack(i)
    failpoints.set_failpoint(failpoint, "kill")
    if failpoint == "fail.compact.publish":
        layer.compact_now("t")  # dies between publish and WAL truncate
    else:
        for i in range(3, 40):  # dies at the armed WAL instant
            ack(i)
    os._exit(42)  # must be unreachable: the failpoint kills


def _crash_stream_reopen(root):
    """Second-phase subprocess: a crash DURING WAL replay at open —
    recovery itself must be idempotent under SIGKILL."""
    from geomesa_tpu import failpoints
    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.store.stream import StreamingStore

    failpoints.set_failpoint("fail.wal.replay", "kill")
    ds = FileSystemDataStore(root, partition_size=128)
    StreamingStore(ds)  # dies scanning the first segment
    os._exit(42)


def _crash_stream_no_arm(root, acked_path):
    """Clean-exit variant (no failpoint): appends acked batches and
    exits WITHOUT compaction or close — the WAL alone must carry them."""
    from geomesa_tpu.conf import set_prop
    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.store.stream import StreamingStore

    set_prop("stream.run.rows", 64)
    set_prop("wal.segment.bytes", 1 << 12)
    set_prop("stream.memtable.rows", 1 << 20)
    ds = FileSystemDataStore(root, partition_size=128)
    layer = StreamingStore(ds)
    fh = open(acked_path, "a")
    for i in range(3):
        cols, fids = _stream_rows(i)
        layer.append("t", cols, fids=fids)
        fh.write(f"{i}\n")
        fh.flush()
        os.fsync(fh.fileno())
    os._exit(43)


def _verify_acked_exactly(root, acked_path):
    """Reopen the store + live layer and assert the served row set is
    EXACTLY seed ∪ acked — no acked row lost, no phantom row invented,
    no row double-applied — and the chunk stats are drift-free."""
    from geomesa_tpu.store.stream import StreamingStore

    with open(acked_path) as fh:
        acked = [int(line) for line in fh.read().split()]
    expected = {int(f) for f in range(N0)}
    for i in acked:
        base = STREAM_FID0 + i * 100
        expected |= set(range(base, base + STREAM_BATCH))
    ds = FileSystemDataStore(root, partition_size=128)
    layer = StreamingStore(ds)
    try:
        batch = layer.query("t").batch
        got = [int(f) for f in batch.fids]
        assert len(got) == len(set(got)), "rows double-applied"
        assert set(got) == expected, (
            f"served {len(got)} rows != seed+acked {len(expected)}"
        )
        assert layer.count("t") == len(expected)
        assert ds.verify_chunk_stats("t") == []  # stats drift-free
    finally:
        layer.close()


@pytest.mark.chaos
@pytest.mark.parametrize(
    "failpoint",
    ["fail.wal.append", "fail.wal.rotate", "fail.compact.publish"],
)
def test_stream_kill_matrix(tmp_path, failpoint):
    """SIGKILL at every streaming-ingest instant: reopened store serves
    exactly the acked rows. ``fail.wal.append`` kills before the record
    lands (the un-acked batch vanishes with its torn tail, acked ones
    survive); ``fail.wal.rotate`` kills at segment seal; and
    ``fail.compact.publish`` kills between manifest publish and WAL
    truncation (the manifest watermark must make replay skip the stale
    segments, not re-apply them)."""
    root = str(tmp_path / "store")
    _populated(root)
    acked_path = str(tmp_path / "acked.txt")

    ctx = mp.get_context("spawn")  # fork is unsafe under JAX threads
    p = ctx.Process(target=_crash_stream, args=(root, failpoint, acked_path))
    p.start()
    p.join(240)
    assert p.exitcode == -signal.SIGKILL, (failpoint, p.exitcode)
    _verify_acked_exactly(root, acked_path)


@pytest.mark.chaos
def test_stream_kill_during_replay(tmp_path):
    """SIGKILL mid-replay: a crash during recovery itself loses nothing
    — the next open replays the same records (idempotent; nothing was
    compacted, so the watermark skips none of them)."""
    root = str(tmp_path / "store")
    _populated(root)
    acked_path = str(tmp_path / "acked.txt")

    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_crash_stream_no_arm, args=(root, acked_path))
    p.start()
    p.join(240)
    assert p.exitcode == 43, p.exitcode  # clean exit, WAL not compacted

    p2 = ctx.Process(target=_crash_stream_reopen, args=(root,))
    p2.start()
    p2.join(240)
    assert p2.exitcode == -signal.SIGKILL, p2.exitcode
    _verify_acked_exactly(root, acked_path)
