"""XML / fixed-width / Avro / JDBC / Shapefile converters."""

import io
import sqlite3
import struct

import numpy as np
import pytest

from geomesa_tpu.convert import converter_for
from geomesa_tpu.features.avro import write_avro
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.geom import MultiLineString, Point, Polygon

SPEC = "name:String,age:Int,*geom:Point"
SFT = SimpleFeatureType.create("people", SPEC)


# -- xml ---------------------------------------------------------------------

XML_DOC = """<?xml version="1.0"?>
<doc>
  <Feature id="f1"><Name>Alice</Name><Age>34</Age><Lon>2.35</Lon><Lat>48.85</Lat></Feature>
  <Feature id="f2"><Name>Bob</Name><Age>41</Age><Lon>-0.12</Lon><Lat>51.5</Lat></Feature>
</doc>
"""

XML_CONFIG = {
    "type": "xml",
    "feature-path": ".//Feature",
    "id-field": "$fid",
    "fields": [
        {"name": "fid", "path": "@id"},
        {"name": "name", "path": "Name/text()"},
        {"name": "age", "path": "Age", "transform": "$age::int"},
        {"name": "lon", "path": "Lon"},
        {"name": "lat", "path": "Lat"},
        {"name": "geom", "transform": "point($lon::double, $lat::double)"},
    ],
}


def test_xml_converter():
    sft = SimpleFeatureType.create(
        "p", "fid:String,name:String,age:Int,lon:Double,lat:Double,*geom:Point"
    )
    res = converter_for(XML_CONFIG, sft).process(XML_DOC)
    assert res.success == 2 and res.failed == 0
    assert list(res.batch.fids) == ["f1", "f2"]
    assert list(res.batch.column("name")) == ["Alice", "Bob"]
    assert res.batch.column("age").tolist() == [34, 41]
    np.testing.assert_allclose(
        res.batch.column("geom"), [[2.35, 48.85], [-0.12, 51.5]]
    )


def test_xml_attribute_and_missing():
    cfg = {
        "type": "xml",
        "feature-path": ".//Feature",
        "fields": [
            {"name": "name", "path": "Name"},
            {"name": "age", "path": "Missing", "transform": "stringToInt($age, 0)"},
            {"name": "geom", "transform": "point(Lon($0), 0)"},
        ],
    }
    # missing path yields None -> stringToInt default kicks in
    sft2 = SimpleFeatureType.create("p", "name:String,age:Int,*geom:Point")
    cfg["fields"][2] = {"name": "geom", "transform": "point(1, 2)"}
    res = converter_for(cfg, sft2).process(XML_DOC)
    assert res.batch.column("age").tolist() == [0, 0]


# -- fixed width -------------------------------------------------------------


def test_fixed_width_converter():
    cfg = {
        "type": "fixed-width",
        "id-field": "$name",
        "fields": [
            {"name": "name", "start": 0, "width": 6},
            {"name": "age", "start": 6, "width": 3, "transform": "$age::int"},
            {"name": "lat", "start": 9, "width": 6},
            {"name": "lon", "start": 15, "width": 7},
            {"name": "geom", "transform": "point($lon::double, $lat::double)"},
        ],
    }
    sft = SimpleFeatureType.create(
        "p", "name:String,age:Int,lat:Double,lon:Double,*geom:Point"
    )
    data = "Alice  34 48.85   2.35\nBob    41 51.50  -0.12\n"
    res = converter_for(cfg, sft).process(data)
    assert res.success == 2
    assert list(res.batch.fids) == ["Alice", "Bob"]
    np.testing.assert_allclose(res.batch.column("lat"), [48.85, 51.5])


def test_fixed_width_bad_row_skipped():
    cfg = {
        "type": "fixed-width",
        "fields": [
            {"name": "age", "start": 0, "width": 3, "transform": "$age::int"},
        ],
    }
    sft = SimpleFeatureType.create("p", "age:Int")
    res = converter_for(cfg, sft).process("34\nxx\n41\n")
    assert res.success == 2 and res.failed == 1
    assert res.batch.column("age").tolist() == [34, 41]


# -- avro --------------------------------------------------------------------


def test_avro_converter_roundtrip():
    # write a container file with our own writer, re-ingest it generically
    src_sft = SimpleFeatureType.create("src", "name:String,age:Int,*geom:Point")
    batch = FeatureBatch.from_columns(
        src_sft,
        {
            "name": ["Alice", "Bob"],
            "age": [34, 41],
            "geom": np.array([[2.35, 48.85], [-0.12, 51.5]]),
        },
        fids=["a", "b"],
    )
    buf = io.BytesIO()
    write_avro(buf, batch)
    cfg = {
        "type": "avro",
        "id-field": "$__fid__",
        "fields": [
            {"name": "name", "path": "name"},
            {"name": "age", "transform": "$age::int"},
            # geom came back as WKT text
            {"name": "geom", "transform": "$geom"},
        ],
    }
    res = converter_for(cfg, SFT).process(buf.getvalue())
    assert res.success == 2
    assert list(res.batch.fids) == ["a", "b"]
    assert list(res.batch.column("name")) == ["Alice", "Bob"]
    np.testing.assert_allclose(
        res.batch.column("geom"), [[2.35, 48.85], [-0.12, 51.5]]
    )


def test_avro_generic_decoder_types():
    from geomesa_tpu.convert.avro_conv import read_generic_avro
    from geomesa_tpu.features.avro import MAGIC, write_bytes, write_long, write_string

    import json as _json

    schema = {
        "type": "record",
        "name": "r",
        "fields": [
            {"name": "s", "type": "string"},
            {"name": "d", "type": "double"},
            {"name": "u", "type": ["null", "long"]},
            {"name": "arr", "type": {"type": "array", "items": "int"}},
        ],
    }
    buf = io.BytesIO()
    buf.write(MAGIC)
    write_long(buf, 2)
    write_string(buf, "avro.schema")
    write_bytes(buf, _json.dumps(schema).encode())
    write_string(buf, "avro.codec")
    write_bytes(buf, b"null")
    write_long(buf, 0)
    sync = b"0123456789abcdef"
    buf.write(sync)
    block = io.BytesIO()
    # record 1: "hi", 2.5, null, [1,2]
    write_string(block, "hi")
    block.write(struct.pack("<d", 2.5))
    write_long(block, 0)
    write_long(block, 2)
    write_long(block, 1)
    write_long(block, 2)
    write_long(block, 0)
    # record 2: "yo", -1.0, 7, []
    write_string(block, "yo")
    block.write(struct.pack("<d", -1.0))
    write_long(block, 1)
    write_long(block, 7)
    write_long(block, 0)
    write_long(buf, 2)
    write_bytes(buf, block.getvalue())
    buf.write(sync)
    recs = read_generic_avro(buf.getvalue())
    assert recs == [
        {"s": "hi", "d": 2.5, "u": None, "arr": [1, 2]},
        {"s": "yo", "d": -1.0, "u": 7, "arr": []},
    ]


# -- jdbc --------------------------------------------------------------------


def test_jdbc_converter(tmp_path):
    db = str(tmp_path / "x.db")
    with sqlite3.connect(db) as conn:
        conn.execute("CREATE TABLE pts (id TEXT, name TEXT, lon REAL, lat REAL)")
        conn.executemany(
            "INSERT INTO pts VALUES (?,?,?,?)",
            [("a", "Alice", 2.35, 48.85), ("b", "Bob", -0.12, 51.5)],
        )
    cfg = {
        "type": "jdbc",
        "connection": db,
        "id-field": "$1",
        "fields": [
            {"name": "name", "transform": "$2"},
            {"name": "age", "transform": "lit(0)::int"},
            {"name": "geom", "transform": "point($3::double, $4::double)"},
        ],
    }
    res = converter_for(cfg, SFT).process("SELECT id, name, lon, lat FROM pts ORDER BY id")
    assert res.success == 2
    assert list(res.batch.fids) == ["a", "b"]
    np.testing.assert_allclose(
        res.batch.column("geom"), [[2.35, 48.85], [-0.12, 51.5]]
    )


# -- shapefile ---------------------------------------------------------------


def _mk_shp(shapes: list) -> bytes:
    """Build a minimal .shp byte blob from (type, payload) tuples."""
    records = []
    for i, (stype, payload) in enumerate(shapes):
        content = struct.pack("<i", stype) + payload
        header = struct.pack(">ii", i + 1, len(content) // 2)
        records.append(header + content)
    body = b"".join(records)
    total_words = (100 + len(body)) // 2
    hdr = struct.pack(">i", 9994) + b"\x00" * 20 + struct.pack(">i", total_words)
    hdr += struct.pack("<ii", 1000, shapes[0][0] if shapes else 0)
    hdr += struct.pack("<8d", 0, 0, 0, 0, 0, 0, 0, 0)
    return hdr + body


def _mk_dbf(names, rows) -> bytes:
    fields = b""
    for name in names:
        fields += name.encode().ljust(11, b"\x00") + b"C" + b"\x00" * 4
        fields += bytes([20, 0]) + b"\x00" * 14
    header_size = 32 + len(fields) + 1
    record_size = 1 + 20 * len(names)
    hdr = bytes([3, 120, 1, 1]) + struct.pack(
        "<iHH", len(rows), header_size, record_size
    )
    hdr += b"\x00" * 20 + fields + b"\x0d"
    body = b""
    for row in rows:
        body += b" " + b"".join(str(v).encode().ljust(20) for v in row)
    return hdr + body


def test_shp_points_with_dbf():
    shp = _mk_shp(
        [
            (1, struct.pack("<dd", 2.35, 48.85)),
            (1, struct.pack("<dd", -0.12, 51.5)),
        ]
    )
    dbf = _mk_dbf(["NAME"], [["Alice"], ["Bob"]])
    cfg = {
        "type": "shp",
        "id-field": "$NAME",
        "fields": [
            {"name": "name", "transform": "$NAME"},
            {"name": "age", "transform": "lit(1)::int"},
            {"name": "geom", "transform": "$geom"},
        ],
    }
    res = converter_for(cfg, SFT).process(shp, dbf=dbf)
    assert res.success == 2
    assert list(res.batch.fids) == ["Alice", "Bob"]
    np.testing.assert_allclose(
        res.batch.column("geom"), [[2.35, 48.85], [-0.12, 51.5]]
    )


def test_shp_polygon_and_polyline():
    from geomesa_tpu.convert.shp import read_shp

    # square polygon, CW ring (outer): (0,0) (0,1) (1,1) (1,0) back to (0,0)
    ring = np.array([[0, 0], [0, 1], [1, 1], [1, 0], [0, 0]], dtype="<f8")
    poly_payload = (
        struct.pack("<4d", 0, 0, 1, 1)
        + struct.pack("<ii", 1, len(ring))
        + struct.pack("<i", 0)
        + ring.tobytes()
    )
    line = np.array([[0, 0], [2, 2], [4, 0]], dtype="<f8")
    line_payload = (
        struct.pack("<4d", 0, 0, 4, 2)
        + struct.pack("<ii", 1, len(line))
        + struct.pack("<i", 0)
        + line.tobytes()
    )
    geoms = read_shp(_mk_shp([(5, poly_payload)]))
    assert isinstance(geoms[0], Polygon)
    np.testing.assert_allclose(geoms[0].shell, ring)
    geoms = read_shp(_mk_shp([(3, line_payload)]))
    np.testing.assert_allclose(geoms[0].coords, line)


def test_shp_default_field_mapping(tmp_path):
    shp = _mk_shp([(1, struct.pack("<dd", 1.0, 2.0))])
    dbf = _mk_dbf(["name", "age"], [["Ann", 3]])
    p = tmp_path / "pts.shp"
    p.write_bytes(shp)
    (tmp_path / "pts.dbf").write_bytes(dbf)
    cfg = {"type": "shp"}
    sft = SimpleFeatureType.create("p", "name:String,age:Int,*geom:Point")
    res = converter_for(cfg, sft).process(str(p))
    assert res.success == 1
    assert list(res.batch.column("name")) == ["Ann"]
    assert res.batch.column("age").tolist() == [3]
