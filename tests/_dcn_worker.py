"""Worker for the two-process DCN test (tests/test_multihost_dcn.py).

Each process forces the CPU platform with 4 virtual devices, joins the
jax.distributed process group over a local coordinator, contributes its
half of the data with host_batches_to_global, and runs the same
sharded_count_scan -- the multi-host ingest + scan path of
parallel/multihost.py, exercised with real cross-process collectives.

Platform setup is manual (not jaxconf.force_cpu_devices) because the
device-count check there would initialize the backend BEFORE
jax.distributed.initialize, which must come first in a multi-process
group.
"""

import os
import sys


def main() -> None:
    proc_id = int(sys.argv[1])
    port = sys.argv[2]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except Exception:
        pass

    from geomesa_tpu.parallel.multihost import (
        global_mesh,
        host_batches_to_global,
        initialize,
    )

    initialize(f"127.0.0.1:{port}", num_processes=2, process_id=proc_id)

    import numpy as np

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from geomesa_tpu.parallel import sharded_count_scan

    mesh = global_mesh()
    assert mesh.shape["shard"] == 8

    # identical global dataset on both processes; each contributes only
    # its local half through the multi-host feed
    rng = np.random.default_rng(0)
    n = 8192
    x = rng.uniform(-180, 180, n).astype(np.float32)
    y = rng.uniform(-90, 90, n).astype(np.float32)
    half = n // 2
    lo = proc_id * half
    cols = host_batches_to_global(
        mesh, {"x": x[lo : lo + half], "y": y[lo : lo + half]}
    )
    for v in cols.values():
        assert v.shape == (n,), v.shape  # global length, local halves

    def fn(c):
        return (c["x"] >= -10) & (c["x"] <= 30) & (c["y"] >= 0)

    count = int(sharded_count_scan(mesh, fn, cols))
    expect = int(((x >= -10) & (x <= 30) & (y >= 0)).sum())
    assert count == expect, (count, expect)
    print(f"proc{proc_id} DCN scan OK count={count}", flush=True)


if __name__ == "__main__":
    main()
