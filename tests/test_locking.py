"""Inter-process locking: flock semantics + concurrent FS-store writers."""

import multiprocessing as mp
import os
import sys

import numpy as np
import pytest

from geomesa_tpu.locking import LockTimeout, file_lock

SPEC = "val:Int,dtg:Date,*geom:Point"


def _lock_holder(path, held, release):
    from geomesa_tpu.locking import file_lock

    with file_lock(path):
        held.set()
        release.wait(10)


class TestFileLock:
    def test_exclusive_blocks_second_process(self, tmp_path):
        path = str(tmp_path / "l")
        holder = _lock_holder
        ctx = mp.get_context("spawn")  # fork is unsafe under JAX threads
        held, release = ctx.Event(), ctx.Event()
        p = ctx.Process(target=holder, args=(path, held, release))
        p.start()
        try:
            assert held.wait(10)
            with pytest.raises(LockTimeout):
                with file_lock(path, timeout_s=0.3):
                    pass
            release.set()
            p.join(10)
            with file_lock(path, timeout_s=5):  # free again
                pass
        finally:
            release.set()
            p.join(5)

    def test_shared_locks_coexist_but_block_exclusive(self, tmp_path):
        path = str(tmp_path / "l")
        with file_lock(path, shared=True):
            with file_lock(path, shared=True, timeout_s=1):
                pass  # two readers fine
            # ...but a writer cannot enter while a reader holds it
            with pytest.raises(LockTimeout):
                with file_lock(path, timeout_s=0.2):
                    pass

    def test_exclusive_reentrancy_is_not_automatic(self, tmp_path):
        # flock on a second fd of the same file blocks even in-process:
        # that is why the FS store tracks a per-thread depth
        path = str(tmp_path / "l")
        with file_lock(path):
            with pytest.raises(LockTimeout):
                with file_lock(path, timeout_s=0.2):
                    pass

    def test_timeout_reports_holder_pid(self, tmp_path):
        # exclusive holders write their pid into the sentinel; a timeout
        # names the (last) writer so operators know whom to chase
        path = str(tmp_path / "l")
        with file_lock(path):
            with pytest.raises(LockTimeout) as ei:
                with file_lock(path, timeout_s=0.2):
                    pass
            assert str(os.getpid()) in str(ei.value)


def _writer_proc(root, wid, n_rounds, n_rows):
    import numpy as np

    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(root, partition_size=256)
    rng = np.random.default_rng(wid)
    for k in range(n_rounds):
        fid0 = wid * 1_000_000 + k * n_rows
        ds.write(
            "t",
            {
                "val": rng.integers(0, 100, n_rows),
                "dtg": rng.integers(0, 10**9, n_rows),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n_rows),
                     rng.uniform(-90, 90, n_rows)], axis=1,
                ),
            },
            fids=np.arange(fid0, fid0 + n_rows),
        )
        ds.flush("t")


@pytest.mark.skipif(sys.platform == "win32", reason="flock")
def test_concurrent_fs_writers_do_not_corrupt(tmp_path):
    """Two processes interleaving flushes on one store root: the
    exclusive lock serializes the in-place rewrites AND each flush
    re-reads the on-disk manifest under the lock before merging, so
    concurrent writers UNION — no process's flushed rows are lost, the
    manifest stays consistent, and a fresh open sees everything."""
    root = str(tmp_path / "store")
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(root, partition_size=256)
    ds.create_schema("t", SPEC)
    del ds

    ctx = mp.get_context("spawn")  # fork is unsafe under JAX threads
    procs = [
        ctx.Process(target=_writer_proc, args=(root, w, 5, 50))
        for w in range(2)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(120)
        assert p.exitcode == 0

    # union semantics: every flushed row from BOTH writers survives
    ds2 = FileSystemDataStore(root, partition_size=256)
    res = ds2.query("t", "INCLUDE")
    assert len(res.batch) == 2 * 5 * 50
    # structural integrity: manifest rows == readable rows, no dangling
    # part files
    part_files = []
    for dirpath, _, files in os.walk(os.path.join(root, "t")):
        part_files += [f for f in files if f.startswith("part-")]
    assert len(part_files) > 0
    st = ds2._types["t"]
    assert len(st.partitions) == len(part_files)
    assert sum(p.count for p in st.partitions) == len(res.batch)
