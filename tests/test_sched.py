"""Device query scheduler: micro-batch scan fusion (fewer launches than
queries, serial-exact results), backpressure (429, never deadlocks),
deadline expiry, priority lanes, tenant fairness — plus regression tests
for the partition-cache aliasing, NaT floordiv, and DBF-date fixes that
ride this PR."""

import json
import threading
import urllib.error
import urllib.request
from urllib.parse import quote

import numpy as np
import pytest

from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.sched import (
    LANE_BATCH,
    DeadlineExpired,
    FusableQuery,
    QueryScheduler,
    RejectedError,
    SchedConfig,
)
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


def _fill(ds, type_name="gdelt", n=3000, seed=5):
    ds.create_schema(type_name, SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(type_name, {
        "name": rng.choice(["a", "b"], n),
        "dtg": t0 + rng.integers(0, 10**8, n),
        "geom": np.stack(
            [rng.uniform(-60, 60, n), rng.uniform(-40, 40, n)], axis=1
        ),
    }, fids=np.arange(n))


@pytest.fixture(scope="module")
def resident_di():
    from geomesa_tpu.device_cache import StreamingDeviceIndex

    ds = MemoryDataStore()
    _fill(ds)
    return ds, StreamingDeviceIndex(ds, "gdelt", z_planes=True)


QUERIES = [
    f"BBOX(geom, {x0}, {y0}, {x0 + 18}, {y0 + 15})"
    for x0, y0 in [(-50, -30), (-20, -10), (0, 0), (15, 5),
                   (-40, 10), (5, -25), (-10, -5), (25, 15)]
]


def _gate_scheduler(**cfg):
    """Scheduler with one worker parked on a gate, so later submissions
    pile into the queue deterministically."""
    sched = QueryScheduler(SchedConfig(
        max_inflight=1, default_deadline_ms=None, **cfg
    ))
    gate = threading.Event()
    started = threading.Event()
    sched.submit(fn=lambda: (started.set(), gate.wait(10)) and None)
    assert started.wait(5), "worker never claimed the blocker"
    return sched, gate


# -- micro-batch fusion ------------------------------------------------------


def test_fused_device_results_match_serial(resident_di):
    """The batched launch (counts AND demuxed feature sets) is exactly
    the serial loose execution, query by query."""
    _, di = resident_di
    serial = [di.count(q, loose=True) for q in QUERIES]
    assert sum(serial) > 0  # the windows actually hit data
    fused = di.fused_loose_counts(QUERIES, loose=True)
    assert fused == serial
    batches = di.fused_loose_query(QUERIES, loose=True)
    assert batches is not None
    for q, got in zip(QUERIES, batches):
        want = di.query(q, loose=True)
        np.testing.assert_array_equal(got.fids, want.fids)


def test_fused_declines_unanswerable_groups(resident_di):
    """A filter the key planes cannot answer poisons the whole group:
    fusion declines (None) and callers run serial — never wrong."""
    _, di = resident_di
    assert di.fused_loose_counts(
        [QUERIES[0], "name = 'a'"], loose=True
    ) is None
    assert di.fused_loose_counts(QUERIES[:2], loose=False) is None


def test_scheduler_fuses_concurrent_queries(resident_di):
    """K compatible queued queries execute in strictly fewer device
    launches than K, with per-query results identical to serial."""
    _, di = resident_di
    serial = [di.count(q, loose=True) for q in QUERIES]
    sched, gate = _gate_scheduler(fusion_window_ms=25.0)
    try:
        reqs = [
            sched.submit(fuse=FusableQuery(di, q, "count", loose=True))
            for q in QUERIES
        ]
        gate.set()
        got = [sched.wait(r) for r in reqs]
        assert got == serial
        assert sched.fused_queries >= len(QUERIES)
        # 1 launch for the gate blocker + the fused group(s): strictly
        # fewer than one launch per query
        assert sched.launches < 1 + len(QUERIES)
        snap = sched.snapshot()
        assert snap["fusion_factor"] is not None
        assert snap["fusion_factor"] > 1.0
    finally:
        gate.set()
        sched.shutdown()


# -- admission control / backpressure ----------------------------------------


def test_backpressure_rejects_and_never_deadlocks():
    sched, gate = _gate_scheduler(max_queue=2, fusion_window_ms=0)
    try:
        r1 = sched.submit(fn=lambda: 1)
        r2 = sched.submit(fn=lambda: 2)
        with pytest.raises(RejectedError) as ei:
            sched.submit(fn=lambda: 3)
        assert ei.value.retry_after_s > 0
        gate.set()
        assert sched.wait(r1) == 1
        assert sched.wait(r2) == 2
        assert sched.rejected == 1
        # queue drained: admission opens again
        assert sched.run(fn=lambda: 4) == 4
    finally:
        gate.set()
        sched.shutdown()


def test_deadline_expires_in_queue():
    sched, gate = _gate_scheduler(fusion_window_ms=0)
    try:
        req = sched.submit(fn=lambda: 1, deadline_ms=30.0)
        with pytest.raises(DeadlineExpired):
            sched.wait(req)
        assert sched.expired >= 1
        gate.set()
        # the expired request is never executed, the queue keeps moving
        assert sched.run(fn=lambda: 2) == 2
    finally:
        gate.set()
        sched.shutdown()


def test_priority_and_tenant_fairness():
    sched, gate = _gate_scheduler(fusion_window_ms=0)
    try:
        order: list = []
        rs = []
        # batch lane first in, interactive still served first
        rs.append(sched.submit(
            fn=lambda: order.append("batch"), lane=LANE_BATCH
        ))
        # noisy tenant A enqueues 3 before quiet tenant B's one; round-
        # robin serves B after A's first, not after A's third
        for i in range(3):
            rs.append(sched.submit(
                fn=lambda i=i: order.append(f"A{i}"), tenant="A"
            ))
        rs.append(sched.submit(fn=lambda: order.append("B0"), tenant="B"))
        gate.set()
        for r in rs:
            sched.wait(r)
        assert order[-1] == "batch"  # interactive lane drains first
        assert order.index("B0") < order.index("A2")  # fairness rotation
    finally:
        gate.set()
        sched.shutdown()


# -- server integration ------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, dict(r.headers), r.read()


def test_server_queue_full_returns_429():
    from geomesa_tpu.server import serve_background

    ds = MemoryDataStore()
    _fill(ds, n=50)
    server, _ = serve_background(
        ds, sched=SchedConfig(max_queue=0, max_inflight=1)
    )
    host, port = server.server_address[:2]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://{host}:{port}/count/gdelt?cql=INCLUDE")
        assert ei.value.code == 429
        assert float(ei.value.headers["Retry-After"]) > 0
    finally:
        server.shutdown()


def test_server_concurrent_fusion_and_stats_endpoint():
    """End to end: concurrent loose bbox counts against a resident
    scheduled server return serial-exact answers, /stats/sched reports a
    fusion factor above 1 (fewer launches than queries)."""
    from geomesa_tpu.server import serve_background

    ds = MemoryDataStore()
    _fill(ds, n=2000, seed=11)
    server, _ = serve_background(
        ds, resident=True,
        sched=SchedConfig(
            max_inflight=1, fusion_window_ms=25.0, max_queue=512
        ),
    )
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    urls = [
        f"{base}/count/gdelt?cql={quote(q)}&loose=1" for q in QUERIES[:4]
    ]
    try:
        # warmup doubles as the serially-executed oracle (a lone request
        # is a group of one: plain serial execution)
        expect = [json.loads(_get(u)[2])["count"] for u in urls]
        di = server.RequestHandlerClass._resident_cache["gdelt"]
        assert expect == [
            di.count(q, loose=True) for q in QUERIES[:4]
        ]
        bad: list = []
        lock = threading.Lock()

        def worker(tid):
            for i in range(6):
                j = (tid + i) % len(urls)
                got = json.loads(_get(urls[j])[2])["count"]
                if got != expect[j]:
                    with lock:
                        bad.append((j, got, expect[j]))

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not bad, bad
        status, _, body = _get(f"{base}/stats/sched")
        assert status == 200
        doc = json.loads(body)
        assert doc["queries"] >= 52  # 4 warm + 48 concurrent
        assert doc["launches"] < doc["queries"]
        assert doc["fusion_factor"] > 1.0
        assert doc["rejected"] == 0
        # the scheduler counters also reach the Prometheus registry
        _, _, metrics_body = _get(f"{base}/metrics")
        assert b"geomesa_sched_launches_total" in metrics_body
    finally:
        server.shutdown()


# -- satellite regressions ---------------------------------------------------


def test_query_partitions_does_not_alias_partition_cache(tmp_path):
    """A full-match query_partitions yield must be a copy: mutating it
    cannot tear the FS store's partition cache (ADVICE round 5)."""
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(str(tmp_path), partition_size=4096)
    _fill(ds, n=500)
    ds.flush("gdelt")
    before = ds.query("gdelt", "INCLUDE").batch.columns["name"].copy()
    parts = list(ds.query_partitions("gdelt"))
    assert parts
    for b in parts:
        b.columns["name"][:] = "corrupted"
    after = ds.query("gdelt", "INCLUDE").batch.columns["name"]
    np.testing.assert_array_equal(after, before)


def test_floordiv_exact_with_nat_sentinel():
    """INT64_MIN (datetime64 NaT) must route to the exact // path: the
    old np.abs guard overflowed it back negative and took the float
    reciprocal path (ADVICE round 5)."""
    from geomesa_tpu.curves.binnedtime import WEEK_MS, _floordiv_i64

    rng = np.random.default_rng(3)
    a = rng.integers(0, 10**12, 1 << 16).astype(np.int64)
    a[0] = np.iinfo(np.int64).min  # NaT sentinel
    a[1] = np.iinfo(np.int64).min + 1
    np.testing.assert_array_equal(_floordiv_i64(a, WEEK_MS), a // WEEK_MS)
    np.testing.assert_array_equal(_floordiv_i64(a, 1000), a // 1000)


def test_dbf_header_last_update_date_is_current():
    """The DBF header packs years-since-1900: a hardcoded 26 decoded as
    1926. It now derives from today (ADVICE round 5)."""
    import datetime

    from geomesa_tpu.convert.shp import write_shp
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType

    sft = SimpleFeatureType.create("t", "name:String,*geom:Point")
    batch = FeatureBatch.from_columns(
        sft, {"name": ["x"], "geom": np.array([[1.0, 2.0]])}, fids=[0]
    )
    _, _, dbf = write_shp(batch)
    today = datetime.date.today()
    assert dbf[1] == min(today.year - 1900, 255)
    assert dbf[2] == today.month
    assert dbf[3] == today.day
