"""End-to-end store tests: schema -> write -> plan -> scan -> results.

The oracle is brute-force host evaluation of the full filter over all data
(result sets must be identical -- the "bit-identical to the Accumulo scan"
bar at the semantic level)."""

import numpy as np
import pytest

from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.query.plan import Query
from geomesa_tpu.store import MemoryDataStore

SPEC = "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"


def make_store(n=20000, seed=11, partition_size=4096):
    store = MemoryDataStore(partition_size=partition_size)
    sft = store.create_schema("gdelt", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    store.write(
        "gdelt",
        {
            "name": rng.choice(["alpha", "beta", "gamma"], n),
            "count": rng.integers(0, 100, n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return store


FILTERS = [
    "BBOX(geom, -5, 42, 8, 51) AND dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z",
    "BBOX(geom, -5, 42, 8, 51)",
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-07T00:00:00Z",
    "INTERSECTS(geom, POLYGON ((-10 30, 20 30, 20 60, -10 60, -10 30))) AND count > 50",
    "BBOX(geom, -5, 42, 8, 51) AND name = 'alpha'",
    "count BETWEEN 10 AND 20",
    "name = 'beta'",
    "INCLUDE",
    "BBOX(geom, 100, -80, 170, -40) OR BBOX(geom, -5, 42, 8, 51)",
    "dtg AFTER 2020-02-20T00:00:00Z AND count < 10 AND name LIKE 'ga%'",
]


@pytest.fixture(scope="module")
def store():
    return make_store()


@pytest.mark.parametrize("ecql", FILTERS)
def test_results_match_oracle(store, ecql):
    st = store._state("gdelt")
    store._flush(st)
    expected = np.sort(st.data.fids[evaluate_host(parse_ecql(ecql), st.data)])
    res = store.query("gdelt", ecql)
    got = np.sort(res.batch.fids)
    np.testing.assert_array_equal(got, expected)


def test_z3_chosen_for_bbox_time(store):
    plan = store.plan("gdelt", FILTERS[0])
    assert plan.index_name == "z3"
    assert plan.ranges, "expected pruning ranges"


def test_z2_chosen_for_bbox_only(store):
    plan = store.plan("gdelt", "BBOX(geom, -5, 42, 8, 51)")
    assert plan.index_name == "z2"


def test_pruning_actually_prunes(store):
    res = store.query("gdelt", FILTERS[2])  # narrow 2-day window
    assert res.scanned < res.total, "time-window query should prune partitions"


def test_explain_output(store):
    text = store.explain("gdelt", FILTERS[0])
    assert "Chosen index: z3" in text
    assert "Ranges:" in text


def test_max_features_and_sort(store):
    res = store.query(
        "gdelt",
        Query(filter="count >= 0", sort_by="count", sort_desc=True, max_features=7),
    )
    assert len(res) == 7
    c = res.batch.column("count")
    assert np.all(np.diff(c) <= 0)


def test_projection(store):
    res = store.query("gdelt", Query(filter=FILTERS[1], properties=["count", "geom"]))
    assert res.batch.sft.attribute_names == ["count", "geom"]


def test_get_by_ids(store):
    b = store.get_by_ids("gdelt", [5, 17, 19999])
    assert len(b) == 3
    np.testing.assert_array_equal(np.sort(b.fids), [5, 17, 19999])


def test_incremental_write_and_delete():
    store = make_store(n=1000)
    store.write(
        "gdelt",
        {
            "name": ["omega"],
            "count": [1],
            "dtg": [parse_instant("2020-01-10T00:00:00")],
            "geom": np.array([[2.0, 48.0]]),
        },
        fids=[99999],
    )
    assert store.count("gdelt", "name = 'omega'") == 1
    assert store.delete("gdelt", [99999]) == 1
    assert store.count("gdelt", "name = 'omega'") == 0


def test_empty_result(store):
    res = store.query("gdelt", "BBOX(geom, 0, 0, 0.0001, 0.0001) AND name = 'nope'")
    assert len(res) == 0


def test_attribute_index():
    store = MemoryDataStore(partition_size=512)
    store.create_schema(
        "t", "tag:String:index=true,count:Int,dtg:Date,*geom:Point"
    )
    rng = np.random.default_rng(2)
    n = 5000
    store.write(
        "t",
        {
            "tag": rng.choice(["a", "b", "c", "d"], n),
            "count": rng.integers(0, 10, n),
            "dtg": rng.integers(0, 10**10, n),
            "geom": np.stack([rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], 1),
        },
    )
    plan = store.plan("t", "tag = 'b'")
    assert plan.index_name == "attr:tag"
    res = store.query("t", "tag = 'b'")
    assert np.all(res.batch.column("tag") == "b")
    st = store._state("t")
    expected = int((st.data.column("tag") == "b").sum())
    assert len(res) == expected
    assert res.scanned < len(st.data)
