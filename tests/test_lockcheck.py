"""Runtime lock-order checker (analysis/lockcheck.py): seeded ABBA
inversion detection, seeded held-across-blocking detection, isolation of
seeded checkers from the global report, and the clean-run invariant over
a real sched + prefetch + store + tracing workload (plus whatever the
rest of the suite exercised before this file ran -- the conftest arms
the checker process-wide)."""

import os
import threading

import numpy as np
import pytest

from geomesa_tpu.analysis import lockcheck
from geomesa_tpu.analysis.lockcheck import CHECKER, CheckedLock, LockCheck


def test_checker_enabled_for_the_suite():
    """The conftest must have armed the checker BEFORE package imports:
    module-level locks (metrics, failpoints) only instrument then."""
    assert lockcheck.enabled()
    # module-level locks register at first import (forced here: in a
    # filtered run this test may be the first to touch these modules)
    import geomesa_tpu.failpoints  # noqa: F401
    import geomesa_tpu.metrics  # noqa: F401

    rep = CHECKER.report()
    # the package's own migrated locks are registered by name
    assert "metrics.registry" in rep["locks"]
    assert "failpoints" in rep["locks"]


def test_seeded_abba_inversion_reports_a_cycle():
    chk = LockCheck("seed-abba")
    a = CheckedLock("A", checker=chk)
    b = CheckedLock("B", checker=chk)

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    # sequential threads: the INVERSION is recorded without any actual
    # deadlock -- exactly the point of graph-based detection
    for fn in (order_ab, order_ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    rep = chk.report()
    assert len(rep["cycles"]) == 1
    assert set(rep["cycles"][0]["locks"]) == {"A", "B"}
    # both directions' threads are named in the report
    assert set(rep["cycles"][0]["edges"]) == {"A->B", "B->A"}


def test_seeded_lock_held_across_open_is_flagged():
    lockcheck.install_probes()
    chk = LockCheck("seed-blocking")
    c = CheckedLock("C", checker=chk)
    with c:
        with open(os.devnull) as fh:
            fh.read(0)
    rep = chk.report()
    assert any(
        b["lock"] == "C" and b["op"] == "open" for b in rep["blocking"]
    )


def test_blocking_ok_lock_is_exempt():
    lockcheck.install_probes()
    chk = LockCheck("seed-exempt")
    d = CheckedLock("D", checker=chk, blocking_ok=True)
    with d:
        with open(os.devnull) as fh:
            fh.read(0)
    assert chk.report()["blocking"] == []


def test_seeded_findings_do_not_pollute_the_global_checker():
    before = CHECKER.report()
    chk = LockCheck("seed-isolated")
    a = CheckedLock("iso-A", checker=chk)
    b = CheckedLock("iso-B", checker=chk)
    with a, b:
        pass
    with b, a:
        pass
    after = CHECKER.report()
    assert len(after["cycles"]) == len(before["cycles"])
    assert "iso-A" not in after["locks"]
    assert chk.report()["cycles"]  # the seeded checker saw it


def test_reentrant_lock_records_no_self_cycle():
    chk = LockCheck("seed-rlock")
    r = CheckedLock("R", checker=chk, reentrant=True)
    with r:
        with r:
            pass
    rep = chk.report()
    assert rep["cycles"] == []
    assert rep["edges"] == []


def test_checked_lock_is_plain_when_disabled(monkeypatch):
    from geomesa_tpu.locking import checked_lock, checked_rlock

    monkeypatch.delenv(lockcheck.ENV_VAR, raising=False)
    assert isinstance(checked_lock("x"), type(threading.Lock()))
    assert isinstance(checked_rlock("x"), type(threading.RLock()))
    monkeypatch.setenv(lockcheck.ENV_VAR, "1")
    assert isinstance(checked_lock("x"), CheckedLock)


def test_clean_run_over_sched_prefetch_store_tracing(tmp_path):
    """Drive the serving stack end to end -- FS store flush + prefetch
    pipeline reads, a traced query, a scheduler run + drain -- and
    assert the GLOBAL checker stays clean: zero lock-order cycles, zero
    held-across-blocking events. Running late in the suite, this also
    covers every suite that ran before it (the conftest prints the same
    report at session end)."""
    from geomesa_tpu.conf import prop_override
    from geomesa_tpu.sched import QueryScheduler, SchedConfig
    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.tracing import TRACER

    store = FileSystemDataStore(str(tmp_path), partition_size=512)
    store.create_schema(
        "pts", "name:String,dtg:Date,*geom:Point:srid=4326"
    )
    rng = np.random.default_rng(7)
    n = 4000
    store.write(
        "pts",
        {
            "name": rng.choice(["a", "b"], n),
            "dtg": rng.integers(1_577_836_800_000, 1_580_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    store.flush("pts")
    with prop_override("io.workers", 2):
        with TRACER.trace("lockcheck-clean-run"):
            res = store.query("pts", "BBOX(geom, -5, -5, 5, 5)")
    assert len(res) > 0
    sched = QueryScheduler(SchedConfig(max_inflight=2, max_queue=8))
    try:
        reqs = [
            sched.submit(fn=lambda i=i: i * i, deadline_ms=None)
            for i in range(8)
        ]
        assert [sched.wait(r) for r in reqs] == [i * i for i in range(8)]
    finally:
        sched.close(timeout=5.0)
    rep = CHECKER.report()
    assert rep["cycles"] == [], rep["cycles"]
    assert rep["blocking"] == [], rep["blocking"]
    assert rep["acquisitions"] > 0


def test_lockcheck_metrics_published():
    from geomesa_tpu import metrics

    CHECKER.report()  # publishes the gauges
    assert metrics.lockcheck_locks.value() > 0
    assert metrics.lockcheck_cycles.value() == 0
    assert metrics.lockcheck_blocking.value() == 0
    text = metrics.REGISTRY.prometheus_text()
    assert "geomesa_lockcheck_locks" in text


def test_scheduler_close_drains_before_join():
    """The close() satellite: queued work COMPLETES (vs shutdown, which
    fails it), and the workers are joined."""
    from geomesa_tpu.sched import QueryScheduler, SchedConfig

    done = []
    sched = QueryScheduler(SchedConfig(max_inflight=1, max_queue=16))
    reqs = [
        sched.submit(fn=lambda i=i: done.append(i), deadline_ms=None)
        for i in range(6)
    ]
    sched.close(timeout=10.0)
    assert sorted(done) == list(range(6))
    for r in reqs:
        assert r.state == "done" and r.error is None
    assert all(not w.is_alive() for w in sched._workers)
    # idempotent, and post-close submits fail loudly
    sched.close(timeout=1.0)
    with pytest.raises(RuntimeError):
        sched.submit(fn=lambda: None)
