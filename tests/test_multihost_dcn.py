"""Two-process DCN test: jax.distributed over a local coordinator.

VERDICT round-1 item 6: parallel/multihost.py had only ever run with
jax.process_count() == 1. This spawns two real processes (4 virtual CPU
devices each), initializes the distributed runtime, and runs the
host_batches_to_global feed + sharded_count_scan across the 8-device
global mesh with cross-process collectives.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import pytest

_WORKER = Path(__file__).with_name("_dcn_worker.py")
_REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="the CPU backend has no multiprocess collectives (XLA "
    "multiprocess runtime unimplemented for CPU): the 2-process DCN "
    "exchange cannot initialize on a CPU-only harness",
)
def test_two_process_scan_over_dcn():
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_NUM_CPU_DEVICES")
    }
    env["PYTHONPATH"] = str(_REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=str(_REPO),
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"DCN workers hung; partial output: {outs}")
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"proc{i} rc={rc}\nstdout:\n{out}\nstderr:\n{err}"
        assert f"proc{i} DCN scan OK" in out, (out, err)
    # both processes computed the same replicated global count
    c0 = outs[0][1].split("count=")[1].strip()
    c1 = outs[1][1].split("count=")[1].strip()
    assert c0 == c1
