"""One-dispatch resident kNN (VERDICT round-3 item 2): DeviceIndex.knn
is a single fused distance + mask + lax.top_k dispatch; it must match the
expanding-window store search (ref KNNQuery, SURVEY section 2.4
[UNVERIFIED - empty reference mount]) on results, tie rules, radius caps,
filters, auths and eviction.
"""

from __future__ import annotations

import numpy as np
import pytest

from geomesa_tpu.device_cache import DeviceIndex, StreamingDeviceIndex
from geomesa_tpu.process.knn import _dist_deg, knn
from geomesa_tpu.store.memory import MemoryDataStore

T0 = 1_577_836_800_000


def _store(n=4000, seed=3, lon=(-180, 180), lat=(-90, 90)):
    rng = np.random.default_rng(seed)
    ds = MemoryDataStore()
    ds.create_schema("ais", "val:Int,dtg:Date,*geom:Point:srid=4326")
    ds.write("ais", {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(T0, T0 + 30 * 86_400_000, n),
        "geom": np.stack(
            [rng.uniform(*lon, n), rng.uniform(*lat, n)], axis=1
        ).astype(np.float32),
    })
    return ds


def _oracle(ds, px, py, k, pred=None, max_r=45.0):
    """Host float32-coordinate oracle with the same metric and caps."""
    batch = ds.query("ais").batch
    x, y = batch.point_coords("geom")
    x = x.astype(np.float32)
    y = y.astype(np.float32)
    keep = (np.abs(x - np.float32(px)) <= max_r) & (
        np.abs(y - np.float32(py)) <= max_r
    )
    if pred is not None:
        keep &= pred(batch)
    d = _dist_deg(x, y, np.float32(px), np.float32(py))
    idx = np.nonzero(keep)[0]
    order = idx[np.argsort(d[idx], kind="stable")[:k]]
    return batch.fids[order], d[order]


def test_one_dispatch_matches_oracle():
    ds = _store()
    di = DeviceIndex(ds, "ais")
    batch, dists = di.knn(2.0, 48.0, 50)
    fids, want = _oracle(ds, 2.0, 48.0, 50)
    np.testing.assert_array_equal(batch.fids, fids)
    np.testing.assert_allclose(dists, want, rtol=1e-5)


def test_process_routes_to_resident_one_dispatch(monkeypatch):
    """knn(..., device_index=) must answer via DeviceIndex.knn (one
    dispatch), never the probing loop."""
    ds = _store()
    di = DeviceIndex(ds, "ais")
    calls = []
    orig = DeviceIndex.knn

    def spy(self, *a, **kw):
        calls.append(a)
        return orig(self, *a, **kw)

    monkeypatch.setattr(DeviceIndex, "knn", spy)
    monkeypatch.setattr(
        DeviceIndex, "bbox_window_query",
        lambda *a, **k: pytest.fail("expanding window probed"),
    )
    batch, d = knn(ds, "ais", 2.0, 48.0, k=10, device_index=di)
    assert len(calls) == 1 and len(batch) == 10


def test_tie_at_kth_distance_prefers_earlier_row():
    """Exact duplicate points at the k-th distance: top_k must keep the
    earlier row, the host stable-argsort rule."""
    ds = MemoryDataStore()
    ds.create_schema("ais", "val:Int,dtg:Date,*geom:Point:srid=4326")
    # rows 0,1 at the target; rows 2,3,4 identical at distance 1.0
    pts = np.array([
        [0.0, 0.0], [0.1, 0.0], [1.0, 0.0], [1.0, 0.0], [1.0, 0.0],
    ], np.float32)
    ds.write("ais", {
        "val": np.arange(5), "dtg": np.full(5, T0), "geom": pts,
    })
    di = DeviceIndex(ds, "ais")
    batch, d = di.knn(0.0, 0.0, 3)
    assert list(batch.column("val")) == [0, 1, 2]  # row 2 wins the tie
    batch4, _ = di.knn(0.0, 0.0, 4)
    assert list(batch4.column("val")) == [0, 1, 2, 3]


def test_k_exceeding_rows_returns_all():
    ds = _store(n=7)
    di = DeviceIndex(ds, "ais")
    # radius cap wider than the globe: every row is a candidate
    batch, d = di.knn(0.0, 0.0, 100, max_radius_deg=360.0)
    assert len(batch) == 7
    assert np.all(np.diff(d) >= 0)


def test_max_radius_box_excludes_far_rows():
    ds = _store(n=500, seed=5)
    di = DeviceIndex(ds, "ais")
    batch, d = di.knn(0.0, 0.0, 500, max_radius_deg=5.0)
    x, y = batch.point_coords("geom")
    assert len(batch) < 500
    assert np.all(np.abs(x) <= 5.0) and np.all(np.abs(y) <= 5.0)
    fids, _ = _oracle(ds, 0.0, 0.0, 500, max_r=5.0)
    np.testing.assert_array_equal(batch.fids, fids)


def test_base_filter_applies_on_device():
    ds = _store()
    di = DeviceIndex(ds, "ais")
    batch, d = di.knn(10.0, 20.0, 25, query="val < 50")
    assert len(batch) == 25 and np.all(batch.column("val") < 50)
    fids, _ = _oracle(
        ds, 10.0, 20.0, 25, pred=lambda b: b.column("val") < 50
    )
    np.testing.assert_array_equal(batch.fids, fids)
    # and through the process surface
    b2, _ = knn(ds, "ais", 10.0, 20.0, k=25, base_filter="val < 50",
                device_index=di)
    np.testing.assert_array_equal(b2.fids, fids)


def test_host_residual_filter_falls_back_to_windows():
    """A filter with host-side residuals cannot fuse: DeviceIndex.knn
    returns None and the process path still answers via windows."""
    ds2 = MemoryDataStore()
    ds2.create_schema("ais", "name:String,dtg:Date,*geom:Point:srid=4326")
    n = 200
    rng = np.random.default_rng(0)
    ds2.write("ais", {
        "name": np.array(["ship-%d" % i for i in range(n)], object),
        "dtg": np.full(n, T0),
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
        ),
    })
    di2 = DeviceIndex(ds2, "ais")
    assert di2.knn(0.0, 0.0, 5, query="name LIKE 'ship-1%'") is None
    batch, _ = knn(ds2, "ais", 0.0, 0.0, k=5,
                   base_filter="name LIKE 'ship-1%'", device_index=di2)
    assert len(batch) == 5
    assert all(str(v).startswith("ship-1") for v in batch.column("name"))


def test_auths_fail_closed_on_resident_knn():
    from geomesa_tpu.features.batch import FeatureBatch

    ds = MemoryDataStore()
    ds.create_schema("ais", "val:Int,dtg:Date,*geom:Point:srid=4326")
    n = 300
    rng = np.random.default_rng(1)
    vis = np.array([None, "secret"], object)[rng.integers(0, 2, n)]
    batch = FeatureBatch.from_columns(
        ds.get_schema("ais"),
        {
            "val": rng.integers(0, 9, n),
            "dtg": np.full(n, T0),
            "geom": np.stack(
                [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
            ),
        },
        fids=np.arange(n),
    ).with_visibility(vis)
    ds.write("ais", batch)
    di = DeviceIndex(ds, "ais")
    got_none, _ = di.knn(0.0, 0.0, n)
    got_all, _ = di.knn(0.0, 0.0, n, auths=("secret",))
    labeled = sum(1 for v in vis if v is not None)
    assert len(got_none) == n - labeled  # fail closed
    assert len(got_all) == n


def test_streaming_eviction_respected():
    ds = _store(n=400, seed=9)
    di = StreamingDeviceIndex(ds, "ais")
    first, _ = di.knn(0.0, 0.0, 5)
    di.evict(first.fids[:2])
    after, _ = di.knn(0.0, 0.0, 5)
    assert not set(first.fids[:2].tolist()) & set(after.fids.tolist())


def test_empty_index():
    ds = MemoryDataStore()
    ds.create_schema("ais", "val:Int,dtg:Date,*geom:Point:srid=4326")
    di = DeviceIndex(ds, "ais")
    batch, d = di.knn(0.0, 0.0, 5)
    assert len(batch) == 0 and len(d) == 0
