"""Device-resident index: pinned columns, repeated queries, refresh."""

import numpy as np

from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"


def _store(n=20000, seed=23):
    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "val": rng.integers(0, 100, n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return ds


def test_resident_count_and_query_match_oracle():
    ds = _store()
    di = DeviceIndex(ds, "t")
    assert len(di) == 20000 and di.nbytes > 0
    all_batch = ds.query("t").batch
    for ecql in [
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z",
        "val >= 50 AND BBOX(geom, 0, 0, 90, 90)",
        "BBOX(geom, -180, -90, 180, 90)",
    ]:
        expect = evaluate_host(parse_ecql(ecql), all_batch)
        assert di.count(ecql) == int(expect.sum()), ecql
        got = di.query(ecql)
        np.testing.assert_array_equal(
            np.sort(got.fids), np.sort(all_batch.fids[expect])
        )


def test_residual_filters_still_exact():
    ds = _store(n=2000)
    di = DeviceIndex(ds, "t")
    # string equality is not a device predicate -> residual path
    ecql = "name = 'a' AND BBOX(geom, -90, -45, 90, 45)"
    all_batch = ds.query("t").batch
    expect = evaluate_host(parse_ecql(ecql), all_batch)
    assert di.count(ecql) == int(expect.sum())
    np.testing.assert_array_equal(
        np.sort(di.query(ecql).fids), np.sort(all_batch.fids[expect])
    )


def test_refresh_after_write():
    ds = _store(n=100)
    di = DeviceIndex(ds, "t")
    assert di.count("INCLUDE") == 100
    ds.write(
        "t",
        {
            "name": ["z"],
            "val": [1],
            "dtg": [parse_instant("2020-01-15T00:00:00")],
            "geom": np.array([[1.0, 2.0]]),
        },
        fids=["extra"],
    )
    assert di.count("INCLUDE") == 100  # stale until refresh
    di.refresh()
    assert di.count("INCLUDE") == 101


def test_attach_live_refreshes():
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stream import LiveFeatureStore

    sft = SimpleFeatureType.create("t", SPEC)
    live = LiveFeatureStore(sft)

    class LiveAdapter:
        """Minimal store facade over the live layer for DeviceIndex."""

        def get_schema(self, _):
            return sft

        def query(self, _, q=None):
            from geomesa_tpu.query.runner import QueryResult

            b = live.snapshot()
            return QueryResult(b, None, len(b), len(b))

    di = DeviceIndex(LiveAdapter(), "t")
    di.attach_live(live)
    live.put(
        {
            "name": ["a"],
            "val": [5],
            "dtg": [0],
            "geom": np.array([[3.0, 4.0]]),
        },
        ["f0"],
    )
    assert di.count("INCLUDE") == 1  # listener refreshed the residency


def test_detach_live_listener():
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stream import LiveFeatureStore

    sft = SimpleFeatureType.create("t", SPEC)
    live = LiveFeatureStore(sft)

    calls = []

    class Adapter:
        def get_schema(self, _):
            return sft

        def query(self, _, q=None):
            from geomesa_tpu.query.runner import QueryResult

            calls.append(1)
            b = live.snapshot()
            return QueryResult(b, None, len(b), len(b))

    di = DeviceIndex(Adapter(), "t")
    detach = di.attach_live(live)
    live.put({"name": ["a"], "val": [1], "dtg": [0],
              "geom": np.zeros((1, 2))}, ["f0"])
    n_after_put = len(calls)
    detach()
    live.put({"name": ["b"], "val": [2], "dtg": [0],
              "geom": np.zeros((1, 2))}, ["f1"])
    assert len(calls) == n_after_put  # no refresh after detach


# -- streaming delta refresh (VERDICT round-1 item 9) -----------------------


def _oracle(ds, ecql):
    b = ds.query("t").batch
    return b, evaluate_host(parse_ecql(ecql), b)


class TestStreamingDeviceIndex:
    ECQL = (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z"
    )

    def _batch(self, sft, n, seed, fid0=0):
        from geomesa_tpu.features.batch import FeatureBatch

        rng = np.random.default_rng(seed)
        t0 = parse_instant("2020-01-01T00:00:00")
        t1 = parse_instant("2020-03-01T00:00:00")
        return FeatureBatch.from_columns(
            sft,
            {
                "name": rng.choice(["a", "b", "c"], n),
                "val": rng.integers(0, 100, n),
                "dtg": rng.integers(t0, t1, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)],
                    axis=1,
                ),
            },
            fids=np.arange(fid0, fid0 + n),
        )

    def test_append_path_matches_full_restage(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=5000)
        di = StreamingDeviceIndex(ds, "t", capacity=1 << 15)
        base_restages = di.restages
        sft = ds.get_schema("t")
        for k in range(8):
            b = self._batch(sft, 500, seed=100 + k, fid0=100_000 + 500 * k)
            ds.write("t", dict(b.columns), fids=b.fids)
            di.append(b)
        assert di.restages == base_restages  # all appends took the delta path
        assert di.delta_appends == 8
        all_batch, expect = _oracle(ds, self.ECQL)
        assert len(di) == 9000
        assert di.count(self.ECQL) == int(expect.sum())
        np.testing.assert_array_equal(
            np.sort(di.query(self.ECQL).fids.astype(np.int64)),
            np.sort(all_batch.fids[expect].astype(np.int64)),
        )

    def test_growth_compacts_and_stays_exact(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=1000)
        di = StreamingDeviceIndex(ds, "t", capacity=1024)
        sft = ds.get_schema("t")
        for k in range(6):  # overflows 1024 quickly -> growth path
            b = self._batch(sft, 700, seed=7 + k, fid0=50_000 + 700 * k)
            ds.write("t", dict(b.columns), fids=b.fids)
            di.append(b)
        assert di.restages > 1
        all_batch, expect = _oracle(ds, self.ECQL)
        assert di.count(self.ECQL) == int(expect.sum())

    def test_evict_and_upsert(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=4000)
        di = StreamingDeviceIndex(ds, "t")
        di.evict(np.arange(1000, 1100))
        assert len(di) == 3900
        # count over INCLUDE sees only live rows
        assert di.count("INCLUDE") == 3900
        # upsert moves a fid's attributes; old row must not answer
        sft = ds.get_schema("t")
        b = self._batch(sft, 50, seed=5, fid0=0)  # overwrite fids 0..49
        b.columns["geom"][:] = [[170.0, 80.0]]  # park them far away
        di.upsert(b)
        assert len(di) == 3900
        got = di.query("BBOX(geom, 169, 79, 171, 81)")
        assert set(got.fids.astype(np.int64).tolist()) >= set(range(50))

    def test_residual_and_host_filters_respect_validity(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=2000)
        di = StreamingDeviceIndex(ds, "t")
        all_batch = ds.query("t").batch
        ecql = "name = 'a' AND BBOX(geom, -90, -45, 90, 45)"
        expect = evaluate_host(parse_ecql(ecql), all_batch)
        victims = all_batch.fids[expect][:20]
        di.evict(victims)
        assert di.count(ecql) == int(expect.sum()) - 20
        got = set(di.query(ecql).fids.tolist())
        assert not (got & set(victims.tolist()))
        # pure-host filter path too
        host_ecql = "name = 'a'"
        h_expect = evaluate_host(parse_ecql(host_ecql), all_batch)
        assert di.count(host_ecql) == int(h_expect.sum()) - 20

    def test_attach_live_applies_deltas_not_restages(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex
        from geomesa_tpu.features.sft import SimpleFeatureType
        from geomesa_tpu.query.runner import QueryResult
        from geomesa_tpu.stream import LiveFeatureStore

        sft = SimpleFeatureType.create("t", SPEC)
        live = LiveFeatureStore(sft)

        class Adapter:
            def get_schema(self, _):
                return sft

            def query(self, _, q=None):
                b = live.snapshot()
                return QueryResult(b, None, len(b), len(b))

        di = StreamingDeviceIndex(Adapter(), "t", capacity=4096)
        di.attach_live(live)
        base_restages = di.restages
        for k in range(10):
            live.put(
                {
                    "name": ["a"],
                    "val": [k],
                    "dtg": [parse_instant("2020-01-15T00:00:00")],
                    "geom": np.array([[float(k), 2.0]]),
                },
                [f"f{k}"],
            )
        assert len(di) == 10
        assert di.count("INCLUDE") == 10
        assert di.restages == base_restages  # puts rode the delta path
        live.remove(np.array(["f3", "f4"], dtype=object))
        assert len(di) == 8
        assert di.count("val >= 0") == 8
        # upsert via live layer: same fid, new position
        live.put(
            {
                "name": ["z"],
                "val": [99],
                "dtg": [parse_instant("2020-01-15T00:00:00")],
                "geom": np.array([[100.0, 50.0]]),
            },
            ["f0"],
        )
        assert len(di) == 8
        assert di.count("BBOX(geom, 99, 49, 101, 51)") == 1

    def test_sustained_ingest_rate(self):
        """The delta path must sustain ingest without per-append restaging:
        200 appends of 1k rows -> at most a handful of growth restages and
        a measured rows/sec figure (printed, not asserted -- CI machines
        vary)."""
        import time

        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=1000)
        sft = ds.get_schema("t")
        di = StreamingDeviceIndex(ds, "t", capacity=1 << 18)
        batches = [
            self._batch(sft, 1000, seed=k, fid0=1_000_000 + 1000 * k)
            for k in range(200)
        ]
        di.count(self.ECQL)  # compile before timing
        t0 = time.perf_counter()
        for b in batches:
            di.append(b)
        dt = time.perf_counter() - t0
        assert di.restages <= 2  # capacity hint absorbs the whole run
        assert len(di) == 201_000
        rate = 200_000 / dt
        print(f"\nsustained ingest: {rate:,.0f} rows/s over 200 appends")
        # correctness after the burst: mirror the appends into the store
        # first so the oracle sees the same rows
        for b in batches:
            ds.write("t", dict(b.columns), fids=b.fids)
        all_batch, expect = _oracle(ds, self.ECQL)
        assert di.count(self.ECQL) == int(expect.sum())


# -- loose (key-only) scans (ref geomesa.loose.bbox) ------------------------


class TestLooseZScan:
    ECQL = (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z"
    )

    def _cell_oracle(self, batch, ecql_env, window_ms):
        """Quantized-cell (loose) semantics computed independently."""
        from geomesa_tpu.curves.binnedtime import (
            bins_for_interval,
            to_binned_time,
        )
        from geomesa_tpu.curves.z3 import Z3SFC

        sfc = Z3SFC()
        x, y = batch.point_coords()
        dtg = batch.column("dtg")
        bins, off = to_binned_time(dtg, sfc.period)
        nx = np.asarray(sfc.lon.normalize(x)).astype(np.int64)
        ny = np.asarray(sfc.lat.normalize(y)).astype(np.int64)
        nt = np.asarray(sfc.time.normalize(off)).astype(np.int64)
        x0, y0, x1, y1 = ecql_env
        sp = (
            (nx >= int(sfc.lon.normalize(x0)))
            & (nx <= int(sfc.lon.normalize(x1)))
            & (ny >= int(sfc.lat.normalize(y0)))
            & (ny <= int(sfc.lat.normalize(y1)))
        )
        tm = np.zeros(len(batch), bool)
        for b, lo, hi in bins_for_interval(window_ms[0], window_ms[1], sfc.period):
            tm |= (
                (bins == b)
                & (nt >= int(sfc.time.normalize(lo)))
                & (nt <= int(sfc.time.normalize(hi)))
            )
        return sp & tm

    def test_loose_matches_cell_oracle_and_contains_exact(self):
        ds = _store(n=20000)
        di = DeviceIndex(ds, "t", z_planes=True)
        all_batch = ds.query("t").batch
        got = di.mask(self.ECQL, loose=True)
        w = (parse_instant("2020-01-10T00:00:00"),
             parse_instant("2020-02-01T00:00:00"))
        expect = self._cell_oracle(all_batch, (-10, 35, 30, 60), w)
        np.testing.assert_array_equal(got, expect)
        # loose is a superset of exact
        exact = evaluate_host(parse_ecql(self.ECQL), all_batch)
        assert not np.any(exact & ~got)
        assert di.count(self.ECQL, loose=True) == int(expect.sum())
        fids = di.query(self.ECQL, loose=True).fids
        np.testing.assert_array_equal(
            np.sort(fids), np.sort(all_batch.fids[expect])
        )

    def test_loose_prop_enables_globally(self):
        from geomesa_tpu.conf import prop_override

        ds = _store(n=3000)
        di = DeviceIndex(ds, "t", z_planes=True)
        exact = di.count(self.ECQL)
        with prop_override("query.loose.bbox", True):
            loose = di.count(self.ECQL)
        assert loose >= exact  # cell-granular superset

    def test_non_bbox_filters_fall_back(self):
        ds = _store(n=3000)
        di = DeviceIndex(ds, "t", z_planes=True)
        # val compare is not answerable from the key: loose must fall
        # back to the exact path and still be correct
        ecql = "val >= 50 AND BBOX(geom, 0, 0, 90, 90)"
        all_batch = ds.query("t").batch
        expect = evaluate_host(parse_ecql(ecql), all_batch)
        assert di.count(ecql, loose=True) == int(expect.sum())

    def test_bbox_only_uses_observed_bin_range(self):
        ds = _store(n=5000)
        di = DeviceIndex(ds, "t", z_planes=True)
        all_batch = ds.query("t").batch
        got = di.mask("BBOX(geom, -10, 35, 30, 60)", loose=True)
        t_lo = int(all_batch.column("dtg").min())
        t_hi = int(all_batch.column("dtg").max())
        expect = self._cell_oracle(
            all_batch, (-10, 35, 30, 60), (t_lo, t_hi)
        )
        np.testing.assert_array_equal(got, expect)

    def test_streaming_loose_respects_validity(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=4000)
        di = StreamingDeviceIndex(ds, "t", z_planes=True)
        before = di.count(self.ECQL, loose=True)
        hit_fids = di.query(self.ECQL, loose=True).fids
        di.evict(hit_fids[:10])
        assert di.count(self.ECQL, loose=True) == before - 10
        got = set(di.query(self.ECQL, loose=True).fids.tolist())
        assert not (got & set(hit_fids[:10].tolist()))

    def test_streaming_append_widens_bins(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex
        from geomesa_tpu.features.batch import FeatureBatch

        ds = _store(n=2000)
        di = StreamingDeviceIndex(ds, "t", z_planes=True, capacity=8192)
        sft = ds.get_schema("t")
        # append rows in a LATER time bin than any original row
        t_new = parse_instant("2020-06-15T00:00:00")
        b = FeatureBatch.from_columns(
            sft,
            {
                "name": ["x"] * 50,
                "val": np.arange(50),
                "dtg": np.full(50, t_new),
                "geom": np.tile([[5.0, 50.0]], (50, 1)),
            },
            fids=np.arange(90000, 90050),
        )
        di.append(b)
        q = ("BBOX(geom, 0, 45, 10, 55) AND "
             "dtg DURING 2020-06-14T00:00:00Z/2020-06-16T00:00:00Z")
        assert di.count(q, loose=True) == 50

    def test_z2_planes_for_dateless_schema(self):
        ds = MemoryDataStore()
        ds.create_schema("p", "val:Int,*geom:Point")
        rng = np.random.default_rng(3)
        n = 5000
        ds.write(
            "p",
            {
                "val": rng.integers(0, 10, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], 1
                ),
            },
            fids=np.arange(n),
        )
        di = DeviceIndex(ds, "p", z_planes=True)
        all_batch = ds.query("p").batch
        got = di.mask("BBOX(geom, -10, 35, 30, 60)", loose=True)
        from geomesa_tpu.curves.z2 import Z2SFC

        sfc = Z2SFC()
        x, y = all_batch.point_coords()
        nx = np.asarray(sfc.lon.normalize(x)).astype(np.int64)
        ny = np.asarray(sfc.lat.normalize(y)).astype(np.int64)
        expect = (
            (nx >= int(sfc.lon.normalize(-10)))
            & (nx <= int(sfc.lon.normalize(30)))
            & (ny >= int(sfc.lat.normalize(35)))
            & (ny <= int(sfc.lat.normalize(60)))
        )
        np.testing.assert_array_equal(got, expect)
        # at 31-bit cells loose == exact for any practical box
        exact = evaluate_host(
            parse_ecql("BBOX(geom, -10, 35, 30, 60)"), all_batch
        )
        assert not np.any(exact & ~got)


# -- pushdown stats (StatsIterator analog) ----------------------------------


class TestDeviceStats:
    ECQL = (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z"
    )
    SPEC = 'Count();MinMax("val");MinMax("dtg");Histogram("val",10,0,100)'

    def _host_oracle(self, ds, ecql, spec):
        from geomesa_tpu.process import run_stats

        return run_stats(ds, "t", ecql, spec)

    def test_fused_stats_match_host_oracle(self):
        ds = _store(n=20000)
        di = DeviceIndex(ds, "t")
        got = di.stats(self.ECQL, self.SPEC)
        exp = self._host_oracle(ds, self.ECQL, self.SPEC)
        g, e = got.to_json(), exp.to_json()
        assert g[0] == e[0]  # count
        assert g[1]["min"] == e[1]["min"] and g[1]["max"] == e[1]["max"]
        assert g[2]["min"] == e[2]["min"] and g[2]["max"] == e[2]["max"]  # dtg i64
        assert g[3]["counts"] == e[3]["counts"]

    def test_host_fallback_parts_still_exact(self):
        ds = _store(n=5000)
        di = DeviceIndex(ds, "t")
        spec = 'Count();TopK("name")'  # TopK is a host stat
        got = di.stats(self.ECQL, spec)
        exp = self._host_oracle(ds, self.ECQL, spec)
        assert got.to_json() == exp.to_json()

    def test_residual_filter_falls_back_entirely(self):
        ds = _store(n=5000)
        di = DeviceIndex(ds, "t")
        ecql = "name = 'a' AND BBOX(geom, -90, -45, 90, 45)"
        got = di.stats(ecql, 'Count();MinMax("val")')
        exp = self._host_oracle(ds, ecql, 'Count();MinMax("val")')
        assert got.to_json() == exp.to_json()

    def test_loose_stats_use_key_planes(self):
        ds = _store(n=8000)
        di = DeviceIndex(ds, "t", z_planes=True)
        got = di.stats(self.ECQL, "Count()", loose=True)
        assert got.stats[0].count == di.count(self.ECQL, loose=True)

    def test_streaming_stats_respect_validity(self):
        from geomesa_tpu.device_cache import StreamingDeviceIndex

        ds = _store(n=6000)
        di = StreamingDeviceIndex(ds, "t")
        before = di.stats(self.ECQL, 'Count();MinMax("val")')
        n0 = before.stats[0].count
        hits = di.query(self.ECQL)
        di.evict(hits.fids[:15])
        after = di.stats(self.ECQL, "Count()")
        assert after.stats[0].count == n0 - 15

    def test_empty_result_leaves_minmax_unset(self):
        ds = _store(n=1000)
        di = DeviceIndex(ds, "t")
        got = di.stats("BBOX(geom, 170, 80, 171, 81) AND "
                       "dtg DURING 2020-01-10T00:00:00Z/2020-01-11T00:00:00Z",
                       'Count();MinMax("val")')
        if got.stats[0].count == 0:
            assert got.stats[1].min is None

    def test_repeated_calls_reuse_compiled_fused_fn(self):
        ds = _store(n=2000)
        di = DeviceIndex(ds, "t")
        di.stats(self.ECQL, self.SPEC)
        assert len(di._agg_cache) == 1
        di.stats(self.ECQL, self.SPEC)
        assert len(di._agg_cache) == 1

    def test_inverted_time_window_loose_returns_empty(self):
        """Regression: an inverted DURING window must yield an empty loose
        result, not crash in np.stack over zero bins."""
        ds = _store(n=500)
        di = DeviceIndex(ds, "t", z_planes=True)
        q = ("BBOX(geom, -10, 35, 30, 60) AND "
             "dtg DURING 2020-02-01T00:00:00Z/2020-01-01T00:00:00Z")
        assert di.count(q, loose=True) == 0
        assert len(di.query(q, loose=True)) == 0

    def test_two_histograms_same_attr_do_not_collide(self):
        ds = _store(n=3000)
        di = DeviceIndex(ds, "t")
        spec = 'Histogram("val",10,0,100);Histogram("val",5,0,50)'
        got = di.stats(self.ECQL, spec)
        exp = self._host_oracle(ds, self.ECQL, spec)
        assert got.to_json() == exp.to_json()

    def test_stats_on_empty_index(self):
        ds = MemoryDataStore()
        ds.create_schema("t", SPEC)
        di = DeviceIndex(ds, "t")
        got = di.stats("INCLUDE", 'Count();MinMax("val")')
        assert got.stats[0].count == 0
        assert got.stats[1].min is None

    def test_missing_resident_columns_fall_back_to_host(self):
        import warnings

        ds = _store(n=2000)
        di = DeviceIndex(ds, "t", columns=["val"])  # no geom planes
        all_batch = ds.query("t").batch
        ecql = "BBOX(geom, -10, 35, 30, 60) AND val >= 50"
        expect = evaluate_host(parse_ecql(ecql), all_batch)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert di.count(ecql) == int(expect.sum())
            np.testing.assert_array_equal(
                np.sort(di.query(ecql).fids),
                np.sort(all_batch.fids[expect]),
            )


def test_streaming_index_tracks_live_expiry():
    """Expiry is a state change like any Remove: an attached delta cache
    must see it, not silently diverge (live.py _expire notifies
    listeners with the expired fids)."""
    from geomesa_tpu.device_cache import StreamingDeviceIndex
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.query.runner import QueryResult
    from geomesa_tpu.stream import LiveFeatureStore

    now = [1_000_000]
    sft = SimpleFeatureType.create("t", SPEC)
    live = LiveFeatureStore(sft, expiry_ms=500, clock=lambda: now[0])

    class Adapter:
        def get_schema(self, _):
            return sft

        def query(self, _, q=None):
            b = live.snapshot()
            return QueryResult(b, None, len(b), len(b))

    di = StreamingDeviceIndex(Adapter(), "t", capacity=4096)
    di.attach_live(live)
    live.put({"name": ["a"] * 5, "val": np.arange(5), "dtg": np.zeros(5),
              "geom": np.zeros((5, 2))}, [f"f{i}" for i in range(5)])
    assert len(di) == 5
    now[0] += 300
    live.put({"name": ["b"] * 2, "val": np.arange(2), "dtg": np.zeros(2),
              "geom": np.zeros((2, 2))}, ["g0", "g1"])
    assert len(di) == 7
    now[0] += 300  # first 5 rows are now older than 500ms
    assert len(live) == 2  # triggers expiry + listener notification
    assert len(di) == 2, "device cache missed the expiry"
    assert di.count("INCLUDE") == 2


# -- non-point (XZ extent-curve) resident serving ---------------------------

POLY_SPEC = "name:String,dtg:Date,*geom:Polygon:srid=4326"


def _poly_wkt(x, y, w, h):
    return (
        f"POLYGON (({x} {y}, {x + w} {y}, {x + w} {y + h}, "
        f"{x} {y + h}, {x} {y}))"
    )


def _poly_store(n=4000, seed=7, with_time=True):
    spec = POLY_SPEC if with_time else "name:String,*geom:Polygon:srid=4326"
    ds = MemoryDataStore()
    ds.create_schema("p", spec)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    cols = {
        "name": rng.choice(["a", "b", "c"], n),
        "geom": np.array(
            [
                _poly_wkt(
                    rng.uniform(-170, 160),
                    rng.uniform(-85, 75),
                    rng.uniform(0.01, 5.0),
                    rng.uniform(0.01, 5.0),
                )
                for _ in range(n)
            ],
            dtype=object,
        ),
    }
    if with_time:
        cols["dtg"] = rng.integers(t0, t1, n)
    ds.write("p", cols, fids=np.arange(n))
    return ds


def test_nonpoint_stages_xz_key_planes():
    from geomesa_tpu.device_cache import Z_BIN, Z_HI, Z_LO

    ds = _poly_store(n=500)
    di = DeviceIndex(ds, "p", z_planes=True)
    assert di._z_kind == "xz3"
    assert Z_BIN in di._cols and Z_HI in di._cols and Z_LO in di._cols
    ds2 = _poly_store(n=500, with_time=False)
    di2 = DeviceIndex(ds2, "p", z_planes=True)
    assert di2._z_kind == "xz2"
    assert Z_HI in di2._cols and Z_BIN not in di2._cols


def test_nonpoint_loose_scan_is_superset_and_exact_query_matches():
    """Loose xz mask: cell-granular superset of the exact bbox hits; the
    exact (non-loose) path equals the store oracle."""
    ds = _poly_store()
    di = DeviceIndex(ds, "p", z_planes=True)
    all_batch = ds.query("p").batch
    ecql = (
        "BBOX(geom, -5, 42, 8, 51) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z"
    )
    expect = evaluate_host(parse_ecql(ecql), all_batch)
    exact = di.count(ecql, loose=False)
    assert exact == int(expect.sum())
    loose = di.count(ecql, loose=True)
    assert loose >= exact
    lm = di.mask(ecql, loose=True)
    em = di.mask(ecql, loose=False)
    assert not np.any(em & ~lm), "loose xz mask dropped an exact hit"
    # exact query results identical to the oracle
    got = di.query(ecql, loose=False)
    np.testing.assert_array_equal(
        np.sort(got.fids), np.sort(all_batch.fids[expect])
    )


def test_nonpoint_xz2_loose_scan():
    ds = _poly_store(with_time=False)
    di = DeviceIndex(ds, "p", z_planes=True)
    all_batch = ds.query("p").batch
    ecql = "BBOX(geom, -5, 42, 8, 51)"
    expect = evaluate_host(parse_ecql(ecql), all_batch)
    assert di.count(ecql, loose=False) == int(expect.sum())
    lm = di.mask(ecql, loose=True)
    em = di.mask(ecql, loose=False)
    assert lm.sum() >= em.sum()
    assert not np.any(em & ~lm)
    # pruning actually happens for a small window
    assert lm.sum() < len(all_batch)


def test_nonpoint_loose_stats_fused():
    """Count stat through the fused loose path on xz key planes."""
    ds = _poly_store()
    di = DeviceIndex(ds, "p", z_planes=True)
    ecql = (
        "BBOX(geom, -5, 42, 8, 51) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z"
    )
    seq = di.stats(ecql, "Count()", loose=True)
    assert seq.stats[0].count == di.count(ecql, loose=True)


def test_staging_device_encode_matches_numpy_oracle():
    """VERDICT round-2 weak #4: staging encodes keys on DEVICE; planes
    must be bit-identical to the host numpy oracle for every kind."""
    from geomesa_tpu.device_cache import _z_planes_np

    for mk, kind in [
        # dim_planes=False: z3 exercises the INTERLEAVED device encode
        # here (the dim-plane staging parity lives in test_dimplane_cache)
        (lambda: _store(n=3000), "z3"),
        (lambda: _poly_store(n=1500), "xz3"),
        (lambda: _poly_store(n=1500, with_time=False), "xz2"),
    ]:
        ds = mk()
        tn = ds.type_names[0]
        di = DeviceIndex(ds, tn, z_planes=True, dim_planes=False)
        assert di._z_kind == kind
        # the DEVICE path must have produced the planes: a latched fallback
        # would make this parity test vacuously compare oracle to oracle
        assert not di._z_encode_failed and di._z_encode_jit is not None
        batch = ds.query(tn).batch
        np_kind, np_planes, _bins = _z_planes_np(batch, di.sft)
        assert np_kind == kind
        for k, v in np_planes.items():
            np.testing.assert_array_equal(
                np.asarray(di._cols[k])[: len(batch)], v, err_msg=f"{kind}:{k}"
            )


def test_staging_device_encode_z2_and_x64_scoping():
    """z2 staging parity + the scoped-x64 encode must not leak x64 into
    the caller's config."""
    import jax

    from geomesa_tpu.device_cache import _z_planes_np

    ds = MemoryDataStore()
    ds.create_schema("z2t", "val:Int,*geom:Point:srid=4326")
    rng = np.random.default_rng(3)
    n = 2000
    ds.write(
        "z2t",
        {
            "val": rng.integers(0, 9, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
    )
    before = jax.config.jax_enable_x64
    # dim_planes=False: this test checks the INTERLEAVED z2 encode parity
    # (z2 now stages dim planes by default; see test_dimplane_cache)
    di = DeviceIndex(ds, "z2t", z_planes=True, dim_planes=False)
    assert jax.config.jax_enable_x64 == before
    assert di._z_kind == "z2"
    batch = ds.query("z2t").batch
    _, np_planes, _bins = _z_planes_np(batch, di.sft)
    for k, v in np_planes.items():
        np.testing.assert_array_equal(np.asarray(di._cols[k]), v)


# -- pushdown density + BIN (VERDICT round-2 item 3) -------------------------


class TestFusedDensityAndBin:
    ECQL = (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z"
    )

    def test_density_fused_matches_host(self):
        from geomesa_tpu.geom import Envelope
        from geomesa_tpu.process.density import _density_host

        ds = _store(n=8000)
        di = DeviceIndex(ds, "t", z_planes=True)
        env = Envelope(-10, 35, 30, 60)
        grid = di.density(self.ECQL, env, 64, 32)
        assert grid is not None and grid.shape == (32, 64)
        # host oracle over the exact hit set
        all_batch = ds.query("t").batch
        m = evaluate_host(parse_ecql(self.ECQL), all_batch)
        x, y = all_batch.point_coords()
        ref = _density_host(x[m], y[m], np.ones(int(m.sum())), env, 64, 32)
        np.testing.assert_allclose(grid, ref, rtol=1e-5)
        assert grid.sum() > 0

    def test_density_weighted_and_loose_superset(self):
        from geomesa_tpu.geom import Envelope

        ds = _store(n=6000)
        di = DeviceIndex(ds, "t", z_planes=True)
        env = Envelope(-10, 35, 30, 60)
        gw = di.density(self.ECQL, env, 32, 32, weight_attr="val")
        assert gw is not None
        all_batch = ds.query("t").batch
        m = evaluate_host(parse_ecql(self.ECQL), all_batch)
        w = all_batch.column("val")[m].astype(np.float64)
        np.testing.assert_allclose(float(gw.sum()), w.sum(), rtol=1e-5)
        # loose mode: cell-granular superset -> total mass >= exact
        gl = di.density(self.ECQL, env, 32, 32, loose=True)
        assert gl is not None
        ge = di.density(self.ECQL, env, 32, 32, loose=False)
        assert gl.sum() >= ge.sum()

    def test_density_process_routes_through_resident(self, monkeypatch):
        """process.density with a device_index must not materialize a
        feature batch from the store."""
        from geomesa_tpu.geom import Envelope
        from geomesa_tpu.process import density as density_fn

        ds = _store(n=3000)
        di = DeviceIndex(ds, "t", z_planes=True)
        calls = []
        real_query = ds.query
        monkeypatch.setattr(
            ds, "query", lambda *a, **k: (calls.append(1), real_query(*a, **k))[1]
        )
        env = Envelope(-10, 35, 30, 60)
        grid = density_fn(ds, "t", self.ECQL, env, 32, 32, device_index=di)
        assert not calls, "resident density still hit the store query path"
        assert grid.shape == (32, 32)

    def test_bin_export_matches_batch_encoder(self):
        from geomesa_tpu.process.binexport import decode_bin, encode_bin

        ds = _store(n=4000)
        di = DeviceIndex(ds, "t", z_planes=True)
        data = di.bin_export(self.ECQL, track_attr="name", sort=True)
        # oracle: full query then the batch-level encoder
        hits = ds.query("t", self.ECQL).batch
        ref = encode_bin(hits, "name", sort=True)
        assert data == ref
        rec = decode_bin(data)
        assert len(rec) == len(hits)

    def test_run_stats_routes_through_device_index(self, monkeypatch):
        from geomesa_tpu.process import run_stats

        ds = _store(n=3000)
        di = DeviceIndex(ds, "t", z_planes=True)
        calls = []
        real_query = ds.query
        monkeypatch.setattr(
            ds, "query", lambda *a, **k: (calls.append(1), real_query(*a, **k))[1]
        )
        seq = run_stats(ds, "t", self.ECQL, "Count()", device_index=di)
        assert not calls, "resident stats still hit the store query path"
        all_batch = real_query("t").batch
        m = evaluate_host(parse_ecql(self.ECQL), all_batch)
        assert seq.stats[0].count == int(m.sum())

    def test_density_viewport_is_runtime_not_recompile(self):
        """Different bboxes reuse ONE compiled dispatch (the viewport is a
        runtime array, not a trace constant)."""
        from geomesa_tpu.geom import Envelope

        ds = _store(n=2000)
        di = DeviceIndex(ds, "t", z_planes=True)
        g1 = di.density(self.ECQL, Envelope(-10, 35, 30, 60), 32, 32)
        n_cached = len(di._agg_cache)
        g2 = di.density(self.ECQL, Envelope(0, 40, 20, 55), 32, 32)
        assert len(di._agg_cache) == n_cached  # same entry, new viewport
        assert g1 is not None and g2 is not None
        assert not np.array_equal(g1, g2)  # different windows, real effect


# -- per-auth resident serving (VERDICT round-2 item 7) -----------------------


class TestPerAuthResident:
    def _labeled_store(self, n=4000, seed=19, labels=("", "A", "B", "A&B", "A|B")):
        from geomesa_tpu.features.batch import FeatureBatch

        ds = MemoryDataStore()
        ds.create_schema("s", SPEC)
        rng = np.random.default_rng(seed)
        t0 = parse_instant("2020-01-01T00:00:00")
        t1 = parse_instant("2020-03-01T00:00:00")
        batch = FeatureBatch.from_columns(
            ds.get_schema("s"),
            {
                "name": rng.choice(["a", "b"], n),
                "val": rng.integers(0, 100, n),
                "dtg": rng.integers(t0, t1, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)],
                    axis=1,
                ),
            },
            fids=np.arange(n),
        ).with_visibility(rng.choice(labels, n))
        ds.write("s", batch)
        return ds

    ECQL = (
        "BBOX(geom, -60, -30, 60, 30) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-20T00:00:00Z"
    )

    def _oracle_fids(self, ds, ecql, auths):
        from geomesa_tpu.query.plan import Query

        return set(
            ds.query("s", Query(ecql, hints={"auths": auths}))
            .batch.fids.tolist()
        )

    def test_query_count_stats_match_store_per_auth(self):
        ds = self._labeled_store()
        di = DeviceIndex(ds, "s", z_planes=True)
        for auths in [(), ("A",), ("B",), ("A", "B"), ("C",), None]:
            want = self._oracle_fids(ds, self.ECQL, auths or ())
            got = di.query(self.ECQL, auths=auths)
            assert set(got.fids.tolist()) == want, f"auths={auths}"
            assert di.count(self.ECQL, auths=auths) == len(want)
            seq = di.stats(self.ECQL, "Count()", auths=auths)
            assert seq.stats[0].count == len(want)

    def test_default_fails_closed(self):
        """No auths argument at all behaves exactly like auths=() —
        labeled rows hidden."""
        ds = self._labeled_store()
        di = DeviceIndex(ds, "s", z_planes=True)
        want = self._oracle_fids(ds, self.ECQL, ())
        assert set(di.query(self.ECQL).fids.tolist()) == want

    def test_loose_per_auth_superset(self):
        ds = self._labeled_store()
        di = DeviceIndex(ds, "s", z_planes=True)
        exact = di.count(self.ECQL, auths=("A",), loose=False)
        loose = di.count(self.ECQL, auths=("A",), loose=True)
        assert loose >= exact > 0
        em = di.mask(self.ECQL, auths=("A",), loose=False)
        lm = di.mask(self.ECQL, auths=("A",), loose=True)
        assert not np.any(em & ~lm)

    def test_density_per_auth(self):
        from geomesa_tpu.geom import Envelope

        ds = self._labeled_store()
        di = DeviceIndex(ds, "s", z_planes=True)
        env = Envelope(-60, -30, 60, 30)
        g_a = di.density(self.ECQL, env, 32, 32, auths=("A", "B"))
        g_none = di.density(self.ECQL, env, 32, 32)
        assert g_a.sum() == di.count(self.ECQL, auths=("A", "B"))
        assert g_none.sum() == di.count(self.ECQL)
        assert g_a.sum() > g_none.sum()

    def test_fuzz_random_filters_vs_store(self):
        """Differential fuzz: random bbox/attr filters x auth sets, the
        resident per-auth result set must equal the store path's."""
        from geomesa_tpu.query.plan import Query

        ds = self._labeled_store(n=2500, seed=31)
        di = DeviceIndex(ds, "s", z_planes=True)
        rng = np.random.default_rng(7)
        auth_sets = [(), ("A",), ("B",), ("A", "B"), ("Z",)]
        for i in range(12):
            x0 = rng.uniform(-180, 120)
            y0 = rng.uniform(-90, 60)
            w = rng.uniform(5, 120)
            v = rng.integers(0, 100)
            ecql = (
                f"BBOX(geom, {x0:.3f}, {y0:.3f}, {x0 + w:.3f}, "
                f"{y0 + w / 2:.3f}) AND val >= {v}"
            )
            auths = auth_sets[i % len(auth_sets)]
            want = self._oracle_fids(ds, ecql, auths)
            got = set(di.query(ecql, auths=auths).fids.tolist())
            assert got == want, f"{ecql} auths={auths}"

    def test_vocab_overflow_falls_back_public_only(self):
        """Past VIS_VOCAB_MAX distinct labels, labeled rows leave the
        resident copy (loudly) and only public rows serve."""
        import pytest

        ds = self._labeled_store(
            n=300, labels=tuple(f"L{i}" for i in range(40)) + ("",)
        )
        class Small(DeviceIndex):
            VIS_VOCAB_MAX = 8

        with pytest.warns(RuntimeWarning, match="vocabulary"):
            di = Small(ds, "s", z_planes=True)
        # resident copy holds only the public rows now
        from geomesa_tpu.query.plan import Query

        pub = self._oracle_fids(ds, "INCLUDE", ())
        assert set(di.query("INCLUDE", auths=("L1",)).fids.tolist()) == pub
        # the store path still serves the labeled rows
        with_l1 = self._oracle_fids(ds, "INCLUDE", ("L1",))
        assert with_l1 > pub

    def test_streaming_labeled_appends(self):
        """Labels arriving mid-stream on an unlabeled store trigger the
        plane-introducing restage; per-auth results stay exact."""
        from geomesa_tpu.device_cache import StreamingDeviceIndex
        from geomesa_tpu.features.batch import FeatureBatch
        from geomesa_tpu.query.plan import Query

        ds = _store(n=1000)  # unlabeled base
        di = StreamingDeviceIndex(ds, "t", z_planes=True)
        sft = ds.get_schema("t")
        rng = np.random.default_rng(5)
        t0 = parse_instant("2020-01-15T00:00:00")
        labeled = FeatureBatch.from_columns(
            sft,
            {
                "name": ["a"] * 50,
                "val": rng.integers(0, 100, 50),
                "dtg": np.full(50, t0),
                "geom": np.stack(
                    [rng.uniform(-10, 10, 50), rng.uniform(-10, 10, 50)],
                    axis=1,
                ),
            },
            fids=np.arange(90_000, 90_050),
        ).with_visibility(["secret"] * 50)
        ds.write("t", labeled)
        di.upsert(labeled)
        ecql = "BBOX(geom, -10, -10, 10, 10)"
        no_auth = di.count(ecql)
        with_auth = di.count(ecql, auths=("secret",))
        assert with_auth == no_auth + 50
        want = set(
            ds.query("t", Query(ecql, hints={"auths": ("secret",)}))
            .batch.fids.tolist()
        )
        got = set(di.query(ecql, auths=("secret",)).fids.tolist())
        assert got == want
