"""Device-resident index: pinned columns, repeated queries, refresh."""

import numpy as np

from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"


def _store(n=20000, seed=23):
    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "val": rng.integers(0, 100, n),
            "dtg": rng.integers(t0, t1, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    return ds


def test_resident_count_and_query_match_oracle():
    ds = _store()
    di = DeviceIndex(ds, "t")
    assert len(di) == 20000 and di.nbytes > 0
    all_batch = ds.query("t").batch
    for ecql in [
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-01T00:00:00Z",
        "val >= 50 AND BBOX(geom, 0, 0, 90, 90)",
        "BBOX(geom, -180, -90, 180, 90)",
    ]:
        expect = evaluate_host(parse_ecql(ecql), all_batch)
        assert di.count(ecql) == int(expect.sum()), ecql
        got = di.query(ecql)
        np.testing.assert_array_equal(
            np.sort(got.fids), np.sort(all_batch.fids[expect])
        )


def test_residual_filters_still_exact():
    ds = _store(n=2000)
    di = DeviceIndex(ds, "t")
    # string equality is not a device predicate -> residual path
    ecql = "name = 'a' AND BBOX(geom, -90, -45, 90, 45)"
    all_batch = ds.query("t").batch
    expect = evaluate_host(parse_ecql(ecql), all_batch)
    assert di.count(ecql) == int(expect.sum())
    np.testing.assert_array_equal(
        np.sort(di.query(ecql).fids), np.sort(all_batch.fids[expect])
    )


def test_refresh_after_write():
    ds = _store(n=100)
    di = DeviceIndex(ds, "t")
    assert di.count("INCLUDE") == 100
    ds.write(
        "t",
        {
            "name": ["z"],
            "val": [1],
            "dtg": [parse_instant("2020-01-15T00:00:00")],
            "geom": np.array([[1.0, 2.0]]),
        },
        fids=["extra"],
    )
    assert di.count("INCLUDE") == 100  # stale until refresh
    di.refresh()
    assert di.count("INCLUDE") == 101


def test_attach_live_refreshes():
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stream import LiveFeatureStore

    sft = SimpleFeatureType.create("t", SPEC)
    live = LiveFeatureStore(sft)

    class LiveAdapter:
        """Minimal store facade over the live layer for DeviceIndex."""

        def get_schema(self, _):
            return sft

        def query(self, _, q=None):
            from geomesa_tpu.query.runner import QueryResult

            b = live.snapshot()
            return QueryResult(b, None, len(b), len(b))

    di = DeviceIndex(LiveAdapter(), "t")
    di.attach_live(live)
    live.put(
        {
            "name": ["a"],
            "val": [5],
            "dtg": [0],
            "geom": np.array([[3.0, 4.0]]),
        },
        ["f0"],
    )
    assert di.count("INCLUDE") == 1  # listener refreshed the residency


def test_detach_live_listener():
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.stream import LiveFeatureStore

    sft = SimpleFeatureType.create("t", SPEC)
    live = LiveFeatureStore(sft)

    calls = []

    class Adapter:
        def get_schema(self, _):
            return sft

        def query(self, _, q=None):
            from geomesa_tpu.query.runner import QueryResult

            calls.append(1)
            b = live.snapshot()
            return QueryResult(b, None, len(b), len(b))

    di = DeviceIndex(Adapter(), "t")
    detach = di.attach_live(live)
    live.put({"name": ["a"], "val": [1], "dtg": [0],
              "geom": np.zeros((1, 2))}, ["f0"])
    n_after_put = len(calls)
    detach()
    live.put({"name": ["b"], "val": [2], "dtg": [0],
              "geom": np.zeros((1, 2))}, ["f1"])
    assert len(calls) == n_after_put  # no refresh after detach
