"""The conf-declared compile-shape ladder (ISSUE 17 tentpole): pure
host arithmetic, so these are exact unit properties — the default
ladder must reproduce the historical next-power-of-two keys bit for
bit, any growth > 1 must yield a strictly increasing closed rung set,
and growth <= 1 must disable bucketing (the parity suite's oracle)."""

import pytest

from geomesa_tpu.bucketing import bucket_cap, ladder, ladder_params
from geomesa_tpu.conf import prop_override


def _pow2(n):
    n = max(int(n), 1)
    v = 1
    while v < n:
        v <<= 1
    return v


def test_default_ladder_is_next_pow2():
    """growth=2.0 / min=1 (the defaults) mints EXACTLY the pow2 keys
    every dispatch site used before the ladder existed — a default
    deployment's jit caches and persistent-cache entries are unchanged
    by this PR."""
    assert ladder_params() == (2.0, 1)
    for n in list(range(1, 70)) + [127, 128, 129, 1000, 4096, 10**6]:
        assert bucket_cap(n) == _pow2(n), n


def test_cap_basic_properties():
    caps = [bucket_cap(n) for n in range(1, 200)]
    for n, c in enumerate(caps, start=1):
        assert c >= n  # never rounds down
        assert bucket_cap(c) == c  # idempotent: rungs are fixpoints
    assert caps == sorted(caps)  # monotone in n


def test_floor_and_degenerate_inputs():
    assert bucket_cap(0) == 1
    assert bucket_cap(-5) == 1
    assert bucket_cap(3, floor=16) == 16
    assert bucket_cap(100, floor=16) == 128


@pytest.mark.parametrize("growth", [1.5, 2.0, 3.0, 1.1])
def test_ladder_closed_under_cap(growth):
    """Every capacity up to a bound lands on a rung the warmup plan
    enumerates for that bound — the property that makes AOT warmup a
    CLOSED set instead of a heuristic."""
    with prop_override("compile.bucket.growth", growth):
        rungs = ladder(200)
        assert rungs == sorted(set(rungs))  # strictly increasing
        for n in range(1, 201):
            assert bucket_cap(n) in rungs, (growth, n)
        assert rungs[-1] == bucket_cap(200)


def test_growth_15_ladder_values():
    with prop_override("compile.bucket.growth", 1.5):
        assert [bucket_cap(n) for n in (1, 2, 3, 7, 8, 9, 17, 100)] == [
            1, 2, 3, 8, 8, 12, 18, 140,
        ]


def test_growth_leq_one_disables_bucketing():
    for g in (0, 1.0, -2):
        with prop_override("compile.bucket.growth", g):
            for n in (1, 3, 7, 17, 100):
                assert bucket_cap(n) == n
            assert ladder(37) == [37]


def test_min_rung_floor():
    with prop_override("compile.bucket.min", 8):
        assert bucket_cap(1) == 8
        assert bucket_cap(3) == 8
        assert bucket_cap(9) == 16
        assert ladder(20)[0] == 8


def test_dispatch_sites_ride_the_ladder():
    """The pre-existing pow2 helpers route through the ladder: an
    off-default growth must change what they return (the rewiring is
    live, not just the new module)."""
    from geomesa_tpu.device_cache import _next_pow2
    from geomesa_tpu.ops.join import next_pow2

    assert _next_pow2(9) == 16 and next_pow2(9) == 16
    with prop_override("compile.bucket.growth", 3.0):
        assert _next_pow2(9) == 9  # ladder 1,3,9
        assert next_pow2(10) == 27
    with prop_override("compile.bucket.growth", 0):
        assert _next_pow2(9) == 9 and next_pow2(10) == 10
