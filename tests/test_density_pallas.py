"""Pallas density kernel (VERDICT round-3 item 3): pixel histograms as
one-hot MXU contractions must match the scatter engine and the host
oracle, for weighted and unweighted grids, odd grid shapes, empty inputs,
and through DeviceIndex.density / the process surface.

Boundary note: the viewport multiply quantizes differently across XLA
fusion choices (FMA vs separate mul), so borderline pixels can land one
cell over between engines. Exactness tests therefore use PIXEL-CENTER
data (no coordinate within 1e-3 of a cell edge); random-data tests
compare total mass with a small tolerance.
"""

from __future__ import annotations

import numpy as np
import pytest

from geomesa_tpu.ops.density_pallas import build_density_pallas, density_oracle

ENV = np.array([-60.0, -45.0, 100.0, 60.0], np.float32)
W, H = 256, 256


def _center_data(n=20000, seed=3, width=W, height=H, env=ENV):
    """Points at pixel centers: engine-independent pixel assignment."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    px = rng.integers(0, width, n)
    py = rng.integers(0, height, n)
    x = env[0] + (px + 0.5) * (env[2] - env[0]) / width
    y = env[1] + (py + 0.5) * (env[3] - env[1]) / height
    m = (rng.random(n) < 0.7).astype(np.int8)
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    return (
        jnp.asarray(x.astype(np.float32)),
        jnp.asarray(y.astype(np.float32)),
        jnp.asarray(m),
        jnp.asarray(w),
    )


def test_unweighted_exact_vs_oracle():
    import jax
    import jax.numpy as jnp

    x, y, m, _ = _center_data()
    fn = build_density_pallas(W, H, False)
    out = np.asarray(jax.jit(fn)(jnp.asarray(ENV), x, y, m))
    want = density_oracle(
        np.asarray(x), np.asarray(y), np.asarray(m), None, ENV, W, H
    )
    np.testing.assert_array_equal(out, want)
    assert out.sum() == int(np.asarray(m).sum())  # all hits inside


def test_weighted_close_vs_oracle():
    import jax
    import jax.numpy as jnp

    x, y, m, w = _center_data()
    fn = build_density_pallas(W, H, True)
    out = np.asarray(jax.jit(fn)(jnp.asarray(ENV), x, y, m, w))
    want = density_oracle(
        np.asarray(x), np.asarray(y), np.asarray(m), np.asarray(w),
        ENV, W, H,
    )
    np.testing.assert_allclose(out, want, rtol=2e-5, atol=1e-3)


@pytest.mark.parametrize("wh", [(100, 37), (512, 64), (16, 16)])
def test_odd_grid_shapes(wh):
    import jax
    import jax.numpy as jnp

    width, height = wh
    x, y, m, _ = _center_data(n=5000, width=width, height=height)
    fn = build_density_pallas(width, height, False)
    out = np.asarray(jax.jit(fn)(jnp.asarray(ENV), x, y, m))
    want = density_oracle(
        np.asarray(x), np.asarray(y), np.asarray(m), None,
        ENV, width, height,
    )
    assert out.shape == (height, width)
    np.testing.assert_array_equal(out, want)


def test_outside_rows_and_empty():
    import jax
    import jax.numpy as jnp

    fn = build_density_pallas(64, 64, False)
    # all rows outside the viewport
    x = jnp.asarray(np.full(500, 150.0, np.float32))
    y = jnp.asarray(np.full(500, 80.0, np.float32))
    m = jnp.asarray(np.ones(500, np.int8))
    env = jnp.asarray(np.array([0, 0, 10, 10], np.float32))
    assert np.asarray(jax.jit(fn)(env, x, y, m)).sum() == 0
    # empty input
    e = jnp.asarray(np.empty(0, np.float32))
    out = np.asarray(fn(env, e, e, jnp.asarray(np.empty(0, np.int8))))
    assert out.shape == (64, 64) and out.sum() == 0


def test_random_data_mass_close_to_scatter():
    """General (borderline-bearing) data: per-cell equality is not
    guaranteed across engines, but total mass must agree within the
    handful of viewport-edge rows."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    n = 50000
    x = jnp.asarray(rng.uniform(-180, 180, n).astype(np.float32))
    y = jnp.asarray(rng.uniform(-90, 90, n).astype(np.float32))
    m = jnp.asarray((rng.random(n) < 0.5).astype(np.int8))
    fn = build_density_pallas(W, H, False)
    got = float(np.asarray(jax.jit(fn)(jnp.asarray(ENV), x, y, m)).sum())
    want = float(
        density_oracle(
            np.asarray(x), np.asarray(y), np.asarray(m), None, ENV, W, H
        ).sum()
    )
    assert abs(got - want) <= 4


def test_device_index_density_uses_pallas(monkeypatch):
    """DeviceIndex.density must serve grids <= 512x512 via the kernel."""
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.geom import Envelope
    from geomesa_tpu.store.memory import MemoryDataStore

    rng = np.random.default_rng(5)
    n = 4000
    width, height = 128, 64
    env = Envelope(-60, -45, 100, 60)
    px = rng.integers(0, width, n)
    py = rng.integers(0, height, n)
    ds = MemoryDataStore()
    ds.create_schema("d", "val:Double,dtg:Date,*geom:Point:srid=4326")
    ds.write("d", {
        "val": rng.uniform(0.5, 2.0, n),
        "dtg": rng.integers(1_577_836_800_000, 1_580_000_000_000, n),
        "geom": np.stack([
            env.xmin + (px + 0.5) * (env.xmax - env.xmin) / width,
            env.ymin + (py + 0.5) * (env.ymax - env.ymin) / height,
        ], axis=1),
    })
    di = DeviceIndex(ds, "d")
    import geomesa_tpu.ops.density_pallas as dpal

    built = []
    orig = dpal.build_density_pallas

    def spy(*a, **k):
        built.append(a)
        return orig(*a, **k)

    monkeypatch.setattr(dpal, "build_density_pallas", spy)
    cql = "BBOX(geom, -179, -89, 179, 89)"
    grid = di.density(cql, env, width, height)
    assert built, "DeviceIndex.density did not build the Pallas kernel"
    # INCLUDE (no filter) also serves from the resident path: the fused
    # hook uses a constant-true mask (a full-viewport render must not
    # fall back to the store)
    g_inc = di.density("INCLUDE", env, width, height)
    assert g_inc is not None
    np.testing.assert_array_equal(g_inc, grid)  # bbox covers everything
    assert grid is not None and grid.shape == (height, width)
    # parity vs the host oracle on the same rows (pixel-center data)
    batch = ds.query("d").batch
    x, y = batch.point_coords("geom")
    want = density_oracle(
        x.astype(np.float32), y.astype(np.float32),
        np.ones(n, np.int8), None,
        np.array([env.xmin, env.ymin, env.xmax, env.ymax], np.float32),
        width, height,
    )
    np.testing.assert_array_equal(grid, want)
    # weighted through the same path
    gw = di.density(cql, env, width, height, weight_attr="val")
    ww = density_oracle(
        x.astype(np.float32), y.astype(np.float32),
        np.ones(n, np.int8), batch.column("val"),
        np.array([env.xmin, env.ymin, env.xmax, env.ymax], np.float32),
        width, height,
    )
    np.testing.assert_allclose(gw, ww, rtol=2e-5, atol=1e-3)


def test_large_grid_falls_back_to_scatter(monkeypatch):
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.geom import Envelope
    from geomesa_tpu.store.memory import MemoryDataStore

    rng = np.random.default_rng(6)
    n = 500
    ds = MemoryDataStore()
    ds.create_schema("d", "dtg:Date,*geom:Point:srid=4326")
    ds.write("d", {
        "dtg": rng.integers(1_577_836_800_000, 1_580_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
        ),
    })
    di = DeviceIndex(ds, "d")
    import geomesa_tpu.ops.density_pallas as dpal

    monkeypatch.setattr(
        dpal, "build_density_pallas",
        lambda *a, **k: pytest.fail("kernel built for an oversize grid"),
    )
    grid = di.density(
        "BBOX(geom, -179, -89, 179, 89)",
        Envelope(-10, -10, 10, 10), 1024, 1024,
    )
    assert grid is not None and grid.sum() == n
