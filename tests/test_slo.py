"""Serving SLO engine + cost ledger (ISSUE 9): window rotation under a
fake clock, burn-rate math against hand-computed fixtures, exemplar
round-trip through the /metrics exposition, compile-ledger attribution
on a forced cold compile, flight-recorder triggers (injected
breaker-open; bounded retention), per-tenant ledger isolation, and the
e2e /stats/slo -> /readyz flow (the whole suite runs under the runtime
lock-order checker, see conftest)."""

import json
import time
import urllib.error
import urllib.request
from urllib.parse import quote

import numpy as np
import pytest

from geomesa_tpu import ledger, resilience, slo
from geomesa_tpu.conf import prop_override
from geomesa_tpu.ledger import (
    COMPILES,
    CostLedger,
    RequestCost,
    cost_from_trace,
)
from geomesa_tpu.slo import SloEngine, WindowedHistogram, slo_def


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- windowed histogram: rotation + quantiles under a fake clock ------------


class TestWindowedHistogram:
    def test_rotation_expires_old_slots(self):
        clk = FakeClock()
        h = WindowedHistogram(window_s=60.0, slots=6, clock=clk)  # 10s slots
        h.observe(0.1)
        assert h.merged()["n"] == 1
        clk.advance(30.0)
        h.observe(0.2)
        assert h.merged()["n"] == 2
        # slot 0 (t=0..10) falls out once the window slides past it
        clk.advance(41.0)  # t=71: window covers (11, 71]
        assert h.merged()["n"] == 1
        clk.advance(200.0)  # everything expired
        assert h.merged()["n"] == 0

    def test_ring_wrap_clears_stale_slot(self):
        clk = FakeClock()
        h = WindowedHistogram(window_s=60.0, slots=6, clock=clk)
        h.observe(0.1, bad=True)
        # t=60 maps to the SAME ring position as t=0 (6 slots of 10s):
        # the stale counts must not leak into the new slot
        clk.t = 60.0
        h.observe(0.2)
        m = h.merged()
        assert m["n"] == 1 and m["bad"] == 0

    def test_sub_window_merge(self):
        clk = FakeClock()
        h = WindowedHistogram(window_s=600.0, slots=60, clock=clk)
        h.observe(0.1)  # t=0
        clk.t = 590.0
        h.observe(0.2)
        assert h.merged()["n"] == 2  # full window sees both
        assert h.merged(50.0)["n"] == 1  # fast window: only the recent one

    def test_quantiles_bucket_upper_bounds(self):
        clk = FakeClock()
        h = WindowedHistogram(window_s=60.0, clock=clk)
        for _ in range(99):
            h.observe(0.004)  # lands in the 0.005 bucket
        h.observe(20.0)  # lands in the 30.0 bucket
        assert h.quantile_ms(0.5) == 5.0
        assert h.quantile_ms(0.99) == 5.0
        assert h.quantile_ms(0.999) == 30000.0

    def test_quantile_none_without_data(self):
        h = WindowedHistogram(window_s=60.0, clock=FakeClock())
        assert h.quantile_ms(0.5) is None


# -- burn-rate math vs hand-computed fixtures -------------------------------


class TestBurnRate:
    def test_burn_hand_computed(self):
        clk = FakeClock(1000.0)
        eng = SloEngine(clock=clk)
        with prop_override("slo.interactive.objective", 0.999), \
                prop_override("slo.interactive.threshold.ms", 100.0):
            d = slo_def("interactive")
            for _ in range(97):
                eng.observe("count", "interactive", 0.001)
            for _ in range(3):
                eng.observe("count", "interactive", 10.0)  # > threshold
            # bad fraction 3/100 over budget 0.001 => burn 30
            assert eng.burn(d, 300.0) == pytest.approx(30.0)

    def test_error_counts_as_bad_even_when_fast(self):
        clk = FakeClock(1000.0)
        eng = SloEngine(clock=clk)
        with prop_override("slo.interactive.objective", 0.9):
            d = slo_def("interactive")
            eng.observe("count", "interactive", 0.001, error=True)
            # 1/1 bad over budget 0.1 => burn 10
            assert eng.burn(d, 300.0) == pytest.approx(10.0)

    def test_no_traffic_is_zero_burn(self):
        eng = SloEngine(clock=FakeClock())
        d = slo_def("interactive")
        assert eng.burn(d, 300.0) == 0.0

    def test_burning_needs_both_windows(self):
        """Fast-window spike over a healthy hour must NOT read as
        burning (the classic multi-window rule: page on fast AND slow)."""
        clk = FakeClock(0.0)
        eng = SloEngine(clock=clk)
        with prop_override("slo.interactive.objective", 0.99), \
                prop_override("slo.interactive.threshold.ms", 100.0), \
                prop_override("slo.interactive.window.s", 3600.0), \
                prop_override("slo.burn.fast.s", 300.0), \
                prop_override("slo.flightrec.burn", 0.0):
            d = slo_def("interactive")
            for _ in range(1000):
                eng.observe("count", "interactive", 0.001)
            clk.advance(3000.0)
            for _ in range(10):
                eng.observe("count", "interactive", 10.0)
            fast = eng.burn(d, 300.0)
            slow = eng.burn(d, 3600.0)
            # fast: 10/10 bad / 0.01 = 100; slow: 10/1010 / 0.01 ~= 0.99
            assert fast == pytest.approx(100.0)
            assert slow == pytest.approx((10 / 1010) / 0.01)
            assert eng.burning() == []
            # once the good traffic ages out of the slow window AND the
            # fast window still sees fresh bad traffic, it reports
            clk.advance(650.0)  # t=3650: good slots fall out of 3600s
            for _ in range(10):
                eng.observe("count", "interactive", 10.0)
            assert eng.burn(d, 3600.0) > 1.0
            assert eng.burn(d, 300.0) > 1.0
            assert "interactive" in eng.burning()

    def test_snapshot_document_shape(self):
        clk = FakeClock(50.0)
        eng = SloEngine(clock=clk)
        with prop_override("slo.flightrec.burn", 0.0):
            eng.observe("count", "interactive", 0.002, trace_id="t1")
        doc = eng.snapshot()
        assert doc["enabled"] is True
        s = doc["slos"]["interactive"]
        assert s["requests"] == 1 and s["bad"] == 0
        assert s["burn"]["fast"]["rate"] == 0.0
        assert doc["series"]["count|interactive"]["p50_ms"] == 2.5


# -- exemplars: observe -> prometheus exposition round trip -----------------


class TestExemplars:
    def test_histogram_exemplar_round_trip(self):
        from geomesa_tpu.metrics import MetricsRegistry

        r = MetricsRegistry()
        h = r.histogram("geomesa_t_seconds", buckets=(0.1, 1.0))
        h.observe(0.5, exemplar={"trace_id": "abc123"})
        h.observe(0.05)  # no exemplar on this bucket
        text = r.prometheus_text(openmetrics=True)
        # cumulative buckets: le="1" counts both observations; the
        # exemplar names the one that landed IN that bucket
        assert (
            'geomesa_t_seconds_bucket{le="1"} 2 # {trace_id="abc123"} 0.5'
            in text
        )
        # the exemplar-less bucket stays plain
        assert 'geomesa_t_seconds_bucket{le="0.1"} 1\n' in text
        assert text.endswith("# EOF\n")

    def test_classic_exposition_never_carries_exemplars(self):
        """The 0.0.4 text format has no exemplar syntax — one suffixed
        line would fail a classic Prometheus scrape ENTIRELY, so the
        default exposition must strip them (OpenMetrics only)."""
        from geomesa_tpu.metrics import MetricsRegistry

        r = MetricsRegistry()
        h = r.histogram("geomesa_t_seconds", buckets=(0.1, 1.0))
        h.observe(0.5, exemplar={"trace_id": "abc123"})
        text = r.prometheus_text()
        assert "trace_id" not in text
        assert "# EOF" not in text
        assert 'geomesa_t_seconds_bucket{le="1"} 1\n' in text

    def test_slo_observe_attaches_exemplar(self):
        from geomesa_tpu.metrics import REGISTRY

        with slo.fresh_engine() as eng, \
                prop_override("slo.flightrec.burn", 0.0):
            eng.observe(
                "count", "interactive", 0.33, trace_id="feedbee1"
            )
        text = REGISTRY.prometheus_text(openmetrics=True)
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("geomesa_slo_latency_seconds_bucket")
            and 'trace_id="feedbee1"' in ln
        )
        assert 'le="0.5"' in line  # 0.33 lands in the 0.5 bucket


# -- compile ledger: forced cold compile attribution ------------------------


class TestCompileLedger:
    def test_cold_compile_charges_request_and_signature(self):
        import jax
        import jax.numpy as jnp

        ledger.install()
        COMPILES.reset()
        # a fresh closure constant makes the HLO unique: this compile
        # cannot be served by any cache, in this process or on disk
        uniq = int(time.perf_counter() * 1e9) % 1_000_003 + 2
        with ledger.collect_cost(
            tenant="t", endpoint="knn", lane="interactive", shape="s"
        ) as cost:
            cost.trace_id = "trace-cold-1"
            with ledger.compile_scope("test.kernel:k=8"):
                jax.jit(lambda x: x * uniq + 1)(jnp.arange(257))
        fields = cost.snapshot_fields()
        assert fields.get("compiles", 0) >= 1
        assert fields.get("compile_seconds", 0) > 0
        snap = COMPILES.snapshot()
        sig = snap["by_signature"]["test.kernel:k=8"]
        assert sig["compiles"] >= 1
        assert sig["last_trace_id"] == "trace-cold-1"
        # the compile also lands in the trace retroactively when one is
        # recording — covered by the e2e test below; here the request
        # aggregate is the contract
        assert snap["total_s"] > 0

    def test_compile_outside_scope_falls_back_to_request_shape(self):
        import jax
        import jax.numpy as jnp

        ledger.install()
        COMPILES.reset()
        uniq = int(time.perf_counter() * 1e9) % 999_983 + 2
        with ledger.collect_cost(
            tenant="t", endpoint="count", lane="batch", shape="count:BBOX"
        ):
            jax.jit(lambda x: x + uniq)(jnp.arange(129))
        assert "request:count:BBOX" in COMPILES.snapshot()["by_signature"]


# -- flight recorder --------------------------------------------------------


@pytest.fixture
def flightrec(tmp_path):
    slo.FLIGHTREC.reset()
    slo.FLIGHTREC.configure(str(tmp_path / "fr"))
    (tmp_path / "fr").mkdir()
    yield slo.FLIGHTREC
    slo.FLIGHTREC.reset()


class TestFlightRecorder:
    def test_injected_breaker_open_writes_bundle(self, flightrec):
        resilience.reset()
        try:
            with prop_override("resilience.breaker.failures", 1), \
                    prop_override("slo.flightrec.interval.s", 0.0):
                resilience.device_breaker().record_failure()
            names = flightrec.bundle_names()
            assert len(names) == 1 and names[0].endswith("breaker-open")
            from pathlib import Path

            bundle = Path(flightrec.dir) / names[0]
            reason = json.loads((bundle / "reason.json").read_text())
            assert reason["reason"] == "breaker-open"
            assert reason["detail"]["domain"] == "device"
            breakers = json.loads((bundle / "breakers.json").read_text())
            assert breakers["device"]["state"] == "open"
            # the rest of the postmortem set is present
            have = {p.name for p in bundle.iterdir()}
            assert {
                "traces.json", "metrics.prom", "slo.json", "ledger.json",
            } <= have
        finally:
            resilience.reset()

    def test_rate_limit_per_reason(self, flightrec):
        with prop_override("slo.flightrec.interval.s", 3600.0):
            assert flightrec.trigger("manual") is not None
            assert flightrec.trigger("manual") is None  # limited
            # a different reason has its own budget
            assert flightrec.trigger("burn-rate") is not None

    def test_bounded_retention(self, flightrec):
        with prop_override("slo.flightrec.interval.s", 0.0), \
                prop_override("slo.flightrec.keep", 3):
            for _ in range(6):
                assert flightrec.trigger("manual") is not None
        assert len(flightrec.bundle_names()) == 3

    def test_unknown_reason_collapses_to_manual(self, flightrec):
        with prop_override("slo.flightrec.interval.s", 0.0):
            path = flightrec.trigger("not-a-reason")
        assert path is not None and path.endswith("manual")

    def test_disabled_without_directory(self):
        slo.FLIGHTREC.reset()
        assert slo.FLIGHTREC.trigger("manual") is None


# -- cost ledger ------------------------------------------------------------


def _cost(tenant, shape="count:BBOX", dur_s=0.01, status=200, **charges):
    c = RequestCost(
        tenant=tenant, endpoint="count", lane="interactive", shape=shape
    )
    for field, amount in charges.items():
        c.charge(field, amount)
    c.dur_s = dur_s
    c.status = status
    return c


class TestCostLedger:
    def test_per_tenant_isolation(self):
        led = CostLedger()
        led.record(_cost("a", device_seconds=1.0, device_launches=1))
        led.record(_cost("b", device_seconds=3.0, device_launches=2))
        led.record(_cost("a", device_seconds=0.5, device_launches=1))
        snap = led.snapshot(top=10)
        ta, tb = snap["tenants"]["a"], snap["tenants"]["b"]
        assert ta["requests"] == 2 and tb["requests"] == 1
        assert ta["cost"]["device_seconds"] == pytest.approx(1.5)
        assert tb["cost"]["device_seconds"] == pytest.approx(3.0)
        assert ta["cost"]["device_launches"] == 2
        # per-shape aggregation sees all three
        assert snap["shapes"]["count:BBOX"]["requests"] == 3

    def test_latency_quantiles_per_tenant(self):
        led = CostLedger()
        for _ in range(9):
            led.record(_cost("a", dur_s=0.004))
        led.record(_cost("a", dur_s=2.0))  # rank 9.9 of 10 => 2.5s bucket
        agg = led.snapshot(top=5)["tenants"]["a"]
        assert agg["p50_ms"] == 5.0
        assert agg["p99_ms"] == 2500.0

    def test_bounded_tenant_keyspace(self):
        led = CostLedger()
        for i in range(300):
            led.record(_cost(f"tenant-{i}"))
        snap = led.snapshot(top=500)
        assert len(snap["tenants"]) <= 257
        assert snap["tenants"]["other"]["requests"] >= 43

    def test_unknown_field_raises(self):
        with pytest.raises(KeyError):
            RequestCost().charge("not_a_ledger_field", 1)

    def test_fusion_width_folds_as_max(self):
        c = RequestCost(tenant="a")
        c.charge("fusion_width", 4)
        c.charge("fusion_width", 2)
        assert c.snapshot_fields()["fusion_width"] == 4

    def test_top_requests_ranked_by_cost(self):
        led = CostLedger()
        cheap = _cost("a", device_seconds=0.001)
        cheap.trace_id = "cheap"
        dear = _cost("b", device_seconds=9.0, compile_seconds=3.0)
        dear.trace_id = "dear"
        led.record(cheap)
        led.record(dear)
        top = led.snapshot(top=1)["top_requests"]
        assert top[0]["trace_id"] == "dear"
        assert top[0]["cost_s"] == pytest.approx(12.0)

    def test_charges_noop_outside_request(self):
        # no collector installed: must neither raise nor leak anywhere
        ledger.charge("device_seconds", 1.0)

    def test_disabled_ledger_skips_the_fold_but_not_slo(self):
        """ledger.enabled=False must not fold into the process ledger —
        and must NOT silently disable the SLO engine, whose only feed
        is finish_request (the switches are independent)."""
        before = ledger.LEDGER.requests
        with prop_override("ledger.enabled", False), \
                prop_override("slo.flightrec.burn", 0.0), \
                slo.fresh_engine() as eng:

            class _Done:
                dur_s = 0.002
                trace_id = "x"
                recording = False

            with ledger.collect_cost(
                tenant="x", endpoint="count", lane="interactive"
            ) as cost:
                assert cost is not None  # SLO still needs the meta
                ledger.charge("device_seconds", 1.0)
                cost.status = 200
            ledger.finish_request(cost, _Done)
            assert ledger.LEDGER.requests == before  # no ledger fold
            d = slo_def("interactive")
            with eng._lock:
                lane = eng._lanes.get("interactive")
                n = lane.merged(d.window_s)["n"] if lane else 0
            assert n == 1  # ...but the SLO engine observed the request


class TestCostFromTrace:
    def test_span_tree_assembly(self):
        doc = {
            "trace_id": "t", "duration_ms": 100.0,
            "spans": {
                "name": "GET /count/x", "dur_ms": 100.0, "attrs": {},
                "children": [
                    {"name": "sched.execute", "dur_ms": 40.0,
                     "attrs": {"fused": 4}, "children": []},
                    {"name": "store.read", "dur_ms": 10.0,
                     "attrs": {"bytes": 2048, "chunks": 3,
                               "chunk_total": 10}, "children": []},
                    {"name": "store.decode", "dur_ms": 5.0, "attrs": {},
                     "children": []},
                    {"name": "xla.compile", "dur_ms": 25.0,
                     "attrs": {"signature": "knn:k=8"}, "children": []},
                ],
            },
        }
        costs = cost_from_trace(doc)
        assert costs["device_launches"] == 1
        assert costs["device_seconds"] == pytest.approx(0.01)  # 40ms / 4
        assert costs["fusion_width"] == 4
        assert costs["read_bytes"] == 2048
        assert costs["chunks_read"] == 3
        assert costs["chunks_pruned"] == 7
        assert costs["decode_seconds"] == pytest.approx(0.005)
        assert costs["compile_seconds"] == pytest.approx(0.025)


# -- e2e: serving flow under lockcheck --------------------------------------


SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


def _fs_store(tmp_path, n=512):
    from geomesa_tpu.filter.ecql import parse_instant
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(str(tmp_path / "store"))
    ds.create_schema("gdelt", SPEC)
    rng = np.random.default_rng(11)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write("gdelt", {
        "name": rng.choice(["a", "b"], n),
        "dtg": t0 + rng.integers(0, 10**8, n),
        "geom": np.stack(
            [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
        ),
    }, fids=np.arange(n))
    ds.flush("gdelt")
    return ds


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return r.status, dict(r.headers), json.loads(r.read())


class TestServingE2E:
    def test_slo_to_readyz_flow(self, tmp_path):
        """The acceptance flow: a breaching workload lights /stats/slo,
        /readyz reports the burning SLO as degraded detail (still 200),
        the /metrics exemplar resolves to a captured trace, the ledger
        attributes per-tenant cost, and the flight recorder lands a
        burn-rate bundle under the store root."""
        from geomesa_tpu.sched import SchedConfig
        from geomesa_tpu.server import serve_background

        ds = _fs_store(tmp_path)
        prev_engine = slo.ENGINE
        slo.ENGINE = SloEngine()
        ledger.LEDGER.reset()
        slo.FLIGHTREC.reset()
        resilience.reset()
        try:
            server, _ = serve_background(
                ds, resident=True,
                sched=SchedConfig(max_inflight=1, default_deadline_ms=None),
            )
            host, port = server.server_address[:2]
            base = f"http://{host}:{port}"
            cql = quote("BBOX(geom, -10, -10, 10, 10)")
            with prop_override("slo.interactive.threshold.ms", 0.0001), \
                    prop_override("slo.flightrec.interval.s", 0.0):
                for i in range(4):
                    st, _, _ = _get(
                        base,
                        f"/count/gdelt?cql={cql}&loose=1&tenant=t{i % 2}",
                    )
                    assert st == 200
                # the SLO fold runs on the server thread AFTER the
                # response body is written: poll (inside the override
                # scope) until the last request has been observed
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    st, _, doc = _get(base, "/stats/slo")
                    if doc["slos"]["interactive"]["requests"] >= 4:
                        break
                    time.sleep(0.02)
            # /stats/slo: every request breached the (absurd) threshold
            assert st == 200
            s = doc["slos"]["interactive"]
            assert s["bad"] == s["requests"] == 4
            assert s["burning"] is True
            # /readyz: burning is degraded DETAIL, not unready
            st, _, ready = _get(base, "/readyz")
            assert st == 200 and ready["ready"] is True
            assert "interactive" in ready["slo_burning"]
            # /metrics: exemplars only under OpenMetrics negotiation —
            # a classic scrape must stay suffix-free
            with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
                assert "trace_id" not in r.read().decode()
            req = urllib.request.Request(
                f"{base}/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert "openmetrics-text" in r.headers.get("Content-Type")
                text = r.read().decode()
            tids = {
                ln.split('trace_id="')[1].split('"')[0]
                for ln in text.splitlines()
                if ln.startswith("geomesa_slo_latency_seconds_bucket")
                and "trace_id=" in ln
            }
            assert tids, "no exemplars on the slo latency histogram"
            resolved = []
            for tid in tids:
                try:
                    st, _, trace = _get(base, f"/debug/traces/{tid}")
                except urllib.error.HTTPError:
                    continue  # an older test's evicted trace
                if st == 200 and trace["trace_id"] == tid:
                    resolved.append(tid)
            assert resolved, f"no exemplar resolved to a trace: {tids}"
            # the ledger attributed per-tenant cost, and the /stats
            # roll-up carries both new sections
            st, _, led = _get(base, "/stats/ledger")
            assert {"t0", "t1"} <= set(led["tenants"])
            assert led["tenants"]["t0"]["cost"].get(
                "device_launches", 0
            ) >= 1
            st, _, roll = _get(base, "/stats")
            assert "slo" in roll and "ledger" in roll
            # the burn crossed slo.flightrec.burn: a bundle exists and
            # names the burn + the compile attribution inside
            names = slo.FLIGHTREC.bundle_names()
            assert any(n.endswith("burn-rate") for n in names)
            server.shutdown()
            server.scheduler.shutdown(timeout=2.0)
        finally:
            slo.ENGINE = prev_engine
            slo.FLIGHTREC.reset()
            ledger.LEDGER.reset()
            resilience.reset()

    def test_fault_free_serving_stays_quiet(self, tmp_path):
        """No breach, no bundle: a healthy serve leg must not trip the
        recorder, and /readyz must report nothing burning."""
        from geomesa_tpu.server import serve_background

        ds = _fs_store(tmp_path, n=128)
        prev_engine = slo.ENGINE
        slo.ENGINE = SloEngine()
        slo.FLIGHTREC.reset()
        try:
            server, _ = serve_background(ds)
            host, port = server.server_address[:2]
            base = f"http://{host}:{port}"
            cql = quote("BBOX(geom, -10, -10, 10, 10)")
            with prop_override("slo.interactive.threshold.ms", 60000.0):
                for _ in range(3):
                    st, _, _ = _get(base, f"/count/gdelt?cql={cql}")
                    assert st == 200
            st, _, ready = _get(base, "/readyz")
            assert ready["slo_burning"] == []
            assert slo.FLIGHTREC.bundle_names() == []
            st, _, doc = _get(base, "/stats/slo")
            assert doc["slos"]["interactive"]["bad"] == 0
            server.shutdown()
        finally:
            slo.ENGINE = prev_engine
            slo.FLIGHTREC.reset()

    def test_slo_disabled_is_inert(self, tmp_path):
        from geomesa_tpu.server import serve_background

        ds = _fs_store(tmp_path, n=64)
        with prop_override("slo.enabled", False):
            server, _ = serve_background(ds)
            host, port = server.server_address[:2]
            base = f"http://{host}:{port}"
            cql = quote("BBOX(geom, -1, -1, 1, 1)")
            st, _, _ = _get(base, f"/count/gdelt?cql={cql}")
            assert st == 200
            st, _, doc = _get(base, "/stats/slo")
            assert doc == {"enabled": False, "slos": {}, "series": {}}
            st, _, ready = _get(base, "/readyz")
            assert ready["slo_burning"] == []
            server.shutdown()
