"""System properties, query interceptors, guard rails, age-off."""

import numpy as np
import pytest

from geomesa_tpu.conf import (
    QueryTimeout,
    clear_prop,
    prop_override,
    set_prop,
    sys_prop,
)
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.kv import KVDataStore, MemoryKV
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,dtg:Date,*geom:Point"


def _write_points(ds, n=10):
    ds.create_schema(SimpleFeatureType.create("t", SPEC))
    ds.write(
        "t",
        {
            "name": [f"n{i}" for i in range(n)],
            "dtg": [1000 * (i + 1) for i in range(n)],
            "geom": np.stack(
                [np.linspace(0, 9, n), np.linspace(0, 9, n)], axis=1
            ),
        },
        fids=[f"f{i}" for i in range(n)],
    )
    return ds


def test_sys_prop_tiers(monkeypatch):
    assert sys_prop("scan.ranges.target") == 2000
    monkeypatch.setenv("GEOMESA_TPU_SCAN_RANGES_TARGET", "77")
    assert sys_prop("scan.ranges.target") == 77
    set_prop("scan.ranges.target", 11)
    assert sys_prop("scan.ranges.target") == 11
    clear_prop("scan.ranges.target")
    assert sys_prop("scan.ranges.target") == 77
    with pytest.raises(KeyError):
        sys_prop("not.a.prop")


def test_sft_user_data_ranges_tier():
    ds = MemoryDataStore()
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.scan.ranges.target"] = "3"
    ds.create_schema(sft)
    ds.write(
        "t",
        {"name": ["a"], "dtg": [1000], "geom": np.array([[1.0, 1.0]])},
    )
    plan = ds.plan("t", "bbox(geom, -60, -60, 60, 60)")
    # the per-envelope budget floors at 16; the tier still shrinks the plan
    assert plan.ranges is not None and len(plan.ranges) <= 16
    del sft.user_data["geomesa.scan.ranges.target"]
    default_plan = ds.plan("t", "bbox(geom, -60, -60, 60, 60)")
    assert len(default_plan.ranges) > len(plan.ranges)


def test_full_table_scan_guard():
    ds = _write_points(MemoryDataStore())
    assert len(ds.query("t").batch) == 10  # allowed by default
    with prop_override("query.block.full.table", True):
        with pytest.raises(ValueError, match="full-table scan"):
            ds.query("t")
        # pruning queries still fine
        assert len(ds.query("t", "bbox(geom, 0, 0, 4, 4)").batch) == 5


def test_full_table_scan_guard_via_user_data():
    ds = MemoryDataStore()
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.block.full.table"] = "true"
    ds.create_schema(sft)
    ds.write(
        "t", {"name": ["a"], "dtg": [1000], "geom": np.array([[1.0, 1.0]])}
    )
    with pytest.raises(ValueError, match="blocked"):
        ds.query("t")


def test_max_features_property():
    ds = _write_points(MemoryDataStore())
    with prop_override("query.max.features", 4):
        assert len(ds.query("t", "bbox(geom, -10, -10, 10, 10)").batch) == 4


def test_custom_interceptor_from_user_data():
    ds = MemoryDataStore()
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.query.interceptors"] = (
        "tests.test_conf_interceptors.OnlyFirstFive"
    )
    ds.create_schema(sft)
    ds.write(
        "t",
        {
            "name": [f"n{i}" for i in range(10)],
            "dtg": [1000] * 10,
            "geom": np.zeros((10, 2)),
        },
    )
    assert len(ds.query("t").batch) == 5


class OnlyFirstFive:
    def rewrite(self, query, sft):
        import dataclasses

        return dataclasses.replace(query, max_features=5)

    def guard(self, plan):
        pass


def test_kv_query_timeout():
    # no scan.chunk shrinking: the deadline must fire even when the whole
    # scan fits in one buffer (checked per range and after the final flush)
    ds = _write_points(KVDataStore(MemoryKV()))
    with prop_override("query.timeout", 1):
        import time

        real = time.perf_counter
        state = {"t": real()}

        def advancing():  # +1s per call: blows the 1ms budget instantly
            state["t"] += 1.0
            return state["t"]

        with pytest.raises(QueryTimeout):
            time.perf_counter = advancing
            try:
                ds.query("t")
            finally:
                time.perf_counter = real


def test_age_off_memory_and_fs(tmp_path):
    ds = _write_points(MemoryDataStore())
    assert ds.age_off("t", before_ms=5500) == 5
    assert len(ds.query("t").batch) == 5

    fs = _write_points(FileSystemDataStore(str(tmp_path)))
    fs.flush("t")
    assert fs.age_off("t", before_ms=5500) == 5
    assert len(fs.query("t").batch) == 5
    # delete survives reopen
    fs2 = FileSystemDataStore(str(tmp_path))
    assert len(fs2.query("t").batch) == 5


def test_fs_delete_all(tmp_path):
    fs = _write_points(FileSystemDataStore(str(tmp_path)), n=3)
    fs.flush("t")
    assert fs.delete("t", ["f0", "f1", "f2"]) == 3
    assert len(fs.query("t").batch) == 0


def test_prop_override_restores_prior_override():
    set_prop("query.timeout", 5000)
    try:
        with prop_override("query.timeout", 0):
            assert sys_prop("query.timeout") == 0
        assert sys_prop("query.timeout") == 5000
    finally:
        clear_prop("query.timeout")


def test_internal_queries_bypass_max_features_cap():
    ds = _write_points(MemoryDataStore())
    with prop_override("query.max.features", 2):
        # age_off must sweep ALL expired rows, not the first 2
        assert ds.age_off("t", before_ms=5500) == 5
        assert len(ds.query("t", "bbox(geom, -10, -10, 10, 10)").batch) == 2


def test_proximity_far_apart_inputs_prunes():
    from geomesa_tpu.geom import Point
    from geomesa_tpu.process import proximity_search

    ds = MemoryDataStore()
    ds.create_schema(SimpleFeatureType.create("t", SPEC))
    n = 50
    ds.write(
        "t",
        {
            "name": [f"n{i}" for i in range(n)],
            "dtg": [1000] * n,
            "geom": np.stack(
                [np.linspace(-40, 40, n), np.zeros(n)], axis=1
            ),
        },
    )
    b, dist = proximity_search(ds, "t", [Point(-40, 0), Point(40, 0)], 0.5)
    # only the two endpoints, nothing from the span in between
    assert len(b) == 2


def test_stateful_interceptor_cached_per_schema():
    ds = MemoryDataStore()
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.query.interceptors"] = (
        "tests.test_conf_interceptors.CountingInterceptor"
    )
    ds.create_schema(sft)
    ds.write(
        "t", {"name": ["a"], "dtg": [1000], "geom": np.array([[1.0, 1.0]])}
    )
    ds.query("t")
    ds.query("t")
    from geomesa_tpu.query.interceptor import _DECLARED_CACHE

    cached = _DECLARED_CACHE["tests.test_conf_interceptors.CountingInterceptor"]
    assert cached[0].calls >= 2  # same instance saw both queries
    # the cache must NOT leak into user_data (it would corrupt sft.spec
    # and brick persisted schema.json manifests)
    assert all(not k.startswith("__") for k in sft.user_data)
    SimpleFeatureType.create("t", sft.spec)  # spec still round-trips


def test_interceptors_persist_through_fs_store(tmp_path):
    # declared interceptor chains (incl. multiple, ':'-separated) survive
    # the spec round-trip through schema.json
    from geomesa_tpu.store.fs import FileSystemDataStore

    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.query.interceptors"] = (
        "tests.test_conf_interceptors.CountingInterceptor:"
        "tests.test_conf_interceptors.OnlyFirstFive"
    )
    ds = FileSystemDataStore(str(tmp_path))
    ds.create_schema(sft)
    ds.write(
        "t",
        {
            "name": [f"n{i}" for i in range(10)],
            "dtg": [1000] * 10,
            "geom": np.zeros((10, 2)),
        },
    )
    ds.flush("t")
    assert len(ds.query("t").batch) == 5  # OnlyFirstFive active
    ds2 = FileSystemDataStore(str(tmp_path))  # reopen from disk
    assert len(ds2.query("t").batch) == 5


def test_comma_user_data_survives_spec_roundtrip(tmp_path):
    # commas inside user-data values are escaped in the spec string, so a
    # ','-joined interceptor list no longer bricks a persisted store
    sft = SimpleFeatureType.create("t", SPEC)
    sft.user_data["geomesa.query.interceptors"] = (
        "tests.test_conf_interceptors.CountingInterceptor,"
        "tests.test_conf_interceptors.OnlyFirstFive"
    )
    rt = SimpleFeatureType.create("t", sft.spec)
    assert rt.user_data == sft.user_data
    ds = FileSystemDataStore(str(tmp_path))
    ds.create_schema(sft)
    ds.write(
        "t",
        {
            "name": [f"n{i}" for i in range(10)],
            "dtg": [1000] * 10,
            "geom": np.zeros((10, 2)),
        },
    )
    ds.flush("t")
    ds2 = FileSystemDataStore(str(tmp_path))  # reopen must not raise
    assert len(ds2.query("t").batch) == 5


def test_full_table_scan_guard_exempts_internal():
    from geomesa_tpu.query.plan import internal_query

    ds = _write_points(MemoryDataStore())
    with prop_override("query.block.full.table", True):
        with pytest.raises(ValueError, match="full-table scan"):
            ds.query("t")
        # internal maintenance scans are exempt
        assert len(ds.query("t", internal_query(ast.Include)).batch) == 10


class CountingInterceptor:
    def __init__(self):
        self.calls = 0

    def rewrite(self, query, sft):
        self.calls += 1
        return query

    def guard(self, plan):
        pass
