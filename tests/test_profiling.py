"""Profiling registry + instrumentation of the query path."""

import numpy as np

from geomesa_tpu import profiling
from geomesa_tpu.store.memory import MemoryDataStore


def test_profile_registry():
    profiling.reset()
    with profiling.profile("unit.block"):
        pass

    @profiling.profiled("unit.fn")
    def f(x):
        return x + 1

    assert f(1) == 2 and f(2) == 3
    t = profiling.timings()
    assert t["unit.block"]["count"] == 1
    assert t["unit.fn"]["count"] == 2
    assert "unit.fn" in profiling.report()
    profiling.reset()
    assert profiling.timings() == {}


def test_query_path_is_instrumented():
    profiling.reset()
    ds = MemoryDataStore()
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.write(
        "t",
        {"dtg": np.arange(100) * 1000, "geom": np.zeros((100, 2))},
        fids=np.arange(100),
    )
    ds.query("t", "BBOX(geom, -1, -1, 1, 1)")
    t = profiling.timings()
    assert t.get("query.scan", {}).get("count", 0) >= 1
    profiling.reset()
