"""Regression tests for code-review findings (host spatial semantics,
parser edge cases, store edge cases, planner budgets)."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.geom import parse_wkt
from geomesa_tpu.geom.predicates import geometry_intersects, geometry_within
from geomesa_tpu.store import MemoryDataStore


def test_multipolygon_contained_part_intersects():
    a = parse_wkt("POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))")
    b = parse_wkt(
        "MULTIPOLYGON (((5 5, 6 5, 6 6, 5 6, 5 5)), "
        "((0.4 0.4, 0.6 0.4, 0.6 0.6, 0.4 0.6, 0.4 0.4)))"
    )
    assert geometry_intersects(a, b)
    assert geometry_intersects(b, a)


def test_geometry_within_semantics():
    outer = parse_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")
    inside = parse_wkt("POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))")
    crossing = parse_wkt("POLYGON ((8 8, 12 8, 12 12, 8 12, 8 8))")
    assert geometry_within(inside, outer)
    assert not geometry_within(crossing, outer)
    assert not geometry_within(outer, inside)


def test_within_contains_on_line_column():
    sft = SimpleFeatureType.create("t", "*geom:LineString")
    batch = FeatureBatch.from_columns(
        sft,
        {
            "geom": [
                "LINESTRING (1 1, 2 2)",  # within P
                "LINESTRING (8 8, 12 12)",  # crosses P boundary
            ]
        },
    )
    within = evaluate_host(
        parse_ecql("WITHIN(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"), batch
    )
    np.testing.assert_array_equal(within, [True, False])
    intersects = evaluate_host(
        parse_ecql("INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"),
        batch,
    )
    np.testing.assert_array_equal(intersects, [True, True])


def test_contains_on_point_column_is_false_for_polygons():
    sft = SimpleFeatureType.create("t", "*geom:Point")
    batch = FeatureBatch.from_columns(sft, {"geom": np.array([[5.0, 5.0]])})
    m = evaluate_host(
        parse_ecql("CONTAINS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"),
        batch,
    )
    np.testing.assert_array_equal(m, [False])


def test_quoted_during_instants():
    f = parse_ecql("dtg DURING '2020-01-01T00:00:00Z'/'2020-01-02T00:00:00Z'")
    assert f.t0 == parse_instant("2020-01-01T00:00:00")
    f2 = parse_ecql("dtg AFTER '2020-01-01T00:00:00'")
    assert f2.value == parse_instant("2020-01-01T00:00:00")


def test_get_by_ids_after_delete_all():
    store = MemoryDataStore()
    store.create_schema("t", "v:Int,*geom:Point")
    store.write("t", {"v": [1, 2], "geom": np.array([[0.0, 0.0], [1.0, 1.0]])}, fids=[10, 20])
    store.delete("t", [10, 20])
    assert len(store.get_by_ids("t", [10])) == 0


def test_huge_interval_range_budget():
    from geomesa_tpu.filter.extract import FilterBounds
    from geomesa_tpu.index.keyspaces import Z3KeySpace

    ks = Z3KeySpace("geom", "dtg")
    t0 = parse_instant("2000-01-01T00:00:00")
    t1 = parse_instant("2020-01-01T00:00:00")  # ~1043 weekly bins
    from geomesa_tpu.geom import Envelope

    geoms = FilterBounds(((Envelope(-5, 42, 8, 51), None),))
    intervals = FilterBounds(((t0, t1),))
    ranges = ks.scan_ranges(geoms, intervals, max_ranges=2000)
    assert len(ranges) <= 2200, f"{len(ranges)} ranges exceed budget"
    # and a 10000-bin day interval collapses to one coarse range
    ks_day = Z3KeySpace("geom", "dtg", period="day")
    from geomesa_tpu.curves.binnedtime import TimePeriod

    ks_day = Z3KeySpace("geom", "dtg", TimePeriod.DAY)
    ranges = ks_day.scan_ranges(geoms, intervals, max_ranges=2000)
    assert len(ranges) == 1


def test_wide_key_zranges_skips_native():
    """dims * bits_per_dim > 64 must not reach the C path (uint64 prefix
    shifts would be UB); the Python oracle handles wide keys."""
    from geomesa_tpu.curves.zranges import zranges

    lo, hi = (1, 2, 3), (2**22 - 2, 2**22 - 3, 2**22 - 5)
    with_native = zranges(lo, hi, bits_per_dim=22)
    without = zranges(lo, hi, bits_per_dim=22, use_native=False)
    assert with_native == without


def test_st_dwithin_exact_on_segment_interiors():
    """st_distance must use point-to-segment distance, not vertex-to-vertex
    (a point near a long edge's interior was wrongly reported far)."""
    from geomesa_tpu.geom import LineString, Point, Polygon
    from geomesa_tpu.sql.functions import st_distance, st_dwithin

    p, line = Point(5, 1), LineString([(0, 0), (10, 0)])
    assert st_distance(p, line) == 1.0
    assert st_dwithin(p, line, 2.0)
    poly = Polygon([(0, 0), (10, 0), (10, 10), (0, 10), (0, 0)])
    assert st_distance(Point(5, -3), poly) == 3.0
    assert st_distance(line, poly) == 0.0  # boundary contact


# -- round-2 review findings -------------------------------------------------


def _kv_store():
    from geomesa_tpu.store.kv import KVDataStore, MemoryKV

    ds = KVDataStore(MemoryKV())
    ds.create_schema(
        SimpleFeatureType.create("t", "name:String,dtg:Date,*geom:Point")
    )
    return ds


def test_kv_overlapping_or_ranges_no_duplicates():
    ds = _kv_store()
    ds.write(
        "t",
        {"name": ["a"], "dtg": [1000], "geom": np.array([[5.0, 5.0]])},
        fids=["f0"],
    )
    q = ds.query("t", "bbox(geom, 0, 0, 10, 10) or bbox(geom, 2, 2, 12, 12)")
    assert list(q.batch.fids) == ["f0"]  # scanned once despite overlapping ranges


def test_kv_upsert_replaces_index_rows():
    ds = _kv_store()
    ds.write(
        "t",
        {"name": ["old"], "dtg": [1000], "geom": np.array([[5.0, 5.0]])},
        fids=["f7"],
    )
    ds.write(
        "t",
        {"name": ["new"], "dtg": [2000], "geom": np.array([[50.0, 50.0]])},
        fids=["f7"],
    )
    # stale z3 row at the old location must be gone
    q_old = ds.query("t", "bbox(geom, 0, 0, 10, 10)")
    assert len(q_old.batch) == 0
    q_new = ds.query("t", "bbox(geom, 45, 45, 55, 55)")
    assert list(q_new.batch.column("name")) == ["new"]
    # and the exact count reflects one live feature
    q_all = ds.query("t")
    assert q_all.total == 1
    # delete removes it everywhere, permanently
    assert ds.delete("t", ["f7"]) == 1
    assert len(ds.query("t").batch) == 0
    assert ds.query("t").total == 0


def test_kv_delete_updates_count_stat():
    ds = _kv_store()
    ds.write(
        "t",
        {
            "name": ["a", "b", "c"],
            "dtg": [1, 2, 3],
            "geom": np.array([[0.0, 0.0], [1.0, 1.0], [2.0, 2.0]]),
        },
        fids=["x", "y", "z"],
    )
    assert ds.query("t").total == 3
    ds.delete("t", ["x", "y"])
    assert ds.query("t").total == 1


def test_st_distance_polygon_hole_vertices():
    from geomesa_tpu.geom import LineString, Polygon
    from geomesa_tpu.sql.functions import st_distance

    shell = np.array([[0, 0], [6, 0], [6, 6], [0, 6], [0, 0]], dtype=float)
    hole = np.array(
        [[1, 2], [2, 2], [2, 1], [4, 1], [4, 4], [1, 4], [1, 2]], dtype=float
    )
    poly = Polygon(shell, (hole,))
    seg = LineString(np.array([[2.2, 2.5], [2.5, 2.2]]))
    d = st_distance(poly, seg)
    # nearest point is the protruding hole corner (2, 2): the segment lies
    # on x + y = 4.7, so the distance is 0.7 / sqrt(2)
    assert abs(d - 0.7 / np.sqrt(2)) < 1e-9


# -- advisor round-2 findings ------------------------------------------------


def test_lambda_str_filter_fails_closed_on_visibility():
    """A visibility-labeled live row must not leak to a caller using the
    plain str/ast filter path (no auths supplied => no authorizations)."""
    from geomesa_tpu.query.plan import Query
    from geomesa_tpu.stream.lambda_store import LambdaDataStore

    sft = SimpleFeatureType.create("lv", "count:Int,*geom:Point:srid=4326")
    persistent = MemoryDataStore()
    persistent.create_schema(sft)
    lam = LambdaDataStore(persistent, "lv")
    batch = FeatureBatch.from_columns(
        sft, {"count": [1, 2], "geom": np.zeros((2, 2))}
    ).with_visibility(["secret", ""])
    lam.live.put(dict(batch.columns), batch.fids)
    # str path: labeled row hidden, unlabeled row visible
    got = lam.query("INCLUDE")
    assert sorted(got.column("count").tolist()) == [2]
    # Query path with the right auths still sees both
    got = lam.query(Query("INCLUDE", hints={"auths": ("secret",)}))
    assert sorted(got.column("count").tolist()) == [1, 2]


def test_fs_failed_flush_preserves_previous_generation(tmp_path, monkeypatch):
    """Write-new-then-publish (ISSUE 3): a flush that fails mid-write
    leaves the PREVIOUS on-disk generation fully published and readable
    — concurrent readers keep serving the old rows, the writer retries
    from its buffered pending, and the retry publishes everything."""
    import geomesa_tpu.store.fs as fsmod
    from geomesa_tpu.store.fs import FileSystemDataStore

    root = str(tmp_path / "cat")
    sft = SimpleFeatureType.create("q", "count:Int,*geom:Point:srid=4326")
    ds = FileSystemDataStore(root)
    ds.create_schema(sft)
    ds.write("q", {"count": [1, 2], "geom": np.zeros((2, 2))})
    ds.flush("q")  # generation 1 published

    ds.write("q", {"count": [3], "geom": np.zeros((1, 2))})
    boom = RuntimeError("disk full")

    def bad_write(*a, **k):
        raise boom

    monkeypatch.setattr(fsmod, "_write_part_file", bad_write)
    with pytest.raises(RuntimeError, match="disk full"):
        ds.flush("q")
    # a second process keeps reading generation 1 — no loss, no raise
    ds2 = FileSystemDataStore(root)
    assert sorted(ds2.query("q").batch.column("count").tolist()) == [1, 2]
    # the writer still holds the new row in pending; its retry (via the
    # query's eager flush) publishes old + new
    monkeypatch.undo()
    assert sorted(ds.query("q").batch.column("count").tolist()) == [1, 2, 3]
    ds3 = FileSystemDataStore(root)
    assert sorted(ds3.query("q").batch.column("count").tolist()) == [1, 2, 3]


def test_fs_legacy_dirty_manifest_still_quarantines(tmp_path):
    """Pre-generation manifests could record a flush that failed AFTER
    unlinking its files (`dirty: true`); readers of such a manifest must
    still fail loudly instead of seeing an empty-but-valid dataset."""
    import json

    from geomesa_tpu.store.fs import FileSystemDataStore

    root = str(tmp_path / "cat")
    sft = SimpleFeatureType.create("q", "count:Int,*geom:Point:srid=4326")
    ds = FileSystemDataStore(root)
    ds.create_schema(sft)
    ds.write("q", {"count": [1, 2], "geom": np.zeros((2, 2))})
    ds.flush("q")
    meta_path = f"{root}/q/schema.json"
    with open(meta_path) as fh:
        meta = json.load(fh)
    meta["dirty"] = True
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)
    ds2 = FileSystemDataStore(root)
    with pytest.raises(RuntimeError, match="quarantined"):
        ds2.query("q")
    ds2.write("q", {"count": [99], "geom": np.zeros((1, 2))})
    with pytest.raises(RuntimeError, match="quarantined"):
        ds2.flush("q")


def test_knn_confidence_pass_respects_max_radius():
    """Near the poles the confidence window rx = kth/cos(lat) can blow up
    ~100x; it must stay clamped to max_radius_deg."""
    from geomesa_tpu.process.knn import knn

    sft = SimpleFeatureType.create("kp", "count:Int,*geom:Point:srid=4326")
    ds = MemoryDataStore()
    ds.create_schema(sft)
    xs = np.array([0.0, 1.0, 2.0, 170.0])
    ys = np.array([89.5, 89.5, 89.5, 89.5])
    ds.write(
        "kp",
        {"count": np.arange(4), "geom": np.stack([xs, ys], axis=1)},
    )
    seen = []
    real_query = ds.query

    def spy(type_name, q):
        f = q.filter if hasattr(q, "filter") else q
        seen.append(f)
        return real_query(type_name, q)

    ds.query = spy
    knn(ds, "kp", 0.0, 89.5, k=3, initial_radius_deg=0.05, max_radius_deg=5.0)
    # every bbox the search issued stays within the max-radius box
    assert seen
    for f in seen:
        bb = f.children[0] if hasattr(f, "children") else f
        assert bb.xmin >= 0.0 - 5.0 - 1e-9
        assert bb.xmax <= 0.0 + 5.0 + 1e-9


def test_geomessage_emits_lowest_compatible_version():
    """Writers emit v2 unless an int fid forces v3, so v2 consumers on a
    shared log keep working; everything still round-trips."""
    from geomesa_tpu.stream.log import Clear, Put, Remove
    from geomesa_tpu.stream.messages import decode_message, encode_message

    sft = SimpleFeatureType.create("vm", "count:Int,*geom:Point:srid=4326")
    put = Put({"count": [1], "geom": np.zeros((1, 2))}, np.array(["a"], dtype=object))
    raw = encode_message(sft, put)
    assert raw[1] == 2
    assert list(decode_message(sft, raw).fids) == ["a"]
    rm_str = Remove(np.array(["a", "b"], dtype=object))
    raw = encode_message(sft, rm_str)
    assert raw[1] == 2
    assert list(decode_message(sft, raw).fids) == ["a", "b"]
    rm_int = Remove(np.array([7, "b"], dtype=object))
    raw = encode_message(sft, rm_int)
    assert raw[1] == 3
    back = decode_message(sft, raw).fids
    assert list(back) == [7, "b"] and isinstance(back[0], int)
