"""Regression tests for code-review findings (host spatial semantics,
parser edge cases, store edge cases, planner budgets)."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.geom import parse_wkt
from geomesa_tpu.geom.predicates import geometry_intersects, geometry_within
from geomesa_tpu.store import MemoryDataStore


def test_multipolygon_contained_part_intersects():
    a = parse_wkt("POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))")
    b = parse_wkt(
        "MULTIPOLYGON (((5 5, 6 5, 6 6, 5 6, 5 5)), "
        "((0.4 0.4, 0.6 0.4, 0.6 0.6, 0.4 0.6, 0.4 0.4)))"
    )
    assert geometry_intersects(a, b)
    assert geometry_intersects(b, a)


def test_geometry_within_semantics():
    outer = parse_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")
    inside = parse_wkt("POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))")
    crossing = parse_wkt("POLYGON ((8 8, 12 8, 12 12, 8 12, 8 8))")
    assert geometry_within(inside, outer)
    assert not geometry_within(crossing, outer)
    assert not geometry_within(outer, inside)


def test_within_contains_on_line_column():
    sft = SimpleFeatureType.create("t", "*geom:LineString")
    batch = FeatureBatch.from_columns(
        sft,
        {
            "geom": [
                "LINESTRING (1 1, 2 2)",  # within P
                "LINESTRING (8 8, 12 12)",  # crosses P boundary
            ]
        },
    )
    within = evaluate_host(
        parse_ecql("WITHIN(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"), batch
    )
    np.testing.assert_array_equal(within, [True, False])
    intersects = evaluate_host(
        parse_ecql("INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"),
        batch,
    )
    np.testing.assert_array_equal(intersects, [True, True])


def test_contains_on_point_column_is_false_for_polygons():
    sft = SimpleFeatureType.create("t", "*geom:Point")
    batch = FeatureBatch.from_columns(sft, {"geom": np.array([[5.0, 5.0]])})
    m = evaluate_host(
        parse_ecql("CONTAINS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))"),
        batch,
    )
    np.testing.assert_array_equal(m, [False])


def test_quoted_during_instants():
    f = parse_ecql("dtg DURING '2020-01-01T00:00:00Z'/'2020-01-02T00:00:00Z'")
    assert f.t0 == parse_instant("2020-01-01T00:00:00")
    f2 = parse_ecql("dtg AFTER '2020-01-01T00:00:00'")
    assert f2.value == parse_instant("2020-01-01T00:00:00")


def test_get_by_ids_after_delete_all():
    store = MemoryDataStore()
    store.create_schema("t", "v:Int,*geom:Point")
    store.write("t", {"v": [1, 2], "geom": np.array([[0.0, 0.0], [1.0, 1.0]])}, fids=[10, 20])
    store.delete("t", [10, 20])
    assert len(store.get_by_ids("t", [10])) == 0


def test_huge_interval_range_budget():
    from geomesa_tpu.filter.extract import FilterBounds
    from geomesa_tpu.index.keyspaces import Z3KeySpace

    ks = Z3KeySpace("geom", "dtg")
    t0 = parse_instant("2000-01-01T00:00:00")
    t1 = parse_instant("2020-01-01T00:00:00")  # ~1043 weekly bins
    from geomesa_tpu.geom import Envelope

    geoms = FilterBounds(((Envelope(-5, 42, 8, 51), None),))
    intervals = FilterBounds(((t0, t1),))
    ranges = ks.scan_ranges(geoms, intervals, max_ranges=2000)
    assert len(ranges) <= 2200, f"{len(ranges)} ranges exceed budget"
    # and a 10000-bin day interval collapses to one coarse range
    ks_day = Z3KeySpace("geom", "dtg", period="day")
    from geomesa_tpu.curves.binnedtime import TimePeriod

    ks_day = Z3KeySpace("geom", "dtg", TimePeriod.DAY)
    ranges = ks_day.scan_ranges(geoms, intervals, max_ranges=2000)
    assert len(ranges) == 1


def test_wide_key_zranges_skips_native():
    """dims * bits_per_dim > 64 must not reach the C path (uint64 prefix
    shifts would be UB); the Python oracle handles wide keys."""
    from geomesa_tpu.curves.zranges import zranges

    lo, hi = (1, 2, 3), (2**22 - 2, 2**22 - 3, 2**22 - 5)
    with_native = zranges(lo, hi, bits_per_dim=22)
    without = zranges(lo, hi, bits_per_dim=22, use_native=False)
    assert with_native == without


def test_st_dwithin_exact_on_segment_interiors():
    """st_distance must use point-to-segment distance, not vertex-to-vertex
    (a point near a long edge's interior was wrongly reported far)."""
    from geomesa_tpu.geom import LineString, Point, Polygon
    from geomesa_tpu.sql.functions import st_distance, st_dwithin

    p, line = Point(5, 1), LineString([(0, 0), (10, 0)])
    assert st_distance(p, line) == 1.0
    assert st_dwithin(p, line, 2.0)
    poly = Polygon([(0, 0), (10, 0), (10, 10), (0, 10), (0, 0)])
    assert st_distance(Point(5, -3), poly) == 3.0
    assert st_distance(line, poly) == 0.0  # boundary contact
