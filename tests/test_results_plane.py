"""Arrow-native result plane (ISSUE 12): content negotiation, streamed
Arrow IPC / BIN serving, device-vs-host BIN bit-identity, delta
dictionary growth across batches, the encode/write span split, ledger
serialization fields, and the merged live-layer round trip."""

import io
import json
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import results
from geomesa_tpu.arrow_io import SORT_KEY_META, read_feature_stream
from geomesa_tpu.conf import prop_override
from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.process.binexport import decode_bin
from geomesa_tpu.server import serve_background
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "track:Integer,name:String,dtg:Date,*geom:Point:srid=4326"
CQL = "BBOX(geom, -5, -5, 5, 5)"


def _seed_store(n=2000, seed=17):
    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write("t", {
        "track": rng.integers(0, 40, n),
        "name": rng.choice(["alpha", "beta", "gamma"], n),
        "dtg": t0 + rng.integers(0, 10**8, n),
        "geom": np.stack(
            [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
        ),
    }, fids=np.arange(n))
    return ds


@pytest.fixture(scope="module")
def resident_url():
    ds = _seed_store()
    server, _ = serve_background(ds, resident=True)
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", server
    server.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=60) as r:
        return r.status, dict(r.headers), r.read()


def _decode_stream(body):
    return list(read_feature_stream(io.BytesIO(body)))


def _concat(batches):
    from geomesa_tpu.features.batch import FeatureBatch

    return FeatureBatch.concat(batches)


# -- content negotiation -----------------------------------------------------


def test_negotiate_format_param_wins():
    nf = results.negotiate_format
    assert nf({"f": "arrow"}, "application/json") == "arrow"
    assert nf({"f": "JSON"}) == "geojson"
    assert nf({"f": "bin"}) == "bin"
    with pytest.raises(ValueError):
        nf({"f": "nope"})


def test_negotiate_format_accept_header():
    nf = results.negotiate_format
    assert nf({}, "application/vnd.apache.arrow.stream") == "arrow"
    assert nf({}, "text/html, application/vnd.geomesa.bin;q=0.9") == "bin"
    assert nf({}, "application/geo+json") == "geojson"
    assert nf({}, "*/*") == "geojson"
    assert nf({}, None) == "geojson"
    # q=0 is an explicit rejection: skip it, not select it
    assert nf(
        {},
        "application/json;q=0, application/vnd.apache.arrow.stream",
    ) == "arrow"
    assert nf({}, "application/json;q=0.5") == "geojson"


def test_unknown_format_is_400(resident_url):
    url, _ = resident_url
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{url}/features/t?f=nope")
    assert e.value.code == 400


# -- streamed arrow serving --------------------------------------------------


def test_arrow_stream_chunked_and_bit_identical(resident_url):
    """f=arrow streams chunked IPC whose decode is bit-identical to the
    resident row set AND row-set-identical to the GeoJSON response."""
    url, server = resident_url
    cql = urllib.parse.quote(CQL)
    _, _, gj = _get(f"{url}/features/t?cql={cql}")
    doc = json.loads(gj)
    status, headers, body = _get(f"{url}/features/t?cql={cql}&f=arrow")
    assert status == 200
    assert headers.get("Transfer-Encoding") == "chunked"
    assert headers.get("Content-Type") == \
        "application/vnd.apache.arrow.stream"
    got = _concat(_decode_stream(body))
    # row-set parity with the GeoJSON response
    assert [str(f) for f in got.fids] == [
        f["id"] for f in doc["features"]
    ]
    # bit-identical columns vs the resident oracle
    di = server.RequestHandlerClass._resident_cache["t"]
    oracle = di.query(CQL)
    assert len(got) == len(oracle) > 0
    for name in oracle.sft.attribute_names:
        a, b = got.column(name), oracle.column(name)
        assert a.dtype == b.dtype
        assert np.array_equal(a, b), name
    # the Z-sorted resident path stamps its sort key (no host re-sort)
    import pyarrow as pa

    schema = pa.ipc.open_stream(io.BytesIO(body)).schema
    assert schema.metadata.get(SORT_KEY_META) == b"z"


def test_arrow_respects_max_features_cap(resident_url):
    url, _ = resident_url
    _, _, body = _get(f"{url}/features/t?f=arrow&maxFeatures=7")
    assert sum(len(b) for b in _decode_stream(body)) == 7


def test_arrow_empty_result(resident_url):
    url, _ = resident_url
    cql = urllib.parse.quote("BBOX(geom, 100, 80, 101, 81)")
    _, _, body = _get(f"{url}/features/t?cql={cql}&f=arrow")
    batches = _decode_stream(body)
    assert sum(len(b) for b in batches) == 0
    # the stream is still self-describing (schema header + EOS)
    import pyarrow as pa

    assert pa.ipc.open_stream(io.BytesIO(body)).schema is not None


def test_arrow_store_rung_partition_stream(tmp_path):
    """Non-resident fs serving streams one batch per partition through
    the prefetch pipeline; the decoded union equals the store query."""
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(str(tmp_path / "s"), partition_size=256)
    ds.create_schema("t", SPEC)
    seed = _seed_store()
    ds.write("t", seed.query("t").batch)
    ds.flush("t")
    server, _ = serve_background(ds)
    host, port = server.server_address[:2]
    try:
        cql = urllib.parse.quote(CQL)
        _, headers, body = _get(
            f"http://{host}:{port}/features/t?cql={cql}&f=arrow"
        )
        assert headers.get("Transfer-Encoding") == "chunked"
        got = _concat(_decode_stream(body))
        expect = ds.query("t", CQL).batch
        assert sorted(str(f) for f in got.fids) == sorted(
            str(f) for f in expect.fids
        )
    finally:
        server.shutdown()


# -- BIN serving -------------------------------------------------------------


def test_bin_endpoint_matches_host_twin(resident_url):
    url, server = resident_url
    di = server.RequestHandlerClass._resident_cache["t"]
    cql = urllib.parse.quote(CQL)
    _, headers, body = _get(
        f"{url}/features/t?cql={cql}&f=bin&track=track"
    )
    assert headers.get("Content-Type") == "application/vnd.geomesa.bin"
    assert body == di.bin_export(CQL, "track")
    assert len(decode_bin(body)) == len(di.query(CQL))
    # 24-byte labeled records + dtg sort
    _, _, body24 = _get(
        f"{url}/features/t?cql={cql}&f=bin&track=track"
        "&label=name&sortBin=1"
    )
    assert body24 == di.bin_export(
        CQL, "track", label_attr="name", sort=True
    )
    rec = decode_bin(body24, labels=True)
    assert (np.diff(rec["dtg"]) >= 0).all()


def test_bin_missing_track_is_400(resident_url):
    url, _ = resident_url
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{url}/features/t?f=bin")
    assert e.value.code == 400


def test_bin_device_rider_bit_identity():
    """The fused device pack (count->cap->compact) is byte-identical to
    the numpy host twin across filter shapes, loose mode, labels, sort
    and the empty edge."""
    ds = _seed_store(seed=23)
    di = DeviceIndex(ds, "t", z_planes=True)
    legs = [
        dict(query="INCLUDE"),
        dict(query=CQL),
        dict(query=CQL, loose=True),
        dict(query=CQL, sort=True),
        dict(query=CQL, label_attr="name"),
        dict(query=CQL, label_attr="name", sort=True),
        dict(query="BBOX(geom, 100, 80, 101, 81)"),  # empty
    ]
    for leg in legs:
        q = leg.pop("query")
        twin = di.bin_export(q, "track", **leg)
        with prop_override("results.bin.engine", "device"):
            dev = results.resident_bin(di, q, "track", **leg)
        assert dev == twin, leg


def test_bin_engine_pin_refuses_inexpressible():
    """A pinned device engine must refuse (not silently switch) when
    the shape needs the host twin — here: non-integer-track is fine,
    but a host-residual filter (attribute equality on a string) is not
    device-expressible."""
    ds = _seed_store(seed=29)
    di = DeviceIndex(ds, "t", z_planes=True)
    q = "name = 'alpha'"
    with prop_override("results.bin.engine", "device"):
        with pytest.raises(ValueError):
            results.resident_bin(di, q, "track")
    # auto falls to the twin silently
    with prop_override("results.bin.engine", "auto"):
        assert results.resident_bin(di, q, "track") == di.bin_export(
            q, "track"
        )


# -- process endpoints through the plane -------------------------------------


def test_knn_arrow_distance_column(resident_url):
    """/knn f=arrow: the kNN distance is a REAL typed column whose
    values match the GeoJSON per-feature properties."""
    url, _ = resident_url
    _, _, gj = _get(f"{url}/knn/t?x=0&y=0&k=5")
    doc = json.loads(gj)
    _, _, body = _get(f"{url}/knn/t?x=0&y=0&k=5&f=arrow")
    got = _concat(_decode_stream(body))
    assert "knn_distance_deg" in got.sft.attribute_names
    assert got.column("knn_distance_deg").dtype == np.float64
    assert [str(f) for f in got.fids] == [
        f["id"] for f in doc["features"]
    ]
    np.testing.assert_allclose(
        got.column("knn_distance_deg"),
        [f["properties"]["knn_distance_deg"] for f in doc["features"]],
    )


def test_proximity_bin_records(resident_url):
    url, _ = resident_url
    pts = urllib.parse.quote("0,0;5,5")
    _, _, body = _get(
        f"{url}/proximity/t?points={pts}&distance=2"
        "&f=bin&track=track"
    )
    _, _, gj = _get(f"{url}/proximity/t?points={pts}&distance=2")
    assert len(decode_bin(body)) == len(json.loads(gj)["features"])


# -- visibility --------------------------------------------------------------


def test_visibility_masked_rows_hidden_in_arrow_and_bin():
    from geomesa_tpu.features.batch import FeatureBatch

    ds = MemoryDataStore()
    ds.create_schema("sec", SPEC)
    n = 300
    rng = np.random.default_rng(31)
    t0 = parse_instant("2020-01-01T00:00:00")
    batch = FeatureBatch.from_columns(
        ds.get_schema("sec"),
        {
            "track": rng.integers(0, 9, n),
            "name": rng.choice(["a", "b"], n),
            "dtg": t0 + rng.integers(0, 10**8, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)],
                axis=1,
            ),
        },
        fids=np.arange(n),
    ).with_visibility(rng.choice(["", "A"], n))
    ds.write("sec", batch)
    server, _ = serve_background(ds, resident=True)
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    try:
        vis = np.asarray(batch.visibilities)
        public, all_rows = int((vis == "").sum()), n
        for auths, expect in ((None, public), ("A", all_rows)):
            sfx = f"&auths={auths}" if auths else ""
            _, _, body = _get(f"{url}/features/sec?f=arrow{sfx}")
            assert sum(len(b) for b in _decode_stream(body)) == expect
            _, _, bn = _get(
                f"{url}/features/sec?f=bin&track=track{sfx}"
            )
            assert len(decode_bin(bn)) == expect
    finally:
        server.shutdown()


# -- streamed live layer -----------------------------------------------------


def test_live_layer_merged_view_arrow_parity(tmp_path):
    """Arrow round trip over the streamed live layer's MERGED
    memtable+disk view: appended-but-uncompacted rows serve in the
    stream, bit-identical to the GeoJSON row set."""
    from geomesa_tpu.store.fs import FileSystemDataStore

    ds = FileSystemDataStore(str(tmp_path / "s"), partition_size=128)
    ds.create_schema("t", SPEC)
    seed = _seed_store(n=400, seed=37)
    ds.write("t", seed.query("t").batch)
    ds.flush("t")
    with prop_override("stream.memtable.rows", 1 << 20):
        server, _ = serve_background(ds, resident=True, stream=True)
        host, port = server.server_address[:2]
        url = f"http://{host}:{port}"
        try:
            doc = {
                "columns": {
                    "track": [7, 7, 7],
                    "name": ["live", "live", "live"],
                    "dtg": [1000, 2000, 3000],
                    "geom": [[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]],
                },
                "fids": [9001, 9002, 9003],
            }
            req = urllib.request.Request(
                f"{url}/append/t", data=json.dumps(doc).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                assert json.loads(r.read())["acked"] == 3
            _, _, gj = _get(f"{url}/features/t")
            geo = json.loads(gj)
            _, _, body = _get(f"{url}/features/t?f=arrow")
            got = _concat(_decode_stream(body))
            assert len(got) == len(geo["features"]) == 403
            assert [str(f) for f in got.fids] == [
                f["id"] for f in geo["features"]
            ]
            assert {"9001", "9002", "9003"} <= {
                str(f) for f in got.fids
            }
            # columns bit-identical to the merged-view oracle
            di = server.RequestHandlerClass._resident_cache["t"]
            oracle = di.query("INCLUDE")
            for name in oracle.sft.attribute_names:
                assert np.array_equal(
                    got.column(name), oracle.column(name)
                ), name
        finally:
            server.shutdown()


# -- delta dictionaries ------------------------------------------------------


def test_delta_dictionary_growth_across_batches():
    """Dictionaries grow monotonically across streamed chunks: later
    record batches reference earlier entries by the SAME ids and carry
    only the new vocabulary."""
    import pyarrow as pa

    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType

    sft = SimpleFeatureType.create("d", "name:String,*geom:Point")
    mk = lambda names, f0: FeatureBatch.from_columns(  # noqa: E731
        sft,
        {"name": names, "geom": np.zeros((len(names), 2))},
        np.arange(f0, f0 + len(names)),
    )
    b1 = mk(["aa", "bb", "aa"], 0)
    b2 = mk(["bb", "cc", "dd"], 3)
    chunks = list(results.arrow_stream_chunks([b1, b2], chunk_rows=8))
    data = b"".join(chunks)
    rdr = pa.ipc.open_stream(io.BytesIO(data))
    dicts = []
    for rb in rdr:
        col = rb.column(rb.schema.get_field_index("name"))
        dicts.append(col.dictionary.to_pylist())
    # batch 1 established [aa, bb]; batch 2 appended ONLY [cc, dd]
    assert dicts[0] == ["aa", "bb"]
    assert dicts[-1] == ["aa", "bb", "cc", "dd"]
    got = _concat(_decode_stream(data))
    assert list(got.column("name")) == [
        "aa", "bb", "aa", "bb", "cc", "dd"
    ]


def test_oocscan_query_batches_feeds_the_encoders(tmp_path):
    """StreamedDeviceScan.query_batches: per-slab hit batches equal the
    materialized query() row set, and the generator feeds the shared
    arrow encoder (the larger-than-HBM export recipe)."""
    from geomesa_tpu.store.fs import FileSystemDataStore
    from geomesa_tpu.store.oocscan import StreamedDeviceScan

    ds = FileSystemDataStore(str(tmp_path / "s"), partition_size=256)
    ds.create_schema("t", SPEC)
    ds.write("t", _seed_store(n=3000, seed=47).query("t").batch)
    ds.flush("t")
    scan = StreamedDeviceScan(ds, "t")
    got = _concat(list(scan.query_batches(CQL)))
    expect = scan.query(CQL)
    assert len(got) == len(expect) > 0
    assert sorted(str(f) for f in got.fids) == sorted(
        str(f) for f in expect.fids
    )
    # the export recipe: stream the scan through the shared encoder
    path = str(tmp_path / "out.arrow")
    n = results.write_arrow_stream_file(
        path, scan.query_batches(CQL), ds.get_schema("t")
    )
    assert n > 0
    with open(path, "rb") as fh:
        dec = _concat(_decode_stream(fh.read()))
    assert sorted(str(f) for f in dec.fids) == sorted(
        str(f) for f in expect.fids
    )
    # a filter the device cannot express falls to the store path
    host_only = list(scan.query_batches("name = 'alpha'"))
    oracle = ds.query("t", "name = 'alpha'").batch
    assert sum(len(b) for b in host_only) == len(oracle)


def test_capped_batches_trims_across_stream():
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType

    sft = SimpleFeatureType.create("c", "v:Int,*geom:Point")
    mk = lambda k, f0: FeatureBatch.from_columns(  # noqa: E731
        sft, {"v": np.arange(k), "geom": np.zeros((k, 2))},
        np.arange(f0, f0 + k),
    )
    out = list(results.capped_batches([mk(3, 0), mk(3, 3), mk(3, 6)], 4))
    assert [len(b) for b in out] == [3, 1]
    assert list(out[1].fids) == [3]
    out = list(results.capped_batches([mk(3, 0)], None))
    assert [len(b) for b in out] == [3]


def test_with_extra_columns_rejects_collision_and_mismatch():
    ds = _seed_store(n=10)
    b = ds.query("t").batch
    with pytest.raises(ValueError):
        results.with_extra_columns(b, {"name": np.zeros(10)})
    with pytest.raises(ValueError):
        results.with_extra_columns(b, {"d": np.zeros(3)})
    out = results.with_extra_columns(b, {"d": np.arange(10.0)})
    assert out.column("d").dtype == np.float64


# -- observability -----------------------------------------------------------


def test_encode_write_span_split_and_ledger_fields(resident_url):
    """One /features request produces SIBLING http.encode + http.write
    spans (a slow client can no longer pollute encode attribution) and
    charges encode_seconds / response_bytes to its shape aggregate."""
    url, _ = resident_url
    rid = "results-span-probe"
    req = urllib.request.Request(f"{url}/features/t?f=arrow")
    req.add_header("X-Request-Id", rid)
    with urllib.request.urlopen(req, timeout=60) as r:
        r.read()
        assert r.headers.get("X-Request-Id") == rid
    # trace retention is decided AFTER the response's last byte hits
    # the socket, so a fresh connection can look up the id before the
    # handler thread files the trace — poll briefly
    deadline = time.monotonic() + 5.0
    while True:
        try:
            _, _, body = _get(f"{url}/debug/traces/{rid}")
            break
        except urllib.error.HTTPError as e:
            if e.code != 404 or time.monotonic() > deadline:
                raise
            time.sleep(0.02)
    doc = json.loads(body)

    def names(span, acc):
        acc.append(span["name"])
        for c in span.get("children", ()):
            names(c, acc)
        return acc

    spans = names(doc["spans"], [])
    assert "http.encode" in spans and "http.write" in spans
    _, _, led = _get(f"{url}/stats/ledger")
    text = json.dumps(json.loads(led))
    assert "encode_seconds" in text and "response_bytes" in text
