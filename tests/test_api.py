"""GeoTools-shaped API: DataStoreFinder params -> store, feature sources,
writers, SPI registration."""

import numpy as np
import pytest

from geomesa_tpu.api import (
    DataStoreFinder,
    register_factory,
)

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"


def _fill(ds, n=500, seed=7):
    rng = np.random.default_rng(seed)
    ds.create_schema("t", SPEC)
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b"], n),
            "val": rng.integers(0, 100, n),
            "dtg": rng.integers(1_577_000_000_000, 1_580_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    if hasattr(ds, "flush"):
        ds.flush("t")
    return ds


class TestFinder:
    def test_fs_params(self, tmp_path):
        ds = DataStoreFinder.get_data_store({"fs.path": str(tmp_path)})
        _fill(ds)
        # a second finder call reopens the same durable store
        ds2 = DataStoreFinder.get_data_store({"fs.path": str(tmp_path)})
        assert ds2.get_type_names() == ["t"]

    def test_kv_params(self):
        ds = DataStoreFinder.get_data_store({"kv.catalog": "cat"})
        _fill(ds)
        assert ds.get_type_names() == ["t"]

    def test_kv_sqlite_params(self, tmp_path):
        p = str(tmp_path / "kv.db")
        ds = DataStoreFinder.get_data_store({"kv.sqlite": p})
        _fill(ds)
        ds2 = DataStoreFinder.get_data_store({"kv.sqlite": p})
        assert ds2.get_type_names() == ["t"]

    def test_memory_params(self):
        ds = DataStoreFinder.get_data_store({"memory": True})
        _fill(ds)
        assert ds.get_type_names() == ["t"]

    def test_unknown_params_raise(self):
        with pytest.raises(ValueError, match="no data store factory"):
            DataStoreFinder.get_data_store({"bogus": 1})

    def test_spi_registration(self):
        sentinel = object()
        register_factory(
            lambda p: p.get("custom.proto") == "x",
            lambda p: sentinel,
        )
        got = DataStoreFinder.get_data_store({"custom.proto": "x"})
        assert got._store is sentinel


class TestFeatureSource:
    @pytest.fixture()
    def source(self):
        ds = _fill(DataStoreFinder.get_data_store({"memory": True}))
        return ds, ds.get_feature_source("t")

    def test_count_and_features_match_store(self, source):
        ds, src = source
        q = "BBOX(geom, -5, -5, 5, 5) AND val >= 50"
        expect = ds.query("t", q).batch
        assert src.get_count(q) == len(expect)
        fc = src.get_features(q)
        assert len(fc) == len(expect)
        feats = list(fc)
        assert {f.fid for f in feats} == set(expect.fids.tolist())
        f0 = feats[0]
        assert f0["val"] == f0.get_attribute("val")
        assert set(f0.attributes) == {"name", "val", "dtg", "geom"}

    def test_bounds(self, source):
        ds, src = source
        env = src.get_bounds()
        assert env is not None
        x, y = ds.query("t", "INCLUDE").batch.point_coords()
        assert env.xmin == pytest.approx(x.min())
        assert env.ymax == pytest.approx(y.max())
        # empty query -> None bounds
        assert src.get_bounds("val > 1000000") is None

    def test_missing_type_raises(self, source):
        ds, _ = source
        with pytest.raises(KeyError):
            ds.get_feature_source("nope")


class TestFeatureWriter:
    def test_append_writer_roundtrip(self):
        ds = DataStoreFinder.get_data_store({"memory": True})
        ds.create_schema("t", SPEC)
        with ds.get_feature_writer_append("t") as w:
            for i in range(5):
                w.write(
                    {"name": "n", "val": i, "dtg": 0,
                     "geom": (float(i), float(i))},
                    fid=f"f{i}",
                )
        src = ds.get_feature_source("t")
        assert src.get_count() == 5
        got = src.get_features("BBOX(geom, 2.5, 2.5, 10, 10)")
        assert {f.fid for f in got} == {"f3", "f4"}


class TestLambdaFactory:
    def test_lambda_params_flow(self, tmp_path):
        # the nested persistent params must describe a DURABLE store that
        # already carries the schema (lambda wraps one existing type)
        root = str(tmp_path)
        pre = DataStoreFinder.get_data_store({"fs.path": root})
        pre.create_schema("t", SPEC)
        lam = DataStoreFinder.get_data_store(
            {"lambda.persistent": {"fs.path": root}, "lambda.type": "t"}
        )
        assert lam.get_type_names() == ["t"]
        with lam.get_feature_writer_append("t") as w:
            w.write({"name": "a", "val": 1, "dtg": 0, "geom": (1.0, 2.0)},
                    fid="L1")
        assert lam.get_feature_source("t").get_count() == 1

    def test_lambda_full_surface(self):
        # persistent store must carry the schema before the lambda wraps it
        import geomesa_tpu.api as api

        persistent = DataStoreFinder.get_data_store({"memory": True})
        persistent.create_schema("t", SPEC)
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        lam = api._LambdaStoreShim(LambdaDataStore(persistent._store, "t"))
        ds = api.DataStoreAdapter(lam)
        assert ds.get_type_names() == ["t"]
        with ds.get_feature_writer_append("t") as w:
            w.write({"name": "a", "val": 1, "dtg": 0, "geom": (1.0, 2.0)},
                    fid="x1")
        src = ds.get_feature_source("t")
        assert src.get_count() == 1
        assert {f.fid for f in src.get_features("BBOX(geom, 0, 1, 2, 3)")} == {"x1"}
        with pytest.raises(KeyError):
            ds.get_feature_source("nope")

    def test_memory_param_string_false(self):
        with pytest.raises(ValueError, match="no data store factory"):
            DataStoreFinder.get_data_store({"memory": "false"})


class TestWriterCoercion:
    def test_wkt_and_tuple_geometries(self):
        ds = DataStoreFinder.get_data_store({"memory": True})
        ds.create_schema("t", SPEC)
        with ds.get_feature_writer_append("t") as w:
            w.write({"name": "a", "val": 1, "dtg": 0, "geom": "POINT (1 2)"})
            w.write({"name": "b", "val": 2, "dtg": 0, "geom": (3.0, 4.0)})
        src = ds.get_feature_source("t")
        assert src.get_count() == 2
        assert src.get_count("BBOX(geom, 0.5, 1.5, 1.5, 2.5)") == 1

    def test_generated_fids_unique_across_sessions(self):
        ds = DataStoreFinder.get_data_store({"memory": True})
        ds.create_schema("t", SPEC)
        for _ in range(2):  # two separate writer sessions, no fids given
            with ds.get_feature_writer_append("t") as w:
                for i in range(3):
                    w.write({"name": "a", "val": i, "dtg": 0,
                             "geom": (float(i), 0.0)})
        assert ds.get_feature_source("t").get_count() == 6  # no upsert collisions


class TestLambdaShimParity:
    def _lam(self, tmp_path):
        import geomesa_tpu.api as api
        from geomesa_tpu.stream.lambda_store import LambdaDataStore

        pre = DataStoreFinder.get_data_store({"fs.path": str(tmp_path)})
        pre.create_schema("t", SPEC)
        return api.DataStoreAdapter(
            api._LambdaStoreShim(LambdaDataStore(pre._store, "t"))
        )

    def test_persist_reachable_through_finder_path(self, tmp_path):
        ds = self._lam(tmp_path)
        with ds.get_feature_writer_append("t") as w:
            w.write({"name": "a", "val": 1, "dtg": 0, "geom": (1.0, 2.0)},
                    fid="p1")
        assert ds.persist() == 0  # too fresh to move, but callable
        assert ds.get_feature_source("t").get_count() == 1

    def test_query_accepts_ast_and_honors_max_features(self, tmp_path):
        from geomesa_tpu.filter.ecql import parse_ecql
        from geomesa_tpu.query.plan import Query

        ds = self._lam(tmp_path)
        with ds.get_feature_writer_append("t") as w:
            for i in range(6):
                w.write({"name": "a", "val": i, "dtg": 0,
                         "geom": (float(i), 0.0)}, fid=f"q{i}")
        # parsed AST filter works like on every other store
        got = ds.query("t", parse_ecql("val >= 3")).batch
        assert len(got) == 3
        # Query post-processing applies
        got = ds.query("t", Query(filter="INCLUDE", max_features=2)).batch
        assert len(got) == 2
        got = ds.query(
            "t", Query(filter="INCLUDE", sort_by="val", sort_desc=True)
        ).batch
        assert list(got.columns["val"][:2]) == [5, 4]


def test_store_write_mixed_geometry_column():
    """_coerce_geometry is per-row tolerant on every ingestion path."""
    from geomesa_tpu.geom import Point

    ds = DataStoreFinder.get_data_store({"memory": True})
    ds.create_schema("t", SPEC)
    ds.write(
        "t",
        {"name": ["a", "b", "c"], "val": [1, 2, 3], "dtg": [0, 0, 0],
         "geom": ["POINT (1 2)", (3.0, 4.0), Point(5.0, 6.0)]},
        fids=["m0", "m1", "m2"],
    )
    src = ds.get_feature_source("t")
    assert src.get_count("BBOX(geom, 0.5, 1.5, 1.5, 2.5)") == 1
    assert src.get_count() == 3

def test_query_hints_auths_reach_persistent_layer():
    """Visibility parity: auths in Query hints must flow through the
    lambda shim to the persistent layer instead of being dropped."""
    import geomesa_tpu.api as api
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.query.plan import Query
    from geomesa_tpu.stream.lambda_store import LambdaDataStore

    pre = DataStoreFinder.get_data_store({"memory": True})
    pre.create_schema("t", SPEC)
    sft = SimpleFeatureType.create("t", SPEC)
    labeled = FeatureBatch.from_columns(
        sft,
        {"name": ["s"], "val": [1], "dtg": [0],
         "geom": np.array([[1.0, 2.0]])},
        fids=np.array(["sec1"], dtype=object),
    ).with_visibility(["admin"])
    pre._store.write("t", labeled)
    ds = api.DataStoreAdapter(
        api._LambdaStoreShim(LambdaDataStore(pre._store, "t"))
    )
    # no auths: labeled row hidden
    assert len(ds.query("t", Query(filter="INCLUDE")).batch) == 0
    # with auths: visible through the lambda shim
    got = ds.query(
        "t", Query(filter="INCLUDE", hints={"auths": ("admin",)})
    ).batch
    assert list(got.fids) == ["sec1"]
