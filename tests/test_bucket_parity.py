"""Bucketing parity (ISSUE 17 acceptance): every family that rounds a
request dimension onto the compile-shape ladder must return results
BIT-IDENTICAL to unbucketed execution. ``compile.bucket.growth <= 1``
is the unbucketed oracle (exact shapes, one compile per size); the
default pow2 ladder and an off-default growth=3 ladder must match it
exactly — counts, fids, distances, density grids, stat sketches and
join pairs — at sizes straddling bucket boundaries (k=7/8/9, prime
widths, a canvas just past the Pallas tile bound) on single chip and
on the 8-virtual-device mesh.

Runs on the 8-virtual-device CPU harness conftest provides.
"""

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override
from geomesa_tpu.device_cache import DeviceIndex
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.geom import Envelope
from geomesa_tpu.join import JoinEngine
from geomesa_tpu.parallel.mesh import make_mesh
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"
N = 1201  # prime: every pad/bucket tail is live

#: window scales chosen to hit different z-range R-buckets (the
#: city/country split of the warmup plan) plus a residual-filter query
ECQLS = [
    "BBOX(geom, -0.4, -0.3, 0.4, 0.3)",
    "BBOX(geom, -12, -9, 11, 8) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-02-01T00:00:00Z",
    "val >= 50 AND BBOX(geom, -18, -18, 18, 18)",
]


@pytest.fixture(scope="module")
def ds():
    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    rng = np.random.default_rng(31)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b", "c"], N),
            "val": rng.integers(0, 100, N),
            "dtg": rng.integers(t0, t1, N),
            "geom": np.stack(
                [rng.uniform(-20, 20, N), rng.uniform(-20, 20, N)], axis=1
            ),
        },
        fids=np.arange(N),
    )
    return ds


def _windows(m, w=2.0):
    rng = np.random.default_rng(m)
    x0 = rng.uniform(-18, 16, m)
    y0 = rng.uniform(-18, 16, m)
    return np.stack([x0, y0, x0 + w, y0 + w], axis=1)


def _battery(ds, growth):
    """Every bucketed family's results under one ladder setting. A
    FRESH DeviceIndex per growth: the per-filter loose-bounds cache
    pins padded shapes, so reusing an index would let one growth's
    caps leak into another's dispatch."""
    with prop_override("compile.bucket.growth", growth):
        di = DeviceIndex(ds, "t", z_planes=True)
        out = {}
        for i, ecql in enumerate(ECQLS):
            out[f"count_loose:{i}"] = di.count(ecql, loose=True)
            out[f"count_exact:{i}"] = di.count(ecql, loose=False)
            out[f"fids:{i}"] = np.sort(di.query(ecql).fids)
        # kNN straddling the k=7/8 rung edge (satellite: one compile)
        for k in (1, 2, 3, 7, 8, 9, 13):
            b, d = di.knn(0.3, 0.2, k)
            out[f"knn_fids:{k}"] = list(b.fids)
            out[f"knn_d:{k}"] = d
        # fused micro-batch widths across the 4 -> 8 rung edge; a
        # mixed-window group may decline to fuse under exact shapes
        # (mixed R buckets) — the API contract is "equals the serial
        # loose counts", so normalize through the documented fallback
        q0 = parse_ecql(ECQLS[0])
        qs = [
            parse_ecql(f"BBOX(geom, {x - 0.4}, -0.3, {x + 0.4}, 0.3)")
            for x in (-9.0, -3.0, 3.0, 9.0)
        ]
        for w in (1, 3, 7, 8):
            out[f"fused_same:{w}"] = di.fused_loose_counts([q0] * w)
            grp = (qs * 2)[:w]
            got = di.fused_loose_counts(grp)
            out[f"fused_mixed:{w}"] = (
                got if got is not None
                else [di.count(q, loose=True) for q in grp]
            )
        for m in (1, 3, 5):
            out[f"union:{m}"] = np.sort(
                di.window_union_query(_windows(m)).fids
            )
        # density: (64, 64) rides the Pallas exact-shape engine,
        # (600, 3) is past the tile bound -> capacity-bucketed scatter
        env = Envelope(-20, -20, 20, 20)
        out["density_pallas"] = di.density(ECQLS[0], env, 64, 64)
        out["density_scatter"] = di.density(ECQLS[1], env, 600, 3)
        out["density_weighted"] = di.density(
            "INCLUDE", env, 600, 3, weight_attr="val"
        )
        seq = di.stats(ECQLS[1], 'Count();MinMax("val")')
        out["stats"] = [s.to_json() for s in seq.stats]
        # join refinement: candidate-capacity buckets (join.refine C=)
        for m in (5, 40):
            res = JoinEngine(di).join(_windows(m, w=1.0))
            out[f"join:{m}"] = list(
                zip(res.rows.tolist(), res.wins.tolist())
            )
        # 8-virtual-device mesh: co-partitioned refinement buckets
        res = JoinEngine(di, mesh=make_mesh(n_devices=8)).join(
            _windows(12, w=1.0)
        )
        out["join_mesh"] = list(zip(res.rows.tolist(), res.wins.tolist()))
        return out


def _assert_same(a, b, ctx):
    assert set(a) == set(b)
    for key in a:
        va, vb = a[key], b[key]
        if isinstance(va, np.ndarray):
            np.testing.assert_array_equal(va, vb, err_msg=f"{ctx}:{key}")
        else:
            assert va == vb, (ctx, key, va, vb)


def test_bucketed_results_bit_identical(ds):
    oracle = _battery(ds, 0)  # growth <= 1: exact shapes, no bucketing
    _assert_same(_battery(ds, 2.0), oracle, "pow2-vs-exact")
    _assert_same(_battery(ds, 3.0), oracle, "growth3-vs-exact")
    # sanity on the oracle itself: it saw real hits, not empty == empty
    assert any(oracle[f"count_loose:{i}"] > 0 for i in range(len(ECQLS)))
    assert len(oracle["join:40"]) > 0
    assert float(oracle["density_scatter"].sum()) > 0


def test_knn_k7_k8_share_one_executable(ds):
    """The satellite in one assertion: k=7 and k=8 land on the same
    rung, so the second call finds the jit entry the first minted —
    one compiled executable, observable as an inproc-tier cache hit."""
    from geomesa_tpu import metrics

    di = DeviceIndex(ds, "t")
    di.knn(0.3, 0.2, 7)
    before = metrics.compile_cache_hits.value(tier="inproc")
    b, d = di.knn(0.3, 0.2, 8)
    assert len(di._knn_jits) == 1
    assert len(b.fids) == 8 and np.all(np.diff(d) >= 0)
    assert metrics.compile_cache_hits.value(tier="inproc") == before + 1
