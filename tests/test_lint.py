"""Invariant linter (analysis/lint.py): package self-lint, one seeded
fixture violation per rule GT001-GT009, the disable-comment escape
hatch, and the CLI exit codes."""

import os

import pytest

from geomesa_tpu.analysis.lint import (
    format_findings,
    lint_package,
    lint_paths,
    main as lint_main,
)

# one seeded violation per rule: (rule, relative path, source)
FIXTURES = {
    "GT001": (
        "locks.py",
        "import threading\n"
        "lock = threading.Lock()\n",
    ),
    "GT002": (
        "blocking.py",
        "import threading\n"
        "lock = threading.Lock()\n"
        "def f(path):\n"
        "    with lock:\n"
        "        open(path)\n",
    ),
    "GT003": (
        "clocks.py",
        "import time\n"
        "def f(timeout):\n"
        "    return time.time() + timeout\n",
    ),
    "GT004": (
        "ops/loopy.py",
        "import numpy as np\n"
        "def f(chunks):\n"
        "    out = []\n"
        "    for c in chunks:\n"
        "        out.append(np.asarray(c))\n"
        "    return out\n",
    ),
    "GT005": (
        "points.py",
        "from geomesa_tpu.failpoints import fail_point\n"
        "def f():\n"
        "    fail_point('fail.not.registered')\n",
    ),
    "GT006": (
        "badmetric.py",
        "from geomesa_tpu.metrics import REGISTRY\n"
        "c = REGISTRY.counter('queries_total')\n",
    ),
    "GT007": (
        "store/publish.py",
        "import os\n"
        "def publish(tmp, path):\n"
        "    os.replace(tmp, path)\n",
    ),
    "GT008": (
        "keys.py",
        "from geomesa_tpu.conf import sys_prop\n"
        "def f():\n"
        "    return sys_prop('no.such.key')\n",
    ),
    "GT009": (
        "costs.py",
        "from geomesa_tpu.ledger import charge\n"
        "def f():\n"
        "    charge('not_a_ledger_field', 1)\n",
    ),
}


def _write_tree(root, fixtures):
    for rule, (rel, src) in fixtures.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)


@pytest.mark.lint
def test_package_self_lint_is_clean():
    """The GT001-GT009 rules over the geomesa_tpu tree itself: every
    baseline violation is fixed or carries a reasoned disable comment.
    Rides tier-1 so a regression fails the next test run, not the next
    CI run."""
    findings = lint_package()
    assert findings == [], "\n" + format_findings(findings)


@pytest.mark.lint
def test_fixture_tree_seeds_every_rule(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    findings = lint_paths([str(tmp_path)])
    found = {f.rule for f in findings}
    assert found >= set(FIXTURES), (
        f"missing rules: {set(FIXTURES) - found}\n" + format_findings(findings)
    )
    # each seeded file is flagged by the rule it seeds
    for rule, (rel, _) in FIXTURES.items():
        assert any(
            f.rule == rule and f.path.endswith(rel.replace("/", os.sep))
            for f in findings
        ), f"{rule} did not fire on {rel}"


@pytest.mark.lint
def test_disable_comment_with_reason_suppresses(tmp_path):
    (tmp_path / "ok.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disable=GT003(epoch timestamp for the log record)\n"
    )
    assert lint_paths([str(tmp_path)]) == []


@pytest.mark.lint
def test_disable_comment_previous_line_suppresses(tmp_path):
    (tmp_path / "ok.py").write_text(
        "import time\n"
        "# lint: disable=GT003(epoch timestamp for the log record)\n"
        "t = time.time()\n"
    )
    assert lint_paths([str(tmp_path)]) == []


@pytest.mark.lint
def test_multi_code_disable_with_reason_suppresses(tmp_path):
    """Regression: the bare-disable detector must not backtrack into a
    reasoned multi-code directive and report its first code as
    reason-less."""
    (tmp_path / "ok.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disable=GT003,GT008(epoch by design)\n"
    )
    assert lint_paths([str(tmp_path)]) == []
    (tmp_path / "bad.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disable=GT003,GT008\n"
    )
    findings = lint_paths([str(tmp_path)])
    # the unsuppressed violation + one reason-less report per code
    assert len(findings) == 3
    assert sum("without a reason" in f.message for f in findings) == 2


@pytest.mark.lint
def test_disable_comment_without_reason_does_not_suppress(tmp_path):
    (tmp_path / "bad.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disable=GT003\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert {f.rule for f in findings} == {"GT003"}
    # both the un-suppressed finding and the reason-less directive report
    assert len(findings) == 2
    assert any("without a reason" in f.message for f in findings)


@pytest.mark.lint
def test_lint_main_exit_codes(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    lines: list = []
    assert lint_main([str(tmp_path)], out=lines.append) == 1
    assert any("finding(s)" in ln for ln in lines)
    clean = tmp_path / "cleantree"
    clean.mkdir()
    (clean / "fine.py").write_text("x = 1\n")
    assert lint_main([str(clean)], out=lines.append) == 0
    assert lint_main([str(tmp_path / "nope.py")], out=lines.append) == 2


@pytest.mark.lint
def test_cli_lint_nonzero_on_fixture_tree(tmp_path, capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    _write_tree(tmp_path, FIXTURES)
    with pytest.raises(SystemExit) as exc:
        cli_main(["lint", str(tmp_path)])
    assert exc.value.code == 1
    out = capsys.readouterr().out
    for rule in FIXTURES:
        assert rule in out


@pytest.mark.lint
def test_cli_lint_clean_repo_exits_zero(capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    cli_main(["lint"])  # no SystemExit -> exit code 0
    assert "clean" in capsys.readouterr().out


@pytest.mark.lint
def test_rule_table_lists_all_rules(capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    cli_main(["lint", "--rules"])
    out = capsys.readouterr().out
    for code in FIXTURES:
        assert code in out
