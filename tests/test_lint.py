"""Invariant linter (analysis/lint.py): package self-lint, one seeded
fixture violation per rule GT001-GT012, the disable-comment escape
hatch, the machine-readable emitters (json/sarif/--changed), and the
CLI exit codes."""

import json
import os

import pytest

from geomesa_tpu.analysis.lint import (
    findings_to_json,
    findings_to_sarif,
    format_findings,
    lint_package,
    lint_paths,
    main as lint_main,
)

# one seeded violation per rule: (rule, relative path, source)
FIXTURES = {
    "GT001": (
        "locks.py",
        "import threading\n"
        "lock = threading.Lock()\n",
    ),
    "GT002": (
        "blocking.py",
        "import threading\n"
        "lock = threading.Lock()\n"
        "def f(path):\n"
        "    with lock:\n"
        "        open(path)\n",
    ),
    "GT003": (
        "clocks.py",
        "import time\n"
        "def f(timeout):\n"
        "    return time.time() + timeout\n",
    ),
    "GT004": (
        "ops/loopy.py",
        "import numpy as np\n"
        "def f(chunks):\n"
        "    out = []\n"
        "    for c in chunks:\n"
        "        out.append(np.asarray(c))\n"
        "    return out\n",
    ),
    "GT005": (
        "points.py",
        "from geomesa_tpu.failpoints import fail_point\n"
        "def f():\n"
        "    fail_point('fail.not.registered')\n",
    ),
    "GT006": (
        "badmetric.py",
        "from geomesa_tpu.metrics import REGISTRY\n"
        "c = REGISTRY.counter('queries_total')\n",
    ),
    "GT007": (
        "store/publish.py",
        "import os\n"
        "def publish(tmp, path):\n"
        "    os.replace(tmp, path)\n",
    ),
    "GT008": (
        "keys.py",
        "from geomesa_tpu.conf import sys_prop\n"
        "def f():\n"
        "    return sys_prop('no.such.key')\n",
    ),
    "GT009": (
        "costs.py",
        "from geomesa_tpu.ledger import charge\n"
        "def f():\n"
        "    charge('not_a_ledger_field', 1)\n",
    ),
    "GT010": (
        "spawny.py",
        "import threading\n"
        "def f():\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n",
    ),
    "GT011": (
        "store/swallow.py",
        "def f(fetch):\n"
        "    try:\n"
        "        return fetch()\n"
        "    except Exception:\n"
        "        return None\n",
    ),
    "GT012": (
        "ops/padder.py",
        "def pad_cap(n):\n"
        "    return max(1, 1 << max(n - 1, 0).bit_length())\n",
    ),
}


def _write_tree(root, fixtures):
    for rule, (rel, src) in fixtures.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)


@pytest.mark.lint
def test_package_self_lint_is_clean():
    """The GT001-GT012 rules over the geomesa_tpu tree itself: every
    baseline violation is fixed or carries a reasoned disable comment.
    Rides tier-1 so a regression fails the next test run, not the next
    CI run."""
    findings = lint_package()
    assert findings == [], "\n" + format_findings(findings)


@pytest.mark.lint
def test_fixture_tree_seeds_every_rule(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    findings = lint_paths([str(tmp_path)])
    found = {f.rule for f in findings}
    assert found >= set(FIXTURES), (
        f"missing rules: {set(FIXTURES) - found}\n" + format_findings(findings)
    )
    # each seeded file is flagged by the rule it seeds
    for rule, (rel, _) in FIXTURES.items():
        assert any(
            f.rule == rule and f.path.endswith(rel.replace("/", os.sep))
            for f in findings
        ), f"{rule} did not fire on {rel}"


@pytest.mark.lint
def test_disable_comment_with_reason_suppresses(tmp_path):
    (tmp_path / "ok.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disable=GT003(epoch timestamp for the log record)\n"
    )
    assert lint_paths([str(tmp_path)]) == []


@pytest.mark.lint
def test_disable_comment_previous_line_suppresses(tmp_path):
    (tmp_path / "ok.py").write_text(
        "import time\n"
        "# lint: disable=GT003(epoch timestamp for the log record)\n"
        "t = time.time()\n"
    )
    assert lint_paths([str(tmp_path)]) == []


@pytest.mark.lint
def test_multi_code_disable_with_reason_suppresses(tmp_path):
    """Regression: the bare-disable detector must not backtrack into a
    reasoned multi-code directive and report its first code as
    reason-less."""
    (tmp_path / "ok.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disable=GT003,GT008(epoch by design)\n"
    )
    assert lint_paths([str(tmp_path)]) == []
    (tmp_path / "bad.py").write_text(
        "import time\n"
        # the token is split so linting THIS file's source (--changed
        # picks test files up) does not see a bare disable directive
        "t = time.time()  # lint: disa" "ble=GT003,GT008\n"
    )
    findings = lint_paths([str(tmp_path)])
    # the unsuppressed violation + one reason-less report per code
    assert len(findings) == 3
    assert sum("without a reason" in f.message for f in findings) == 2


@pytest.mark.lint
def test_disable_comment_without_reason_does_not_suppress(tmp_path):
    (tmp_path / "bad.py").write_text(
        "import time\n"
        "t = time.time()  # lint: disa" "ble=GT003\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert {f.rule for f in findings} == {"GT003"}
    # both the un-suppressed finding and the reason-less directive report
    assert len(findings) == 2
    assert any("without a reason" in f.message for f in findings)


@pytest.mark.lint
def test_lint_main_exit_codes(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    lines: list = []
    assert lint_main([str(tmp_path)], out=lines.append) == 1
    assert any("finding(s)" in ln for ln in lines)
    clean = tmp_path / "cleantree"
    clean.mkdir()
    (clean / "fine.py").write_text("x = 1\n")
    assert lint_main([str(clean)], out=lines.append) == 0
    assert lint_main([str(tmp_path / "nope.py")], out=lines.append) == 2


@pytest.mark.lint
def test_cli_lint_nonzero_on_fixture_tree(tmp_path, capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    _write_tree(tmp_path, FIXTURES)
    with pytest.raises(SystemExit) as exc:
        cli_main(["lint", str(tmp_path)])
    assert exc.value.code == 1
    out = capsys.readouterr().out
    for rule in FIXTURES:
        assert rule in out


@pytest.mark.lint
def test_cli_lint_clean_repo_exits_zero(capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    cli_main(["lint"])  # no SystemExit -> exit code 0
    assert "clean" in capsys.readouterr().out


@pytest.mark.lint
def test_rule_table_lists_all_rules(capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    cli_main(["lint", "--rules"])
    out = capsys.readouterr().out
    for code in FIXTURES:
        assert code in out


# -- the PR 20 rules: edge semantics ----------------------------------------


@pytest.mark.lint
def test_gt010_flags_every_raw_spawn_flavor(tmp_path):
    (tmp_path / "flavors.py").write_text(
        "import threading\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "from threading import Thread, Timer\n"
        "a = threading.Thread(target=print)\n"
        "b = ThreadPoolExecutor(max_workers=2)\n"
        "c = Thread(target=print)\n"
        "d = Timer(1.0, print)\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert [f.rule for f in findings] == ["GT010"] * 4
    assert [f.line for f in findings] == [4, 5, 6, 7]


@pytest.mark.lint
def test_gt010_ignores_blessed_spawn_and_annotations(tmp_path):
    (tmp_path / "ok.py").write_text(
        "import threading\n"
        "from geomesa_tpu.spawn import ContextPool, spawn_thread\n"
        "def start(fn) -> threading.Thread:\n"  # reference, not a call
        "    t = spawn_thread(fn, name='worker', context=False)\n"
        "    t.start()\n"
        "    return t\n"
        "pool = ContextPool(4, thread_name_prefix='w')\n"
    )
    assert lint_paths([str(tmp_path)]) == []


@pytest.mark.lint
def test_gt011_passes_when_the_fault_is_routed(tmp_path):
    (tmp_path / "store").mkdir()
    (tmp_path / "store" / "routed.py").write_text(
        "from geomesa_tpu.resilience import classify, note_degraded\n"
        "def a(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except Exception:\n"
        "        raise\n"
        "def b(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except Exception as e:\n"
        "        classify(e)\n"
        "        return None\n"
        "def c(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except Exception:\n"
        "        note_degraded('store_fault')\n"
        "        return None\n"
        "def d(fn, log):\n"
        "    try:\n"
        "        return fn()\n"
        "    except Exception as e:\n"  # bound-name use counts as routing
        "        log.warning('fetch failed: %s', e)\n"
        "        return None\n"
    )
    assert lint_paths([str(tmp_path)]) == []


@pytest.mark.lint
def test_gt011_only_fires_on_the_serving_surface(tmp_path):
    src = (
        "def f(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except Exception:\n"
        "        return None\n"
    )
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "helper.py").write_text(src)
    assert lint_paths([str(tmp_path)]) == []
    (tmp_path / "join").mkdir()
    (tmp_path / "join" / "hot.py").write_text(src)
    findings = lint_paths([str(tmp_path)])
    assert [f.rule for f in findings] == ["GT011"]
    assert findings[0].path.endswith(os.path.join("join", "hot.py"))


@pytest.mark.lint
def test_gt012_flags_log2_and_spares_bucketing_users(tmp_path):
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "logpad.py").write_text(
        "import math\n"
        "def cap(n):\n"
        "    return 2 ** math.ceil(math.log2(max(n, 1)))\n"
    )
    (tmp_path / "ops" / "bucketed.py").write_text(
        "from geomesa_tpu.bucketing import bucket_cap\n"
        "def cap(n):\n"
        "    return bucket_cap(n)\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert [f.rule for f in findings] == ["GT012"]
    assert findings[0].path.endswith("logpad.py")


@pytest.mark.lint
def test_pr17_regression_fixture_raw_thread_plus_jit(tmp_path):
    """The static half of the ISSUE regression: a raw thread that jits
    with no compile_scope attribution must be caught by GT010 at the
    spawn site (the runtime halves live in test_ctxcheck /
    test_compilecheck)."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "rogue.py").write_text(
        "import threading\n"
        "import jax\n"
        "def warm(fn, x):\n"
        "    t = threading.Thread(target=lambda: jax.jit(fn)(x))\n"
        "    t.start()\n"
        "    return t\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "GT010" in {f.rule for f in findings}


# -- machine-readable emitters ----------------------------------------------


@pytest.mark.lint
def test_json_emitter_round_trips_findings(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    findings = lint_paths([str(tmp_path)])
    doc = json.loads(findings_to_json(findings))
    assert len(doc) == len(findings)
    assert {d["rule"] for d in doc} >= set(FIXTURES)
    for d in doc:
        assert set(d) == {"rule", "path", "line", "col", "message", "title"}
        assert d["line"] >= 1 and d["title"]


@pytest.mark.lint
def test_sarif_emitter_is_valid_2_1_0(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    findings = lint_paths([str(tmp_path)])
    doc = json.loads(findings_to_sarif(findings))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "geomesa-tpu-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == {f.rule for f in findings}
    assert len(run["results"]) == len(findings)
    for res in run["results"]:
        loc = res["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] >= 1
        assert "\\" not in loc["artifactLocation"]["uri"]


@pytest.mark.lint
def test_sarif_emitter_clean_run_still_emits_a_log():
    doc = json.loads(findings_to_sarif([]))
    assert doc["runs"][0]["results"] == []
    assert json.loads(findings_to_json([])) == []


@pytest.mark.lint
def test_main_format_modes_share_exit_codes(tmp_path):
    _write_tree(tmp_path, FIXTURES)
    for fmt in ("text", "json", "sarif"):
        lines: list = []
        assert lint_main([str(tmp_path)], out=lines.append, fmt=fmt) == 1
        assert lines
    clean = tmp_path / "cleantree"
    clean.mkdir()
    (clean / "fine.py").write_text("x = 1\n")
    for fmt in ("json", "sarif"):
        lines = []
        assert lint_main([str(clean)], out=lines.append, fmt=fmt) == 0
        json.loads(lines[0])  # clean runs still emit a parseable doc


@pytest.mark.lint
def test_cli_lint_format_sarif(tmp_path, capsys):
    from geomesa_tpu.tools.cli import main as cli_main

    _write_tree(tmp_path, FIXTURES)
    with pytest.raises(SystemExit) as exc:
        cli_main(["lint", str(tmp_path), "--format", "sarif"])
    assert exc.value.code == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert {r["ruleId"] for r in doc["runs"][0]["results"]} >= set(FIXTURES)


@pytest.mark.lint
def test_changed_scope_lints_only_touched_files(tmp_path, monkeypatch):
    """--changed in a scratch repo: only the dirty file is linted."""
    import subprocess

    def git(*args):
        subprocess.run(
            ("git",) + args, cwd=tmp_path, check=True,
            capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-q")
    (tmp_path / "committed.py").write_text(
        "import time\nt = time.time()\n"  # GT003, but committed clean
    )
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    (tmp_path / "fresh.py").write_text(
        "import threading\nt = threading.Thread(target=print)\n"
    )
    monkeypatch.chdir(tmp_path)
    lines: list = []
    rc = lint_main(out=lines.append, changed=True)
    assert rc == 1
    body = "\n".join(lines)
    assert "fresh.py" in body and "GT010" in body
    # the committed-but-untouched violation stays out of scope
    assert "committed.py" not in body


@pytest.mark.lint
def test_changed_scope_clean_when_nothing_changed(tmp_path, monkeypatch):
    import subprocess

    env = {**os.environ,
           "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    subprocess.run(("git", "init", "-q"), cwd=tmp_path, check=True,
                   capture_output=True, env=env)
    (tmp_path / "seed.py").write_text("x = 1\n")
    subprocess.run(("git", "add", "-A"), cwd=tmp_path, check=True,
                   capture_output=True, env=env)
    subprocess.run(("git", "commit", "-q", "-m", "seed"), cwd=tmp_path,
                   check=True, capture_output=True, env=env)
    monkeypatch.chdir(tmp_path)
    assert lint_main(out=[].append, changed=True) == 0


@pytest.mark.lint
def test_changed_scope_outside_a_repo_is_exit_2(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    lines: list = []
    assert lint_main(out=lines.append, changed=True) == 2
    assert any("error:" in ln for ln in lines)
