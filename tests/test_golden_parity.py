"""Golden parity fixtures (SURVEY.md section 4 rebuild plan item 4):
a deterministic GDELT-like slice with canned queries whose exact
feature-id sets are pinned. Guards cross-round regressions in the whole
stack (quantization, range decomposition, planner, scan, residuals) --
any drift in the result SET is a correctness break even if counts match.

The fixture is self-seeding: ids are derived from a fixed RNG; expected
sets were computed by the host oracle (evaluate_host) and are asserted
against BOTH the oracle and every store implementation, so the pins catch
oracle drift too.
"""

import hashlib

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql, parse_instant
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.kv import KVDataStore, MemoryKV
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"
N = 20000

QUERIES = [
    "BBOX(geom, -10, 35, 30, 60) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z",
    "BBOX(geom, 100, -50, 179, 20) AND name = 'a'",
    "val BETWEEN 10 AND 20 AND BBOX(geom, -180, -90, 0, 90)",
    "dtg DURING 2020-02-01T00:00:00Z/2020-02-02T00:00:00Z",
    "BBOX(geom, -0.5, -0.5, 0.5, 0.5)",
]

# sha256 of the sorted hit-id list, comma-joined -- pinned golden outputs.
# If an intentional semantic change moves these, recompute via
# _digest(oracle_ids) and document why in the commit message.
GOLDEN = {
    0: "290f6059137d1f5094134bddd4f427e2d9cbac02fa375122808d705d02480bff",  # 82 hits
    1: "2a3cdc5345205613de4c74717d57339b95a7a38b367c2286a61d5ef5890dd110",  # 547 hits
    2: "044fb3a8f6ed17fae37eb9f662765c7e83c7ba7ffd608fb12d85136935f24e7a",  # 1097 hits
    3: "bd707307e77798394ad31b8b5590d8a211aa669b96e5492dc0231e272f12ea81",  # 344 hits
    4: "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855",  # 0 hits
}


def _data():
    rng = np.random.default_rng(20260730)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    return {
        "name": rng.choice(["a", "b", "c"], N),
        "val": rng.integers(0, 100, N),
        "dtg": rng.integers(t0, t1, N),
        "geom": np.stack(
            [rng.uniform(-180, 180, N), rng.uniform(-90, 90, N)], axis=1
        ),
    }


def _digest(ids) -> str:
    joined = ",".join(str(i) for i in sorted(int(v) for v in ids))
    return hashlib.sha256(joined.encode()).hexdigest()


@pytest.fixture(scope="module")
def oracle_sets():
    sft = SimpleFeatureType.create("g", SPEC)
    batch = FeatureBatch.from_columns(sft, _data(), np.arange(N))
    out = {}
    for i, q in enumerate(QUERIES):
        mask = evaluate_host(parse_ecql(q), batch)
        out[i] = set(batch.fids[mask].tolist())
    return out


def test_oracle_matches_golden_digests(oracle_sets):
    for i, ids in oracle_sets.items():
        assert _digest(ids) == GOLDEN[i], f"query {i} drifted from golden"


@pytest.mark.parametrize(
    "make_store",
    [
        lambda tmp: MemoryDataStore(),
        lambda tmp: KVDataStore(MemoryKV()),
        lambda tmp: FileSystemDataStore(str(tmp), partition_size=2048),
    ],
    ids=["memory", "kv", "fs"],
)
def test_stores_match_golden(tmp_path, oracle_sets, make_store):
    ds = make_store(tmp_path)
    ds.create_schema("g", SPEC)
    ds.write("g", _data(), fids=np.arange(N))
    if hasattr(ds, "flush"):
        ds.flush("g")
    for i, q in enumerate(QUERIES):
        got = set(int(v) for v in ds.query("g", q).batch.fids)
        assert got == oracle_sets[i], f"query {i}: store != oracle"
        assert _digest(got) == GOLDEN[i], f"query {i}: store != golden"
