"""Proximity / route / date-offset / conversion processes."""

import io

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.geom import LineString, Point
from geomesa_tpu.process import (
    arrow_conversion,
    bin_conversion,
    date_offset,
    parse_duration_ms,
    proximity_search,
    route_search,
)
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,heading:Double,dtg:Date,*geom:Point"


@pytest.fixture()
def store():
    ds = MemoryDataStore()
    sft = SimpleFeatureType.create("ships", SPEC)
    ds.create_schema(sft)
    # three points along the x-axis route, one far away
    ds.write(
        "ships",
        {
            "name": ["a", "b", "c", "far"],
            "heading": [90.0, 270.0, 85.0, 0.0],
            "dtg": [1000, 2000, 3000, 4000],
            "geom": np.array(
                [[0.5, 0.05], [1.5, -0.08], [2.5, 0.0], [10.0, 5.0]]
            ),
        },
        fids=["a", "b", "c", "far"],
    )
    return ds


def test_proximity_search(store):
    batch, dist = proximity_search(
        store, "ships", [Point(0.5, 0.0), Point(2.5, 0.2)], 0.25
    )
    assert sorted(batch.column("name")) == ["a", "c"]
    assert (dist <= 0.25).all()


def test_proximity_search_segment_input(store):
    # a line input catches everything within buffer of the whole segment
    line = LineString(np.array([[0.0, 0.0], [3.0, 0.0]]))
    batch, dist = proximity_search(store, "ships", [line], 0.1)
    assert sorted(batch.column("name")) == ["a", "b", "c"]


def test_route_search_orders_along_route(store):
    route = np.array([[0.0, 0.0], [3.0, 0.0]])
    batch, dist, along = route_search(store, "ships", route, 0.2)
    assert list(batch.column("name")) == ["a", "b", "c"]
    assert np.all(np.diff(along) > 0)
    np.testing.assert_allclose(along, [0.5, 1.5, 2.5], atol=1e-9)


def test_route_search_heading_filter(store):
    route = np.array([[0.0, 0.0], [3.0, 0.0]])  # bearing 90 (due east)
    batch, _, _ = route_search(
        store, "ships", route, 0.2, heading_attr="heading",
        heading_tolerance_deg=30.0,
    )
    # a (90) and c (85) match; b (270) is opposite
    assert sorted(batch.column("name")) == ["a", "c"]
    batch2, _, _ = route_search(
        store, "ships", route, 0.2, heading_attr="heading",
        heading_tolerance_deg=30.0, bidirectional=True,
    )
    assert sorted(batch2.column("name")) == ["a", "b", "c"]


def test_date_offset():
    assert parse_duration_ms("P1D") == 86400_000
    assert parse_duration_ms("PT6H30M") == 23400_000
    assert parse_duration_ms("-PT15S") == -15_000
    assert parse_duration_ms(250) == 250
    with pytest.raises(ValueError):
        parse_duration_ms("nope")
    sft = SimpleFeatureType.create("t", "dtg:Date,*geom:Point")
    b = FeatureBatch.from_columns(
        sft, {"dtg": [1000, 2000], "geom": np.array([[0.0, 0.0], [1.0, 1.0]])}
    )
    out = date_offset(b, "PT1M")
    assert out.column("dtg").tolist() == [61000, 62000]
    assert b.column("dtg").tolist() == [1000, 2000]  # input untouched


def test_arrow_conversion_roundtrip(store):
    from geomesa_tpu.arrow_io import read_feature_stream

    payload = arrow_conversion(store, "ships", "BBOX(geom, 0, -1, 3, 1)")
    batches = list(read_feature_stream(io.BytesIO(payload)))
    names = sorted(
        n for b in batches for n in (b.column("name") if len(b) else [])
    )
    assert names == ["a", "b", "c"]


def test_bin_conversion(store):
    from geomesa_tpu.process import decode_bin

    payload = bin_conversion(
        store, "ships", "name", query="BBOX(geom, 0, -1, 3, 1)", sort=True
    )
    rec = decode_bin(payload)
    assert len(rec) == 3
    assert list(rec["dtg"]) == [1, 2, 3]  # seconds, sorted


def test_knn_resident_matches_store_path():
    """kNN over a resident DeviceIndex returns exactly the store path's
    neighbors (same expanding-window algorithm, fused window scans)."""
    import numpy as np

    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.process.knn import knn
    from geomesa_tpu.store.memory import MemoryDataStore

    ds = MemoryDataStore()
    ds.create_schema("kp", "c:Int,*geom:Point:srid=4326")
    rng = np.random.default_rng(9)
    n = 3000
    ds.write("kp", {
        "c": np.arange(n),
        "geom": np.stack(
            [rng.uniform(-30, 30, n), rng.uniform(-30, 30, n)], axis=1
        ),
    })
    di = DeviceIndex(ds, "kp")
    b_store, d_store = knn(ds, "kp", 2.0, 5.0, k=25)
    b_res, d_res = knn(ds, "kp", 2.0, 5.0, k=25, device_index=di)
    np.testing.assert_array_equal(b_res.fids, b_store.fids)
    np.testing.assert_allclose(d_res, d_store)


def test_tube_and_proximity_resident_match_store_path():
    """Tube select and proximity search over a resident DeviceIndex (one
    union-of-windows dispatch) return exactly the store path's results."""
    import numpy as np

    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.process.proximity import proximity_search
    from geomesa_tpu.process.tube import tube_select
    from geomesa_tpu.store.memory import MemoryDataStore

    ds = MemoryDataStore()
    ds.create_schema("ais", "c:Int,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(12)
    n = 5000
    t0 = 1_577_836_800_000
    ds.write("ais", {
        "c": np.arange(n),
        "dtg": t0 + rng.integers(0, 86_400_000, n),
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
        ),
    })
    di = DeviceIndex(ds, "ais")
    # a 12-segment track crossing the data
    m = 13
    track = np.stack(
        [np.linspace(-8, 8, m), np.linspace(-6, 7, m) + 0.5 * np.sin(np.arange(m))],
        axis=1,
    )
    track_t = t0 + np.linspace(0, 86_400_000, m).astype(np.int64)
    b_store = tube_select(ds, "ais", track, track_t, 1.5, 3_600_000)
    b_res = tube_select(
        ds, "ais", track, track_t, 1.5, 3_600_000, device_index=di
    )
    assert len(b_store) > 0
    np.testing.assert_array_equal(
        np.sort(b_res.fids), np.sort(b_store.fids)
    )

    pts = [(-5.0, -2.0), (3.0, 4.0), (8.0, -8.0)]
    bp_store, dp_store = proximity_search(ds, "ais", pts, 1.0)
    bp_res, dp_res = proximity_search(
        ds, "ais", pts, 1.0, device_index=di
    )
    assert len(bp_store) > 0
    np.testing.assert_array_equal(
        np.sort(bp_res.fids), np.sort(bp_store.fids)
    )
    np.testing.assert_allclose(
        dp_res[np.argsort(bp_res.fids)], dp_store[np.argsort(bp_store.fids)]
    )


def test_tube_with_base_filter_stays_one_dispatch(monkeypatch):
    """A corridor query WITH a CQL base filter must still run the
    union-of-windows kernel (the base's compiled mask fuses into the
    same dispatch — VERDICT round-3 weak #6: it used to fall back to the
    76s-class per-segment store path) and match the store path exactly."""
    import numpy as np

    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.process.proximity import proximity_search
    from geomesa_tpu.process.tube import tube_select
    from geomesa_tpu.store.memory import MemoryDataStore

    ds = MemoryDataStore()
    ds.create_schema("ais", "c:Int,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(21)
    n = 4000
    t0 = 1_577_836_800_000
    ds.write("ais", {
        "c": np.arange(n),
        "dtg": t0 + rng.integers(0, 86_400_000, n),
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)], axis=1
        ),
    })
    di = DeviceIndex(ds, "ais")
    union_calls = []
    orig = DeviceIndex.window_union_query

    def spy(self, *a, **kw):
        out = orig(self, *a, **kw)
        union_calls.append(out is not None)
        return out

    monkeypatch.setattr(DeviceIndex, "window_union_query", spy)
    store_probes = []
    orig_q = MemoryDataStore.query

    def qspy(self, *a, **kw):
        store_probes.append(a)
        return orig_q(self, *a, **kw)

    m = 9
    track = np.stack(
        [np.linspace(-8, 8, m), np.linspace(-6, 7, m)], axis=1
    )
    track_t = t0 + np.linspace(0, 86_400_000, m).astype(np.int64)
    base = "c < 2000"
    b_store = tube_select(ds, "ais", track, track_t, 1.5, 3_600_000,
                          base_filter=base)
    monkeypatch.setattr(MemoryDataStore, "query", qspy)
    b_res = tube_select(ds, "ais", track, track_t, 1.5, 3_600_000,
                        base_filter=base, device_index=di)
    assert union_calls == [True], "union kernel skipped with base filter"
    assert not store_probes, "per-segment store queries ran"
    assert len(b_res) > 0
    assert np.all(b_res.column("c") < 2000)
    np.testing.assert_array_equal(
        np.sort(b_res.fids), np.sort(b_store.fids)
    )

    # proximity with a base filter: same one-dispatch contract
    union_calls.clear()
    pts = [(-5.0, -2.0), (3.0, 4.0)]
    bp_res, _ = proximity_search(ds, "ais", pts, 1.0, base_filter=base,
                                 device_index=di)
    monkeypatch.setattr(MemoryDataStore, "query", orig_q)
    bp_store, _ = proximity_search(ds, "ais", pts, 1.0, base_filter=base)
    assert union_calls == [True]
    np.testing.assert_array_equal(
        np.sort(bp_res.fids), np.sort(bp_store.fids)
    )

    # a base with host residuals cannot fuse: falls back, still correct
    union_calls.clear()
    got = di.window_union_query(
        np.array([[-10, -10, 10, 10]]), base="c < 2000 AND dtg IS NULL"
    )
    assert got is None or len(got) == 0  # IS NULL never matches here


def test_processes_honor_auths_on_both_paths():
    """tube/proximity/knn auths reach the STORE fallback path too (a
    base filter forces it) — labeled rows must not silently vanish."""
    import numpy as np

    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.process.knn import knn
    from geomesa_tpu.process.proximity import proximity_search
    from geomesa_tpu.process.tube import tube_select
    from geomesa_tpu.store.memory import MemoryDataStore

    ds = MemoryDataStore()
    ds.create_schema("s", "c:Int,dtg:Date,*geom:Point:srid=4326")
    rng = np.random.default_rng(4)
    n = 500
    t0 = 1_577_836_800_000
    batch = FeatureBatch.from_columns(
        ds.get_schema("s"),
        {
            "c": np.arange(n),
            "dtg": t0 + rng.integers(0, 86_400_000, n),
            "geom": np.stack(
                [rng.uniform(-5, 5, n), rng.uniform(-5, 5, n)], axis=1
            ),
        },
        fids=np.arange(n),
    ).with_visibility(["secret"] * n)
    ds.write("s", batch)
    di = DeviceIndex(ds, "s")
    track = np.array([[-4.0, -4.0], [4.0, 4.0]])
    track_t = np.array([t0, t0 + 86_400_000])
    for base in (None, "c >= 0"):  # device path, then forced store path
        b = tube_select(
            ds, "s", track, track_t, 2.0, 90_000_000,
            base_filter=base, device_index=di, auths=("secret",),
        )
        assert len(b) > 0, f"tube base={base!r}"
        p, _ = proximity_search(
            ds, "s", [(0.0, 0.0)], 2.0,
            base_filter=base, device_index=di, auths=("secret",),
        )
        assert len(p) > 0, f"proximity base={base!r}"
    got, _ = knn(ds, "s", 0.0, 0.0, k=5, base_filter="c >= 0",
                 device_index=di, auths=("secret",))
    assert len(got) == 5
    # and no auths = fail closed everywhere
    b0 = tube_select(ds, "s", track, track_t, 2.0, 90_000_000,
                     device_index=di)
    assert len(b0) == 0
