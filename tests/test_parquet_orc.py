"""Parquet converter + ORC filesystem storage encoding."""

import io
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from geomesa_tpu.convert import converter_for
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.store.fs import FileSystemDataStore

SPEC = "name:String,count:Int,dtg:Date,*geom:Point:srid=4326"


def _parquet_bytes():
    table = pa.table(
        {
            "id": ["a", "b", "c"],
            "name": ["alpha", "beta", "gamma"],
            "count": pa.array([1, 2, 3], pa.int32()),
            "ts": pa.array([1000, 2000, 3000], pa.timestamp("ms")),
            "lon": [2.35, -0.12, 13.4],
            "lat": [48.85, 51.5, 52.5],
        }
    )
    sink = io.BytesIO()
    pq.write_table(table, sink)
    return sink.getvalue()


def test_parquet_converter():
    sft = SimpleFeatureType.create("p", SPEC)
    cfg = {
        "type": "parquet",
        "id-field": "$id",
        "fields": [
            {"name": "name", "path": "name"},
            {"name": "count", "transform": "$count::int"},
            {"name": "dtg", "transform": "millisToDate($ts)"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    }
    res = converter_for(cfg, sft).process(_parquet_bytes())
    assert res.success == 3 and res.failed == 0
    assert list(res.batch.fids) == ["a", "b", "c"]
    assert res.batch.column("count").tolist() == [1, 2, 3]
    assert res.batch.column("dtg").tolist() == [1000, 2000, 3000]
    np.testing.assert_allclose(
        res.batch.column("geom"),
        [[2.35, 48.85], [-0.12, 51.5], [13.4, 52.5]],
    )


def test_parquet_converter_from_path(tmp_path):
    path = tmp_path / "in.parquet"
    path.write_bytes(_parquet_bytes())
    sft = SimpleFeatureType.create("p", "name:String,*geom:Point")
    cfg = {
        "type": "parquet",
        "fields": [
            {"name": "name", "path": "name"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    }
    with open(path, "rb") as fh:
        res = converter_for(cfg, sft).process(fh)
    assert res.success == 3


def _fill(store, n=5000, seed=7):
    store.create_schema("gdelt", SPEC)
    rng = np.random.default_rng(seed)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    cols = {
        "name": rng.choice(["alpha", "beta"], n),
        "count": rng.integers(0, 100, n),
        "dtg": rng.integers(t0, t1, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    store.write("gdelt", cols, fids=np.arange(n))
    store.flush("gdelt")
    return cols


def test_fs_orc_roundtrip(tmp_path):
    store = FileSystemDataStore(str(tmp_path), partition_size=1024, encoding="orc")
    _fill(store)
    files = os.listdir(tmp_path / "gdelt")
    assert any(f.endswith(".orc") for f in files)
    assert not any(f.endswith(".parquet") for f in files)
    res = store.query(
        "gdelt",
        "BBOX(geom, -10, 40, 10, 55) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z",
    )
    assert len(res) > 0
    assert res.scanned < res.total  # manifest prune still applies


def test_fs_orc_reopen(tmp_path):
    store = FileSystemDataStore(str(tmp_path), encoding="orc")
    _fill(store, n=500)
    n1 = store.count("gdelt")
    # reopen with default (parquet) encoding: per-type encoding persisted
    store2 = FileSystemDataStore(str(tmp_path))
    assert store2.count("gdelt") == n1 == 500


def test_cli_export_orc(tmp_path, capsys):
    from geomesa_tpu.tools.cli import main

    store = FileSystemDataStore(str(tmp_path / "store"))
    _fill(store, n=100)
    out = str(tmp_path / "out.orc")
    main(
        [
            "--root",
            str(tmp_path / "store"),
            "export",
            "-f",
            "gdelt",
            "-F",
            "orc",
            "-o",
            out,
        ]
    )
    import pyarrow.orc as orc

    assert orc.read_table(out).num_rows == 100
