"""New CLI commands: stats-*, age-off, keywords, convert, reindex, etc."""

import json

import numpy as np
import pytest

from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.tools.cli import main

SPEC = "name:String,val:Int,dtg:Date,*geom:Point:srid=4326"


@pytest.fixture
def store_root(tmp_path):
    root = str(tmp_path / "store")
    ds = FileSystemDataStore(root)
    ds.create_schema("t", SPEC)
    n = 300
    rng = np.random.default_rng(1)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "t",
        {
            "name": rng.choice(["a", "b", "c"], n),
            "val": rng.integers(0, 100, n),
            "dtg": t0 + rng.integers(0, 10**9, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    ds.flush("t")
    return root


def _run(root, *args, capsys=None):
    main(["--root", root, *args])


def test_version_and_env(store_root, capsys):
    main(["version"])
    out = capsys.readouterr().out
    assert "geomesa-tpu" in out
    main(["--root", store_root, "env"])
    out = capsys.readouterr().out
    assert "system properties" in out and "schemas:" in out and "t" in out


def test_stats_commands(store_root, capsys):
    main(["--root", store_root, "stats-count", "-f", "t"])
    assert json.loads(capsys.readouterr().out)["count"] == 300

    main(["--root", store_root, "stats-bounds", "-f", "t"])
    out = capsys.readouterr().out
    assert "val:" in out and "dtg:" in out and "geom: bbox" in out

    main(["--root", store_root, "stats-top-k", "-f", "t", "-a", "name", "-k", "2"])
    top = json.loads(capsys.readouterr().out)
    assert len(top["counters"]) == 2

    main(["--root", store_root, "stats-histogram", "-f", "t", "-a", "val",
          "--bins", "5"])
    h = json.loads(capsys.readouterr().out)
    assert sum(h["counts"]) == 300

    main(["--root", store_root, "stats-analyze", "-f", "t"])
    out = capsys.readouterr().out
    assert '"count": 300' in out and "name:" in out


def test_delete_features_and_age_off(store_root, capsys):
    main(["--root", store_root, "delete-features", "-f", "t", "--ids", "0,1,2"])
    assert "deleted 3" in capsys.readouterr().out
    main(["--root", store_root, "age-off", "-f", "t",
          "--before", "2020-01-06T00:00:00", "--dry-run"])
    out = capsys.readouterr().out
    assert "dry run" in out
    n_dry = int(out.split()[2])
    main(["--root", store_root, "age-off", "-f", "t",
          "--before", "2020-01-06T00:00:00"])
    assert f"removed {n_dry}" in capsys.readouterr().out
    main(["--root", store_root, "count", "-f", "t"])
    assert int(capsys.readouterr().out) == 297 - n_dry


def test_keywords_roundtrip(store_root, capsys):
    main(["--root", store_root, "keywords", "-f", "t", "-a", "gdelt", "news"])
    assert capsys.readouterr().out.split() == ["gdelt", "news"]
    # persisted across store reopen
    main(["--root", store_root, "keywords", "-f", "t"])
    assert capsys.readouterr().out.split() == ["gdelt", "news"]
    main(["--root", store_root, "keywords", "-f", "t", "-r", "news"])
    assert capsys.readouterr().out.split() == ["gdelt"]


def test_convert_standalone(tmp_path, capsys):
    src = tmp_path / "in.csv"
    src.write_text("a,1.0,2.0\nb,3.0,4.0\n")
    conv = tmp_path / "conv.json"
    conv.write_text(json.dumps({
        "type": "delimited-text",
        "format": "csv",
        "id-field": "$1",
        "fields": [
            {"name": "name", "transform": "$1"},
            {"name": "geom", "transform": "point($2::double, $3::double)"},
        ],
    }))
    out = tmp_path / "out.parquet"
    main(["convert", "-s", "name:String,*geom:Point", "-C", str(conv),
          "-F", "parquet", "-o", str(out), str(src)])
    import pyarrow.parquet as pq

    assert pq.read_table(str(out)).num_rows == 2


def test_reindex_repartition_compact_cli(store_root, capsys):
    main(["--root", store_root, "reindex", "-f", "t", "--index", "z2"])
    assert "reindexed" in capsys.readouterr().out
    main(["--root", store_root, "repartition", "-f", "t",
          "--scheme", "attribute:name"])
    assert "repartitioned" in capsys.readouterr().out
    main(["--root", store_root, "compact", "-f", "t"])
    assert "compacted" in capsys.readouterr().out
    main(["--root", store_root, "count", "-f", "t"])
    assert int(capsys.readouterr().out) == 300


def test_leaflet_export(store_root, tmp_path, capsys):
    out = str(tmp_path / "map.html")
    main(["--root", store_root, "export", "-f", "t",
          "-q", "BBOX(geom, -50, -50, 50, 50)", "-F", "leaflet", "-o", out])
    html = open(out).read()
    assert html.startswith("<!DOCTYPE html>")
    assert "L.geoJSON" in html and "FeatureCollection" in html
    import json as _json

    start = html.index("var data = ") + len("var data = ")
    end = html.index(";\nvar map")
    doc = _json.loads(html[start:end])
    assert len(doc["features"]) > 0


def test_leaflet_export_escapes_hostile_values(tmp_path):
    from geomesa_tpu.export import write_leaflet_html
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.features.sft import SimpleFeatureType
    import numpy as _np

    sft = SimpleFeatureType.create("t", "name:String,*geom:Point")
    batch = FeatureBatch.from_columns(
        sft,
        {
            "name": ["</script><script>alert(1)</script>", "<img onerror=x>"],
            "geom": _np.zeros((2, 2)),
        },
        ["</script>evil", "ok"],
    )
    out = tmp_path / "m.html"
    write_leaflet_html(batch, str(out), title="<b>t</b>")
    html = out.read_text()
    assert "</script><script>alert" not in html  # cannot break out of JSON
    assert "<img onerror" not in html  # popup values escaped
    assert "<b>t</b>" not in html  # title escaped
    # well-formed: exactly the two template script elements close
    assert html.count("</script>") == 2
