"""HTTP serving bridge: capabilities, features (geojson/arrow), count,
explain, density."""

import json
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.filter.ecql import parse_instant
from geomesa_tpu.server import serve_background
from geomesa_tpu.store.memory import MemoryDataStore

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


@pytest.fixture(scope="module")
def server_url():
    ds = MemoryDataStore()
    ds.create_schema("gdelt", SPEC)
    n = 2000
    rng = np.random.default_rng(17)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "gdelt",
        {
            "name": rng.choice(["a", "b"], n),
            "dtg": t0 + rng.integers(0, 10**8, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    server, _ = serve_background(ds)
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", ds
    server.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


def test_capabilities(server_url):
    url, _ = server_url
    status, ctype, body = _get(f"{url}/capabilities")
    assert status == 200 and "json" in ctype
    doc = json.loads(body)
    assert "gdelt" in doc["types"]
    assert doc["types"]["gdelt"]["geometry"] == "geom"


def test_features_geojson_matches_store(server_url):
    url, ds = server_url
    cql = "BBOX(geom, -5, -5, 5, 5)"
    status, _, body = _get(
        f"{url}/features/gdelt?cql={urllib.request.quote(cql)}"
    )
    assert status == 200
    doc = json.loads(body)
    expected = len(ds.query("gdelt", cql))
    assert len(doc["features"]) == expected
    f0 = doc["features"][0]
    assert f0["geometry"]["type"] == "Point"
    assert set(f0["properties"]) == {"name", "dtg"}


def test_features_arrow(server_url):
    url, ds = server_url
    status, ctype, body = _get(f"{url}/features/gdelt?f=arrow&maxFeatures=50")
    assert status == 200 and "arrow" in ctype
    import io

    from geomesa_tpu.arrow_io import read_feature_stream

    batches = list(read_feature_stream(io.BytesIO(body)))
    assert sum(len(b) for b in batches) == 50


def test_count_and_explain(server_url):
    url, ds = server_url
    cql = urllib.request.quote("name = 'a'")
    status, _, body = _get(f"{url}/count/gdelt?cql={cql}")
    assert status == 200
    assert json.loads(body)["count"] == len(ds.query("gdelt", "name = 'a'"))
    status, ctype, body = _get(f"{url}/explain/gdelt?cql={cql}")
    assert status == 200 and "text/plain" in ctype
    assert b"Chosen index" in body


def test_density_grid(server_url):
    url, ds = server_url
    status, _, body = _get(
        f"{url}/density/gdelt?bbox=-20,-20,20,20&width=16&height=8"
    )
    assert status == 200
    doc = json.loads(body)
    counts = np.asarray(doc["counts"])
    assert counts.shape == (8, 16)
    assert counts.sum() == 2000  # every point lands in the grid


def test_nan_values_serialize_as_null():
    from geomesa_tpu.export import feature_collection

    ds = MemoryDataStore()
    ds.create_schema("t", "v:Double,*geom:Point")
    ds.write("t", {"v": [float("nan"), 1.5], "geom": np.zeros((2, 2))}, [0, 1])
    doc = feature_collection(ds.query("t").batch)
    text = json.dumps(doc)
    json.loads(text)  # strict parse succeeds
    assert "NaN" not in text
    vals = sorted(
        (f["properties"]["v"] is None, f["properties"]["v"]) for f in doc["features"]
    )
    assert vals[0][1] == 1.5 and vals[1][1] is None


def test_errors(server_url):
    url, _ = server_url
    status, _, body = _get_allow_error(f"{url}/features/nope")
    assert status == 404
    status, _, body = _get_allow_error(f"{url}/features/gdelt?cql=BAD%20CQL(")
    assert status == 400
    status, _, body = _get_allow_error(f"{url}/bogus")
    assert status == 404
    status, _, body = _get_allow_error(f"{url}/density/gdelt")
    assert status == 400 and b"bbox" in body


def _get_allow_error(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.headers.get("Content-Type"), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read()


# -- stats endpoint + resident mode -----------------------------------------


def test_stats_endpoint(server_url):
    url, ds = server_url
    cql = "BBOX(geom, -5, -5, 5, 5)"
    spec = 'Count();MinMax("dtg")'
    status, _, body = _get(
        f"{url}/stats/gdelt?cql={urllib.request.quote(cql)}"
        f"&stats={urllib.request.quote(spec)}"
    )
    assert status == 200
    doc = json.loads(body)
    from geomesa_tpu.process import run_stats

    exp = run_stats(ds, "gdelt", cql, spec).to_json()
    assert doc == exp


def test_stats_endpoint_requires_spec(server_url):
    url, _ = server_url
    import urllib.error

    try:
        _get(f"{url}/stats/gdelt")
        raise AssertionError("should have 400'd")
    except urllib.error.HTTPError as e:
        assert e.code == 400


@pytest.fixture(scope="module")
def resident_url():
    ds = MemoryDataStore()
    ds.create_schema("gdelt", SPEC)
    n = 3000
    rng = np.random.default_rng(5)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "gdelt",
        {
            "name": rng.choice(["a", "b"], n),
            "dtg": t0 + rng.integers(0, 10**8, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
            ),
        },
        fids=np.arange(n),
    )
    server, _ = serve_background(ds, resident=True)
    host, port = server.server_address[:2]
    yield f"http://{host}:{port}", ds
    server.shutdown()


def test_resident_count_and_features_match_store(resident_url):
    url, ds = resident_url
    cql = "BBOX(geom, -5, -5, 5, 5)"
    expect = len(ds.query("gdelt", cql))
    status, _, body = _get(
        f"{url}/count/gdelt?cql={urllib.request.quote(cql)}"
    )
    assert status == 200 and json.loads(body)["count"] == expect
    status, _, body = _get(
        f"{url}/features/gdelt?cql={urllib.request.quote(cql)}"
    )
    doc = json.loads(body)
    assert len(doc["features"]) == expect


def test_resident_loose_is_superset(resident_url):
    url, ds = resident_url
    cql = "BBOX(geom, -5, -5, 5, 5)"
    exact = len(ds.query("gdelt", cql))
    status, _, body = _get(
        f"{url}/count/gdelt?cql={urllib.request.quote(cql)}&loose=1"
    )
    assert status == 200
    assert json.loads(body)["count"] >= exact


def test_resident_stats_pushdown(resident_url):
    url, ds = resident_url
    spec = 'Count();MinMax("dtg")'
    status, _, body = _get(
        f"{url}/stats/gdelt?stats={urllib.request.quote(spec)}"
        f"&cql={urllib.request.quote('BBOX(geom, -5, -5, 5, 5)')}"
    )
    assert status == 200
    doc = json.loads(body)
    from geomesa_tpu.process import run_stats

    exp = run_stats(
        ds, "gdelt", "BBOX(geom, -5, -5, 5, 5)", spec
    ).to_json()
    assert doc == exp


def test_resident_refresh_after_write(resident_url):
    url, ds = resident_url
    status, _, body = _get(f"{url}/count/gdelt?cql=INCLUDE")
    before = json.loads(body)["count"]
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write(
        "gdelt",
        {"name": ["z"], "dtg": [t0], "geom": np.array([[0.0, 0.0]])},
        fids=["fresh-row"],
    )
    # snapshot semantics: stale until refresh
    status, _, body = _get(f"{url}/count/gdelt?cql=INCLUDE")
    assert json.loads(body)["count"] == before
    status, _, body = _get(f"{url}/refresh/gdelt")
    assert status == 200 and json.loads(body)["rows"] == before + 1
    status, _, body = _get(f"{url}/count/gdelt?cql=INCLUDE")
    assert json.loads(body)["count"] == before + 1


def test_refresh_rejected_without_resident_mode(server_url):
    url, _ = server_url
    import urllib.error

    try:
        _get(f"{url}/refresh/gdelt")
        raise AssertionError("should have 400'd")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_resident_respects_max_features_cap(resident_url):
    url, _ = resident_url
    from geomesa_tpu.conf import prop_override

    with prop_override("query.max.features", 7):
        status, _, body = _get(f"{url}/features/gdelt?cql=INCLUDE")
        assert status == 200
        assert len(json.loads(body)["features"]) == 7
        # interceptor parity: an EXPLICIT maxFeatures overrides the
        # global cap, exactly like MaxFeaturesInterceptor
        status, _, body = _get(
            f"{url}/features/gdelt?cql=INCLUDE&maxFeatures=20"
        )
        assert len(json.loads(body)["features"]) == 20
        # /count applies the global cap like the plain path counts the
        # capped result
        status, _, body = _get(f"{url}/count/gdelt?cql=INCLUDE")
        assert json.loads(body)["count"] == 7
    # explicit maxFeatures caps the resident count like the plain path
    status, _, body = _get(f"{url}/count/gdelt?cql=INCLUDE&maxFeatures=5")
    assert json.loads(body)["count"] == 5


def test_resident_count_max_features_zero(resident_url):
    url, _ = resident_url
    # explicit 0 caps to 0 (interceptor parity edge case)
    status, _, body = _get(f"{url}/count/gdelt?cql=INCLUDE&maxFeatures=0")
    assert status == 200 and json.loads(body)["count"] == 0


def test_metrics_endpoint(server_url):
    url, _ = server_url
    _get(f"{url}/count/gdelt?cql=INCLUDE")  # generate at least one query metric
    status, ctype, body = _get(f"{url}/metrics")
    assert status == 200 and "text/plain" in ctype
    text = body.decode()
    assert "geomesa_queries_total" in text
    assert "# TYPE geomesa_query_duration_seconds histogram" in text


def test_resident_density_fused(resident_url):
    """/density in resident mode runs the fused device path and matches
    the store-path grid."""
    url, ds = resident_url
    cql = "BBOX(geom, -5, -5, 5, 5)"
    status, _, body = _get(
        f"{url}/density/gdelt?bbox=-5,-5,5,5&width=16&height=8"
        f"&cql={urllib.request.quote(cql)}"
    )
    assert status == 200
    doc = json.loads(body)
    from geomesa_tpu.geom import Envelope
    from geomesa_tpu.process import density

    ref = density(ds, "gdelt", cql, Envelope(-5, -5, 5, 5), 16, 8)
    np.testing.assert_allclose(np.array(doc["counts"]), ref, rtol=1e-5)
    assert np.array(doc["counts"]).sum() > 0


def test_server_auths_param_resident_and_store():
    """auths=A,B serves labeled rows from the resident fast path; absent
    auths fail closed. Store-path (non-resident) behavior is identical."""
    from geomesa_tpu.features.batch import FeatureBatch

    for resident in (True, False):
        ds = MemoryDataStore()
        ds.create_schema("sec", SPEC)
        n = 400
        rng = np.random.default_rng(13)
        t0 = parse_instant("2020-01-01T00:00:00")
        batch = FeatureBatch.from_columns(
            ds.get_schema("sec"),
            {
                "name": rng.choice(["a", "b"], n),
                "dtg": t0 + rng.integers(0, 10**8, n),
                "geom": np.stack(
                    [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
                ),
            },
            fids=np.arange(n),
        ).with_visibility(rng.choice(["", "A", "A&B"], n))
        ds.write("sec", batch)
        server, _ = serve_background(ds, resident=resident)
        host, port = server.server_address[:2]
        url = f"http://{host}:{port}"
        try:
            from geomesa_tpu.query.plan import Query

            cql = "BBOX(geom, -20, -20, 20, 20)"
            for auths in ((), ("A",), ("A", "B")):
                want = len(
                    ds.query("sec", Query(cql, hints={"auths": auths})).batch
                )
                qs = f"&auths={','.join(auths)}" if auths else ""
                status, _, body = _get(
                    f"{url}/count/sec?cql={urllib.request.quote(cql)}{qs}"
                )
                assert status == 200
                assert json.loads(body)["count"] == want, (resident, auths)
                status, _, body = _get(
                    f"{url}/features/sec?cql={urllib.request.quote(cql)}{qs}"
                )
                assert len(json.loads(body)["features"]) == want
        finally:
            server.shutdown()


def test_knn_endpoint(server_url):
    """/knn returns k nearest features with distances, matching the
    process-layer result."""
    from urllib.parse import quote

    from geomesa_tpu.process.knn import knn

    url, ds = server_url
    status, _, body = _get(f"{url}/knn/gdelt?x=2.0&y=5.0&k=7")
    assert status == 200
    doc = json.loads(body)
    assert len(doc["features"]) == 7
    dists = [f["properties"]["knn_distance_deg"] for f in doc["features"]]
    assert dists == sorted(dists)
    batch, want = knn(ds, "gdelt", 2.0, 5.0, k=7)
    got_ids = [f["id"] for f in doc["features"]]
    assert got_ids == [str(f) for f in batch.fids]
    # with a base filter
    status, _, body = _get(
        f"{url}/knn/gdelt?x=2.0&y=5.0&k=5&cql={quote(chr(39).join(['name = ', 'a', '']))}"
    )
    assert status == 200
    doc = json.loads(body)
    assert len(doc["features"]) == 5
    assert all(f["properties"]["name"] == "a" for f in doc["features"])


def test_tube_endpoint(server_url):
    url, ds = server_url
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = t0 + 10**8
    track = f"-10,-10,{t0};0,0,{(t0 + t1) // 2};10,10,{t1}"
    status, _, body = _get(
        f"{url}/tube/gdelt?track={track}&buffer=2.0&maxDt={10**8}"
    )
    assert status == 200
    doc = json.loads(body)
    from geomesa_tpu.process.tube import tube_select

    want = tube_select(
        ds, "gdelt",
        np.array([[-10, -10], [0, 0], [10, 10]], float),
        np.array([t0, (t0 + t1) // 2, t1], np.int64),
        buffer_deg=2.0, max_dt_ms=10**8,
    )
    assert sorted(f["id"] for f in doc["features"]) == sorted(
        str(f) for f in want.fids
    )
    assert len(doc["features"]) > 0


def test_proximity_endpoint(server_url):
    url, ds = server_url
    status, _, body = _get(
        f"{url}/proximity/gdelt?points=0,0;5,5&distance=1.5"
    )
    assert status == 200
    doc = json.loads(body)
    from geomesa_tpu.geom.base import Point
    from geomesa_tpu.process.proximity import proximity_search

    want, wd = proximity_search(
        ds, "gdelt", [Point(0, 0), Point(5, 5)], 1.5
    )
    assert sorted(f["id"] for f in doc["features"]) == sorted(
        str(f) for f in want.fids
    )
    assert len(doc["features"]) > 0
    for f in doc["features"]:
        assert f["properties"]["proximity_distance_deg"] <= 1.5 + 1e-9


def test_process_endpoints_resident_mode():
    """The process endpoints work identically in resident mode (served
    by the one-dispatch device paths)."""
    ds = MemoryDataStore()
    ds.create_schema("r", SPEC)
    n = 1500
    rng = np.random.default_rng(23)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write("r", {
        "name": rng.choice(["a", "b"], n),
        "dtg": t0 + rng.integers(0, 10**8, n),
        "geom": np.stack(
            [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
        ),
    }, fids=np.arange(n))
    server, _ = serve_background(ds, resident=True)
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    try:
        s1, _, b1 = _get(f"{url}/knn/r?x=1.0&y=1.0&k=9")
        assert s1 == 200
        from geomesa_tpu.process.knn import knn

        want, _ = knn(ds, "r", 1.0, 1.0, k=9)
        got = [f["id"] for f in json.loads(b1)["features"]]
        assert got == [str(f) for f in want.fids]
        s2, _, b2 = _get(f"{url}/proximity/r?points=2,2&distance=1.0")
        assert s2 == 200 and len(json.loads(b2)["features"]) > 0
    finally:
        server.shutdown()


def test_warm_server_precompiles_and_serves():
    """make_server(warm=True) stages every type and pre-compiles the
    serving kernels before accepting traffic; requests then serve with
    no first-touch build."""
    from geomesa_tpu.server import make_server
    import threading

    ds = MemoryDataStore()
    ds.create_schema("gdelt", SPEC)
    n = 500
    rng = np.random.default_rng(23)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write("gdelt", {
        "name": rng.choice(["a", "b"], n),
        "dtg": t0 + rng.integers(0, 10**8, n),
        "geom": np.stack(
            [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
        ),
    }, fids=np.arange(n))
    server = make_server(ds, resident=True, warm=True)
    # the resident cache is populated BEFORE the first request
    assert "gdelt" in server.RequestHandlerClass._resident_cache
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        host, port = server.server_address[:2]
        status, _, body = _get(
            f"http://{host}:{port}/count/gdelt?cql=INCLUDE"
        )
        assert status == 200 and json.loads(body)["count"] == n
    finally:
        server.shutdown()


def test_device_index_warmup_legs():
    """warmup() compiles every serving kernel family and reports a
    per-leg duration (None only for legs the schema can't serve)."""
    from geomesa_tpu.device_cache import DeviceIndex

    ds = MemoryDataStore()
    ds.create_schema("t", SPEC)
    n = 300
    rng = np.random.default_rng(5)
    t0 = parse_instant("2020-01-01T00:00:00")
    ds.write("t", {
        "name": rng.choice(["a", "b"], n),
        "dtg": t0 + rng.integers(0, 10**8, n),
        "geom": np.stack(
            [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], axis=1
        ),
    }, fids=np.arange(n))
    di = DeviceIndex(ds, "t", z_planes=True)
    out = di.warmup()
    assert {"knn", "density", "stats", "mask", "window_union"} <= set(out)
    assert all(v is not None for v in out.values()), out
    # warmed: a real request compiles nothing. The bound distinguishes
    # "no compile" (~10ms on the CPU mesh) from "compiled here"
    # (seconds) with slack for a loaded CI box — NOT a latency SLO.
    import time as _t
    t = _t.perf_counter()
    di.knn(0.0, 0.0, 5)
    assert (_t.perf_counter() - t) < 2.0


def test_device_index_warmup_non_point_schema():
    """Non-point schemas warm their envelope-plane kernels; only the
    point-only legs (kNN, density) report unavailable."""
    from geomesa_tpu.device_cache import DeviceIndex
    from geomesa_tpu.sql.functions import st_makeBBOX

    ds = MemoryDataStore()
    ds.create_schema("zones", "name:String,dtg:Date,*geom:Polygon:srid=4326")
    t0 = parse_instant("2020-01-01T00:00:00")
    polys = np.array(
        [st_makeBBOX(i, i, i + 1, i + 1) for i in range(40)], dtype=object
    )
    ds.write("zones", {
        "name": [f"z{i}" for i in range(40)],
        "dtg": t0 + np.arange(40) * 10**6,
        "geom": polys,
    }, fids=np.arange(40))
    di = DeviceIndex(ds, "zones", z_planes=True)
    out = di.warmup()
    assert out["knn"] is None and out["density"] is None
    others = {k: v for k, v in out.items() if k not in ("knn", "density")}
    assert others and all(v is not None for v in others.values()), out
