"""Shapefile WRITER (convert/shp.py write_shp): roundtrip through the
reader, dbf typing, ring orientation, export dispatch."""

import numpy as np
import pytest

from geomesa_tpu.convert.shp import (
    ShapefileConverter,
    read_dbf,
    read_shp,
    write_shapefile,
    write_shp,
)
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.geom import MultiPolygon, Point, Polygon


def _point_batch(n=25):
    sft = SimpleFeatureType.create(
        "pts", "name:String,val:Int,score:Double,flag:Boolean,"
        "dtg:Date,*geom:Point:srid=4326"
    )
    rng = np.random.default_rng(4)
    return FeatureBatch.from_columns(sft, {
        "name": [f"n{i}" for i in range(n)],
        "val": rng.integers(-50, 50, n),
        "score": rng.uniform(-5, 5, n),
        "flag": rng.integers(0, 2, n).astype(bool),
        "dtg": np.full(n, 1_577_836_800_000 + 86_400_000),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], axis=1
        ),
    }, fids=np.arange(n))


def test_point_roundtrip_with_attributes():
    b = _point_batch()
    shp, shx, dbf = write_shp(b)
    geoms = read_shp(shp)
    assert len(geoms) == len(b)
    for i, g in enumerate(geoms):
        assert isinstance(g, Point)
        assert g.x == pytest.approx(float(b.columns["geom"][i, 0]))
        assert g.y == pytest.approx(float(b.columns["geom"][i, 1]))
    names, rows = read_dbf(dbf)
    assert names == ["name", "val", "score", "flag", "dtg"]
    for i, row in enumerate(rows):
        assert row[0] == f"n{i}"
        assert row[1] == int(b.columns["val"][i])
        assert row[2] == pytest.approx(float(b.columns["score"][i]), abs=1e-6)
        assert row[3] == bool(b.columns["flag"][i])
        assert row[4] == 1_577_836_800_000 + 86_400_000  # date roundtrip (day)
    # .shx: one 8-byte entry per record after the 100-byte header
    assert len(shx) == 100 + 8 * len(b)


def test_polygon_with_holes_roundtrip(tmp_path):
    sft = SimpleFeatureType.create("z", "name:String,*geom:Polygon:srid=4326")
    outer = np.array(
        [[0.0, 0.0], [10.0, 0.0], [10.0, 10.0], [0.0, 10.0], [0.0, 0.0]]
    )
    hole = np.array(
        [[4.0, 4.0], [6.0, 4.0], [6.0, 6.0], [4.0, 6.0], [4.0, 4.0]]
    )
    mp = MultiPolygon((
        Polygon(outer, (hole,)),
        Polygon(outer + 20.0),
    ))
    b = FeatureBatch.from_columns(sft, {
        "name": ["a", "b"],
        "geom": np.array([Polygon(outer, (hole,)), mp], dtype=object),
    }, fids=np.arange(2))
    write_shapefile(b, str(tmp_path / "zones.shp"))
    conv = ShapefileConverter({}, sft)
    back = conv.process(str(tmp_path / "zones.shp")).batch
    g0 = back.columns["geom"][0]
    assert isinstance(g0, Polygon) and len(g0.holes) == 1
    g1 = back.columns["geom"][1]
    assert isinstance(g1, MultiPolygon) and len(g1.polygons) == 2
    # area is orientation-independent: hole subtracts
    from geomesa_tpu.sql.functions import st_area

    assert st_area(g0) == pytest.approx(100.0 - 4.0)
    assert st_area(g1) == pytest.approx(100.0 - 4.0 + 100.0)


def test_export_dispatch_and_cli_choice(tmp_path):
    from geomesa_tpu.export import write_batch

    b = _point_batch(5)
    write_batch(b, str(tmp_path / "out.shp"), "shp")
    for ext in (".shp", ".shx", ".dbf"):
        assert (tmp_path / f"out{ext}").exists()


def test_mixed_shape_types_refused():
    sft = SimpleFeatureType.create("m", "*geom:Geometry:srid=4326")
    b = FeatureBatch.from_columns(sft, {
        "geom": np.array([
            Point(0.0, 0.0),
            Polygon(np.array(
                [[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 0.0]]
            )),
        ], dtype=object),
    }, fids=np.arange(2))
    with pytest.raises(ValueError, match="ONE shape type"):
        write_shp(b)


def test_null_geometry_and_numeric_overflow():
    sft = SimpleFeatureType.create("n", "big:Long,*geom:Polygon:srid=4326")
    tri = Polygon(np.array([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 0.0]]))
    b = FeatureBatch.from_columns(sft, {
        "big": np.array([1, 2], np.int64),
        "geom": np.array([tri, None], dtype=object),
    }, fids=np.arange(2))
    shp, _, _ = write_shp(b)  # null shape writes, bbox skips it
    geoms = read_shp(shp)
    assert isinstance(geoms[0], Polygon) and geoms[1] is None
    # a Long too wide for dbf N(18) refuses instead of silently
    # truncating trailing digits
    b2 = FeatureBatch.from_columns(sft, {
        "big": np.array([10**18, 1], np.int64),
        "geom": np.array([tri, tri], dtype=object),
    }, fids=np.arange(2))
    with pytest.raises(ValueError, match="does not fit"):
        write_shp(b2)


def test_utm_antimeridian_roundtrip():
    from geomesa_tpu.sql.functions import st_transform

    pts = np.array([[-175.0, 10.0], [179.9, -20.0]])
    out = st_transform(pts, "4326", "32660")  # zone 60: CM 177E
    back = st_transform(out, "32660", "4326")
    np.testing.assert_allclose(back, pts, atol=1e-9)
    assert np.all(back[:, 0] <= 180) and np.all(back[:, 0] > -180)
