"""Leaflet HTML rendering (geomesa-spark-jupyter-leaflet analog)."""

import json

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.sql.leaflet import leaflet_map, save_map

SFT = SimpleFeatureType.create("pts", "name:String,*geom:Point:srid=4326")


def _batch(n=20):
    rng = np.random.default_rng(1)
    return FeatureBatch.from_columns(SFT, {
        "name": [f"p{i}" for i in range(n)],
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(40, 50, n)], axis=1
        ),
    }, fids=np.arange(n))


def test_features_map_embeds_geojson():
    html = leaflet_map(features=_batch())
    assert "L.geoJSON" in html and "leaflet" in html
    # the embedded collection round-trips as JSON
    start = html.index("var fc = ") + len("var fc = ")
    end = html.index(";", start)
    fc = json.loads(html[start:end])
    assert len(fc["features"]) == 20
    assert fc["features"][0]["properties"]["name"] == "p0"
    # auto-center lands inside the data envelope
    assert "setView([4" in html  # lat ~40-50


def test_density_map_embeds_grid_and_bounds():
    from geomesa_tpu.geom import Envelope

    grid = np.zeros((8, 16), np.float32)
    grid[2, 3] = 5.0
    html = leaflet_map(density=(grid, Envelope(-10, 40, 10, 50)))
    assert "imageOverlay" in html
    assert "[[40.0, -10.0], [50.0, 10.0]]" in html
    start = html.index("var grid = ") + len("var grid = ")
    g = json.loads(html[start: html.index(";", start)])
    assert len(g) == 8 and len(g[0]) == 16 and g[2][3] == 5.0


def test_combined_and_cap(tmp_path):
    from geomesa_tpu.geom import Envelope

    big = _batch(50)
    html = leaflet_map(
        features=big,
        density=(np.ones((4, 4)), Envelope(-10, 40, 10, 50)),
        max_features=10,
    )
    start = html.index("var fc = ") + len("var fc = ")
    fc = json.loads(html[start: html.index(";", start)])
    assert len(fc["features"]) == 10  # capped
    assert "imageOverlay" in html
    p = save_map(str(tmp_path / "m.html"), features=_batch(3))
    assert open(p).read().startswith("<!DOCTYPE html>")


def test_requires_some_layer():
    with pytest.raises(ValueError):
        leaflet_map()


# -- st_transform / st_azimuth (live here with the other map-facing bits) ----


def test_transform_known_values_and_roundtrip():
    from geomesa_tpu.geom.base import Point
    from geomesa_tpu.sql.functions import st_transform

    # known anchor: (lon 0, lat 0) -> (0, 0); lon 180 -> pi*R
    p = st_transform(Point(180.0, 0.0), "EPSG:4326", "EPSG:3857")
    assert p.x == pytest.approx(20037508.342789244)
    assert p.y == pytest.approx(0.0, abs=1e-6)
    # paris, independently computed web-mercator coordinates
    paris = st_transform(Point(2.3522, 48.8566), "4326", "3857")
    assert paris.x == pytest.approx(261848.15, rel=1e-4)
    assert paris.y == pytest.approx(6250566.72, rel=1e-4)
    # roundtrip on a column
    rng = np.random.default_rng(2)
    col = np.stack(
        [rng.uniform(-179, 179, 500), rng.uniform(-84, 84, 500)], axis=1
    )
    back = st_transform(
        st_transform(col, "4326", "3857"), "EPSG:3857", "EPSG:4326"
    )
    np.testing.assert_allclose(back, col, atol=1e-9)
    # same-CRS short circuit and unsupported pair (UTM 32633 is
    # supported since round 5; Lambert-93 is not)
    assert st_transform(col, "4326", "CRS84") is col
    with pytest.raises(ValueError, match="unsupported CRS"):
        st_transform(col, "4326", "2154")
    # latitude clamps to the mercator domain
    pole = st_transform(Point(0.0, 90.0), "4326", "3857")
    assert pole.y == pytest.approx(20037508.34, rel=1e-4)


def test_transform_polygon_geometry():
    from geomesa_tpu.sql.functions import st_area, st_makeBBOX, st_transform

    box = st_makeBBOX(0, 0, 1, 1)
    merc = st_transform(box, "4326", "3857")
    # a 1-degree box at the equator is ~111.3km on a side in mercator
    assert st_area(merc) == pytest.approx((111319.49) ** 2, rel=1e-3)


def test_azimuth():
    from geomesa_tpu.geom.base import Point
    from geomesa_tpu.sql.functions import st_azimuth

    assert st_azimuth(Point(0, 0), Point(0, 1)) == pytest.approx(0.0)
    assert st_azimuth(Point(0, 0), Point(1, 0)) == pytest.approx(np.pi / 2)
    assert st_azimuth(Point(0, 0), Point(0, -1)) == pytest.approx(np.pi)
    assert st_azimuth(Point(0, 0), Point(-1, 0)) == pytest.approx(
        3 * np.pi / 2
    )
    assert np.isnan(st_azimuth(Point(2, 2), Point(2, 2)))
    col = np.array([[0.0, 0.0], [1.0, 1.0]])
    az = st_azimuth(col, Point(1.0, 1.0))
    assert az[0] == pytest.approx(np.pi / 4) and np.isnan(az[1])


# -- script-injection hardening ----------------------------------------------


def test_embedded_json_escapes_script_close():
    """A '</script>' inside an attribute value must not terminate the
    script element of the generated page (stored XSS)."""
    evil = "</script><script>alert(1)</script>"
    b = FeatureBatch.from_columns(SFT, {
        "name": [evil],
        "geom": np.array([[1.0, 2.0]]),
    }, fids=np.arange(1))
    html = leaflet_map(features=b, title="t </script><svg onload=x>")
    # the raw close-tag never appears inside the generated page except
    # as the legitimate final closers
    body = html[html.index("<script>"):]
    assert "alert(1)" in body  # data is preserved...
    assert "</script><script>" not in html  # ...but cannot close the block
    assert "<svg onload" not in html  # title is HTML-escaped
    # and the embedded payload still parses as JSON ('\/' is valid JSON)
    start = html.index("var fc = ") + len("var fc = ")
    fc = json.loads(html[start: html.index(";\n", start)])
    assert fc["features"][0]["properties"]["name"] == evil


def test_embedded_json_escapes_comment_open_as_valid_json():
    """'<!--' must be neutralized with a VALID JSON escape (\\u003c), so
    strict consumers of the embedded payload still parse it."""
    b = FeatureBatch.from_columns(SFT, {
        "name": ["x<!--y"],
        "geom": np.array([[1.0, 2.0]]),
    }, fids=np.arange(1))
    html = leaflet_map(features=b)
    assert "<!--" not in html
    start = html.index("var fc = ") + len("var fc = ")
    fc = json.loads(html[start: html.index(";\n", start)])
    assert fc["features"][0]["properties"]["name"] == "x<!--y"


def test_popup_rows_escaped_in_js():
    html = leaflet_map(features=_batch(1))
    assert "var esc = function" in html  # popup values routed through esc()


# -- UTM transforms (Krueger series; live with the other CRS tests) ----------


def test_utm_central_meridian_and_zone_edge():
    from geomesa_tpu.sql.functions import st_transform

    # a point ON zone 31N's central meridian (3E) at the equator maps to
    # the false easting exactly, northing 0
    p = st_transform(np.array([[3.0, 0.0]]), "EPSG:4326", "EPSG:32631")
    assert abs(p[0, 0] - 500_000.0) < 1e-6 and abs(p[0, 1]) < 1e-6
    # the classic zone-31N example: (0E, 0N) -> E 166021.443 (published)
    p = st_transform(np.array([[0.0, 0.0]]), "4326", "32631")
    assert p[0, 0] == pytest.approx(166_021.443, abs=0.01)
    assert abs(p[0, 1]) < 1e-6
    # meridian arc scale: 1 deg of latitude on the central meridian is
    # the WGS84 arc (110574.4m) times k0
    b = st_transform(np.array([[3.0, 1.0]]), "4326", "32631")
    assert b[0, 1] == pytest.approx(110_574.4 * 0.9996, abs=5.0)
    # far outside the zone: raise, never silently misproject
    with pytest.raises(ValueError, match="validity domain"):
        st_transform(np.array([[93.0, 0.0]]), "4326", "32631")


def test_utm_roundtrip_and_south():
    from geomesa_tpu.sql.functions import st_transform

    rng = np.random.default_rng(0)
    for zone, south in ((31, False), (15, False), (34, True), (60, True)):
        lon0 = zone * 6 - 183
        lat = (
            rng.uniform(-79, -1, 500) if south else rng.uniform(1, 83, 500)
        )
        pts = np.stack(
            [rng.uniform(lon0 - 2.9, lon0 + 2.9, 500), lat], axis=1
        )
        code = f"{'327' if south else '326'}{zone:02d}"
        out = st_transform(pts, "4326", code)
        if south:
            assert np.all(out[:, 1] < 10_000_000) and np.all(out[:, 1] > 0)
        back = st_transform(out, code, "4326")
        assert np.abs(back - pts).max() < 1e-9


def test_utm_composes_with_web_mercator_and_rejects_unknown():
    from geomesa_tpu.sql.functions import st_transform

    # 3 degrees of longitude in 3857 metres at the equator
    x3857 = 6_378_137.0 * np.radians(3.0)
    p = st_transform(np.array([[x3857, 0.0]]), "3857", "32631")
    assert p[0, 0] == pytest.approx(500_000.0, abs=0.01)
    with pytest.raises(ValueError):
        st_transform(np.array([[0.0, 0.0]]), "4326", "2154")  # Lambert-93
    with pytest.raises(ValueError):
        st_transform(np.array([[0.0, 0.0]]), "4326", "32661")  # UPS: no
