"""Leaflet HTML rendering (geomesa-spark-jupyter-leaflet analog)."""

import json

import numpy as np
import pytest

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.sql.leaflet import leaflet_map, save_map

SFT = SimpleFeatureType.create("pts", "name:String,*geom:Point:srid=4326")


def _batch(n=20):
    rng = np.random.default_rng(1)
    return FeatureBatch.from_columns(SFT, {
        "name": [f"p{i}" for i in range(n)],
        "geom": np.stack(
            [rng.uniform(-10, 10, n), rng.uniform(40, 50, n)], axis=1
        ),
    }, fids=np.arange(n))


def test_features_map_embeds_geojson():
    html = leaflet_map(features=_batch())
    assert "L.geoJSON" in html and "leaflet" in html
    # the embedded collection round-trips as JSON
    start = html.index("var fc = ") + len("var fc = ")
    end = html.index(";", start)
    fc = json.loads(html[start:end])
    assert len(fc["features"]) == 20
    assert fc["features"][0]["properties"]["name"] == "p0"
    # auto-center lands inside the data envelope
    assert "setView([4" in html  # lat ~40-50


def test_density_map_embeds_grid_and_bounds():
    from geomesa_tpu.geom import Envelope

    grid = np.zeros((8, 16), np.float32)
    grid[2, 3] = 5.0
    html = leaflet_map(density=(grid, Envelope(-10, 40, 10, 50)))
    assert "imageOverlay" in html
    assert "[[40.0, -10.0], [50.0, 10.0]]" in html
    start = html.index("var grid = ") + len("var grid = ")
    g = json.loads(html[start: html.index(";", start)])
    assert len(g) == 8 and len(g[0]) == 16 and g[2][3] == 5.0


def test_combined_and_cap(tmp_path):
    from geomesa_tpu.geom import Envelope

    big = _batch(50)
    html = leaflet_map(
        features=big,
        density=(np.ones((4, 4)), Envelope(-10, 40, 10, 50)),
        max_features=10,
    )
    start = html.index("var fc = ") + len("var fc = ")
    fc = json.loads(html[start: html.index(";", start)])
    assert len(fc["features"]) == 10  # capped
    assert "imageOverlay" in html
    p = save_map(str(tmp_path / "m.html"), features=_batch(3))
    assert open(p).read().startswith("<!DOCTYPE html>")


def test_requires_some_layer():
    with pytest.raises(ValueError):
        leaflet_map()
