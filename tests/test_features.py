"""SFT spec parsing, FeatureBatch columns, Arrow round-trip, geometry."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, SimpleFeatureType
from geomesa_tpu.geom import (
    Envelope,
    Point,
    Polygon,
    parse_wkt,
    points_in_polygon,
    to_wkt,
)


SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval=week"


class TestSFT:
    def test_parse(self):
        sft = SimpleFeatureType.create("gdelt", SPEC)
        assert sft.attribute_names == ["name", "age", "dtg", "geom"]
        assert sft.geom_field == "geom"
        assert sft.dtg_field == "dtg"
        assert sft.descriptor("age").type_name == "Integer"
        assert sft.descriptor("geom").options["srid"] == "4326"
        assert sft.z3_interval == "week"

    def test_spec_roundtrip(self):
        sft = SimpleFeatureType.create("gdelt", SPEC)
        again = SimpleFeatureType.create("gdelt", sft.spec)
        assert again == sft

    def test_defaults_and_errors(self):
        sft = SimpleFeatureType.create("t", "a,b:Double")
        assert sft.descriptor("a").type_name == "String"
        assert sft.geom_field is None
        with pytest.raises(ValueError):
            SimpleFeatureType.create("t", "a:Nope")
        with pytest.raises(ValueError):
            SimpleFeatureType.create("t", "a:Int,a:Int")

    def test_dtg_user_data_override(self):
        sft = SimpleFeatureType.create(
            "t", "d1:Date,d2:Date;geomesa.index.dtg=d2"
        )
        assert sft.dtg_field == "d2"


class TestBatch:
    def _batch(self, n=100):
        sft = SimpleFeatureType.create("gdelt", SPEC)
        rng = np.random.default_rng(1)
        return FeatureBatch.from_columns(
            sft,
            {
                "name": [f"ev{i}" for i in range(n)],
                "age": rng.integers(0, 100, n),
                "dtg": rng.integers(0, 10**12, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
                ),
            },
        )

    def test_build_and_take(self):
        b = self._batch()
        assert len(b) == 100
        sub = b.take([3, 5, 7])
        assert len(sub) == 3
        assert sub.columns["name"][0] == "ev3"

    def test_point_coords(self):
        b = self._batch()
        x, y = b.point_coords()
        assert x.shape == (100,)
        np.testing.assert_array_equal(b.bboxes()[:, 0], x)

    def test_arrow_roundtrip(self):
        b = self._batch()
        t = b.to_arrow()
        back = FeatureBatch.from_arrow(t, b.sft)
        np.testing.assert_array_equal(back.columns["age"], b.columns["age"])
        np.testing.assert_array_equal(back.columns["dtg"], b.columns["dtg"])
        np.testing.assert_allclose(back.columns["geom"], b.columns["geom"])
        np.testing.assert_array_equal(back.fids, b.fids)

    def test_wkt_geometry_column(self):
        sft = SimpleFeatureType.create("t", "*geom:Polygon")
        b = FeatureBatch.from_columns(
            sft, {"geom": ["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))"]}
        )
        bb = b.bboxes()
        np.testing.assert_array_equal(bb[0], [0, 0, 2, 2])

    def test_date_string_coercion(self):
        sft = SimpleFeatureType.create("t", "dtg:Date")
        b = FeatureBatch.from_columns(sft, {"dtg": ["2020-01-01T00:00:01"]})
        assert b.columns["dtg"][0] == np.datetime64("2020-01-01T00:00:01").astype(
            "datetime64[ms]"
        ).astype(np.int64)


class TestGeom:
    def test_wkt_roundtrip(self):
        for w in [
            "POINT (1.5 -2.5)",
            "LINESTRING (0 0, 1 1, 2 0)",
            "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))",
            "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)), ((5 5, 6 5, 6 6, 5 5)))",
        ]:
            g = parse_wkt(w)
            assert to_wkt(g) == w

    def test_envelope_geotools_order(self):
        e = parse_wkt("ENVELOPE (10, 20, -5, 5)")
        assert isinstance(e, Envelope)
        assert (e.xmin, e.xmax, e.ymin, e.ymax) == (10, 20, -5, 5)

    def test_point_in_polygon(self):
        # square with a hole
        poly = parse_wkt(
            "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (4 4, 6 4, 6 6, 4 6, 4 4))"
        )
        px = np.array([5.0, 1.0, 11.0, 5.0])
        py = np.array([5.0, 1.0, 5.0, 4.5])
        res = points_in_polygon(px, py, poly.rings())
        np.testing.assert_array_equal(res, [False, True, False, False])

    def test_point_in_polygon_jax_matches(self, rng):
        import jax.numpy as jnp

        poly = parse_wkt("POLYGON ((0 0, 10 0, 12 6, 5 11, -2 6, 0 0))")
        px = rng.uniform(-5, 15, 5000)
        py = rng.uniform(-5, 15, 5000)
        host = points_in_polygon(px, py, poly.rings())
        from geomesa_tpu.geom import points_in_polygon_jax

        dev = np.asarray(
            points_in_polygon_jax(jnp.asarray(px), jnp.asarray(py), poly.rings())
        )
        np.testing.assert_array_equal(host, dev)
