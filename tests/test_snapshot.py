"""Snapshot shipping + self-healing replicas (ISSUE 15).

The contracts under test:

- **Capture + pin**: a snapshot is the published manifest + that
  generation's partition files + the WAL watermark, captured under the
  publish lock; its pin keeps those files on disk across compactions
  that supersede the generation, and release makes them reclaimable.
- **Wire framing**: the snapshot stream roundtrips byte-exactly, every
  file checksum-verified as it lands; truncation is detectable (no END
  record) and resume is per-file.
- **Orphan reclaim**: a SIGKILLed stream's pin ages out under
  ``snapshot.pin.ttl.s`` and is reclaimed WITHOUT tearing a live
  stream's (in-process active) pin; stale download stages sweep too.
- **Self-healing e2e**: a follower that hits 410-Gone (compacted past)
  or a diverged tail reprovisions itself from a leader snapshot and
  converges to bit-identical rows — under concurrent appends.
- **Bounce epoch**: a follower's 503 append bounce carries the
  election epoch; the router adopts the newer leader and ignores
  staler bounces.
- **Backup/restore**: the CLI backup is a consistent snapshot + the
  trailing WAL segments; restore replays them and passes fsck.
"""

import io
import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.conf import prop_override
from geomesa_tpu.store import snapshot
from geomesa_tpu.store.fs import FileSystemDataStore
from geomesa_tpu.store.stream import StreamingStore
from geomesa_tpu.store.wal import WriteAheadLog

SPEC = "val:Int,dtg:Date,*geom:Point:srid=4326"
N0 = 40
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rows(n, seed, fid0=0):
    rng = np.random.default_rng(seed)
    cols = {
        "val": rng.integers(0, 100, n),
        "dtg": rng.integers(0, 10**9, n),
        "geom": np.stack(
            [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], axis=1
        ),
    }
    return cols, np.arange(fid0, fid0 + n)


def _seeded_root(tmp_path, name="leader", n0=N0):
    root = str(tmp_path / name)
    ds = FileSystemDataStore(root, partition_size=128)
    ds.create_schema("t", SPEC)
    cols, fids = _rows(n0, seed=1)
    ds.write("t", cols, fids=fids)
    ds.flush("t")
    del ds
    return root


def _get(base, path, timeout=60):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, doc, timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _append_doc(fids, x=10.0):
    n = len(fids)
    return {
        "columns": {
            "val": list(range(n)),
            "dtg": [1000 + i for i in range(n)],
            "geom": [[x, x]] * n,
        },
        "fids": list(fids),
    }


def _fids(base):
    feats = _get(base, "/features/t?cql=INCLUDE&maxFeatures=100000")
    return {int(f["id"]) for f in feats["features"]}


def _wait(pred, timeout_s=30.0, poll_s=0.05, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out waiting for {msg}")


# -- capture / pin / framing unit tests ---------------------------------------


def test_capture_stream_install_roundtrip(tmp_path):
    """Capture -> wire -> read_stream -> install lands a bit-identical,
    openable store directory (the reprovision/backup primitive)."""
    root = _seeded_root(tmp_path, "src")
    ds = FileSystemDataStore(root, partition_size=128)
    doc = snapshot.capture(ds, "t")
    try:
        assert doc["type"] == "t" and doc["snapshot_id"]
        assert doc["files"][-1]["rel"] == "schema.json"  # manifest LAST
        assert doc["wal_watermark"] >= -1
        wire = b"".join(snapshot.iter_stream(ds, "t", doc))
        stage = str(tmp_path / "stage")
        got_doc, done, complete = snapshot.read_stream(
            io.BytesIO(wire), stage
        )
        assert complete and done == len(doc["files"])
        assert got_doc["snapshot_id"] == doc["snapshot_id"]
        dst = str(tmp_path / "dst" / "t")
        os.makedirs(dst, exist_ok=True)
        snapshot.install_files(dst, got_doc, stage)
        ds2 = FileSystemDataStore(str(tmp_path / "dst"), partition_size=128)
        assert ds2.count("t") == N0
    finally:
        snapshot.release(ds, "t", doc["snapshot_id"])


def test_truncated_stream_resumes_per_file(tmp_path):
    """A stream cut mid-file reports (done < total, complete=False) and
    unlinks the partial file; resuming from ``done`` completes it."""
    root = _seeded_root(tmp_path, "src")
    ds = FileSystemDataStore(root, partition_size=128)
    doc = snapshot.capture(ds, "t")
    try:
        wire = b"".join(snapshot.iter_stream(ds, "t", doc))
        stage = str(tmp_path / "stage")
        # cut inside the LAST file's bytes: everything before it landed
        cut = len(wire) - (doc["files"][-1]["nbytes"] // 2 + 20)
        got_doc, done, complete = snapshot.read_stream(
            io.BytesIO(wire[:cut]), stage
        )
        assert not complete and 0 < done < len(doc["files"])
        # the partial file must not linger (a resume re-lands it whole)
        landed = {
            os.path.relpath(os.path.join(dp, f), stage).replace(os.sep, "/")
            for dp, _, fs in os.walk(stage) for f in fs
        }
        assert landed == {r["rel"] for r in doc["files"][:done]}
        wire2 = b"".join(
            snapshot.iter_stream(ds, "t", doc, from_file=done)
        )
        _, done2, complete2 = snapshot.read_stream(io.BytesIO(wire2), stage)
        assert complete2 and done + done2 == len(doc["files"])
    finally:
        snapshot.release(ds, "t", doc["snapshot_id"])


def test_corrupted_stream_raises_not_misinstalls(tmp_path):
    root = _seeded_root(tmp_path, "src")
    ds = FileSystemDataStore(root, partition_size=128)
    doc = snapshot.capture(ds, "t")
    try:
        wire = bytearray(b"".join(snapshot.iter_stream(ds, "t", doc)))
        # flip a bit deep in the first file's content: the per-file
        # manifest checksum must catch it before anything installs
        wire[len(wire) // 2] ^= 0xFF
        with pytest.raises(snapshot.SnapshotError):
            snapshot.read_stream(
                io.BytesIO(bytes(wire)), str(tmp_path / "stage")
            )
    finally:
        snapshot.release(ds, "t", doc["snapshot_id"])


def test_pin_blocks_gc_across_compaction_release_sweeps(tmp_path):
    """The satellite GC contract: a pinned generation's files survive
    the compaction that supersedes them; release + recover reclaims."""
    root = _seeded_root(tmp_path, "s")
    ds = FileSystemDataStore(root, partition_size=128)
    layer = StreamingStore(ds)
    doc = snapshot.capture(ds, "t")
    pinned = [
        os.path.join(ds._dir("t"), r["rel"]) for r in doc["files"]
        if r["rel"] != "schema.json"
    ]
    assert pinned and all(os.path.exists(p) for p in pinned)
    # rewrite every partition (same rows appended again -> same
    # partitions republished at a new generation) and compact: the old
    # generation is superseded but the pin must keep its files
    cols, fids = _rows(N0, seed=1, fid0=10_000)
    layer.append("t", cols, fids=fids)
    layer.compact_now("t")
    ds.recover("t")  # an explicit sweep, pin still held
    assert all(os.path.exists(p) for p in pinned), \
        "GC reclaimed files under a live pin"
    snapshot.release(ds, "t", doc["snapshot_id"])
    ds.recover("t")
    assert any(not os.path.exists(p) for p in pinned), \
        "release did not make the superseded generation reclaimable"
    assert layer.count("t") == 2 * N0  # the sweep touched only orphans
    layer.close()


_KILLED_STREAMER = """\
import sys
from geomesa_tpu.store import snapshot
from geomesa_tpu.store.fs import FileSystemDataStore

store = FileSystemDataStore(sys.argv[1], partition_size=128)
doc = snapshot.capture(store, "t")
print(doc["snapshot_id"], flush=True)
for _ in snapshot.iter_stream(store, "t", doc):
    pass  # fail.snapshot.stream=kill SIGKILLs before the first file
print("UNREACHABLE", flush=True)
"""


def test_orphaned_pin_reclaimed_after_sigkill_mid_stream(tmp_path):
    """Regression (satellite): SIGKILL a process mid-snapshot-stream;
    its orphaned pin is reclaimed once untouched past
    ``snapshot.pin.ttl.s`` — without tearing a live (in-process
    active) stream's pin — and stale download stages sweep with it."""
    root = _seeded_root(tmp_path, "s")
    env = dict(os.environ)
    env["GEOMESA_TPU_FAILPOINTS"] = "fail.snapshot.stream=kill"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", _KILLED_STREAMER, root],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == -9, (proc.returncode, proc.stderr)
    orphan_sid = proc.stdout.split()[0]
    assert "UNREACHABLE" not in proc.stdout

    ds = FileSystemDataStore(root, partition_size=128)
    pdir = os.path.join(ds._dir("t"), "_pins")
    orphan_pin = os.path.join(pdir, orphan_sid + ".json")
    assert os.path.exists(orphan_pin)  # the crash left its pin behind
    # a live local stream: its pin is in-process ACTIVE, so even an
    # ancient mtime must not get it reclaimed
    live = snapshot.capture(ds, "t")
    live_pin = os.path.join(pdir, live["snapshot_id"] + ".json")
    old = time.time() - 3600
    for p in (orphan_pin, live_pin):
        os.utime(p, (old, old))
    # a download stage a dead reprovision left behind
    stale_stage = snapshot.stage_path(ds, "t", "deadbeef")
    os.makedirs(stale_stage, exist_ok=True)
    os.utime(stale_stage, (old, old))
    with prop_override("snapshot.pin.ttl.s", 0.5):
        keep = snapshot.pinned_paths(ds, "t")
    assert not os.path.exists(orphan_pin), "orphaned pin not reclaimed"
    assert os.path.exists(live_pin), "TTL tore a live stream's pin"
    assert not os.path.exists(stale_stage), "stale stage not swept"
    # the keep-set is exactly the live pin's files, all still on disk
    want = {
        os.path.abspath(os.path.join(ds._dir("t"), r["rel"]))
        for r in live["files"]
    }
    assert keep == want and all(os.path.exists(p) for p in want)
    snapshot.release(ds, "t", live["snapshot_id"])


def test_recovery_walk_skips_underscore_dirs(tmp_path):
    """``part-``-named junk under ``_snapstage``/``_wal`` must never be
    swept (or counted) by the GC walk — those dirs are pruned."""
    root = _seeded_root(tmp_path, "s")
    ds = FileSystemDataStore(root, partition_size=128)
    d = ds._dir("t")
    staged = os.path.join(d, "_snapstage", "x", "part-999-00000.npz")
    os.makedirs(os.path.dirname(staged), exist_ok=True)
    with open(staged, "wb") as fh:
        fh.write(b"staged-not-yours")
    ds.recover("t")
    assert os.path.exists(staged)
    assert ds.count("t") == N0


# -- self-healing e2e ---------------------------------------------------------


@pytest.fixture
def pair(tmp_path):
    """Leader + follower on copied roots with fast replication AND
    reprovision knobs; yields (lbase, fbase, lsrv, fsrv_box) where
    ``fsrv_box`` is a one-item list so tests can restart the follower
    and teardown still reaps the current instance."""
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    lroot = _seeded_root(tmp_path, "leader")
    froot = str(tmp_path / "follower")
    shutil.copytree(lroot, froot)
    with prop_override("replica.lease.s", 1.5), \
            prop_override("replica.poll.ms", 25.0), \
            prop_override("replica.failover.s", 30.0), \
            prop_override("replica.retain.s", 0.6), \
            prop_override("replica.reprovision.s", 30.0):
        lsrv, _ = serve_background(
            FileSystemDataStore(lroot, partition_size=128),
            stream=True, replica=ReplicaConfig(role="leader"),
        )
        lbase = "http://%s:%s" % lsrv.server_address[:2]
        fsrv, _ = serve_background(
            FileSystemDataStore(froot, partition_size=128),
            stream=True,
            replica=ReplicaConfig(role="follower", leader_url=lbase),
        )
        fsrv_box = [fsrv]
        yield lbase, froot, lsrv, fsrv_box
        for s in (lsrv, fsrv_box[0]):
            try:
                s.shutdown()
                s.server_close()
            except Exception:
                pass


def _fbase(fsrv):
    return "http://%s:%s" % fsrv.server_address[:2]


def _restart_follower(froot, lbase):
    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background

    srv, _ = serve_background(
        FileSystemDataStore(froot, partition_size=128),
        stream=True,
        replica=ReplicaConfig(role="follower", leader_url=lbase),
    )
    return srv


def _wait_reprovisioned(fbase, lbase, timeout_s=60.0):
    def healed():
        st = _get(fbase, "/stats/replica")
        return (
            st["reprovision"]["completed"] >= 1
            and not st["reprovision"]["pending"]
            and st["reprovision"]["active"] is None
            and _get(fbase, "/count/t")["count"]
            == _get(lbase, "/count/t")["count"]
        )

    _wait(healed, timeout_s=timeout_s, msg="auto-reprovision")


def test_410_gone_auto_reprovision_under_concurrent_appends(pair):
    """E2e (satellite): compact the leader past a dead follower's
    position; on restart the follower's 410 turns into an automatic
    snapshot reprovision that converges bit-identically while appends
    keep landing."""
    lbase, froot, lsrv, fsrv_box = pair
    _wait(lambda: _get(_fbase(fsrv_box[0]), "/count/t")["count"] == N0,
          msg="initial catch-up")
    fsrv_box[0].shutdown()
    fsrv_box[0].server_close()
    with prop_override("wal.segment.bytes", 1):  # clamps to 4 KiB
        for i in range(24):
            _post(lbase, "/append/t",
                  _append_doc(list(range(9000 + i * 8, 9008 + i * 8))))
    time.sleep(0.8)  # age the dead follower past replica.retain.s
    stream = lsrv.stream_layer
    stream.compact_now("t")
    assert stream._ts("t").wal.first_seq() > 0  # history really gone

    stop = threading.Event()
    errors = []

    def appender():
        i = 0
        while not stop.is_set():
            try:
                _post(lbase, "/append/t", _append_doc([20_000 + i]))
            except Exception as e:  # leader must never shed here
                errors.append(e)
                return
            i += 1
            time.sleep(0.02)

    th = threading.Thread(target=appender, daemon=True)
    th.start()
    try:
        fsrv_box[0] = _restart_follower(froot, lbase)
        fbase = _fbase(fsrv_box[0])
        _wait_reprovisioned(fbase, lbase)
    finally:
        stop.set()
        th.join(timeout=10)
    assert not errors
    fbase = _fbase(fsrv_box[0])
    _wait(lambda: _fids(fbase) == _fids(lbase), msg="bit-identical rows")
    st = _get(fbase, "/stats/replica")
    assert st["reprovision"]["completed"] >= 1
    assert not st["reprovision"]["last"]["error"]
    assert st["lag_records"] == 0


def test_diverged_tail_auto_reprovision(pair):
    """E2e (satellite): a follower whose WAL runs AHEAD of the leader
    (forked tail) must rebuild from a snapshot, not serve phantoms."""
    lbase, froot, lsrv, fsrv_box = pair
    _wait(lambda: _get(_fbase(fsrv_box[0]), "/count/t")["count"] == N0,
          msg="initial catch-up")
    _post(lbase, "/append/t", _append_doc([9001, 9002, 9003]))
    _wait(lambda: _get(_fbase(fsrv_box[0]), "/count/t")["count"] == N0 + 3,
          msg="pre-divergence catch-up")
    fsrv_box[0].shutdown()
    fsrv_box[0].server_close()
    # forge a diverged tail: replay the follower's own last record at
    # 50 consecutive seqs its leader never assigned
    wal = WriteAheadLog(os.path.join(froot, "t", "_wal"))
    payloads = [p for _, p in wal.read_from(-1)]
    assert payloads
    for _ in range(50):
        wal.append_at(wal.next_seq, payloads[-1])
    wal.close()
    fsrv_box[0] = _restart_follower(froot, lbase)
    fbase = _fbase(fsrv_box[0])
    _wait_reprovisioned(fbase, lbase)
    _wait(lambda: _fids(fbase) == _fids(lbase), msg="fork healed")
    # phantom rows from the forked tail must be gone, not merged
    assert _get(fbase, "/count/t")["count"] == N0 + 3


def test_bootstrap_from_zero_via_fleet_add_node(pair):
    """``fleet add-node``: a follower with an EMPTY store joins, pulls
    every type as a snapshot, and serves bit-identical counts."""
    import socket

    from geomesa_tpu.replica import ReplicaConfig
    from geomesa_tpu.server import serve_background
    from geomesa_tpu.tools import fleet

    lbase, froot, lsrv, fsrv_box = pair
    newroot = os.path.join(os.path.dirname(froot), "fresh")
    os.makedirs(newroot)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    new_url = f"http://127.0.0.1:{port}"
    started = []

    def start(url, role, leader_url):
        assert role == "follower" and leader_url == lbase
        srv, _ = serve_background(
            FileSystemDataStore(newroot, partition_size=128),
            port=port, stream=True,
            replica=ReplicaConfig(role="follower", leader_url=leader_url),
        )
        started.append(srv)

    try:
        report = fleet.add_node(
            [lbase], new_url, start, timeout_s=90.0, log=lambda *_: None,
        )
        assert report["added"] == new_url
        assert report["counts"]["t"] == _get(lbase, "/count/t")["count"]
        st = _get(new_url, "/stats/replica")
        assert st["reprovision"]["completed"] >= 1
        assert _fids(new_url) == _fids(lbase)
    finally:
        for srv in started:
            try:
                srv.shutdown()
                srv.server_close()
            except Exception:
                pass


def test_append_bounce_carries_epoch_and_router_adopts_it(pair):
    """Satellites: the follower's 503 bounce body names the leader AND
    the election epoch; the router consumes it (one-hop re-discovery)
    and ignores staler bounces."""
    from geomesa_tpu.router import Router

    lbase, froot, lsrv, fsrv_box = pair
    fbase = _fbase(fsrv_box[0])
    _wait(lambda: _get(fbase, "/count/t")["count"] == N0,
          msg="initial catch-up")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(fbase, "/append/t", _append_doc([1]))
    assert ei.value.code == 503
    doc = json.loads(ei.value.read())
    assert doc["leader"] == lbase
    assert isinstance(doc["epoch"], int) and doc["epoch"] >= 0

    rt = Router([fbase, lbase])  # never started: pure state checks
    fb, lb = rt.backends
    rt.note_bounce(fb, {"leader": doc["leader"], "epoch": doc["epoch"] + 1})
    assert fb.role == "follower" and lb.role == "leader"
    # a revenant ex-leader's staler bounce must not un-learn that
    rt.note_bounce(lb, {"leader": fbase, "epoch": doc["epoch"]})
    assert lb.role == "leader" and fb.role == "follower"


# -- backup / restore ---------------------------------------------------------


def test_backup_restore_fsck_roundtrip(tmp_path):
    """CLI backup -> restore: compacted rows ride the snapshot, acked-
    but-uncompacted rows ride the trailing WAL segments; restore
    replays them, passes fsck, and serves identical counts."""
    from geomesa_tpu.tools.cli import main as cli_main

    root = _seeded_root(tmp_path, "live")
    ds = FileSystemDataStore(root, partition_size=128)
    layer = StreamingStore(ds)
    cols, fids = _rows(10, seed=3, fid0=50_000)
    layer.append("t", cols, fids=fids)
    layer.close()  # compact=False: the 10 rows exist ONLY in the WAL
    del layer, ds

    out = str(tmp_path / "bk")
    cli_main(["--root", root, "backup", "--out", out])
    assert os.path.exists(os.path.join(out, "t", "schema.json"))
    assert any(
        f.startswith("wal-") for f in os.listdir(os.path.join(out, "t", "_wal"))
    )
    newroot = str(tmp_path / "restored")
    cli_main(["--root", newroot, "restore", "--backup", out])
    ds2 = FileSystemDataStore(newroot, partition_size=128)
    assert ds2.count("t") == N0 + 10
    # a second restore into the same root must refuse, not clobber
    with pytest.raises(SystemExit):
        cli_main(["--root", newroot, "restore", "--backup", out])


def test_backup_no_wal_skips_trailing_segments(tmp_path):
    from geomesa_tpu.tools.cli import main as cli_main

    root = _seeded_root(tmp_path, "live")
    layer = StreamingStore(FileSystemDataStore(root, partition_size=128))
    cols, fids = _rows(5, seed=4, fid0=60_000)
    layer.append("t", cols, fids=fids)
    layer.close()
    out = str(tmp_path / "bk")
    cli_main(["--root", root, "backup", "--out", out, "--no-wal"])
    assert not os.path.isdir(os.path.join(out, "t", "_wal"))
    newroot = str(tmp_path / "restored")
    cli_main(["--root", newroot, "restore", "--backup", out])
    # snapshot-only restore: the compacted N0, not the WAL-only 5
    ds2 = FileSystemDataStore(newroot, partition_size=128)
    assert ds2.count("t") == N0
