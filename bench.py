#!/usr/bin/env python
"""Benchmark: bbox+time filter throughput through the real framework path.

Shape of BASELINE config #1 (GDELT bbox+during): synthetic GDELT-like
points resident on device, one ECQL filter compiled by
``geomesa_tpu.filter.compile_filter``, its fused device mask + count jitted
and timed. Metric: features/sec/chip scanned by the fused predicate kernel
(the north-star counts features *evaluated* per second against the
baseline's >= 62.5M features/sec/chip target).

Roofline honesty: K scan invocations are chained inside ONE dispatched jit
(``lax.scan`` whose body is tied to the loop carry with an
``optimization_barrier`` so XLA cannot hoist the loop-invariant kernel),
synced once with a scalar fetch. Per-invocation time therefore excludes
the axon tunnel's ~50-100ms dispatch latency, and the JSON line reports
achieved GB/s against the v5e HBM peak alongside features/sec.

The default mode runs BOTH the filter scan and the Z3 build benchmarks and
prints exactly one JSON line to stdout with the build metric as a field of
the same line; all logs go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

V5E_HBM_PEAK_GBPS = 819.0  # TPU v5e: 16GB HBM2 @ ~819 GB/s per chip


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _default_n(args, platform: str) -> int:
    """Rows resident on device: 2^28 = 3-4GB of planes fits v5e HBM with
    headroom and amortizes dispatch latency; smaller elsewhere."""
    return args.n or (
        (1 << 28) if platform == "tpu"
        else (1 << 27) if platform != "cpu"
        else (1 << 20)
    )


def _measure(chain, inputs, args, k: int, n: int, bytes_per_row: int,
             platform: str, label: str) -> dict:
    """Timed median-of-iters protocol shared by the scan benchmarks: one
    scalar fetch per chain dispatch is the only sync point."""
    times = []
    for _ in range(args.iters):
        t = time.perf_counter()
        int(chain(*inputs))
        times.append(time.perf_counter() - t)
    best = min(times) / k
    per_inv = sorted(times)[len(times) // 2] / k
    feats_per_sec = n / per_inv
    gbps = n * bytes_per_row / per_inv / 1e9
    hbm_pct = (
        round(100.0 * gbps / V5E_HBM_PEAK_GBPS, 1)
        if platform == "tpu"
        else None
    )
    log(
        f"{label} best={best*1e3:.2f}ms median={per_inv*1e3:.2f}ms per "
        f"invocation ({bytes_per_row}B/row) -> "
        f"{feats_per_sec/1e9:.2f}B features/sec/chip, {gbps:.0f} GB/s"
        + (f" ({hbm_pct}% of v5e HBM peak)" if hbm_pct is not None else "")
    )
    return {
        "value": round(feats_per_sec, 1),
        "gbps": round(gbps, 1),
        "hbm_pct": hbm_pct,
        "per_invocation_ms": round(per_inv * 1e3, 3),
    }


def _chain(scan_fn, k):
    """One jitted dispatch running ``scan_fn`` k times: the barrier ties
    every input to the loop carry, so the loop body cannot be hoisted or
    CSE'd, yet no data is copied. Returns the jitted chain fn (uint32
    checksum output = the single scalar sync point)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(*args):
        def body(carry, _):
            args_b, carry_b = jax.lax.optimization_barrier((args, carry))
            return carry_b + scan_fn(*args_b).astype(jnp.uint32), None

        total, _ = jax.lax.scan(
            body, jnp.zeros((), jnp.uint32), None, length=k
        )
        return total

    return chain


def bench_filter(args) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    platform = jax.devices()[0].platform
    n = _default_n(args, platform)
    log(f"platform={platform} device={jax.devices()[0]} n={n:,}")

    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.filter.compile import compile_filter
    from geomesa_tpu.filter.ecql import parse_ecql, parse_instant

    sft = SimpleFeatureType.create(
        "gdelt", "count:Int,dtg:Date,*geom:Point:srid=4326"
    )
    # Europe bbox + 5-day window over a 60-day span (GDELT-style selectivity)
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    ecql = (
        "BBOX(geom, -10, 35, 30, 60) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-01-15T00:00:00Z"
    )
    compiled = compile_filter(parse_ecql(ecql), sft)
    assert compiled.fully_on_device

    # generate data on device: float32 coords; int64 epoch-ms materialized
    # as the storage-format hi/lo word planes (ops/int64lanes.py)
    log("generating device-resident columns...")
    from geomesa_tpu.jaxconf import require_x64

    require_x64()  # only for generating the i64 oracle column
    key = jax.random.PRNGKey(42)
    kx, ky, kt = jax.random.split(key, 3)

    @jax.jit
    def make_cols():
        dtg = jax.random.randint(kt, (n,), t0, t1, jnp.int64)
        return {
            "geom__x": jax.random.uniform(kx, (n,), jnp.float32, -180.0, 180.0),
            "geom__y": jax.random.uniform(ky, (n,), jnp.float32, -90.0, 90.0),
            "dtg__hi": (dtg >> 32).astype(jnp.int32),
            "dtg__lo": (dtg & 0xFFFFFFFF).astype(jnp.uint32),
        }

    # only the scan planes stay resident: keeping the 8B/row int64 dtg
    # alive through the timed loop would waste 2GB of HBM at n=2^28;
    # the --check host oracle recomputes it from the same PRNG key
    cols = jax.block_until_ready(make_cols())
    assert sorted(compiled.device_cols) == sorted(cols)
    bytes_per_row = sum(v.dtype.itemsize for v in cols.values())

    if args.engine == "pallas":
        scan = compiled.pallas_scan()
        assert scan is not None, "filter not pallas-tileable"
        scan_fn = scan[0]
    else:
        def scan_fn(c):
            return compiled.device_fn(c).sum()
    scan_count = jax.jit(scan_fn)

    # compile + warmup the single-invocation kernel (used for the check)
    t_compile = time.perf_counter()
    hits = int(scan_count(cols))
    log(f"compiled in {time.perf_counter() - t_compile:.1f}s; hits={hits:,} "
        f"(selectivity {hits / n:.4%})")

    if args.check:
        if n <= (1 << 27):
            x = np.asarray(cols["geom__x"])
            y = np.asarray(cols["geom__y"])
            d = np.asarray(jax.jit(
                lambda: jax.random.randint(kt, (n,), t0, t1, jnp.int64)
            )())
            expect = int(
                (
                    (x >= -10) & (x <= 30) & (y >= 35) & (y <= 60)
                    & (d >= parse_instant("2020-01-10T00:00:00"))
                    & (d <= parse_instant("2020-01-15T00:00:00"))
                ).sum()
            )
            oracle = "host numpy oracle"
        else:
            # fetching 4+GB of columns through the device tunnel for the
            # numpy oracle is slower than the whole benchmark; cross-check
            # against the OTHER engine so the two independent kernels must
            # agree (pallas <-> XLA-fused)
            if args.engine == "pallas":
                other = jax.jit(lambda c: compiled.device_fn(c).sum())
                oracle = "independent XLA-engine count"
            else:
                other = jax.jit(compiled.pallas_scan()[0])
                oracle = "independent Pallas-engine count"
            expect = int(other(cols))
        assert hits == expect, f"device {hits} != oracle {expect}"
        log(f"count verified against {oracle}")

    k = args.chain
    chain = _chain(scan_fn, k)
    t_compile = time.perf_counter()
    total = int(chain(cols))
    log(f"chain (K={k}) compiled in {time.perf_counter() - t_compile:.1f}s")
    # the chain must have run the same kernel K times
    assert total == (k * hits) % (1 << 32), (total, hits, k)

    m = _measure(chain, (cols,), args, k, n, bytes_per_row, platform, "filter")
    baseline_per_chip = 62.5e6  # BASELINE.json north star / 8 chips
    return {
        "metric": "bbox+time filter throughput (fused device scan)",
        "value": m["value"],
        "unit": "features/sec/chip",
        "vs_baseline": round(m["value"] / baseline_per_chip, 2),
        "gbps": m["gbps"],
        "hbm_pct": m["hbm_pct"],
        "chain": k,
        "per_invocation_ms": m["per_invocation_ms"],
        "n": n,
    }


def bench_zscan(args) -> dict:
    """Z3Iterator-analog scan: filter by the resident KEY planes alone
    (bin int32 + z hi/lo uint32 = 12B/row vs 16B/row of attribute
    planes). The masked-compare kernel needs no de-interleave — Morton
    spreading is monotonic (ops/zscan.py); loose cell-granular semantics,
    exactly what the reference's Z3Iterator answers without residual
    refinement."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from geomesa_tpu.curves import Z3SFC
    from geomesa_tpu.curves.binnedtime import WEEK_MS
    from geomesa_tpu.filter.ecql import parse_instant
    from geomesa_tpu.ops import zscan

    platform = jax.devices()[0].platform
    n = _default_n(args, platform)
    log(f"platform={platform} device={jax.devices()[0]} n={n:,} (zscan mode)")
    sfc = Z3SFC()
    t0 = parse_instant("2020-01-01T00:00:00")
    t1 = parse_instant("2020-03-01T00:00:00")
    qt0 = parse_instant("2020-01-10T00:00:00")
    qt1 = parse_instant("2020-01-15T00:00:00")
    qx0, qy0, qx1, qy1 = -10.0, 35.0, 30.0, 60.0

    from geomesa_tpu.jaxconf import require_x64

    require_x64()  # i64 only while deriving the resident planes
    key = jax.random.PRNGKey(42)
    kx, ky, kt = jax.random.split(key, 3)

    def _coords():
        x = jax.random.uniform(kx, (n,), jnp.float32, -180.0, 180.0)
        y = jax.random.uniform(ky, (n,), jnp.float32, -90.0, 90.0)
        dtg = jax.random.randint(kt, (n,), t0, t1, jnp.int64)
        bins64 = dtg // WEEK_MS
        off = ((dtg - bins64 * WEEK_MS) // 1000).astype(jnp.float32)
        return x, y, off, bins64

    @jax.jit
    def make_planes():
        x, y, off, bins64 = _coords()
        z_hi, z_lo = sfc.index_jax_hi_lo(x, y, off)
        # only the key planes leave this jit: the coordinate planes are
        # scratch, freed before the timed loop (the --check oracle
        # recomputes them from the same PRNG keys)
        return bins64.astype(jnp.int32), z_hi, z_lo

    bins, z_hi, z_lo = jax.block_until_ready(make_planes())
    bounds_np, ids_np = zscan.z3_query_bounds(
        sfc, qx0, qy0, qx1, qy1, qt0, qt1
    )
    bounds_np, ids_np = zscan.pad_bins(bounds_np, ids_np)
    bounds, ids = jnp.asarray(bounds_np), jnp.asarray(ids_np)
    log(f"query spans {int((ids_np >= 0).sum())} period bins "
        f"(padded to {len(ids_np)})")

    # XLA-fused path, deliberately: measured on v5e, the hand-tiled Pallas
    # variant (zscan.build_z3_pallas_scan, CI-verified in interpret mode)
    # tops out ~305 GB/s while XLA's fusion pipeline reaches ~410-450 GB/s
    # for this pure compare+reduce shape — the opposite of the attribute
    # filter scan, where the Pallas tiles win. Engine choice is per-kernel,
    # decided by measurement (README component map).
    def scan_fn(b, zh, zl):
        return zscan.z3_zscan_mask(zh, zl, b, bounds, ids).sum()

    bytes_per_row = 12  # int32 bin + 2x uint32 z planes
    hits = int(jax.jit(scan_fn)(bins, z_hi, z_lo))
    log(f"hits={hits:,} (selectivity {hits / n:.4%}, loose cell semantics)")

    if args.check:
        # independent oracle: per-dimension cell compare on the raw
        # coordinate planes (no interleave anywhere in this path)
        from geomesa_tpu.curves.binnedtime import bins_for_interval

        cell_bounds = []
        for b, lo_off, hi_off in bins_for_interval(qt0, qt1, sfc.period):
            cell_bounds.append((b, (
                int(sfc.lon.normalize(qx0)), int(sfc.lat.normalize(qy0)),
                int(sfc.time.normalize(lo_off))), (
                int(sfc.lon.normalize(qx1)), int(sfc.lat.normalize(qy1)),
                int(sfc.time.normalize(hi_off)))))

        @jax.jit
        def oracle():
            # identical PRNG keys -> identical coordinates; no interleave
            # anywhere in this path, and nothing stays resident after
            xa, ya, offa, bins64 = _coords()
            nx = sfc.lon.normalize_jax(xa).astype(jnp.int32)
            ny = sfc.lat.normalize_jax(ya).astype(jnp.int32)
            nt = sfc.time.normalize_jax(offa).astype(jnp.int32)
            m = jnp.zeros(n, bool)
            for b, qlo, qhi in cell_bounds:
                m_b = bins64.astype(jnp.int32) == b
                m_b &= (nx >= qlo[0]) & (nx <= qhi[0])
                m_b &= (ny >= qlo[1]) & (ny <= qhi[1])
                m_b &= (nt >= qlo[2]) & (nt <= qhi[2])
                m = m | m_b
            return m.sum()

        expect = int(oracle())
        assert hits == expect, f"zscan {hits} != cell oracle {expect}"
        log("count verified against per-dimension cell oracle")

    k = args.chain
    chain = _chain(scan_fn, k)
    t_c = time.perf_counter()
    total = int(chain(bins, z_hi, z_lo))
    log(f"zscan chain (K={k}) compiled in {time.perf_counter() - t_c:.1f}s")
    assert total == (k * hits) % (1 << 32), (total, hits, k)

    m = _measure(
        chain, (bins, z_hi, z_lo), args, k, n, bytes_per_row, platform,
        "zscan",
    )
    return {
        "metric": "key-only z scan (Z3Iterator analog)",
        "value": m["value"],
        "unit": "features/sec/chip",
        "gbps": m["gbps"],
        "hbm_pct": m["hbm_pct"],
        "n": n,
    }


def bench_build(args) -> dict:
    """Z3 index build on device: fused quantize+interleave key encode
    (hi/lo uint32 lanes) + lexicographic sort carrying a row-id payload
    lane -- the permutation a real build needs, not just sorted keys
    (BASELINE config #2 shape: OSM-GPS-style points, full build path
    minus file IO)."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.curves import Z3SFC

    platform = jax.devices()[0].platform
    n = args.n or ((1 << 26) if platform != "cpu" else (1 << 20))
    log(f"platform={platform} device={jax.devices()[0]} n={n:,} (build mode)")
    sfc = Z3SFC()
    key = jax.random.PRNGKey(7)
    kx, ky, kt = jax.random.split(key, 3)
    x = jax.random.uniform(kx, (n,), jnp.float32, -180.0, 180.0)
    y = jax.random.uniform(ky, (n,), jnp.float32, -90.0, 90.0)
    t = jax.random.uniform(kt, (n,), jnp.float32, 0.0, 604800.0)
    jax.block_until_ready((x, y, t))

    def build_step(xc, yc, tc):
        hi, lo = sfc.index_jax_hi_lo(xc, yc, tc)
        rid = jnp.arange(n, dtype=jnp.uint32)
        hi_s, lo_s, rid_s = jax.lax.sort((hi, lo, rid), num_keys=2)
        # order-dependent checksum: forces the full sorted arrays (keys AND
        # permutation) to materialize (a bare block_until_ready does not
        # sync through the remote-execution tunnel, and returning only
        # extremes would let XLA reduce the sort to min/max)
        w = jnp.arange(n, dtype=jnp.uint32)
        return (hi_s * w).sum() + (lo_s * w).sum() + (rid_s * w).sum()

    if args.check:
        import numpy as np

        @jax.jit
        def build_full(xc, yc, tc):
            hi, lo = sfc.index_jax_hi_lo(xc, yc, tc)
            rid = jnp.arange(n, dtype=jnp.uint32)
            return jax.lax.sort((hi, lo, rid), num_keys=2)

        hi_s, lo_s, rid_s = build_full(x, y, t)
        hi_s = np.asarray(hi_s).astype(np.uint64)
        lo_s = np.asarray(lo_s).astype(np.uint64)
        got = (hi_s << np.uint64(32)) | lo_s
        # oracle for the sort: the same device encode (f32 lanes -- the
        # f64-parity of the encode itself is covered by the unit tests),
        # host-sorted, must equal the device-sorted output exactly; the
        # rid permutation must reproduce the unsorted keys
        hi_u, lo_u = jax.jit(sfc.index_jax_hi_lo)(x, y, t)
        z_u = (np.asarray(hi_u).astype(np.uint64) << np.uint64(32)) | np.asarray(
            lo_u
        ).astype(np.uint64)
        assert np.array_equal(got, np.sort(z_u)), "device sort != host sort"
        perm = np.asarray(rid_s).astype(np.int64)
        assert np.array_equal(z_u[perm], got), "rid payload mis-permuted"
        del hi_s, lo_s, rid_s, got, z_u, perm
        log("sorted keys + rid permutation verified against host oracle")

    k = args.chain_build
    chain = _chain(build_step, k)
    t0 = time.perf_counter()
    chk = int(chain(x, y, t))
    log(f"build chain (K={k}) compiled+first in "
        f"{time.perf_counter() - t0:.1f}s (chk {chk})")

    times = []
    for _ in range(args.iters):
        t1 = time.perf_counter()
        int(chain(x, y, t))  # scalar fetch = hard sync point
        times.append(time.perf_counter() - t1)
    per_inv = sorted(times)[len(times) // 2] / k
    pts_per_sec = n / per_inv
    log(f"median={per_inv*1e3:.2f}ms per build -> "
        f"{pts_per_sec/1e6:.0f}M pts/sec/chip")
    return {
        "metric": "Z3 index build (encode + device sort + rid payload)",
        "value": round(pts_per_sec, 1),
        "unit": "pts/sec/chip",
        "vs_baseline": None,  # BASELINE.json: 'TBD at first measurement'
        "build_chain": k,
        "build_n": n,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None, help="rows resident on device")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--chain",
        type=int,
        default=32,
        help="scan invocations chained per dispatch (filter mode)",
    )
    ap.add_argument(
        "--chain-build",
        type=int,
        default=8,
        help="build invocations chained per dispatch (build mode)",
    )
    ap.add_argument("--check", action="store_true", help="verify count vs host oracle")
    ap.add_argument(
        "--engine",
        choices=("pallas", "xla"),
        default="pallas",
        help="fused scan kernel: hand-written Pallas tiles or XLA-fused jnp",
    )
    ap.add_argument(
        "--mode",
        choices=("all", "filter", "zscan", "build"),
        default="all",
        help="all: filter scan + key-only z scan + Z3 build, one JSON "
        "line with everything (what the driver records); "
        "filter / zscan / build: that one alone",
    )
    args = ap.parse_args()

    if args.mode == "filter":
        out = bench_filter(args)
    elif args.mode == "zscan":
        out = bench_zscan(args)
    elif args.mode == "build":
        out = bench_build(args)
    else:
        out = bench_filter(args)
        z = bench_zscan(args)
        out["zscan_feats_per_sec"] = z["value"]
        out["zscan_gbps"] = z["gbps"]
        out["zscan_hbm_pct"] = z["hbm_pct"]
        build = bench_build(args)
        out["build_pts_per_sec"] = build["value"]
        out["build_chain"] = build["build_chain"]
        out["build_n"] = build["build_n"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
